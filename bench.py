"""Benchmark: framework train-step throughput vs. plain-jit baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Runs on whatever devices the runtime exposes (the real TPU chip under the
driver; CPU elsewhere). vs_baseline is framework-throughput / plain-jit-DP
throughput on the identical model+batch (>= 1.0 means we match or beat the
hand-written JAX data-parallel step).

Methodology notes (the device may sit behind a high-latency tunnel and
throttle under sustained load, so naive one-shot loops are biased):
- the batch is device-resident for BOTH paths (the framework's Remapper
  places it once; the baseline gets a device_put) — feeding numpy to one
  path would bill host->device transfer to that path only;
- both paths donate their state buffers;
- vs_baseline is the MEDIAN over many order-alternated paired phases:
  single pairs swing 0.4-2.3x under throttling, so no point estimate is
  trustworthy; the median of paired ratios is robust to throttle windows
  landing on either path.
"""
import functools
import json
import statistics
import time

import numpy as np


def _phase_rate(fn, iters):
    import jax
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    return iters / (time.perf_counter() - t0)


def main():
    import jax
    import jax.numpy as jnp
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy

    rng = np.random.RandomState(0)
    batch_size = 256
    d_in, d_h, d_out = 1024, 4096, 1024

    params = {
        "l1": {"k": jnp.asarray(rng.randn(d_in, d_h) * 0.02, jnp.float32),
               "b": jnp.zeros((d_h,), jnp.float32)},
        "l2": {"k": jnp.asarray(rng.randn(d_h, d_h) * 0.02, jnp.float32),
               "b": jnp.zeros((d_h,), jnp.float32)},
        "l3": {"k": jnp.asarray(rng.randn(d_h, d_out) * 0.02, jnp.float32),
               "b": jnp.zeros((d_out,), jnp.float32)},
    }

    def loss_fn(p, batch):
        h = jnp.tanh(batch["x"] @ p["l1"]["k"] + p["l1"]["b"])
        h = jnp.tanh(h @ p["l2"]["k"] + p["l2"]["b"])
        pred = h @ p["l3"]["k"] + p["l3"]["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch_np = {"x": rng.randn(batch_size, d_in).astype(np.float32),
                "y": rng.randn(batch_size, d_out).astype(np.float32)}
    opt = optax.adam(1e-3)

    # ---- baseline: plain jit data-parallel step, donated state,
    #      device-resident batch
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def baseline_step(p, s, b):
        loss, g = jax.value_and_grad(loss_fn)(p, b)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, loss

    # real copies: baseline_step donates these, and `params` is reused below
    base_batch = jax.device_put(batch_np)
    base_box = [jax.device_put(jax.device_get(params)),
                jax.device_put(jax.device_get(opt.init(params)))]

    def run_baseline():
        p, s, loss = baseline_step(base_box[0], base_box[1], base_batch)
        base_box[0], base_box[1] = p, s
        return loss

    # ---- framework: AllReduce strategy through the full stack
    adt.reset()
    ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
    runner = ad.build(loss_fn, opt, params, batch_np)
    runner.init(params)
    sharded = runner.remapper.remap_feed(batch_np)
    state_box = [runner.state]

    def run_fw():
        st, m = runner.distributed_step(state_box[0], sharded)
        state_box[0] = st
        return m["loss"]

    # warmup (compile + a few steps each)
    for _ in range(5):
        run_baseline()
        run_fw()
    jax.block_until_ready((base_box[0], state_box[0].params))

    # device throughput under the tunnel swings wildly between adjacent
    # windows (paired-phase ratios observed anywhere in 0.4-2.3x on a
    # throttled chip), so no single phase pair is trustworthy: measure many
    # alternating pairs (order flipped each time to kill drift bias) and
    # report the MEDIAN ratio — robust to throttle windows landing on
    # either path — plus the median framework rate
    ratios, fw_rates = [], []
    for k in range(20):
        if k % 2 == 0:
            rb = _phase_rate(run_baseline, 12)
            rf = _phase_rate(run_fw, 12)
        else:
            rf = _phase_rate(run_fw, 12)
            rb = _phase_rate(run_baseline, 12)
        ratios.append(rf / rb)
        fw_rates.append(rf)
    median_ratio = statistics.median(ratios)
    median_rate = statistics.median(fw_rates)

    print(json.dumps({
        "metric": "mlp_train_examples_per_sec",
        "value": round(median_rate * batch_size, 2),
        "unit": "examples/s",
        "vs_baseline": round(median_ratio, 4),
    }))


if __name__ == "__main__":
    main()
