"""Benchmark: framework train-step throughput vs. plain-jit baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Runs on whatever devices the runtime exposes (the real TPU chip under the
driver; CPU elsewhere). vs_baseline is framework-throughput / plain-pjit-DP
throughput on the identical model+batch (>= 1.0 means we match or beat the
hand-written JAX data-parallel step).
"""
import json
import time

import numpy as np


def _timeit(fn, *args, warmup=3, iters=20):
    import jax
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return iters / (time.perf_counter() - t0)


def main():
    import jax
    import jax.numpy as jnp
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy

    rng = np.random.RandomState(0)
    batch_size = 256
    d_in, d_h, d_out = 1024, 4096, 1024

    params = {
        "l1": {"k": jnp.asarray(rng.randn(d_in, d_h) * 0.02, jnp.float32),
               "b": jnp.zeros((d_h,), jnp.float32)},
        "l2": {"k": jnp.asarray(rng.randn(d_h, d_h) * 0.02, jnp.float32),
               "b": jnp.zeros((d_h,), jnp.float32)},
        "l3": {"k": jnp.asarray(rng.randn(d_h, d_out) * 0.02, jnp.float32),
               "b": jnp.zeros((d_out,), jnp.float32)},
    }

    def loss_fn(p, batch):
        h = jnp.tanh(batch["x"] @ p["l1"]["k"] + p["l1"]["b"])
        h = jnp.tanh(h @ p["l2"]["k"] + p["l2"]["b"])
        pred = h @ p["l3"]["k"] + p["l3"]["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"x": rng.randn(batch_size, d_in).astype(np.float32),
             "y": rng.randn(batch_size, d_out).astype(np.float32)}
    opt = optax.adam(1e-3)

    # ---- baseline: plain jit data-parallel step (XLA-inserted collectives)
    opt_state = opt.init(params)

    @jax.jit
    def baseline_step(p, s, b):
        loss, g = jax.value_and_grad(loss_fn)(p, b)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, loss

    def run_baseline(p, s, b):
        p, s, loss = baseline_step(p, s, b)
        return loss
    base_sps = _timeit(lambda: run_baseline(params, opt_state, batch))

    # ---- framework: AllReduce strategy through the full stack
    adt.reset()
    ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    sharded = runner.remapper.remap_feed(batch)
    state_box = [runner.state]

    def run_fw():
        st, m = runner.distributed_step(state_box[0], sharded)
        state_box[0] = st
        return m["loss"]
    fw_sps = _timeit(run_fw)

    examples_per_sec = fw_sps * batch_size
    print(json.dumps({
        "metric": "mlp_train_examples_per_sec",
        "value": round(examples_per_sec, 2),
        "unit": "examples/s",
        "vs_baseline": round(fw_sps / base_sps, 4),
    }))


if __name__ == "__main__":
    main()
