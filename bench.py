"""Benchmark: framework train-step throughput vs. plain-jit baselines.

Prints cumulative JSON result lines to stdout — one after EVERY model
completes (last line wins): {"metric", "value", "unit", "vs_baseline",
"models"}. Three flagship models (the BASELINE.md bar): resnet50 (batch
256), bert_base (bf16), and the lm1b-config transformer LM (bf16). For
each, the framework's full stack (strategy build -> lowering -> Runner
step) races a hand-written jit data-parallel step on the identical
model/optimizer/batch. ``vs_baseline`` >= 1.0 means the framework matches
or beats hand-written JAX; the headline ``vs_baseline`` is the MINIMUM
ratio across models that ran (the conservative claim), per-model detail in
"models" (each with examples/sec and MFU).

Survivability (the device sits behind a high-latency tunnel whose stalls
can stretch a 20s compile to many minutes, and the driver enforces a hard
wall clock):
- each model runs in its OWN subprocess with a hard parent-side timeout —
  a wedged compile costs one model, never the artifact;
- the parent prints the cumulative result after every model and on
  SIGTERM/SIGINT, so a driver kill at any point still leaves the most
  recent complete line on stdout;
- children share the persistent XLA compile cache (/tmp/adt_jax_cache),
  so repeat runs skip the compile cost entirely;
- inside a model, the pair loop checks a soft deadline and emits with the
  pairs it has rather than running past its budget;
- every timing point synchronizes by VALUE READBACK (``_sync``), not
  ``block_until_ready`` — the tunnel transport can acknowledge readiness
  before execution drains, which once produced MFU "39" (physically
  impossible; a real step takes >100x longer than the acked time).

Methodology (unchanged from round 2):
- batches are device-resident for BOTH paths; both donate state buffers;
- vs_baseline is the MEDIAN over order-alternated paired phases — single
  pairs swing 0.4-2.3x under throttling; the median of paired ratios is
  robust to throttle windows landing on either path;
- MFU = (compiled cost-analysis FLOPs per step) / steady-state step time /
  chip peak — computed from the framework path's own best phase so tunnel
  stalls don't understate it.
"""
import contextlib
import functools
import json
import os
import signal
import statistics
import subprocess
import sys
import time

import numpy as np

# bf16 dense peak FLOP/s by platform (public figures)
PEAK_FLOPS = {"v5 lite": 197e12, "v5e": 197e12, "v4": 275e12,
              "v5p": 918e12, "cpu": 5e10}

MODEL_LABELS = ["resnet50", "bert_base", "lm1b"]
RESULT_TAG = "ADT_MODEL_RESULT\t"


def _sync(out) -> float:
    """Forced VALUE readback of a scalar. On the tunnel transport,
    ``jax.block_until_ready`` can acknowledge before execution drains
    (observed: a 'resnet-256 step' timed at 5 ms, MFU 39 — physically
    impossible); fetching the value cannot return early. Costs one RTT
    per call, which the adaptive >=1 s phases amortize."""
    import jax
    import numpy as np
    return float(np.asarray(jax.device_get(out)))


def _phase_rate(fn, iters):
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn()
    _sync(out)
    return iters / (time.perf_counter() - t0)


def _chip_peak():
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, peak in PEAK_FLOPS.items():
        if key in kind:
            return peak
    return PEAK_FLOPS["cpu"] if jax.devices()[0].platform == "cpu" else 197e12


def _compiled_flops(lowered_compiled) -> float:
    try:
        ca = lowered_compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0))
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return 0.0


def _model_spec(label, batch_size=None):
    """(registry name, setup kwargs, batch key, flops_extra) for a
    flagship label. ``flops_extra`` corrects XLA cost-analysis blind
    spots (it counts a ``lax.scan`` body ONCE regardless of trip count)
    with closed-form hand counts, so memory-lean scanned ops can be
    benched at their best operating point without misreporting MFU."""
    import jax.numpy as jnp
    if label == "resnet50":
        # batch 256: a realistic v5e operating point (batch 64 leaves the
        # MXU underfed; see BENCHMARKS.md for the batch-64 comparison)
        return "resnet50", dict(batch_size=batch_size or 256), "image", 0.0
    if label == "bert_base":
        # bf16 like every real TPU deployment; the driver's child benches
        # batch 64 AND 128 as paired phases in one run and headlines the
        # artifact winner (batch 256 RESOURCE_EXHAUSTs on the 16 GB v5e)
        return "bert_base", dict(batch_size=batch_size or 128, seq_len=128,
                                 dtype=jnp.bfloat16), "input_ids", 0.0
    if label == "lm1b":
        from autodist_tpu.models.lm import LMConfig
        cfg = LMConfig.lm1b(dtype=jnp.bfloat16)
        # seq 128, not 256: at 256 the lean-head compile plus the pair
        # phases regularly overran the per-model budget and lm1b reported
        # NOTHING (the worst outcome — ROADMAP pain point); half the
        # tokens per step lands the compile and >= 2 pairs inside the
        # budget. ADT_BENCH_LM1B_SEQ=256 restores the full-length run
        # when the budget allows.
        batch = batch_size or 64
        seq = int(os.environ.get("ADT_BENCH_LM1B_SEQ", "128"))
        # lean (chunked) LM head: the ONLY head that fits batch 64 on the
        # 16 GB chip (the standard head OOMs — BENCHMARKS.md "Memory-lean
        # LM head"). XLA's cost analysis counts its vocab-chunk scan body
        # once, so the head FLOPs are hand-computed in closed form:
        # fwd logits matmul 2*T*D*V + backward dx and dW matmuls (4*T*D*V)
        # = 6*T*D*V total, of which XLA sees one chunk's worth.
        from autodist_tpu.ops.xent import _layout
        chunk_eff, _n = _layout(cfg.vocab_size, 8192)
        tokens = batch * seq
        flops_extra = 6.0 * tokens * cfg.d_model * (cfg.vocab_size
                                                    - chunk_eff)
        return "lm", dict(config=cfg, batch_size=batch, seq_len=seq,
                          lean_head=True), "tokens", flops_extra
    if label == "smoke":  # tiny CPU-runnable config for harness tests
        return ("resnet18", dict(batch_size=batch_size or 4, image_size=32),
                "image", 0.0)
    raise ValueError(label)


def bench_model(label, pairs=8, iters=4, deadline=None, batch_size=None):
    import jax
    name, setup_kw, batch_key, flops_extra = _model_spec(label, batch_size)
    print("bench_model:", label, setup_kw, file=sys.stderr, flush=True)
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy
    from autodist_tpu.models import make_train_setup

    loss_fn, params, batch_np, _ = make_train_setup(name, **setup_kw)
    opt = optax.adam(1e-3)
    batch_size = int(np.shape(batch_np[batch_key])[0])

    # ---- baseline: plain jit data-parallel step, donated state,
    #      device-resident batch
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def baseline_step(p, s, b):
        loss, g = jax.value_and_grad(loss_fn)(p, b)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, loss

    base_batch = jax.device_put(batch_np)
    # the baseline donates its state buffers, so it needs its OWN copies
    # (the originals feed the framework path later) — copied ON DEVICE:
    # a device_get/device_put round trip costs minutes for bert-sized
    # params when the host<->device link is a throttled tunnel
    import jax.numpy as jnp
    copy_tree = jax.jit(lambda t: jax.tree_util.tree_map(jnp.copy, t))
    base_box = [copy_tree(params), jax.jit(opt.init)(params)]
    t0 = time.perf_counter()
    # AOT-compile once and call the executable directly: one compile serves
    # both the FLOPs count and the baseline steps
    baseline_exec = baseline_step.lower(
        base_box[0], base_box[1], base_batch).compile()
    flops = _compiled_flops(baseline_exec)
    if flops:
        flops += flops_extra  # closed-form scan-body correction
    print("  baseline compiled in %.1fs, flops/step=%.3g"
          % (time.perf_counter() - t0, flops), file=sys.stderr, flush=True)

    def run_baseline():
        p, s, loss = baseline_exec(base_box[0], base_box[1], base_batch)
        base_box[0], base_box[1] = p, s
        return loss

    # ---- framework: AllReduce strategy through the full stack
    adt.reset()
    ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
    runner = ad.build(loss_fn, opt, params, batch_np)
    runner.init(params)
    sharded = runner.remapper.remap_feed(batch_np)
    state_box = [runner.state]

    def run_fw():
        st, m = runner.distributed_step(state_box[0], sharded)
        state_box[0] = st
        return m["loss"]

    # warmup (compile + a few steps each)
    t0 = time.perf_counter()
    for _ in range(3):
        lb = run_baseline()
        lf = run_fw()
    _sync(lb), _sync(lf)
    print("  warmup done in %.1fs" % (time.perf_counter() - t0),
          file=sys.stderr, flush=True)

    # adaptive phase length: short steps need more iterations per phase or
    # a single throttle window dominates the pair ratio (bert-sized steps
    # at 4 iters/phase swung medians 0.87-1.00 between runs). The probe is
    # a median of 3 so one throttled probe step can't pin iters low.
    probes = []
    for _ in range(3):
        t0 = time.perf_counter()
        _sync(run_fw())
        probes.append(time.perf_counter() - t0)
    step_s = max(statistics.median(probes), 1e-4)
    iters = max(iters, min(64, int(round(1.0 / step_s))))
    print("  step=%.0fms -> %d iters/phase" % (step_s * 1e3, iters),
          file=sys.stderr, flush=True)

    ratios, fw_rates = [], []
    for k in range(pairs):
        if deadline is not None and ratios and time.perf_counter() > deadline:
            print("  deadline: stopping after %d pairs" % len(ratios),
                  file=sys.stderr, flush=True)
            break
        if k % 2 == 0:
            rb = _phase_rate(run_baseline, iters)
            rf = _phase_rate(run_fw, iters)
        else:
            rf = _phase_rate(run_fw, iters)
            rb = _phase_rate(run_baseline, iters)
        ratios.append(rf / rb)
        fw_rates.append(rf)
    fused_extra = _maybe_fused_phases(runner, state_box, sharded, run_fw,
                                      iters)
    wire_extra = _wire_dtype_phases(loss_fn, opt, params, batch_np,
                                    run_fw, iters)
    zero_extra = _zero_phases(loss_fn, opt, params, batch_np, run_fw,
                              iters)
    overlap_extra = _overlap_phases(loss_fn, opt, params, batch_np,
                                    run_fw, iters)
    bf16_extra = _bf16_phases(loss_fn, opt, params, batch_np, run_fw,
                              iters)
    adt.reset()
    search_extra = _search_phases(loss_fn, opt, params, batch_np, iters,
                                  fw_rates, deadline)
    best_rate = max(fw_rates)  # steady-state (least-throttled) phase
    # flops is the GLOBAL per-step count; aggregate peak scales with the
    # device count the framework step runs over
    agg_peak = _chip_peak() * len(jax.devices())
    mfu = (flops * best_rate / agg_peak) if flops else 0.0
    # median alongside best: best is the steady-state claim under a
    # throttled shared chip, median is the can't-be-cherry-picked floor
    mfu_median = (flops * statistics.median(fw_rates) / agg_peak
                  if flops else 0.0)
    out = {
        "examples_per_sec": round(statistics.median(fw_rates) * batch_size, 2),
        "vs_baseline": round(statistics.median(ratios), 4),
        "mfu": round(mfu, 4),
        "mfu_median": round(mfu_median, 4),
        "flops_per_step": flops,
        "batch_size": batch_size,
        "pairs": len(ratios),
    }
    out.update(fused_extra)
    out.update(wire_extra)
    out.update(zero_extra)
    out.update(overlap_extra)
    out.update(bf16_extra)
    out.update(search_extra)
    return out


def _paired_strategy_phases(builder, loss_fn, opt, params, batch_np,
                            run_fw, iters, steps, tol, leg):
    """Shared mechanics of the opt-in paired strategy harnesses
    (`_wire_dtype_phases`, `_zero_phases`): build the SAME model under
    ``builder``, train a short accuracy leg, snapshot the telemetry
    counters, train a FRESH fp32 `AllReduce()` reference from identical
    params on the identical batch (the main `run_fw` runner has already
    trained through warmup/probe/pair phases — comparing against it
    would measure training progress, not the variant's error), assert
    final-loss parity within ``tol``, then run order-alternated paired
    throughput phases against the main framework path. Returns
    ``(variant_losses, ref_losses, median_ratio, counters,
    variant_runner)`` — callers add their leg-specific assertions."""
    import autodist_tpu as adt
    from autodist_tpu import strategy
    from autodist_tpu.telemetry import spans as tel
    adt.reset()
    ad = adt.AutoDist(strategy_builder=builder)
    vrunner = ad.build(loss_fn, opt, params, batch_np)
    vrunner.init(params)
    vsharded = vrunner.remapper.remap_feed(batch_np)
    vbox = [vrunner.state]

    def run_v():
        st, m = vrunner.distributed_step(vbox[0], vsharded)
        vbox[0] = st
        return m["loss"]

    v_losses = [_sync(run_v()) for _ in range(steps)]
    counters = dict(tel.counters())
    adt.reset()
    ad_fp = adt.AutoDist(strategy_builder=strategy.AllReduce())
    frunner = ad_fp.build(loss_fn, opt, params, batch_np)
    frunner.init(params)
    fsharded = frunner.remapper.remap_feed(batch_np)
    fbox = [frunner.state]
    f_losses = []
    for _ in range(steps):
        st, m = frunner.distributed_step(fbox[0], fsharded)
        fbox[0] = st
        f_losses.append(_sync(m["loss"]))
    final_gap = abs(v_losses[-1] - f_losses[-1]) / max(
        abs(f_losses[-1]), 1e-9)
    assert final_gap <= tol, (
        "%s broke loss parity: %.6g vs fp32 %.6g (gap %.3f > tol %.3f)"
        % (leg, v_losses[-1], f_losses[-1], final_gap, tol))
    ratios = []
    for j in range(4):
        if j % 2 == 0:
            rv = _phase_rate(run_v, iters)
            rf = _phase_rate(run_fw, iters)
        else:
            rf = _phase_rate(run_fw, iters)
            rv = _phase_rate(run_v, iters)
        ratios.append(rv / rf)
    return (v_losses, f_losses, statistics.median(ratios), counters,
            vrunner)


def _wire_dtype_phases(loss_fn, opt, params, batch_np, run_fw, iters):
    """Opt-in (ADT_BENCH_WIRE_DTYPE=int8) quantized-wire accuracy +
    throughput harness for the artifact rounds: builds the SAME model
    under ``AllReduce(wire_dtype="int8")``, runs order-alternated paired
    phases against the fp32 framework path, trains a short paired leg
    from identical params on identical batches, and ASSERTS loss-curve
    parity (final loss within the harness tolerance,
    ADT_BENCH_WIRE_TOL, default 10%). Reports the telemetry-measured
    wire reduction (wire.bytes_quantized / wire.bytes_saved — the >= 3x
    payload-drop criterion reads straight off these). Best-effort: a
    failure is recorded, never fatal to the model's main result."""
    mode = (os.environ.get("ADT_BENCH_WIRE_DTYPE", "") or "").strip()
    if mode not in ("int8", "1"):
        return {}
    from autodist_tpu import strategy
    tol = float(os.environ.get("ADT_BENCH_WIRE_TOL", "0.1"))
    steps = int(os.environ.get("ADT_BENCH_WIRE_STEPS", "8"))
    try:
        q_losses, f_losses, ratio, counters, _ = _paired_strategy_phases(
            strategy.AllReduce(wire_dtype="int8"), loss_fn, opt, params,
            batch_np, run_fw, iters, steps, tol, "quantized wire")
        quantized = counters.get("wire.bytes_quantized", 0.0)
        saved = counters.get("wire.bytes_saved", 0.0)
        assert quantized > 0 and saved > 0, counters
        reduction = (quantized + saved) / quantized
        return {"wire_dtype": "int8",
                "wire_reduction_x": round(reduction, 3),
                "wire_bytes_quantized": quantized,
                "wire_bytes_saved": saved,
                "wire_loss_final": [round(q_losses[-1], 6),
                                    round(f_losses[-1], 6)],
                "wire_vs_fp32": round(ratio, 4)}
    except Exception as e:  # noqa: BLE001 — opt-in extra, never fatal
        print("  wire-dtype phases failed: %s" % e, file=sys.stderr,
              flush=True)
        return {"wire_dtype": "int8",
                "wire_error": "%s: %s" % (type(e).__name__, str(e)[:160])}


def _zero_phases(loss_fn, opt, params, batch_np, run_fw, iters):
    """Opt-in (ADT_BENCH_ZERO=1) ZeRO-sharded-update harness for the
    artifact rounds: builds the SAME model under ``ZeroSharded()``,
    trains a short paired leg from identical params on identical batches
    and ASSERTS loss parity with the fp32 AllReduce path (the fp32
    sharded update is exact modulo float reassociation — tolerance
    ADT_BENCH_ZERO_TOL, default 2%), checks the projected per-chip
    opt-state saving is positive (zero.hbm_saved_bytes — the number the
    ADT501 gate stops charging), and runs order-alternated paired
    throughput phases against the plain AllReduce framework path (rs+ag
    move the same ring bytes, so the ratio isolates launch overhead).
    Best-effort: a failure is recorded, never fatal."""
    if (os.environ.get("ADT_BENCH_ZERO", "") or "").strip() not in ("1",):
        return {}
    from autodist_tpu import strategy
    tol = float(os.environ.get("ADT_BENCH_ZERO_TOL", "0.02"))
    steps = int(os.environ.get("ADT_BENCH_ZERO_STEPS", "8"))
    try:
        z_losses, f_losses, ratio, counters, zrunner = \
            _paired_strategy_phases(
                strategy.ZeroSharded(), loss_fn, opt, params, batch_np,
                run_fw, iters, steps, tol, "sharded update")
        meta = zrunner.distributed_step.metadata
        saved = float(meta.get("zero_hbm_saved_bytes", 0.0))
        assert meta.get("zero_sharded"), "no variable took the zero path"
        assert saved > 0, "zero leg projects no opt-state HBM saving"
        assert counters.get("zero.rs_bytes", 0.0) > 0, counters
        assert counters.get("zero.ag_bytes", 0.0) > 0, counters
        return {"zero_sharded_vars": len(meta["zero_sharded"]),
                "zero_hbm_saved_bytes": saved,
                "zero_rs_bytes": counters.get("zero.rs_bytes", 0.0),
                "zero_ag_bytes": counters.get("zero.ag_bytes", 0.0),
                "zero_loss_final": [round(z_losses[-1], 6),
                                    round(f_losses[-1], 6)],
                "zero_vs_allreduce": round(ratio, 4)}
    except Exception as e:  # noqa: BLE001 — opt-in extra, never fatal
        print("  zero phases failed: %s" % e, file=sys.stderr, flush=True)
        return {"zero_error": "%s: %s" % (type(e).__name__, str(e)[:160])}


def _overlap_phases(loss_fn, opt, params, batch_np, run_fw, iters):
    """Opt-in (ADT_BENCH_OVERLAP=1) comm/compute-overlap harness for the
    artifact rounds: builds the SAME model under
    ``AllReduce(chunk_size=<small>, overlap=True)`` — the bucketed
    gradient-sync schedule, reverse layer order, barrier-chained so XLA
    can hide each bucket's reduce behind the remaining backward — trains
    a short paired leg from identical params on identical batches,
    ASSERTS loss parity with the epilogue path (the schedule reorders
    WHEN collectives launch, never what they compute — tolerance
    ADT_BENCH_OVERLAP_TOL, default 0.1%), checks the lowering really
    armed a multi-stage schedule (metadata + overlap.buckets), and
    reports the order-alternated paired throughput ratio. Best-effort:
    a failure is recorded, never fatal."""
    if (os.environ.get("ADT_BENCH_OVERLAP", "") or "").strip() not in ("1",):
        return {}
    from autodist_tpu import strategy
    tol = float(os.environ.get("ADT_BENCH_OVERLAP_TOL", "0.001"))
    steps = int(os.environ.get("ADT_BENCH_OVERLAP_STEPS", "8"))
    chunk = int(os.environ.get("ADT_BENCH_OVERLAP_CHUNK", "8"))
    try:
        o_losses, f_losses, ratio, counters, orunner = \
            _paired_strategy_phases(
                strategy.AllReduce(chunk_size=chunk, overlap=True),
                loss_fn, opt, params, batch_np, run_fw, iters, steps,
                tol, "overlap schedule")
        meta = orunner.distributed_step.metadata
        assert meta.get("overlap"), "overlap never armed: %s" % meta
        stages = int(meta.get("overlap_stages", 0))
        assert stages >= 2, "degenerate %d-stage schedule" % stages
        assert counters.get("overlap.buckets", 0.0) > 0, counters
        return {"overlap_stages": stages,
                "overlap_loss_final": [round(o_losses[-1], 6),
                                       round(f_losses[-1], 6)],
                "overlap_vs_epilogue": round(ratio, 4)}
    except Exception as e:  # noqa: BLE001 — opt-in extra, never fatal
        print("  overlap phases failed: %s" % e, file=sys.stderr, flush=True)
        return {"overlap_error": "%s: %s" % (type(e).__name__, str(e)[:160])}


def _bf16_phases(loss_fn, opt, params, batch_np, run_fw, iters):
    """Opt-in (ADT_BENCH_BF16=1) managed-bf16-compute harness for the
    artifact rounds: builds the SAME model under
    ``AllReduce(compute_dtype="bf16")`` — bf16 forward/backward beside
    the f32 master params the ADT60x analyzer certifies — trains a short
    paired leg from identical params on identical batches, ASSERTS
    final-loss parity with the f32 path (tolerance ADT_BENCH_BF16_TOL,
    default 5%), checks the lowered step really runs the half tier
    (metadata ``compute_dtype``), and reports the order-alternated
    paired throughput ratio — the bf16-vs-f32 pair the search's compute
    axis is priced against. Best-effort: a failure is recorded, never
    fatal to the model's main result."""
    if (os.environ.get("ADT_BENCH_BF16", "") or "").strip() not in ("1",):
        return {}
    from autodist_tpu import strategy
    tol = float(os.environ.get("ADT_BENCH_BF16_TOL", "0.05"))
    steps = int(os.environ.get("ADT_BENCH_BF16_STEPS", "8"))
    try:
        b_losses, f_losses, ratio, _counters, brunner = \
            _paired_strategy_phases(
                strategy.AllReduce(compute_dtype="bf16"), loss_fn, opt,
                params, batch_np, run_fw, iters, steps, tol,
                "bf16 compute")
        meta = brunner.distributed_step.metadata
        assert meta.get("compute_dtype") == "bf16", meta
        return {"bf16_compute": True,
                "bf16_loss_final": [round(b_losses[-1], 6),
                                    round(f_losses[-1], 6)],
                "bf16_vs_f32": round(ratio, 4)}
    except Exception as e:  # noqa: BLE001 — opt-in extra, never fatal
        print("  bf16 phases failed: %s" % e, file=sys.stderr, flush=True)
        return {"bf16_error": "%s: %s" % (type(e).__name__, str(e)[:160])}


def _maybe_fused_phases(runner, state_box, sharded, run_fw, iters):
    """Opt-in (ADT_BENCH_FUSED=k) paired fused-vs-per-step phases for the
    artifact rounds: the fused engine runs k microsteps per dispatch over
    a [k, ...] stack of the SAME batch, so the ratio isolates the per-step
    host round-trip the fusion removes. Best-effort — a failure here is
    recorded, never fatal to the model's main result."""
    fuse_k = int(os.environ.get("ADT_BENCH_FUSED", "0") or 0)
    if fuse_k <= 1:
        return {}
    import jax
    try:
        import numpy as np
        host = jax.tree_util.tree_map(
            lambda v: np.stack([np.asarray(jax.device_get(v))] * fuse_k),
            sharded)
        stacked = runner.remapper.remap_feed_stack(host)

        def run_fw_fused():
            st, m = runner.distributed_step.run_multi(state_box[0], stacked)
            state_box[0] = st
            return m["loss"][-1]

        _sync(run_fw_fused())  # compile + one superstep
        fused_iters = max(1, iters // fuse_k)
        ratios = []
        for j in range(4):
            if j % 2 == 0:
                rp = _phase_rate(run_fw, iters)
                rf = _phase_rate(run_fw_fused, fused_iters)
            else:
                rf = _phase_rate(run_fw_fused, fused_iters)
                rp = _phase_rate(run_fw, iters)
            # rf counts SUPERSTEPS; x k converts to microsteps/s
            ratios.append(rf * fuse_k / rp)
        return {"fuse_steps": fuse_k,
                "fused_vs_per_step": round(statistics.median(ratios), 4)}
    except Exception as e:  # noqa: BLE001 — opt-in extra, never fatal
        print("  fused phases failed: %s" % e, file=sys.stderr, flush=True)
        return {"fuse_steps": fuse_k,
                "fused_error": "%s: %s" % (type(e).__name__, str(e)[:160])}


def _search_phases(loss_fn, opt, params, batch_np, iters, fw_rates,
                   deadline):
    """Searched-vs-zoo leg of each model bench: run the per-variable plan
    search (autodist_tpu/search/) on the bench model, scored through the
    calibrated cost model — static only, NO candidate is compiled, so this
    is seconds even for the flagship models — and record the searched and
    best-zoo ESTIMATED step times side by side. With ADT_BENCH_SEARCH=1
    the chosen plan is additionally compiled through the full stack and
    timed, recording the MEASURED searched step rate beside the main
    path's rates (sequential phases, not paired: the process holds one
    AutoDist at a time). Best-effort — a failure here is recorded, never
    fatal to the model's main result."""
    if deadline is not None and time.perf_counter() > deadline:
        return {"search": {"skipped": "model budget exhausted"}}
    try:
        from autodist_tpu.model_item import ModelItem
        from autodist_tpu.resource_spec import ResourceSpec
        from autodist_tpu.search.drivers import SearchConfig, run_search
        from autodist_tpu.search.scoring import zoo_best
        from autodist_tpu.simulator.simulator import Simulator

        item = ModelItem(loss_fn=loss_fn, optimizer=opt, params=params,
                         example_batch=batch_np).prepare()
        spec = ResourceSpec.from_local()
        sim = Simulator(item, spec)
        budget = int(os.environ.get("ADT_BENCH_SEARCH_BUDGET", "64"))
        res = run_search(item, spec, config=SearchConfig(budget=budget),
                         simulator=sim)
        if not res.ok:
            return {"search": {"error": "all %d candidates pruned (%s)"
                               % (res.candidates,
                                  res.trace.prune_reasons())}}
        zoo_label, zoo_score, zoo = zoo_best(item, spec, sim)
        doc = {"plan": res.trace.result["plan"],
               "est_searched_ms": round(res.record.step_time_s * 1e3, 4),
               "zoo_best": zoo_label,
               "est_zoo_ms": round(zoo.step_time_s * 1e3, 4),
               "beats_zoo": bool(res.record.score_s <= zoo_score + 1e-12),
               "candidates": res.candidates, "pruned": res.pruned,
               "search_s": round(res.wall_s, 3)}
        print("  search: %s est %.3f ms vs zoo %s %.3f ms (%.1fs)"
              % (doc["plan"], doc["est_searched_ms"], zoo_label,
                 doc["est_zoo_ms"], res.wall_s),
              file=sys.stderr, flush=True)
    except Exception as e:  # noqa: BLE001 — extra leg, never fatal
        print("  search leg failed: %s" % e, file=sys.stderr, flush=True)
        return {"search": {"error": "%s: %s" % (type(e).__name__,
                                                str(e)[:160])}}
    if (os.environ.get("ADT_BENCH_SEARCH", "0") or "0") != "0":
        doc.update(_measured_search_phases(loss_fn, opt, params, batch_np,
                                           res.strategy, iters, fw_rates))
    return {"search": doc}


def _measured_search_phases(loss_fn, opt, params, batch_np, strategy,
                            iters, fw_rates):
    """Opt-in (ADT_BENCH_SEARCH=1) measured side of the search leg:
    compile the searched plan through the full stack and time it."""
    import autodist_tpu as adt
    from autodist_tpu.strategy.base import StrategyBuilder

    class _Fixed(StrategyBuilder):
        def __init__(self, s):
            self._s = s

        def build(self, model_item, resource_spec):
            return self._s

    try:
        adt.reset()
        ad = adt.AutoDist(strategy_builder=_Fixed(strategy))
        runner = ad.build(loss_fn, opt, params, batch_np)
        runner.init(params)
        sharded = runner.remapper.remap_feed(batch_np)
        box = [runner.state]

        def run_searched():
            st, m = runner.distributed_step(box[0], sharded)
            box[0] = st
            return m["loss"]

        lo = None
        for _ in range(2):
            lo = run_searched()
        _sync(lo)
        rates = [_phase_rate(run_searched, iters) for _ in range(4)]
        adt.reset()
        r = statistics.median(rates)
        return {"measured_searched_steps_per_s": round(r, 4),
                "measured_vs_zoo": round(r / statistics.median(fw_rates),
                                         4)}
    except Exception as e:  # noqa: BLE001 — opt-in extra, never fatal
        print("  measured search phases failed: %s" % e, file=sys.stderr,
              flush=True)
        return {"measured_error": "%s: %s" % (type(e).__name__,
                                              str(e)[:160])}


def smoke_main(fused: bool = False):
    """CI leg (``bench.py --smoke [--fused]``): a tiny MLP through the
    full stack on CPU — seconds, not minutes. With ``--fused`` it also
    compiles the fused multi-step engine (``fit(fuse_steps=4,
    metrics_every=2)``), asserts parity with the per-step loop AND the
    k× dispatch reduction, and reports the paired fused-vs-per-step
    throughput ratio — so the scan-fused lowering path compiles (and
    stays numerically honest) on every PR.

    Under ``ADT_TRACE=1`` the run also exports a Perfetto-loadable trace
    (``ADT_TRACE_FILE`` or ``<trace dir>/smoke-trace.json``), validates
    it against the chrome-trace schema, and embeds a per-subsystem
    timing breakdown + the registry counters in the BENCH json — future
    rounds get phase-level attribution of where the smoke seconds went."""
    # >= 2 virtual devices so a REAL gradient wire exists for the
    # quantized-AR leg (takes effect as long as the backend has not
    # initialized yet; the leg falls back to the host-PS wire otherwise)
    if "--xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2").strip()
    import jax
    jax.config.update("jax_platforms",
                      os.environ.get("ADT_BENCH_PLATFORM") or "cpu")
    import numpy as np
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy

    rng = np.random.RandomState(0)
    params = {"w1": rng.randn(16, 32).astype(np.float32) * 0.1,
              "b1": np.zeros((32,), np.float32),
              "w2": rng.randn(32, 4).astype(np.float32) * 0.1}

    def loss_fn(p, b):
        import jax.numpy as jnp
        h = jnp.tanh(b["x"] @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] - b["y"]) ** 2)

    batches = [{"x": rng.randn(32, 16).astype(np.float32),
                "y": rng.randn(32, 4).astype(np.float32)}
               for _ in range(16)]

    def build():
        adt.reset()
        ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
        runner = ad.build(loss_fn, optax.adam(1e-2), params, batches[0])
        runner.init(params)
        return runner

    # sentinel + quantized-wire legs FIRST: their builds reset the
    # telemetry recorder, and the exported smoke trace / phase breakdown
    # must cover the main plain+fused legs below (the same ordering
    # constraint the serve bench documents for its per-model resets)
    sentinel_result = _smoke_sentinel(loss_fn, params, batches,
                                      len(batches))
    quantized_result = _smoke_quantized_wire(loss_fn, params, batches)
    zero_result = _smoke_zero(loss_fn, params, batches)
    overlap_result = _smoke_overlap(loss_fn, params, batches)
    bf16_result = _smoke_bf16(loss_fn, params, batches)

    t0 = time.perf_counter()
    r1 = build()
    h1 = r1.fit(list(batches))
    per_step_s = time.perf_counter() - t0
    result = {"metric": "smoke", "per_step_loop_s": round(per_step_s, 3),
              "steps": len(h1), "final_loss": round(float(h1[-1]["loss"]), 6)}
    if fused:
        k = 4
        t0 = time.perf_counter()
        r2 = build()
        h2 = r2.fit(list(batches), fuse_steps=k, metrics_every=2)
        result["fused_loop_s"] = round(time.perf_counter() - t0, 3)
        d1, d2 = (r1.distributed_step.dispatches,
                  r2.distributed_step.dispatches)
        assert d2 == d1 // k, "dispatches %d != %d/%d" % (d2, d1, k)
        np.testing.assert_allclose([m["loss"] for m in h1],
                                   [m["loss"] for m in h2],
                                   rtol=1e-5, atol=1e-6)
        # snapshot stats BEFORE the paired loops: the registry (process-
        # global) still holds exactly r2's fused fit here, so the
        # telemetry section agrees with the per-runner step counts beside
        # it — after loop_plain it would also count r1's per-step work
        fused_stats = r2.step_stats()
        # steady-state paired ratio (post-compile): per-step vs fused
        def loop_plain():
            r1.fit(list(batches))
        def loop_fused():
            r2.fit(list(batches), fuse_steps=k, metrics_every=4)
        t0 = time.perf_counter(); loop_plain(); tp = time.perf_counter() - t0
        t0 = time.perf_counter(); loop_fused(); tf = time.perf_counter() - t0
        result.update(fuse_steps=k, dispatches=[d1, d2],
                      fused_vs_per_step=round(tp / max(tf, 1e-9), 4),
                      stats=fused_stats)
    result["sentinel"] = sentinel_result
    result["quantized_wire"] = quantized_result
    result["zero_sharded"] = zero_result
    result["overlap"] = overlap_result
    result["bf16_compute"] = bf16_result
    result["search"] = _smoke_search(loss_fn, params, batches[0])
    result["topology"] = _smoke_topology(loss_fn, params, batches[0])
    # trace export BEFORE the elastic leg: its builds reset the recorder
    # (and its reconfigure clears the XLA backend — rebuilt on demand,
    # but the paired timing legs above must not pay that), so it runs
    # dead last with the main legs' telemetry already harvested
    result.update(_smoke_telemetry())
    result["elastic"] = _smoke_elastic(loss_fn, params, batches)
    result["preempt"] = _smoke_preempt(loss_fn, params, batches)
    result["autoscale"] = _smoke_autoscale(loss_fn, params, batches)
    adt.reset()
    print(RESULT_TAG + json.dumps(result), flush=True)


@contextlib.contextmanager
def _inrun_elastic_sandbox(extra_env=None):
    """Shared harness of the elastic/preempt smoke legs: a fresh
    coordination service on a free port, the in-run elastic knobs
    exported (restored afterwards), and a clean AutoDist registry on
    entry AND exit. Yields the service port."""
    import socket

    import autodist_tpu as adt
    from autodist_tpu.runtime.coordination import CoordinationServer

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {"ADT_COORDSVC_PORT": str(port), "ADT_ELASTIC": "1",
           "ADT_ELASTIC_SYNC": "1", "ADT_ELASTIC_INRUN": "1",
           "ADT_ELASTIC_POLL_S": "0.01"}
    env.update(extra_env or {})
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    srv = None
    try:
        # INSIDE the try: a bind race / failed service start must still
        # restore the exported elastic knobs, or they silently apply to
        # everything that runs after this leg in the same process
        srv = CoordinationServer(port)
        srv.start()
        adt.reset()
        yield port
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        adt.reset()
        if srv is not None:
            srv.stop()


def _smoke_preempt(loss_fn, params, batches):
    """Preemption leg of the smoke bench: two symmetric shrink legs of a
    2-member roster (this process + a phantom peer) down to 1 — one
    PLANNED (the peer announces its departure: cluster-agreed rescue
    checkpoint, pre-staged snapshot, ``planned`` reconfigure) and one
    UNPLANNED (no notice; the snapshot is taken inside the reconfigure
    span) — so every BENCH round records rescue-save latency and
    planned-handoff downtime NEXT TO the unplanned-shrink downtime, plus
    the detection floor (``ADT_HEARTBEAT_TIMEOUT_S``) only the
    un-announced death pays end to end. The planned leg runs FIRST (any
    process-level cache warming then favors the baseline). Asserted on
    the planned leg: exactly one rescue save, zero ``ckpt.fallback``
    restores."""
    import tempfile

    import optax
    import autodist_tpu as adt
    from autodist_tpu import const, strategy
    from autodist_tpu.runtime import elastic, preemption
    from autodist_tpu.runtime.coordination import CoordinationClient
    from autodist_tpu.telemetry import spans as tel

    def shrink_leg(planned):
        """Fresh service + runner: pre-published [me, phantom] roster,
        then a shrink to [me] — announced (notice first) or not.
        Returns (downtime_s, step_stats)."""
        ckpt_dir = tempfile.mkdtemp(prefix="adt-preempt-smoke-")
        with _inrun_elastic_sandbox({"ADT_PREEMPT_POLL_S": "0.01",
                                     "ADT_CKPT_DIR": ckpt_dir}) as port:
            client = CoordinationClient("127.0.0.1", port)
            me = "127.0.0.1"
            elastic.publish_epoch(client, 1, [me, "peer-evicted"])
            ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
            runner = ad.build(loss_fn, optax.adam(1e-2), params,
                              batches[0])
            runner.init(params)
            n = len(batches)
            for i, b in enumerate(batches):
                runner.run(b)
                if planned and i == 2:
                    # the peer's eviction is announced: rescue
                    # checkpoint at the agreed boundary + pre-stage
                    preemption.publish_notice(client, "peer-evicted",
                                              deadline_s=60,
                                              reason="maintenance")
                    time.sleep(0.05)
                elif i == n // 2:
                    # the shrink epoch (for the planned leg: published
                    # while the announced leaver is still "alive")
                    elastic.publish_epoch(client, 2, [me])
                    time.sleep(0.05)
            client.close()
            stats = runner.step_stats()
            assert stats["elastic"]["reconfigs"] == 1, stats["elastic"]
            # counters/histograms must be read INSIDE the sandbox: its
            # teardown resets the telemetry recorder
            leg_telemetry = (tel.counters().get("ckpt.fallback", 0.0),
                             tel.hist_quantile("preempt.rescue_save_ms",
                                               0.5))
            return (stats["elastic"]["last_reconfigure_s"], stats,
                    leg_telemetry)

    try:
        planned_s, planned_stats, (fallback, rescue_ms) = \
            shrink_leg(planned=True)
        assert planned_stats["preempt"]["rescue_saves"] == 1.0, \
            planned_stats["preempt"]
        assert fallback == 0.0, "planned handoff touched ckpt.fallback"
        unplanned_s, _, _ = shrink_leg(planned=False)
        # the structural gap: an UN-announced death is invisible until
        # the watchdog's heartbeat window expires, so its end-to-end
        # downtime floors at detection + reconfigure; an announced
        # departure pays reconfigure alone (the notice precedes the
        # death). The reconfigure spans are recorded raw side by side;
        # the *_total_* fields add that detection floor.
        detect_floor = const.ENV.ADT_HEARTBEAT_TIMEOUT_S.val
        return {
            "rescue_save_ms": round(rescue_ms or 0.0, 2),
            "planned_handoff_downtime_s": round(planned_s, 4),
            "unplanned_shrink_downtime_s": round(unplanned_s, 4),
            "unplanned_detection_floor_s": round(detect_floor, 1),
            "planned_total_downtime_s": round(planned_s, 4),
            "unplanned_total_downtime_s": round(unplanned_s + detect_floor,
                                                4),
            "notices": planned_stats["preempt"]["notices"],
            "rescue_saves": planned_stats["preempt"]["rescue_saves"],
            "ckpt_fallback": fallback,
        }
    except Exception as e:  # noqa: BLE001 — a broken preempt leg must
        # not sink the whole smoke round; surface it in the json instead
        print("[bench] preempt smoke leg failed: %s" % e, file=sys.stderr,
              flush=True)
        return {"error": "%s: %s" % (type(e).__name__, str(e)[:160])}


def _smoke_autoscale(loss_fn, params, batches, osc=False):
    """Autoscale leg (``bench.py --autoscale``, and the smoke round):
    the REAL serving stack (engine + micro-batcher) under a seeded load
    ramp, with a :class:`FleetAutoscaler` closing the loop against a
    phantom-peer fleet — launch roster ``[me, replica-b]``, pool
    ``[replica-c, replica-d]``, so the 2→4→2 ramp exercises the real
    admission/retirement wire without extra processes (the phantom
    pattern the preempt leg established). The engine gets a synthetic
    per-batch service time so a burst SUSTAINS a backlog on CPU.

    Ramp leg asserts: >= 1 grow under sustained queue depth, >= 1
    planned shrink (preemption notice + survivor epoch) back down, zero
    ``ckpt.fallback``, zero sheds OUTSIDE the overload window, at least
    one brownout entry and one deadline shed (the degradation paths),
    and every observed shed carrying a populated ``retry_after_s``.
    Oscillating leg (``osc=True``): bursts shorter than the policy's
    sustain window must produce at most 2 scale events — the hysteresis
    band + sustain window bound flap, which is the whole point."""
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy
    from autodist_tpu.runtime import elastic
    from autodist_tpu.runtime.coordination import CoordinationClient
    from autodist_tpu.serving import (AutoscalePolicy, FleetAutoscaler,
                                      InferenceEngine, MicroBatcher,
                                      ServingConfig, ServingUnavailable)
    from autodist_tpu.telemetry import spans as tel

    try:
        with _inrun_elastic_sandbox({"ADT_PREEMPT_POLL_S": "0.01"}) as port:
            client = CoordinationClient("127.0.0.1", port)
            me = "127.0.0.1"
            elastic.publish_epoch(client, 1, [me, "replica-b"])
            ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
            runner = ad.build(loss_fn, optax.adam(1e-2), params,
                              batches[0])
            runner.init(params)
            import jax.numpy as jnp

            def serve_fn(p, b):
                h = jnp.tanh(b["x"] @ p["w1"] + p["b1"])
                return {"y": h @ p["w2"]}

            replicas = runner.remapper.num_replicas
            engine = InferenceEngine(
                runner, serve_fn, {"x": batches[0]["x"][0]},
                ServingConfig(buckets=(replicas, 8 * replicas),
                              max_delay_ms=2.0, max_queue=64,
                              brownout_queue_frac=0.5,
                              brownout_sustain_s=0.02,
                              brownout_delay_factor=4.0)).warmup()
            mb = MicroBatcher(engine)
            # synthetic service time: the smoke MLP would drain any
            # burst instantly on CPU, and the controller needs a backlog
            # that SUSTAINS past its window to have anything to measure
            real_run = engine.run_batch

            def slow_run(reqs):
                time.sleep(0.015)
                return real_run(reqs)

            engine.run_batch = slow_run
            if osc:
                # sustain window LONGER than any burst: the leg proves
                # the window + hysteresis band bound scale events
                policy = AutoscalePolicy(min_replicas=2, max_replicas=4,
                                         queue_high=8, queue_low=2,
                                         sustain_s=0.5,
                                         grow_cooldown_s=30.0,
                                         shrink_cooldown_s=30.0)
            else:
                policy = AutoscalePolicy(min_replicas=2, max_replicas=4,
                                         queue_high=8, queue_low=2,
                                         sustain_s=0.05,
                                         grow_cooldown_s=0.02,
                                         shrink_cooldown_s=0.02)
            scaler = FleetAutoscaler(client, policy, me,
                                     pool=["replica-c", "replica-d"],
                                     notice_deadline_s=60.0)
            shed_hints, unset_hints = [], 0
            futures = []

            def burst(n, deadline_every=0):
                for i in range(n):
                    dl = (0.001 if deadline_every
                          and i % deadline_every == 0 else None)
                    try:
                        futures.append(mb.submit(
                            {"x": batches[i % len(batches)]["x"][0]},
                            deadline_s=dl))
                    except ServingUnavailable as e:
                        shed_hints.append(e.retry_after_s)

            def settle(fs):
                nonlocal unset_hints
                for f in fs:
                    try:
                        f.result(timeout=30)
                    except ServingUnavailable as e:
                        shed_hints.append(e.retry_after_s)
                        if e.retry_after_s is None:
                            unset_hints += 1
                fs.clear()

            try:
                if osc:
                    # bursts shorter than the sustain window, drained
                    # between spikes — the fleet must NOT move
                    deadline = time.perf_counter() + 2.0
                    while time.perf_counter() < deadline:
                        burst(12)
                        scaler.step()
                        time.sleep(0.05)
                    settle(futures)
                    st = scaler.stats()
                    events = st["grows"] + st["shrinks"]
                    assert events <= 2, (
                        "oscillating load flapped the fleet: %d scale "
                        "events despite sustain %.1fs > burst length"
                        % (events, policy.sustain_s))
                    assert st["holds"] >= 10, st
                    mb.close()
                    return {"mode": "oscillating",
                            "scale_events": events,
                            "holds": st["holds"],
                            "decisions": st["decisions"]}
                # ---- overload window: sustained backlog, fleet 2 -> 4
                overload_t0 = time.perf_counter()
                grow_deadline = overload_t0 + 10.0
                while ((scaler.stats()["grows"] < 2
                        or mb.stats()["brownout"]["entries"] < 1)
                       and time.perf_counter() < grow_deadline):
                    burst(24, deadline_every=8)
                    scaler.step()
                    time.sleep(0.01)
                shed_in_overload = len(shed_hints)
                settle(futures)
                overload_s = time.perf_counter() - overload_t0
                c_shed_after_overload = tel.counters().get("serve.shed",
                                                           0.0)
                # ---- idle window: no traffic, fleet 4 -> 2 via the
                # planned-departure path
                idle_deadline = time.perf_counter() + 10.0
                while (scaler.stats()["shrinks"] < 2
                       and time.perf_counter() < idle_deadline):
                    scaler.step()
                    time.sleep(0.02)
                idle_shed = (tel.counters().get("serve.shed", 0.0)
                             - c_shed_after_overload)
                st = scaler.stats()
                info = elastic.read_epoch(client)
                stats = mb.stats()
                counters = tel.counters()
                mb.close()
                assert st["grows"] >= 1, "no grow under sustained load: %s" % st
                assert st["shrinks"] >= 1, "no shrink under idle: %s" % st
                assert counters.get("preempt.notices", 0.0) >= 1, (
                    "shrink did not go through the planned-departure "
                    "notice path")
                assert counters.get("ckpt.fallback", 0.0) == 0, (
                    "autoscale shrink touched the checkpoint fallback")
                assert idle_shed == 0, (
                    "%d sheds OUTSIDE the overload window" % idle_shed)
                assert unset_hints == 0 and all(
                    h is not None for h in shed_hints), (
                    "a shed was raised without a populated retry_after_s")
                assert info is not None and len(info[1]) == 2, (
                    "fleet did not return to 2 replicas: %s" % (info,))
                assert stats["brownout"]["entries"] >= 1, (
                    "sustained overload never entered brownout: %s"
                    % stats["brownout"])
                assert stats["deadline_shed"] >= 1, (
                    "expired-deadline requests were not shed: %s"
                    % stats["deadline_shed"])
                return {
                    "mode": "ramp",
                    "grows": st["grows"], "shrinks": st["shrinks"],
                    "holds": st["holds"], "refusals": st["refusals"],
                    "final_epoch": info[0],
                    "final_replicas": len(info[1]),
                    "overload_window_s": round(overload_s, 3),
                    "sheds_in_overload": shed_in_overload,
                    "sheds_outside_overload": idle_shed,
                    "deadline_sheds": stats["deadline_shed"],
                    "brownout_entries": stats["brownout"]["entries"],
                    "notices": counters.get("preempt.notices", 0.0),
                    "ckpt_fallback": counters.get("ckpt.fallback", 0.0),
                    "retry_after_hints": len(shed_hints),
                }
            finally:
                mb.close()  # idempotent; a failed assert must not leak
                # the worker thread into the next leg
                client.close()
    except Exception as e:  # noqa: BLE001 — surfaced in the json; the
        # CLI entry (autoscale_main) re-raises so CI stays strict
        print("[bench] autoscale smoke leg failed: %s" % e,
              file=sys.stderr, flush=True)
        return {"error": "%s: %s" % (type(e).__name__, str(e)[:160])}


def _smoke_elastic(loss_fn, params, batches):
    """Elastic leg of the smoke bench: run the smoke MLP under an in-run
    membership, publish a same-roster epoch bump mid-run, and record what
    one reconfiguration event COSTS — span-derived downtime seconds and
    the steps it blocked (downtime / steady median step) — plus the
    fenced-write counter, so BENCH rounds track the price of an elastic
    event alongside throughput."""
    import numpy as np
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy
    from autodist_tpu.runtime import elastic
    from autodist_tpu.runtime.coordination import CoordinationClient
    from autodist_tpu.telemetry import spans as tel

    try:
        with _inrun_elastic_sandbox() as port:
            ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
            runner = ad.build(loss_fn, optax.adam(1e-2), params, batches[0])
            runner.init(params)
            client = CoordinationClient("127.0.0.1", port)
            m = elastic.current()
            assert m is not None, "elastic membership was not armed"
            for i, b in enumerate(batches):
                runner.run(b)
                if i == len(batches) // 2:
                    elastic.publish_epoch(client, m.epoch + 1, m.roster)
                    time.sleep(0.05)  # let the poll window lapse
            client.close()
            stats = runner.step_stats()
            assert stats["elastic"]["reconfigs"] == 1, stats["elastic"]
            spans = tel.get_recorder().durations_s("elastic.reconfigure")
            downtime = spans[0] if spans else stats["elastic"][
                "last_reconfigure_s"]
            steady = stats["steady_median_s"] or 0.0
            return {
                "reconfigs": stats["elastic"]["reconfigs"],
                "epoch": stats["elastic"]["epoch"],
                "reconfigure_downtime_s": round(float(downtime or 0.0), 4),
                "steps_blocked": (int(np.ceil(downtime / steady))
                                  if downtime and steady else None),
                "fenced_writes": stats["elastic"]["fenced_writes"],
            }
    except Exception as e:  # noqa: BLE001 — a broken elastic leg must
        # not sink the whole smoke round; surface it in the json instead
        print("[bench] elastic smoke leg failed: %s" % e, file=sys.stderr,
              flush=True)
        return {"error": "%s: %s" % (type(e).__name__, str(e)[:160])}


def _smoke_sentinel(loss_fn, params, batches, plain_steps):
    """Health-sentinel leg of the smoke bench: train the smoke MLP with
    in-graph guards armed and a NaN gradient injected at step 3
    (``ADT_GRAD_FAULT_PLAN``) — the poisoned step must be discarded
    in-graph (``sentinel.skips == 1``), the final loss must stay finite,
    and the guarded program must dispatch exactly as often as the
    unguarded loop beside it (the zero-overhead contract: the verdict
    rides the existing metrics readback). Gates every PR on the
    detect-and-skip path actually compiling."""
    import numpy as np
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy
    from autodist_tpu.telemetry import spans as tel

    plan = json.dumps({"faults": [{"var": "w1", "mode": "nan", "step": 3}]})
    prev = os.environ.get("ADT_GRAD_FAULT_PLAN")
    os.environ["ADT_GRAD_FAULT_PLAN"] = plan
    try:
        adt.reset()
        ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
        runner = ad.build(loss_fn, optax.adam(1e-2), params, batches[0],
                          sentinel=True)
        runner.init(params)
        hist = runner.fit(list(batches))
        stats = runner.step_stats()["sentinel"]
        final_loss = float(hist[-1]["loss"])
        assert np.isfinite(final_loss), "sentinel failed to contain the NaN"
        assert stats["skips"] == 1, stats
        assert tel.counters()["sentinel.skips"] == 1
        assert len(hist) == plain_steps
        d = runner.distributed_step.dispatches
        assert d == plain_steps, (
            "guards changed the dispatch count: %d for %d steps"
            % (d, plain_steps))
        return {"skips": stats["skips"], "final_loss": round(final_loss, 6),
                "dispatches": d,
                "last_grad_norm": round(stats["last_grad_norm"], 4)}
    finally:
        if prev is None:
            os.environ.pop("ADT_GRAD_FAULT_PLAN", None)
        else:
            os.environ["ADT_GRAD_FAULT_PLAN"] = prev


def _smoke_quantized_wire(loss_fn, params, batches):
    """Quantized-wire leg of the smoke bench: train the smoke MLP twice —
    fp32 wire vs the blockwise-int8 wire (``AllReduce(wire_dtype=
    "int8")``) — and ASSERT (a) the quantized leg actually saved wire
    bytes (``wire.bytes_saved > 0``, the telemetry counters the lowering
    credits per dispatch), (b) it dispatched exactly as often as the fp32
    leg (the codec lives inside the one program — no extra host
    round-trips), and (c) error feedback kept the loss curve in parity.
    Gates every PR on the two-phase quantized collective compiling AND
    staying honest about its payload reduction."""
    import jax
    import numpy as np
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy
    from autodist_tpu.telemetry import spans as tel

    # single-device fallback: no gradient collective exists, but the
    # host-PS pull/push wire does — quantize that instead
    family = (strategy.AllReduce if len(jax.devices()) > 1
              else strategy.PS)

    def leg(wire):
        adt.reset()
        ad = adt.AutoDist(strategy_builder=family(wire_dtype=wire))
        runner = ad.build(loss_fn, optax.adam(1e-2), params, batches[0])
        runner.init(params)
        hist = runner.fit(list(batches))
        return ([float(m["loss"]) for m in hist],
                runner.distributed_step.dispatches,
                dict(tel.counters()))

    fp_losses, fp_dispatches, _ = leg("fp32")
    q_losses, q_dispatches, counters = leg("int8")
    saved = counters.get("wire.bytes_saved", 0.0)
    quantized = counters.get("wire.bytes_quantized", 0.0)
    assert saved > 0, "quantized leg saved no wire bytes: %s" % counters
    assert q_dispatches == fp_dispatches, (
        "quantized wire changed the dispatch count: %d vs %d"
        % (q_dispatches, fp_dispatches))
    # loss-curve parity: error feedback keeps the quantized trajectory on
    # the fp32 curve (loose per-step band + matching final loss)
    np.testing.assert_allclose(q_losses, fp_losses, rtol=0.2, atol=1e-3)
    assert abs(q_losses[-1] - fp_losses[-1]) <= (
        0.1 * max(abs(fp_losses[-1]), 1e-3) + 1e-3), (q_losses[-1],
                                                      fp_losses[-1])
    reduction = (quantized + saved) / max(quantized, 1.0)
    return {"final_loss_fp32": round(fp_losses[-1], 6),
            "final_loss_int8": round(q_losses[-1], 6),
            "bytes_quantized": quantized, "bytes_saved": saved,
            "wire_reduction_x": round(reduction, 3),
            "dispatches": q_dispatches}


def _smoke_bf16(loss_fn, params, batches):
    """Managed-bf16-compute leg of the smoke bench: train the smoke MLP
    twice — f32 vs ``AllReduce(compute_dtype="bf16")`` with the health
    sentinel armed (the ADT604 contract: half precision ships WITH the
    skip/rollback net) — and ASSERT (a) the bf16 step program really ran
    the half tier (``step_stats()["compute_dtype"] == "bf16"``), (b) the
    master params stayed float32 end to end (the f32-master discipline
    ADT602 certifies), (c) loss-curve parity within the sentinel's
    bounds with ZERO guards tripped (bf16 rounding alone must never look
    like a health fault), and (d) the dispatch count is unchanged (the
    casts live inside the one program). Gates every PR on the bf16
    lowering compiling and staying numerically honest."""
    import jax
    import numpy as np
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy

    def leg(compute_dtype, sentinel=None):
        adt.reset()
        ad = adt.AutoDist(strategy_builder=strategy.AllReduce(
            compute_dtype=compute_dtype))
        runner = ad.build(loss_fn, optax.adam(1e-2), params, batches[0],
                          sentinel=sentinel)
        runner.init(params)
        hist = runner.fit(list(batches))
        return ([float(m["loss"]) for m in hist], runner)

    f_losses, f_runner = leg("f32")
    f_dispatches = f_runner.distributed_step.dispatches
    b_losses, b_runner = leg("bf16", sentinel=True)
    stats = b_runner.step_stats()
    assert stats["compute_dtype"] == "bf16", stats
    leaf_dtypes = {str(x.dtype)
                   for x in jax.tree_util.tree_leaves(
                       b_runner.gather_params())}
    assert leaf_dtypes == {"float32"}, (
        "bf16 compute leaked into the master params: %s" % leaf_dtypes)
    # parity within the sentinel's bounds: bf16 rounds every activation,
    # so the band is wider than the int8 wire's error-feedback leg, but
    # the curve must track and the final losses must agree
    np.testing.assert_allclose(b_losses, f_losses, rtol=0.3, atol=5e-3)
    assert abs(b_losses[-1] - f_losses[-1]) <= (
        0.1 * max(abs(f_losses[-1]), 1e-3) + 1e-3), (b_losses[-1],
                                                     f_losses[-1])
    assert stats["sentinel"]["skips"] == 0, stats["sentinel"]
    assert stats["sentinel"]["rollbacks"] == 0, stats["sentinel"]
    b_dispatches = b_runner.distributed_step.dispatches
    assert b_dispatches == f_dispatches, (
        "bf16 tier changed the dispatch count: %d vs %d"
        % (b_dispatches, f_dispatches))
    return {"final_loss_f32": round(f_losses[-1], 6),
            "final_loss_bf16": round(b_losses[-1], 6),
            "sentinel_skips": stats["sentinel"]["skips"],
            "dispatches": b_dispatches}


def _smoke_zero(loss_fn, params, batches):
    """ZeRO-sharded-update leg of the smoke bench: train the smoke MLP
    under ``ZeroSharded()`` and ASSERT (a) per-step parity with the
    AllReduce loop (the fp32 sharded update is exact modulo float
    reassociation), (b) fused k=4 matches the per-step zero loop with
    the k x dispatch reduction (the sharded opt state rides the scan
    carry), (c) dispatch parity with AllReduce (rs + sharded apply + ag
    all live inside the one program), and (d) the projected per-chip
    opt-state saving is positive (zero.hbm_saved_bytes — what the
    ADT501 plan gate stops charging). Gates every PR on the sharded
    update compiling and staying numerically honest."""
    import numpy as np
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy
    from autodist_tpu.telemetry import spans as tel

    def leg(builder, fuse=0):
        adt.reset()
        ad = adt.AutoDist(strategy_builder=builder)
        runner = ad.build(loss_fn, optax.adam(1e-2), params, batches[0])
        runner.init(params)
        if fuse:
            hist = runner.fit(list(batches), fuse_steps=fuse,
                              metrics_every=1)
        else:
            hist = runner.fit(list(batches))
        return ([float(m["loss"]) for m in hist], runner,
                dict(tel.counters()))

    ar_losses, ar_runner, _ = leg(strategy.AllReduce())
    z_losses, z_runner, counters = leg(strategy.ZeroSharded())
    meta = z_runner.distributed_step.metadata
    assert meta["zero_sharded"], "no variable took the zero path"
    saved = float(meta.get("zero_hbm_saved_bytes", 0.0))
    assert saved > 0, "zero leg projects no opt-state HBM saving"
    assert counters.get("zero.rs_bytes", 0.0) > 0, counters
    assert counters.get("zero.ag_bytes", 0.0) > 0, counters
    assert (z_runner.distributed_step.dispatches
            == ar_runner.distributed_step.dispatches), (
        "sharded update changed the dispatch count")
    np.testing.assert_allclose(z_losses, ar_losses, rtol=1e-4, atol=1e-6)
    zf_losses, zf_runner, _ = leg(strategy.ZeroSharded(), fuse=4)
    np.testing.assert_allclose(zf_losses, z_losses, rtol=1e-5, atol=1e-6)
    assert zf_runner.distributed_step.dispatches == \
        z_runner.distributed_step.dispatches // 4
    return {"final_loss_allreduce": round(ar_losses[-1], 6),
            "final_loss_zero": round(z_losses[-1], 6),
            "zero_sharded_vars": len(meta["zero_sharded"]),
            "hbm_saved_bytes": saved,
            "rs_bytes": counters.get("zero.rs_bytes", 0.0),
            "ag_bytes": counters.get("zero.ag_bytes", 0.0),
            "dispatches": z_runner.distributed_step.dispatches}


def _smoke_overlap(loss_fn, params, batches):
    """Comm/compute-overlap leg of the smoke bench: train the smoke MLP
    under ``AllReduce(chunk_size=1, overlap=True)`` — one sync unit per
    variable, lowered as the reverse-layer-order barrier-chained
    schedule — and ASSERT (a) per-step loss parity with the plain
    epilogue loop (the schedule reorders WHEN collectives launch, never
    what they compute), (b) the lowering really armed a multi-stage
    schedule (metadata + the optimization_barrier chain in the lowered
    StableHLO — the structural proof XLA received a launch order it can
    hide), and (c) the cost model prices the schedule's exposed wire
    tail strictly below the serial epilogue's allreduce term (the claim
    the searcher's overlap knob ranks on). Real collective_wait
    shrinkage needs a multi-process run (the goodput bucket reads the
    coordinator barrier); on the CI host this leg proves structure +
    parity + pricing instead. Gates every PR on the overlap lowering
    compiling and staying numerically honest."""
    import numpy as np
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy
    from autodist_tpu.telemetry import spans as tel

    def leg(builder):
        adt.reset()
        ad = adt.AutoDist(strategy_builder=builder)
        runner = ad.build(loss_fn, optax.adam(1e-2), params, batches[0])
        runner.init(params)
        hist = runner.fit(list(batches))
        return ([float(m["loss"]) for m in hist], runner,
                dict(tel.counters()))

    ar_losses, _ar_runner, _ = leg(strategy.AllReduce(chunk_size=1))
    o_losses, o_runner, counters = leg(
        strategy.AllReduce(chunk_size=1, overlap=True))
    meta = o_runner.distributed_step.metadata
    assert meta.get("overlap"), "overlap never armed: %s" % meta
    stages = int(meta.get("overlap_stages", 0))
    assert stages >= 2, "degenerate %d-stage schedule" % stages
    text = o_runner.lowered_text(batches[0])
    barriers = (text.count("optimization_barrier")
                + text.count("opt-barrier"))
    assert barriers >= 1, "no barrier chain reached the program"
    assert counters.get("overlap.buckets", 0.0) > 0, counters
    np.testing.assert_allclose(o_losses, ar_losses, rtol=1e-6, atol=1e-7)
    # the pricing claim, on a spec with a real wire (the local CPU
    # "mesh" has no modeled ICI): exposed tail < serial epilogue wire
    from autodist_tpu.analysis.cli import default_spec
    from autodist_tpu.model_item import ModelItem
    from autodist_tpu.simulator.cost_model import CostModel
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-2),
                     params=params, example_batch=batches[0]).prepare()
    spec = default_spec(4)
    cm = CostModel(item, spec)
    bd = cm.estimate(
        strategy.AllReduce(chunk_size=1, overlap=True).build(item, spec))
    assert bd.overlap and bd.overlap_stages >= 2, bd
    assert 0.0 < bd.overlap_exposed_s < bd.allreduce_s, (
        "overlap pricing must expose less wire than the %0.3e s epilogue"
        " (got %0.3e s)" % (bd.allreduce_s, bd.overlap_exposed_s))
    return {"final_loss_epilogue": round(ar_losses[-1], 6),
            "final_loss_overlap": round(o_losses[-1], 6),
            "stages": stages, "barriers": barriers,
            "predicted_exposed_ms": round(bd.overlap_exposed_s * 1e3, 6),
            "predicted_epilogue_ms": round(bd.allreduce_s * 1e3, 6)}


def _smoke_search(loss_fn, params, batch):
    """Auto-search leg of the smoke bench: run the per-variable plan
    search on the smoke MLP and ASSERT the searched plan's estimated
    step time is <= the best zoo candidate's under the same cost model
    (both scored with the ranking's lossy-compression premium). No
    candidate is compiled — this is seconds of pure static scoring, and
    it gates every PR on the searched-beats-zoo contract."""
    import optax
    from autodist_tpu.analysis.cli import default_spec
    from autodist_tpu.model_item import ModelItem
    from autodist_tpu.search.drivers import SearchConfig, run_search
    from autodist_tpu.search.scoring import zoo_best
    from autodist_tpu.simulator.simulator import Simulator

    item = ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-2),
                     params=params, example_batch=batch).prepare()
    spec = default_spec(4)
    sim = Simulator(item, spec)
    t0 = time.perf_counter()
    res = run_search(item, spec, config=SearchConfig(budget=48),
                     simulator=sim)
    search_s = time.perf_counter() - t0
    assert res.ok, "smoke search produced no plan"
    zoo_label, zoo_score, zoo = zoo_best(item, spec, sim)
    assert res.record.score_s <= zoo_score + 1e-12, (
        "searched plan scores %.3e but zoo %s scores %.3e"
        % (res.record.score_s, zoo_label, zoo_score))
    return {"chosen": res.trace.result["plan"],
            "est_search_ms": round(res.record.step_time_s * 1e3, 4),
            "zoo_best": zoo_label,
            "est_zoo_ms": round(zoo.step_time_s * 1e3, 4),
            "candidates": res.candidates, "pruned": res.pruned,
            "search_s": round(search_s, 3)}


def _smoke_topology(loss_fn, params, batch):
    """Topology-ranking leg: price the synthesized collective schedules
    (flat ring / recursive halving-doubling / hierarchical two-level) on
    a simulated 8-host x 8-chip pod with a slow inter-host level — pure
    static scoring, zero hardware — and ASSERT the hierarchical route is
    strictly cheapest AND its plan-level profile crosses strictly fewer
    inter-host bytes than the flat ring's. The per-PR gate on the ADT52x
    analyzer's ranking contract (docs/performance.md)."""
    import optax
    from autodist_tpu.analysis.cli import topology_spec
    from autodist_tpu.analysis.topology import plan_level_bytes
    from autodist_tpu.model_item import ModelItem
    from autodist_tpu.resource_spec import Topology
    from autodist_tpu.search.space import PlanSpace, VarChoice
    from autodist_tpu.simulator.cost_model import CostModel

    topo = Topology.from_dict(
        {"hosts": 8, "chips_per_host": 8,
         "levels": [{"name": "ici", "bandwidth_gbps": 400},
                    {"name": "dcn", "bandwidth_gbps": 25}]})
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-2),
                     params=params, example_batch=batch).prepare()
    spec = topology_spec(topo)
    space = PlanSpace(item, spec)
    cm = CostModel(item, spec)
    ar_s, inter_bytes = {}, {}
    for sched in ("ring", "rhd", "hier"):
        plan = space.make_plan(
            {n: VarChoice(schedule=sched) for n in space.var_names})
        strat = space.build(plan)
        ar_s[sched] = cm.estimate(strat).allreduce_s
        inter_bytes[sched] = plan_level_bytes(
            strat, item, topo).get("dcn", 0.0)
    assert ar_s["hier"] < ar_s["ring"], ar_s
    assert 0 < inter_bytes["hier"] < inter_bytes["ring"], inter_bytes
    return {"allreduce_ms": {k: round(v * 1e3, 5)
                             for k, v in ar_s.items()},
            "inter_host_bytes": {k: round(v) for k, v in inter_bytes.items()},
            "inter_bytes_ratio": round(
                inter_bytes["ring"] / inter_bytes["hier"], 2)}


def _smoke_telemetry():
    """Trace export + phase breakdown for the smoke result (ADT_TRACE=1).
    Per-subsystem total seconds come from the recorded span categories,
    and the ATTRIBUTED goodput buckets (telemetry/goodput.py self-time
    decomposition: compute / collective-wait / PS-wire / host-input /
    readback / checkpoint / rollback-replay) ride beside them, so a
    BENCH reader sees WHERE the smoke wall time went — per bucket, with
    the buckets summing to the recorded wall time — plus the straggler
    summary (EWMA flags + last z), not just ex/s and MFU."""
    from autodist_tpu import const
    from autodist_tpu.telemetry import export, goodput, spans
    if not spans.tracing_enabled():
        return {}
    rec = spans.get_recorder()
    by_cat = {}
    for row in rec.summary().values():
        agg = by_cat.setdefault(row["cat"], {"count": 0, "total_s": 0.0})
        agg["count"] += row["count"]
        agg["total_s"] = round(agg["total_s"] + row["total_s"], 6)
    path = (const.ENV.ADT_TRACE_FILE.val
            or os.path.join(const.DEFAULT_TRACE_DIR, "smoke-trace.json"))
    gp = goodput.build_report(rec)
    # attributed buckets land INSIDE phase_breakdown (the r06+ trajectory
    # key) plus the full report (wall/coverage/dispatch stats) beside it
    by_cat["attributed"] = {k: round(v, 6) for k, v in gp.buckets.items()}
    counters = rec.counters()
    gauges = rec.gauges()
    out = {"phase_breakdown": by_cat,
           "goodput": gp.to_dict(),
           "straggler": {
               "flags": counters.get("telemetry.straggler_flags", 0.0),
               "gauge_z": gauges.get("telemetry.straggler"),
           },
           "telemetry_counters": {k: v for k, v in counters.items()
                                  if v}}
    try:
        export.write_trace(path)
        errors = export.validate_chrome_trace(export.load_trace(path))
        if errors:
            raise ValueError("; ".join(errors))
        out["trace_file"] = path
        out["trace_events"] = len(rec.events())
    except Exception as e:  # noqa: BLE001 — telemetry must not fail smoke
        out["trace_error"] = "%s: %s" % (type(e).__name__, str(e)[:160])
    return out


# ------------------------------------------------------------- serving leg


SERVE_MODELS = ["dlrm", "ncf"]


def _serve_setup(label, smoke):
    """(loss_fn, params, example_batch, serve_fn, feature_keys, builder)
    for one serving bench model. DLRM rides Parallax (tables on
    load-balanced PS, dense MLPs on AllReduce — the canonical
    recommendation split); NCF rides host-PS. Both are zoo strategies."""
    from autodist_tpu import strategy as S
    if label == "dlrm":
        from autodist_tpu.models.dlrm import DLRMConfig, make_train_setup
        cfg = (DLRMConfig.tiny() if smoke else
               DLRMConfig(table_sizes=(100_000, 50_000, 10_000, 1_000)))
        loss_fn, params, batch, apply_fn = make_train_setup(
            cfg, batch_size=64 if smoke else 256)
        serve_fn = lambda p, b: {  # noqa: E731
            "score": apply_fn(p, b["dense"], b["sparse"])}
        return loss_fn, params, batch, serve_fn, ("dense", "sparse"), \
            S.Parallax()
    if label == "ncf":
        from autodist_tpu.models.ncf import NCFConfig, make_train_setup
        cfg = NCFConfig.tiny() if smoke else NCFConfig()
        loss_fn, params, batch, apply_fn = make_train_setup(
            cfg, batch_size=64 if smoke else 256)
        serve_fn = lambda p, b: {  # noqa: E731
            "score": apply_fn(p, b["user"], b["item"])}
        return loss_fn, params, batch, serve_fn, ("user", "item"), S.PS()
    raise ValueError(label)


def _request_pool(batch, feature_keys):
    """Per-example request pytrees (label leaves dropped) from the
    synthetic example batch — the traffic generator's working set."""
    import jax
    feats = {k: batch[k] for k in feature_keys}
    n = int(np.shape(next(iter(feats.values())))[0])
    return [jax.tree_util.tree_map(lambda a, _i=i: np.asarray(a)[_i],
                                   feats) for i in range(n)]


def _drive_traffic(mb, requests, duration_s, concurrency):
    """Closed-loop clients: ``concurrency`` threads each submit one
    request and wait for its result, for ``duration_s``. Returns
    (completed, shed, errors, wall_s) — QPS is completed/wall."""
    import threading
    from autodist_tpu.serving import ServingUnavailable
    stop_at = time.perf_counter() + duration_s
    done = [0] * concurrency
    shed = [0] * concurrency
    errors = [0] * concurrency

    def client(i):
        rng = np.random.RandomState(i)
        while time.perf_counter() < stop_at:
            req = requests[rng.randint(len(requests))]
            try:
                mb.submit(req).result(timeout=60)
                done[i] += 1
            except ServingUnavailable:
                shed[i] += 1
                time.sleep(0.002)  # back off as a real client would
            except Exception:  # noqa: BLE001 — count, keep driving
                errors[i] += 1
    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 120)
    return sum(done), sum(shed), sum(errors), time.perf_counter() - t0


def _serve_fault_leg(runner, engine, mb, requests, duration_s,
                     concurrency):
    """Degraded-but-alive leg (runs when ``ADT_FAULT_PLAN`` is set): the
    runner's PS store is re-wired as a NON-OWNING serving replica that
    fetches every value group over the real coordination wire — through
    a FaultyProxy executing the fault plan — while a second store (the
    owner) publishes the authoritative values. Faults surface exactly
    where production would see them (resets/delays/truncation on real
    sockets); the assertion is behavioral: traffic keeps completing,
    degraded reads and shed requests are COUNTED, nothing hangs."""
    from autodist_tpu.parallel.ps import PSStore
    from autodist_tpu.runtime import ps_service as pss
    from autodist_tpu.runtime.coordination import CoordinationServer
    from autodist_tpu.runtime.faultinject import FaultPlan, FaultyProxy
    from autodist_tpu.runtime.resilience import ResilientCoordinationClient
    from autodist_tpu.telemetry import spans as tel

    plan = FaultPlan.from_env()
    if not plan.rules:
        return None
    store = runner.distributed_step.ps_store
    if store is None:
        return {"skipped": "no host-PS store (AllReduce-only strategy)"}
    hosts = {d.split(":")[0]
             for p in store.plans.values() for d in p.destinations if d}
    if len(hosts) > 1:
        return {"skipped": "multi-owner plans: one-process fault leg "
                           "models a single owner host"}
    owner_host = hosts.pop() if hosts else "127.0.0.1"

    import socket as socket_lib
    with socket_lib.socket() as s:
        s.bind(("127.0.0.1", 0))
        svc_port = s.getsockname()[1]
    server = CoordinationServer(port=svc_port)
    server.start()
    proxy = FaultyProxy("127.0.0.1", svc_port, plan=plan).start()
    owner = PSStore(dict(store.plans), store._var_infos, store._optimizer)
    try:
        def factory(host):
            return pss.CoordPSService(
                lambda: ResilientCoordinationClient(
                    "127.0.0.1", proxy.port, rpc_timeout=2.0,
                    max_retries=2, seed=0),
                prefix="ps:" + host)
        # the owner publishes the CURRENT trained values on the real wire
        owner.init_params(store.full_values())
        owner.enable_serving(factory, my_host=owner_host)
        # the serving replica owns nothing: every snapshot refresh now
        # crosses the faulted wire
        store.enable_serving(factory, my_host="bench-serve-replica")
        engine.config.snapshot_max_age_s = 0.0  # refresh every batch
        c0 = tel.counters()
        done, shed, errors, wall = _drive_traffic(
            mb, requests, duration_s, concurrency)
        c1 = tel.counters()
        return {
            "qps": round(done / wall, 2),
            "completed": done, "shed": shed, "errors": errors,
            "alive": done > 0,
            "degraded_snapshots":
                c1.get("serve.degraded", 0) - c0.get("serve.degraded", 0),
            "degraded_ps_pulls": c1.get("ps.degraded_pulls", 0)
                - c0.get("ps.degraded_pulls", 0),
            "shed_requests":
                c1.get("serve.shed", 0) - c0.get("serve.shed", 0),
            "faults_injected": len(plan.injected),
        }
    finally:
        proxy.stop()
        owner.close()
        server.stop()


def _serve_bench_model(label, smoke, fault):
    """One model's serving leg: build the strategy-compiled engine, warm
    every bucket, drive closed-loop traffic, report QPS + latency
    percentiles (+ the fault leg when a plan is set)."""
    import optax
    import autodist_tpu as adt
    from autodist_tpu.serving import (InferenceEngine, MicroBatcher,
                                      ServingConfig)
    from autodist_tpu.telemetry import spans as tel

    loss_fn, params, batch, serve_fn, feature_keys, builder = _serve_setup(
        label, smoke)
    adt.reset()
    ad = adt.AutoDist(strategy_builder=builder)
    runner = ad.build(loss_fn, optax.adam(1e-3), params, batch)
    runner.init(params)
    runner.run(batch)  # one train step: serve values that actually moved
    requests = _request_pool(batch, feature_keys)
    replicas = runner.remapper.num_replicas
    buckets = ((4 * replicas, 8 * replicas) if smoke else None)
    engine = InferenceEngine(
        runner, serve_fn, requests[0],
        ServingConfig(buckets=buckets,
                      max_delay_ms=1.0 if smoke else 2.0))
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    duration = float(os.environ.get("ADT_SERVE_DURATION_S",
                                    "2" if smoke else "10"))
    concurrency = int(os.environ.get("ADT_SERVE_CONCURRENCY",
                                     "8" if smoke else "32"))
    mb = MicroBatcher(engine)
    try:
        done, shed, errors, wall = _drive_traffic(mb, requests, duration,
                                                  concurrency)
        stats = mb.stats()
        result = {
            "strategy": type(builder).__name__,
            "buckets": stats["buckets"],
            "warmup_s": round(warmup_s, 3),
            "qps": round(done / wall, 2),
            "completed": done, "shed": shed, "errors": errors,
            "p50_ms": (round(stats["p50_ms"], 3)
                       if stats["p50_ms"] is not None else None),
            "p99_ms": (round(stats["p99_ms"], 3)
                       if stats["p99_ms"] is not None else None),
            "batches": stats["batches"],
            "avg_batch_fill": round(stats["fan_out"]
                                    / max(stats["batches"], 1), 2),
            "padded_rows": stats["padded_rows"],
            "recompiles_after_warmup": stats["recompiles_after_warmup"],
        }
        assert result["recompiles_after_warmup"] == 0, (
            "steady-state serving recompiled %d time(s) after warmup"
            % result["recompiles_after_warmup"])
        assert errors == 0, "%d serving requests errored" % errors
        if fault:
            fault_res = _serve_fault_leg(runner, engine, mb, requests,
                                         duration, concurrency)
            if fault_res is not None:
                result["fault"] = fault_res
        # per-replica QPS: the millions-of-users scaling unit
        import jax
        result["qps_per_replica"] = round(result["qps"]
                                          / max(len(jax.devices()), 1), 2)
        result["latency_histogram"] = tel.histograms().get(
            "serve.latency_ms", {})
        return result
    finally:
        # close the batcher thread but do NOT adt.reset() here: the next
        # model's build-time reset (and serve_main's final one) handles
        # isolation, and resetting now would wipe the recorder before
        # serve_main exports the ADT_TRACE=1 trace artifact
        mb.close()


def serve_main(smoke: bool):
    """``bench.py --serve`` (and the ``--smoke --serve`` CI leg): serving
    QPS + p50/p99 latency for the recommendation flagships (DLRM, NCF)
    on zoo strategies, with the zero-recompile contract asserted and —
    under ``ADT_FAULT_PLAN`` — a degraded-but-alive fault leg on the
    real coordination wire. Under ``ADT_TRACE=1`` the run exports a
    validated Perfetto trace with the ``serve.*`` spans."""
    import jax
    jax.config.update("jax_platforms",
                      os.environ.get("ADT_BENCH_PLATFORM") or "cpu")
    labels = [s for s in os.environ.get(
        "ADT_SERVE_MODELS", ",".join(SERVE_MODELS)).split(",") if s]
    fault = bool(os.environ.get("ADT_FAULT_PLAN"))
    from autodist_tpu.telemetry import export as tel_export, spans as tel
    models = {}
    traces = []
    for label in labels:
        try:
            models[label] = _serve_bench_model(label, smoke, fault)
            print("  serve %s: %s qps, p50 %s ms, p99 %s ms"
                  % (label, models[label]["qps"], models[label]["p50_ms"],
                     models[label]["p99_ms"]), file=sys.stderr, flush=True)
        except Exception as e:  # noqa: BLE001 — one model must not cost
            # the artifact; smoke re-raises below so CI stays strict
            models[label] = {"error": "%s: %s" % (type(e).__name__,
                                                  str(e)[:200])}
            if smoke:
                raise
            print("  serve %s FAILED: %s" % (label, models[label]["error"]),
                  file=sys.stderr, flush=True)
        # snapshot THIS model's spans now: the next model's build-time
        # adt.reset() wipes the recorder, and the exported artifact must
        # cover every model, not just the last
        if tel.tracing_enabled():
            traces.append(tel_export.chrome_trace())
    result = {"metric": "serve", "smoke": smoke, "models": models}
    result.update(_smoke_telemetry())
    if len(traces) > 1 and result.get("trace_file"):
        merged = tel_export.merge_traces(traces)
        if not tel_export.validate_chrome_trace(merged):
            with open(result["trace_file"], "w") as f:
                json.dump(merged, f)
            result["trace_events"] = len(merged["traceEvents"])
    import autodist_tpu as adt
    adt.reset()
    print(RESULT_TAG + json.dumps(result), flush=True)


def _serve_decode_leg(runner, cfg, admission, smoke):
    """One admission policy's leg of the continuous-vs-static decode
    head-to-head: same runner, same request trace, same slot count —
    only the admission rule differs."""
    from autodist_tpu.models import lm
    from autodist_tpu.serving.decode import DecodeConfig, DecodeEngine

    replicas = runner.remapper.num_replicas
    r = max(replicas, 1)
    slots = max((4 if smoke else 8) // r, 1) * r
    groups = int(os.environ.get("ADT_DECODE_GROUPS", "6" if smoke else "12"))
    n_requests = groups * slots
    prefill_len = 8
    longest = min(48, max(8, cfg.max_seq_len - prefill_len))
    short = max(longest // 6, 2)
    setup = lm.make_decode_setup(cfg)
    engine = DecodeEngine(runner, setup, DecodeConfig(
        slots=slots, max_new_tokens=longest, prefill_len=prefill_len,
        admission=admission))
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    # mixed-length generations — one long sequence per slot group among
    # shorts — are the canonical serving workload: the static baseline
    # idles every freed slot until the longest sequence of its batch
    # finishes, exactly the waste continuous batching reclaims
    import numpy as np
    rng = np.random.RandomState(7)
    trace = [(rng.randint(0, cfg.vocab_size,
                          (1 + i % 6,)).astype(np.int32),
              longest if i % slots == 0 else short)
             for i in range(n_requests)]
    try:
        t0 = time.perf_counter()
        futures = [engine.submit(p, max_new_tokens=m) for p, m in trace]
        results = [f.result(timeout=600) for f in futures]
        wall = time.perf_counter() - t0
        stats = engine.stats()
        tokens = sum(len(r["tokens"]) for r in results)
        leg = {
            "admission": admission,
            "slots": slots,
            "sequences": len(results),
            "tokens": tokens,
            "tokens_per_s": round(tokens / wall, 2),
            "warmup_s": round(warmup_s, 3),
            "steps": stats["steps"],
            "prefill_admits": stats["prefill_admits"],
            "evictions": stats["evictions"],
            "peak_occupancy": round(stats["peak_occupancy"], 3),
            "token_p50_ms": (round(stats["token_p50_ms"], 3)
                             if stats["token_p50_ms"] is not None else None),
            "token_p99_ms": (round(stats["token_p99_ms"], 3)
                             if stats["token_p99_ms"] is not None else None),
            "errors": stats["errors"],
            "recompiles_after_warmup": stats["recompiles_after_warmup"],
        }
        assert leg["recompiles_after_warmup"] == 0, (
            "%s decode recompiled %d time(s) after warmup"
            % (admission, leg["recompiles_after_warmup"]))
        assert leg["errors"] == 0, (
            "%d decode errors (%s)" % (leg["errors"], admission))
        assert leg["tokens_per_s"] > 0, "no decode throughput"
        assert leg["peak_occupancy"] > 0, (
            "slot occupancy never moved (%s)" % admission)
        return leg
    finally:
        engine.close()


def serve_decode_main(smoke: bool):
    """``bench.py --serve-decode`` (and the ``--smoke --serve-decode``
    CI leg): continuous vs static batching head-to-head on the lm1b
    model family — same trained runner, same request trace, same slot
    count; report tokens/s and per-token p50/p99 per admission policy.
    Continuous batching must sustain strictly higher tokens/s at
    equal-or-better per-token p99, with zero recompiles after warmup
    asserted on both legs."""
    import jax
    jax.config.update("jax_platforms",
                      os.environ.get("ADT_BENCH_PLATFORM") or "cpu")
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy as S
    from autodist_tpu.models import lm

    cfg = lm.LMConfig.tiny() if smoke else lm.LMConfig(
        vocab_size=8192, d_model=256, num_layers=4, num_heads=8,
        mlp_dim=1024, max_seq_len=64)
    loss_fn, params, batch, _ = lm.make_train_setup(
        cfg, seq_len=16 if smoke else 32, batch_size=8)
    adt.reset()
    ad = adt.AutoDist(strategy_builder=S.PS())
    runner = ad.build(loss_fn, optax.adam(1e-3), params, batch)
    runner.init(params)
    runner.run(batch)  # one train step: decode params that actually moved

    legs = {}
    for admission in ("continuous", "static"):
        legs[admission] = _serve_decode_leg(runner, cfg, admission, smoke)
        print("  decode %s: %s tokens/s, token p50 %s ms, p99 %s ms"
              % (admission, legs[admission]["tokens_per_s"],
                 legs[admission]["token_p50_ms"],
                 legs[admission]["token_p99_ms"]),
              file=sys.stderr, flush=True)
    cont, stat = legs["continuous"], legs["static"]
    speedup = cont["tokens_per_s"] / max(stat["tokens_per_s"], 1e-9)
    assert cont["tokens_per_s"] > stat["tokens_per_s"], (
        "continuous batching (%.1f tok/s) did not beat static (%.1f "
        "tok/s)" % (cont["tokens_per_s"], stat["tokens_per_s"]))
    # per-step compute is shape-fixed, so per-token p99 should be on par;
    # 25% covers scheduler jitter on shared CI runners
    if cont["token_p99_ms"] is not None and stat["token_p99_ms"]:
        assert cont["token_p99_ms"] <= stat["token_p99_ms"] * 1.25, (
            "continuous p99 %.2fms regressed past static %.2fms"
            % (cont["token_p99_ms"], stat["token_p99_ms"]))
    result = {"metric": "serve_decode", "smoke": smoke,
              "continuous": cont, "static": stat,
              "speedup": round(speedup, 3)}
    result.update(_smoke_telemetry())
    adt.reset()
    print(RESULT_TAG + json.dumps(result), flush=True)


def autoscale_main(osc: bool = False):
    """``bench.py --autoscale [--osc]`` — the load-adaptive serving leg
    standalone: the seeded 2→4→2 phantom-peer ramp (CI), or the
    oscillating-load hysteresis leg (``--osc``, nightly chaos). Unlike
    the best-effort smoke wiring, a failed assertion here FAILS the
    process — this is the enforcement entry CI runs."""
    if "--xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2").strip()
    import jax
    jax.config.update("jax_platforms",
                      os.environ.get("ADT_BENCH_PLATFORM") or "cpu")
    rng = np.random.RandomState(0)
    params = {"w1": rng.randn(16, 32).astype(np.float32) * 0.1,
              "b1": np.zeros((32,), np.float32),
              "w2": rng.randn(32, 4).astype(np.float32) * 0.1}

    def loss_fn(p, b):
        import jax.numpy as jnp
        h = jnp.tanh(b["x"] @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] - b["y"]) ** 2)

    batches = [{"x": rng.randn(32, 16).astype(np.float32),
                "y": rng.randn(32, 4).astype(np.float32)}
               for _ in range(16)]
    result = {"metric": "autoscale",
              "autoscale": _smoke_autoscale(loss_fn, params, batches,
                                            osc=osc)}
    if "error" in result["autoscale"]:
        print(RESULT_TAG + json.dumps(result), flush=True)
        raise SystemExit("autoscale leg failed: %s"
                         % result["autoscale"]["error"])
    import autodist_tpu as adt
    adt.reset()
    print(RESULT_TAG + json.dumps(result), flush=True)


def probe_main():
    """Trivial device matmul — the parent's preflight. A tunnel that
    cannot run this will time out every model; recording that fact in
    the artifact separates 'framework broken' from 'device unreachable'."""
    import jax
    if os.environ.get("ADT_BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["ADT_BENCH_PLATFORM"])
    t0 = time.perf_counter()
    x = jax.numpy.ones((64, 64)) @ jax.numpy.ones((64, 64))
    _sync(x.sum())
    print(RESULT_TAG + json.dumps(
        {"probe_s": round(time.perf_counter() - t0, 2)}), flush=True)


def child_main(label):
    """Run one model and print its result dict, tagged, as the last line."""
    import jax
    # Persistent compilation cache: XLA compiles through the tunnel cost
    # minutes per model; the cache makes repeat runs (and the driver's
    # run after ours, same host) near-instant on the compile side.
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/adt_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — older jax: run uncached
        pass
    if os.environ.get("ADT_BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["ADT_BENCH_PLATFORM"])
    budget = float(os.environ.get("ADT_BENCH_MODEL_BUDGET_S", "600"))
    deadline = time.perf_counter() + budget
    if label == "bert_base":
        # ALL candidate operating points measured in ONE artifact run;
        # the headline is the artifact winner — never a one-off probe
        # (VERDICT-r4 #4: the table must quote the artifact). 160 is the
        # probed sweet spot (192 flat, 256 RESOURCE_EXHAUSTs).
        # winner-first order: if the budget kills the child mid-sweep,
        # the headline operating point is already measured
        batches = (160, 128, 64)
        res, results = None, {}
        for i, bs in enumerate(batches):
            share = (deadline - time.perf_counter()) / (len(batches) - i)
            try:
                r = bench_model(label, deadline=time.perf_counter() + share,
                                batch_size=bs)
            except Exception as e:  # noqa: BLE001 — one operating point
                # near the OOM cliff must not discard the others' results
                r = {"error": "%s: %s" % (type(e).__name__, str(e)[:160])}
                print("  bert batch %d failed: %s" % (bs, r["error"]),
                      file=sys.stderr, flush=True)
            results["batch_%d" % bs] = r
            if "examples_per_sec" in r and (
                    res is None
                    or r["examples_per_sec"] > res["examples_per_sec"]):
                res = r
        if res is None:
            raise RuntimeError("every bert operating point failed: %s"
                               % results)
        res = dict(res)
        res.update(results)
    else:
        res = bench_model(label, deadline=deadline)
    print(RESULT_TAG + json.dumps(res), flush=True)


def _run_tagged_child(args, timeout, child_box, env=None):
    """Spawn a tagged child of this script (probe or model), enforce the
    hard timeout (killing the child's whole process group, guarded
    against it exiting in the race window), and return
    (parsed result dict | None, error string | None)."""
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)] + list(args),
        stdout=subprocess.PIPE, env=env, start_new_session=True, text=True)
    child_box[0] = proc
    try:
        try:
            out, _ = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.communicate()
            return None, "timeout"
    finally:
        child_box[0] = None
    tagged = [ln for ln in out.splitlines() if ln.startswith(RESULT_TAG)]
    if proc.returncode == 0 and tagged:
        return json.loads(tagged[-1][len(RESULT_TAG):]), None
    return None, "child rc=%s, no result" % proc.returncode


def _emit(models, preflight=None):
    """Print the cumulative result line (full schema, always valid)."""
    skipped = sorted(k for k, m in models.items() if "skipped" in m)
    failed = sorted(k for k, m in models.items() if "error" in m)
    ran = {k: m for k, m in models.items() if "vs_baseline" in m}
    worst = min((m["vs_baseline"] for m in ran.values()), default=0.0)
    # headline: resnet50 if it ran, else any model that did
    head_key = "resnet50" if "resnet50" in ran else (
        sorted(ran)[0] if ran else None)
    result = {
        "metric": ("%s_train_examples_per_sec" % head_key) if head_key
        else "bench_incomplete",
        "value": ran[head_key]["examples_per_sec"] if head_key else 0.0,
        "unit": "examples/s",
        # min across the models that RAN; "skipped_models" flags any the
        # budget or a tunnel fault dropped, so coverage is explicit
        "vs_baseline": worst,
        "models": models,
    }
    if skipped:
        result["skipped_models"] = skipped
    if failed:
        # crashes are NOT budget skips: flag them distinctly so a green
        # vs_baseline over the survivors cannot mask a real failure
        result["failed_models"] = failed
    if preflight is not None:
        result["preflight"] = preflight
    print(json.dumps(result), flush=True)


def main():
    budget_s = float(os.environ.get("ADT_BENCH_BUDGET_S", "1380"))
    per_model_cap = float(os.environ.get("ADT_BENCH_MODEL_CAP_S", "600"))
    labels = [s for s in os.environ.get(
        "ADT_BENCH_MODELS", ",".join(MODEL_LABELS)).split(",") if s]
    t_start = time.perf_counter()
    models = {label: {"skipped": "not reached"} for label in labels}
    preflight = [None]

    def emit():
        _emit(models, preflight[0])

    emit()  # a parseable line exists from second zero

    child_box = [None]

    def _on_term(signum, frame):  # noqa: ARG001
        proc = child_box[0]
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
        # the cumulative line for everything finished so far is already on
        # stdout; just leave cleanly
        sys.exit(1)

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    # preflight: can the device run a trivial matmul right now? An
    # unreachable tunnel will time out every model; the artifact should
    # say which failure this is
    try:
        res, err = _run_tagged_child(["--probe"], 150, child_box)
        if err == "timeout":
            preflight[0] = {"error": "device unreachable (probe timeout)"}
            print("  PREFLIGHT: device unreachable", file=sys.stderr,
                  flush=True)
        else:
            preflight[0] = res if res is not None else {"error": err}
    except Exception as e:  # noqa: BLE001
        preflight[0] = {"error": str(e)[:120]}
    emit()

    attempted = False
    # tunnel stalls are transient: models that error out on the first
    # pass get ONE retry each while budget remains (second pass)
    queue = list(labels)
    for attempt in range(2):
        for label in queue:
            if "vs_baseline" in models.get(label, {}):
                continue  # already measured
            elapsed = time.perf_counter() - t_start
            remaining = budget_s - elapsed
            # skip once out of budget after ANY attempt (a timed-out
            # attempt consumed the budget just the same as a success);
            # never downgrade an error record to a budget skip
            if attempted and remaining < 180:
                if "error" not in models.get(label, {}):
                    models[label] = {"skipped": "bench budget"}
                    emit()
                print("  skipping %s: %.0fs elapsed, budget %.0fs"
                      % (label, elapsed, budget_s),
                      file=sys.stderr, flush=True)
                continue
            if attempt:
                print("  retrying %s" % label, file=sys.stderr, flush=True)
            _run_model(label, models, remaining, per_model_cap, child_box)
            attempted = True
            emit()
        queue = [l for l in labels if "error" in models.get(l, {})]
        if not queue:
            break


def _run_model(label, models, remaining, per_model_cap, child_box):
    """Run one model in a child subprocess with a hard timeout; record its
    result (or error) in ``models``."""
    floor = float(os.environ.get("ADT_BENCH_MODEL_FLOOR_S", "120"))
    grace = float(os.environ.get("ADT_BENCH_HARD_GRACE_S", "180"))
    soft = max(floor, min(remaining - 60.0, per_model_cap))
    hard = soft + grace  # grace for in-flight compile/phase to land
    env = dict(os.environ, ADT_BENCH_MODEL_BUDGET_S=str(soft))
    t_model = time.perf_counter()
    try:
        res, err = _run_tagged_child(["--model", label], hard, child_box,
                                     env=env)
        if err == "timeout":
            models[label] = {"error": "timeout after %.0fs" % hard}
            print("  %s TIMED OUT (%.0fs hard limit)" % (label, hard),
                  file=sys.stderr, flush=True)
        elif res is not None:
            models[label] = res
            print("  %s done in %.0fs" % (
                label, time.perf_counter() - t_model),
                file=sys.stderr, flush=True)
        else:
            models[label] = {"error": err}
    except Exception as e:  # noqa: BLE001 — one flaky model must not
        # cost the whole artifact
        models[label] = {"error": "%s: %s"
                         % (type(e).__name__, str(e)[:200])}


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--model":
        child_main(sys.argv[2])
    elif len(sys.argv) >= 2 and sys.argv[1] == "--probe":
        probe_main()
    elif "--autoscale" in sys.argv[1:]:
        autoscale_main(osc="--osc" in sys.argv[1:])
    elif "--serve-decode" in sys.argv[1:]:
        serve_decode_main(smoke="--smoke" in sys.argv[1:])
    elif "--serve" in sys.argv[1:]:
        serve_main(smoke="--smoke" in sys.argv[1:])
    elif len(sys.argv) >= 2 and sys.argv[1] == "--smoke":
        smoke_main(fused="--fused" in sys.argv[2:])
    else:
        main()
