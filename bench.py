"""Benchmark: framework train-step throughput vs. plain-jit baselines.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "models"}.
Three flagship models (the BASELINE.md bar): resnet50, bert_base, and the
lm1b-config transformer LM. For each, the framework's full stack (strategy
build -> lowering -> Runner step) races a hand-written jit data-parallel
step on the identical model/optimizer/batch. ``vs_baseline`` >= 1.0 means
the framework matches or beats hand-written JAX; the headline value is the
MINIMUM ratio across models (the conservative claim), per-model detail in
"models" (each with examples/sec and MFU).

Methodology (the device may sit behind a high-latency tunnel and throttle
under sustained load, so naive one-shot loops are biased):
- batches are device-resident for BOTH paths; both donate state buffers;
- vs_baseline is the MEDIAN over order-alternated paired phases — single
  pairs swing 0.4-2.3x under throttling; the median of paired ratios is
  robust to throttle windows landing on either path;
- MFU = (compiled cost-analysis FLOPs per step) / steady-state step time /
  chip peak — computed from the framework path's own best phase so tunnel
  stalls don't understate it.
"""
import functools
import json
import statistics
import time

import numpy as np

# bf16 dense peak FLOP/s by platform (public figures)
PEAK_FLOPS = {"v5 lite": 394e12, "v5e": 394e12, "v4": 275e12,
              "v5p": 918e12, "cpu": 5e10}
# int8-free bf16 peak for v5e is 197 TFLOP/s per the public spec sheet;
# 394 is the int8 figure — use the bf16 number for MFU honesty
PEAK_FLOPS["v5 lite"] = 197e12
PEAK_FLOPS["v5e"] = 197e12


def _phase_rate(fn, iters):
    import jax
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    return iters / (time.perf_counter() - t0)


def _chip_peak():
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, peak in PEAK_FLOPS.items():
        if key in kind:
            return peak
    return PEAK_FLOPS["cpu"] if jax.devices()[0].platform == "cpu" else 197e12


def _compiled_flops(lowered_compiled) -> float:
    try:
        ca = lowered_compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0))
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return 0.0


def bench_model(name, setup_kw, batch_key, pairs=8, iters=4):
    import sys
    import jax
    print("bench_model:", name, setup_kw, file=sys.stderr, flush=True)
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy
    from autodist_tpu.models import make_train_setup

    loss_fn, params, batch_np, _ = make_train_setup(name, **setup_kw)
    opt = optax.adam(1e-3)
    batch_size = int(np.shape(batch_np[batch_key])[0])

    # ---- baseline: plain jit data-parallel step, donated state,
    #      device-resident batch
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def baseline_step(p, s, b):
        loss, g = jax.value_and_grad(loss_fn)(p, b)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, loss

    base_batch = jax.device_put(batch_np)
    base_box = [jax.device_put(jax.device_get(params)),
                jax.device_put(jax.device_get(opt.init(params)))]
    t0 = time.perf_counter()
    # AOT-compile once and call the executable directly: one compile serves
    # both the FLOPs count and the baseline steps
    baseline_exec = baseline_step.lower(
        base_box[0], base_box[1], base_batch).compile()
    flops = _compiled_flops(baseline_exec)
    print("  baseline compiled in %.1fs, flops/step=%.3g"
          % (time.perf_counter() - t0, flops), file=sys.stderr, flush=True)

    def run_baseline():
        p, s, loss = baseline_exec(base_box[0], base_box[1], base_batch)
        base_box[0], base_box[1] = p, s
        return loss

    # ---- framework: AllReduce strategy through the full stack
    adt.reset()
    ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
    runner = ad.build(loss_fn, opt, params, batch_np)
    runner.init(params)
    sharded = runner.remapper.remap_feed(batch_np)
    state_box = [runner.state]

    def run_fw():
        st, m = runner.distributed_step(state_box[0], sharded)
        state_box[0] = st
        return m["loss"]

    # warmup (compile + a few steps each)
    t0 = time.perf_counter()
    for _ in range(3):
        run_baseline()
        run_fw()
    jax.block_until_ready((base_box[0], state_box[0].params))
    print("  warmup done in %.1fs" % (time.perf_counter() - t0),
          file=sys.stderr, flush=True)

    # adaptive phase length: short steps need more iterations per phase or
    # a single throttle window dominates the pair ratio (bert-sized steps
    # at 4 iters/phase swung medians 0.87-1.00 between runs). The probe is
    # a median of 3 so one throttled probe step can't pin iters low.
    probes = []
    for _ in range(3):
        t0 = time.perf_counter()
        run_fw()
        jax.block_until_ready(state_box[0].params)
        probes.append(time.perf_counter() - t0)
    step_s = max(statistics.median(probes), 1e-4)
    iters = max(iters, min(64, int(round(1.0 / step_s))))
    print("  step=%.0fms -> %d iters/phase" % (step_s * 1e3, iters),
          file=sys.stderr, flush=True)

    ratios, fw_rates = [], []
    for k in range(pairs):
        if k % 2 == 0:
            rb = _phase_rate(run_baseline, iters)
            rf = _phase_rate(run_fw, iters)
        else:
            rf = _phase_rate(run_fw, iters)
            rb = _phase_rate(run_baseline, iters)
        ratios.append(rf / rb)
        fw_rates.append(rf)
    adt.reset()
    best_rate = max(fw_rates)  # steady-state (least-throttled) phase
    # flops is the GLOBAL per-step count; aggregate peak scales with the
    # device count the framework step runs over
    agg_peak = _chip_peak() * len(jax.devices())
    mfu = (flops * best_rate / agg_peak) if flops else 0.0
    return {
        "examples_per_sec": round(statistics.median(fw_rates) * batch_size, 2),
        "vs_baseline": round(statistics.median(ratios), 4),
        "mfu": round(mfu, 4),
        "flops_per_step": flops,
        "batch_size": batch_size,
    }


def main():
    import os
    import sys
    import jax
    import jax.numpy as jnp
    # Persistent compilation cache: XLA compiles through the tunnel cost
    # minutes per model; the cache makes repeat runs (and the driver's
    # run after ours, same host) near-instant on the compile side.
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/adt_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — older jax: run uncached
        pass
    from autodist_tpu.models.lm import LMConfig

    # lm1b config at bf16 (TPU-first; the f32 99k-vocab variant compiles
    # ~2x slower through the tunnel for the same capability claim)
    lm1b_cfg = LMConfig.lm1b(dtype=jnp.bfloat16)
    configs = [
        ("resnet50", dict(batch_size=64), "image"),
        ("bert_base", dict(batch_size=16, seq_len=128), "input_ids"),
        ("lm", dict(config=lm1b_cfg, batch_size=16, seq_len=256), "tokens"),
    ]
    budget_s = float(os.environ.get("ADT_BENCH_BUDGET_S", "2700"))
    t_start = time.perf_counter()
    models = {}
    for name, kw, batch_key in configs:
        label = "lm1b" if name == "lm" else name
        elapsed = time.perf_counter() - t_start
        # start a model only while meaningful time remains (compiles through
        # the tunnel dominate; phases themselves are cheap)
        if models and elapsed > budget_s - 300:
            print("  skipping %s: %.0fs elapsed, budget %.0fs"
                  % (label, elapsed, budget_s), file=sys.stderr, flush=True)
            models[label] = {"skipped": "bench budget"}
            continue
        try:
            models[label] = bench_model(name, kw, batch_key)
        except Exception as e:  # noqa: BLE001 — the tunnel drops compiles;
            # one flaky model must not cost the whole artifact
            print("  %s FAILED: %s: %s" % (label, type(e).__name__, e),
                  file=sys.stderr, flush=True)
            models[label] = {"error": "%s: %s" % (type(e).__name__, str(e)[:200])}

    skipped = sorted(k for k, m in models.items() if "skipped" in m)
    failed = sorted(k for k, m in models.items() if "error" in m)
    ran = {k: m for k, m in models.items() if "vs_baseline" in m}
    worst = min((m["vs_baseline"] for m in ran.values()), default=0.0)
    # headline: resnet50 if it ran, else any model that did
    head_key = "resnet50" if "resnet50" in ran else (
        sorted(ran)[0] if ran else None)
    result = {
        "metric": ("%s_train_examples_per_sec" % head_key) if head_key
        else "bench_failed",
        "value": ran[head_key]["examples_per_sec"] if head_key else 0.0,
        "unit": "examples/s",
        # min across the models that RAN; "skipped_models" flags any the
        # budget or a tunnel fault dropped, so coverage is explicit
        "vs_baseline": worst,
        "models": models,
    }
    if skipped:
        result["skipped_models"] = skipped
    if failed:
        # crashes are NOT budget skips: flag them distinctly so a green
        # vs_baseline over the survivors cannot mask a real failure
        result["failed_models"] = failed
    print(json.dumps(result))


if __name__ == "__main__":
    main()
