"""Tiny image classifier example (mirror of reference examples/image_classifier.py)."""

if __package__ in (None, ""):  # direct invocation: put the repo root on sys.path
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import jax.numpy as jnp
import numpy as np
import optax

import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.models import resnet


def main():
    ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
    loss_fn, params, batch, _ = resnet.make_train_setup(
        resnet.ResNetTiny, num_classes=10, image_size=32, batch_size=64,
        dtype=jnp.float32)
    step = ad.function(loss_fn, optimizer=optax.sgd(0.1, momentum=0.9),
                       params=params)
    for i in range(30):
        m = step(batch)
        if i % 10 == 0:
            print("step %d loss %.4f" % (i, m["loss"]))


if __name__ == "__main__":
    main()
