"""DLRM recommender benchmark — the auto-strategy flagship.

The BASELINE target config: a large-embedding CTR model where the right
distribution plan is NOT obvious — giant uneven tables want load-balanced
or partitioned PS with the sparse wire, the dense MLPs want AllReduce —
so the default strategy here is ``AutoStrategy``, which ranks the
candidates with the analytic cost model (including the HBM feasibility
gate) and reports what it picked.
"""

if __package__ in (None, ""):  # direct invocation: put the repo root on sys.path
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__)))))
import argparse

import optax

import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.models import dlrm
from examples.benchmark.utils.logs import BenchmarkLogger, ExamplesPerSecondHook
from examples.benchmark.imagenet import make_builder


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--autodist_strategy", default="AutoStrategy",
                   help="AutoStrategy (default) ranks candidates with the "
                        "cost model; any named builder forces it")
    p.add_argument("--batch_size", type=int, default=2048)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--embed_dim", type=int, default=64)
    p.add_argument("--resource_spec", default=None)
    args = p.parse_args()

    builder = (strategy.AutoStrategy()
               if args.autodist_strategy == "AutoStrategy"
               else make_builder(args.autodist_strategy, 512))
    ad = adt.AutoDist(resource_spec_file=args.resource_spec,
                      strategy_builder=builder)
    cfg = dlrm.DLRMConfig(embed_dim=args.embed_dim,
                          bottom_mlp=(512, 256, args.embed_dim))
    loss_fn, params, batch, _ = dlrm.make_train_setup(
        cfg, batch_size=args.batch_size)
    runner = ad.build(loss_fn, optax.adam(1e-3), params, batch)
    runner.init(params)
    hook = ExamplesPerSecondHook(args.batch_size, every_n_steps=20,
                                 name="dlrm")
    m = runner.run(batch)
    for _ in range(args.steps - 1):
        m = runner.run(batch)
        hook.after_step()

    picked = None
    if isinstance(builder, strategy.AutoStrategy) and builder.last_ranking:
        picked = builder.last_ranking[0].label
    meta = runner.distributed_step.metadata
    table_bytes = sum(
        v.byte_size
        for n, v in runner.distributed_step.model_item.var_infos.items()
        if "table_" in n)
    BenchmarkLogger().log(
        model="dlrm", strategy=args.autodist_strategy,
        picked=picked, embedding_gb=round(table_bytes / 1e9, 2),
        sparse_wire_vars=len(meta["sparse_wire"]),
        ps_resident_vars=len(meta["ps_host_resident"]),
        examples_per_sec=round(hook.average, 1),
        final_loss=float(m["loss"]))


if __name__ == "__main__":
    main()
