"""Bench: host-PS transfer/compute overlap (PSPipeline) on a
transfer-bound config.

The serial PS step pays compute + pull(H2D) + push(D2H + host apply) per
step; with the pipeline (ADT_PS_OVERLAP=1, default) the transfers ride a
background worker. Sync PS keeps exact ordering (the win is bounded by
dispatch/host overlap); PS(staleness=1) allows the stale-by-one prefetch
and should approach step ~= max(compute, transfer).

Config: a deliberately PCIe-heavy MLP — most parameters host-resident
(no-proxy PS), small batch so compute is modest and the wire dominates.
Prints one JSON line per mode: {"mode", "step_ms", "pull_mb", "push_mb"}.

Run on the real chip from the repo root:  python examples/benchmark/ps_overlap.py
"""
import json
import os
import sys
import time

import numpy as np

# repo-root import without PYTHONPATH (which breaks axon plugin registration)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def timed_run(overlap: int, staleness: int, steps: int = 8):
    os.environ["ADT_PS_OVERLAP"] = str(overlap)
    import jax
    import jax.numpy as jnp
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy

    adt.reset()
    rng = np.random.RandomState(0)
    d = 2048
    params = {
        "w1": jnp.asarray(rng.randn(d, d) * 0.02, jnp.float32),
        "w2": jnp.asarray(rng.randn(d, d) * 0.02, jnp.float32),
        "w3": jnp.asarray(rng.randn(d, d) * 0.02, jnp.float32),
        "w4": jnp.asarray(rng.randn(d, 8) * 0.02, jnp.float32),
    }

    def loss_fn(p, batch):
        h = jnp.tanh(batch["x"] @ p["w1"])
        h = jnp.tanh(h @ p["w2"])
        h = jnp.tanh(h @ p["w3"])
        return jnp.mean((h @ p["w4"] - batch["y"]) ** 2)

    batch = {"x": rng.randn(16, d).astype(np.float32),
             "y": rng.randn(16, 8).astype(np.float32)}
    runner = adt.AutoDist(
        strategy_builder=strategy.PS(staleness=staleness)).build(
        loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    # warmup (compile + first transfers)
    for _ in range(3):
        runner.run(batch)
    runner.distributed_step.flush_ps()
    store = runner.distributed_step.ps_store
    b0 = dict(store.stats)
    t0 = time.perf_counter()
    last = None
    for _ in range(steps):
        last = runner.run(batch)
    # value readback sync + flush the pipeline so the window includes the
    # final push (fair vs serial)
    float(last["loss"])
    runner.distributed_step.flush_ps()
    dt = time.perf_counter() - t0
    out = {
        "mode": "overlap" if overlap else "serial",
        "staleness": staleness,
        "step_ms": round(1e3 * dt / steps, 2),
        "pull_mb": round((store.stats["bytes_pulled"] - b0["bytes_pulled"])
                         / steps / 1e6, 1),
        "push_mb": round((store.stats["bytes_pushed"] - b0["bytes_pushed"])
                         / steps / 1e6, 1),
    }
    adt.reset()
    return out


def apply_scaling(n_shards: int = 4, rows: int = 16384, cols: int = 2048,
                  iters: int = 12, threads=(1, 2, 4)):
    """Store-level microbench of the host optimizer apply: one DLRM-ish
    partitioned table, adam, timed through PSStore.apply_local with the
    thread pool at 1 (baseline) vs N workers. Shards are independent, so
    the update parallelizes across host cores (ADT_PS_APPLY_THREADS)."""
    import jax.numpy as jnp
    import optax
    from autodist_tpu.parallel.ps import PSStore, PSVarPlan

    rng = np.random.RandomState(0)
    full = rng.randn(rows, cols).astype(np.float32) * 0.02
    grad = rng.randn(rows, cols).astype(np.float32) * 0.001
    sizes = tuple([rows // n_shards] * n_shards)
    plan = PSVarPlan(var_name="emb", destinations=("127.0.0.1",) * n_shards,
                     shard_sizes=sizes)

    class _Info:
        shape = (rows, cols)
    out = {"bench": "apply_scaling", "n_shards": n_shards,
           "mb": round(full.nbytes / 1e6, 1)}
    base_ms = None
    for n in threads:
        os.environ["ADT_PS_APPLY_THREADS"] = str(n)
        store = PSStore({"emb": plan}, {"emb": _Info()}, optax.adam(1e-3))
        store.init_params({"emb": jnp.asarray(full)})
        store.push({"emb": grad})  # warmup: trace + compile the groups
        t0 = time.perf_counter()
        for _ in range(iters):
            store.push({"emb": grad})
        ms = 1e3 * (time.perf_counter() - t0) / iters
        store.close()
        if base_ms is None:
            base_ms = ms
        out["threads_%d_ms" % n] = round(ms, 2)
        out["threads_%d_speedup" % n] = round(base_ms / ms, 2)
    os.environ.pop("ADT_PS_APPLY_THREADS", None)
    return out


def main():
    results = []
    for staleness in (0, 1):
        for overlap in (0, 1):
            r = timed_run(overlap, staleness)
            results.append(r)
            print(json.dumps(r), flush=True)
    by = {(r["mode"], r["staleness"]): r["step_ms"] for r in results}
    summary = {
        "sync_speedup": round(by[("serial", 0)] / by[("overlap", 0)], 3),
        "stale1_speedup": round(by[("serial", 1)] / by[("overlap", 1)], 3),
    }
    print(json.dumps({"summary": summary}), flush=True)
    print(json.dumps(apply_scaling()), flush=True)


if __name__ == "__main__":
    main()
