"""ImageNet CNN benchmark harness.

Mirror of reference ``examples/benchmark/imagenet.py``: model selected by
``--model`` (resnet18/50/101, vgg16, inceptionv3, densenet121), strategy by
``--autodist_strategy`` (``:160-182``), per-model all-reduce chunk sizes
(``:150-158``), examples/sec logging. Synthetic ImageNet-shaped data.

  python examples/benchmark/imagenet.py --model resnet50 \
      --autodist_strategy AllReduce --batch_size 64 --steps 200
"""

if __package__ in (None, ""):  # direct invocation: put the repo root on sys.path
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__)))))
import argparse

import jax.numpy as jnp
import numpy as np
import optax

import autodist_tpu as adt
from autodist_tpu import strategy as S
from autodist_tpu import models
from examples.benchmark.utils.logs import BenchmarkLogger, ExamplesPerSecondHook

# per-model chunk sizes, as tuned in the reference (imagenet.py:150-158:
# vgg16=25, resnet101=200, inceptionv3=30, else 512)
CHUNK_SIZES = {"resnet101": 200, "vgg16": 25, "inceptionv3": 30}

# ImageNet-shaped entries of the shared model registry (which also holds
# bert/lm/ncf); per-model defaults like inceptionv3's 299px live there
MODELS = ("resnet18", "resnet50", "resnet101", "vgg16", "inceptionv3",
          "densenet121")


def make_builder(name: str, chunk: int):
    builders = {
        "PS": lambda: S.PS(),
        "PSLoadBalancing": lambda: S.PSLoadBalancing(),
        "PartitionedPS": lambda: S.PartitionedPS(),
        "AllReduce": lambda: S.AllReduce(chunk_size=chunk),
        "PartitionedAR": lambda: S.PartitionedAR(chunk_size=chunk),
        "Parallax": lambda: S.Parallax(chunk_size=chunk),
    }
    return builders[name]()


def _make_record_dataset(example_batch, args):
    """Write a few batches of synthetic records once; return
    (dataset, record_path). The caller unlinks path/path+'.json' when done
    (~150-275 MB of synthetic images per run)."""
    import os
    import tempfile
    from autodist_tpu.data import RecordFileDataset, RecordFileWriter
    fd, path = tempfile.mkstemp(suffix=".adt", prefix="imagenet_bench_")
    os.close(fd)
    img_shape = tuple(example_batch["image"].shape[1:])
    rng = np.random.RandomState(0)
    with RecordFileWriter(path, fields=[("image", np.float32, img_shape),
                                        ("label", np.int32, ())]) as w:
        for _ in range(args.batch_size * 4):  # 4 batches, shuffled each epoch
            w.write({"image": rng.randn(*img_shape).astype(np.float32),
                     "label": np.int32(rng.randint(1000))})
    return RecordFileDataset(path, args.batch_size, seed=0, num_threads=2), path


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50", choices=sorted(MODELS))
    p.add_argument("--autodist_strategy", default="AllReduce")
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--image_size", type=int, default=None,
                   help="default 224 (299 for inceptionv3)")
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--resource_spec", default=None)
    p.add_argument("--bf16", action=argparse.BooleanOptionalAction,
                   default=True, help="bfloat16 compute (--no-bf16 for f32)")
    p.add_argument("--lr", type=float, default=None,
                   help="SGD lr (default 0.1; 0.01 for vgg16, whose "
                        "flatten-head gradients diverge at 0.1 from scratch)")
    p.add_argument("--record_pipeline", action="store_true",
                   help="feed through the native record loader + device "
                        "prefetcher instead of a fixed device-resident "
                        "batch (measures the full input path)")
    args = p.parse_args()

    chunk = CHUNK_SIZES.get(args.model, 512)
    ad = adt.AutoDist(resource_spec_file=args.resource_spec,
                      strategy_builder=make_builder(args.autodist_strategy, chunk))
    kw = dict(batch_size=args.batch_size,
              dtype=jnp.bfloat16 if args.bf16 else jnp.float32)
    if args.image_size is not None:
        kw["image_size"] = args.image_size
    loss_fn, params, batch, _ = models.make_train_setup(args.model, **kw)
    lr = args.lr if args.lr is not None else (0.01 if args.model == "vgg16"
                                              else 0.1)
    # clip: from-scratch CNNs at benchmark lrs throw early gradient spikes
    # (vgg16's flatten head especially); clipping keeps every model finite
    opt = optax.chain(optax.clip_by_global_norm(1.0),
                      optax.sgd(lr, momentum=0.9))
    # chains bypass the optimizer-capture patch; register so the serialized
    # strategy still records what optimizer trained it
    from autodist_tpu import patch
    patch.register_optimizer(opt, "sgd",
                             {"learning_rate": lr, "momentum": 0.9,
                              "clip_global_norm": 1.0})
    hook = ExamplesPerSecondHook(args.batch_size, every_n_steps=20,
                                 name=args.model)
    m = {"loss": float("nan")}
    if args.record_pipeline:
        # full input path: native loader threads -> device prefetcher ->
        # mesh-placed batches -> runner.fit
        import os
        from autodist_tpu.data import DevicePrefetcher
        runner = ad.build(loss_fn, opt, params, batch)
        runner.init(params)
        ds, record_path = _make_record_dataset(batch, args)
        try:
            with ds:
                history = runner.fit(DevicePrefetcher(ds, runner, depth=2),
                                     steps=args.steps,
                                     callbacks=[lambda i, _m: hook.after_step()])
        finally:
            for f in (record_path, record_path + ".json"):
                try:
                    os.unlink(f)
                except FileNotFoundError:
                    pass
        if history:
            m = history[-1]
    else:
        step = ad.function(loss_fn, optimizer=opt, params=params)
        for i in range(args.steps):
            m = step(batch)
            hook.after_step()
    BenchmarkLogger().log(model=args.model, strategy=args.autodist_strategy,
                          batch_size=args.batch_size,
                          examples_per_sec=round(hook.average, 1),
                          final_loss=float(m["loss"]))


if __name__ == "__main__":
    main()
