"""Benchmark logging utilities.

Analog of the reference's vendored ``examples/benchmark/utils/logs/``
(``hooks.py:28`` ExamplesPerSecondHook, ``logger.py`` BenchmarkLogger): a
throughput meter that logs examples/sec every N steps and a JSON-line
benchmark logger.
"""
import json
import time


class ExamplesPerSecondHook:
    def __init__(self, batch_size: int, every_n_steps: int = 100, name: str = ""):
        self.batch_size = batch_size
        self.every_n = every_n_steps
        self.name = name
        self._t0 = None
        self._step0 = 0
        self._step = 0
        self.history = []

    def after_step(self):
        self._step += 1
        if self._t0 is None:
            self._t0, self._step0 = time.perf_counter(), self._step
            return None
        if (self._step - self._step0) >= self.every_n:
            dt = time.perf_counter() - self._t0
            eps = (self._step - self._step0) * self.batch_size / dt
            self.history.append(eps)
            print("%s step %d: %.1f examples/sec" % (self.name, self._step, eps))
            self._t0, self._step0 = time.perf_counter(), self._step
            return eps
        return None

    @property
    def average(self):
        if self.history:
            return sum(self.history) / len(self.history)
        # run shorter than one window: rate over whatever completed
        # (excluding the first, compile-bearing step)
        if self._t0 is not None and self._step > self._step0:
            dt = time.perf_counter() - self._t0
            return (self._step - self._step0) * self.batch_size / dt
        return 0.0


def _emit(line, path=None):
    print(line)
    if path:
        with open(path, "a") as f:
            f.write(line + "\n")


class BenchmarkLogger:
    def __init__(self, path=None):
        self.path = path

    def log(self, **record):
        record.setdefault("timestamp", time.time())
        _emit(json.dumps(record, sort_keys=True), self.path)


class MLPerfLogger:
    """MLPerf logging-spec lines (the reference vendors
    ``utils/logs/mlperf_helper.py`` for the same purpose):
    ``:::MLLOG {json}`` with ``time_ms``/``namespace``/``event_type``/
    ``key``/``value``/``metadata`` fields, the format the ``mlperf_logging``
    compliance checker parses."""

    def __init__(self, benchmark: str, path=None, namespace: str = ""):
        self.benchmark = benchmark
        self.namespace = namespace
        self.path = path

    def event(self, key, value=None, event_type="POINT_IN_TIME", **metadata):
        record = {
            "namespace": self.namespace,
            "time_ms": int(time.time() * 1000),
            "event_type": event_type,
            "key": key,
            "value": value,
            "metadata": metadata or None,
        }
        _emit(":::MLLOG " + json.dumps(record, sort_keys=True), self.path)

    # common MLPerf keys as conveniences
    def run_start(self, **md):
        self.event("run_start", event_type="INTERVAL_START", **md)

    def run_stop(self, status="success", **md):
        self.event("run_stop", event_type="INTERVAL_END", status=status, **md)

    def epoch(self, num, **md):
        self.event("epoch_num", num, **md)
