"""Benchmark logging utilities.

Analog of the reference's vendored ``examples/benchmark/utils/logs/``
(``hooks.py:28`` ExamplesPerSecondHook, ``logger.py`` BenchmarkLogger): a
throughput meter that logs examples/sec every N steps and a JSON-line
benchmark logger.
"""
import json
import time


class ExamplesPerSecondHook:
    def __init__(self, batch_size: int, every_n_steps: int = 100, name: str = ""):
        self.batch_size = batch_size
        self.every_n = every_n_steps
        self.name = name
        self._t0 = None
        self._step0 = 0
        self._step = 0
        self.history = []

    def after_step(self):
        self._step += 1
        if self._t0 is None:
            self._t0, self._step0 = time.perf_counter(), self._step
            return None
        if (self._step - self._step0) >= self.every_n:
            dt = time.perf_counter() - self._t0
            eps = (self._step - self._step0) * self.batch_size / dt
            self.history.append(eps)
            print("%s step %d: %.1f examples/sec" % (self.name, self._step, eps))
            self._t0, self._step0 = time.perf_counter(), self._step
            return eps
        return None

    @property
    def average(self):
        if self.history:
            return sum(self.history) / len(self.history)
        # run shorter than one window: rate over whatever completed
        # (excluding the first, compile-bearing step)
        if self._t0 is not None and self._step > self._step0:
            dt = time.perf_counter() - self._t0
            return (self._step - self._step0) * self.batch_size / dt
        return 0.0


class BenchmarkLogger:
    def __init__(self, path=None):
        self.path = path

    def log(self, **record):
        record.setdefault("timestamp", time.time())
        line = json.dumps(record, sort_keys=True)
        print(line)
        if self.path:
            with open(self.path, "a") as f:
                f.write(line + "\n")
