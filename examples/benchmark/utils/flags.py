"""Declarative benchmark flag system.

Analog of the reference's vendored TF-official flag package
(reference ``examples/benchmark/utils/flags/`` — ``core.py`` re-exporting
absl-style ``DEFINE_*`` plus grouped ``define_base`` /
``define_performance`` / ``define_benchmark`` helpers, consumed as
``flags.FLAGS`` in ``examples/benchmark/bert.py:50-79`` etc.). The
reference vendors absl; here the same declarative surface is ~150 lines
over argparse — a registry of typed flags, a module-level ``FLAGS``
namespace populated by ``parse()``, and the grouped define helpers the
benchmark scripts share.

Usage mirrors the reference scripts::

    from examples.benchmark.utils import flags

    flags.DEFINE_integer("train_batch_size", 8, "Total batch size.")
    flags.DEFINE_boolean("proxy", True, "turn on/off the proxy")
    flags.define_base()
    flags.define_performance()

    FLAGS = flags.FLAGS
    flags.parse()            # or parse(argv) for tests
    print(FLAGS.train_batch_size)

Flags may also be set from the environment as ``ADT_FLAG_<NAME>``
(checked at parse time, command line wins) — the knob the reference's
benchmark CI used absl's ``--flagfile`` for.
"""
import argparse
import os
from typing import Any, Dict, Optional, Sequence


class _FlagValues:
    """The ``FLAGS`` namespace: attribute access to parsed values;
    raises before ``parse()`` so an unparsed read cannot silently hand
    out defaults the command line would have overridden."""

    def __init__(self):
        object.__setattr__(self, "_values", None)

    def __getattr__(self, name):
        values = object.__getattribute__(self, "_values")
        if values is None:
            raise AttributeError(
                "FLAGS.%s read before flags.parse()" % name)
        try:
            return values[name]
        except KeyError:
            raise AttributeError("unknown flag %r (defined: %s)"
                                 % (name, sorted(values))) from None

    def __setattr__(self, name, value):
        values = object.__getattribute__(self, "_values")
        if values is None:
            raise AttributeError("FLAGS assignment before flags.parse()")
        values[name] = value


FLAGS = _FlagValues()

_registry: Dict[str, dict] = {}


def _define(name: str, default, help_str: str, typ, choices=None):
    if name in _registry:
        raise ValueError("flag %r already defined" % name)
    _registry[name] = {"default": default, "help": help_str, "type": typ,
                       "choices": choices}


def DEFINE_string(name, default, help):  # noqa: A002 — absl surface
    _define(name, default, help, str)


def DEFINE_integer(name, default, help):  # noqa: A002
    _define(name, default, help, int)


def DEFINE_float(name, default, help):  # noqa: A002
    _define(name, default, help, float)


def DEFINE_boolean(name=None, default=None, help=None, **kw):  # noqa: A002
    # the reference calls both positionally and with keywords
    # (``flags.DEFINE_boolean(name='proxy', default=True, ...)``)
    name = kw.get("name", name)
    default = kw.get("default", default)
    _define(name, bool(default), kw.get("help", help), bool)


DEFINE_bool = DEFINE_boolean


def DEFINE_enum(name, default, enum_values, help):  # noqa: A002
    _define(name, default, help, str, choices=list(enum_values))


# ---------------------------------------------------------------- groups


def define_base(data_dir=True, model_dir=True, train_epochs=True,
                batch_size=True):
    """The reference's shared training flags
    (``utils/flags/_base.py:28``)."""
    if data_dir and "data_dir" not in _registry:
        DEFINE_string("data_dir", "/tmp/data",
                      "Directory with input data (ADT record files).")
    if model_dir and "model_dir" not in _registry:
        DEFINE_string("model_dir", "/tmp/model",
                      "Directory for checkpoints/exports.")
    if train_epochs and "train_epochs" not in _registry:
        DEFINE_integer("train_epochs", 1, "Number of training epochs.")
    if batch_size and "batch_size" not in _registry:
        DEFINE_integer("batch_size", 32, "Global batch size.")


def define_performance(dtype=True, synthetic_data=True):
    """The reference's performance flags
    (``utils/flags/_performance.py:57``), TPU-native knobs."""
    if dtype and "dtype" not in _registry:
        DEFINE_enum("dtype", "bf16", ["bf16", "fp32"],
                    "Compute dtype (bf16 is the TPU deployment default).")
    if synthetic_data and "use_synthetic_data" not in _registry:
        DEFINE_boolean("use_synthetic_data", True,
                       "Synthetic batches instead of reading data_dir.")


def define_benchmark(benchmark_log_dir=True):
    """The reference's benchmark-logging flags
    (``utils/flags/_benchmark.py:26``); the BigQuery uploader has no
    analog here — logs are JSON lines (``utils/logs.py``)."""
    if benchmark_log_dir and "benchmark_log_dir" not in _registry:
        DEFINE_string("benchmark_log_dir", "",
                      "Where BenchmarkLogger writes metric JSON lines "
                      "('' = stderr only).")


# ----------------------------------------------------------------- parse


def parse(argv: Optional[Sequence[str]] = None) -> argparse.Namespace:
    """Parse ``argv`` (default sys.argv[1:]) against every defined flag.
    Precedence: command line > ``ADT_FLAG_<NAME>`` env > default."""
    p = argparse.ArgumentParser()
    for name, spec in sorted(_registry.items()):
        default = spec["default"]
        env = os.environ.get("ADT_FLAG_" + name.upper())
        if env is not None:
            if spec["type"] is bool:
                low = env.strip().lower()
                if low in ("1", "true", "yes", "on"):
                    default = True
                elif low in ("", "0", "false", "no", "off"):
                    default = False
                else:
                    raise SystemExit(
                        "ADT_FLAG_%s=%r is not a boolean (use 1/0, "
                        "true/false, yes/no, on/off)" % (name.upper(), env))
            else:
                default = spec["type"](env)
                if spec["choices"] and default not in spec["choices"]:
                    # argparse only validates EXPLICIT values, not defaults
                    raise SystemExit(
                        "ADT_FLAG_%s=%r not in choices %s"
                        % (name.upper(), env, spec["choices"]))
        if spec["type"] is bool:
            p.add_argument("--" + name, default=default,
                           action=argparse.BooleanOptionalAction,
                           help=spec["help"])
        else:
            p.add_argument("--" + name, type=spec["type"], default=default,
                           choices=spec["choices"], help=spec["help"])
    ns = p.parse_args(argv)
    object.__setattr__(FLAGS, "_values", vars(ns))
    return ns


def reset() -> None:
    """Drop every defined flag and parsed value (tests)."""
    _registry.clear()
    object.__setattr__(FLAGS, "_values", None)



def flags_dict() -> Dict[str, Any]:
    """The parsed values as a plain dict (the reference logger's
    ``flags_core.get_nondefault_flags_as_str`` use case)."""
    values = object.__getattribute__(FLAGS, "_values")
    if values is None:
        raise RuntimeError("flags.parse() has not run")
    return dict(values)
