"""NCF (NeuMF) recommender benchmark harness.

Mirror of reference ``examples/benchmark/ncf.py`` (MovieLens NeuMF):
synthetic interactions, examples/sec metric; the four embedding tables
stress the sparse/PS path.
"""

if __package__ in (None, ""):  # direct invocation: put the repo root on sys.path
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__)))))
import argparse

import optax

import autodist_tpu as adt
from autodist_tpu.models import ncf
from examples.benchmark.utils.logs import BenchmarkLogger, ExamplesPerSecondHook
from examples.benchmark.imagenet import make_builder


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--autodist_strategy", default="PSLoadBalancing")
    p.add_argument("--batch_size", type=int, default=1024)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--resource_spec", default=None)
    args = p.parse_args()

    ad = adt.AutoDist(resource_spec_file=args.resource_spec,
                      strategy_builder=make_builder(args.autodist_strategy, 512))
    loss_fn, params, batch, _ = ncf.make_train_setup(
        batch_size=args.batch_size)
    step = ad.function(loss_fn, optimizer=optax.adam(1e-3), params=params)
    hook = ExamplesPerSecondHook(args.batch_size, every_n_steps=20, name="ncf")
    for _ in range(args.steps):
        m = step(batch)
        hook.after_step()
    BenchmarkLogger().log(model="ncf", strategy=args.autodist_strategy,
                          examples_per_sec=round(hook.average, 1),
                          final_loss=float(m["loss"]))


if __name__ == "__main__":
    main()
