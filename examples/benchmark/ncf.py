"""NCF (NeuMF) recommender benchmark harness.

Mirror of reference ``examples/benchmark/ncf.py`` (MovieLens NeuMF).
``--data ratings.dat`` runs the REAL pipeline (reference
``utils/recommendation/``): parse ml-1m-format ratings, leave-one-out
split, positives through the native record loader, per-batch negative
sampling, HR@10/NDCG@10 eval, and a sparse-wire byte report on the real
id distribution. Without ``--data`` it benchmarks on synthetic
interactions (the r2 behavior); a synthetic ml-1m-format slice ships at
``examples/benchmark/data/ml_tiny_synthetic.dat``.
"""

if __package__ in (None, ""):  # direct invocation: put the repo root on sys.path
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__)))))
import argparse
import os
import tempfile

import numpy as np
import optax

import autodist_tpu as adt
from autodist_tpu.models import ncf
from examples.benchmark.utils.logs import BenchmarkLogger, ExamplesPerSecondHook
from examples.benchmark.imagenet import make_builder


def run_real_data(args, builder):
    from autodist_tpu.data import movielens
    data = movielens.load_ratings(args.data)
    train, holdout = movielens.leave_one_out_split(data)
    record_path = os.path.join(tempfile.gettempdir(),
                               "ncf_train_%d.adt" % os.getpid())
    movielens.write_train_records(train, record_path)
    try:
        _run_real_data_inner(args, builder, train, holdout, record_path)
    finally:
        for p in (record_path, record_path + ".json"):
            try:
                os.unlink(p)
            except OSError:
                pass


def _run_real_data_inner(args, builder, train, holdout, record_path):
    import math
    import jax
    from autodist_tpu.data import movielens
    # AutoDist BEFORE any device query (multi-node chief-launch joins the
    # distributed runtime at construction)
    ad = adt.AutoDist(resource_spec_file=args.resource_spec,
                      strategy_builder=builder)
    cfg = ncf.NCFConfig(num_users=train.num_users, num_items=train.num_items)
    loss_fn, params, _, apply_fn = ncf.make_train_setup(cfg)

    # global batch = pos x (1 + negatives) and must divide by the replica
    # count; round pos to the smallest multiple that makes it so
    group = 1 + args.neg_per_pos
    n_dev = len(jax.devices())
    step = n_dev // math.gcd(group, n_dev)
    pos_per_batch = max(step, (args.batch_size // group) // step * step)
    batches = movielens.train_batches(record_path, train, pos_per_batch,
                                      neg_per_pos=args.neg_per_pos)
    first = next(batches)
    runner = ad.build(loss_fn, optax.adam(1e-3), params, first)
    runner.init(params)
    hook = ExamplesPerSecondHook(len(first["user"]), every_n_steps=20,
                                 name="ncf")
    m = runner.run(first)
    for _ in range(args.steps - 1):
        m = runner.run(next(batches))
        hook.after_step()

    # sparse-wire accounting on the real id distribution
    wire = sorted(runner.distributed_step.metadata["sparse_wire"])
    store = runner.distributed_step.ps_store
    extra = {}
    if store is not None and store.stats["pushes"]:
        dense = sum(int(np.prod(v.shape)) * 4
                    for n, v in
                    runner.distributed_step.model_item.var_infos.items()
                    if n in wire and n in store.plans)
        pushed = store.stats["bytes_pushed"] / store.stats["pushes"]
        extra = {"dense_grad_bytes": dense,
                 "pushed_bytes_per_step": round(pushed),
                 "wire_savings_x": round(dense / max(pushed, 1), 1)}

    gathered = runner.gather_params()

    def score_fn(users, items):
        import jax.numpy as jnp
        return apply_fn(gathered, jnp.asarray(users), jnp.asarray(items))

    ev = movielens.evaluate_hit_ndcg(score_fn, holdout, train,
                                     num_negatives=args.eval_negatives)
    BenchmarkLogger().log(model="ncf", strategy=args.autodist_strategy,
                          data=os.path.basename(args.data),
                          interactions=train.n,
                          users=train.num_users, items=train.num_items,
                          examples_per_sec=round(hook.average, 1),
                          final_loss=float(m["loss"]),
                          hr_at_10=round(ev["hr"], 4),
                          ndcg_at_10=round(ev["ndcg"], 4),
                          sparse_wire_vars=len(wire), **extra)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--autodist_strategy", default="PSLoadBalancing")
    p.add_argument("--batch_size", type=int, default=1024)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--resource_spec", default=None)
    p.add_argument("--data", default=None,
                   help="MovieLens ratings file (ml-1m .dat or csv); "
                        "omit for synthetic interactions")
    p.add_argument("--neg_per_pos", type=int, default=4)
    p.add_argument("--eval_negatives", type=int, default=99)
    args = p.parse_args()

    builder = make_builder(args.autodist_strategy, 512)
    if args.data:
        run_real_data(args, builder)
        return
    ad = adt.AutoDist(resource_spec_file=args.resource_spec,
                      strategy_builder=builder)
    loss_fn, params, batch, _ = ncf.make_train_setup(
        batch_size=args.batch_size)
    step = ad.function(loss_fn, optimizer=optax.adam(1e-3), params=params)
    hook = ExamplesPerSecondHook(args.batch_size, every_n_steps=20, name="ncf")
    for _ in range(args.steps):
        m = step(batch)
        hook.after_step()
    BenchmarkLogger().log(model="ncf", strategy=args.autodist_strategy,
                          examples_per_sec=round(hook.average, 1),
                          final_loss=float(m["loss"]))


if __name__ == "__main__":
    main()
