"""BERT pretraining benchmark harness.

Mirror of reference ``examples/benchmark/bert.py`` (chunk_size 256 at
``:62``; strategy flag incl. Parallax): masked-LM pretraining on synthetic
sequences, samples/sec metric.

  python examples/benchmark/bert.py --config base --autodist_strategy Parallax
"""

if __package__ in (None, ""):  # direct invocation: put the repo root on sys.path
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__)))))
import argparse

import optax

import autodist_tpu as adt
from autodist_tpu.models import bert
from examples.benchmark.utils.logs import BenchmarkLogger, ExamplesPerSecondHook
from examples.benchmark.imagenet import make_builder

CONFIGS = {"tiny": bert.BertConfig.tiny, "base": bert.BertConfig.base,
           "large": bert.BertConfig.large}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="base", choices=sorted(CONFIGS))
    p.add_argument("--autodist_strategy", default="Parallax")
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--seq_len", type=int, default=128)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--resource_spec", default=None)
    args = p.parse_args()

    ad = adt.AutoDist(resource_spec_file=args.resource_spec,
                      strategy_builder=make_builder(args.autodist_strategy, 256))
    loss_fn, params, batch, _ = bert.make_train_setup(
        CONFIGS[args.config](), seq_len=args.seq_len,
        batch_size=args.batch_size)
    step = ad.function(loss_fn, optimizer=optax.adamw(1e-4), params=params)
    hook = ExamplesPerSecondHook(args.batch_size, every_n_steps=10, name="bert")
    for _ in range(args.steps):
        m = step(batch)
        hook.after_step()
    BenchmarkLogger().log(model="bert_" + args.config,
                          strategy=args.autodist_strategy,
                          samples_per_sec=round(hook.average, 1),
                          final_loss=float(m["loss"]))


if __name__ == "__main__":
    main()
