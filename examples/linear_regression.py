"""Linear regression — the canonical 3-line-change example.

Mirror of reference ``examples/linear_regression.py:16-73``: an ordinary
single-device JAX training script distributed by (1) constructing AutoDist
with a resource spec, (2) wrapping the step with ``ad.function``, (3)
feeding host batches. Run: ``python examples/linear_regression.py
[resource_spec.yml]``.
"""

if __package__ in (None, ""):  # direct invocation: put the repo root on sys.path
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import sys

import jax.numpy as jnp
import numpy as np
import optax

import autodist_tpu as adt
from autodist_tpu import strategy

TRUE_W, TRUE_B = 3.0, 2.0
NUM_EXAMPLES = 2048
BATCH = 256


def main():
    spec_file = sys.argv[1] if len(sys.argv) > 1 else None
    ad = adt.AutoDist(resource_spec_file=spec_file,
                      strategy_builder=strategy.PS(sync=True))  # change 1

    rng = np.random.RandomState(0)
    inputs = rng.randn(NUM_EXAMPLES).astype(np.float32)
    noise = 0.1 * rng.randn(NUM_EXAMPLES).astype(np.float32)
    outputs = inputs * TRUE_W + TRUE_B + noise

    params = {"W": jnp.asarray(5.0), "b": jnp.asarray(0.0)}

    def loss_fn(p, batch):
        pred = batch["x"] * p["W"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    train_step = ad.function(loss_fn, optimizer=optax.sgd(0.01),
                             params=params)                      # change 2

    for epoch in range(10):
        for i in range(0, NUM_EXAMPLES, BATCH):
            batch = {"x": inputs[i:i + BATCH], "y": outputs[i:i + BATCH]}
            metrics = train_step(batch)                          # change 3
        print("epoch %d loss %.5f" % (epoch, metrics["loss"]))

    final = train_step.get_runner().gather_params()
    print("W=%.3f (true %.1f)  b=%.3f (true %.1f)"
          % (final["W"], TRUE_W, final["b"], TRUE_B))


if __name__ == "__main__":
    main()
