"""lm1b-style language-model training (words/sec metric).

Mirror of reference ``examples/lm1b/lm1b_train.py`` (``:62-75`` logs wps =
batch x num_replicas x log_frequency / elapsed): a causal transformer LM on
synthetic 1B-word-shaped data under PartitionedPS (the reference's lm1b
config per BASELINE.md).
"""

if __package__ in (None, ""):  # direct invocation: put the repo root on sys.path
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__)))))
import argparse
import dataclasses
import os
import time

import optax

import autodist_tpu as adt
from autodist_tpu import strategy as S
from autodist_tpu.models import lm


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="tiny",
                   choices=["tiny", "byte", "default", "lm1b"])
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--seq_len", type=int, default=128)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--log_frequency", type=int, default=20)
    p.add_argument("--resource_spec", default=None)
    p.add_argument("--data", default="synthetic",
                   help="'synthetic', or a directory of text files to "
                        "tokenize (byte-level) through the native record "
                        "loader; 'docs' uses the repo's own documentation")
    args = p.parse_args()

    cfg = {"tiny": lm.LMConfig.tiny, "default": lm.LMConfig,
           # byte-level vocab for raw-text corpora (--data), small dims
           "byte": lambda: lm.LMConfig(vocab_size=256, d_model=128,
                                       num_layers=2, num_heads=4,
                                       mlp_dim=256),
           "lm1b": lm.LMConfig.lm1b}[args.config]()
    if cfg.max_seq_len < args.seq_len:
        cfg = dataclasses.replace(cfg, max_seq_len=args.seq_len)
    ad = adt.AutoDist(resource_spec_file=args.resource_spec,
                      strategy_builder=S.PartitionedPS())
    loss_fn, params, batch, _ = lm.make_train_setup(
        cfg, seq_len=args.seq_len, batch_size=args.batch_size)
    step = ad.function(loss_fn, optimizer=optax.adam(1e-3), params=params)

    batches = None
    if args.data != "synthetic":
        # real text -> ADT1 records -> native loader (vocab must be
        # byte-level for raw text)
        import glob
        import tempfile
        from autodist_tpu.data import text as text_lib
        from autodist_tpu.data.record_dataset import RecordFileDataset
        if cfg.vocab_size < text_lib.BYTE_VOCAB:
            raise SystemExit("--data needs vocab_size >= 256 (byte tokens)")
        if args.data == "docs":
            repo = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            paths = text_lib.repo_docs_corpus(repo)
        else:
            paths = sorted(glob.glob(os.path.join(args.data, "*")))
        # per-process path: concurrent runs must not clobber each other's
        # records while the native loader has them mmapped
        rec = os.path.join(tempfile.gettempdir(),
                           "lm1b_text_%d.adt" % os.getpid())
        n = text_lib.write_lm_records(paths, rec, seq_len=args.seq_len)
        print("real-text corpus: %d files -> %d records" % (len(paths), n))
        ds = RecordFileDataset(rec, batch_size=args.batch_size, shuffle=True)
        batches = iter(ds)

    t0, words = time.perf_counter(), 0
    run_t0, run_words, m = None, 0, {"loss": float("nan")}
    for i in range(args.steps):
        m = step(batch if batches is None else next(batches))
        words += args.batch_size * args.seq_len
        if run_t0 is None:
            run_t0 = time.perf_counter()  # post-compile clock for the summary
        else:
            run_words += args.batch_size * args.seq_len
        if (i + 1) % args.log_frequency == 0:
            dt = time.perf_counter() - t0
            print("step %d loss %.4f wps %.1f" % (i + 1, m["loss"], words / dt))
            t0, words = time.perf_counter(), 0
    wps = run_words / (time.perf_counter() - run_t0) if run_words else 0.0
    print("lm1b done: %d steps, final loss %.4f, %.1f words/sec"
          % (args.steps, m["loss"], wps))


if __name__ == "__main__":
    main()
