"""lm1b-style language-model training (words/sec metric).

Mirror of reference ``examples/lm1b/lm1b_train.py`` (``:62-75`` logs wps =
batch x num_replicas x log_frequency / elapsed): a causal transformer LM on
synthetic 1B-word-shaped data under PartitionedPS (the reference's lm1b
config per BASELINE.md).
"""

if __package__ in (None, ""):  # direct invocation: put the repo root on sys.path
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__)))))
import argparse
import dataclasses
import os
import time

import optax

import autodist_tpu as adt
from autodist_tpu import strategy as S
from autodist_tpu.models import lm


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="tiny",
                   choices=["tiny", "byte", "default", "lm1b"])
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--seq_len", type=int, default=128)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--log_frequency", type=int, default=20)
    p.add_argument("--resource_spec", default=None)
    p.add_argument("--data", default="synthetic",
                   help="'synthetic', or a directory of text files to "
                        "tokenize (byte-level) through the native record "
                        "loader; 'docs' uses the repo's own documentation")
    p.add_argument("--decode", type=int, default=0, metavar="N",
                   help="after training, generate N tokens per prompt "
                        "through the continuous-batching DecodeEngine "
                        "(serving/decode.py)")
    args = p.parse_args()

    cfg = {"tiny": lm.LMConfig.tiny, "default": lm.LMConfig,
           # byte-level vocab for raw-text corpora (--data), small dims
           "byte": lambda: lm.LMConfig(vocab_size=256, d_model=128,
                                       num_layers=2, num_heads=4,
                                       mlp_dim=256),
           "lm1b": lm.LMConfig.lm1b}[args.config]()
    if cfg.max_seq_len < args.seq_len:
        cfg = dataclasses.replace(cfg, max_seq_len=args.seq_len)
    ad = adt.AutoDist(resource_spec_file=args.resource_spec,
                      strategy_builder=S.PartitionedPS())
    loss_fn, params, batch, _ = lm.make_train_setup(
        cfg, seq_len=args.seq_len, batch_size=args.batch_size)
    step = ad.function(loss_fn, optimizer=optax.adam(1e-3), params=params)

    batches = None
    if args.data != "synthetic":
        # real text -> ADT1 records -> native loader (vocab must be
        # byte-level for raw text)
        import glob
        import tempfile
        from autodist_tpu.data import text as text_lib
        from autodist_tpu.data.record_dataset import RecordFileDataset
        if cfg.vocab_size < text_lib.BYTE_VOCAB:
            raise SystemExit("--data needs vocab_size >= 256 (byte tokens)")
        if args.data == "docs":
            repo = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            paths = text_lib.repo_docs_corpus(repo)
        else:
            paths = sorted(glob.glob(os.path.join(args.data, "*")))
        # per-process path: concurrent runs must not clobber each other's
        # records while the native loader has them mmapped
        rec = os.path.join(tempfile.gettempdir(),
                           "lm1b_text_%d.adt" % os.getpid())
        n = text_lib.write_lm_records(paths, rec, seq_len=args.seq_len)
        print("real-text corpus: %d files -> %d records" % (len(paths), n))
        ds = RecordFileDataset(rec, batch_size=args.batch_size, shuffle=True)
        batches = iter(ds)

    t0, words = time.perf_counter(), 0
    run_t0, run_words, m = None, 0, {"loss": float("nan")}
    for i in range(args.steps):
        m = step(batch if batches is None else next(batches))
        words += args.batch_size * args.seq_len
        if run_t0 is None:
            run_t0 = time.perf_counter()  # post-compile clock for the summary
        else:
            run_words += args.batch_size * args.seq_len
        if (i + 1) % args.log_frequency == 0:
            dt = time.perf_counter() - t0
            print("step %d loss %.4f wps %.1f" % (i + 1, m["loss"], words / dt))
            t0, words = time.perf_counter(), 0
    wps = run_words / (time.perf_counter() - run_t0) if run_words else 0.0
    print("lm1b done: %d steps, final loss %.4f, %.1f words/sec"
          % (args.steps, m["loss"], wps))

    if args.decode > 0:
        decode(step.get_runner(), cfg, args.decode, args.batch_size)


def decode(runner, cfg, n_tokens: int, batch_size: int):
    """Autoregressive generation from the trained checkpoint through the
    continuous-batching decode engine — the runnable entry point behind
    ``bench.py --serve-decode`` and docs/serving.md."""
    import numpy as np

    from autodist_tpu.serving.decode import DecodeConfig, DecodeEngine

    replicas = runner.remapper.num_replicas
    slots = max(8 // max(replicas, 1), 1) * max(replicas, 1)
    setup = lm.make_decode_setup(cfg)
    engine = DecodeEngine(runner, setup, DecodeConfig(
        slots=slots, max_new_tokens=n_tokens,
        prefill_len=min(16, cfg.max_seq_len // 2)))
    engine.warmup()
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, (1 + i % 8,)).astype(np.int32)
               for i in range(min(batch_size, 2 * slots))]
    t0 = time.perf_counter()
    futures = [engine.submit(p) for p in prompts]
    results = [f.result(timeout=600) for f in futures]
    dt = time.perf_counter() - t0
    stats = engine.stats()
    total = sum(len(r["tokens"]) for r in results)
    for p, r in zip(prompts[:4], results[:4]):
        print("prompt %s -> %s (%s)" % (list(map(int, p)),
                                        list(map(int, r["tokens"])),
                                        r["finished"]))
    print("decode done: %d sequences, %d tokens, %.1f tokens/sec, "
          "token p50 %.2fms p99 %.2fms, recompiles after warmup: %d"
          % (len(results), total, total / dt,
             stats["token_p50_ms"] or 0.0, stats["token_p99_ms"] or 0.0,
             stats["recompiles_after_warmup"]))
    engine.close()


if __name__ == "__main__":
    main()
