"""Calibrate AutoStrategy's cost model from measured runs, then reuse it.

The analytic cost model ranks candidate strategies from closed-form
constants; real hardware disagrees (throttled chips, slow host links).
This example measures a few strategies for real, fits the model's term
scales to those measurements (``Simulator.calibrate`` — the reference's
AutoSync measured-runs idea, ``autodist/simulator/dataset/README.md``,
realized over our analytic model), persists them, and lets
``AutoStrategy(calibration=...)`` pick with corrected constants.

Run on anything (CPU works):
    python examples/autostrategy_calibrate.py
"""
if __package__ in (None, ""):  # direct invocation: repo root on sys.path
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))))
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import autodist_tpu as adt
from autodist_tpu import strategy as S
from autodist_tpu.model_item import ModelItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.simulator.simulator import Simulator

from autodist_tpu import const

CAL_PATH = os.path.join(const.DEFAULT_WORKING_DIR, "calibration.json")


def build_case(seed=0):
    rng = np.random.RandomState(seed)
    params = {"emb": jnp.asarray(rng.randn(8192, 64), jnp.float32),
              "w": jnp.asarray(rng.randn(64, 8), jnp.float32)}

    def loss_fn(p, b):
        e = jnp.take(p["emb"], b["ids"], axis=0)
        return jnp.mean((e @ p["w"] - b["y"]) ** 2)

    batch = {"ids": rng.randint(0, 8192, (64,)).astype(np.int32),
             "y": rng.randn(64, 8).astype(np.float32)}
    return loss_fn, params, batch


def measure(builder, loss_fn, params, batch, steps=10):
    """Median steady step time through the full framework stack (the
    Runner's own step_stats supplies the steady median and goodput)."""
    adt.reset()
    ad = adt.AutoDist(strategy_builder=builder)
    runner = ad.build(loss_fn, optax.adam(1e-3), params, batch)
    runner.init(params)
    for _ in range(3 + steps):
        runner.run(batch)
    stats = runner.step_stats()
    strat = runner.distributed_step.strategy
    print("  %-18s steady=%.2fms goodput=%.2f"
          % (type(builder).__name__, stats["steady_median_s"] * 1e3,
             stats["goodput"]))
    return strat, stats["steady_median_s"]


def main():
    loss_fn, params, batch = build_case()
    print("measuring candidate strategies for real:")
    measured = [measure(b, loss_fn, params, batch)
                for b in (S.AllReduce(), S.PSLoadBalancing(), S.Parallax())]
    adt.reset()

    item = ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-3),
                     params=params, example_batch=batch).prepare()
    sim = Simulator(item, ResourceSpec.from_local())
    cal = sim.calibrate(measured, save_path=CAL_PATH)
    print("fitted scales:", cal.to_dict())
    print("saved ->", CAL_PATH)

    # future sessions on the same hardware reuse the file
    builder = S.AutoStrategy(calibration=CAL_PATH)
    ad = adt.AutoDist(strategy_builder=builder)
    step = ad.function(loss_fn, optimizer=optax.adam(1e-3), params=params)
    t0 = time.perf_counter()
    losses = [float(step(batch)["loss"]) for _ in range(5)]
    print("AutoStrategy picked %s; 5 steps in %.2fs, loss %.4f -> %.4f"
          % (builder.last_ranking[0].label, time.perf_counter() - t0,
             losses[0], losses[-1]))
    adt.reset()


if __name__ == "__main__":
    main()
