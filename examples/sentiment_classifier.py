"""Sentiment classifier with a partitioned embedding.

Mirror of reference ``examples/sentiment_classifier.py`` (embedding model
under PartitionedPS, ``:12,22-41``): mean-pooled word embeddings + dense
head; the vocabulary table is sharded across parameter servers.
Synthetic data (the reference downloads IMDB).
"""

if __package__ in (None, ""):  # direct invocation: put the repo root on sys.path
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
import jax
import jax.numpy as jnp
import numpy as np
import optax

import autodist_tpu as adt
from autodist_tpu import strategy

VOCAB, SEQ, BATCH, EMBED = 10_000, 64, 128, 64


def main():
    ad = adt.AutoDist(strategy_builder=strategy.PartitionedPS())

    rng = np.random.RandomState(0)
    key = jax.random.PRNGKey(0)
    params = {
        "embedding": jax.random.normal(key, (VOCAB, EMBED)) * 0.05,
        "dense": {"kernel": jax.random.normal(key, (EMBED, 1)) * 0.1,
                  "bias": jnp.zeros((1,))},
    }

    def loss_fn(p, batch):
        # named lookup -> sparse (ids, values) gradient wire
        from autodist_tpu.ops.embedding import embedding_lookup
        emb = embedding_lookup(p["embedding"], batch["tokens"],
                               name="embedding")  # [B,S,E]
        pooled = jnp.mean(emb, axis=1)
        logits = (pooled @ p["dense"]["kernel"] + p["dense"]["bias"])[..., 0]
        labels = batch["label"].astype(jnp.float32)
        loss = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(
            jnp.exp(-jnp.abs(logits)))
        return jnp.mean(loss)

    step = ad.function(loss_fn, optimizer=optax.adam(1e-3), params=params)
    for i in range(50):
        batch = {"tokens": rng.randint(0, VOCAB, (BATCH, SEQ)).astype(np.int32),
                 "label": rng.randint(0, 2, (BATCH,)).astype(np.int32)}
        m = step(batch)
        if i % 10 == 0:
            print("step %d loss %.4f" % (i, m["loss"]))


if __name__ == "__main__":
    main()
