"""Checkpoint tests.

Mirrors reference ``tests/checkpoint/test_partitionedPS_saver.py``: train
under PartitionedPS, save, then reload and continue training in *vanilla*
JAX/optax (no framework objects), asserting loss continuity; plus
framework-side resume and the SavedModel-style export
(``tests/checkpoint/test_saved_model.py``).
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.checkpoint.saved_model_builder import SavedModelBuilder


def _problem():
    rng = np.random.RandomState(1)
    params = {"emb": jnp.asarray(rng.randn(16, 4).astype(np.float32)),
              "w": jnp.asarray(rng.randn(4, 2).astype(np.float32))}

    def loss_fn(p, batch):
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        pred = feat @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, 16, (16,)).astype(np.int32),
             "y": rng.randn(16, 2).astype(np.float32)}
    return params, loss_fn, batch


def test_partitioned_save_restores_in_vanilla_jax(tmp_path):
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedPS())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        m = runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    path = saver.save(runner)
    assert path is not None

    # --- vanilla continuation: numpy.load only, no framework objects
    flat = dict(np.load(path + ".params.npz"))
    assert set(flat) == {"emb", "w"}
    assert flat["emb"].shape == (16, 4)  # original, unpadded layout
    vanilla_params = {"emb": jnp.asarray(flat["emb"]), "w": jnp.asarray(flat["w"])}
    vp_loss_before = float(loss_fn(vanilla_params, batch))
    # continuity: step metrics report the PRE-update loss, so the saved
    # (post-step-3) params must reproduce step 4's reported loss exactly
    m4 = runner.run(batch)
    assert abs(vp_loss_before - m4["loss"]) < 1e-4

    vopt_state = opt.init(vanilla_params)
    g = jax.grad(loss_fn)(vanilla_params, batch)
    updates, vopt_state = opt.update(g, vopt_state, vanilla_params)
    vanilla_params = optax.apply_updates(vanilla_params, updates)
    assert float(loss_fn(vanilla_params, batch)) < vp_loss_before * 1.2


def test_framework_resume_bitexact(tmp_path):
    """Save at step 3, keep training to 5; restore at 3 and retrain to 5:
    identical params (optimizer state round-trips exactly)."""
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    saver.save(runner)
    for _ in range(2):
        runner.run(batch)
    final_a = runner.gather_params()

    state, step = saver.restore(runner)
    assert step == 3
    for _ in range(2):
        runner.run(batch)
    final_b = runner.gather_params()
    for k in final_a:
        np.testing.assert_allclose(np.asarray(final_a[k]), np.asarray(final_b[k]),
                                   rtol=1e-6, atol=1e-6)


def test_resume_with_compressor_state_bitexact(tmp_path):
    """Error-feedback residuals must round-trip through checkpoints."""
    params, loss_fn, batch = _problem()
    opt = optax.sgd(0.05)
    ad = autodist_tpu.AutoDist(
        strategy_builder=S.AllReduce(compressor="HorovodCompressorEF"))
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    path = saver.save(runner)
    import os
    assert os.path.exists(path + ".sync.npz")
    for _ in range(2):
        runner.run(batch)
    final_a = runner.gather_params()

    saver.restore(runner, path)
    for _ in range(2):
        runner.run(batch)
    final_b = runner.gather_params()
    for k in final_a:
        np.testing.assert_allclose(np.asarray(final_a[k]), np.asarray(final_b[k]),
                                   rtol=1e-6, atol=1e-6)


def test_gc_ignores_foreign_files(tmp_path):
    (tmp_path / "best-model.meta.json").write_text("{}")
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    runner.run(batch)
    saver = Saver(directory=str(tmp_path), max_to_keep=1)
    assert saver.save(runner) is not None  # must not crash on the foreign file
    assert (tmp_path / "best-model.meta.json").exists()


def test_max_to_keep(tmp_path):
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    saver = Saver(directory=str(tmp_path), max_to_keep=2)
    for i in range(4):
        runner.run(batch)
        saver.save(runner)
    import os
    metas = [f for f in os.listdir(tmp_path) if f.endswith(".meta.json")]
    assert len(metas) == 2
    assert saver.latest().endswith("ckpt-4")


def test_saved_model_export(tmp_path):
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.Parallax())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    runner.run(batch)
    out = SavedModelBuilder(str(tmp_path / "export")).save(runner)
    import json, os
    spec = json.load(open(os.path.join(out, "model_spec.json")))
    assert spec["optimizer_name"] == "sgd"
    flat = dict(np.load(os.path.join(out, "params.npz")))
    assert flat["emb"].shape == (16, 4)


def test_async_save_equivalent_and_overlapping(tmp_path):
    """async_save writes the same bytes as sync save; training continues
    while the write is in flight; latest()/restore join the writer."""
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)

    sync_saver = Saver(directory=str(tmp_path / "sync"))
    sync_saver.save(runner)
    async_saver = Saver(directory=str(tmp_path / "async"), async_save=True)
    async_saver.save(runner)
    runner.run(batch)  # train while the write may still be in flight

    a, b = sync_saver.latest(), async_saver.latest()  # latest() joins writer
    assert a is not None and b is not None
    fa, fb = dict(np.load(a + ".params.npz")), dict(np.load(b + ".params.npz"))
    assert sorted(fa) == sorted(fb)
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k])

    # restore from the async checkpoint resumes at the saved step
    state, step = async_saver.restore(runner)
    assert step == 3
    # back-to-back async saves serialize (at most one writer in flight)
    async_saver.save(runner, step=100)
    async_saver.save(runner, step=101)
    async_saver.wait()
    steps = [s for s, _ in async_saver._own_metas()]
    assert 100 in steps and 101 in steps
