"""Checkpoint tests.

Mirrors reference ``tests/checkpoint/test_partitionedPS_saver.py``: train
under PartitionedPS, save, then reload and continue training in *vanilla*
JAX/optax (no framework objects), asserting loss continuity; plus
framework-side resume and the SavedModel-style export
(``tests/checkpoint/test_saved_model.py``).
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.checkpoint.saved_model_builder import SavedModelBuilder


def _problem():
    rng = np.random.RandomState(1)
    params = {"emb": jnp.asarray(rng.randn(16, 4).astype(np.float32)),
              "w": jnp.asarray(rng.randn(4, 2).astype(np.float32))}

    def loss_fn(p, batch):
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        pred = feat @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, 16, (16,)).astype(np.int32),
             "y": rng.randn(16, 2).astype(np.float32)}
    return params, loss_fn, batch


def test_partitioned_save_restores_in_vanilla_jax(tmp_path):
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedPS())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        m = runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    path = saver.save(runner)
    assert path is not None

    # --- vanilla continuation: numpy.load only, no framework objects
    flat = dict(np.load(path + ".params.npz"))
    assert set(flat) == {"emb", "w"}
    assert flat["emb"].shape == (16, 4)  # original, unpadded layout
    vanilla_params = {"emb": jnp.asarray(flat["emb"]), "w": jnp.asarray(flat["w"])}
    vp_loss_before = float(loss_fn(vanilla_params, batch))
    # continuity: step metrics report the PRE-update loss, so the saved
    # (post-step-3) params must reproduce step 4's reported loss exactly
    m4 = runner.run(batch)
    assert abs(vp_loss_before - m4["loss"]) < 1e-4

    vopt_state = opt.init(vanilla_params)
    g = jax.grad(loss_fn)(vanilla_params, batch)
    updates, vopt_state = opt.update(g, vopt_state, vanilla_params)
    vanilla_params = optax.apply_updates(vanilla_params, updates)
    assert float(loss_fn(vanilla_params, batch)) < vp_loss_before * 1.2


def test_framework_resume_bitexact(tmp_path):
    """Save at step 3, keep training to 5; restore at 3 and retrain to 5:
    identical params (optimizer state round-trips exactly)."""
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    saver.save(runner)
    for _ in range(2):
        runner.run(batch)
    final_a = runner.gather_params()

    state, step = saver.restore(runner)
    assert step == 3
    for _ in range(2):
        runner.run(batch)
    final_b = runner.gather_params()
    for k in final_a:
        np.testing.assert_allclose(np.asarray(final_a[k]), np.asarray(final_b[k]),
                                   rtol=1e-6, atol=1e-6)


def test_resume_with_compressor_state_bitexact(tmp_path):
    """Error-feedback residuals must round-trip through checkpoints."""
    params, loss_fn, batch = _problem()
    opt = optax.sgd(0.05)
    ad = autodist_tpu.AutoDist(
        strategy_builder=S.AllReduce(compressor="HorovodCompressorEF"))
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    path = saver.save(runner)
    import os
    assert os.path.exists(path + ".sync.npz")
    for _ in range(2):
        runner.run(batch)
    final_a = runner.gather_params()

    saver.restore(runner, path)
    for _ in range(2):
        runner.run(batch)
    final_b = runner.gather_params()
    for k in final_a:
        np.testing.assert_allclose(np.asarray(final_a[k]), np.asarray(final_b[k]),
                                   rtol=1e-6, atol=1e-6)


def test_gc_ignores_foreign_files(tmp_path):
    (tmp_path / "best-model.meta.json").write_text("{}")
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    runner.run(batch)
    saver = Saver(directory=str(tmp_path), max_to_keep=1)
    assert saver.save(runner) is not None  # must not crash on the foreign file
    assert (tmp_path / "best-model.meta.json").exists()


def test_max_to_keep(tmp_path):
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    saver = Saver(directory=str(tmp_path), max_to_keep=2)
    for i in range(4):
        runner.run(batch)
        saver.save(runner)
    import os
    metas = [f for f in os.listdir(tmp_path) if f.endswith(".meta.json")]
    assert len(metas) == 2
    assert saver.latest().endswith("ckpt-4")


def test_saved_model_export(tmp_path):
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.Parallax())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    runner.run(batch)
    out = SavedModelBuilder(str(tmp_path / "export")).save(runner)
    import json, os
    spec = json.load(open(os.path.join(out, "model_spec.json")))
    assert spec["optimizer_name"] == "sgd"
    flat = dict(np.load(os.path.join(out, "params.npz")))
    assert flat["emb"].shape == (16, 4)


def test_async_save_equivalent_and_overlapping(tmp_path):
    """async_save writes the same bytes as sync save; training continues
    while the write is in flight; latest()/restore join the writer."""
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)

    sync_saver = Saver(directory=str(tmp_path / "sync"))
    sync_saver.save(runner)
    async_saver = Saver(directory=str(tmp_path / "async"), async_save=True)
    async_saver.save(runner)
    runner.run(batch)  # train while the write may still be in flight

    a, b = sync_saver.latest(), async_saver.latest()  # latest() joins writer
    assert a is not None and b is not None
    fa, fb = dict(np.load(a + ".params.npz")), dict(np.load(b + ".params.npz"))
    assert sorted(fa) == sorted(fb)
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k])

    # restore from the async checkpoint resumes at the saved step
    state, step = async_saver.restore(runner)
    assert step == 3
    # back-to-back async saves serialize (at most one writer in flight)
    async_saver.save(runner, step=100)
    async_saver.save(runner, step=101)
    async_saver.wait()
    steps = [s for s, _ in async_saver._own_metas()]
    assert 100 in steps and 101 in steps


# ---------------------------------------------------------------- sharded


def _shard_files(d):
    import os
    return sorted(f for f in os.listdir(d) if ".shard-p" in f and
                  f.endswith(".npz"))


def test_sharded_roundtrip_bitexact(tmp_path):
    """Sharded save at step 3 -> restore -> retrain == uninterrupted run,
    with per-slice keys (not whole tensors) in the shard file."""
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = ShardedSaver(directory=str(tmp_path))
    base = saver.save(runner)
    assert base is not None
    # the partitioned var is stored as per-device slices
    flat = np.load(base + ".shard-p0.npz")
    emb_keys = [k for k in flat.files if k.startswith("P|emb|")]
    assert len(emb_keys) == 8  # one slice per device of the 8-way mesh
    got = {k: flat[k].shape for k in emb_keys}
    assert all(s[0] == 2 for s in got.values()), got  # 16/8 rows each

    for _ in range(2):
        runner.run(batch)
    final_a = runner.gather_params()

    state, step = saver.restore(runner)
    assert step == 3
    for _ in range(2):
        runner.run(batch)
    final_b = runner.gather_params()
    for k in final_a:
        np.testing.assert_array_equal(np.asarray(final_a[k]),
                                      np.asarray(final_b[k]))


def test_sharded_host_ps_roundtrip(tmp_path):
    """Host-resident PS vars (values + per-shard optimizer state) ride the
    sharded format and resume bit-exact."""
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedPS())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    assert runner.distributed_step.ps_store is not None
    for _ in range(3):
        runner.run(batch)
    saver = ShardedSaver(directory=str(tmp_path))
    base = saver.save(runner)
    flat = np.load(base + ".shard-p0.npz")
    assert any(k.startswith("H|") for k in flat.files)
    assert any(k.startswith("Ho|") for k in flat.files)

    for _ in range(2):
        runner.run(batch)
    final_a = runner.gather_params()

    saver.restore(runner)
    for _ in range(2):
        runner.run(batch)
    final_b = runner.gather_params()
    for k in final_a:
        np.testing.assert_array_equal(np.asarray(final_a[k]),
                                      np.asarray(final_b[k]))


def test_sharded_export_matches_plain_saver(tmp_path):
    """export_full() produces a byte-identical Saver-format checkpoint —
    the vanilla-reload property survives as an export."""
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(
        strategy_builder=S.AllReduce(compressor="HorovodCompressorEF"))
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    plain = Saver(directory=str(tmp_path / "plain"))
    ppath = plain.save(runner)
    sharded = ShardedSaver(directory=str(tmp_path / "sharded"))
    sharded.save(runner)
    epath = sharded.export_full(out_dir=str(tmp_path / "export"))

    for suffix in (".params.npz", ".opt.npz", ".sync.npz"):
        a = dict(np.load(ppath + suffix))
        b = dict(np.load(epath + suffix))
        assert sorted(a) == sorted(b), (suffix, sorted(a), sorted(b))
        for k in a:
            np.testing.assert_array_equal(a[k], b[k], err_msg="%s %s"
                                          % (suffix, k))

    # the exported checkpoint restores through the plain Saver
    restorer = Saver(directory=str(tmp_path / "export"))
    state, step = restorer.restore(runner)
    assert step == 3


def test_sharded_export_ps_matches_plain_saver(tmp_path):
    """Same export equivalence for the host-PS (partitioned, no-proxy)
    path: values from store shards, optimizer slots reassembled."""
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedPS())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    plain = Saver(directory=str(tmp_path / "plain"))
    ppath = plain.save(runner)
    sharded = ShardedSaver(directory=str(tmp_path / "sharded"))
    sharded.save(runner)
    epath = sharded.export_full(out_dir=str(tmp_path / "export"))
    for suffix in (".params.npz", ".opt.npz"):
        a = dict(np.load(ppath + suffix))
        b = dict(np.load(epath + suffix))
        assert sorted(a) == sorted(b), (suffix, sorted(a), sorted(b))
        for k in a:
            np.testing.assert_array_equal(a[k], b[k], err_msg="%s %s"
                                          % (suffix, k))


def test_sharded_max_to_keep_and_async(tmp_path):
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    saver = ShardedSaver(directory=str(tmp_path), max_to_keep=2,
                         async_save=True)
    for _ in range(4):
        runner.run(batch)
        saver.save(runner)
    saver.wait()
    import os
    metas = [f for f in os.listdir(tmp_path) if f.endswith("shard-meta.json")]
    assert len(metas) == 2
    assert saver.latest().endswith("ckpt-4")
    # evicted steps' shard files are gone too
    assert not any(f.startswith("ckpt-1.") or f.startswith("ckpt-2.")
                   for f in os.listdir(tmp_path))
    state, step = saver.restore(runner)
    assert step == 4


def _ragged_problem():
    """Split dim 18 is NOT divisible by 8/4/2 the same way, so every mesh
    size pads differently (8-way -> 24, 4-way -> 20, 2-way -> 18): the
    cross-topology restore must re-pad, not just re-slice."""
    rng = np.random.RandomState(7)
    params = {"emb": jnp.asarray(rng.randn(18, 4).astype(np.float32)),
              "w": jnp.asarray(rng.randn(4, 2).astype(np.float32))}

    def loss_fn(p, batch):
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        pred = feat @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, 18, (16,)).astype(np.int32),
             "y": rng.randn(16, 2).astype(np.float32)}
    return params, loss_fn, batch


def _cpu_spec(n):
    from autodist_tpu.resource_spec import ResourceSpec
    return ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True,
                    "cpus": list(range(n))}]})


@pytest.mark.parametrize("builder", ["PartitionedAR", "PartitionedPS"])
def test_sharded_restore_across_topologies(tmp_path, builder):
    """VERDICT-r4 #1: save on an 8-device mesh, restore BIT-EXACT on 4 and
    on 2 (different padding each time), then scale back up 2 -> 8 — slices
    reassembled from the global ranges in the npz keys, the reference's
    topology-independent SaveSliceInfo property
    (reference ``autodist/kernel/partitioner.py:292-347``)."""
    from autodist_tpu.checkpoint import ShardedSaver
    make = lambda: getattr(S, builder)()  # noqa: E731
    params, loss_fn, batch = _ragged_problem()
    opt = optax.adam(0.05)
    ad8 = autodist_tpu.AutoDist(strategy_builder=make())
    runner8 = ad8.build(loss_fn, opt, params, batch)
    runner8.init(params)
    for _ in range(3):
        runner8.run(batch)
    want = {k: np.asarray(v) for k, v in runner8.gather_params().items()}
    saver = ShardedSaver(directory=str(tmp_path))
    saver.save(runner8)

    down_losses = {}
    for n in (4, 2):
        autodist_tpu.reset()
        ad_n = autodist_tpu.AutoDist(resource_spec=_cpu_spec(n),
                                     strategy_builder=make())
        runner_n = ad_n.build(loss_fn, opt, params, batch)
        runner_n.init(params)
        state, step = saver.restore(runner_n)
        assert step == 3
        got = {k: np.asarray(v) for k, v in runner_n.gather_params().items()}
        assert sorted(got) == sorted(want)
        for k in want:
            np.testing.assert_array_equal(got[k], want[k],
                                          err_msg="8->%d %s" % (n, k))
        # training continues: the restored optimizer state is live too
        down_losses[n] = [float(runner_n.run(batch)["loss"])
                          for _ in range(2)]
        if n == 2:
            saver2 = ShardedSaver(directory=str(tmp_path / "from2"))
            saver2.save(runner_n)
            want2 = {k: np.asarray(v)
                     for k, v in runner_n.gather_params().items()}

    # data-parallel math is device-count-invariant (global-batch mean), so
    # the two scale-down continuations must agree closely
    np.testing.assert_allclose(down_losses[4], down_losses[2], rtol=1e-5)

    # scale-UP: the 2-device checkpoint restores bit-exact on 8 devices
    autodist_tpu.reset()
    ad8b = autodist_tpu.AutoDist(strategy_builder=make())
    runner8b = ad8b.build(loss_fn, opt, params, batch)
    runner8b.init(params)
    state, step = saver2.restore(runner8b)
    assert step == 5
    got = {k: np.asarray(v) for k, v in runner8b.gather_params().items()}
    for k in want2:
        np.testing.assert_array_equal(got[k], want2[k],
                                      err_msg="2->8 %s" % k)


def test_sharded_flex_refuses_unknown_axis(tmp_path):
    """Cross-topology restore still refuses what it cannot do: a leaf
    sharded over a mesh axis the running mesh does not have."""
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    runner.run(batch)
    saver = ShardedSaver(directory=str(tmp_path))
    base = saver.save(runner)
    import json
    meta = json.load(open(base + ".shard-meta.json"))
    meta["mesh"]["shape"] = [4]  # force the flex path
    meta["leaves"]["P|emb"]["spec"] = ["model"]
    json.dump(meta, open(base + ".shard-meta.json", "w"))
    with pytest.raises(ValueError, match="absent from the running mesh"):
        saver.restore(runner)


def test_sharded_commit_rejects_stale_index(tmp_path):
    """A crashed earlier attempt's index file (nonce not matching the
    npz) must never satisfy the commit barrier — the chief times out
    instead of committing a torn checkpoint."""
    import json
    from autodist_tpu.checkpoint.sharded import (ShardedSaver,
                                                 _StreamingNpzWriter)
    base = str(tmp_path / "ckpt-7")
    # fresh npz with nonce A ...
    w = _StreamingNpzWriter(base + ".shard-p1.npz")
    w.write("__nonce__", np.frombuffer(b"nonce-A", np.uint8))
    w.write("P|w|0:4,0:2", np.zeros((4, 2), np.float32))
    w.close()
    # ... but a stale index with nonce B (earlier attempt, pre-crash)
    with open(base + ".shard-p1.index.json", "w") as f:
        json.dump({"pid": 1, "nonce": "nonce-B",
                   "keys": ["P|w|0:4,0:2"]}, f)
    saver = ShardedSaver(directory=str(tmp_path), barrier_timeout=0.5)
    with pytest.raises(TimeoutError, match="never wrote their index"):
        saver._await_indexes(base, 2)
    # repair the index with the matching nonce: commit proceeds
    with open(base + ".shard-p1.index.json", "w") as f:
        json.dump({"pid": 1, "nonce": "nonce-A",
                   "keys": ["P|w|0:4,0:2"]}, f)
    with open(base + ".shard-p0.index.json", "w") as f:
        json.dump({"pid": 0, "nonce": "nonce-C", "keys": []}, f)
    w = _StreamingNpzWriter(base + ".shard-p0.npz")
    w.write("__nonce__", np.frombuffer(b"nonce-C", np.uint8))
    w.close()
    assert saver._await_indexes(base, 2) == {"P|w|0:4,0:2": 1}


def test_fit_save_every(tmp_path, monkeypatch):
    """fit(save_every=N) checkpoints every N steps plus a final partial
    window, through an async saver on ADT_CKPT_DIR — the periodic save
    sync-elastic recovery resumes from."""
    monkeypatch.setenv("ADT_CKPT_DIR", str(tmp_path))
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.05), params, batch)
    runner.init(params)
    history = runner.fit([batch] * 7, save_every=3)
    assert len(history) == 7
    saver = Saver(directory=str(tmp_path))
    steps = [s for s, _ in saver._own_metas()]
    assert steps == [3, 6, 7], steps  # two windows + the final partial
    state, step = saver.restore(runner)
    assert step == 7


def test_sharded_roundtrip_tensor_parallel(tmp_path):
    """Model-parallel (mp_axes) layouts ride the sharded format: each
    device's TP shard is its own slice key, restore reassembles the
    sharded storage, and training resumes bit-exact."""
    from autodist_tpu.checkpoint import ShardedSaver
    from autodist_tpu.models import tp_lm
    cfg = tp_lm.TPLMConfig.tiny()
    loss_fn, params, batch, _ = tp_lm.make_train_setup(cfg, seq_len=16,
                                                       batch_size=8)
    ad = autodist_tpu.AutoDist(strategy_builder=S.TensorParallel(
        tp_shards=2, mp_rules=tp_lm.tp_rules()))
    runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
    assert any(l.mp_axes for l in runner.distributed_step.layouts.values())
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = ShardedSaver(directory=str(tmp_path))
    base = saver.save(runner)
    flat = np.load(base + ".shard-p0.npz")
    # a TP-sharded var (wq sharded on its head dim) stores per-slice keys
    wq_keys = [k for k in flat.files if k.startswith("P|") and "/wq|" in k]
    assert len(wq_keys) >= 2, flat.files[:20]

    for _ in range(2):
        runner.run(batch)
    final_a = runner.gather_params()
    saver.restore(runner)
    for _ in range(2):
        runner.run(batch)
    final_b = runner.gather_params()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        final_a, final_b)


def test_sharded_flex_restore_resets_compressor_state(tmp_path):
    """Per-device compressor state (EF residuals, leading device axis
    sized by the SAVE topology) cannot be re-sliced across device counts
    — a cross-topology restore resets it to fresh init (a safe error-
    feedback restart) while params/opt restore bit-exact, and training
    continues in BOTH directions (8 -> 4 and 4 -> 8, where naive
    re-slicing would crash on the uneven leading dim)."""
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    make = lambda: S.AllReduce(compressor="HorovodCompressorEF")  # noqa: E731
    ad8 = autodist_tpu.AutoDist(strategy_builder=make())
    runner8 = ad8.build(loss_fn, optax.sgd(0.05), params, batch)
    runner8.init(params)
    for _ in range(3):
        runner8.run(batch)
    want = {k: np.asarray(v) for k, v in runner8.gather_params().items()}
    saver = ShardedSaver(directory=str(tmp_path))
    saver.save(runner8)

    autodist_tpu.reset()
    ad4 = autodist_tpu.AutoDist(resource_spec=_cpu_spec(4),
                                strategy_builder=make())
    runner4 = ad4.build(loss_fn, optax.sgd(0.05), params, batch)
    runner4.init(params)
    _, step = saver.restore(runner4)
    assert step == 3
    got = {k: np.asarray(v) for k, v in runner4.gather_params().items()}
    for k in want:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)
    # residuals reset: sync state equals a fresh init, and training runs
    fresh = runner4.distributed_step._sync_state_init()
    restored = runner4.distributed_step.gather_sync_state(runner4.state)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        fresh, restored)
    assert np.isfinite(runner4.run(batch)["loss"])
    for _ in range(2):
        runner4.run(batch)
    saver2 = ShardedSaver(directory=str(tmp_path / "up"))
    saver2.save(runner4)

    # scale UP 4 -> 8: the leading device axis would not even divide
    autodist_tpu.reset()
    ad8b = autodist_tpu.AutoDist(strategy_builder=make())
    runner8b = ad8b.build(loss_fn, optax.sgd(0.05), params, batch)
    runner8b.init(params)
    _, step = saver2.restore(runner8b)
    assert step == 6
    assert np.isfinite(runner8b.run(batch)["loss"])
    autodist_tpu.reset()


def test_fit_save_every_with_sharded_saver(tmp_path):
    """Runner.fit(save_every=N, saver=ShardedSaver) commits sharded
    checkpoints on the training loop (same call contract as Saver), and
    auto-resume machinery can read them back."""
    from autodist_tpu.checkpoint import ShardedSaver, latest_checkpoint
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.sgd(0.05), params, batch)
    runner.init(params)
    saver = ShardedSaver(directory=str(tmp_path), async_save=True)
    history = runner.fit(iter([batch] * 5), save_every=2, saver=saver)
    assert len(history) == 5
    step, found = latest_checkpoint(str(tmp_path))
    assert isinstance(found, ShardedSaver) and step == 5
    state, got_step = found.restore(runner)
    assert got_step == 5


def test_flex_ps_provider_copies_shape_coincident_leaves(tmp_path):
    """A shard-invariant optimizer leaf whose one extent coincides with
    the saved shard size (e.g. per-column stats of shape (8,) on (8, 8)
    value shards) must be COPIED on a cross-layout restore, not
    re-sliced — classification is full shape equality with the shard's
    value, not an axis-extent coincidence."""
    from autodist_tpu.checkpoint import ShardedSaver
    from autodist_tpu.checkpoint.sharded import _group_keys
    from autodist_tpu.parallel.ps import PSVarPlan

    colstats = np.arange(8).astype(np.float32)  # (8,) == shard rows
    data = {
        "H|emb::0": np.arange(64).reshape(8, 8).astype(np.float32),
        "H|emb::1": (np.arange(64) + 64).reshape(8, 8).astype(np.float32),
        "Ho|emb::0|0/colstats/v": colstats,
        "Ho|emb::1|0/colstats/v": colstats,
        "Ho|emb::0|0/mu/v": np.zeros((8, 8), np.float32),
        "Ho|emb::1|0/mu/v": np.ones((8, 8), np.float32),
    }
    meta = {"ps": {"emb": {"axis": 0, "nshards": 2, "shard_sizes": [8, 8]}},
            "keys": {k: 0 for k in data}}

    class _Store:
        plans = {"emb": PSVarPlan(var_name="emb",
                                  destinations=("h",) * 4,
                                  shard_sizes=(4, 4, 4, 4))}

    saver = ShardedSaver(directory=str(tmp_path))
    provider = saver._flex_ps_provider(meta, data.__getitem__,
                                       _group_keys(meta), _Store())
    # new shard 1 covers saved rows 4:8 of saved shard 0
    value, opt = provider("emb", 1)
    np.testing.assert_array_equal(value, data["H|emb::0"][4:8])
    # var-shaped leaf re-slices with the value...
    np.testing.assert_array_equal(opt["0/mu/v"], np.zeros((4, 8)))
    # ...the coincidence leaf is copied whole (a slice would read (4,))
    np.testing.assert_array_equal(opt["0/colstats/v"], colstats)
