"""Checkpoint tests.

Mirrors reference ``tests/checkpoint/test_partitionedPS_saver.py``: train
under PartitionedPS, save, then reload and continue training in *vanilla*
JAX/optax (no framework objects), asserting loss continuity; plus
framework-side resume and the SavedModel-style export
(``tests/checkpoint/test_saved_model.py``).
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.checkpoint.saved_model_builder import SavedModelBuilder


def _problem():
    rng = np.random.RandomState(1)
    params = {"emb": jnp.asarray(rng.randn(16, 4).astype(np.float32)),
              "w": jnp.asarray(rng.randn(4, 2).astype(np.float32))}

    def loss_fn(p, batch):
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        pred = feat @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, 16, (16,)).astype(np.int32),
             "y": rng.randn(16, 2).astype(np.float32)}
    return params, loss_fn, batch


def test_partitioned_save_restores_in_vanilla_jax(tmp_path):
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedPS())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        m = runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    path = saver.save(runner)
    assert path is not None

    # --- vanilla continuation: numpy.load only, no framework objects
    flat = dict(np.load(path + ".params.npz"))
    assert set(flat) == {"emb", "w"}
    assert flat["emb"].shape == (16, 4)  # original, unpadded layout
    vanilla_params = {"emb": jnp.asarray(flat["emb"]), "w": jnp.asarray(flat["w"])}
    vp_loss_before = float(loss_fn(vanilla_params, batch))
    # continuity: step metrics report the PRE-update loss, so the saved
    # (post-step-3) params must reproduce step 4's reported loss exactly
    m4 = runner.run(batch)
    assert abs(vp_loss_before - m4["loss"]) < 1e-4

    vopt_state = opt.init(vanilla_params)
    g = jax.grad(loss_fn)(vanilla_params, batch)
    updates, vopt_state = opt.update(g, vopt_state, vanilla_params)
    vanilla_params = optax.apply_updates(vanilla_params, updates)
    assert float(loss_fn(vanilla_params, batch)) < vp_loss_before * 1.2


def test_framework_resume_bitexact(tmp_path):
    """Save at step 3, keep training to 5; restore at 3 and retrain to 5:
    identical params (optimizer state round-trips exactly)."""
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    saver.save(runner)
    for _ in range(2):
        runner.run(batch)
    final_a = runner.gather_params()

    state, step = saver.restore(runner)
    assert step == 3
    for _ in range(2):
        runner.run(batch)
    final_b = runner.gather_params()
    for k in final_a:
        np.testing.assert_allclose(np.asarray(final_a[k]), np.asarray(final_b[k]),
                                   rtol=1e-6, atol=1e-6)


def test_resume_with_compressor_state_bitexact(tmp_path):
    """Error-feedback residuals must round-trip through checkpoints."""
    params, loss_fn, batch = _problem()
    opt = optax.sgd(0.05)
    ad = autodist_tpu.AutoDist(
        strategy_builder=S.AllReduce(compressor="HorovodCompressorEF"))
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    path = saver.save(runner)
    import os
    assert os.path.exists(path + ".sync.npz")
    for _ in range(2):
        runner.run(batch)
    final_a = runner.gather_params()

    saver.restore(runner, path)
    for _ in range(2):
        runner.run(batch)
    final_b = runner.gather_params()
    for k in final_a:
        np.testing.assert_allclose(np.asarray(final_a[k]), np.asarray(final_b[k]),
                                   rtol=1e-6, atol=1e-6)


def test_gc_ignores_foreign_files(tmp_path):
    (tmp_path / "best-model.meta.json").write_text("{}")
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    runner.run(batch)
    saver = Saver(directory=str(tmp_path), max_to_keep=1)
    assert saver.save(runner) is not None  # must not crash on the foreign file
    assert (tmp_path / "best-model.meta.json").exists()


def test_max_to_keep(tmp_path):
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    saver = Saver(directory=str(tmp_path), max_to_keep=2)
    for i in range(4):
        runner.run(batch)
        saver.save(runner)
    import os
    metas = [f for f in os.listdir(tmp_path) if f.endswith(".meta.json")]
    assert len(metas) == 2
    assert saver.latest().endswith("ckpt-4")


def test_saved_model_export(tmp_path):
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.Parallax())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    runner.run(batch)
    out = SavedModelBuilder(str(tmp_path / "export")).save(runner)
    import json, os
    spec = json.load(open(os.path.join(out, "model_spec.json")))
    assert spec["optimizer_name"] == "sgd"
    flat = dict(np.load(os.path.join(out, "params.npz")))
    assert flat["emb"].shape == (16, 4)


def test_async_save_equivalent_and_overlapping(tmp_path):
    """async_save writes the same bytes as sync save; training continues
    while the write is in flight; latest()/restore join the writer."""
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)

    sync_saver = Saver(directory=str(tmp_path / "sync"))
    sync_saver.save(runner)
    async_saver = Saver(directory=str(tmp_path / "async"), async_save=True)
    async_saver.save(runner)
    runner.run(batch)  # train while the write may still be in flight

    a, b = sync_saver.latest(), async_saver.latest()  # latest() joins writer
    assert a is not None and b is not None
    fa, fb = dict(np.load(a + ".params.npz")), dict(np.load(b + ".params.npz"))
    assert sorted(fa) == sorted(fb)
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k])

    # restore from the async checkpoint resumes at the saved step
    state, step = async_saver.restore(runner)
    assert step == 3
    # back-to-back async saves serialize (at most one writer in flight)
    async_saver.save(runner, step=100)
    async_saver.save(runner, step=101)
    async_saver.wait()
    steps = [s for s, _ in async_saver._own_metas()]
    assert 100 in steps and 101 in steps


# ---------------------------------------------------------------- sharded


def _shard_files(d):
    import os
    return sorted(f for f in os.listdir(d) if ".shard-p" in f and
                  f.endswith(".npz"))


def test_sharded_roundtrip_bitexact(tmp_path):
    """Sharded save at step 3 -> restore -> retrain == uninterrupted run,
    with per-slice keys (not whole tensors) in the shard file."""
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = ShardedSaver(directory=str(tmp_path))
    base = saver.save(runner)
    assert base is not None
    # the partitioned var is stored as per-device slices
    flat = np.load(base + ".shard-p0.npz")
    emb_keys = [k for k in flat.files if k.startswith("P|emb|")]
    assert len(emb_keys) == 8  # one slice per device of the 8-way mesh
    got = {k: flat[k].shape for k in emb_keys}
    assert all(s[0] == 2 for s in got.values()), got  # 16/8 rows each

    for _ in range(2):
        runner.run(batch)
    final_a = runner.gather_params()

    state, step = saver.restore(runner)
    assert step == 3
    for _ in range(2):
        runner.run(batch)
    final_b = runner.gather_params()
    for k in final_a:
        np.testing.assert_array_equal(np.asarray(final_a[k]),
                                      np.asarray(final_b[k]))


def test_sharded_host_ps_roundtrip(tmp_path):
    """Host-resident PS vars (values + per-shard optimizer state) ride the
    sharded format and resume bit-exact."""
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedPS())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    assert runner.distributed_step.ps_store is not None
    for _ in range(3):
        runner.run(batch)
    saver = ShardedSaver(directory=str(tmp_path))
    base = saver.save(runner)
    flat = np.load(base + ".shard-p0.npz")
    assert any(k.startswith("H|") for k in flat.files)
    assert any(k.startswith("Ho|") for k in flat.files)

    for _ in range(2):
        runner.run(batch)
    final_a = runner.gather_params()

    saver.restore(runner)
    for _ in range(2):
        runner.run(batch)
    final_b = runner.gather_params()
    for k in final_a:
        np.testing.assert_array_equal(np.asarray(final_a[k]),
                                      np.asarray(final_b[k]))


def test_sharded_export_matches_plain_saver(tmp_path):
    """export_full() produces a byte-identical Saver-format checkpoint —
    the vanilla-reload property survives as an export."""
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(
        strategy_builder=S.AllReduce(compressor="HorovodCompressorEF"))
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    plain = Saver(directory=str(tmp_path / "plain"))
    ppath = plain.save(runner)
    sharded = ShardedSaver(directory=str(tmp_path / "sharded"))
    sharded.save(runner)
    epath = sharded.export_full(out_dir=str(tmp_path / "export"))

    for suffix in (".params.npz", ".opt.npz", ".sync.npz"):
        a = dict(np.load(ppath + suffix))
        b = dict(np.load(epath + suffix))
        assert sorted(a) == sorted(b), (suffix, sorted(a), sorted(b))
        for k in a:
            np.testing.assert_array_equal(a[k], b[k], err_msg="%s %s"
                                          % (suffix, k))

    # the exported checkpoint restores through the plain Saver
    restorer = Saver(directory=str(tmp_path / "export"))
    state, step = restorer.restore(runner)
    assert step == 3


def test_sharded_export_ps_matches_plain_saver(tmp_path):
    """Same export equivalence for the host-PS (partitioned, no-proxy)
    path: values from store shards, optimizer slots reassembled."""
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    opt = optax.adam(0.05)
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedPS())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    plain = Saver(directory=str(tmp_path / "plain"))
    ppath = plain.save(runner)
    sharded = ShardedSaver(directory=str(tmp_path / "sharded"))
    sharded.save(runner)
    epath = sharded.export_full(out_dir=str(tmp_path / "export"))
    for suffix in (".params.npz", ".opt.npz"):
        a = dict(np.load(ppath + suffix))
        b = dict(np.load(epath + suffix))
        assert sorted(a) == sorted(b), (suffix, sorted(a), sorted(b))
        for k in a:
            np.testing.assert_array_equal(a[k], b[k], err_msg="%s %s"
                                          % (suffix, k))


def test_sharded_max_to_keep_and_async(tmp_path):
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    saver = ShardedSaver(directory=str(tmp_path), max_to_keep=2,
                         async_save=True)
    for _ in range(4):
        runner.run(batch)
        saver.save(runner)
    saver.wait()
    import os
    metas = [f for f in os.listdir(tmp_path) if f.endswith("shard-meta.json")]
    assert len(metas) == 2
    assert saver.latest().endswith("ckpt-4")
    # evicted steps' shard files are gone too
    assert not any(f.startswith("ckpt-1.") or f.startswith("ckpt-2.")
                   for f in os.listdir(tmp_path))
    state, step = saver.restore(runner)
    assert step == 4


def _ragged_problem():
    """Split dim 18 is NOT divisible by 8/4/2 the same way, so every mesh
    size pads differently (8-way -> 24, 4-way -> 20, 2-way -> 18): the
    cross-topology restore must re-pad, not just re-slice."""
    rng = np.random.RandomState(7)
    params = {"emb": jnp.asarray(rng.randn(18, 4).astype(np.float32)),
              "w": jnp.asarray(rng.randn(4, 2).astype(np.float32))}

    def loss_fn(p, batch):
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        pred = feat @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, 18, (16,)).astype(np.int32),
             "y": rng.randn(16, 2).astype(np.float32)}
    return params, loss_fn, batch


def _cpu_spec(n):
    from autodist_tpu.resource_spec import ResourceSpec
    return ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True,
                    "cpus": list(range(n))}]})


@pytest.mark.parametrize("builder", ["PartitionedAR", "PartitionedPS"])
def test_sharded_restore_across_topologies(tmp_path, builder):
    """VERDICT-r4 #1: save on an 8-device mesh, restore BIT-EXACT on 4 and
    on 2 (different padding each time), then scale back up 2 -> 8 — slices
    reassembled from the global ranges in the npz keys, the reference's
    topology-independent SaveSliceInfo property
    (reference ``autodist/kernel/partitioner.py:292-347``)."""
    from autodist_tpu.checkpoint import ShardedSaver
    make = lambda: getattr(S, builder)()  # noqa: E731
    params, loss_fn, batch = _ragged_problem()
    opt = optax.adam(0.05)
    ad8 = autodist_tpu.AutoDist(strategy_builder=make())
    runner8 = ad8.build(loss_fn, opt, params, batch)
    runner8.init(params)
    for _ in range(3):
        runner8.run(batch)
    want = {k: np.asarray(v) for k, v in runner8.gather_params().items()}
    saver = ShardedSaver(directory=str(tmp_path))
    saver.save(runner8)

    down_losses = {}
    for n in (4, 2):
        autodist_tpu.reset()
        ad_n = autodist_tpu.AutoDist(resource_spec=_cpu_spec(n),
                                     strategy_builder=make())
        runner_n = ad_n.build(loss_fn, opt, params, batch)
        runner_n.init(params)
        state, step = saver.restore(runner_n)
        assert step == 3
        got = {k: np.asarray(v) for k, v in runner_n.gather_params().items()}
        assert sorted(got) == sorted(want)
        for k in want:
            np.testing.assert_array_equal(got[k], want[k],
                                          err_msg="8->%d %s" % (n, k))
        # training continues: the restored optimizer state is live too
        down_losses[n] = [float(runner_n.run(batch)["loss"])
                          for _ in range(2)]
        if n == 2:
            saver2 = ShardedSaver(directory=str(tmp_path / "from2"))
            saver2.save(runner_n)
            want2 = {k: np.asarray(v)
                     for k, v in runner_n.gather_params().items()}

    # data-parallel math is device-count-invariant (global-batch mean), so
    # the two scale-down continuations must agree closely
    np.testing.assert_allclose(down_losses[4], down_losses[2], rtol=1e-5)

    # scale-UP: the 2-device checkpoint restores bit-exact on 8 devices
    autodist_tpu.reset()
    ad8b = autodist_tpu.AutoDist(strategy_builder=make())
    runner8b = ad8b.build(loss_fn, opt, params, batch)
    runner8b.init(params)
    state, step = saver2.restore(runner8b)
    assert step == 5
    got = {k: np.asarray(v) for k, v in runner8b.gather_params().items()}
    for k in want2:
        np.testing.assert_array_equal(got[k], want2[k],
                                      err_msg="2->8 %s" % k)


def test_sharded_flex_refuses_unknown_axis(tmp_path):
    """Cross-topology restore still refuses what it cannot do: a leaf
    sharded over a mesh axis the running mesh does not have."""
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
    runner.init(params)
    runner.run(batch)
    saver = ShardedSaver(directory=str(tmp_path))
    base = saver.save(runner)
    import json
    meta = json.load(open(base + ".shard-meta.json"))
    meta["mesh"]["shape"] = [4]  # force the flex path
    meta["leaves"]["P|emb"]["spec"] = ["model"]
    json.dump(meta, open(base + ".shard-meta.json", "w"))
    with pytest.raises(ValueError, match="absent from the running mesh"):
        saver.restore(runner)


def test_sharded_commit_rejects_stale_index(tmp_path):
    """A crashed earlier attempt's index file (nonce not matching the
    npz) must never satisfy the commit barrier — the chief times out
    instead of committing a torn checkpoint."""
    import json
    from autodist_tpu.checkpoint.sharded import (ShardedSaver,
                                                 _StreamingNpzWriter)
    base = str(tmp_path / "ckpt-7")
    # fresh npz with nonce A ...
    w = _StreamingNpzWriter(base + ".shard-p1.npz")
    w.write("__nonce__", np.frombuffer(b"nonce-A", np.uint8))
    w.write("P|w|0:4,0:2", np.zeros((4, 2), np.float32))
    w.close()
    # ... but a stale index with nonce B (earlier attempt, pre-crash)
    with open(base + ".shard-p1.index.json", "w") as f:
        json.dump({"pid": 1, "nonce": "nonce-B",
                   "keys": ["P|w|0:4,0:2"]}, f)
    saver = ShardedSaver(directory=str(tmp_path), barrier_timeout=0.5)
    # the timeout NAMES the laggards: which pid is missing its index file
    # outright, and which has a stale (nonce-mismatched) pairing
    with pytest.raises(TimeoutError) as ei:
        saver._await_indexes(base, 2)
    msg = str(ei.value)
    assert "never wrote a valid index" in msg
    assert "p0: index file ckpt-7.shard-p0.index.json not written" in msg
    assert "p1: index" in msg and "nonce mismatch" in msg
    # repair the index with the matching nonce: commit proceeds
    with open(base + ".shard-p1.index.json", "w") as f:
        json.dump({"pid": 1, "nonce": "nonce-A",
                   "keys": ["P|w|0:4,0:2"]}, f)
    with open(base + ".shard-p0.index.json", "w") as f:
        json.dump({"pid": 0, "nonce": "nonce-C", "keys": []}, f)
    w = _StreamingNpzWriter(base + ".shard-p0.npz")
    w.write("__nonce__", np.frombuffer(b"nonce-C", np.uint8))
    w.close()
    assert saver._await_indexes(base, 2) == {"P|w|0:4,0:2": 1}


def test_fit_save_every(tmp_path, monkeypatch):
    """fit(save_every=N) checkpoints every N steps plus a final partial
    window, through an async saver on ADT_CKPT_DIR — the periodic save
    sync-elastic recovery resumes from."""
    monkeypatch.setenv("ADT_CKPT_DIR", str(tmp_path))
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.05), params, batch)
    runner.init(params)
    history = runner.fit([batch] * 7, save_every=3)
    assert len(history) == 7
    saver = Saver(directory=str(tmp_path))
    steps = [s for s, _ in saver._own_metas()]
    assert steps == [3, 6, 7], steps  # two windows + the final partial
    state, step = saver.restore(runner)
    assert step == 7


def test_sharded_roundtrip_tensor_parallel(tmp_path):
    """Model-parallel (mp_axes) layouts ride the sharded format: each
    device's TP shard is its own slice key, restore reassembles the
    sharded storage, and training resumes bit-exact."""
    from autodist_tpu.checkpoint import ShardedSaver
    from autodist_tpu.models import tp_lm
    cfg = tp_lm.TPLMConfig.tiny()
    loss_fn, params, batch, _ = tp_lm.make_train_setup(cfg, seq_len=16,
                                                       batch_size=8)
    ad = autodist_tpu.AutoDist(strategy_builder=S.TensorParallel(
        tp_shards=2, mp_rules=tp_lm.tp_rules()))
    runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
    assert any(l.mp_axes for l in runner.distributed_step.layouts.values())
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = ShardedSaver(directory=str(tmp_path))
    base = saver.save(runner)
    flat = np.load(base + ".shard-p0.npz")
    # a TP-sharded var (wq sharded on its head dim) stores per-slice keys
    wq_keys = [k for k in flat.files if k.startswith("P|") and "/wq|" in k]
    assert len(wq_keys) >= 2, flat.files[:20]

    for _ in range(2):
        runner.run(batch)
    final_a = runner.gather_params()
    saver.restore(runner)
    for _ in range(2):
        runner.run(batch)
    final_b = runner.gather_params()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        final_a, final_b)


def test_sharded_flex_restore_resets_compressor_state(tmp_path):
    """Per-device compressor state (EF residuals, leading device axis
    sized by the SAVE topology) cannot be re-sliced across device counts
    — a cross-topology restore resets it to fresh init (a safe error-
    feedback restart) while params/opt restore bit-exact, and training
    continues in BOTH directions (8 -> 4 and 4 -> 8, where naive
    re-slicing would crash on the uneven leading dim)."""
    from autodist_tpu.checkpoint import ShardedSaver
    params, loss_fn, batch = _problem()
    make = lambda: S.AllReduce(compressor="HorovodCompressorEF")  # noqa: E731
    ad8 = autodist_tpu.AutoDist(strategy_builder=make())
    runner8 = ad8.build(loss_fn, optax.sgd(0.05), params, batch)
    runner8.init(params)
    for _ in range(3):
        runner8.run(batch)
    want = {k: np.asarray(v) for k, v in runner8.gather_params().items()}
    saver = ShardedSaver(directory=str(tmp_path))
    saver.save(runner8)

    autodist_tpu.reset()
    ad4 = autodist_tpu.AutoDist(resource_spec=_cpu_spec(4),
                                strategy_builder=make())
    runner4 = ad4.build(loss_fn, optax.sgd(0.05), params, batch)
    runner4.init(params)
    _, step = saver.restore(runner4)
    assert step == 3
    got = {k: np.asarray(v) for k, v in runner4.gather_params().items()}
    for k in want:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)
    # residuals reset: sync state equals a fresh init, and training runs
    fresh = runner4.distributed_step._sync_state_init()
    restored = runner4.distributed_step.gather_sync_state(runner4.state)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        fresh, restored)
    assert np.isfinite(runner4.run(batch)["loss"])
    for _ in range(2):
        runner4.run(batch)
    saver2 = ShardedSaver(directory=str(tmp_path / "up"))
    saver2.save(runner4)

    # scale UP 4 -> 8: the leading device axis would not even divide
    autodist_tpu.reset()
    ad8b = autodist_tpu.AutoDist(strategy_builder=make())
    runner8b = ad8b.build(loss_fn, optax.sgd(0.05), params, batch)
    runner8b.init(params)
    _, step = saver2.restore(runner8b)
    assert step == 6
    assert np.isfinite(runner8b.run(batch)["loss"])
    autodist_tpu.reset()


def test_fit_save_every_with_sharded_saver(tmp_path):
    """Runner.fit(save_every=N, saver=ShardedSaver) commits sharded
    checkpoints on the training loop (same call contract as Saver), and
    auto-resume machinery can read them back."""
    from autodist_tpu.checkpoint import ShardedSaver, latest_checkpoint
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.sgd(0.05), params, batch)
    runner.init(params)
    saver = ShardedSaver(directory=str(tmp_path), async_save=True)
    history = runner.fit(iter([batch] * 5), save_every=2, saver=saver)
    assert len(history) == 5
    step, found = latest_checkpoint(str(tmp_path))
    assert isinstance(found, ShardedSaver) and step == 5
    state, got_step = found.restore(runner)
    assert got_step == 5


def test_flex_ps_provider_copies_shape_coincident_leaves(tmp_path):
    """A shard-invariant optimizer leaf whose one extent coincides with
    the saved shard size (e.g. per-column stats of shape (8,) on (8, 8)
    value shards) must be COPIED on a cross-layout restore, not
    re-sliced — classification is full shape equality with the shard's
    value, not an axis-extent coincidence."""
    from autodist_tpu.checkpoint import ShardedSaver
    from autodist_tpu.checkpoint.sharded import _group_keys
    from autodist_tpu.parallel.ps import PSVarPlan

    colstats = np.arange(8).astype(np.float32)  # (8,) == shard rows
    data = {
        "H|emb::0": np.arange(64).reshape(8, 8).astype(np.float32),
        "H|emb::1": (np.arange(64) + 64).reshape(8, 8).astype(np.float32),
        "Ho|emb::0|0/colstats/v": colstats,
        "Ho|emb::1|0/colstats/v": colstats,
        "Ho|emb::0|0/mu/v": np.zeros((8, 8), np.float32),
        "Ho|emb::1|0/mu/v": np.ones((8, 8), np.float32),
    }
    meta = {"ps": {"emb": {"axis": 0, "nshards": 2, "shard_sizes": [8, 8]}},
            "keys": {k: 0 for k in data}}

    class _Store:
        plans = {"emb": PSVarPlan(var_name="emb",
                                  destinations=("h",) * 4,
                                  shard_sizes=(4, 4, 4, 4))}

    saver = ShardedSaver(directory=str(tmp_path))
    provider = saver._flex_ps_provider(meta, data.__getitem__,
                                       _group_keys(meta), _Store())
    # new shard 1 covers saved rows 4:8 of saved shard 0
    value, opt = provider("emb", 1)
    np.testing.assert_array_equal(value, data["H|emb::0"][4:8])
    # var-shaped leaf re-slices with the value...
    np.testing.assert_array_equal(opt["0/mu/v"], np.zeros((4, 8)))
    # ...the coincidence leaf is copied whole (a slice would read (4,))
    np.testing.assert_array_equal(opt["0/colstats/v"], colstats)


# ----------------------------------------------- durability & last-good


def _counters():
    from autodist_tpu.telemetry import spans as tel
    return tel.counters()


def test_saver_atomic_write_checksums_and_latency_hist(tmp_path):
    """Plain saves go through tmp + os.replace (no .tmp survivors, no
    torn finals), the meta records per-file crc32+bytes that deep fsck
    verifies, and the save-latency histogram observes the write."""
    import os
    from autodist_tpu.checkpoint import integrity
    from autodist_tpu.telemetry import spans as tel
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.adam(0.05), params, batch)
    runner.init(params)
    runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    path = saver.save(runner)
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]
    import json
    meta = json.load(open(path + ".meta.json"))
    assert set(meta["files"]) == {"ckpt-1.params.npz", "ckpt-1.opt.npz"}
    for fname, digest in meta["files"].items():
        assert digest["bytes"] == os.path.getsize(tmp_path / fname)
    status = integrity.validate_plain(str(tmp_path), 1, deep=True)
    assert status.committed and not status.problems, status.to_dict()
    hist = tel.histograms().get("ckpt.save_ms")
    assert hist is not None and hist["count"] >= 1


def test_plain_restore_falls_back_past_torn_and_corrupt(tmp_path):
    """Newest checkpoint truncated (torn write on a non-atomic fs),
    next-newest missing its meta (crash pre-commit): restore lands on the
    last GOOD one, counts the fallbacks, and an explicit path to the
    damaged one is refused."""
    import os
    from autodist_tpu.checkpoint import CheckpointDamaged
    from autodist_tpu.runtime.faultinject import truncate_file
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.adam(0.05), params, batch)
    runner.init(params)
    saver = Saver(directory=str(tmp_path))
    for _ in range(3):
        runner.run(batch)
        saver.save(runner)
    truncate_file(str(tmp_path / "ckpt-3.params.npz"), 100)
    os.remove(tmp_path / "ckpt-2.meta.json")
    c0 = _counters()
    state, step = saver.restore(runner)
    c1 = _counters()
    assert step == 1
    assert c1["ckpt.fallback"] - c0["ckpt.fallback"] >= 2
    assert c1["ckpt.corrupt_shards"] > c0["ckpt.corrupt_shards"]
    with pytest.raises(CheckpointDamaged, match="corrupt"):
        saver.restore(runner, str(tmp_path / "ckpt-3"))
    # latest() agrees: the damaged/torn steps are not "the latest"
    assert saver.latest().endswith("ckpt-1")


def test_restore_explicit_path_outside_saver_directory(tmp_path):
    """An explicit restore(path=...) is validated where the PATH lives,
    not in the saver's own directory — a valid checkpoint from another
    job's directory restores fine, a damaged one there is still refused,
    and a non-checkpoint path gets a clear error."""
    from autodist_tpu.checkpoint import CheckpointDamaged, ShardedSaver
    from autodist_tpu.checkpoint import integrity
    from autodist_tpu.runtime.faultinject import flip_bit
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.adam(0.05), params, batch)
    runner.init(params)
    runner.run(batch)
    theirs = tmp_path / "their-job"
    theirs.mkdir()
    for saver_cls in (Saver, ShardedSaver):
        src = saver_cls(directory=str(theirs / saver_cls.__name__))
        path = src.save(runner)
        mine = saver_cls(directory=str(tmp_path / "mine"))
        _, step = mine.restore(runner, path=path)  # validated at `path`
        assert step == 1
    # damage the foreign sharded checkpoint (mid-file: entry data):
    # still refused via the path
    flip_bit(str(theirs / "ShardedSaver" / "ckpt-1.shard-p0.npz"))
    with pytest.raises(CheckpointDamaged):
        ShardedSaver(directory=str(tmp_path / "mine")).restore(
            runner, path=str(theirs / "ShardedSaver" / "ckpt-1"))
    with pytest.raises(ValueError, match="ckpt-<step>"):
        integrity.parse_base(str(tmp_path / "not-a-checkpoint"))
    assert integrity.parse_base("ckpt-7") == (".", 7)


def test_sharded_restore_falls_back_on_truncated_shard(tmp_path):
    """Truncated shard npz in the newest sharded checkpoint: fast
    validation classifies it corrupt, restore falls back to the previous
    committed step, and an explicit path is refused."""
    from autodist_tpu.checkpoint import CheckpointDamaged, ShardedSaver
    from autodist_tpu.checkpoint import integrity
    from autodist_tpu.runtime.faultinject import truncate_file
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.adam(0.05), params, batch)
    runner.init(params)
    saver = ShardedSaver(directory=str(tmp_path))
    for _ in range(2):
        runner.run(batch)
        saver.save(runner)
    truncate_file(str(tmp_path / "ckpt-2.shard-p0.npz"), 200)
    assert integrity.validate_sharded(str(tmp_path), 2).state == "corrupt"
    c0 = _counters()
    state, step = saver.restore(runner)
    assert step == 1
    assert _counters()["ckpt.fallback"] - c0["ckpt.fallback"] >= 1
    with pytest.raises(CheckpointDamaged, match="corrupt"):
        saver.restore(runner, str(tmp_path / "ckpt-2"))
    assert saver.latest().endswith("ckpt-1")


def test_sharded_restore_falls_back_on_bitflip(tmp_path):
    """A single flipped bit in a committed shard file — invisible to
    structural checks — surfaces as a CRC failure while reading and the
    restore falls back to the previous committed checkpoint instead of
    loading silently-corrupted state."""
    from autodist_tpu.checkpoint import ShardedSaver
    from autodist_tpu.checkpoint import integrity
    from autodist_tpu.runtime.faultinject import flip_bit
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.adam(0.05), params, batch)
    runner.init(params)
    saver = ShardedSaver(directory=str(tmp_path))
    for _ in range(2):
        runner.run(batch)
        saver.save(runner)
    flip_bit(str(tmp_path / "ckpt-2.shard-p0.npz"), -5000)
    # deep fsck provably finds the damage even when fast checks pass
    deep = integrity.validate_sharded(str(tmp_path), 2, deep=True)
    assert deep.state == "corrupt", deep.to_dict()
    c0 = _counters()
    state, step = saver.restore(runner)
    assert step == 1
    c1 = _counters()
    assert c1["ckpt.fallback"] - c0["ckpt.fallback"] >= 1
    assert c1["ckpt.corrupt_shards"] - c0["ckpt.corrupt_shards"] >= 1


def test_gc_removes_failed_attempts(tmp_path):
    """Failed-attempt debris (meta-less shard files, .tmp leftovers) at
    steps below the newest commit is GC'd on the next successful save."""
    import os
    from autodist_tpu.checkpoint import ShardedSaver
    from autodist_tpu.checkpoint.sharded import _StreamingNpzWriter
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.adam(0.05), params, batch)
    runner.init(params)
    saver = ShardedSaver(directory=str(tmp_path))
    runner.run(batch)
    saver.save(runner)  # committed step 1
    # debris: a torn attempt at step 0 and a .tmp under committed step 1
    w = _StreamingNpzWriter(str(tmp_path / "ckpt-0.shard-p0.npz"))
    w.write("__nonce__", np.frombuffer(b"x", np.uint8))
    w.close()
    (tmp_path / "ckpt-1.shard-p0.npz.tmp").write_bytes(b"partial")
    c0 = _counters()
    runner.run(batch)
    saver.save(runner)  # committed step 2 -> gc sweeps the debris
    assert not os.path.exists(tmp_path / "ckpt-0.shard-p0.npz")
    assert not os.path.exists(tmp_path / "ckpt-1.shard-p0.npz.tmp")
    assert _counters()["ckpt.gc_orphans"] - c0["ckpt.gc_orphans"] >= 2
    # the committed checkpoints survived
    state, step = saver.restore(runner)
    assert step == 2


def test_checkpoint_cli_ls_fsck_gc(tmp_path, capsys):
    """The lifecycle CLI end to end: ls shows validity states, fsck
    exits 1 exactly when a committed checkpoint is damaged, gc --orphans
    clears failed attempts."""
    import json
    import os
    from autodist_tpu.checkpoint import ShardedSaver
    from autodist_tpu.checkpoint.cli import main
    from autodist_tpu.checkpoint.sharded import _StreamingNpzWriter
    from autodist_tpu.runtime.faultinject import flip_bit
    params, loss_fn, batch = _problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.adam(0.05), params, batch)
    runner.init(params)
    saver = ShardedSaver(directory=str(tmp_path))
    for _ in range(2):
        runner.run(batch)
        saver.save(runner)
    # a torn attempt newer than every commit (crash mid-save of step 9)
    w = _StreamingNpzWriter(str(tmp_path / "ckpt-9.shard-p0.npz"))
    w.write("__nonce__", np.frombuffer(b"x", np.uint8))
    w.close()

    assert main(["--dir", str(tmp_path), "ls", "--json"]) == 0
    rows = {r["step"]: r for r in json.loads(capsys.readouterr().out)}
    assert rows[1]["state"] == "committed"
    assert rows[2]["state"] == "committed"
    assert rows[9]["state"] == "torn"

    # clean directory (modulo the torn attempt): fsck passes...
    assert main(["--dir", str(tmp_path), "fsck"]) == 0
    # ...but --strict flags the torn attempt
    assert main(["--dir", str(tmp_path), "fsck", "--strict"]) == 1
    capsys.readouterr()

    # damage a committed checkpoint: fsck exits 1
    flip_bit(str(tmp_path / "ckpt-2.shard-p0.npz"), -5000)
    assert main(["--dir", str(tmp_path), "fsck"]) == 1
    out = capsys.readouterr().out
    assert "corrupt" in out

    # gc --orphans clears the torn attempt, keeps committed files
    assert main(["--dir", str(tmp_path), "gc", "--orphans"]) == 0
    capsys.readouterr()
    assert not os.path.exists(tmp_path / "ckpt-9.shard-p0.npz")
    assert os.path.exists(tmp_path / "ckpt-1.shard-meta.json")
    # gc --keep 1 drops the (damaged) step-2? No: --keep counts committed
    # checkpoints; step 2 is corrupt so step 1 is retained as the newest
    # committed. Bad usage is a usage error.
    assert main(["--dir", str(tmp_path), "gc"]) == 2

    # gc --damaged is the follow-up to the failing fsck: the corrupt
    # step-2 files go, the committed step-1 stays, and fsck passes again
    assert main(["--dir", str(tmp_path), "gc", "--damaged"]) == 0
    capsys.readouterr()
    assert not os.path.exists(tmp_path / "ckpt-2.shard-p0.npz")
    assert not os.path.exists(tmp_path / "ckpt-2.shard-meta.json")
    assert os.path.exists(tmp_path / "ckpt-1.shard-meta.json")
    assert main(["--dir", str(tmp_path), "fsck", "--strict"]) == 0
    capsys.readouterr()


def test_ckpt_fault_plan_kills_and_damage(tmp_path, monkeypatch):
    """CheckpointFaultPlan mechanics without a real SIGKILL: nth-phase
    kill matching, and file damage ops (truncate/bitflip) applied to
    matching targets."""
    import json
    from autodist_tpu.runtime import faultinject as fi

    kills = []
    monkeypatch.setattr(fi, "_kill_self", lambda: kills.append(True))
    plan = fi.CheckpointFaultPlan({
        "kills": [{"phase": "meta", "nth": 2}],
        "damage": [{"op": "truncate", "phase": "committed",
                    "file": "shard-p0.npz", "bytes": 10}],
    })
    target = tmp_path / "ckpt-4.shard-p0.npz"
    target.write_bytes(b"A" * 100)
    plan.fire("write", path=str(target))     # no rule for this phase
    plan.fire("meta")                        # nth=1 < 2: armed, no fire
    assert not kills
    plan.fire("meta")                        # nth=2: fires
    assert kills == [True]
    plan.fire("committed", path=str(tmp_path / "ckpt-4"))  # base expansion
    assert target.stat().st_size == 10
    assert plan.injected == ["kill:meta", "truncate:ckpt-4.shard-p0.npz"]

    # the env-driven hook: parsed once, re-parsed when the value changes
    plan_file = tmp_path / "plan.json"
    plan_file.write_text(json.dumps(
        {"damage": [{"op": "bitflip", "phase": "committed",
                     "file": "ckpt-4.shard-p0.npz", "offset": 0}]}))
    monkeypatch.setenv("ADT_CKPT_FAULT_PLAN", "@%s" % plan_file)
    before = target.read_bytes()
    fi.checkpoint_fault("committed", path=str(target))
    after = target.read_bytes()
    assert before[0] ^ after[0] == 0x01 and before[1:] == after[1:]

    # probabilistic rules roll against the plan-level seeded rng: prob=0
    # never fires (and stays armed — not silently consumed), prob=1 always
    plan = fi.CheckpointFaultPlan({
        "seed": 7,
        "damage": [{"op": "truncate", "phase": "committed",
                    "file": "shard-p0.npz", "prob": 0.0, "bytes": 1},
                   {"op": "bitflip", "phase": "committed",
                    "file": "shard-p0.npz", "prob": 1.0, "offset": 0}]})
    for _ in range(5):
        plan.fire("committed", path=str(target))
    assert target.stat().st_size == 10          # prob=0 never truncated
    assert len(plan.injected) == 1              # prob=1 fired exactly once
    assert not plan.rules[0]._spent             # still armed


def test_validation_and_read_error_hardening(tmp_path):
    """Three review-hardened edges: a legacy (no recorded file list) meta
    whose params file is gone is CORRUPT, not committed; a read-path
    failure surfaces as CheckpointDamaged (never a FileNotFoundError that
    Runner.init would misread as start-fresh); committed_newest_first is
    lazy — consuming only the newest entry validates only that step."""
    import json
    from autodist_tpu.checkpoint import integrity
    from autodist_tpu.checkpoint.saver import _read_npz

    # legacy meta, params npz missing -> corrupt (restore must not pick it)
    (tmp_path / "ckpt-3.meta.json").write_text(json.dumps({"step": 3}))
    (tmp_path / "ckpt-3.opt.npz").write_bytes(b"not-a-zip")
    status = integrity.validate_plain(str(tmp_path), 3)
    assert status.state == integrity.CORRUPT
    assert any("params.npz missing" in p for p in status.problems)

    with pytest.raises(integrity.CheckpointDamaged, match="unreadable"):
        _read_npz(str(tmp_path / "ckpt-3.params.npz"))  # vanished file
    with pytest.raises(integrity.CheckpointDamaged, match="unreadable"):
        _read_npz(str(tmp_path / "ckpt-3.opt.npz"))     # torn bytes

    gen = integrity.committed_newest_first(str(tmp_path), "plain")
    assert next(gen).step == 3  # lazy: a generator, newest first
    assert next(gen, None) is None


def test_parallax_host_ps_cross_topology_restore(tmp_path):
    """Satellite: host-PS strategies across topologies. Parallax routes
    the sparse embedding to the host-PS store and the dense var to
    compressed AllReduce — an 8->4 restore must re-slice the PS shards,
    restore params bit-exact, and reset the per-device compressor state
    to fresh init (the documented topology-bound-residuals rule), then
    keep training; 4->8 scales back up."""
    from autodist_tpu.checkpoint import ShardedSaver
    make = lambda: S.Parallax(compressor="HorovodCompressorEF")  # noqa: E731
    params, loss_fn, batch = _problem()
    ad8 = autodist_tpu.AutoDist(strategy_builder=make())
    runner8 = ad8.build(loss_fn, optax.adam(0.05), params, batch)
    assert runner8.distributed_step.ps_store is not None
    runner8.init(params)
    for _ in range(3):
        runner8.run(batch)
    want = {k: np.asarray(v) for k, v in runner8.gather_params().items()}
    saver = ShardedSaver(directory=str(tmp_path))
    base = saver.save(runner8)
    flat = np.load(base + ".shard-p0.npz")
    assert any(k.startswith("H|emb") for k in flat.files)  # PS rode along
    assert any(k.startswith("S|") for k in flat.files)     # EF residuals

    autodist_tpu.reset()
    ad4 = autodist_tpu.AutoDist(resource_spec=_cpu_spec(4),
                                strategy_builder=make())
    runner4 = ad4.build(loss_fn, optax.adam(0.05), params, batch)
    runner4.init(params)
    _, step = saver.restore(runner4)
    assert step == 3
    got = {k: np.asarray(v) for k, v in runner4.gather_params().items()}
    for k in want:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)
    # per-device compressor state reset to fresh init on the new mesh
    fresh = runner4.distributed_step._sync_state_init()
    restored = runner4.distributed_step.gather_sync_state(runner4.state)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        fresh, restored)
    assert np.isfinite(runner4.run(batch)["loss"])
    saver2 = ShardedSaver(directory=str(tmp_path / "up"))
    saver2.save(runner4)

    autodist_tpu.reset()
    ad8b = autodist_tpu.AutoDist(strategy_builder=make())
    runner8b = ad8b.build(loss_fn, optax.adam(0.05), params, batch)
    runner8b.init(params)
    _, step = saver2.restore(runner8b)
    assert step == 4
    assert np.isfinite(runner8b.run(batch)["loss"])
    autodist_tpu.reset()
