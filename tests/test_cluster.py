"""Cluster/Coordinator dry-run tests (ADT_DEBUG_REMOTE, the analog of the
reference's AUTODIST_DEBUG_REMOTE suppressed-SSH tests)."""
import os

import pytest

from autodist_tpu import const
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.runtime.cluster import SSHCluster


@pytest.fixture(autouse=True)
def _debug_remote():
    os.environ[const.ENV.ADT_DEBUG_REMOTE.name_str] = "1"
    yield
    os.environ.pop(const.ENV.ADT_DEBUG_REMOTE.name_str, None)


def _spec():
    return ResourceSpec.from_dict({
        "nodes": [
            {"address": "10.0.0.2", "tpus": 4},
            {"address": "10.0.0.1", "tpus": 4, "chief": True},
        ],
        "ssh": {"g": {"username": "u", "key_file": "/k"}},
    })


def test_deterministic_process_layout():
    c = SSHCluster(_spec())
    assert c.num_processes == 2
    assert c.process_addresses == ["10.0.0.1", "10.0.0.2"]  # chief first
    assert c.process_id("10.0.0.1") == 0
    assert c.coordinator_address == "10.0.0.1:%d" % const.DEFAULT_COORDINATOR_PORT


def test_worker_env():
    c = SSHCluster(_spec())
    env = c.worker_env("10.0.0.2")
    assert env["ADT_WORKER"] == "10.0.0.2"
    assert env["ADT_PROCESS_ID"] == "1"
    assert env["ADT_NUM_PROCESSES"] == "2"
    assert env["ADT_COORDINATOR_ADDR"] == c.coordinator_address


def test_remote_exec_dry_run():
    c = SSHCluster(_spec())
    assert c.remote_exec("echo hi", "10.0.0.2", env={"A": "1"}) is None
    assert c.remote_copy("/tmp/x", "/tmp/dir", "10.0.0.2") is True


def test_coordinator_launch_dry_run(tmp_path):
    from autodist_tpu.runtime.coordinator import Coordinator
    from autodist_tpu.strategy.base import Strategy
    s = Strategy()
    s.serialize()
    c = SSHCluster(_spec())
    coord = Coordinator(s, c)
    coord.launch_clients()  # dry-run: no processes spawned
    coord.join()
