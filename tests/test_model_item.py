"""ModelItem capture tests.

The key coverage mirror of reference ``tests/test_graph_item.py:54-84``: a
matrix of optimizer configs, asserting variable/optimizer metadata capture
finds every trainable variable; plus sparse (embedding) detection — the
analog of the reference recognizing sparse update ops.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.model_item import ModelItem
from autodist_tpu.kernel.common.variable_utils import match_state_to_var

OPTIMIZER_CASES = [
    ("sgd", lambda: optax.sgd(0.1)),
    ("sgd_momentum", lambda: optax.sgd(0.1, momentum=0.9)),
    ("sgd_nesterov", lambda: optax.sgd(0.1, momentum=0.9, nesterov=True)),
    ("adam", lambda: optax.adam(1e-3)),
    ("adamw", lambda: optax.adamw(1e-3)),
    ("adagrad", lambda: optax.adagrad(0.1)),
    ("adadelta", lambda: optax.adadelta(0.1)),
    ("rmsprop", lambda: optax.rmsprop(0.01)),
    ("rmsprop_momentum", lambda: optax.rmsprop(0.01, momentum=0.9)),
    ("rmsprop_centered", lambda: optax.rmsprop(0.01, centered=True)),
    ("lamb", lambda: optax.lamb(1e-3)),
    ("lion", lambda: optax.lion(1e-4)),
    ("nadam", lambda: optax.nadam(1e-3)),
    ("adafactor", lambda: optax.adafactor(1e-3)),
]


def _params():
    return {"dense": {"kernel": jnp.ones((4, 3)), "bias": jnp.zeros((3,))},
            "out": {"kernel": jnp.ones((3, 1))}}


def _loss(params, batch):
    x, y = batch["x"], batch["y"]
    h = jnp.tanh(x @ params["dense"]["kernel"] + params["dense"]["bias"])
    pred = h @ params["out"]["kernel"]
    return jnp.mean((pred - y) ** 2)


def _batch():
    return {"x": np.ones((8, 4), np.float32), "y": np.zeros((8, 1), np.float32)}


@pytest.mark.parametrize("name,make_opt", OPTIMIZER_CASES, ids=[c[0] for c in OPTIMIZER_CASES])
def test_optimizer_matrix(name, make_opt):
    """Every optimizer: capture succeeds, every trainable var is found, the
    optimizer ctor info is recorded, and every var-shaped optimizer state
    leaf maps back to its variable."""
    opt = make_opt()
    item = ModelItem(loss_fn=_loss, optimizer=opt, params=_params(),
                     example_batch=_batch()).prepare()
    assert sorted(item.trainable_var_names) == [
        "dense/bias", "dense/kernel", "out/kernel"]
    assert item.optimizer_name == name.split("_")[0]
    # grads pair 1:1 with vars
    loss, grads = item.grad_fn()(item.params, _batch())
    assert jax.tree_util.tree_structure(grads) == jax.tree_util.tree_structure(item.params)
    # opt state leaves match vars (adafactor factors states; skip its check)
    if name == "adafactor":
        return
    state = opt.init(item.params)
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    for path, leaf in flat:
        shape = getattr(leaf, "shape", ())
        if tuple(shape) in {(4, 3), (3,), (3, 1)}:
            from autodist_tpu.model_item import _normalize_path
            var = match_state_to_var(_normalize_path(path), shape, item.var_infos)
            assert var, "unmatched state leaf %s" % _normalize_path(path)


def test_sparse_detection():
    params = {"emb": {"table": jnp.ones((100, 8))},
              "out": {"kernel": jnp.ones((8, 1))}}

    def loss(p, batch):
        e = jnp.take(p["emb"]["table"], batch["ids"], axis=0)
        pred = jnp.sum(e, axis=1) @ p["out"]["kernel"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"ids": np.zeros((4, 5), np.int32), "y": np.zeros((4, 1), np.float32)}
    item = ModelItem(loss_fn=loss, optimizer=optax.sgd(0.1), params=params,
                     example_batch=batch).prepare()
    assert item.sparse_var_names == ["emb/table"]
    assert item.var_infos["out/kernel"].sparse is False


def test_var_info_byte_size():
    item = ModelItem(loss_fn=_loss, optimizer=optax.sgd(0.1), params=_params(),
                     example_batch=_batch()).prepare()
    assert item.var_infos["dense/kernel"].byte_size == 4 * 3 * 4
    assert item.total_bytes() == (12 + 3 + 3) * 4


def test_spec_serialization_round_trip():
    item = ModelItem(loss_fn=_loss, optimizer=optax.adam(1e-3), params=_params(),
                     example_batch=_batch()).prepare()
    spec = ModelItem.spec_from_bytes(item.serialize_spec())
    assert spec["optimizer_name"] == "adam"
    assert len(spec["vars"]) == 3
    assert spec["mode"] == "loss_fn"


def test_detect_sparse_vars_under_mesh_collectives():
    """A loss using mesh collectives (ring attention, Megatron psum) can't
    trace bare — detection retries under a size-1 axis environment and
    must still see THROUGH the shard_map wrapper to the gather inside
    (regression: the shard_map eqn stores a plain Jaxpr, not ClosedJaxpr)."""
    import jax
    import jax.numpy as jnp
    from autodist_tpu.model_item import detect_sparse_vars

    params = {"emb": jnp.ones((16, 4)), "w": jnp.ones((4, 2))}
    batch = {"ids": jnp.zeros((8,), jnp.int32),
             "y": jnp.zeros((8, 2))}

    def loss_fn(p, b):
        feat = jnp.take(p["emb"], b["ids"], axis=0)
        out = feat @ p["w"]
        # unbound outside a mesh: forces the axis-env retry path
        out = jax.lax.psum(out, "model")
        return jnp.mean((out - b["y"]) ** 2)

    assert detect_sparse_vars(loss_fn, params, batch) == {"emb"}


def test_gather_walker_sees_through_shard_map():
    """The gather walker must recurse into a shard_map eqn, whose body is
    a PLAIN Jaxpr (not ClosedJaxpr) — the sub-jaxpr extraction's second
    branch. Wrap the loss in an explicit jax.shard_map and assert the
    table is still detected."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from autodist_tpu.model_item import detect_sparse_vars

    params = {"emb": jnp.ones((16, 4)), "w": jnp.ones((4, 2))}
    batch = {"ids": jnp.zeros((8,), jnp.int32), "y": jnp.zeros((8, 2))}

    def loss_fn(p, b):
        feat = jnp.take(p["emb"], b["ids"], axis=0)
        return jnp.mean((feat @ p["w"] - b["y"]) ** 2)

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("model",))
    wrapped = jax.shard_map(loss_fn, mesh=mesh, in_specs=(P(), P()),
                            out_specs=P(), check_vma=False)
    # sanity: the wrapper really produces a shard_map eqn with a plain
    # Jaxpr body (the regression this test pins down)
    jaxpr = jax.make_jaxpr(wrapped)(params, batch).jaxpr
    sm = [e for e in jaxpr.eqns if e.primitive.name == "shard_map"]
    assert sm and not hasattr(sm[0].params["jaxpr"], "jaxpr")
    assert detect_sparse_vars(wrapped, params, batch) == {"emb"}
