"""DLRM — the large-embedding auto-strategy flagship (BASELINE target).

The giant uneven tables are the regime where strategy choice matters
most: AutoStrategy must route them off pure dense AllReduce, the sparse
wire must carry their gradients batch-sized, and training must converge
through whatever plan gets picked.
"""
import numpy as np
import jax.numpy as jnp
import optax
import pytest

import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.models import dlrm


def test_forward_and_interactions_shape():
    cfg = dlrm.DLRMConfig.tiny()
    loss_fn, params, batch, apply_fn = dlrm.make_train_setup(
        cfg, batch_size=16)
    logits = apply_fn(params, jnp.asarray(batch["dense"]),
                      jnp.asarray(batch["sparse"]))
    assert logits.shape == (16,)
    assert np.isfinite(float(loss_fn(params, batch)))


def test_bottom_mlp_dim_validated():
    with pytest.raises(ValueError, match="bottom_mlp"):
        dlrm.DLRMConfig.tiny(bottom_mlp=(16, 12))  # != embed_dim 8


def test_trains_under_auto_strategy_with_sparse_wire():
    """The BASELINE bullet end-to-end: AutoStrategy picks a plan, the
    tables ride the (ids, values) wire (batch << vocab), and the loss
    decreases."""
    cfg = dlrm.DLRMConfig.tiny(table_sizes=(4096, 2048, 512, 64),
                               embed_dim=32, bottom_mlp=(16, 32))
    loss_fn, params, batch, _ = dlrm.make_train_setup(cfg, batch_size=16)
    auto = strategy.AutoStrategy()
    ad = adt.AutoDist(strategy_builder=auto)
    runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
    runner.init(params)
    assert auto.last_ranking, "AutoStrategy did not rank"
    wire = set(runner.distributed_step.metadata["sparse_wire"])
    # the two big tables must not ship vocab-sized gradients
    assert {"params/table_0/embedding", "params/table_1/embedding"} <= wire, \
        (auto.last_ranking[0].label, wire)
    losses = [float(runner.run(batch)["loss"]) for _ in range(15)]
    assert losses[-1] < losses[0], losses
    adt.reset()


def test_hot_id_skew_in_synthetic_batch():
    """The synthetic ids reproduce CTR skew: most lookups land in the hot
    fraction of each vocabulary (what PS load balancing actually faces)."""
    cfg = dlrm.DLRMConfig.tiny(table_sizes=(10_000,), bottom_mlp=(16, 8))
    _, _, batch, _ = dlrm.make_train_setup(cfg, batch_size=512)
    hot = (batch["sparse"][:, 0] < 500).mean()
    assert hot > 0.7, hot


def test_wide_and_deep_variant():
    """wide=True adds the linear memorization term (1-dim per-table
    embeddings + dense linear, arXiv 1606.07792); the wide tables ride
    the sparse wire alongside the deep ones and training converges."""
    cfg = dlrm.DLRMConfig.tiny(table_sizes=(4096, 512), embed_dim=32,
                               bottom_mlp=(16, 32), wide=True)
    loss_fn, params, batch, _ = dlrm.make_train_setup(cfg, batch_size=16)
    assert "wide_table_0" in params["params"]
    assert "wide_dense" in params["params"]
    ad = adt.AutoDist(strategy_builder=strategy.Parallax(require_sparse=True))
    runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
    runner.init(params)
    wire = set(runner.distributed_step.metadata["sparse_wire"])
    assert "params/wide_table_0/embedding" in wire, wire
    losses = [float(runner.run(batch)["loss"]) for _ in range(10)]
    assert losses[-1] < losses[0], losses
    adt.reset()
