"""Checkpoint crash-resume chaos: SIGKILL mid-save + mesh-shrink resume.

The ROADMAP item-4 success criterion, end to end in real subprocesses:

1. Train on an 8-device host-platform mesh, sharded-checkpointing every
   2 steps; an ``ADT_CKPT_FAULT_PLAN`` kill rule delivers a REAL SIGKILL
   inside the 3rd save (phase ``meta``: shard + index files on disk, the
   commit meta not yet written) — the crash the atomic-write protocol
   exists for.
2. Assert the debris is classified ``torn`` (never half-visible), then
   injure a COMMITTED checkpoint (bit flip) to model storage rot on top
   of the crash; ``fsck`` must exit 1.
3. Restart the job on a **4-device** mesh with ``ADT_AUTO_RESUME``: it
   must fall back past the torn attempt AND the corrupt step to the last
   good checkpoint (counted in ``ckpt.fallback``), re-shard onto the
   smaller mesh, and finish training.
4. The resumed run's loss trajectory must match an uncrashed reference
   run (data-parallel step math is device-count-invariant).

Real processes, real SIGKILL, real files — marked slow+chaos; runs in
the nightly chaos workflow (fast fsck/fallback legs live in
tests/test_checkpoint.py and run per-PR).
"""
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
DRIVER = os.path.join(HERE, "ckpt_chaos_driver.py")

SPEC_8 = """
nodes:
  - address: 127.0.0.1
    chief: true
    cpus: [0, 1, 2, 3, 4, 5, 6, 7]
"""

SPEC_4 = """
nodes:
  - address: 127.0.0.1
    chief: true
    cpus: [0, 1, 2, 3]
"""

pytestmark = [pytest.mark.slow, pytest.mark.chaos]


def _run_driver(spec, out, builder, ckpt_dir, steps, devices, extra_env):
    env = dict(os.environ)
    for k in ("JAX_PLATFORMS", "ADT_WORKER", "ADT_CKPT_FAULT_PLAN",
              "ADT_AUTO_RESUME"):
        env.pop(k, None)
    env.update({
        "XLA_FLAGS": "--xla_force_host_platform_device_count=%d" % devices,
        "ADT_CKPT_DIR": str(ckpt_dir),
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE)] +
            ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH")
             else [])),
    })
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, DRIVER, str(spec), str(out), builder,
         str(ckpt_dir), str(steps)],
        env=env, capture_output=True, text=True, timeout=300)


def _fsck(ckpt_dir, *args):
    return subprocess.run(
        [sys.executable, "-m", "autodist_tpu.checkpoint",
         "--dir", str(ckpt_dir), "fsck", *args],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": os.path.dirname(HERE)},
        capture_output=True, text=True, timeout=120)


@pytest.mark.parametrize("builder", ["PartitionedAR", "PartitionedPS"])
def test_sigkill_mid_save_resume_on_smaller_mesh(tmp_path, builder):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    spec8 = tmp_path / "spec8.yml"
    spec8.write_text(SPEC_8)
    spec4 = tmp_path / "spec4.yml"
    spec4.write_text(SPEC_4)
    steps = 10

    # ---- incarnation 1: 8 devices, SIGKILLed inside the 3rd save (step
    # 6), after the shard npz + index landed but BEFORE the commit meta
    proc = _run_driver(
        spec8, tmp_path / "out_crash.json", builder, ckpt, steps, 8,
        {"ADT_CKPT_FAULT_PLAN": json.dumps(
            {"kills": [{"phase": "meta", "nth": 3}]})})
    assert proc.returncode == -signal.SIGKILL, (
        proc.returncode, proc.stdout[-2000:], proc.stderr[-4000:])
    assert not (tmp_path / "out_crash.json").exists()  # it really died

    # the crash is visible as a TORN attempt, never a half-committed
    # checkpoint: steps 2 and 4 committed, step 6 has no meta
    from autodist_tpu.checkpoint import integrity
    states = {s.step: s.state for s in integrity.scan(str(ckpt))}
    assert states[2] == "committed" and states[4] == "committed", states
    assert states[6] == "torn", states
    assert not os.path.exists(ckpt / "ckpt-6.shard-meta.json")

    # ---- storage rot on the newest COMMITTED checkpoint: restore must
    # not load it, and fsck must fail loudly
    from autodist_tpu.runtime.faultinject import flip_bit
    flip_bit(str(ckpt / "ckpt-4.shard-p0.npz"), -4096)
    assert integrity.validate_sharded(str(ckpt), 4,
                                      deep=True).state == "corrupt"
    fsck = _fsck(ckpt)
    assert fsck.returncode == 1, fsck.stdout + fsck.stderr
    assert "corrupt" in fsck.stdout

    # ---- incarnation 2: FOUR devices + auto-resume. Falls back past
    # torn step 6 and corrupt step 4 to committed step 2, re-shards onto
    # the smaller mesh, finishes training.
    proc = _run_driver(
        spec4, tmp_path / "out_resume.json", builder, ckpt, steps, 4,
        {"ADT_AUTO_RESUME": "1"})
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-4000:])
    assert "ADT_AUTO_RESUME: restored step 2" in proc.stderr, \
        proc.stderr[-4000:]
    resumed = json.loads((tmp_path / "out_resume.json").read_text())
    assert resumed["start"] == 2, resumed
    assert resumed["device_count"] == 4
    # the skipped torn + corrupt checkpoints were counted as fallbacks
    assert resumed["counters"]["ckpt.fallback"] >= 2, resumed["counters"]
    assert resumed["counters"]["ckpt.restores"] >= 1

    # ---- reference: the SAME job, uncrashed, 8 devices end to end
    proc = _run_driver(spec8, tmp_path / "out_ref.json", builder,
                       tmp_path / "ckpt_ref", steps, 8, {})
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-4000:])
    ref = json.loads((tmp_path / "out_ref.json").read_text())
    assert ref["start"] == 0

    # loss trajectory: every post-resume step matches the uncrashed run
    # (global-batch data-parallel math is device-count-invariant)
    for i in range(3, steps + 1):
        np.testing.assert_allclose(
            resumed["losses"][str(i)], ref["losses"][str(i)],
            rtol=1e-4, err_msg="step %d diverged after crash-resume" % i)
