"""Host-offloaded PS path: the tests VERDICT r1 asked for — PS and
AllReduce must lower to *different* programs with different per-device
resident bytes, the proxy knob must change the data path, and uneven
shard_sizes must be honored by real (ragged) storage.
"""
import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.parallel import ps as ps_lib


def _model(seed=0, d=16):
    rng = np.random.RandomState(seed)
    params = {
        "w1": jnp.asarray(rng.randn(d, d), jnp.float32),
        "w2": jnp.asarray(rng.randn(d, 4), jnp.float32),
        "b": jnp.zeros((4,), jnp.float32),
    }

    def loss_fn(p, batch):
        h = jnp.tanh(batch["x"] @ p["w1"])
        pred = h @ p["w2"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"x": rng.randn(8, d).astype(np.float32),
             "y": rng.randn(8, 4).astype(np.float32)}
    return loss_fn, params, batch


def _build(builder, opt=None):
    loss_fn, params, batch = _model()
    ad = adt.AutoDist(strategy_builder=builder)
    runner = ad.build(loss_fn, opt or optax.sgd(0.1), params, batch)
    runner.init(params)
    return runner, params, batch


def _device_param_bytes(state):
    return sum(int(np.prod(l.shape)) * l.dtype.itemsize
               for l in jax.tree_util.tree_leaves(state.params))


def test_ps_and_ar_lower_to_different_programs():
    """The r1 gap: every PS variant compiled to the same program as
    AllReduce. Now the PS step has extra inputs (pulled values) and extra
    outputs (reduced grads), and the device state holds no PS leaves."""
    r_ps, params, batch = _build(strategy.PS(), opt=optax.adam(1e-2))
    adt.reset()
    r_ar, _, _ = _build(strategy.AllReduce(), opt=optax.adam(1e-2))

    ds_ps, ds_ar = r_ps.distributed_step, r_ar.distributed_step
    # PS: device TrainState carries NO parameter leaves (all host-resident)
    assert ps_lib.holes_of(ds_ps._holed_template) == sorted(
        n for n in ds_ps.model_item.var_infos)
    assert _device_param_bytes(r_ps.state) == 0
    assert _device_param_bytes(r_ar.state) > 0
    # ... and no adam moments on device either (they live in the store):
    # PS device state = step counter + count leaves only
    ps_state_leaves = len(jax.tree_util.tree_leaves(
        (r_ps.state.params, r_ps.state.opt_state)))
    ar_state_leaves = len(jax.tree_util.tree_leaves(
        (r_ar.state.params, r_ar.state.opt_state)))
    assert ps_state_leaves < ar_state_leaves

    # different programs: the PS step's HLO takes the pulled values as
    # arguments and returns the reduced grads
    sharded_batch = r_ps.remapper.remap_feed(batch)
    hlo_ps = ds_ps.lowered_text(r_ps.state, sharded_batch)
    hlo_ar = ds_ar.lowered_text(r_ar.state, sharded_batch)
    assert hlo_ps != hlo_ar

    def main_sig_args(hlo):
        sig = hlo.split("func.func public @main(")[1]
        depth, out = 1, []
        for ch in sig:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    break
            out.append(ch)
        return "".join(out).count("tensor<")
    assert main_sig_args(hlo_ps) != main_sig_args(hlo_ar)

    # store accounting: a real step pulls and pushes real bytes
    store = ds_ps.ps_store
    assert store is not None and ds_ar.ps_store is None
    r_ps.run(batch)
    ds_ps.flush_ps()  # the pipelined push lands off-thread
    assert store.stats["pulls"] >= 1 and store.stats["pushes"] >= 1
    total = sum(v.byte_size for v in ds_ps.model_item.var_infos.values())
    assert store.resident_bytes() == total


def test_proxy_toggle_changes_data_path():
    """local_replication=True (the reference's proxy) keeps params on
    device: no store, no per-step host traffic."""
    r_proxy, _, batch = _build(strategy.PS(local_proxy_variable=True))
    assert r_proxy.distributed_step.ps_store is None
    assert _device_param_bytes(r_proxy.state) > 0
    adt.reset()
    r_ps, _, _ = _build(strategy.PS(local_proxy_variable=False))
    assert r_ps.distributed_step.ps_store is not None
    assert _device_param_bytes(r_ps.state) == 0


def test_ps_numerics_match_allreduce():
    """Same model+data: host-applied PS updates equal on-device AR updates
    (both are mean-grad SGD)."""
    results = {}
    for name, builder in [("ps", strategy.PS()),
                          ("ps_proxy", strategy.PS(local_proxy_variable=True)),
                          ("ar", strategy.AllReduce())]:
        r, params, batch = _build(builder)
        for _ in range(3):
            r.run(batch)
        results[name] = r.gather_params()
        adt.reset()
    for name in ("ps", "ps_proxy"):
        for k in results["ar"]:
            np.testing.assert_allclose(
                np.asarray(results[name][k]), np.asarray(results["ar"][k]),
                rtol=2e-5, atol=2e-6, err_msg="%s vs ar mismatch at %s" % (name, k))


def test_ps_pull_push_counts_and_wire_bytes():
    r, params, batch = _build(strategy.PS())
    store = r.distributed_step.ps_store
    base_pulls = store.stats["pulls"]
    for _ in range(4):
        r.run(batch)
    r.distributed_step.flush_ps()  # the pipelined pushes land off-thread
    # the pipeline prefetches one pull ahead, so 4 steps cost 4 or 5 pulls
    assert base_pulls + 4 <= store.stats["pulls"] <= base_pulls + 5
    assert store.stats["pushes"] >= 4
    per_step = sum(v.byte_size
                   for v in r.distributed_step.model_item.var_infos.values())
    assert store.stats["bytes_pulled"] >= 4 * per_step


def test_uneven_partitioned_storage_is_ragged():
    """shard_sizes must be honored by real per-shard arrays — no padding
    (reference uneven_partition_ps_strategy.py:128-137)."""
    from autodist_tpu.strategy.uneven_partition_ps_strategy import (
        UnevenPartitionedPS, first_non_divisor_shards, uneven_shard_sizes)
    r, params, batch = _build(UnevenPartitionedPS())
    store = r.distributed_step.ps_store
    d = 16
    nsh = first_non_divisor_shards(d, 3)
    assert nsh > 1  # 16: first non-divisor >= 2 is 3
    want = tuple(uneven_shard_sizes(d, nsh))
    plan = store.plans["w1"]
    assert plan.shard_sizes == want
    shards = store._values["w1"]
    assert tuple(s.shape[0] for s in shards) == want
    assert len(set(s.shape[0] for s in shards)) > 1  # actually uneven
    # training works + values stay consistent with an even-free roundtrip
    before = store.full_values()["w1"].copy()
    r.run(batch)
    r.distributed_step.flush_ps()  # the pipelined push lands off-thread
    after = store.full_values()["w1"]
    assert after.shape == before.shape and not np.allclose(before, after)


def test_partitioned_ps_owner_load_spread():
    """Round-robin shard destinations actually spread resident bytes (the
    PS load-balancing accounting is real, not metadata)."""
    r, _, _ = _build(strategy.PartitionedPS())
    store = r.distributed_step.ps_store
    loads = store.resident_bytes_by_destination()
    assert sum(loads.values()) == store.resident_bytes()


def test_ps_adam_resume_bit_exact(tmp_path):
    """Checkpoint round-trip through the host store: values AND adam
    moments reconstruct in the original layout; resume is bit-exact."""
    from autodist_tpu.checkpoint.saver import Saver
    loss_fn, params, batch = _model()
    ad = adt.AutoDist(strategy_builder=strategy.PartitionedPS())
    runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = Saver(directory=str(tmp_path), chief_only=False)
    saver.save(runner)
    # continue 2 more steps -> reference trajectory
    for _ in range(2):
        runner.run(batch)
    want = runner.gather_params()

    # fresh build, restore, rerun the same 2 steps
    adt.reset()
    ad2 = adt.AutoDist(strategy_builder=strategy.PartitionedPS())
    runner2 = ad2.build(loss_fn, optax.adam(1e-2), params, batch)
    saver2 = Saver(directory=str(tmp_path), chief_only=False)
    saver2.restore(runner2)
    for _ in range(2):
        runner2.run(batch)
    got = runner2.gather_params()
    for k in want:
        np.testing.assert_array_equal(np.asarray(want[k]), np.asarray(got[k]),
                                      err_msg="resume drift at %s" % k)


def test_ps_opt_state_gathers_in_original_layout():
    """gather_opt_state reconstructs adam mu/nu for host-resident vars in
    the full original layout (the framework-free checkpoint property)."""
    loss_fn, params, batch = _model()
    ad = adt.AutoDist(strategy_builder=strategy.PS())
    runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
    runner.init(params)
    runner.run(batch)
    opt = runner.distributed_step.gather_opt_state(runner.state)
    from autodist_tpu.kernel.common import variable_utils
    names, leaves, _ = variable_utils.flatten_named(opt)
    by_name = dict(zip(names, [np.asarray(l) for l in leaves]))
    assert by_name["0/mu/w1"].shape == (16, 16)
    assert by_name["0/nu/w2"].shape == (16, 4)
    assert np.any(by_name["0/mu/w1"] != 0)  # a step actually happened


def test_mirror_digest_tracks_values():
    """mirror_digest: equal for identically-stepped stores, changed by an
    extra step — the primitive behind the cross-process divergence check
    (ADT_PS_MIRROR_CHECK_EVERY)."""
    r1, _, batch = _build(strategy.PS())
    for _ in range(2):
        r1.run(batch)
    r1.distributed_step.flush_ps()
    d1 = r1.distributed_step.ps_store.mirror_digest()
    adt.reset()
    r2, _, batch2 = _build(strategy.PS())
    for _ in range(2):
        r2.run(batch2)
    r2.distributed_step.flush_ps()
    d2 = r2.distributed_step.ps_store.mirror_digest()
    assert d1 == d2  # deterministic replay => identical mirrors
    r2.run(batch2)
    r2.distributed_step.flush_ps()
    assert r2.distributed_step.ps_store.mirror_digest() != d2
    adt.reset()


def test_ps_chained_optimizer_clips_per_var_as_documented():
    """Cross-variable optimizer coupling (global-norm clipping) decouples
    on the host-PS path: each variable's update applies through its OWN
    little optimizer tree, so the clip norm is per-variable — exactly the
    reference's semantics with per-PS-device update ops, and exactly what
    the PSStore docstring promises. Pin both sides with hand math: AR
    clips by the GLOBAL norm, PS by each var's own."""
    clip_c = 0.05
    opt = optax.chain(optax.clip_by_global_norm(clip_c), optax.sgd(1.0))
    loss_fn, params, batch = _model()

    # hand-computed grads
    g = jax.grad(loss_fn)(
        {k: jnp.asarray(v) for k, v in params.items()}, batch)
    flat = {k: np.asarray(v) for k, v in g.items()}
    global_norm = np.sqrt(sum(float((a ** 2).sum()) for a in flat.values()))

    r_ar, _, _ = _build(strategy.AllReduce(), opt=opt)
    r_ar.run(batch)
    got_ar = r_ar.gather_params()
    adt.reset()
    r_ps, _, _ = _build(strategy.PS(), opt=opt)
    r_ps.run(batch)
    got_ps = r_ps.gather_params()
    adt.reset()

    for k, g_k in flat.items():
        var_norm = float(np.sqrt((g_k ** 2).sum()))
        ar_scale = min(1.0, clip_c / global_norm)
        ps_scale = min(1.0, clip_c / var_norm)
        np.testing.assert_allclose(
            np.asarray(got_ar[k]), params[k] - ar_scale * g_k,
            rtol=1e-5, atol=1e-6, err_msg="AR global clip at %s" % k)
        np.testing.assert_allclose(
            np.asarray(got_ps[k]), params[k] - ps_scale * g_k,
            rtol=1e-5, atol=1e-6, err_msg="PS per-var clip at %s" % k)


def test_ps_rejects_structure_sensitive_optimizer():
    """optax.multi_transform decides each leaf's transform from the TREE
    it sees; the host store applies per-variable little trees, where a
    label function resolves wrong — a variable would silently train
    under the wrong transform. The build must refuse loudly; the proxied
    (device-resident) path, which applies the optimizer on the full
    tree, accepts the same optimizer."""
    loss_fn, params, batch = _model()
    opt = optax.multi_transform(
        {"slow": optax.sgd(0.01), "fast": optax.sgd(0.5)},
        lambda p: {k: ("fast" if k == "b" else "slow") for k in p})
    ad = adt.AutoDist(strategy_builder=strategy.PS())
    with pytest.raises(ValueError, match="structure-sensitive"):
        ad.build(loss_fn, opt, params, batch)
    adt.reset()
    r, _, _ = _build(strategy.PS(local_proxy_variable=True), opt=opt)
    g = jax.grad(loss_fn)({k: jnp.asarray(v) for k, v in params.items()},
                          batch)
    r.run(batch)
    got = r.gather_params()
    # the full-tree labels really applied: "b" stepped at the fast rate,
    # "w1" at the slow one (finite loss alone cannot catch mislabeled
    # transforms)
    np.testing.assert_allclose(np.asarray(got["b"]),
                               params["b"] - 0.5 * np.asarray(g["b"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got["w1"]),
                               params["w1"] - 0.01 * np.asarray(g["w1"]),
                               rtol=1e-5, atol=1e-6)
    adt.reset()


# ----------------------------------------------------------- overlap pipeline


def test_ps_overlap_pipeline_bitexact_vs_serial(monkeypatch):
    """Sync host-PS with the transfer/compute overlap pipeline (default)
    must produce the exact trajectory of the serial pull->step->push
    baseline (ADT_PS_OVERLAP=0) — same calls, same order, just off the
    main thread."""
    def run(overlap):
        monkeypatch.setenv("ADT_PS_OVERLAP", "1" if overlap else "0")
        adt.reset()
        runner, params, batch = _build(strategy.PartitionedPS(),
                                       opt=optax.adam(1e-2))
        assert (runner.distributed_step._ps_pipe is not None) == overlap
        losses = [float(runner.run(batch)["loss"]) for _ in range(6)]
        final = runner.gather_params()
        return losses, final

    l_serial, p_serial = run(False)
    l_pipe, p_pipe = run(True)
    np.testing.assert_array_equal(l_serial, l_pipe)
    for k in p_serial:
        np.testing.assert_array_equal(np.asarray(p_serial[k]),
                                      np.asarray(p_pipe[k]))


def test_ps_overlap_stale_mode_prefetches_before_apply():
    """With staleness>=1 the pipeline issues the next pull BEFORE applying
    this step's grads (reads lag applies by exactly one — the overlap that
    makes step time ~ max(compute, transfer)), and still converges."""
    runner, params, batch = _build(strategy.PS(staleness=1),
                                   opt=optax.sgd(0.1))
    dstep = runner.distributed_step
    pipe = dstep._ps_pipe
    assert pipe is not None and pipe._stale_ok
    store = dstep.ps_store

    order = []
    real_pull, real_push = store.pull, store.push

    def pull_spy():
        order.append("pull")
        return real_pull()

    def push_spy(grads):
        order.append("push")
        return real_push(grads)

    store.pull, store.push = pull_spy, push_spy
    try:
        losses = [float(runner.run(batch)["loss"]) for _ in range(5)]
        dstep.flush_ps()
    finally:
        store.pull, store.push = real_pull, real_push
    # stale mode runs pulls on their own lane so they overlap the pushes:
    # each step contributes one prefetch pull and one push, and the pull
    # for step N+1 is SUBMITTED before step N's push (the overlap)
    assert pipe._pull_exec is not pipe._exec  # separate lanes engaged
    assert order.count("pull") >= 5 and order.count("push") >= 4, order
    assert losses[-1] < losses[0], losses
    # stale-by-one reads still track the applies: one serial step from the
    # gathered params must equal what the NEXT pipelined pull will see
    final = runner.gather_params()
    assert all(np.isfinite(np.asarray(v)).all() for v in final.values())


def test_ps_overlap_flush_before_checkpoint(tmp_path):
    """gather_params (and thus Saver.save) must see the in-flight push
    applied: checkpoint equals serial-mode checkpoint bit-for-bit."""
    from autodist_tpu.checkpoint.saver import Saver
    runner, params, batch = _build(strategy.PS(), opt=optax.adam(1e-2))
    assert runner.distributed_step._ps_pipe is not None
    for _ in range(3):
        runner.run(batch)
    path = Saver(directory=str(tmp_path)).save(runner)
    flat = dict(np.load(path + ".params.npz"))

    import os
    os.environ["ADT_PS_OVERLAP"] = "0"
    try:
        adt.reset()
        runner2, _, _ = _build(strategy.PS(), opt=optax.adam(1e-2))
        for _ in range(3):
            runner2.run(batch)
        path2 = Saver(directory=str(tmp_path / "serial")).save(runner2)
        flat2 = dict(np.load(path2 + ".params.npz"))
    finally:
        os.environ.pop("ADT_PS_OVERLAP", None)
    for k in flat:
        np.testing.assert_array_equal(flat[k], flat2[k])


def test_ps_threaded_apply_bitexact_vs_single(monkeypatch):
    """ADT_PS_APPLY_THREADS=4 fans the per-shard optimizer apply over a
    thread pool; shard grouping never changes per-shard math, so the
    trajectory is BIT-exact vs the single-dispatch baseline (and the pool
    really engages: >1 shard groups on a partitioned var)."""
    def run(threads):
        monkeypatch.setenv("ADT_PS_APPLY_THREADS", str(threads))
        adt.reset()
        runner, params, batch = _build(strategy.PartitionedPS(),
                                       opt=optax.adam(1e-2))
        store = runner.distributed_step.ps_store
        assert store is not None and store._apply_threads == threads
        losses = [float(runner.run(batch)["loss"]) for _ in range(6)]
        runner.distributed_step.flush_ps()
        if threads > 1:
            # the pool actually engaged (lazily built on first apply)
            assert store._apply_pool is not None
        final = runner.gather_params()
        return losses, final

    l1, p1 = run(1)
    l4, p4 = run(4)
    np.testing.assert_array_equal(l1, l4)
    for k in p1:
        np.testing.assert_array_equal(np.asarray(p1[k]), np.asarray(p4[k]))


def test_evaluate_pulls_ps_once_for_whole_loop():
    """Runner.evaluate pulls the host-PS values ONCE for the whole eval
    loop — no pushes happen between eval batches, so per-batch re-pulls
    would be pure PCIe waste (1 GB of store params x 100 batches = 100 GB
    of transfer for unchanged values)."""
    runner, params, batch = _build(strategy.PS(), opt=optax.sgd(0.05))
    runner.init(params)
    runner.run(batch)
    runner.distributed_step.flush_ps()
    store = runner.distributed_step.ps_store
    before = store.stats["pulls"]
    runner.evaluate(iter([batch] * 5))
    assert store.stats["pulls"] - before <= 1, store.stats
