"""Cost model / simulator / AutoStrategy tests."""
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.model_item import ModelItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.simulator.simulator import Simulator
from autodist_tpu.strategy.auto_strategy import AutoStrategy


def _item(dense_dim=512, vocab=4096):
    params = {"emb": jnp.zeros((vocab, 64)),
              "w1": jnp.zeros((64, dense_dim)),
              "w2": jnp.zeros((dense_dim, 1))}

    def loss_fn(p, batch):
        e = jnp.take(p["emb"], batch["ids"], axis=0)
        h = jnp.tanh(e @ p["w1"])
        return jnp.mean((h @ p["w2"] - batch["y"]) ** 2)

    batch = {"ids": np.zeros((32,), np.int32),
             "y": np.zeros((32, 1), np.float32)}
    return ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1), params=params,
                     example_batch=batch).prepare()


def _spec(n_nodes=4, tpus=4):
    nodes = [{"address": "10.0.0.%d" % (i + 1), "tpus": tpus,
              "chief": i == 0, "network_bandwidth": 25}
             for i in range(n_nodes)]
    return ResourceSpec.from_dict({"nodes": nodes,
                                   "slice": {"type": "v5e", "ici_bandwidth": 400}})


def test_breakdown_positive_and_ordered():
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    r_ar = sim.simulate(S.AllReduce().build(item, spec), "ar")
    r_ps = sim.simulate(S.PS().build(item, spec), "ps")
    assert r_ar.step_time_s > 0 and r_ps.step_time_s > 0
    # a single PS server's NIC carries everything; ICI all-reduce must win
    assert r_ar.step_time_s < r_ps.step_time_s


def test_lb_beats_single_ps():
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    r_ps = sim.simulate(S.PS().build(item, spec), "ps")
    r_lb = sim.simulate(S.PSLoadBalancing().build(item, spec), "lb")
    assert r_lb.breakdown.ps_s <= r_ps.breakdown.ps_s


def test_compression_reduces_ar_cost():
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    plain = sim.simulate(S.AllReduce().build(item, spec), "plain")
    bf16 = sim.simulate(
        S.AllReduce(compressor="HorovodCompressor").build(item, spec), "bf16")
    assert bf16.breakdown.allreduce_s < plain.breakdown.allreduce_s


def test_auto_strategy_picks_and_runs():
    """AutoStrategy must return a lowerable strategy that trains."""
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(16, 4).astype(np.float32))}
    loss = lambda p, b: jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)
    batch = {"x": rng.randn(16, 16).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}
    builder = AutoStrategy()
    ad = autodist_tpu.AutoDist(strategy_builder=builder)
    step = ad.function(loss, optimizer=optax.sgd(0.1), params=params)
    losses = [step(batch)["loss"] for _ in range(5)]
    assert losses[-1] < losses[0]
    assert builder.last_ranking is not None
    assert len(builder.last_ranking) >= 5
    autodist_tpu.reset()


def test_auto_strategy_deterministic():
    item, spec = _item(), _spec()
    s1 = AutoStrategy().build(item, spec)
    s2 = AutoStrategy().build(item, spec)
    d1, d2 = s1.to_dict(), s2.to_dict()
    d1.pop("id"), d2.pop("id")
    assert d1 == d2


def test_proxy_ps_cheaper_than_host_ps():
    """The cost model reflects the real data paths: a proxied (device-
    resident) PS variable syncs over ICI while a host-resident one pays
    PCIe pull/push each step — so the proxy plan must rank cheaper on an
    ICI-rich slice."""
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    host = sim.simulate(S.PS().build(item, spec), "host")
    proxy = sim.simulate(S.PS(local_proxy_variable=True).build(item, spec),
                         "proxy")
    # the ranking itself, not just the (structurally zero) proxy ps term
    assert proxy.step_time_s < host.step_time_s
    assert proxy.breakdown.ps_s == 0.0  # device-resident: no PS wire at all
    # host path's PCIe term exists even on a single node
    single = _spec(n_nodes=1)
    sim1 = Simulator(item, single)
    host1 = sim1.simulate(S.PS().build(item, single), "host1")
    assert host1.breakdown.ps_s > 0


def test_auto_strategy_avoids_host_ps_for_hbm_fitting_model():
    """With PCIe-honest PS costs, AutoStrategy must not pick the
    host-offloaded PS family for a model that trivially fits HBM."""
    item, spec = _item(), _spec()
    auto = AutoStrategy()
    chosen = auto.build(item, spec)
    from autodist_tpu.parallel.ps import plan_host_ps
    assert not plan_host_ps(chosen, item.var_infos), \
        "AutoStrategy picked host-resident PS for an HBM-fitting model"
