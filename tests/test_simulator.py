"""Cost model / simulator / AutoStrategy tests."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.model_item import ModelItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.simulator.simulator import Simulator
from autodist_tpu.strategy.auto_strategy import AutoStrategy


def _item(dense_dim=512, vocab=4096):
    params = {"emb": jnp.zeros((vocab, 64)),
              "w1": jnp.zeros((64, dense_dim)),
              "w2": jnp.zeros((dense_dim, 1))}

    def loss_fn(p, batch):
        e = jnp.take(p["emb"], batch["ids"], axis=0)
        h = jnp.tanh(e @ p["w1"])
        return jnp.mean((h @ p["w2"] - batch["y"]) ** 2)

    batch = {"ids": np.zeros((32,), np.int32),
             "y": np.zeros((32, 1), np.float32)}
    return ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1), params=params,
                     example_batch=batch).prepare()


def _spec(n_nodes=4, tpus=4):
    nodes = [{"address": "10.0.0.%d" % (i + 1), "tpus": tpus,
              "chief": i == 0, "network_bandwidth": 25}
             for i in range(n_nodes)]
    return ResourceSpec.from_dict({"nodes": nodes,
                                   "slice": {"type": "v5e", "ici_bandwidth": 400}})


def test_breakdown_positive_and_ordered():
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    r_ar = sim.simulate(S.AllReduce().build(item, spec), "ar")
    r_ps = sim.simulate(S.PS().build(item, spec), "ps")
    assert r_ar.step_time_s > 0 and r_ps.step_time_s > 0
    # a single PS server's NIC carries everything; ICI all-reduce must win
    assert r_ar.step_time_s < r_ps.step_time_s


def test_lb_beats_single_ps():
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    r_ps = sim.simulate(S.PS().build(item, spec), "ps")
    r_lb = sim.simulate(S.PSLoadBalancing().build(item, spec), "lb")
    assert r_lb.breakdown.ps_s <= r_ps.breakdown.ps_s


def test_compression_reduces_ar_cost():
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    plain = sim.simulate(S.AllReduce().build(item, spec), "plain")
    bf16 = sim.simulate(
        S.AllReduce(compressor="HorovodCompressor").build(item, spec), "bf16")
    assert bf16.breakdown.allreduce_s < plain.breakdown.allreduce_s


def test_auto_strategy_picks_and_runs():
    """AutoStrategy must return a lowerable strategy that trains."""
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(16, 4).astype(np.float32))}
    loss = lambda p, b: jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)
    batch = {"x": rng.randn(16, 16).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}
    builder = AutoStrategy()
    ad = autodist_tpu.AutoDist(strategy_builder=builder)
    step = ad.function(loss, optimizer=optax.sgd(0.1), params=params)
    losses = [step(batch)["loss"] for _ in range(5)]
    assert losses[-1] < losses[0]
    assert builder.last_ranking is not None
    assert len(builder.last_ranking) >= 5
    autodist_tpu.reset()


def test_auto_strategy_deterministic():
    item, spec = _item(), _spec()
    s1 = AutoStrategy().build(item, spec)
    s2 = AutoStrategy().build(item, spec)
    d1, d2 = s1.to_dict(), s2.to_dict()
    d1.pop("id"), d2.pop("id")
    assert d1 == d2


def test_proxy_ps_cheaper_than_host_ps():
    """The cost model reflects the real data paths: a proxied (device-
    resident) PS variable syncs over ICI while a host-resident one pays
    PCIe pull/push each step — so the proxy plan must rank cheaper on an
    ICI-rich slice."""
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    host = sim.simulate(S.PS().build(item, spec), "host")
    proxy = sim.simulate(S.PS(local_proxy_variable=True).build(item, spec),
                         "proxy")
    # the ranking itself, not just the (structurally zero) proxy ps term
    assert proxy.step_time_s < host.step_time_s
    assert proxy.breakdown.ps_s == 0.0  # device-resident: no PS wire at all
    # host path's PCIe term exists even on a single node
    single = _spec(n_nodes=1)
    sim1 = Simulator(item, single)
    host1 = sim1.simulate(S.PS().build(item, single), "host1")
    assert host1.breakdown.ps_s > 0


def test_auto_strategy_avoids_host_ps_for_hbm_fitting_model():
    """With PCIe-honest PS costs, AutoStrategy must not pick the
    host-offloaded PS family for a model that trivially fits HBM."""
    item, spec = _item(), _spec()
    auto = AutoStrategy()
    chosen = auto.build(item, spec)
    from autodist_tpu.parallel.ps import plan_host_ps
    assert not plan_host_ps(chosen, item.var_infos), \
        "AutoStrategy picked host-resident PS for an HBM-fitting model"


def test_hbm_estimate_orders_strategies():
    """Host-PS offloads optimizer state (lower device bytes than AR with
    the same optimizer); remat shrinks the activation term below the
    plain program; remat also costs more compute."""
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    r_ar = sim.simulate(S.AllReduce().build(item, spec), "ar")
    r_ps = sim.simulate(S.PS().build(item, spec), "ps")
    r_remat = sim.simulate(
        S.WithRemat(S.AllReduce(), policy="dots").build(item, spec), "remat")
    assert r_ar.breakdown.hbm_bytes > 0
    # sgd has no moments; use adam to see the opt-state offload
    import optax as _o
    adam_item = ModelItem(loss_fn=item.loss_fn, optimizer=_o.adam(1e-3),
                          params=item.params,
                          example_batch=item.example_batch).prepare()
    sim_a = Simulator(adam_item, spec)
    a_ar = sim_a.simulate(S.AllReduce().build(adam_item, spec), "ar")
    a_ps = sim_a.simulate(S.PS().build(adam_item, spec), "ps")
    assert a_ps.breakdown.hbm_bytes < a_ar.breakdown.hbm_bytes
    assert r_remat.breakdown.hbm_bytes < r_ar.breakdown.hbm_bytes
    assert r_remat.breakdown.compute_s > r_ar.breakdown.compute_s


def test_feasibility_gate_prefers_remat_when_tight():
    """With HBM capacity squeezed below the plain program's estimate (but
    above the remat one), the ranking puts the remat candidate first even
    though it is slower; with ample capacity the plain program wins."""
    item, spec = _item(), _spec()
    cands = [("plain", S.AllReduce().build(item, spec)),
             ("remat", S.WithRemat(S.AllReduce(),
                                   policy="dots").build(item, spec))]
    roomy = Simulator(item, spec, hbm_capacity_bytes=1e15)
    assert roomy.rank(cands)[0].label == "plain"
    plain_hbm = roomy.simulate(cands[0][1]).breakdown.hbm_bytes
    remat_hbm = roomy.simulate(cands[1][1]).breakdown.hbm_bytes
    tight = Simulator(item, spec,
                      hbm_capacity_bytes=(plain_hbm + remat_hbm) / 2)
    ranked = tight.rank(cands)
    assert ranked[0].label == "remat"
    assert ranked[0].breakdown.feasible
    assert not ranked[1].breakdown.feasible


def test_rank_skip_projected_oom_drops_adt501_candidates(caplog):
    """Satellite: with ``skip_projected_oom=True`` a candidate whose
    memory estimate raises ADT501 (projected per-device OOM) is DROPPED
    from the ranking with a logged reason — mirroring the verify() skip
    path — and when every candidate would OOM, the unskipped ranking is
    returned with a warning instead of an empty list."""
    import logging as pylogging
    from autodist_tpu.utils.logging import get_logger
    item, spec = _item(), _spec()
    cands = [("plain", S.AllReduce().build(item, spec)),
             ("remat", S.WithRemat(S.AllReduce(),
                                   policy="dots").build(item, spec))]
    roomy = Simulator(item, spec, hbm_capacity_bytes=1e15)
    plain_hbm = roomy.simulate(cands[0][1]).breakdown.hbm_bytes
    remat_hbm = roomy.simulate(cands[1][1]).breakdown.hbm_bytes
    tight = Simulator(item, spec,
                      hbm_capacity_bytes=(plain_hbm + remat_hbm) / 2)
    # default keeps the soft behavior: infeasible candidates rank last
    assert [r.label for r in tight.rank(cands)] == ["remat", "plain"]
    logger = get_logger()
    logger.addHandler(caplog.handler)
    try:
        with caplog.at_level(pylogging.INFO, logger="autodist_tpu"):
            skipped = tight.rank(cands, skip_projected_oom=True)
            # every candidate OOMs -> fall back to the full ranking
            impossible = Simulator(item, spec,
                                   hbm_capacity_bytes=min(plain_hbm,
                                                          remat_hbm) / 2)
            all_oom = impossible.rank(cands, skip_projected_oom=True)
    finally:
        logger.removeHandler(caplog.handler)
    assert [r.label for r in skipped] == ["remat"]
    assert any("skipping projected-OOM" in r.getMessage()
               and "ADT501" in r.getMessage() for r in caplog.records)
    assert len(all_oom) == 2
    assert any("every candidate is projected to OOM" in r.getMessage()
               for r in caplog.records)


def _activation_heavy_item(batch=8192, width=64, depth=8):
    """Small params, huge per-step activations — the regime where remat
    (not ZeRO/host-PS, which relieve PARAM/opt memory) is the right
    memory lever."""
    params = {"w%d" % i: jnp.zeros((width, width)) for i in range(depth)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(depth):
            h = jnp.tanh(h @ p["w%d" % i])
        return jnp.mean(h ** 2)

    batch_np = {"x": np.zeros((batch, width), np.float32)}
    return ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1),
                     params=params, example_batch=batch_np).prepare()


def test_auto_strategy_remat_fallback_candidate():
    """On an activation-dominated model the remat candidate needs less
    HBM than every param-relief candidate (ZeRO, host-PS); squeeze
    capacity between the remat estimate and the rest and the remat
    strategy must win the ranking outright."""
    item, spec = _activation_heavy_item(), _spec()
    # search=False: this test probes the ZOO ranking mechanics (the
    # per-variable search would synthesize its own remat'd plan and win)
    probe = AutoStrategy(search=False, hbm_capacity_bytes=1e15)
    probe.build(item, spec)
    by_label = {r.label: r.breakdown.hbm_bytes for r in probe.last_ranking}
    remat_hbm = by_label.pop("AllReduce/remat")
    others_min = min(by_label.values())
    assert remat_hbm < others_min, (remat_hbm, by_label)
    auto = AutoStrategy(search=False,
                        hbm_capacity_bytes=(remat_hbm + others_min) / 2)
    built = auto.build(item, spec)
    assert auto.last_ranking[0].label == "AllReduce/remat"
    assert built.graph_config.remat == "dots"
    # the searched space satisfies the same squeeze, but is NOT required
    # to satisfy it with remat: with the bf16 compute tier and per-var
    # sharding in the space the search can project even less HBM than
    # the remat zoo candidate — assert the budget is respected and that
    # the winning plan relieves HBM through one of the managed axes
    cap = (remat_hbm + others_min) / 2
    auto2 = AutoStrategy(hbm_capacity_bytes=cap)
    searched = auto2.build(item, spec)
    assert auto2.last_ranking[0].breakdown.hbm_bytes <= cap
    assert (searched.graph_config.remat == "dots"
            or searched.graph_config.compute_dtype == "bf16")


def test_scan_activations_scale_with_trip_count():
    """A 1-layer body scanned N times saves ~N layers of residuals — the
    profile must multiply scan bodies by their trip count (a single-visit
    walk undercounts by N and the feasibility gate passes OOMing
    programs)."""
    from autodist_tpu.simulator.cost_model import CostModel

    def make(n_layers):
        params = {"w": jnp.zeros((64, 64))}

        def loss_fn(p, b):
            def body(h, _):
                return jnp.tanh(h @ p["w"]), None
            h, _ = jax.lax.scan(body, b["x"], None, length=n_layers)
            return jnp.mean(h ** 2)

        return ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1),
                         params=params,
                         example_batch={"x": np.zeros((256, 64),
                                                      np.float32)}).prepare()

    spec = _spec()
    act2 = CostModel(make(2), spec)._activation_profile()[0]
    act32 = CostModel(make(32), spec)._activation_profile()[0]
    assert act32 > 10 * act2, (act2, act32)


# ------------------------------------------------------------- calibration

def test_calibration_recovers_known_scales(tmp_path):
    """Synthetic ground truth: 'measured' times generated from the
    model's own raw breakdowns under known term scales. Each recoverable
    term dominates at least one measurement (compute via the int8-wire
    candidate, collectives via plain/bf16 AR, host link via the PS pair);
    the latency term never dominates anything, so the regularizer must
    hold it at ~1.0 instead of letting it wander."""
    from autodist_tpu.simulator.calibration import Calibration, _predict
    item, spec = _item(dense_dim=16384), _spec()
    # flops override puts raw compute at ~8e-5 s — at the int8-AR wire
    # time (the sparse emb now prices uncompressed, raising that wire)
    # and well under the plain-AR wire, so the max() switches dominance
    # per candidate
    sim = Simulator(item, spec, flops_per_step=1e11)
    candidates = [
        ("ar", S.AllReduce().build(item, spec)),
        ("ar_bf16", S.AllReduce(compressor="HorovodCompressor").build(item, spec)),
        ("ar_int8", S.AllReduce(compressor="Int8CompressorEF").build(item, spec)),
        ("ps", S.PS().build(item, spec)),
        ("lb", S.PSLoadBalancing().build(item, spec)),
    ]
    true_scales = (3.0, 2.0, 2.0, 1.0)
    raw = [sim._cost_model.estimate(s) for _, s in candidates]
    # sanity of the test setup itself: every fitted term dominates somewhere
    assert any(3.0 * b.compute_s > 2.0 * (b.allreduce_s + b.ps_s) for b in raw)
    assert any(2.0 * b.allreduce_s > 3.0 * b.compute_s for b in raw)
    assert any(2.0 * b.ps_s > 3.0 * b.compute_s for b in raw)
    measured = [(s, _predict(b, true_scales))
                for (_, s), b in zip(candidates, raw)]

    cal = sim.calibrate(measured, save_path=str(tmp_path / "cal.json"))
    assert abs(cal.compute_scale - 3.0) / 3.0 < 0.2
    assert abs(cal.ar_scale - 2.0) / 2.0 < 0.2
    assert abs(cal.ps_scale - 2.0) / 2.0 < 0.2
    assert 0.5 < cal.latency_scale < 2.0  # unidentifiable -> regularized ~1
    # post-fit predictions match the synthetic measurements closely
    for (s, t) in measured:
        pred = sim.simulate(s).step_time_s
        assert abs(pred - t) / t < 0.05, (t, pred)

    # round-trip through disk and the CostModel(calibration=path) hook
    loaded = Calibration.load(str(tmp_path / "cal.json"))
    assert loaded.to_dict() == pytest.approx(cal.to_dict())
    sim2 = Simulator(item, spec, flops_per_step=1e11,
                     calibration=str(tmp_path / "cal.json"))
    for (s, t) in measured:
        assert abs(sim2.simulate(s).step_time_s - t) / t < 0.05


def test_calibration_fixes_misranking():
    """On hardware where collectives are far slower than the analytic
    ICI assumption and the host link far faster (say, chips linked only
    over DCN but with NVMe-fast host staging), AllReduce no longer beats
    PS — the uncalibrated model still says it does; fitting two measured
    points flips the ranking to the truth."""
    from autodist_tpu.simulator.calibration import _predict
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    a = S.AllReduce().build(item, spec)
    p = S.PS().build(item, spec)
    raw_a, raw_p = sim._cost_model.estimate(a), sim._cost_model.estimate(p)
    true_scales = (1.0, 25.0, 0.05, 1.0)
    t_a, t_p = _predict(raw_a, true_scales), _predict(raw_p, true_scales)
    assert t_p < t_a  # ground truth: PS wins on this hardware
    uncal = sim.rank([("ar", a), ("ps", p)])
    assert uncal[0].label == "ar"  # the analytic model gets it wrong
    sim.calibrate([(a, t_a), (p, t_p)])
    cal_rank = sim.rank([("ar", a), ("ps", p)])
    assert cal_rank[0].label == "ps"  # measurements corrected the choice


def test_calibration_rejects_bad_input():
    from autodist_tpu.simulator import calibration as cal_lib
    with pytest.raises(ValueError):
        cal_lib.fit([], [])
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    s = S.AllReduce().build(item, spec)
    with pytest.raises(ValueError):
        sim.calibrate([(s, -1.0)])


def test_calibration_auto_span_handles_structural_mismatch():
    """Hardware whose step times are ~1000x the analytic terms (e.g. a
    dispatch-dominated CPU mesh) saturates the default span; the auto
    expansion must still produce a fit that explains the measurements."""
    from autodist_tpu.simulator import calibration as cal_lib
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    strategies = [S.AllReduce().build(item, spec),
                  S.PSLoadBalancing().build(item, spec)]
    raw = [sim._cost_model.estimate(s) for s in strategies]
    measured = [0.011, 0.013]  # ms-scale reality vs us-scale model terms
    tight = cal_lib.fit(raw, measured, span=30.0)
    assert cal_lib.rel_rmse(raw, measured, tight) > 0.5  # saturated
    auto = cal_lib.fit_auto_span(raw, measured)
    assert cal_lib.rel_rmse(raw, measured, auto) < 0.1


def test_calibration_rejects_nan_measurement():
    from autodist_tpu.simulator import calibration as cal_lib
    item, spec = _item(), _spec()
    sim = Simulator(item, spec)
    s = S.AllReduce().build(item, spec)
    raw = sim._cost_model.estimate(s)
    for bad in (float("nan"), float("inf")):
        with pytest.raises(ValueError):
            cal_lib.fit([raw], [bad])


# ---------------------------------------------- model-parallel accounting

def _tp_case(seq_len=16, batch_size=8):
    from autodist_tpu.models import tp_lm
    cfg = tp_lm.TPLMConfig.tiny()
    loss_fn, params, batch, _ = tp_lm.make_train_setup(
        cfg, seq_len=seq_len, batch_size=batch_size)
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1),
                     params=params, example_batch=batch).prepare()
    return item, tp_lm.tp_rules()


def test_collective_profile_sees_megatron_psums():
    from autodist_tpu.kernel.common.utils import collective_comm_profile
    from autodist_tpu.utils.axis_env import bound_axes
    item, _ = _tp_case()
    with bound_axes():
        jx = jax.make_jaxpr(item.loss_fn)(item.params, item.example_batch)
    prof = collective_comm_profile(jx.jaxpr)
    # row-parallel psums are "reduce"-class: full payload on the wire
    assert prof["model"]["reduce"] > 0


def test_psum_cost_not_divided_by_axis_size():
    """Reduce-class payload must NOT shrink with axis extent: a tp8 psum
    all-reduces the same full activation as a tp2 psum, at a slightly
    larger ring factor."""
    from autodist_tpu.strategy.tensor_parallel_strategy import TensorParallel
    item, rules = _tp_case()
    spec = _spec(n_nodes=1, tpus=8)
    sim = Simulator(item, spec)
    tp2 = TensorParallel(tp_shards=2, mp_rules=rules).build(item, spec)
    tp8 = TensorParallel(tp_shards=8, mp_rules=rules).build(item, spec)
    mp2 = sim.simulate(tp2).breakdown.mp_s
    mp8 = sim.simulate(tp8).breakdown.mp_s
    assert mp8 > mp2  # ring factor grows with k; payload does not shrink


def test_mp_term_prices_tensor_parallel():
    """A TensorParallel strategy carries a nonzero serial mp_s term that
    grows with payload; DP strategies carry none. On an ICI-rich spec the
    small model ranks DP first; with HBM capacity squeezed below DP's
    needs (but above TP's sharded storage) the feasibility gate flips the
    ranking to TP — memory pressure is WHY one goes model-parallel."""
    from autodist_tpu.strategy.tensor_parallel_strategy import TensorParallel
    item, rules = _tp_case()
    spec = _spec(n_nodes=1, tpus=8)
    sim = Simulator(item, spec)
    tp = TensorParallel(tp_shards=2, mp_rules=rules).build(item, spec)
    dp = S.AllReduce().build(item, spec)
    b_tp, b_dp = sim.simulate(tp).breakdown, sim.simulate(dp).breakdown
    assert b_tp.mp_s > 0
    assert b_dp.mp_s == 0
    assert sim.rank([("dp", dp), ("tp", tp)])[0].label == "dp"
    # squeeze HBM: DP infeasible, TP's sharded params fit
    mid = (b_dp.hbm_bytes + b_tp.hbm_bytes) / 2
    assert b_tp.hbm_bytes < b_dp.hbm_bytes
    tight = Simulator(item, spec, hbm_capacity_bytes=mid)
    ranked = tight.rank([("dp", dp), ("tp", tp)])
    assert ranked[0].label == "tp"
    assert ranked[0].breakdown.feasible and not ranked[1].breakdown.feasible


def test_auto_strategy_extra_candidates_rank_and_build():
    """extra_candidates extends the default pool; the chosen strategy
    (whichever wins) must lower and train."""
    from autodist_tpu.strategy.tensor_parallel_strategy import TensorParallel
    import autodist_tpu as adt
    from autodist_tpu.models import tp_lm
    adt.reset()
    cfg = tp_lm.TPLMConfig.tiny()
    loss_fn, params, batch, _ = tp_lm.make_train_setup(
        cfg, seq_len=16, batch_size=8)
    builder = AutoStrategy(extra_candidates=[
        ("tp2", TensorParallel(tp_shards=2, mp_rules=tp_lm.tp_rules()))])
    ad = adt.AutoDist(strategy_builder=builder)
    step = ad.function(loss_fn, optimizer=optax.sgd(0.1), params=params)
    losses = [float(step(batch)["loss"]) for _ in range(3)]
    assert losses[-1] < losses[0]
    labels = [r.label for r in builder.last_ranking]
    assert "tp2" in labels and len(labels) > 5
    adt.reset()


def test_pp_bubble_prices_microbatching():
    """The GPipe bubble inflates compute by (S-1+M)/M: more microbatches
    amortize the bubble; the factor survives strategy serialization."""
    from autodist_tpu.strategy.pipeline_parallel_strategy import PipelineParallel
    from autodist_tpu.strategy.base import Strategy
    from autodist_tpu.models import pipe_lm
    cfg = pipe_lm.TPLMConfig.tiny()
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=8, n_microbatches=4)
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1),
                     params=params, example_batch=batch).prepare()
    spec = _spec(n_nodes=1, tpus=8)
    sim = Simulator(item, spec)
    rules = pipe_lm.pp_rules(model_axis="model")
    few = PipelineParallel(pp_shards=4, n_microbatches=2,
                           mp_rules=rules).build(item, spec)
    many = PipelineParallel(pp_shards=4, n_microbatches=16,
                            mp_rules=rules).build(item, spec)
    c_few = sim.simulate(few).breakdown.compute_s
    c_many = sim.simulate(many).breakdown.compute_s
    # (4-1+2)/2 = 2.5x vs (4-1+16)/16 ~= 1.19x
    assert c_few / c_many == pytest.approx(2.5 / (19 / 16), rel=1e-6)
    # the factor must survive the file handoff (workers re-rank nothing,
    # but the chief's AutoStrategy decisions must be reproducible from
    # the serialized form)
    rt = Strategy.from_dict(few.to_dict())
    assert rt.graph_config.pp_microbatches == 2
    assert sim.simulate(rt).breakdown.compute_s == pytest.approx(c_few)


# ------------------------------------------------------- widened auto search


def test_auto_default_pool_covers_framework_families():
    """The default candidate pool spans the framework's strategy space:
    host-PS, proxy-PS, staleness, quantized + PowerSGD compression,
    int8-Parallax, ZeRO, remat (VERDICT r3 #5)."""
    from autodist_tpu.strategy.auto_strategy import default_candidates
    labels = {l for l, _ in default_candidates()}
    for want in ("PS", "PS/proxy", "PS/stale2", "AllReduce/psgd2",
                 "Parallax/int8", "PartitionedAR", "AllReduce/remat"):
        assert want in labels, (want, labels)


def test_auto_pick_flips_across_families_with_resources():
    """Sweeping compute-intensity/memory/bandwidth flips the auto pick
    through >= 4 distinct strategies from >= 3 families, each justified
    by its CostBreakdown (VERDICT r3 #5)."""
    from autodist_tpu.parallel.ps import plan_host_ps

    def family(result):
        label = result.label
        if "remat" in label:
            return "remat"
        if any(t in label for t in ("psgd", "int8")):
            return "lossy-compress"
        if label.startswith("Partitioned") or plan_host_ps(
                result.strategy, {}) is None:
            pass
        return label.split("/")[0]

    picks = {}

    # 1) compute-bound (flops pinned high), roomy HBM -> a LOSSLESS pick:
    #    the wire hides behind compute, so the accuracy-risk premium keeps
    #    lossy compression out
    item, spec = _item(), _spec()
    auto = AutoStrategy(search=False, hbm_capacity_bytes=1e15,
                        flops_per_step=5e13)
    auto.build(item, spec)
    best1 = auto.last_ranking[0]
    picks["compute_bound"] = best1.label
    assert best1.breakdown.feasible
    assert not any(t in best1.label for t in ("psgd", "int8")), best1.label
    assert best1.breakdown.compute_s > best1.breakdown.allreduce_s

    # 2) activation-dominated model + HBM squeezed between the remat
    #    estimate and every store-all variant -> remat wins the gate
    import jax.numpy as jnp

    def big_batch_loss(p, batch):
        h = jnp.tanh(batch["x"] @ p["w1"])
        return jnp.mean((h @ p["w2"] - batch["y"]) ** 2)

    rng = np.random.RandomState(0)
    act_params = {"w1": jnp.zeros((64, 256), jnp.float32),
                  "w2": jnp.zeros((256, 1), jnp.float32)}
    act_batch = {"x": np.zeros((16384, 64), np.float32),
                 "y": np.zeros((16384, 1), np.float32)}
    act_item = ModelItem(loss_fn=big_batch_loss, optimizer=optax.sgd(0.1),
                         params=act_params,
                         example_batch=act_batch).prepare()
    sim2 = Simulator(act_item, spec)
    remat_hbm = sim2.simulate(
        S.WithRemat(S.AllReduce(chunk_size=512), policy="dots")
        .build(act_item, spec)).breakdown.hbm_bytes
    plain_hbms = [
        sim2.simulate(b.build(act_item, spec)).breakdown.hbm_bytes
        for b in (S.AllReduce(chunk_size=512), S.PartitionedAR(), S.PS())]
    assert remat_hbm < min(plain_hbms)  # activations dominate this model
    squeeze = (remat_hbm + min(plain_hbms)) / 2
    auto2 = AutoStrategy(search=False, hbm_capacity_bytes=squeeze)
    auto2.build(act_item, spec)
    best2 = auto2.last_ranking[0]
    picks["activation_squeeze"] = best2.label
    assert "remat" in best2.label, picks
    assert best2.breakdown.feasible
    assert best2.breakdown.hbm_bytes <= squeeze

    # 3) optimizer-state-heavy model, HBM just above the smallest
    #    estimate -> ZeRO-partitioned storage or host-PS offload wins;
    #    plain AllReduce provably infeasible
    import optax as _o
    from autodist_tpu.model_item import ModelItem as _MI
    adam_item = _MI(loss_fn=item.loss_fn, optimizer=_o.adam(1e-3),
                    params=item.params,
                    example_batch=item.example_batch).prepare()
    sim_a = Simulator(adam_item, spec)
    min_hbm = min(
        sim_a.simulate(b.build(adam_item, spec)).breakdown.hbm_bytes
        for b in (S.PartitionedAR(), S.PS()))
    auto3 = AutoStrategy(search=False, hbm_capacity_bytes=min_hbm * 1.05)
    auto3.build(adam_item, spec)
    best3 = auto3.last_ranking[0]
    picks["opt_heavy_tiny_hbm"] = best3.label
    assert best3.breakdown.feasible
    plain_a = sim_a.simulate(
        S.AllReduce(chunk_size=512).build(adam_item, spec))
    assert plain_a.breakdown.hbm_bytes > min_hbm * 1.05  # plain can't fit
    assert (plan_host_ps(best3.strategy, adam_item.var_infos)
            or best3.label.startswith("Partitioned")), best3.label

    # 4) starved inter-node bandwidth -> aggressive lossy compression is
    #    decisively faster and the premium no longer blocks it
    slow = ResourceSpec.from_dict({
        "nodes": [{"address": "10.0.0.%d" % (i + 1), "tpus": 4,
                   "chief": i == 0, "network_bandwidth": 0.05}
                  for i in range(4)],
        "slice": {"type": "v5e", "ici_bandwidth": 400}})
    auto4 = AutoStrategy(search=False, hbm_capacity_bytes=1e15)
    auto4.build(item, slow)
    best4 = auto4.last_ranking[0]
    picks["slow_net"] = best4.label
    assert any(t in best4.label for t in ("psgd", "int8", "bf16")), picks
    by_label = {r.label: r for r in auto4.last_ranking}
    assert (best4.breakdown.allreduce_s + best4.breakdown.ps_s
            < by_label["AllReduce/512"].breakdown.allreduce_s)

    assert len(set(picks.values())) >= 4, picks
    fams = {family(r) for r in (best1, best2, best3, best4)}
    assert len(fams) >= 3, (picks, fams)


def test_auto_enumerates_tp_candidates_from_mp_rules():
    """A model that registers mp_rules enters the TensorParallel search
    space: TP candidates appear in the ranking, priced by mp_comm_time."""
    import jax.numpy as jnp
    from autodist_tpu.models import tp_lm
    cfg = tp_lm.TPLMConfig(vocab_size=256, d_model=64, num_heads=4,
                           num_layers=2, mlp_dim=128, max_seq_len=32)
    loss_fn, params, batch, _apply = tp_lm.make_train_setup(
        cfg, seq_len=16, batch_size=8)
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1),
                     params=params, example_batch=batch,
                     mp_rules=tp_lm.tp_rules()).prepare()
    spec = _spec()
    auto = AutoStrategy(hbm_capacity_bytes=1e15)
    auto.build(item, spec)
    labels = {r.label for r in auto.last_ranking}
    assert any(l.startswith("TensorParallel/") for l in labels), labels
    tp = [r for r in auto.last_ranking
          if r.label.startswith("TensorParallel/")][0]
    assert tp.breakdown.mp_s > 0  # the TP psums are priced, not free


# --------------------------------------------- PP/EP/SP search (r5)


def test_auto_enumerates_pp_candidates_and_picks_1f1b_under_squeeze():
    """VERDICT-r4 #3: a stacked-blocks model registering pipe rules enters
    the PipelineParallel search space (gpipe AND 1f1b, per its mp_meta);
    under an HBM squeeze between the two schedules' footprints the auto
    pick lands on PP/1f1b, justified by the feasibility gate in its
    CostBreakdown."""
    from autodist_tpu.models import pipe_lm
    from autodist_tpu.models.tp_lm import TPLMConfig
    cfg = TPLMConfig.tiny(num_layers=8, d_model=64, mlp_dim=256)
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=64, batch_size=64, n_microbatches=16)
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-3),
                     params=params, example_batch=batch,
                     mp_rules=pipe_lm.pp_rules(),
                     mp_meta={"pp_microbatches": 16,
                              "pp_schedule": "gpipe",
                              "pp_schedules": ["gpipe", "1f1b"]}).prepare()
    spec = ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 8}],
         "slice": {"type": "v5e", "ici_bandwidth": 400}})

    roomy = AutoStrategy(search=False, hbm_capacity_bytes=1e15)
    roomy.build(item, spec)
    labels = {r.label for r in roomy.last_ranking}
    assert any(l.startswith("PipelineParallel/") and l.endswith("gpipe")
               for l in labels), labels
    assert any(l.endswith("1f1b") for l in labels), labels
    by = {r.label: r for r in roomy.last_ranking}
    g = by["PipelineParallel/8/gpipe"].breakdown.hbm_bytes
    f = by["PipelineParallel/8/1f1b"].breakdown.hbm_bytes
    assert f < g  # the schedule's whole point: S-bounded residency

    # squeeze: cap between the leanest 1f1b candidate and everything else
    f_min = min(r.breakdown.hbm_bytes for r in roomy.last_ranking
                if "1f1b" in r.label)
    others = min(r.breakdown.hbm_bytes for r in roomy.last_ranking
                 if "1f1b" not in r.label)
    assert f_min < others, "1f1b must be the leanest family here"
    cap = (f_min + others) / 2
    tight = AutoStrategy(search=False, hbm_capacity_bytes=cap)
    tight.build(item, spec)
    best = tight.last_ranking[0]
    assert "1f1b" in best.label, [r.label for r in tight.last_ranking[:5]]
    assert best.breakdown.feasible
    # the ADT501 skip dropped every projected-OOM family from the ranking
    tight_labels = {r.label for r in tight.last_ranking}
    assert "PipelineParallel/8/gpipe" not in tight_labels, tight_labels
    assert all(r.breakdown.feasible for r in tight.last_ranking)


def test_auto_enumerates_ep_for_moe_model():
    """A MoE ModelItem (expert-axis rules) enters the ExpertParallel
    space; with slow inter-chip links and an HBM cap that rules out the
    host-PS family's pulled copies, the auto pick IS an EP candidate —
    its expert-sharded stacks sync only the 1/ep local shard over the
    dp complement (the dense families ship every expert's gradient)."""
    from autodist_tpu.models import moe_lm
    cfg = moe_lm.MoEConfig.tiny(num_experts=8, d_model=64, expert_dim=512)
    loss_fn, params, batch, _ = moe_lm.make_train_setup(
        cfg, seq_len=32, batch_size=32)
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-3),
                     params=params, example_batch=batch,
                     mp_rules=moe_lm.ep_rules()).prepare()
    spec = ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 8}],
         "slice": {"type": "v5e", "ici_bandwidth": 1}})
    auto = AutoStrategy(search=False, hbm_capacity_bytes=1e15)
    auto.build(item, spec)
    by = {r.label: r for r in auto.last_ranking}
    assert "ExpertParallel/8" in by, sorted(by)
    # expert-sharded storage undercuts dense replication...
    assert (by["ExpertParallel/8"].breakdown.hbm_bytes
            < by["AllReduce/512"].breakdown.hbm_bytes)
    # ...and its gradient wire is the 1/ep local shard, not the full stack
    assert (by["ExpertParallel/8"].breakdown.allreduce_s
            < 0.2 * by["AllReduce/512"].breakdown.allreduce_s)
    # cap between EP-8 and the PS family's pulled-copy footprint: the
    # feasible set is the storage-sharded families, and EP's lean wire
    # beats ZeRO's full param gather on the slow links
    cap = (by["ExpertParallel/8"].breakdown.hbm_bytes
           + by["PS"].breakdown.hbm_bytes) / 2
    tight = AutoStrategy(search=False, hbm_capacity_bytes=cap)
    tight.build(item, spec)
    best = tight.last_ranking[0]
    assert best.label.startswith("ExpertParallel/"), \
        [r.label for r in tight.last_ranking[:5]]
    assert best.breakdown.feasible
    # PS projects OOM under the cap, so the ADT501 skip drops it outright
    by_t = {r.label: r for r in tight.last_ranking}
    assert "PS" not in by_t, sorted(by_t)


def test_auto_composite_pp_tp_for_big_model_small_hbm():
    """pipe+model rules yield composite PP x TP grids. The regime where
    a composite genuinely wins: long-sequence activations dominate HBM
    (ZeRO's param sharding is beside the point), the 1F1B schedule's S/M
    residency beats pure data parallelism's 1/dp, and the tp dims shave
    the remaining param share below pure-PP — under a cap between the
    composite and pure-PP footprints, only composites are feasible and
    the pick is PPxTP, justified by the HBM gate."""
    from autodist_tpu.models import pipe_lm
    from autodist_tpu.models.tp_lm import TPLMConfig
    cfg = TPLMConfig.tiny(num_layers=8, d_model=256, mlp_dim=1024,
                          num_heads=8, max_seq_len=512)
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=512, batch_size=64, n_microbatches=64,
        model_axis="model", schedule="1f1b")
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-3),
                     params=params, example_batch=batch,
                     mp_rules=pipe_lm.pp_rules(model_axis="model"),
                     mp_meta={"pp_microbatches": 64,
                              "pp_schedule": "1f1b",
                              "pp_schedules": ["1f1b"]}).prepare()
    spec = ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 8}],
         "slice": {"type": "v5e", "ici_bandwidth": 400}})
    auto = AutoStrategy(search=False, hbm_capacity_bytes=1e15)
    auto.build(item, spec)
    by = {r.label: r for r in auto.last_ranking}
    comp = [l for l in by if l.startswith("PP") and "TP" in l]
    assert comp, sorted(by)
    comp_hbm = min(by[l].breakdown.hbm_bytes for l in comp)
    others = min(v.breakdown.hbm_bytes for l, v in by.items()
                 if l not in comp)
    assert comp_hbm < others  # composites are the leanest family here
    cap = (comp_hbm + others) / 2
    tight = AutoStrategy(search=False, hbm_capacity_bytes=cap)
    tight.build(item, spec)
    best = tight.last_ranking[0]
    assert best.label.startswith("PP") and "TP" in best.label, \
        [r.label for r in tight.last_ranking[:5]]
    assert best.breakdown.feasible
    # the gate did the picking: ZeRO and pure-PP project OOM under the
    # cap and the ADT501 skip drops them from the ranking entirely
    tight_labels = {r.label for r in tight.last_ranking}
    assert "PartitionedAR" not in tight_labels, tight_labels
    assert all(r.breakdown.feasible for r in tight.last_ranking)


def test_auto_enumerates_sp_when_model_declares_it():
    """mp_meta['seq_parallel'] puts SequenceParallel candidates in the
    pool (the long-context family has no var rules to detect from)."""
    item = _item()
    item.mp_meta = {"seq_parallel": True, "sp_attention": "ring"}
    spec = _spec()
    auto = AutoStrategy(hbm_capacity_bytes=1e15)
    auto.build(item, spec)
    labels = {r.label for r in auto.last_ranking}
    assert any(l.startswith("SequenceParallel/") for l in labels), labels


def test_dual_class_backward_pricing():
    """VERDICT-r4 #9: the backward collective is priced as its DUAL class
    with the dual's payload (gather <-> scatter, permute/alltoall
    self-dual) — and per class the dual's wire equals the forward's, so
    the fwd+bwd sum reproduces the old 2x shortcut by ALGEBRA, not by
    assertion."""
    from autodist_tpu.simulator.cost_model import collective_wire_bytes
    k, B = 8, 1024.0
    # gather traces one shard B: fwd all_gather moves (k-1)B; the
    # transpose is a reduce_scatter of the FULL kB cotangent
    assert collective_wire_bytes("gather", B, k, "fwd") == (k - 1) * B
    assert (collective_wire_bytes("gather", B, k, "bwd")
            == collective_wire_bytes("scatter", k * B, k, "fwd")
            == pytest.approx((k - 1) * B))
    # scatter traces the full input B: fwd reduce_scatter moves (k-1)/k B;
    # the transpose all_gathers k shards of B/k
    assert (collective_wire_bytes("scatter", B, k, "fwd")
            == pytest.approx((k - 1) / k * B))
    assert (collective_wire_bytes("scatter", B, k, "bwd")
            == collective_wire_bytes("gather", B / k, k, "fwd")
            == pytest.approx((k - 1) / k * B))
    # reduce pairs with its dual layer's psum; permute/alltoall self-dual
    for kind in ("reduce", "permute", "alltoall"):
        assert (collective_wire_bytes(kind, B, k, "bwd")
                == collective_wire_bytes(kind, B, k, "fwd"))


def test_pp_candidate_enumeration_skips_invalid_interleaved_geometry():
    """An interleaved alternate whose M is not divisible by some pp_shards
    (or by a composite's pp) is SKIPPED, not a crash inside
    mp_candidates() before the per-candidate try/except."""
    from autodist_tpu.strategy.auto_strategy import mp_candidates
    from autodist_tpu.models import pipe_lm
    from autodist_tpu.models.tp_lm import TPLMConfig
    cfg = TPLMConfig.tiny(num_layers=8)
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=8, n_microbatches=4)
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1),
                     params=params, example_batch=batch,
                     mp_rules=pipe_lm.pp_rules(model_axis="model"),
                     mp_meta={"pp_microbatches": 4,
                              "pp_schedule": "interleaved",
                              "pp_virtual": 2}).prepare()
    spec = ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 8}]})
    cands = mp_candidates(item, spec)  # must not raise
    labels = [l for l, _ in cands]
    # pp8 x M4 violates M % S == 0: absent, while pp2/pp4 are present
    assert any("PipelineParallel/2/interleaved" == l for l in labels)
    assert not any(l.startswith("PipelineParallel/8/") for l in labels)
    # composites inherit the same guard (PP4 x TP2 ok, PP8 never built)
    assert any(l.startswith("PP4 x TP2") for l in labels), labels
