"""Strategy serialization round-trip (analog of reference ``tests/test_strategy_base.py``)."""
from autodist_tpu.strategy.base import (AllReduceSynchronizer, GraphConfig,
                                        PSSynchronizer, Strategy, VarConfig)


def _sample():
    return Strategy(
        node_config=[
            VarConfig("w", AllReduceSynchronizer(spec="AUTO", compressor="HorovodCompressor", group=1)),
            VarConfig("emb", partitioner="2,1",
                      part_configs=[
                          VarConfig("emb/part_0", PSSynchronizer(reduction_destination="a:CPU:0")),
                          VarConfig("emb/part_1", PSSynchronizer(reduction_destination="b:CPU:0")),
                      ],
                      shard_sizes=[3, 2]),
        ],
        graph_config=GraphConfig(replicas=["a:TPU:0", "a:TPU:1"]))


def test_round_trip(tmp_path):
    s = _sample()
    path = s.serialize(str(tmp_path / "strat"))
    s2 = Strategy.deserialize(path=path)
    assert s2.to_dict() == s.to_dict()
    assert s2.id == s.id


def test_var_config_partition_props():
    s = _sample()
    node = s.find("emb")
    assert node.partition_axis == 0
    assert node.num_shards == 2
    assert s.find("w").num_shards == 1
    assert s.find("missing") is None


def test_nccl_alias_normalizes():
    ar = AllReduceSynchronizer(spec="NCCL")
    assert ar.spec == "ICI"
