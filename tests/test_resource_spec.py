"""ResourceSpec parsing tests (analog of reference ``tests/test_resource_spec.py``)."""
import pytest

from autodist_tpu.resource_spec import DeviceType, ResourceSpec

SPEC_MULTI = """
nodes:
  - address: 10.0.0.1
    tpus: 4
    chief: true
    ssh_config: conf
    network_bandwidth: 100
  - address: 10.0.0.2
    tpus: 4
    ssh_config: conf
ssh:
  conf:
    username: tpu
    key_file: /k
    port: 2222
slice:
  type: v5e-8
  ici_bandwidth: 400
"""

SPEC_CPU_ONLY = """
nodes:
  - address: 127.0.0.1
    cpus: [0, 1]
"""


def _write(tmp_path, text):
    p = tmp_path / "spec.yml"
    p.write_text(text)
    return str(p)


def test_multi_node(tmp_path):
    spec = ResourceSpec(_write(tmp_path, SPEC_MULTI))
    assert spec.num_nodes == 2
    assert spec.chief == "10.0.0.1"
    assert spec.num_tpus == 8
    assert [d.name_string() for d in spec.devices][:2] == ["10.0.0.1:TPU:0", "10.0.0.1:TPU:1"]
    assert spec.network_bandwidth_gbps("10.0.0.1") == 100
    assert spec.network_bandwidth_gbps("10.0.0.2") == 1  # default
    assert spec.ici_bandwidth_gbps() == 400
    conf = spec.ssh_config_map.for_host("10.0.0.2")
    assert conf.username == "tpu" and conf.port == 2222


def test_cpu_only(tmp_path):
    spec = ResourceSpec(_write(tmp_path, SPEC_CPU_ONLY))
    assert spec.num_tpus == 0
    assert len(spec.devices) == 2
    assert spec.devices[0].device_type == DeviceType.CPU
    assert spec.chief == "127.0.0.1"  # single node auto-chief


def test_gpu_synonym(tmp_path):
    spec = ResourceSpec(_write(tmp_path, "nodes:\n  - address: a\n    gpus: 2\n"))
    assert spec.num_tpus == 2


def test_multi_node_requires_chief(tmp_path):
    bad = "nodes:\n  - address: a\n    tpus: 1\n  - address: b\n    tpus: 1\n"
    with pytest.raises(ValueError):
        ResourceSpec(_write(tmp_path, bad))


def test_from_local():
    spec = ResourceSpec.from_local()
    assert spec.is_single_node()
    assert len(spec.devices) >= 1
