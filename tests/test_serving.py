"""Serving subsystem: strategy-compiled batched inference.

The serving tentpole's correctness contracts:

- **bitwise identity**: an engine dispatch on padded requests returns,
  row for row, exactly what the same compiled forward program
  (``DistributedStep.predict_program``) returns on the same padded
  inputs — for a PS-backed AND an AllReduce strategy — with the padded
  rows masked out of the fetches;
- **zero recompiles after warmup**: every bucket compiles once in
  :meth:`InferenceEngine.warmup`; steady-state traffic across mixed
  group sizes never grows the jit cache;
- **shed, never hang**: queue overflow, a closed batcher, and an
  exhausted PS-degradation window all fail with the typed
  :class:`ServingUnavailable` in bounded time, while the worker loop
  survives per-group errors and keeps serving;
- **pad-to-bucket** in ``stack_batches`` (repeat-last padding, caller
  masks) and its multi-process global-array refusal.
"""
import threading
import time
from unittest import mock

import jax
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.data.prefetch import stack_batches
from autodist_tpu.serving import (InferenceEngine, MicroBatcher,
                                  ServingConfig, ServingUnavailable)
from autodist_tpu.telemetry import spans as tel


# ---------------------------------------------------------------- fixture


def _make_problem(seed=0, n=16):
    """Tiny embedding scorer — the recommendation-shaped toy: a request
    is one {"ids": scalar} row of the training batch (labels dropped)."""
    rng = np.random.RandomState(seed)
    params = {"w": rng.randn(4, 2).astype(np.float32),
              "b": np.zeros((2,), np.float32),
              "emb": rng.randn(16, 4).astype(np.float32)}

    def loss_fn(p, batch):
        import jax.numpy as jnp
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        pred = feat @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    def serve_fn(p, batch):
        import jax.numpy as jnp
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        return {"score": feat @ p["w"] + p["b"]}

    batch = {"ids": rng.randint(0, 16, size=(n,)).astype(np.int32),
             "y": rng.randn(n, 2).astype(np.float32)}
    requests = [{"ids": batch["ids"][i]} for i in range(n)]
    return params, loss_fn, serve_fn, batch, requests


def _build_runner(make_builder, train_steps=1):
    params, loss_fn, serve_fn, batch, requests = _make_problem()
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=make_builder())
    runner = ad.build(loss_fn, optax.adam(0.1), params, batch)
    runner.init(params)
    for _ in range(train_steps):
        runner.run(batch)  # serve values that actually moved
    return runner, serve_fn, batch, requests


def _expected_scores(runner, ids):
    """Host-side reference: the CURRENT (trained) full params applied to
    ``ids`` — value-level (allclose) check; bitwise identity is asserted
    program-call-vs-program-call below."""
    full = {k: np.asarray(v) for k, v in runner.gather_params().items()}
    full.update({k: np.asarray(v)
                 for k, v in runner.distributed_step.pull_ps().items()})
    return np.take(full["emb"], np.asarray(ids), axis=0) @ full["w"] \
        + full["b"]


BUILDERS = [("PS", lambda: S.PS()), ("AllReduce", lambda: S.AllReduce())]


# ---------------------------------------------------------- stack_batches


def test_stack_batches_pads_by_repeating_last():
    group = [{"x": np.full((2,), i, np.float32)} for i in range(3)]
    out = stack_batches(group, pad_to=8)
    assert out["x"].shape == (8, 2)
    np.testing.assert_array_equal(out["x"][:3, 0], [0.0, 1.0, 2.0])
    # padded rows repeat the LAST real element — real data, no NaN risk
    np.testing.assert_array_equal(out["x"][3:, 0], [2.0] * 5)
    # pad_to == len is a plain stack
    np.testing.assert_array_equal(stack_batches(group, pad_to=3)["x"],
                                  out["x"][:3])


def test_stack_batches_pad_to_smaller_than_group_raises():
    group = [{"x": np.zeros((2,))} for _ in range(4)]
    with pytest.raises(ValueError, match="pad_to must be >="):
        stack_batches(group, pad_to=2)


def test_stack_batches_refuses_multiprocess_global_arrays():
    """A non-fully-addressable jax.Array cannot be re-stacked process-
    locally; the error must say what to do, not bubble jnp.stack's."""
    leaf = mock.MagicMock(spec=jax.Array)
    leaf.is_fully_addressable = False
    with pytest.raises(ValueError,
                       match="multi-process global arrays"):
        stack_batches([{"x": leaf}, {"x": leaf}])


# ----------------------------------------------------------------- engine


@pytest.mark.parametrize("name,make_builder", BUILDERS,
                         ids=[b[0] for b in BUILDERS])
def test_engine_bitwise_identity_and_zero_recompiles(name, make_builder):
    """The two acceptance criteria in one build: (a) after warming both
    buckets, mixed-size traffic performs ZERO recompiles; (b) every
    served row is bitwise identical to the same compiled program called
    directly on the same padded inputs, padding masked out."""
    runner, serve_fn, batch, requests = _build_runner(make_builder)
    engine = InferenceEngine(
        runner, serve_fn, requests[0],
        ServingConfig(buckets=(8, 16), snapshot_max_age_s=0.0)).warmup()
    dstep = runner.distributed_step
    # predict_program caches per (serve_fn, donate, structure): the
    # engine's own program comes back — identical executable, not merely
    # an equivalent one
    program = dstep.predict_program(
        serve_fn, donate_batch=True,
        example_batch=stack_batches([requests[0]], pad_to=8))
    for n in (3, 8, 11, 16):
        got, n_out = engine.run_batch(requests[:n])
        assert n_out == n
        assert got["score"].shape == (n, 2)
        bucket = engine.bucket_for(n)
        host = stack_batches(requests[:n], pad_to=bucket)
        placed = runner.remapper.remap_feed(host)
        direct = runner.remapper.remap_fetch(
            program(runner.state, dstep.pull_ps(), placed))
        # bitwise, not allclose: same executable, same inputs
        np.testing.assert_array_equal(got["score"],
                                      np.asarray(direct["score"])[:n])
        np.testing.assert_allclose(
            got["score"], _expected_scores(runner, host["ids"][:n]),
            rtol=1e-5, atol=1e-6)
    assert engine.recompiles_after_warmup() == 0
    assert engine.stats["padded_rows"] == (8 - 3) + (16 - 11)
    # per-request convenience fans out one tree per request
    rows = engine.predict(requests[:3])
    assert len(rows) == 3
    np.testing.assert_array_equal(
        np.stack([r["score"] for r in rows]),
        engine.run_batch(requests[:3])[0]["score"])


def test_bucket_validation_and_selection():
    runner, serve_fn, _, requests = _build_runner(lambda: S.AllReduce())
    replicas = runner.remapper.num_replicas
    # defaults round up to replica multiples
    engine = InferenceEngine(runner, serve_fn, requests[0])
    assert all(b % replicas == 0 for b in engine.buckets)
    assert engine.buckets == tuple(sorted(engine.buckets))
    eng = InferenceEngine(runner, serve_fn, requests[0],
                          ServingConfig(buckets=(8, 16)))
    assert eng.bucket_for(1) == 8 and eng.bucket_for(9) == 16
    with pytest.raises(ServingUnavailable, match="largest bucket"):
        eng.bucket_for(17)
    with pytest.raises(ValueError, match="not multiples"):
        InferenceEngine(runner, serve_fn, requests[0],
                        ServingConfig(buckets=(replicas + 1,)))
    with pytest.raises(ValueError, match="duplicate"):
        InferenceEngine(runner, serve_fn, requests[0],
                        ServingConfig(buckets=(8, 8)))
    with pytest.raises(ValueError):
        ServingConfig(max_delay_ms=-1)
    with pytest.raises(ValueError):
        ServingConfig(max_queue=0)


def test_engine_degraded_window_then_shed_then_recovery(monkeypatch):
    """The PR 1 staleness-window contract on the serving side: snapshot
    refresh failures serve the LAST good snapshot for ``degraded_batches``
    batches (counted), then shed with the typed error; a successful
    refresh resets the window."""
    runner, serve_fn, _, requests = _build_runner(lambda: S.PS())
    engine = InferenceEngine(
        runner, serve_fn, requests[0],
        ServingConfig(buckets=(8,), snapshot_max_age_s=0.0,
                      degraded_batches=2)).warmup()
    good, _ = engine.run_batch(requests[:4])
    dstep = runner.distributed_step
    real_pull = dstep.pull_ps

    def failing_pull():
        raise OSError("coordination service unreachable")

    c0 = tel.counters()["serve.degraded"]
    monkeypatch.setattr(dstep, "pull_ps", failing_pull)
    for i in (1, 2):  # inside the window: serve the last snapshot
        degraded, _ = engine.run_batch(requests[:4])
        np.testing.assert_array_equal(degraded["score"], good["score"])
        assert engine.stats["degraded"] == i
    assert tel.counters()["serve.degraded"] == c0 + 2
    with pytest.raises(ServingUnavailable, match="degraded window"):
        engine.run_batch(requests[:4])
    # the engine object survives the shed: recovery resets the window
    monkeypatch.setattr(dstep, "pull_ps", real_pull)
    recovered, _ = engine.run_batch(requests[:4])
    np.testing.assert_array_equal(recovered["score"], good["score"])
    assert engine._degraded_used == 0


def test_engine_requires_initialized_runner():
    params, loss_fn, serve_fn, batch, requests = _make_problem()
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.adam(0.1), params, batch)
    engine = InferenceEngine(runner, serve_fn, requests[0],
                             ServingConfig(buckets=(8,)))
    with pytest.raises(RuntimeError, match="uninitialized"):
        engine.run_batch(requests[:2])


# ------------------------------------------------------------ microbatcher


def test_microbatcher_fans_out_per_request():
    """Concurrent submits group into padded buckets and fan back out:
    every caller gets ITS row, latency histogram + counters account every
    request."""
    runner, serve_fn, batch, requests = _build_runner(lambda: S.PS())
    engine = InferenceEngine(
        runner, serve_fn, requests[0],
        ServingConfig(buckets=(8, 16), max_delay_ms=20.0)).warmup()
    with MicroBatcher(engine) as mb:
        futures = [(r, mb.submit(r)) for r in requests[:12]]
        for r, f in futures:
            row = f.result(timeout=30)
            assert row["score"].shape == (2,)
            np.testing.assert_allclose(
                row["score"], _expected_scores(runner, r["ids"]),
                rtol=1e-5, atol=1e-6)
        one = mb.predict_one(requests[0], timeout=30)
        np.testing.assert_allclose(
            one["score"], _expected_scores(runner, requests[0]["ids"]),
            rtol=1e-5, atol=1e-6)
        stats = mb.stats()
    assert stats["requests"] == 13 and stats["fan_out"] == 13
    assert stats["errors"] == 0 and stats["shed"] == 0
    assert stats["recompiles_after_warmup"] == 0
    # grouped dispatches, not 13 size-1 batches (20ms deadline, 12
    # requests enqueued before the worker wakes)
    assert stats["batches"] < 13
    assert stats["p50_ms"] is not None and stats["p99_ms"] is not None
    assert stats["p99_ms"] >= stats["p50_ms"]


def test_microbatcher_sheds_on_queue_full_and_close(monkeypatch):
    runner, serve_fn, _, requests = _build_runner(lambda: S.PS())
    engine = InferenceEngine(
        runner, serve_fn, requests[0],
        ServingConfig(buckets=(8,), max_queue=2)).warmup()
    release = threading.Event()
    real_run = engine.run_batch

    def slow_run(reqs):
        release.wait(timeout=30)
        return real_run(reqs)

    monkeypatch.setattr(engine, "run_batch", slow_run)
    mb = MicroBatcher(engine)
    try:
        first = mb.submit(requests[0])  # consumed by the (blocked) worker
        time.sleep(0.1)
        queued = [mb.submit(r) for r in requests[1:3]]  # fills the queue
        with pytest.raises(ServingUnavailable, match="queue full"):
            mb.submit(requests[3])
        assert mb.stats()["shed"] == 1
    finally:
        release.set()
    first.result(timeout=30)
    for f in queued:
        f.result(timeout=30)
    mb.close()
    with pytest.raises(ServingUnavailable, match="closed"):
        mb.submit(requests[0])


def test_microbatcher_close_fails_still_queued_futures(monkeypatch):
    runner, serve_fn, _, requests = _build_runner(lambda: S.PS())
    engine = InferenceEngine(
        runner, serve_fn, requests[0],
        ServingConfig(buckets=(8,))).warmup()
    hold = threading.Event()
    real_run = engine.run_batch
    monkeypatch.setattr(
        engine, "run_batch",
        lambda reqs: (hold.wait(timeout=30), real_run(reqs))[1])
    mb = MicroBatcher(engine)
    mb.submit(requests[0])
    time.sleep(0.1)
    straggler = mb.submit(requests[1])

    def unblock():
        time.sleep(0.3)
        hold.set()
    threading.Thread(target=unblock, daemon=True).start()
    mb.close()
    # whatever close could not drain carries the typed shed, not a hang
    if not straggler.done():
        straggler.result(timeout=1)
    else:
        exc = straggler.exception(timeout=1)
        assert exc is None or isinstance(exc, ServingUnavailable)


def test_microbatcher_survives_group_errors_and_typed_sheds(monkeypatch):
    """A malformed request fails ITS group's futures with the real error;
    a ServingUnavailable from the engine (degradation exhausted) sheds
    the group; the worker keeps serving afterwards in both cases."""
    runner, serve_fn, _, requests = _build_runner(lambda: S.PS())
    engine = InferenceEngine(
        runner, serve_fn, requests[0],
        ServingConfig(buckets=(8,), max_delay_ms=1.0)).warmup()
    with MicroBatcher(engine) as mb:
        bad = mb.submit({"ids": np.zeros((3, 3), np.float32)})  # bad tree
        with pytest.raises(Exception) as ei:
            bad.result(timeout=30)
        assert not isinstance(ei.value, ServingUnavailable)
        real_run = engine.run_batch
        monkeypatch.setattr(
            engine, "run_batch",
            mock.MagicMock(side_effect=ServingUnavailable("window out")))
        shed = mb.submit(requests[0])
        with pytest.raises(ServingUnavailable):
            shed.result(timeout=30)
        monkeypatch.setattr(engine, "run_batch", real_run)
        good = mb.submit(requests[1])  # the worker thread is still alive
        np.testing.assert_allclose(
            good.result(timeout=30)["score"],
            _expected_scores(runner, requests[1]["ids"]),
            rtol=1e-5, atol=1e-6)
        stats = mb.stats()
        assert stats["errors"] == 1 and stats["shed"] >= 1


# -------------------------------------------------- runner predict / eval


def test_runner_predict_named_fetches_match_reference():
    runner, serve_fn, batch, requests = _build_runner(lambda: S.PS())
    feats = {"ids": batch["ids"]}
    out = runner.predict(feats, serve_fn)
    assert set(out) == {"score"}
    assert out["score"].shape == (16, 2)
    np.testing.assert_allclose(out["score"],
                               _expected_scores(runner, batch["ids"]),
                               rtol=1e-5, atol=1e-6)
    # snapshot reuse path (the caller-loop contract evaluate also uses)
    snap = runner.distributed_step.pull_ps()
    again = runner.predict(feats, serve_fn, ps_vals=snap)
    np.testing.assert_array_equal(np.asarray(out["score"]),
                                  np.asarray(again["score"]))


def test_evaluate_weights_scalars_by_example_count():
    """The mean-of-means fix: a ragged final batch contributes by its
    example count, not as a full batch's worth of mean."""
    runner, serve_fn, batch, _ = _build_runner(lambda: S.PS(),
                                               train_steps=0)
    rng = np.random.RandomState(7)
    big = {"ids": rng.randint(0, 16, size=(16,)).astype(np.int32),
           "y": rng.randn(16, 2).astype(np.float32)}
    small = {"ids": rng.randint(0, 16, size=(8,)).astype(np.int32),
             "y": 10.0 + rng.randn(8, 2).astype(np.float32)}
    loss_big = runner.evaluate([big])["loss"]
    loss_small = runner.evaluate([small])["loss"]
    combined = runner.evaluate([big, small])["loss"]
    weighted = (16 * loss_big + 8 * loss_small) / 24
    naive = (loss_big + loss_small) / 2
    np.testing.assert_allclose(combined, weighted, rtol=1e-6)
    assert abs(combined - naive) > 1e-3  # the bias the fix removes


# ----------------------------------------------- batcher degradation paths
# (pure-python fake engine: these contracts are the BATCHER's — queue
# accounting, Retry-After population, deadlines, brownout — and must be
# testable without compiling a program)


class _FakeEngine:
    """Minimal engine surface the MicroBatcher consumes. ``block`` (a
    threading.Event) parks the FIRST dispatch until set, so tests can
    pile up a queue behind a busy worker deterministically."""

    def __init__(self, config=None, block=None):
        self.config = config or ServingConfig(buckets=(8,),
                                              max_delay_ms=0.0)
        self.max_batch = 8
        self.buckets = (8,)
        self.stats = {"padded_rows": 0}
        self._block = block

    def run_batch(self, requests):
        if self._block is not None:
            self._block.wait(timeout=30)
        return list(requests), len(requests)

    def fan_out(self, fetched, n):
        return fetched

    def recompiles_after_warmup(self):
        return 0


def _gauge():
    return tel.gauges().get("serve.queue_depth")


def test_queue_depth_gauge_fresh_after_traffic_stops():
    """Regression: the gauge was only written on submit(), so it read
    stale-high forever once traffic stopped. The worker loop now writes
    it after EVERY wakeup, so an idle tier reads 0."""
    mb = MicroBatcher(_FakeEngine())
    futs = [mb.submit({"x": i}) for i in range(6)]
    for f in futs:
        f.result(timeout=5)
    deadline = time.perf_counter() + 5
    while _gauge() != 0 and time.perf_counter() < deadline:
        time.sleep(0.005)
    assert _gauge() == 0
    mb.close()


def test_queue_depth_gauge_zero_after_drain():
    block = threading.Event()
    mb = MicroBatcher(_FakeEngine(block=block))
    mb.submit({"x": 0})              # in-flight, parked
    time.sleep(0.05)
    for i in range(4):
        mb.submit({"x": i})          # queued behind the parked worker
    assert _gauge() >= 1
    threading.Timer(0.1, block.set).start()
    mb.drain(timeout=10)
    assert _gauge() == 0


def test_queue_full_shed_carries_computed_clamped_retry_after():
    """Regression: queue-full sheds raised with retry_after_s=None.
    Every shed now carries a populated hint — the drain knob before any
    measurement exists, the measured drain-rate estimate after."""
    block = threading.Event()
    mb = MicroBatcher(_FakeEngine(block=block), max_queue=2)
    mb.submit({"x": 0})              # in-flight, parked
    time.sleep(0.05)
    mb.submit({"x": 1})
    mb.submit({"x": 2})              # queue now at max_queue
    with pytest.raises(ServingUnavailable) as ei:
        mb.submit({"x": 3})
    # no group has completed yet: the knob is the honest fallback
    assert ei.value.retry_after_s == pytest.approx(5.0)
    block.set()
    deadline = time.perf_counter() + 5
    while mb._drain_rate is None and time.perf_counter() < deadline:
        time.sleep(0.005)
    # measured now: still populated, and clamped to the sane band
    retry = mb._computed_retry_after(depth=4)
    assert retry is not None and 0.05 <= retry <= 60.0
    mb.close()


def test_closed_batcher_shed_carries_retry_after():
    """Regression: a submit against a plainly closed (not draining)
    batcher shed with retry_after_s=None."""
    mb = MicroBatcher(_FakeEngine())
    mb.close()
    with pytest.raises(ServingUnavailable) as ei:
        mb.submit({"x": 0})
    assert ei.value.retry_after_s == pytest.approx(5.0)


def test_close_while_queued_sheds_with_retry_after():
    """Close with the worker wedged mid-dispatch: whatever is still
    queued when the join times out sheds typed WITH a Retry-After (the
    regression: it shed with None)."""
    block = threading.Event()
    mb = MicroBatcher(_FakeEngine(block=block))
    f0 = mb.submit({"x": 0})         # in-flight, parked for the whole close
    time.sleep(0.05)
    queued = [mb.submit({"x": i}) for i in range(2)]
    mb.close(timeout=0.2)            # join times out; queue must shed
    for f in queued:
        with pytest.raises(ServingUnavailable) as ei:
            f.result(timeout=5)
        assert ei.value.retry_after_s == pytest.approx(5.0)
    block.set()                      # release the worker; in-flight lands
    f0.result(timeout=5)


def test_expired_deadline_sheds_before_dispatch():
    """A request whose deadline passed while it queued is shed at group
    time — typed, with a populated Retry-After — instead of consuming a
    dispatch slot."""
    block = threading.Event()
    mb = MicroBatcher(_FakeEngine(block=block))
    before = tel.counters().get("serve.deadline_shed", 0.0)
    mb.submit({"x": 0})              # in-flight, parked
    time.sleep(0.05)
    doomed = mb.submit({"x": 1}, deadline_s=0.01)
    alive = mb.submit({"x": 2})
    time.sleep(0.05)                 # the deadline lapses in queue
    block.set()
    assert alive.result(timeout=5) == {"x": 2}
    with pytest.raises(ServingUnavailable) as ei:
        doomed.result(timeout=5)
    assert ei.value.retry_after_s is not None
    assert mb.stats_local["deadline_shed"] == 1
    assert tel.counters()["serve.deadline_shed"] == before + 1
    mb.close()


def test_brownout_widens_group_deadline_under_sustained_overload():
    block = threading.Event()
    cfg = ServingConfig(buckets=(8,), max_delay_ms=1.0, max_queue=8,
                        brownout_queue_frac=0.5, brownout_sustain_s=0.0,
                        brownout_delay_factor=3.0)
    mb = MicroBatcher(_FakeEngine(config=cfg, block=block))
    mb.submit({"x": 0})              # in-flight, parked
    time.sleep(0.05)
    for i in range(6):               # queue past frac*max_queue, twice
        mb.submit({"x": i})          # observed (arm, then enter)
    assert mb.stats()["brownout"] == {"active": True, "entries": 1}
    assert mb._effective_delay_s == pytest.approx(3.0 * mb.max_delay_s)
    tel_entries = tel.counters().get("serve.brownouts", 0.0)
    assert tel_entries >= 1
    block.set()
    # backlog recedes: the worker loop exits brownout at half the entry
    # threshold and restores the configured deadline
    deadline = time.perf_counter() + 5
    while (mb.stats()["brownout"]["active"]
           and time.perf_counter() < deadline):
        time.sleep(0.005)
    assert mb.stats()["brownout"]["active"] is False
    assert mb._effective_delay_s == pytest.approx(mb.max_delay_s)
    mb.close()


def test_stats_autoscale_subdict_stable_keys():
    """The autoscale sub-dict rides stats() with stable keys whether or
    not a controller runs in this process (pre-registered counters)."""
    mb = MicroBatcher(_FakeEngine())
    sub = mb.stats()["autoscale"]
    assert set(sub) == {"grows", "shrinks", "holds", "refusals"}
    mb.close()


def test_brownout_config_validation():
    with pytest.raises(ValueError, match="brownout_queue_frac"):
        ServingConfig(brownout_queue_frac=0.0)
    with pytest.raises(ValueError, match="brownout_delay_factor"):
        ServingConfig(brownout_delay_factor=0.5)
