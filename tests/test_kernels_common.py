"""kernel.common utilities tests (analog of reference
``tests/test_kernels/test_common/test_utils.py``)."""
import jax
import jax.numpy as jnp

from autodist_tpu.kernel.common import op_info, utils
from autodist_tpu.kernel.common.proxy_variable import ProxyVariable
from autodist_tpu.kernel.partitioner import VarLayout
from autodist_tpu.utils import network


def _jaxpr():
    def f(w, x, ids):
        e = jnp.take(w, ids, axis=0)
        h = jnp.tanh(e @ x)
        return jnp.sum(h)
    return jax.make_jaxpr(f)(jnp.ones((8, 4)), jnp.ones((4, 2)),
                             jnp.zeros((3,), jnp.int32)).jaxpr


def test_find_primitives():
    jaxpr = _jaxpr()
    gathers = utils.find_primitives(jaxpr, op_info.INDEXED_READ_PRIMITIVES)
    assert len(gathers) == 1
    dots = utils.find_primitives(jaxpr, {"dot_general"})
    assert len(dots) == 1


def test_consumers_and_ancestors():
    jaxpr = _jaxpr()
    w = jaxpr.invars[0]
    cons = utils.consumers(jaxpr, w)
    # jnp.take wraps its gather in an inner jit; the top-level consumer is
    # that wrapper eqn
    assert cons and cons[0].primitive.name in ("gather", "jit", "pjit")
    out = jaxpr.outvars[0]
    anc = utils.get_ancestors(jaxpr, out)
    assert w in anc and jaxpr.invars[1] in anc


def test_control_flow_detection():
    def f(x):
        return jax.lax.fori_loop(0, 3, lambda i, a: a * 2.0, x)
    jaxpr = jax.make_jaxpr(f)(1.0).jaxpr
    assert utils.uses_control_flow(jaxpr)
    assert not utils.uses_control_flow(_jaxpr())


def test_flops_estimate_positive():
    assert utils.count_flops_estimate(_jaxpr()) > 0


def test_proxy_plan():
    """local_replication (the reference's proxy) decides device-cached vs
    host-PS-resident — for partitioned and unpartitioned vars alike."""
    from autodist_tpu.strategy.base import PSSynchronizer as PSConfig
    part = VarLayout(name="v", partitioned=True, axis=0, num_shards=2,
                     orig_dim=8, padded_dim=8)
    rep = VarLayout(name="v")
    proxied = PSConfig(local_replication=True)
    resident = PSConfig(local_replication=False)
    for lay in (part, rep):
        assert ProxyVariable.plan("v", proxied, lay).cached is True
        assert ProxyVariable.plan("v", resident, lay).cached is False


def test_network_utils():
    assert network.is_loopback_address("127.0.0.1:TPU:0")
    assert network.is_local_address("localhost")
    assert not network.is_loopback_address("10.0.0.1")
