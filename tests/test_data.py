"""Native data loader + device prefetch tests.

Covers the ADT1 writer/reader round-trip, deterministic shuffling across
threads, epoch permutation semantics, the zero-copy mode's validity
window, and DevicePrefetcher equivalence with direct feeding.
"""
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.data import DevicePrefetcher, RecordFileDataset, RecordFileWriter

N, BATCH = 24, 4


@pytest.fixture
def record_file(tmp_path):
    path = str(tmp_path / "train.adt")
    with RecordFileWriter(path, fields=[("x", np.float32, (3, 2)),
                                        ("y", np.int32, ())]) as w:
        for i in range(N):
            w.write({"x": np.full((3, 2), i, np.float32),
                     "y": np.int32(i)})
    return path


def _epoch_ids(ds):
    ids = []
    for _ in range(ds.batches_per_epoch):
        ids.extend(next(ds)["y"].tolist())
    return ids


def test_roundtrip_ordered(record_file):
    with RecordFileDataset(record_file, BATCH, shuffle=False) as ds:
        assert ds.num_records == N
        assert ds.batches_per_epoch == N // BATCH
        b = next(ds)
        assert b["x"].shape == (BATCH, 3, 2) and b["y"].shape == (BATCH,)
        assert b["y"].tolist() == [0, 1, 2, 3]
        np.testing.assert_array_equal(b["x"][2], np.full((3, 2), 2))
        # the rest of epoch 1 continues in order; epoch 2 repeats it
        rest = _epoch_ids(ds)  # reads batches_per_epoch more batches
        assert rest == list(range(BATCH, N)) + [0, 1, 2, 3]
        assert next(ds)["y"].tolist() == [4, 5, 6, 7]


def test_shuffle_is_epoch_permutation_and_seed_deterministic(record_file):
    with RecordFileDataset(record_file, BATCH, seed=7) as a, \
         RecordFileDataset(record_file, BATCH, seed=7, num_threads=4,
                           ring_slots=3) as b:
        ep_a1, ep_a2 = _epoch_ids(a), _epoch_ids(a)
        ep_b1, ep_b2 = _epoch_ids(b), _epoch_ids(b)
        # same seed -> identical stream, regardless of thread/ring config
        assert ep_a1 == ep_b1 and ep_a2 == ep_b2
        # each epoch is a full permutation, and epochs differ
        assert sorted(ep_a1) == list(range(N)) == sorted(ep_a2)
        assert ep_a1 != ep_a2 and ep_a1 != list(range(N))
    with RecordFileDataset(record_file, BATCH, seed=8) as c:
        assert _epoch_ids(c) != ep_a1


def test_drop_remainder(tmp_path):
    path = str(tmp_path / "odd.adt")
    with RecordFileWriter(path, fields=[("y", np.int64, ())]) as w:
        for i in range(10):
            w.write({"y": np.int64(i)})
    with RecordFileDataset(path, 4, shuffle=False) as ds:
        assert ds.batches_per_epoch == 2
        assert next(ds)["y"].tolist() == [0, 1, 2, 3]
        assert next(ds)["y"].tolist() == [4, 5, 6, 7]
        # records 8,9 dropped; next epoch restarts
        assert next(ds)["y"].tolist() == [0, 1, 2, 3]


def test_copy_false_views_are_transient(record_file):
    with RecordFileDataset(record_file, BATCH, shuffle=False,
                           copy=False) as ds:
        b1 = next(ds)
        first = b1["y"].copy()
        next(ds)  # releases b1's slot; b1's views may now be rewritten
        assert first.tolist() == [0, 1, 2, 3]
    with RecordFileDataset(record_file, BATCH, shuffle=False, copy=True) as ds:
        b1 = next(ds)
        next(ds)
        assert b1["y"].tolist() == [0, 1, 2, 3]  # owning copy survives


def test_writer_shape_validation(tmp_path):
    w = RecordFileWriter(str(tmp_path / "bad.adt"),
                         fields=[("x", np.float32, (2,))])
    with pytest.raises(ValueError, match="shape"):
        w.write({"x": np.zeros((3,), np.float32)})
    w.close()


def test_prefetcher_matches_direct_feed(record_file):
    gb = 8  # global batch: divisible by the 8-device test mesh
    loss = lambda p, b: ((b["x"].reshape(b["x"].shape[0], -1)  # noqa: E731
                          @ p["w"]).mean() - b["y"].mean()) ** 2
    import jax.numpy as jnp

    def run(use_prefetch):
        autodist_tpu.reset()
        ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
        params = {"w": jnp.ones((6, 1))}
        with RecordFileDataset(record_file, gb, shuffle=False) as ex_ds:
            example = next(ex_ds)
        runner = ad.build(loss, optax.sgd(0.01), params, example)
        runner.init(params)
        losses = []
        with RecordFileDataset(record_file, gb, seed=3) as ds:
            if use_prefetch:
                for b in DevicePrefetcher(ds, runner, depth=2).take(12):
                    losses.append(float(runner.run(b)["loss"]))
            else:
                for _ in range(12):
                    losses.append(float(runner.run(next(ds))["loss"]))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6)


def test_prefetcher_depth_validation():
    with pytest.raises(ValueError):
        DevicePrefetcher([], lambda b: b, depth=0)
    # finite iterable drains cleanly
    out = list(DevicePrefetcher([1, 2, 3], lambda b: b * 10, depth=2))
    assert out == [10, 20, 30]


def test_sharded_loader_partitions_disjointly(record_file):
    """shard=(i, k) loaders cover disjoint strided record subsets whose
    union is the whole file; shuffling stays within the shard; epochs
    are deterministic per (seed, shard)."""
    seen = {}
    for i in (0, 1, 2):
        ds = RecordFileDataset(record_file, batch_size=4, shuffle=True,
                               seed=7, shard=(i, 3))
        it = iter(ds)
        assert ds.num_records == 8  # 24 records / 3 shards
        assert ds.num_records_global == N  # whole-file count, shard-invariant
        ids = []
        for _ in range(ds.batches_per_epoch):
            ids.extend(next(it)["y"].tolist())
        seen[i] = set(ids)
        assert seen[i] == {r for r in range(N) if r % 3 == i}
        ds.close()
    assert seen[0] | seen[1] | seen[2] == set(range(N))
    # deterministic: same (seed, shard) -> same stream
    a = RecordFileDataset(record_file, batch_size=4, shuffle=True, seed=7,
                          shard=(1, 3))
    b = RecordFileDataset(record_file, batch_size=4, shuffle=True, seed=7,
                          shard=(1, 3))
    ia, ib = iter(a), iter(b)
    for _ in range(4):
        np.testing.assert_array_equal(next(ia)["y"], next(ib)["y"])
    a.close(), b.close()


def test_sharded_loader_rejects_bad_shard(record_file):
    with pytest.raises(ValueError):
        RecordFileDataset(record_file, batch_size=4, shard=(3, 3))
    with pytest.raises(ValueError):
        # 24/5 = 4 records in shard 4 < batch 8
        RecordFileDataset(record_file, batch_size=8, shard=(4, 5))
