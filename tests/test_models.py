"""Model-zoo × strategy coverage matrix (tiny configs).

The analog of reference ``tests/integration/test_all.py``'s model cases
c1/c2/c5/c6: each model family trains end-to-end on the 8-device mesh under
representative strategies, with sparse-embedding detection checked where
embeddings exist.
"""
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.model_item import ModelItem
from autodist_tpu.models import bert, cnn, lm, ncf, resnet

CASES = [
    ("resnet_tiny_ar", lambda: resnet.make_train_setup(
        resnet.ResNetTiny, num_classes=10, image_size=32, batch_size=16,
        dtype=jnp.float32), S.AllReduce),
    ("vgg_tiny_ar", lambda: resnet.make_train_setup(
        cnn.VGGTiny, num_classes=10, image_size=32, batch_size=16,
        dtype=jnp.float32), S.AllReduce),
    ("inception_tiny_ps", lambda: resnet.make_train_setup(
        cnn.InceptionTiny, num_classes=10, image_size=75, batch_size=16,
        dtype=jnp.float32), S.PSLoadBalancing),
    ("densenet_tiny_ar", lambda: resnet.make_train_setup(
        cnn.DenseNetTiny, num_classes=10, image_size=32, batch_size=16,
        dtype=jnp.float32), S.AllReduce),
    ("bert_tiny_parallax", lambda: bert.make_train_setup(
        bert.BertConfig.tiny(), seq_len=32, batch_size=16), S.Parallax),
    ("lm_tiny_partitioned_ps", lambda: lm.make_train_setup(
        lm.LMConfig.tiny(), seq_len=32, batch_size=16), S.PartitionedPS),
    ("ncf_tiny_ps_lb", lambda: ncf.make_train_setup(
        ncf.NCFConfig.tiny(), batch_size=32), S.PSLoadBalancing),
]


@pytest.mark.parametrize("name,setup,builder", CASES, ids=[c[0] for c in CASES])
def test_model_trains(name, setup, builder):
    loss_fn, params, batch, _apply = setup()
    ad = autodist_tpu.AutoDist(strategy_builder=builder())
    step = ad.function(loss_fn, optimizer=optax.adam(1e-3), params=params)
    losses = [step(batch)["loss"] for _ in range(5)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
    autodist_tpu.reset()


@pytest.mark.slow  # pallas interpret mode: ~30s on CPU; nightly runs it
def test_lm_flash_attention_mode_matches_default():
    """attention="flash" (interpreted on CPU) must train and agree with the
    XLA path — the kernel is numerics-preserving, not an approximation."""
    losses = {}
    for mode in ("flash", "default"):
        autodist_tpu.reset()
        loss_fn, params, batch, _ = lm.make_train_setup(
            lm.LMConfig.tiny(), seq_len=32, batch_size=8, attention=mode)
        ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
        step = ad.function(loss_fn, optimizer=optax.adam(1e-3), params=params)
        losses[mode] = [float(step(batch)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(losses["flash"], losses["default"],
                               rtol=1e-4, atol=1e-5)
    assert losses["flash"][-1] < losses["flash"][0]


def test_bert_embeddings_detected_sparse():
    loss_fn, params, batch, _ = bert.make_train_setup(
        bert.BertConfig.tiny(), seq_len=16, batch_size=8)
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1), params=params,
                     example_batch=batch).prepare()
    sparse = set(item.sparse_var_names)
    assert any("word_embeddings" in n for n in sparse), sparse
    assert any("position_embeddings" in n for n in sparse), sparse


def test_ncf_embeddings_detected_sparse():
    loss_fn, params, batch, _ = ncf.make_train_setup(ncf.NCFConfig.tiny(),
                                                     batch_size=8)
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1), params=params,
                     example_batch=batch).prepare()
    sparse = set(item.sparse_var_names)
    assert sum("embedding" in n for n in sparse) == 4, sparse


def test_registry():
    from autodist_tpu.models import make_train_setup
    with pytest.raises(ValueError):
        make_train_setup("nope")


@pytest.mark.slow  # pallas interpret mode: ~30s on CPU; nightly runs it
def test_bert_flash_attention_matches_xla():
    """BERT with the flash kernel (padding mask as segment ids) computes
    the same loss and grads as the XLA attention path on real-token
    positions — MLM weights only cover real tokens, so trajectories
    match."""
    import jax
    from autodist_tpu.models import bert
    cfg = bert.BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                          num_heads=2, mlp_dim=64, max_position=128)
    lf_f, pf, batch, _ = bert.make_train_setup(cfg, seq_len=128,
                                               batch_size=2,
                                               attention="flash")
    lf_x, px, _, _ = bert.make_train_setup(cfg, seq_len=128, batch_size=2,
                                           attention="xla")
    # same init (same seed) and a REAL padding pattern
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), pf, px)
    batch = dict(batch)
    mask = np.ones((2, 128), np.int32)
    mask[:, 96:] = 0  # last quarter is padding
    batch["attention_mask"] = mask
    batch["mlm_weights"] = batch["mlm_weights"] * mask  # loss on real tokens
    lf = float(lf_f(pf, batch))
    lx = float(lf_x(px, batch))
    np.testing.assert_allclose(lf, lx, rtol=2e-5, atol=2e-5)
    gf = jax.grad(lf_f)(pf, batch)
    gx = jax.grad(lf_x)(px, batch)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-3, atol=2e-4),
        gf, gx)
