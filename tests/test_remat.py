"""Gradient rematerialization (strategy.WithRemat / graph_config.remat).

Remat must (a) change the lowered program — the backward recomputes
forward contractions instead of reading stored activations — while (b)
computing bit-identical gradients, and (c) ride the serialized strategy
like every other field so workers lower the same program.
"""
import re

import numpy as np
import jax.numpy as jnp
import optax
import pytest

import autodist_tpu as adt
from autodist_tpu import strategy


def _mlp(seed=0, depth=4, width=32):
    rng = np.random.RandomState(seed)
    params = {"w%d" % i: jnp.asarray(rng.randn(width, width) * 0.3,
                                     jnp.float32) for i in range(depth)}

    def loss_fn(p, batch):
        h = batch["x"]
        for i in range(depth):
            h = jnp.tanh(h @ p["w%d" % i])
        return jnp.mean((h - batch["y"]) ** 2)

    batch = {"x": rng.randn(16, width).astype(np.float32),
             "y": rng.randn(16, width).astype(np.float32)}
    return params, loss_fn, batch


def _lowered_and_losses(builder, n_steps=3):
    params, loss_fn, batch = _mlp()
    ad = adt.AutoDist(strategy_builder=builder)
    runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
    runner.init(params)
    hlo = runner.distributed_step.lowered_text(
        runner.state, runner.remapper.remap_feed(batch))
    losses = [float(runner.run(batch)["loss"]) for _ in range(n_steps)]
    gathered = {k: np.asarray(v) for k, v in runner.gather_params().items()}
    remat = runner.distributed_step.strategy.graph_config.remat
    adt.reset()
    return hlo, losses, gathered, remat


def test_remat_recomputes_but_matches_exactly():
    hlo0, losses0, params0, r0 = _lowered_and_losses(strategy.AllReduce())
    hlo1, losses1, params1, r1 = _lowered_and_losses(
        strategy.WithRemat(strategy.AllReduce(), policy="full"))
    assert r0 is None and r1 == "full"
    # the rematerialized program recomputes the forward's contractions in
    # the backward: strictly more dot ops than the store-activations plan
    dots0 = len(re.findall(r"\bstablehlo\.dot_general\b", hlo0))
    dots1 = len(re.findall(r"\bstablehlo\.dot_general\b", hlo1))
    assert dots1 > dots0, (dots0, dots1)
    # same math to the bit
    np.testing.assert_array_equal(losses0, losses1)
    for k in params0:
        np.testing.assert_array_equal(params0[k], params1[k])


def test_remat_dots_policy_lowers_and_matches():
    _, losses0, params0, _ = _lowered_and_losses(strategy.AllReduce())
    _, losses1, params1, r = _lowered_and_losses(
        strategy.WithRemat(strategy.AllReduce(), policy="dots"))
    assert r == "dots"
    np.testing.assert_array_equal(losses0, losses1)
    for k in params0:
        np.testing.assert_array_equal(params0[k], params1[k])


def test_remat_serializes_with_strategy():
    from autodist_tpu.strategy.base import Strategy
    params, loss_fn, batch = _mlp()
    ad = adt.AutoDist(strategy_builder=strategy.WithRemat(
        strategy.PSLoadBalancing(), policy="dots"))
    runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
    sid = runner.distributed_step.strategy.id
    loaded = Strategy.deserialize(sid)
    assert loaded.graph_config.remat == "dots"
    adt.reset()


def test_remat_rejects_unknown_policy():
    with pytest.raises(ValueError, match="remat policy"):
        strategy.WithRemat(strategy.AllReduce(), policy="everything")


def test_remat_composes_with_sequence_parallel():
    """Long context is where remat matters most: WithRemat around
    SequenceParallelAR — jax.checkpoint over a loss containing ring
    attention's collective_permute — must lower, run, and match the
    non-remat SP trajectory to float tolerance (recompute changes XLA's
    fusion boundaries, so ulp-level drift is expected — unlike the plain
    MLP case, where the programs happen to agree bit-for-bit)."""
    import jax
    from autodist_tpu.models import lm

    cfg = lm.LMConfig.tiny()
    sp_loss, params, batch, _ = lm.make_sp_train_setup(
        cfg, seq_len=32, batch_size=8, attention="ring")

    def run(builder):
        ad = adt.AutoDist(strategy_builder=builder)
        runner = ad.build(sp_loss, optax.sgd(0.1), params, batch)
        runner.init(params)
        losses = [float(runner.run(batch)["loss"]) for _ in range(2)]
        got = {jax.tree_util.keystr(p): np.asarray(v)
               for p, v in jax.tree_util.tree_flatten_with_path(
                   runner.gather_params())[0]}
        adt.reset()
        return losses, got

    plain_losses, plain = run(strategy.SequenceParallelAR(seq_shards=4))
    remat_losses, remat = run(strategy.WithRemat(
        strategy.SequenceParallelAR(seq_shards=4), policy="full"))
    np.testing.assert_allclose(plain_losses, remat_losses,
                               rtol=1e-6, atol=1e-6)
    for k in plain:
        np.testing.assert_allclose(plain[k], remat[k], rtol=1e-5,
                                   atol=1e-6, err_msg=k)


def test_remat_composes_with_tensor_parallel():
    """WithRemat around TensorParallel: jax.checkpoint over a loss whose
    forward issues Megatron psums must lower, run, and track the
    non-remat TP trajectory."""
    from autodist_tpu.models import tp_lm

    cfg = tp_lm.TPLMConfig.tiny()
    loss_fn, params, batch, _ = tp_lm.make_train_setup(
        cfg, seq_len=16, batch_size=8)

    def run(builder):
        adt.reset()
        ad = adt.AutoDist(strategy_builder=builder)
        runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
        runner.init(params)
        losses = [float(runner.run(batch)["loss"]) for _ in range(2)]
        adt.reset()
        return losses

    tp = strategy.TensorParallel(tp_shards=2, mp_rules=tp_lm.tp_rules())
    plain = run(tp)
    remat = run(strategy.WithRemat(
        strategy.TensorParallel(tp_shards=2, mp_rules=tp_lm.tp_rules()),
        policy="dots"))
    np.testing.assert_allclose(plain, remat, rtol=1e-5, atol=1e-6)
