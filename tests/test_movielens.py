"""MovieLens NCF pipeline: parse -> split -> native records -> negative
sampling -> Parallax training with the sparse wire -> HR/NDCG eval.

The reference ingests real MovieLens through ~3k LoC of
``utils/recommendation/`` (VERDICT r2 missing #3); the bundled slice here
is SYNTHETIC but in the exact ml-1m ``user::item::rating::timestamp``
format, so the same code path serves a real download.
"""
import os

import numpy as np
import pytest

from autodist_tpu.data import movielens

DATA = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "examples", "benchmark", "data", "ml_tiny_synthetic.dat")


@pytest.fixture(scope="module")
def ratings():
    return movielens.load_ratings(DATA)


def test_parse_and_remap(ratings):
    assert ratings.n > 2000
    # contiguous remap: every id in range, both extremes used
    assert ratings.users.min() == 0
    assert ratings.users.max() == ratings.num_users - 1
    assert ratings.items.min() == 0
    assert ratings.items.max() == ratings.num_items - 1
    assert ratings.users.dtype == np.int32


def test_leave_one_out_split(ratings):
    train, holdout = movielens.leave_one_out_split(ratings)
    # exactly one held-out item per user, and it is the user's LATEST
    assert len(holdout) == ratings.num_users
    assert train.n == ratings.n - ratings.num_users
    for u in (0, 1, ratings.num_users - 1):
        mask = ratings.users == u
        latest = ratings.items[mask][np.argmax(ratings.timestamps[mask])]
        assert holdout[u] == int(latest)
        # the held-out (u, item) PAIR is really absent from train (items
        # are unique per user in this data, so pair-absence is exact)
        assert not np.any((train.users == u)
                          & (train.items == holdout[u]))


def test_negative_sampler_rejects_positives(ratings):
    train, _ = movielens.leave_one_out_split(ratings)
    sampler = movielens.NegativeSampler(train, neg_per_pos=4, seed=0)
    batch = sampler.batch(train.users[:128], train.items[:128])
    assert batch["user"].shape == (128 * 5,)
    assert set(np.unique(batch["label"])) == {0, 1}
    negs = batch["label"] == 0
    # no sampled negative is a training positive
    assert not sampler._is_positive(batch["user"][negs],
                                    batch["item"][negs]).any()
    assert sampler.false_negatives == 0


def test_native_record_pipeline_roundtrip(ratings, tmp_path):
    train, _ = movielens.leave_one_out_split(ratings)
    path = movielens.write_train_records(train, str(tmp_path / "ncf.adt"))
    it = movielens.train_batches(path, train, pos_per_batch=64,
                                 neg_per_pos=3)
    batch = next(it)
    assert batch["user"].shape == (64 * 4,)
    # positives really come from the dataset (valid remapped ids)
    assert batch["item"].max() < train.num_items
    assert batch["label"][:64].all() and not batch["label"][64:].any()


def test_train_ncf_on_real_pipeline_with_parallax(ratings, tmp_path):
    """End-to-end: records -> sampler -> Parallax NCF training on the
    8-device mesh. The embedding tables must ride the sparse (ids,
    values) wire, and the measured wire bytes on this REAL id
    distribution must undercut dense vocab-sized gradients."""
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy
    from autodist_tpu.models import ncf

    train, holdout = movielens.leave_one_out_split(ratings)
    path = movielens.write_train_records(train, str(tmp_path / "ncf.adt"))
    # dims/batch chosen so the sparse wire PAYS on this vocabulary (the
    # cost gate compares batch-scale ids+values against vocab-scale dense
    # — with 64-dim tables and 8 local rows the wire wins on every table)
    cfg = ncf.NCFConfig(num_users=train.num_users,
                        num_items=train.num_items,
                        mf_dim=64, mlp_dims=(128, 64))
    loss_fn, params, _, apply_fn = ncf.make_train_setup(cfg, batch_size=8)

    batches = movielens.train_batches(path, train, pos_per_batch=16,
                                      neg_per_pos=3)
    first = next(batches)
    adt.reset()
    ad = adt.AutoDist(strategy_builder=strategy.Parallax())
    runner = ad.build(loss_fn, optax.adam(5e-3), params, first)
    runner.init(params)
    # all four embedding tables ride the sparse wire under Parallax
    wire = set(runner.distributed_step.metadata["sparse_wire"])
    assert {"params/mf_user_embedding/embedding",
            "params/mf_item_embedding/embedding",
            "params/mlp_user_embedding/embedding",
            "params/mlp_item_embedding/embedding"} <= wire, wire

    losses = [float(runner.run(first)["loss"])]
    for _ in range(30):
        losses.append(float(runner.run(next(batches))["loss"]))
    assert losses[-1] < losses[0], losses

    # wire accounting on the real id distribution: batch-scale
    # (ids+values) vs vocab-scale dense gradients for the PS-routed tables
    store = runner.distributed_step.ps_store
    if store is not None and store.stats["pushes"]:
        dense_per_step = sum(
            int(np.prod(v.shape)) * 4
            for n, v in runner.distributed_step.model_item.var_infos.items()
            if n in wire and n in store.plans)
        pushed_per_step = store.stats["bytes_pushed"] / store.stats["pushes"]
        assert pushed_per_step < dense_per_step, (
            "sparse wire heavier than dense: %s vs %s"
            % (pushed_per_step, dense_per_step))

    # eval protocol: scores from the trained model, HR/NDCG in [0, 1]
    gathered = runner.gather_params()

    def score_fn(users, items):
        import jax.numpy as jnp
        return apply_fn({"params": gathered["params"]} if "params" in
                        gathered else gathered,
                        jnp.asarray(users), jnp.asarray(items))

    m = movielens.evaluate_hit_ndcg(score_fn, holdout, train,
                                    num_negatives=20, k=10)
    assert m["users"] == train.num_users
    assert 0.0 <= m["ndcg"] <= m["hr"] <= 1.0
    adt.reset()


def test_eval_protocol_perfect_and_random():
    """Protocol sanity: an oracle that always scores the held-out item
    highest gets HR=NDCG=1; scoring by item id gives something less."""
    rng = np.random.RandomState(0)
    users = np.repeat(np.arange(8, dtype=np.int32), 10)
    items = np.concatenate([rng.permutation(50)[:10] for _ in range(8)]
                           ).astype(np.int32)
    data = movielens.RatingsData(users=users, items=items,
                                 timestamps=np.arange(80, dtype=np.int64),
                                 num_users=8, num_items=50)
    _, holdout = movielens.leave_one_out_split(data)

    def oracle(u, i):
        held = np.asarray([holdout[int(x)] for x in np.asarray(u)])
        return (np.asarray(i) == held).astype(np.float32)

    m = movielens.evaluate_hit_ndcg(oracle, holdout, data, num_negatives=20)
    assert m["hr"] == 1.0 and m["ndcg"] == 1.0
