"""Randomized chaos campaign — all five fault planes, one seeded run.

A *campaign* composes a seeded schedule across every fault plane the
repo can inject — **wire** (FaultyProxy delays/resets), **partition**
(the zombie-revival blackhole window), **ckpt** (post-commit damage),
**grad** (a traced NaN the sentinel must skip), and **preempt** (a real
SIGTERM with a deadline-to-SIGKILL, ``faultinject.deliver_preemption``)
— against a real training subprocess, then restarts it with
``ADT_AUTO_RESUME`` and asserts the standing invariants:

- **loss continuity within tolerance** — the interrupted + resumed
  trajectory matches an uncrashed reference run step for step (training
  is deterministic; the grad fault and sentinel run identically in
  both);
- **zero fenced-write corruption / always-resumable** — every
  checkpoint the integrity scan sees is committed-or-expected-debris,
  and the newest committed one restores (the deliberately damaged one,
  when the schedule includes damage, is skipped by the fallback scan);
- **the rescue checkpoint landed** — a graceful (exit 0) preemption
  leaves a committed checkpoint at the rescue step, and the planned
  path never touches ``ckpt.fallback``.

Each campaign writes a JSON transcript (schedule, observed events,
assertion outcomes) — the nightly workflow uploads them as artifacts::

    python tests/chaos_campaign.py --seeds 101,202,303 --out /tmp/chaos

``tests/test_preemption.py`` runs one seed as the slow/chaos pytest leg.
"""
import argparse
import json
import os
import random
import socket
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))

DRIVER = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import optax
import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.runtime import preemption

steps = int(sys.argv[1])
progress_path = sys.argv[2]

rng = np.random.RandomState(7)
params = {"w": jax.numpy.asarray(rng.randn(8, 4) * 0.3, jax.numpy.float32)}

def loss_fn(p, batch):
    return jax.numpy.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

batch = {"x": rng.randn(8, 8).astype(np.float32),
         "y": rng.randn(8, 4).astype(np.float32)}

ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
runner = ad.build(loss_fn, optax.sgd(0.05), params, batch)
runner.init(params)
start = int(np.asarray(jax.device_get(runner.state.step)).ravel()[0])

from autodist_tpu.checkpoint.saver import Saver
saver = Saver(directory=os.environ["ADT_CKPT_DIR"])
runner._preempt.attach_saver(saver)

try:
    for i in range(start, steps):
        m = runner.run(batch)
        with open(progress_path, "a") as f:
            f.write("%d %.8f\n" % (i, float(m["loss"])))
            f.flush()
            os.fsync(f.fileno())
        if (i + 1) % 3 == 0:
            saver.save(runner)
            saver.wait()
except preemption.PlannedDeparture as e:
    print("DRIVER_PLANNED_DEPARTURE %s" % e, flush=True)
    raise
print("DRIVER_DONE", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def build_schedule(seed: int, steps: int = 15) -> dict:
    """One seeded composition across the five fault planes."""
    rng = random.Random(seed)
    return {
        "seed": seed,
        "steps": steps,
        # grad plane: a transient NaN the sentinel skips (identical in
        # the reference run, so trajectories stay comparable)
        "grad_fault_step": rng.randrange(2, 5),
        # wire plane: a delayed RPC and an ambiguous reset
        "wire": [
            {"op": "delay", "match": "PUT",
             "nth": rng.randrange(3, 9), "delay_s": 0.05},
            {"op": "reset", "match": "GET", "when": "after",
             "nth": rng.randrange(6, 18)},
        ],
        # partition plane: a short global blackhole window
        "partition": {"op": "partition", "match": "PUT",
                      "nth": rng.randrange(4, 10),
                      "duration_s": round(rng.uniform(0.1, 0.3), 2)},
        # preempt plane: SIGTERM after this many observed steps, SIGKILL
        # deadline_s later — the window the rescue + handoff must fit
        "preempt_after_steps": rng.randrange(7, 10),
        "deadline_s": round(rng.uniform(8.0, 15.0), 1),
        # ckpt plane: flip a bit in the newest committed checkpoint
        # before the resume (the fallback scan must skip past it)
        "ckpt_damage": rng.random() < 0.5,
    }


def _spawn(script_path: str, schedule: dict, env_extra: dict,
           progress_path: str, tmpdir: str) -> subprocess.Popen:
    env = dict(os.environ)
    for k in ("ADT_WORKER", "ADT_ELASTIC", "ADT_ELASTIC_SYNC",
              "ADT_ELASTIC_INRUN", "ADT_AUTO_RESUME", "ADT_FAULT_PLAN",
              "ADT_GRAD_FAULT_PLAN", "ADT_CKPT_FAULT_PLAN",
              "ADT_SENTINEL", "ADT_NUM_PROCESSES", "ADT_STRATEGY_ID"):
        env.pop(k, None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "ADT_WORKING_DIR": os.path.join(tmpdir, "work"),
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE)] +
            ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH")
             else [])),
        # grad plane + the sentinel that survives it
        "ADT_GRAD_FAULT_PLAN": json.dumps({"faults": [
            {"var": "w", "mode": "nan",
             "step": schedule["grad_fault_step"]}]}),
        "ADT_SENTINEL": "1",
    })
    env.update(env_extra)
    return subprocess.Popen(
        [sys.executable, script_path, str(schedule["steps"]),
         progress_path],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _read_progress(path: str) -> list:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) == 2:
                out.append((int(parts[0]), float(parts[1])))
    return out


def _wait_for_steps(path: str, n: int, timeout_s: float = 300.0) -> list:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        prog = _read_progress(path)
        if len(prog) >= n:
            return prog
        time.sleep(0.05)
    raise TimeoutError("victim never reached step %d (have %d)"
                       % (n, len(_read_progress(path))))


def run_campaign(seed: int, outdir: str) -> dict:
    """Run one seeded campaign end to end; returns (and writes) the
    transcript. Raises AssertionError when an invariant breaks."""
    from autodist_tpu.checkpoint import integrity
    from autodist_tpu.runtime import faultinject
    from autodist_tpu.runtime.coordination import CoordinationServer

    schedule = build_schedule(seed)
    os.makedirs(outdir, exist_ok=True)
    campaign_dir = os.path.join(outdir, "campaign-%d" % seed)
    os.makedirs(campaign_dir, exist_ok=True)
    script = os.path.join(campaign_dir, "driver.py")
    with open(script, "w") as f:
        f.write(DRIVER)
    ckpt_dir = os.path.join(campaign_dir, "ckpt")
    transcript = {"format": "adt-chaos-campaign-v1", "schedule": schedule,
                  "events": [], "invariants": {}}

    def event(kind, **data):
        transcript["events"].append(
            {"t": round(time.time(), 3), "kind": kind, **data})

    # ---- phase 0: uncrashed reference (grad fault + sentinel only; no
    # wire/partition/preempt/ckpt planes, no coordination service)
    ref_progress = os.path.join(campaign_dir, "ref.txt")
    ref = _spawn(script, schedule, {
        "ADT_CKPT_DIR": os.path.join(campaign_dir, "ref-ckpt"),
    }, ref_progress, campaign_dir)
    ref_out, ref_err = ref.communicate(timeout=300)
    assert ref.returncode == 0, ref_out[-2000:] + ref_err[-4000:]
    ref_losses = dict(_read_progress(ref_progress))
    assert len(ref_losses) == schedule["steps"]
    event("reference_done", steps=len(ref_losses))

    # ---- phase 1: the victim, all five planes armed
    svc_port = _free_port()
    server = CoordinationServer(port=svc_port)
    server.start()
    plan = faultinject.FaultPlan({"seed": seed, "faults":
                                  schedule["wire"] + [schedule["partition"]]})
    proxy = faultinject.FaultyProxy("127.0.0.1", svc_port, plan=plan)
    proxy.start()
    progress = os.path.join(campaign_dir, "victim.txt")
    victim_env = {
        "ADT_COORDSVC_PORT": str(proxy.port),
        "ADT_CKPT_DIR": ckpt_dir,
        "ADT_ELASTIC": "1", "ADT_ELASTIC_SYNC": "1",
        "ADT_ELASTIC_INRUN": "1", "ADT_ELASTIC_POLL_S": "0.05",
        "ADT_PREEMPT_POLL_S": "0.05",
        "ADT_PREEMPT_DEADLINE_S": str(schedule["deadline_s"]),
    }
    victim = _spawn(script, schedule, victim_env, progress, campaign_dir)
    try:
        _wait_for_steps(progress, schedule["preempt_after_steps"])
        event("preempt_delivered", pid=victim.pid,
              deadline_s=schedule["deadline_s"])
        killer = faultinject.deliver_preemption(
            victim.pid, deadline_s=schedule["deadline_s"],
            reason="campaign-%d" % seed)
        v_out, v_err = victim.communicate(timeout=schedule["deadline_s"]
                                          + 60)
        killer.join(timeout=1)
    finally:
        proxy.stop()
        server.stop()
    event("victim_exit", code=victim.returncode,
          injected=list(plan.injected))
    graceful = victim.returncode == 0
    transcript["invariants"]["graceful_departure"] = graceful
    if graceful:
        assert "DRIVER_PLANNED_DEPARTURE" in v_out, (
            "exit 0 without the planned-departure path:\n"
            + v_out[-2000:] + v_err[-4000:])

    # invariant: a committed checkpoint exists (the rescue save on the
    # graceful path; the last periodic save otherwise), and the
    # integrity scan classifies nothing as corrupt
    victim_steps = _read_progress(progress)
    assert victim_steps, "victim made no progress"
    statuses = list(integrity.scan(ckpt_dir))
    committed = [s for s in statuses if s.state == "committed"]
    assert committed, "no committed checkpoint after preemption: %s" % (
        [(s.step, s.state) for s in statuses],)
    assert not [s for s in statuses if s.state == "corrupt"], statuses
    if graceful:
        rescue_step = max(s.step for s in committed)
        assert rescue_step >= victim_steps[-1][0], (
            "graceful departure without a rescue checkpoint at the final "
            "boundary: newest committed step %d < last trained step %d"
            % (rescue_step, victim_steps[-1][0]))
        transcript["invariants"]["rescue_step"] = rescue_step
    event("integrity_scan",
          committed=[s.step for s in committed])

    # ---- phase 2 (ckpt plane): damage the newest committed checkpoint,
    # the resume must fall back past it — always-resumable
    if schedule["ckpt_damage"]:
        newest = max(committed, key=lambda s: s.step)
        target = os.path.join(ckpt_dir, "ckpt-%d.params.npz" % newest.step)
        if os.path.exists(target):
            faultinject.flip_bit(target)
            event("ckpt_damaged", step=newest.step)

    # ---- phase 3: restart with auto-resume; the trajectory must match
    # the reference at every step it trains
    resume_env = dict(victim_env)
    resume_env.pop("ADT_COORDSVC_PORT", None)  # serviceless resume
    resume_env["ADT_AUTO_RESUME"] = "1"
    resume_progress = os.path.join(campaign_dir, "resume.txt")
    resumed = _spawn(script, schedule, resume_env, resume_progress,
                     campaign_dir)
    r_out, r_err = resumed.communicate(timeout=300)
    assert resumed.returncode == 0, r_out[-2000:] + r_err[-4000:]
    resume_losses = _read_progress(resume_progress)
    assert resume_losses, "resume trained nothing (nothing to restore?)"
    assert resume_losses[-1][0] == schedule["steps"] - 1
    event("resume_done", first_step=resume_losses[0][0],
          steps=len(resume_losses))

    # loss continuity: every resumed step's loss matches the uncrashed
    # reference (training is deterministic; the grad fault ran in both)
    worst = 0.0
    for step, loss in resume_losses:
        ref_loss = ref_losses[step]
        denom = max(abs(ref_loss), 1e-12)
        worst = max(worst, abs(loss - ref_loss) / denom)
    assert worst < 1e-4, (
        "resumed trajectory diverged from the reference: max rel err %g"
        % worst)
    transcript["invariants"].update(
        loss_continuity_max_rel_err=worst,
        always_resumable=True,
        zero_corrupt_committed=True,
    )
    path = os.path.join(campaign_dir, "transcript.json")
    with open(path, "w") as f:
        json.dump(transcript, f, indent=2, sort_keys=True)
    transcript["path"] = path
    return transcript


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seeds", default="101",
                   help="comma-separated campaign seeds")
    p.add_argument("--out", default="/tmp/adt-chaos-campaigns")
    args = p.parse_args(argv)
    failures = 0
    for seed in [int(s) for s in args.seeds.split(",") if s]:
        t0 = time.monotonic()
        try:
            t = run_campaign(seed, args.out)
            print("campaign %d OK in %.1fs: %s"
                  % (seed, time.monotonic() - t0,
                     json.dumps(t["invariants"], sort_keys=True)))
        except (AssertionError, TimeoutError) as e:
            failures += 1
            print("campaign %d FAILED: %s" % (seed, e))
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
