"""Static HBM footprint & collective-schedule analyzer (ADT5xx).

Four layers, matching the analyzer's design:

1. parser units: entry signatures (sharding, donation), statement sizes,
   collective extraction with replica groups and loop depth, on fixture
   StableHLO text;
2. schedule checks: cross-program compatibility (ADT510 reorder, ADT511
   replica-group mismatch) and the fused per-step embedding;
3. memory: the liveness estimator, budget gates (ADT501/502), donation
   (ADT503), plan-level gate with NO compile attempt, and the e2e
   accuracy bound — ``Runner.memory_report()`` within 20% of XLA's
   ``compiled.memory_analysis()`` for the PS and AllReduce examples on
   the 2x2 CPU mesh;
4. the measured ``static_profile`` feeding ``CostModel.estimate`` —
   ranking reproduced, per-class drift logged — and the CLI's
   ``--programs`` / ``--hbm-budget`` / ``--format json`` surfaces.
"""
import json

import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.analysis import cli, hlo
from autodist_tpu.analysis import memory as memory_lib
from autodist_tpu.analysis.diagnostics import Severity

GIB = memory_lib.GIB

# A hand-written program exercising every parsed construct: sharded +
# donated args, labeled results, a region collective, a region-free
# collective, and a while loop calling into the microstep function.
FIXTURE = """
module @jit_step attributes {mhlo.num_partitions = 4 : i32, mhlo.num_replicas = 1 : i32} {
  func.func public @main(%arg0: tensor<8x4xf32> {tf.aliasing_output = 0 : i32, mhlo.sharding = "{replicated}"}, %arg1: tensor<16x8xf32> {mhlo.sharding = "{devices=[4,1]<=[4]}"}) -> (tensor<8x4xf32> {jax.result_info = "[0].params['w']"}, tensor<f32> {jax.result_info = "[1]['loss']"}) {
    %0:2 = call @shmap_body(%arg0, %arg1) : (tensor<8x4xf32>, tensor<16x8xf32>) -> (tensor<8x4xf32>, tensor<f32>)
    return %0#0, %0#1 : tensor<8x4xf32>, tensor<f32>
  }
  func.func private @shmap_body(%arg0: tensor<8x4xf32>, %arg1: tensor<4x8xf32>) -> (tensor<8x4xf32>, tensor<f32>) {
    %0 = stablehlo.dot_general %arg1, %arg0, contracting_dims = [1] x [0] : (tensor<4x8xf32>, tensor<8x4xf32>) -> tensor<4x4xf32>
    %1 = "stablehlo.all_reduce"(%0) <{channel_handle = #stablehlo.channel_handle<handle = 1, type = 1>, replica_groups = dense<[[0, 1, 2, 3]]> : tensor<1x4xi64>, use_global_device_ids}> ({
    ^bb0(%arg2: tensor<f32>, %arg3: tensor<f32>):
      %9 = stablehlo.add %arg2, %arg3 : tensor<f32>
      stablehlo.return %9 : tensor<f32>
    }) : (tensor<4x4xf32>) -> tensor<4x4xf32>
    %2 = "stablehlo.collective_permute"(%1) {source_target_pairs = dense<[[0, 1], [1, 2], [2, 3], [3, 0]]> : tensor<4x2xi64>} : (tensor<4x4xf32>) -> tensor<4x4xf32>
    %3 = stablehlo.while(%iterArg = %2) : tensor<4x4xf32>
     cond {
      %c = stablehlo.constant dense<0> : tensor<i32>
      %9 = stablehlo.compare LT, %c, %c, SIGNED : (tensor<i32>, tensor<i32>) -> tensor<i1>
      stablehlo.return %9 : tensor<i1>
    } do {
      %9 = func.call @micro(%iterArg) : (tensor<4x4xf32>) -> tensor<4x4xf32>
      stablehlo.return %9 : tensor<4x4xf32>
    }
    %cst = stablehlo.constant dense<0.0> : tensor<f32>
    %4 = stablehlo.reduce(%3 init: %cst) applies stablehlo.add across dimensions = [0, 1] : (tensor<4x4xf32>, tensor<f32>) -> tensor<f32>
    return %arg0, %4 : tensor<8x4xf32>, tensor<f32>
  }
  func.func private @micro(%arg0: tensor<4x4xf32>) -> tensor<4x4xf32> {
    %0 = "stablehlo.all_reduce"(%arg0) <{channel_handle = #stablehlo.channel_handle<handle = 2, type = 1>, replica_groups = dense<[[0, 1, 2, 3]]> : tensor<1x4xi64>, use_global_device_ids}> ({
    ^bb0(%arg2: tensor<f32>, %arg3: tensor<f32>):
      %9 = stablehlo.add %arg2, %arg3 : tensor<f32>
      stablehlo.return %9 : tensor<f32>
    }) : (tensor<4x4xf32>) -> tensor<4x4xf32>
    return %0 : tensor<4x4xf32>
  }
}
"""


def codes(diags):
    return {d.code for d in diags}


# ------------------------------------------------------------- 1. parser


def test_tensor_type_bytes():
    assert hlo.tensor_type_bytes("8x4xf32") == 128
    assert hlo.tensor_type_bytes("i32") == 4
    assert hlo.tensor_type_bytes("16xbf16") == 32
    assert hlo.tensor_type_bytes("2x3xi1") == 6


def test_sharding_divisor():
    assert hlo.sharding_divisor("{replicated}") == 1
    assert hlo.sharding_divisor("{devices=[4,1]<=[4]}") == 4
    assert hlo.sharding_divisor("{devices=[2,1,2]<=[4] "
                                "last_tile_dim_replicate}") == 2
    assert hlo.sharding_divisor("") == 1


def test_parse_entry_signature():
    p = hlo.parse_hlo_text(FIXTURE)
    assert p.entry.name == "main" and p.num_partitions == 4
    a0, a1 = p.entry.args
    assert a0.aliased_output == 0 and a0.donated and a0.type_bytes == 128
    assert not a1.donated and a1.per_device_bytes == 512 / 4
    r0, r1 = p.entry.results
    assert r0.result_info == "[0].params['w']" and r0.type_bytes == 128
    assert r1.type_bytes == 4
    assert set(p.funcs) == {"main", "shmap_body", "micro"}


def test_buffer_donor_spelling_parses_as_donated():
    text = ('func.func public @main(%arg0: tensor<4xf32> '
            '{jax.buffer_donor = true}, %arg1: tensor<4xf32>) '
            '-> (tensor<4xf32>) {\n  return %arg0 : tensor<4xf32>\n}\n')
    p = hlo.parse_hlo_text(text)
    assert p.entry.args[0].donated and not p.entry.args[1].donated


def test_collective_schedule_order_groups_and_loop_depth():
    sched = hlo.collective_schedule(FIXTURE)
    kinds = [c.kind for c in sched]
    assert kinds == ["reduce", "permute", "reduce"]
    assert all(c.replica_groups or c.kind == "permute" for c in sched)
    first = sched[0]
    assert first.payload_bytes == 64 and first.group_size == 4
    assert first.replica_groups == ((0, 1, 2, 3),)
    # the third collective lives in @micro, CALLED from the while body:
    # call-site loop depth must propagate
    assert sched[2].loop_depth == 1 and sched[0].loop_depth == 0


def test_per_step_strips_only_fully_in_loop_schedules():
    """A fused program has EVERY collective inside the microstep scan —
    per_step() unwraps one loop level. A per-step program with a
    model-internal loop (mixed depths, like the fixture) must be left
    alone, or its gradient collectives would vanish from the profile."""
    import dataclasses
    mixed = hlo.collective_schedule(FIXTURE)
    assert [c.loop_depth for c in mixed] == [0, 0, 1]
    assert list(mixed.per_step()) == list(mixed)
    fused = hlo.CollectiveSchedule(
        dataclasses.replace(c, loop_depth=c.loop_depth + 1) for c in mixed)
    assert [c.loop_depth for c in fused.per_step()] == [0, 0, 1]


# ---------------------------------------------------- 2. schedule checks


def _sched(entries):
    return hlo.CollectiveSchedule(
        hlo.CollectiveOp(kind=k, op=k, payload_bytes=b, result_bytes=b,
                         replica_groups=g, channel=i, lineno=i,
                         loop_depth=0)
        for i, (k, b, g) in enumerate(entries))


G4 = ((0, 1, 2, 3),)
G22 = ((0, 1), (2, 3))


def test_compare_schedules_subset_is_clean():
    train = _sched([("reduce", 16, G4), ("reduce", 128, G4),
                    ("reduce", 4, G4)])
    evalp = _sched([("reduce", 4, G4)])
    assert hlo.compare_schedules(train, evalp) == []


def test_compare_schedules_reorder_yields_adt510():
    train = _sched([("reduce", 16, G4), ("reduce", 128, G4)])
    evalp = _sched([("reduce", 128, G4), ("reduce", 16, G4)])
    diags = hlo.compare_schedules(train, evalp)
    assert codes(diags) == {"ADT510"}
    assert diags[0].severity >= Severity.ERROR


def test_compare_schedules_group_mismatch_yields_adt511():
    train = _sched([("reduce", 16, G4), ("reduce", 128, G4)])
    evalp = _sched([("reduce", 16, G4), ("reduce", 128, G22)])
    assert codes(hlo.compare_schedules(train, evalp)) == {"ADT511"}


def test_compare_schedules_extra_collective_yields_adt510():
    train = _sched([("reduce", 16, G4)])
    evalp = _sched([("gather", 64, G4), ("reduce", 16, G4)])
    assert "ADT510" in codes(hlo.compare_schedules(train, evalp))


# ------------------------------------------------------------- 3. memory


def test_memory_estimate_fixture():
    est = memory_lib.estimate_from_text(FIXTURE)
    # args: 128 (replicated, donated) + 512/4; outputs: 128 + 4; donated
    # arg aliases at most output bytes
    assert est.args_bytes == 128 + 128
    assert est.output_bytes == 132
    assert est.aliased_bytes == 128
    assert est.peak_temp_bytes > 0
    assert est.peak_hbm_bytes == (est.args_bytes + est.output_bytes
                                  - est.aliased_bytes + est.peak_temp_bytes)
    assert est.outputs_by_label["params"] == 128


def test_budget_diagnostics_codes():
    assert codes(memory_lib.budget_diagnostics(11 * GIB, 10 * GIB)) == {
        "ADT501"}
    assert codes(memory_lib.budget_diagnostics(9.5 * GIB, 10 * GIB)) == {
        "ADT502"}
    assert memory_lib.budget_diagnostics(5 * GIB, 10 * GIB) == []
    assert memory_lib.budget_diagnostics(5 * GIB, 0) == []


def test_donation_diagnostics_adt503():
    p = hlo.parse_hlo_text(FIXTURE)
    # fixture main HAS a donated arg: clean even with a loop
    assert memory_lib.donation_diagnostics(p, fuse_steps=4) == []
    undonated = FIXTURE.replace("tf.aliasing_output = 0 : i32, ", "")
    assert codes(memory_lib.donation_diagnostics(undonated,
                                                 fuse_steps=4)) == {"ADT503"}
    # without the caller declaring the program fused, a while op alone is
    # no evidence: per-step programs legitimately contain model-internal
    # loops and eval programs are never donated — no false ADT503
    assert memory_lib.donation_diagnostics(undonated, fuse_steps=1) == []
    flat = "func.func public @main(%arg0: tensor<4xf32>) -> " \
           "(tensor<4xf32>) {\n  return %arg0 : tensor<4xf32>\n}\n"
    assert memory_lib.donation_diagnostics(flat, fuse_steps=1) == []


def test_resource_spec_chip_hbm_capacity():
    from autodist_tpu.resource_spec import CHIP_HBM_BYTES, ResourceSpec
    cpu = ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 0,
                    "cpus": 4}]})
    assert cpu.chip_kind() == "cpu"
    assert cpu.chip_hbm_bytes() == CHIP_HBM_BYTES["cpu"]
    v5p = ResourceSpec.from_dict(
        {"nodes": [{"address": "10.0.0.1", "chief": True, "tpus": 4}],
         "slice": {"type": "v5p-8"}})
    assert v5p.chip_kind() == "v5p"
    assert v5p.chip_hbm_bytes() == CHIP_HBM_BYTES["v5p"]
    override = ResourceSpec.from_dict(
        {"nodes": [{"address": "10.0.0.1", "chief": True, "tpus": 4}],
         "slice": {"type": "v4-8", "hbm_gib": 3}})
    assert override.chip_hbm_bytes() == 3 * GIB


def test_plan_gate_flags_oversized_model_without_compiling():
    """Acceptance: a deliberately oversized model raises ADT501 at lint
    time — the plan-level estimator never traces, lowers, compiles, or
    allocates anything (a 64 GiB parameter tensor could not possibly be
    materialized by this test process, which is the point)."""
    from tests.test_analysis import DictItem, clean_strategy, spec_2x2
    from autodist_tpu.model_item import VarInfo

    class Item(DictItem):
        def total_bytes(self):
            return sum(v.byte_size for v in self.var_infos.values())

    huge = {"w": VarInfo("w", (1 << 17, 1 << 17), "float32")}
    item = Item(huge)
    strategy = clean_strategy(huge, spec_2x2())
    report = memory_lib.plan_memory_report(strategy, item, spec_2x2(),
                                           budget_bytes=32 * GIB)
    assert report["peak_hbm_gib"] > 32
    assert "ADT501" in codes(report["diagnostics"])
    # under a roomy budget the same plan is clean
    roomy = memory_lib.plan_memory_report(strategy, item, spec_2x2(),
                                          budget_bytes=2 ** 50)
    assert not [d for d in roomy["diagnostics"]
                if d.severity >= Severity.ERROR]


# --------------------------------------------------------------- 4. e2e


@pytest.fixture(scope="module")
def built_artifacts():
    """One AllReduce and one PS build on the 2x2 CPU mesh: lowered texts
    (train/eval/fused, donated and not), memory reports, schedule lints,
    static profiles, and XLA's compiled memory stats — collected once,
    consumed by several tests."""
    import optax
    import jax
    import autodist_tpu
    from autodist_tpu import strategy as S

    def mlp_setup():
        key = jax.random.PRNGKey(0)
        params = {"w1": jax.random.normal(key, (64, 128)) * 0.1,
                  "b1": jnp.zeros((128,)),
                  "w2": jax.random.normal(key, (128, 32)) * 0.1,
                  "b2": jnp.zeros((32,))}

        def loss_fn(p, b):
            h = jnp.tanh(b["x"] @ p["w1"] + p["b1"])
            return jnp.mean(((h @ p["w2"] + p["b2"]) - b["y"]) ** 2)

        batch = {"x": np.zeros((32, 64), np.float32),
                 "y": np.zeros((32, 32), np.float32)}
        return loss_fn, params, batch

    from autodist_tpu.model_item import ModelItem
    loss_fn, params, batch = mlp_setup()
    out = {"item": ModelItem(loss_fn=loss_fn, params=params,
                             example_batch=batch).prepare()}
    for name, builder in (("AllReduce", S.AllReduce), ("PS", S.PS)):
        autodist_tpu.reset()
        loss_fn, params, batch = mlp_setup()
        ad = autodist_tpu.AutoDist(strategy_builder=builder(),
                                   validate="error")
        runner = ad.build(loss_fn, optax.adam(1e-3), params, batch)
        runner.init(params)
        dstep = runner.distributed_step
        ps_avals, _ = dstep._ps_avals()
        placed = runner.remapper.remap_feed(batch)
        ma = dstep._step_fn_nodonate.lower(
            runner.state, ps_avals, placed).compile().memory_analysis()
        out[name] = {
            "strategy": dstep.strategy,
            "report_nodonate": runner.memory_report(batch, donate=False),
            "report": runner.memory_report(batch),
            "train_text": runner.lowered_text(batch),
            "eval_text": runner.lowered_text(batch, program="eval"),
            "schedule_lint": runner.lint_schedules(batch, fuse_steps=4),
            "profile": runner.static_profile(batch),
            "xla_peak": (ma.argument_size_in_bytes
                         + ma.output_size_in_bytes
                         + ma.temp_size_in_bytes
                         - ma.alias_size_in_bytes),
        }
    autodist_tpu.reset()
    return out


@pytest.mark.parametrize("name", ["AllReduce", "PS"])
def test_memory_report_within_20pct_of_xla(built_artifacts, name):
    """Acceptance: the static peak-HBM estimate tracks XLA's own buffer
    assignment within 20% on the 2x2 CPU mesh (same un-donated program
    variant on both sides)."""
    art = built_artifacts[name]
    est = art["report_nodonate"]["peak_hbm_bytes"]
    xla = art["xla_peak"]
    assert xla > 0
    assert abs(est - xla) / xla < 0.20, (name, est, xla)


@pytest.mark.parametrize("name", ["AllReduce", "PS"])
def test_memory_report_shape_and_budget(built_artifacts, name):
    rep = built_artifacts[name]["report"]
    assert rep["estimate"]["args_bytes"] > 0
    assert rep["collectives"]["count"] >= 1
    # AutoDist plumbed the spec-derived budget (cpu default, 64 GB)
    assert rep["budget_bytes"] > 0 and rep["utilization"] < 0.01
    assert not [d for d in rep["diagnostics"]
                if d.severity >= Severity.ERROR]


def test_fused_program_lints_clean_against_per_step(built_artifacts):
    """Acceptance: the fused multi_step(k) program's per-microstep body
    embeds into the per-step program's schedule — and the real eval
    program embeds too (no ADT510/511 on an honest build)."""
    for name in ("AllReduce", "PS"):
        assert built_artifacts[name]["schedule_lint"] == [], name


def test_hand_mutated_eval_program_yields_adt510(built_artifacts, tmp_path):
    """Acceptance: reordering two collectives of the real lowered train
    program (playing the role of a drifted eval build) yields ADT510
    through the API and exit 1 + ADT510 through the CLI."""
    text = built_artifacts["AllReduce"]["train_text"]
    sched = hlo.collective_schedule(text)
    assert len(sched) >= 2
    lines = text.splitlines(True)
    # swap the full statement blocks of the first two collectives (each
    # runs from its opener line to its `}) : ...` close line)
    def block(c):
        start = c.lineno - 1
        end = start
        while "}) :" not in lines[end]:
            end += 1
        return "".join(lines[start:end + 1])
    b1, b2 = block(sched[0]), block(sched[1])
    assert b1 != b2
    mutated = text.replace(b1, "@@TMP@@").replace(b2, b1).replace(
        "@@TMP@@", b2)
    diags = hlo.compare_schedules(text, mutated, "train", "eval")
    assert "ADT510" in codes(diags)
    train_f = tmp_path / "train.hlo"
    eval_f = tmp_path / "eval.hlo"
    train_f.write_text(text)
    eval_f.write_text(mutated)
    rc = cli.main(["--programs", str(train_f), str(eval_f)])
    assert rc == 1


def test_static_profile_reproduces_ranking_and_logs_drift(
        built_artifacts, caplog):
    """Acceptance: attaching measured static profiles (extracted from the
    real lowerings of the SAME model) reproduces the heuristic ranking
    on the strategy zoo and logs per-class heuristic-vs-measured
    drift."""
    import logging as pylogging
    from autodist_tpu.simulator.simulator import Simulator
    from autodist_tpu.utils.logging import get_logger
    from tests.test_analysis import spec_2x2
    item = built_artifacts["item"]
    spec = spec_2x2()
    builders = cli._builders(None)
    zoo = [(n, builders[n]().build(item, spec))
           for n in ("AllReduce", "PartitionedAR", "PS", "PSLoadBalancing",
                     "Parallax")]
    sim = Simulator(item, spec)
    heuristic_order = [r.label for r in sim.rank(zoo)]
    # measured profiles for the two strategies we actually lowered
    by_label = dict(zoo)
    sim.attach_static_profile(built_artifacts["AllReduce"]["profile"],
                              by_label["AllReduce"])
    sim.attach_static_profile(built_artifacts["PS"]["profile"],
                              by_label["PS"])
    logger = get_logger()
    logger.addHandler(caplog.handler)
    try:
        with caplog.at_level(pylogging.INFO, logger="autodist_tpu"):
            measured_order = [r.label for r in sim.rank(zoo)]
    finally:
        logger.removeHandler(caplog.handler)
    # same candidate set; the two MEASURED candidates keep their relative
    # order (a measured-vs-heuristic drift of ~1.2x can legitimately move
    # a profiled candidate past an UNprofiled near-tie — that re-pricing
    # is the feature, not a regression)
    assert set(measured_order) == set(heuristic_order)

    def restricted(order):
        return [x for x in order if x in ("AllReduce", "PS")]
    assert restricted(measured_order) == restricted(heuristic_order)
    drift_lines = [r.getMessage() for r in caplog.records
                   if "static profile drift" in r.getMessage()]
    assert any("/reduce" in m for m in drift_lines), drift_lines


def test_cli_hbm_budget_flags_oom_on_example(capsys):
    """The CLI's plan-level gate: an absurdly small budget turns a clean
    example x strategy combo into ADT501 at exit 1 — still with no
    compile attempt."""
    rc = cli.main(["sentiment_classifier", "--strategy", "AllReduce",
                   "--hbm-budget", "0.00001"])
    out = capsys.readouterr().out
    assert rc == 1 and "ADT501" in out
    rc = cli.main(["sentiment_classifier", "--strategy", "AllReduce",
                   "--hbm-budget", "32", "--quiet"])
    assert rc == 0


def test_cli_format_json_memory_and_programs(tmp_path, capsys):
    rc = cli.main(["linear_regression", "--strategy", "PS",
                   "--hbm-budget", "32", "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["errors"] == 0
    assert doc["memory"]["budget_gib"] == 32.0
    assert doc["memory"]["peak_hbm_bytes"] > 0
    # programs mode JSON: per-program memory + schedule_check section
    f = tmp_path / "prog.hlo"
    f.write_text(FIXTURE)
    rc = cli.main(["--programs", str(f), str(f), "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["schedule_check"]["diagnostics"] == []
    assert doc["programs"][0]["memory"]["peak_hbm_bytes"] > 0
    assert doc["programs"][0]["collectives"] == 3


def test_bf16_program_priced_at_half_widths():
    """Byte-size audit regression (bf16 must never be priced at f32
    widths): lower a REAL bf16-compute program and check the parser's
    dtype->width table end to end — bf16 statements at 2 B/element in
    the liveness estimate, and the StaticCollectiveProfile wire bytes
    of a bf16 payload at exactly half its f32 twin."""
    import optax
    import autodist_tpu
    from autodist_tpu import strategy as S
    from autodist_tpu.simulator.cost_model import StaticCollectiveProfile

    params = {"w": jnp.zeros((64, 32), jnp.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    batch = {"x": np.zeros((16, 64), np.float32),
             "y": np.zeros((16, 32), np.float32)}
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(
        strategy_builder=S.AllReduce(compute_dtype="bf16"),
        validate="error")
    runner = ad.build(loss_fn, optax.adam(1e-3), params, batch)
    runner.init(params)
    prog = hlo.parse_hlo_text(runner.lowered_text(batch))
    autodist_tpu.reset()

    bf16_stmts = [st for fn in prog.funcs.values()
                  for st in fn.statements if "bf16" in st.out_dtypes]
    assert bf16_stmts, "bf16 compute tier lowered no bf16 statements"
    # the sizer and the dtype column describe the SAME tensors: the
    # per-replica forward matmul (2x64 @ 64x32 -> 2x32 on the 8-way
    # mesh) lowers in bf16 and must be priced at 2*32*2 = 128 bytes,
    # not the 256 of an f32 width
    dots = [st for st in bf16_stmts if st.op == "dot_general"
            and st.out_dtypes == ["bf16"] and 128 in st.out_bytes]
    assert dots, ("no bf16 dot_general priced at half width: %s"
                  % [(st.op, st.out_dtypes, st.out_bytes)
                     for st in bf16_stmts])
    # the width table itself: half floats at 2, f8 family at 1
    assert hlo.tensor_type_bytes("8x4xbf16") == 64
    assert hlo.tensor_type_bytes("8x4xf16") == 64
    assert hlo.tensor_type_bytes("8x4xf8e4m3fn") == 32
    assert hlo.tensor_type_bytes("8x4xf32") == 128

    # wire pricing: a bf16 collective ships half the bytes of its f32
    # twin through StaticCollectiveProfile (same kind, same group)
    def sched(dtype, bytes_):
        c = hlo.CollectiveOp(kind="reduce", op="all_reduce",
                             payload_bytes=bytes_, result_bytes=bytes_,
                             replica_groups=((0, 1, 2, 3),), channel=0,
                             lineno=1, loop_depth=0, elem_dtype=dtype,
                             payload_elems=bytes_ // (2 if dtype in
                                                      hlo.HALF_DTYPES
                                                      else 4))
        s = hlo.CollectiveSchedule([c])
        return s

    f32_wire = StaticCollectiveProfile.from_schedule(
        sched("f32", 4096), default_group_size=4).total_wire_bytes
    bf16_wire = StaticCollectiveProfile.from_schedule(
        sched("bf16", 2048), default_group_size=4).total_wire_bytes
    assert f32_wire > 0
    assert bf16_wire == pytest.approx(f32_wire / 2)
