"""Environment capability probes for the multi-process test suites.

The two-OS-process suites (``test_distributed``, ``test_elastic``,
``test_local_launch``) need REAL cross-process collectives on the CPU
backend: two processes join one ``jax.distributed`` job and psum across
the process boundary. Some jaxlib builds (including slim CI containers)
ship a CPU backend without multi-process support — every collective fails
with ``Multiprocess computations aren't implemented on the CPU backend``
and the suites carry dozens of environment (not code) failures.

:func:`multiprocess_collectives_supported` answers the question ONCE per
pytest run with an actual two-process probe on the real wire path (two
children, one ``jax.distributed`` job, one broadcast collective) so the
suites can ``skipif`` cleanly instead. Override with ``ADT_MP_PROBE=1``
(force-run the suites) or ``ADT_MP_PROBE=0`` (force-skip, e.g. to keep a
known-bad sandbox fast).
"""
import os
import socket
import subprocess
import sys

import pytest

_PROBE_CHILD = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address="127.0.0.1:%s" % sys.argv[1],
                           num_processes=2, process_id=int(sys.argv[2]))
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices("adt-mp-probe")
assert len(jax.devices()) == 2, jax.devices()
print("MP_PROBE_OK", flush=True)
"""

_RESULT = {}

MP_SKIP_REASON = ("this jaxlib's CPU backend has no multi-process "
                  "collectives (probe failed; ADT_MP_PROBE=1 overrides)")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def multiprocess_collectives_supported(timeout_s: float = 90.0) -> bool:
    """True when two OS processes can run a jax.distributed CPU
    collective here. One real probe per pytest run (memoized)."""
    if "ok" not in _RESULT:
        override = os.environ.get("ADT_MP_PROBE", "").strip()
        if override in ("0", "1"):
            _RESULT["ok"] = override == "1"
        else:
            _RESULT["ok"] = _run_probe(timeout_s)
    return _RESULT["ok"]


def needs_mp_collectives():
    """Decorator for tests whose child processes must psum ACROSS the
    process boundary (global-mesh training, external-launch strategy
    broadcast, sync-elastic restore). Async-PS tests that keep per-process
    local meshes but launch through the collective strategy broadcast need
    it too; pure control-plane tests (supervision, reap patterns, local
    remapper validation) do not and keep running everywhere.

    Returns a plain marker; conftest's ``pytest_runtest_setup`` hook runs
    the (memoized) probe at the FIRST marked test's setup, so collection
    and probe-free runs (``--collect-only``, ``-k`` selecting none of the
    multi-process tests) never pay the two-process spawn."""
    return pytest.mark.needs_mp_collectives


def _run_probe(timeout_s: float) -> bool:
    port = _free_port()
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               JAX_PLATFORMS="cpu")
    # the children must not inherit a worker identity from the test env
    for k in ("ADT_WORKER", "ADT_PROCESS_ID", "ADT_NUM_PROCESSES"):
        env.pop(k, None)
    procs = [
        subprocess.Popen([sys.executable, "-c", _PROBE_CHILD, str(port),
                          str(i)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env)
        for i in (0, 1)]
    ok = True
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout_s)
            ok = ok and p.returncode == 0 and "MP_PROBE_OK" in out
    except subprocess.TimeoutExpired:
        ok = False
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return ok
