"""ZeRO-style sharded weight update (the ZeroSharded synchronizer kind).

Pins the PR's contracts end to end: strategy IR round-trip, training
parity with the AllReduce baseline (per-step AND fused k=4, fp32 and
int8 wire), dispatch parity, the zero.rs_bytes/ag_bytes counters and the
zero.hbm_saved_bytes gauge, the synchronizer-aware plan-level memory
gate (projection within the 20% tolerance of XLA's own buffer
assignment, and a previously-ADT501-gated plan passing and training
under ZeroSharded), the ADT312/313 diagnostics and the search-space
canon that never emits them, the searcher choosing ZeroSharded under a
memory-constrained ResourceSpec, original-layout optimizer-state
reconstruction for checkpoints, and the sharded saver's 4->2
replica-count restore re-laying-out the optimizer shards.
"""
import random
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.analysis import memory as memory_lib
from autodist_tpu.analysis import verify
from autodist_tpu.analysis.diagnostics import Severity
from autodist_tpu.model_item import ModelItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.telemetry import spans as tel


def _spec(n_cpus):
    return ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True,
                    "cpus": list(range(n_cpus))}]})


def _mlp_setup(seed=0, din=64, dout=8, batch=32):
    rng = np.random.RandomState(seed)
    params = {"w": jnp.asarray(rng.randn(din, dout) * 0.1, jnp.float32),
              "v": jnp.asarray(rng.randn(dout, dout) * 0.1, jnp.float32)}
    batch_np = {"x": rng.randn(batch, din).astype(np.float32),
                "y": rng.randn(batch, dout).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["w"])
        return jnp.mean((h @ p["v"] - b["y"]) ** 2)

    return loss_fn, params, batch_np


def _train(builder, loss_fn, params, batch, steps=10, fuse=0, spec=None):
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=builder, resource_spec=spec)
    runner = ad.build(loss_fn, optax.adam(0.05), params, batch)
    runner.init(params)
    if fuse:
        hist = runner.fit([batch] * steps, fuse_steps=fuse)
    else:
        hist = runner.fit([batch] * steps)
    return [float(m["loss"]) for m in hist], runner


# ------------------------------------------------------------ strategy IR


def test_ir_roundtrip_and_unknown_kind():
    loss_fn, params, batch = _mlp_setup()
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-3),
                     params=params, example_batch=batch).prepare()
    spec = _spec(4)
    for builder in (S.ZeroSharded(), S.ZeroSharded(wire_dtype="int8")):
        strat = builder.build(item, spec)
        clone = S.Strategy.from_dict(strat.to_dict())
        assert clone.to_dict() == strat.to_dict()
        assert any(getattr(n.synchronizer, "kind", "") == "ZeroSharded"
                   for n in clone.node_config)
        errs = [d for d in verify(strat, item, spec)
                if d.severity >= Severity.ERROR]
        assert not errs, (builder, errs)
    # the kind is registered in the deserializer's error surface
    from autodist_tpu.analysis.diagnostics import DiagnosticError
    from autodist_tpu.strategy.base import synchronizer_from_dict
    with pytest.raises(DiagnosticError, match="ZeroSharded"):
        synchronizer_from_dict({"kind": "Nope"}, "w")


# --------------------------------------------------------- training parity


def test_zero_parity_per_step_and_fused():
    """Acceptance: ZeroSharded is allclose to the AllReduce baseline
    (params + opt + metrics) per-step, and fused k=4 matches the
    per-step zero loop with the k x dispatch reduction — the sharded
    opt state rides the lax.scan carry."""
    loss_fn, params, batch = _mlp_setup()
    fp, r_fp = _train(S.AllReduce(), loss_fn, params, batch)
    z, r_z = _train(S.ZeroSharded(), loss_fn, params, batch)
    np.testing.assert_allclose(z, fp, rtol=1e-4, atol=1e-6)
    assert (r_z.distributed_step.dispatches
            == r_fp.distributed_step.dispatches)
    # params and reconstructed full optimizer state match the baseline
    pz, pf = r_z.gather_params(), r_fp.gather_params()
    for a, b in zip(jax.tree_util.tree_leaves(pz),
                    jax.tree_util.tree_leaves(pf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    oz = r_z.distributed_step.gather_opt_state(r_z.state)
    of = r_fp.distributed_step.gather_opt_state(r_fp.state)
    za, fa = jax.tree_util.tree_leaves(oz), jax.tree_util.tree_leaves(of)
    assert [np.shape(a) for a in za] == [np.shape(a) for a in fa]
    for a, b in zip(za, fa):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    zf, r_zf = _train(S.ZeroSharded(), loss_fn, params, batch, fuse=5)
    np.testing.assert_allclose(zf, z, rtol=1e-5, atol=1e-6)
    assert (r_zf.distributed_step.dispatches
            == r_z.distributed_step.dispatches // 5)


def test_zero_int8_wire_parity_and_counters():
    """The int8 wire (quantized reduce-scatter + quantized update
    all-gather) stays on the fp32 trajectory; the zero.* counters
    report the payloads; dispatch count is unchanged. Vars sized above
    the per-shard-block int8 floor (>= 8 replicas x 256-element
    blocks)."""
    loss_fn, params, batch = _mlp_setup(seed=3, din=512, dout=64,
                                        batch=16)
    fp, r_fp = _train(S.AllReduce(), loss_fn, params, batch)
    q, r_q = _train(S.ZeroSharded(wire_dtype="int8"), loss_fn, params,
                    batch)
    np.testing.assert_allclose(q, fp, rtol=0.25, atol=1e-3)
    assert abs(q[-1] - fp[-1]) < 0.1 * max(abs(fp[-1]), 1e-3) + 1e-3
    counters = tel.counters()
    assert counters["zero.rs_bytes"] > 0
    assert counters["zero.ag_bytes"] > 0
    assert (r_q.distributed_step.dispatches
            == r_fp.distributed_step.dispatches)
    meta = r_q.distributed_step.metadata
    assert meta["zero_wire_int8"], meta
    # counters == static accounting, exactly (same formula, same source)
    steps = r_q.distributed_step.dispatches
    assert counters["zero.rs_bytes"] == pytest.approx(
        meta["zero_rs_bytes_per_step"] * steps)
    # the quantized payload is far below the fp32 one
    fp32_rs = sum(zs.padded_elems * 4.0
                  for zs in r_q.distributed_step.zero_syncs.values())
    assert meta["zero_rs_bytes_per_step"] < fp32_rs / 2.0
    # fused k=5 matches the per-step quantized loop
    per, _ = _train(S.ZeroSharded(wire_dtype="int8"), loss_fn, params,
                    batch)
    fused, _ = _train(S.ZeroSharded(wire_dtype="int8"), loss_fn, params,
                      batch, fuse=5)
    np.testing.assert_allclose(fused, per, rtol=1e-5, atol=1e-6)


def test_zero_int8_gate_requires_one_block_per_shard():
    """A var above one block TOTAL but below one block PER SHARD must
    stay fp32 (the kernel rounds each shard to whole blocks, so int8
    would ship MORE bytes than fp32 there) — and the cost model's
    padded pricing agrees with the kernel's accounting exactly."""
    from autodist_tpu.kernel.synchronization.zero_synchronizer import (
        zero_wire_payload_bytes)
    from autodist_tpu.parallel.collectives import wire_block_size
    from autodist_tpu.strategy.zero_sharded_strategy import (
        zero_wire_quantizable)
    block = wire_block_size()
    n = 8

    class Info:
        sparse = False
        dtype = "float32"
        num_elements = block + 50  # one block total, sub-block per shard

    assert not zero_wire_quantizable(Info(), n)
    Info.num_elements = n * block
    assert zero_wire_quantizable(Info(), n)
    # below the gate, the padded int8 payload really is worse than fp32
    worse = zero_wire_payload_bytes(block + 50, n, "int8")
    assert worse > zero_wire_payload_bytes(block + 50, n, "fp32")
    # the builder applies the gate: small-var int8 plans self-gate
    loss_fn, params, batch = _mlp_setup()  # 512- and 64-element vars
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-3),
                     params=params, example_batch=batch).prepare()
    strat = S.ZeroSharded(wire_dtype="int8").build(item, _spec(8))
    assert all(n_.synchronizer.wire_dtype == "fp32"
               for n_ in strat.node_config
               if getattr(n_.synchronizer, "kind", "") == "ZeroSharded")


def test_zero_hbm_saved_gauge_and_metadata():
    loss_fn, params, batch = _mlp_setup()
    _, r = _train(S.ZeroSharded(), loss_fn, params, batch, steps=2)
    meta = r.distributed_step.metadata
    assert set(meta["zero_sharded"]) == {"w", "v"}
    assert meta["zero_hbm_saved_bytes"] > 0
    from autodist_tpu.telemetry.spans import get_recorder
    assert get_recorder().gauges().get("zero.hbm_saved_bytes", 0) > 0


def test_zero_single_replica_degrades_to_allreduce():
    loss_fn, params, batch = _mlp_setup(seed=5)
    spec1 = _spec(1)
    fp, _ = _train(S.AllReduce(), loss_fn, params, batch, steps=6,
                   spec=spec1)
    z, r_z = _train(S.ZeroSharded(), loss_fn, params, batch, steps=6,
                    spec=spec1)
    np.testing.assert_allclose(z, fp, rtol=1e-6, atol=1e-7)
    assert not r_z.distributed_step.metadata["zero_sharded"]


# -------------------------------------------------------------- memory gate


@pytest.fixture(scope="module")
def _mem_artifacts():
    """One AllReduce and one ZeroSharded build on a 4-replica CPU mesh,
    sized so optimizer state dominates: plan-level projections and XLA's
    compiled memory stats for both (donated variant — the steady state
    the plan-level heuristic models)."""
    rng = np.random.RandomState(0)
    params = {"w1": np.asarray(rng.randn(256, 512) * 0.05, np.float32),
              "w2": np.asarray(rng.randn(512, 64) * 0.05, np.float32)}
    batch = {"x": rng.randn(16, 256).astype(np.float32),
             "y": rng.randn(16, 64).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["w1"])
        return jnp.mean((h @ p["w2"] - b["y"]) ** 2)

    spec4 = _spec(4)
    out = {"spec": spec4, "loss_fn": loss_fn, "params": params,
           "batch": batch}
    for name, builder in (("ar", S.AllReduce()), ("zero", S.ZeroSharded())):
        autodist_tpu.reset()
        ad = autodist_tpu.AutoDist(strategy_builder=builder,
                                   resource_spec=spec4)
        runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
        runner.init(params)
        dstep = runner.distributed_step
        ps_avals, _ = dstep._ps_avals()
        placed = runner.remapper.remap_feed(batch)
        ma = dstep._step_fn.lower(
            runner.state, ps_avals, placed).compile().memory_analysis()
        out[name] = {
            "strategy": dstep.strategy,
            "item": dstep.model_item,
            "xla_peak": (ma.argument_size_in_bytes
                         + ma.output_size_in_bytes
                         + ma.temp_size_in_bytes
                         - ma.alias_size_in_bytes),
            "metadata": dict(dstep.metadata),
        }
    autodist_tpu.reset()
    return out


def test_plan_gate_projects_zero_drop_within_20pct(_mem_artifacts):
    """Satellite: the synchronizer-aware plan-level gate projects the
    ZeroSharded footprint within the existing 20% tolerance of XLA's own
    buffer assignment, and the projected drop vs AllReduce equals the
    (P-1)/P opt-state fraction the lowering reports."""
    art = _mem_artifacts
    spec, item = art["spec"], art["zero"]["item"]
    p_ar = memory_lib.plan_peak_hbm(art["ar"]["strategy"], item, spec)
    p_z = memory_lib.plan_peak_hbm(art["zero"]["strategy"], item, spec)
    assert p_z < p_ar
    x_z = art["zero"]["xla_peak"]
    assert x_z > 0
    assert abs(p_z - x_z) / x_z < 0.20, (p_z, x_z)
    # the projection's drop IS the lowering's reported opt-state saving
    saved = art["zero"]["metadata"]["zero_hbm_saved_bytes"]
    assert saved > 0
    assert p_ar - p_z == pytest.approx(saved, rel=1e-6)
    # and the measured (XLA) drop confirms the saving is real
    x_ar = art["ar"]["xla_peak"]
    assert x_ar - x_z > 0.5 * saved


def test_adt501_gated_plan_unlocks_and_trains(_mem_artifacts):
    """Acceptance: a budget between the two footprints fails AllReduce
    with ADT501 at plan-lint time, passes ZeroSharded clean — and the
    ZeroSharded plan actually trains under that spec."""
    art = _mem_artifacts
    loss_fn, params, batch = (art["loss_fn"], art["params"], art["batch"])
    tight = ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True,
                    "cpus": [0, 1, 2, 3]}],
         "slice": {"hbm_gib": 2.2 / 1024.0}})
    item = art["zero"]["item"]
    rep_ar = memory_lib.plan_memory_report(
        S.AllReduce().build(item, tight), item, tight)
    rep_z = memory_lib.plan_memory_report(
        S.ZeroSharded().build(item, tight), item, tight)
    assert "ADT501" in [d.code for d in rep_ar["diagnostics"]]
    assert not [d for d in rep_z["diagnostics"]
                if d.severity >= Severity.ERROR]
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.ZeroSharded(),
                               resource_spec=tight)
    runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
    runner.init(params)
    losses = [float(runner.run(batch)["loss"]) for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


# ------------------------------------------------------------- diagnostics


def _emb_item():
    params = {"emb": jnp.zeros((4096, 64)),
              "w": jnp.zeros((64, 512)),
              "tiny": jnp.zeros((2,))}

    def loss_fn(p, batch):
        e = jnp.take(p["emb"], batch["ids"], axis=0)
        return jnp.mean((e @ p["w"]).sum(-1) + p["tiny"].sum())

    batch = {"ids": np.zeros((32,), np.int32)}
    return ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-3),
                     params=params, example_batch=batch).prepare()


def _tpu_spec():
    return ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 4}]})


def test_adt312_and_adt313():
    from autodist_tpu.strategy.base import (GraphConfig, PSSynchronizer,
                                            Strategy, VarConfig,
                                            ZeroShardedSynchronizer)
    item, spec = _emb_item(), _tpu_spec()
    replicas = [d.name_string() for d in spec.devices]

    def plan(nodes):
        return Strategy(node_config=nodes,
                        graph_config=GraphConfig(replicas=replicas))

    def base():
        return [VarConfig(var_name="w",
                          synchronizer=ZeroShardedSynchronizer()),
                VarConfig(var_name="tiny",
                          synchronizer=S.AllReduceSynchronizer()),
                VarConfig(var_name="emb", synchronizer=PSSynchronizer(
                    reduction_destination="127.0.0.1:CPU:0"))]

    # sparse var on the sharded update: error
    n = base()
    n[2] = VarConfig(var_name="emb",
                     synchronizer=ZeroShardedSynchronizer())
    d = verify(plan(n), item, spec)
    assert any(x.code == "ADT312" and x.severity.name == "ERROR"
               and x.var == "emb" for x in d), d
    # sub-shard var: ADT313 warning
    n = base()
    n[1] = VarConfig(var_name="tiny",
                     synchronizer=ZeroShardedSynchronizer())
    d = verify(plan(n), item, spec)
    assert any(x.code == "ADT313" and x.var == "tiny" for x in d), d
    # mp_axes conflict: error
    n = base()
    n[0] = VarConfig(var_name="w", synchronizer=ZeroShardedSynchronizer(),
                     mp_axes={0: "model"})
    d = verify(plan(n), item, spec)
    assert any(x.code == "ADT312" and x.severity.name == "ERROR"
               for x in d), d
    # partitioner conflict: error
    n = base()
    n[0] = VarConfig(var_name="w", synchronizer=ZeroShardedSynchronizer(),
                     partitioner="2,1")
    d = verify(plan(n), item, spec)
    assert any(x.code == "ADT312" and x.severity.name == "ERROR"
               for x in d), d
    # staleness>0 PS beside a zero var: error
    n = base()
    n[2] = VarConfig(var_name="emb", synchronizer=PSSynchronizer(
        reduction_destination="127.0.0.1:CPU:0", staleness=2))
    d = verify(plan(n), item, spec)
    assert any(x.code == "ADT312" and x.severity.name == "ERROR"
               for x in d), d
    # async PS beside a zero var: ADT307 (all-or-nothing) + ADT312
    n = base()
    n[2] = VarConfig(var_name="emb", synchronizer=PSSynchronizer(
        reduction_destination="127.0.0.1:CPU:0", sync=False))
    codes = {x.code for x in verify(plan(n), item, spec)}
    assert "ADT312" in codes and "ADT307" in codes
    # a clean zero plan carries neither
    d = verify(plan(base()), item, spec)
    assert not [x for x in d if x.code in ("ADT312", "ADT313")], d


def test_lowering_raises_what_lint_lists():
    """The compile path refuses the same ADT312 combinations the linter
    reports (sparse var on the sharded update)."""
    loss_fn_params = _emb_item()
    from autodist_tpu.kernel.graph_transformer import GraphTransformer
    from autodist_tpu.strategy.base import (GraphConfig, Strategy,
                                            VarConfig,
                                            ZeroShardedSynchronizer)
    from jax.sharding import Mesh
    item = loss_fn_params
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("data",))
    strat = Strategy(
        node_config=[
            VarConfig(var_name="emb",
                      synchronizer=ZeroShardedSynchronizer()),
            VarConfig(var_name="w", synchronizer=S.AllReduceSynchronizer()),
            VarConfig(var_name="tiny",
                      synchronizer=S.AllReduceSynchronizer())],
        graph_config=GraphConfig(replicas=["127.0.0.1:CPU:%d" % i
                                           for i in range(4)]))
    with pytest.raises(ValueError, match="ADT312"):
        GraphTransformer(strat, mesh, item).transform()


# ------------------------------------------------------------------ search


def test_search_space_zero_axis_canon_sweep():
    """120 random mutations (zero operator included): every materialized
    plan verifies with zero ADT312/313 diagnostics of ANY severity."""
    from autodist_tpu.search.space import PlanSpace
    item, spec = _emb_item(), _tpu_spec()
    space = PlanSpace(item, spec)
    assert space.zero_ok["w"]
    assert not space.zero_ok["emb"]    # sparse
    assert not space.zero_ok["tiny"]   # sub-replica-sized
    seeds = dict(space.seeds())
    assert "seed:zero" in seeds and "seed:zero-int8w" in seeds
    cm = seeds["seed:zero"].choice_map()
    assert cm["w"].zero and not cm["emb"].zero and not cm["tiny"].zero
    cmq = seeds["seed:zero-int8w"].choice_map()
    assert cmq["w"].zero and cmq["w"].wire_dtype == "int8"
    rng = random.Random(0)
    plan = seeds["seed:zero"]
    seen = False
    for _ in range(120):
        out = space.mutate(plan, rng)
        if out is None:
            continue
        plan, desc = out
        seen |= desc.startswith("zero[")
        strat = space.build(plan)
        bad = [d for d in verify(strat, item, spec)
               if d.code in ("ADT312", "ADT313")]
        assert not bad, (desc, plan, bad)
    assert seen, "zero operator never fired in 120 draws"


def test_from_strategy_roundtrips_zero_axis():
    from autodist_tpu.search.space import PlanSpace
    item, spec = _emb_item(), _tpu_spec()
    space = PlanSpace(item, spec)
    plan = space.from_strategy(
        S.ZeroSharded(wire_dtype="int8").build(item, spec))
    assert plan is not None
    cm = plan.choice_map()
    assert cm["w"].zero and cm["w"].wire_dtype == "int8"
    assert not cm["emb"].zero and not cm["tiny"].zero
    assert "zero=" in plan.describe()


def test_search_picks_zero_when_memory_constrained(monkeypatch):
    """Satellite: a memory-constrained ResourceSpec (small
    slice.hbm_gib) makes the searcher pick ZeroSharded for the large
    vars (prime dims keep divisor-based partitioning out of the space —
    the flat ZeRO shard is the only sharding that applies); a
    headroom-rich spec refuses the extra collective launches."""
    from autodist_tpu.search.drivers import SearchConfig, run_search
    from autodist_tpu.simulator import cost_model as cm_lib
    monkeypatch.setattr(cm_lib, "PCIE_BANDWIDTH_BYTES_S", 1e8)
    width = 257  # prime: no divisor-based partitioning exists
    params = {"w%d" % i: jnp.zeros((width, width)) for i in range(3)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(3):
            h = jnp.tanh(h @ p["w%d" % i])
        return jnp.mean(h ** 2)

    batch = {"x": np.zeros((16, width), np.float32)}
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-3),
                     params=params, example_batch=batch).prepare()
    tight = ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 4}],
         "slice": {"hbm_gib": 2.83 / 1024.0}})
    r = run_search(item, tight, config=SearchConfig(budget=48, seed=0))
    assert r.ok
    zeroed = [n for n, c in r.plan.choices if c.zero]
    assert zeroed, ("memory-constrained search never chose ZeroSharded: "
                    "%s" % r.plan.describe())
    rich = _tpu_spec()
    r2 = run_search(item, rich, config=SearchConfig(budget=48, seed=0))
    assert r2.ok
    assert not [n for n, c in r2.plan.choices if c.zero], \
        r2.plan.describe()


def test_cost_model_prices_zero_like_allreduce_wire():
    """rs + ag move the same ring bytes as the all-reduce: identical
    allreduce_s, strictly lower HBM, and the int8 wire prices at the
    quantized payload."""
    from autodist_tpu.simulator.cost_model import CostModel
    item, spec = _emb_item(), _tpu_spec()
    cm = CostModel(item, spec)
    ar = cm.estimate(S.AllReduce().build(item, spec))
    z = cm.estimate(S.ZeroSharded().build(item, spec))
    assert z.allreduce_s == pytest.approx(ar.allreduce_s)
    assert z.hbm_bytes < ar.hbm_bytes
    # the int8 wire prices the eligible var at the quantized payload
    # (the sparse emb's dense-priced wire dominates this model, so the
    # total shrinks by w's 3/4 saving only)
    zq = cm.estimate(S.ZeroSharded(wire_dtype="int8").build(item, spec))
    assert zq.allreduce_s < z.allreduce_s
    w_bytes = item.var_infos["w"].num_elements * 4
    saved = (z.allreduce_s - zq.allreduce_s)
    assert saved > 0.5 * (2.0 * 3 / 4) * w_bytes * 0.75 / (
        spec.ici_bandwidth_gbps() * 1e9 / 8)


# -------------------------------------------------------------- checkpoints


def test_plain_saver_roundtrip_and_full_opt_layout(tmp_path):
    """Original-layout checkpoints: gather_opt_state reconstructs the
    full optimizer tree from the sync_state shards, and a save/restore
    round trip replays deterministically."""
    from autodist_tpu.checkpoint import Saver
    loss_fn, params, batch = _mlp_setup(seed=7)
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.ZeroSharded())
    runner = ad.build(loss_fn, optax.adam(0.05), params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    saver.save(runner)
    for _ in range(2):
        runner.run(batch)
    a = runner.gather_params()
    saver.restore(runner)
    for _ in range(2):
        runner.run(batch)
    b = runner.gather_params()
    np.testing.assert_array_equal(np.asarray(a["w"]), np.asarray(b["w"]))


def test_elastic_snapshot_adopt_relays_zero_shards():
    """In-run elastic shrink path: `elastic.snapshot_runner_state` on a
    4-replica ZeroSharded runner adopts onto a 2-replica rebuild with
    the optimizer shards re-laid-out (the live-handoff analog of the
    sharded checkpoint's cross-topology restore) — adam moments
    preserved, training continues."""
    from autodist_tpu.runtime import elastic
    loss_fn, params, batch = _mlp_setup(seed=11, din=128, dout=16)
    _, r4 = _train(S.ZeroSharded(), loss_fn, params, batch, steps=3,
                   spec=_spec(4))
    snap = elastic.snapshot_runner_state(r4)
    assert snap is not None and snap.get("mesh")
    opt4 = r4.distributed_step.gather_opt_state(r4.state)
    p4 = r4.gather_params()
    autodist_tpu.reset()
    ad2 = autodist_tpu.AutoDist(strategy_builder=S.ZeroSharded(),
                                resource_spec=_spec(2))
    r2 = ad2.build(loss_fn, optax.adam(0.05), params, batch)
    r2.init(params)
    elastic.adopt_snapshot(r2, snap)
    for a, b in zip(jax.tree_util.tree_leaves(p4),
                    jax.tree_util.tree_leaves(r2.gather_params())):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    opt2 = r2.distributed_step.gather_opt_state(r2.state)
    for a, b in zip(jax.tree_util.tree_leaves(opt4),
                    jax.tree_util.tree_leaves(opt2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    assert np.isfinite(float(r2.run(batch)["loss"]))


def test_sharded_restore_across_replica_count_change():
    """Satellite: the sharded saver stores only locally-owned opt-state
    shards (they ride the sync_state tree's per-device slices), and a
    4 -> 2 replica-count restore re-lays the optimizer shards out
    exactly — adam moments survive the topology change — falling back
    through the existing integrity scan when the newest checkpoint is
    damaged."""
    from autodist_tpu.checkpoint.sharded import ShardedSaver
    loss_fn, params, batch = _mlp_setup(seed=9, din=128, dout=16)
    d = tempfile.mkdtemp()
    _, r4 = _train(S.ZeroSharded(), loss_fn, params, batch, steps=3,
                   spec=_spec(4))
    saver = ShardedSaver(directory=d)
    saver.save(r4)  # the good checkpoint (step 3)
    full_opt_4 = r4.distributed_step.gather_opt_state(r4.state)
    full_params_4 = r4.gather_params()
    r4.run(batch)
    base = saver.save(r4)  # newest (step 4) — about to be damaged
    import glob
    import os
    shard = glob.glob(base + ".shard-p*.npz")[0]
    with open(shard, "r+b") as f:
        f.seek(0)
        f.write(b"\0" * 64)

    autodist_tpu.reset()
    ad2 = autodist_tpu.AutoDist(strategy_builder=S.ZeroSharded(),
                                resource_spec=_spec(2))
    r2 = ad2.build(loss_fn, optax.adam(0.05), params, batch)
    r2.init(params)
    state, step = ShardedSaver(directory=d).restore(r2)
    assert step == 3  # integrity scan skipped the damaged newest save
    full_opt_2 = r2.distributed_step.gather_opt_state(r2.state)
    full_params_2 = r2.gather_params()
    for a, b in zip(jax.tree_util.tree_leaves(full_params_4),
                    jax.tree_util.tree_leaves(full_params_2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(full_opt_4),
                    jax.tree_util.tree_leaves(full_opt_2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    m = r2.run(batch)
    assert np.isfinite(float(m["loss"]))
    assert os.path.isdir(d)
