"""Training health sentinel: in-graph guards, skip/rollback policy,
gradient fault injection, healthy-stamped checkpoints.

The acceptance matrix (ISSUE 9):

- zero-overhead clean path: guards add no dispatches and no extra
  readbacks, and guarded numerics match the unguarded run exactly;
- chaos proof under ``ADT_GRAD_FAULT_PLAN``: (a) a transient NaN step is
  skipped in-graph and the run converges to the fault-free loss, (b) a
  sustained corruption rolls back to the last healthy-stamped checkpoint
  and completes without ``TrainingDiverged``, (c) the same plan with the
  sentinel disabled demonstrably corrupts the run;
- fused parity: ``multi_step(k=4)`` under guards is allclose to the
  guarded per-step loop and a mid-scan NaN poisons exactly that
  microstep's stacked verdict;
- quarantine: saves vetoed while the verdict is bad, the ``healthy``
  stamp steers restore/auto-resume away from poisoned checkpoints, and
  pre-stamp checkpoints stay resumable (healthy-unknown).

Fast variants run in tier-1; the heavier strategy matrix is slow-marked
for nightly-chaos.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.runtime.sentinel import (Sentinel, SentinelPolicy,
                                           TrainingDiverged, resolve_policy)
from autodist_tpu.telemetry import spans as tel


def _problem(seed=0):
    rng = np.random.RandomState(seed)
    params = {"w": jnp.asarray(rng.randn(4, 2).astype(np.float32)),
              "b": jnp.zeros((2,), jnp.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] + p["b"] - b["y"]) ** 2)

    batch = {"x": rng.randn(16, 4).astype(np.float32),
             "y": rng.randn(16, 2).astype(np.float32)}
    return params, loss_fn, batch


def _build(make_builder, params, loss_fn, batch, sentinel=None, opt=None):
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=make_builder())
    runner = ad.build(loss_fn, opt or optax.adam(0.1), params, batch,
                      sentinel=sentinel)
    runner.init(params)
    return runner


def _train(runner, batch, steps):
    return [float(runner.run(batch)["loss"]) for _ in range(steps)]


def _set_plan(monkeypatch, faults):
    monkeypatch.setenv("ADT_GRAD_FAULT_PLAN",
                       json.dumps({"faults": faults}))


# ------------------------------------------------------------ clean path


def test_clean_path_zero_overhead_and_parity():
    """Guards must be free on the healthy path: identical numerics,
    identical dispatch count, identical readback count — the verdict
    rides the existing metrics transfer."""
    params, loss_fn, batch = _problem()
    plain = _build(lambda: S.AllReduce(), params, loss_fn, batch)
    losses_plain = _train(plain, batch, 6)
    d_plain = plain.distributed_step.dispatches
    rb_plain = tel.counters()["runner.readbacks"]

    guarded = _build(lambda: S.AllReduce(), params, loss_fn, batch,
                     sentinel=True)
    losses_guarded = _train(guarded, batch, 6)
    assert guarded.distributed_step.dispatches == d_plain
    assert tel.counters()["runner.readbacks"] == rb_plain
    np.testing.assert_allclose(losses_guarded, losses_plain, rtol=1e-6)
    gp = guarded.distributed_step.gather_params(guarded.state)
    pp = plain.distributed_step.gather_params(plain.state)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), gp, pp)
    stats = guarded.step_stats()["sentinel"]
    assert stats["skips"] == 0 and stats["rollbacks"] == 0
    assert stats["last_grad_norm"] is not None
    assert stats["quarantined"] is False
    autodist_tpu.reset()


# ------------------------------------------- chaos criteria (a) and (c)


@pytest.mark.parametrize("name,make_builder", [
    ("AllReduce", lambda: S.AllReduce()),
    ("PS", lambda: S.PS()),
], ids=["AllReduce", "PS"])
def test_transient_nan_skipped_and_converges(monkeypatch, name,
                                             make_builder):
    """Criterion (a): a NaN gradient at one step is discarded in-graph
    (params carry unchanged, PS push suppressed) and the run converges
    to the fault-free loss."""
    params, loss_fn, batch = _problem()
    clean = _build(make_builder, params, loss_fn, batch)
    loss_clean = _train(clean, batch, 30)[-1]

    _set_plan(monkeypatch, [{"var": "w", "mode": "nan", "step": 3}])
    runner = _build(make_builder, params, loss_fn, batch, sentinel=True)
    losses = _train(runner, batch, 30)
    assert all(np.isfinite(losses))
    # the skipped step's update was discarded: the NEXT step sees the
    # same params, so its loss repeats the pre-fault value
    assert losses[4] == pytest.approx(losses[3])
    stats = runner.step_stats()["sentinel"]
    assert stats["skips"] == 1
    assert tel.counters()["sentinel.skips"] == 1
    assert tel.counters()["sentinel.nan_steps"] == 1
    # one discarded update costs one step of progress, not convergence
    assert losses[-1] == pytest.approx(loss_clean, rel=0.15)
    if name == "PS":
        assert tel.counters()["sentinel.ps_suppressed"] >= 1
    autodist_tpu.reset()


def test_sentinel_disabled_same_plan_corrupts(monkeypatch):
    """Criterion (c): without the sentinel the identical plan poisons the
    run — the guard is what makes the difference."""
    params, loss_fn, batch = _problem()
    _set_plan(monkeypatch, [{"var": "w", "mode": "nan", "step": 3}])
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batch)
    losses = _train(runner, batch, 8)
    assert not np.isfinite(losses[-1])
    autodist_tpu.reset()


def test_grad_norm_limit_skips_scale_spike(monkeypatch):
    """A finite scale-spike passes the NaN guards but trips the
    grad-norm limit; ``nan_steps`` stays untouched (it counts nonfinite
    faults only)."""
    params, loss_fn, batch = _problem()
    _set_plan(monkeypatch, [{"var": "w", "mode": "scale", "step": 2,
                             "factor": 1e6}])
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batch,
                    sentinel=SentinelPolicy(grad_norm_limit=100.0))
    losses = _train(runner, batch, 8)
    assert all(np.isfinite(losses))
    assert losses[3] == pytest.approx(losses[2])  # spiked update discarded
    assert runner.step_stats()["sentinel"]["skips"] == 1
    assert tel.counters()["sentinel.nan_steps"] == 0
    autodist_tpu.reset()


def test_bitflip_injection_is_deterministic(monkeypatch):
    """Bit-flip mode: flipping a float32 exponent MSB blows the gradient
    up to nonfinite/huge — caught by the guards — and two identical runs
    inject identically (step-keyed, not wall-clock-keyed)."""
    params, loss_fn, batch = _problem()
    _set_plan(monkeypatch, [{"var": "w", "mode": "bitflip", "step": 2,
                             "bit": 30, "index": 0}])
    skips = []
    for _ in range(2):
        runner = _build(lambda: S.AllReduce(), params, loss_fn, batch,
                        sentinel=SentinelPolicy(grad_norm_limit=100.0))
        losses = _train(runner, batch, 6)
        assert all(np.isfinite(losses))
        skips.append(runner.step_stats()["sentinel"]["skips"])
    assert skips[0] == skips[1] == 1
    autodist_tpu.reset()


def test_sharded_storage_grad_norm_is_exact():
    """Partitioned storage reports the SAME global grad norm as
    replicated storage: sharded leaves contribute ``local * S/N``
    through one psum — the scaling must be exact, not approximate."""
    rng = np.random.RandomState(0)
    params = {"big": jnp.asarray(rng.randn(64, 8).astype(np.float32)),
              "w": jnp.asarray(rng.randn(8, 2).astype(np.float32))}

    def loss_fn(p, b):
        return jnp.mean(((b["x"] @ p["big"]) @ p["w"] - b["y"]) ** 2)

    batch = {"x": rng.randn(16, 64).astype(np.float32),
             "y": rng.randn(16, 2).astype(np.float32)}
    part = _build(lambda: S.PartitionedAR(), params, loss_fn, batch,
                  sentinel=True, opt=optax.sgd(0.01))
    assert any(l.partitioned for l in part.distributed_step.layouts.values())
    norm_part = float(part.run(batch)["sentinel"]["grad_norm"])
    repl = _build(lambda: S.AllReduce(), params, loss_fn, batch,
                  sentinel=True, opt=optax.sgd(0.01))
    norm_repl = float(repl.run(batch)["sentinel"]["grad_norm"])
    np.testing.assert_allclose(norm_part, norm_repl, rtol=1e-4)
    autodist_tpu.reset()


# -------------------------------------------------- fused parity (k=4)


@pytest.mark.parametrize("name,make_builder", [
    ("AllReduce", lambda: S.AllReduce()),
    ("PS", lambda: S.PS()),
], ids=["AllReduce", "PS"])
def test_fused_guarded_parity_and_microstep_verdict(monkeypatch, name,
                                                    make_builder):
    """Fused k=4 under guards: allclose to the guarded per-step loop
    (params + opt + skip decisions), and a mid-scan NaN microstep
    poisons exactly that microstep's stacked verdict."""
    params, loss_fn, batch = _problem()
    _set_plan(monkeypatch, [{"var": "w", "mode": "nan", "step": 2}])
    stack = jax.tree_util.tree_map(lambda l: np.stack([l] * 4), batch)

    per_step = _build(make_builder, params, loss_fn, batch, sentinel=True)
    step_losses = _train(per_step, batch, 4)
    per_step.distributed_step.flush_ps()
    p_ref = per_step.distributed_step.gather_params(per_step.state)
    o_ref = per_step.distributed_step.gather_opt_state(per_step.state)
    skips_ref = per_step.step_stats()["sentinel"]["skips"]

    fused = _build(make_builder, params, loss_fn, batch, sentinel=True)
    handle = fused.run_superstep(stack, sync=True)
    oks = [int(m["sentinel"]["ok"]) for m in
           [jax.tree_util.tree_map(lambda a, i=i: np.asarray(a)[i], handle)
            for i in range(4)]]
    assert oks == [1, 1, 0, 1]  # exactly the faulted microstep is bad
    fused_losses = [float(np.asarray(handle["loss"])[i]) for i in range(4)]
    np.testing.assert_allclose(fused_losses, step_losses, rtol=1e-5)
    fused.distributed_step.flush_ps()
    p_fused = fused.distributed_step.gather_params(fused.state)
    o_fused = fused.distributed_step.gather_opt_state(fused.state)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
        p_fused, p_ref)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
        o_fused, o_ref)
    assert fused.step_stats()["sentinel"]["skips"] == skips_ref == 1
    autodist_tpu.reset()


# --------------------------------------- rollback ladder (criterion b)


def test_sustained_corruption_rolls_back_and_completes(monkeypatch,
                                                       tmp_path):
    """Criterion (b): a bounded sustained NaN window exhausts the skip
    budget, training rolls back to the last healthy-stamped checkpoint,
    the widened replay budget skips through the window, and the run
    completes without ``TrainingDiverged``."""
    from autodist_tpu.checkpoint.saver import Saver
    params, loss_fn, batch = _problem()
    _set_plan(monkeypatch, [{"var": "w", "mode": "nan", "step": 4,
                             "until": 6}])
    policy = SentinelPolicy(max_skips_per_window=2, window_steps=50)
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batch,
                    sentinel=policy)
    saver = Saver(directory=str(tmp_path), max_to_keep=10)
    import itertools
    history = runner.fit(itertools.repeat(batch), steps=16, save_every=2,
                         saver=saver)
    assert len(history) == 16
    stats = runner.step_stats()["sentinel"]
    assert stats["rollbacks"] == 1
    # pass 1 skips all 3 faulty steps (rollback pends on the 3rd, past
    # budget 2); the replay skips them again under the widened budget
    assert stats["skips"] == 6
    assert tel.counters()["sentinel.rollbacks"] == 1
    assert tel.counters()["ckpt.restores"] >= 1
    final_loss = float(history[-1]["loss"])
    assert np.isfinite(final_loss)
    # training genuinely progressed past the fault window
    assert final_loss < float(history[0]["loss"])
    autodist_tpu.reset()


def test_unbounded_corruption_escalates_to_typed_failure(monkeypatch,
                                                         tmp_path):
    """The escalation ladder's hard floor: an unbounded fault defeats
    skip-widening and LR-halving, and the run fails with the typed
    ``TrainingDiverged`` after ``max_rollbacks_per_step`` rollbacks."""
    from autodist_tpu.checkpoint.saver import Saver
    params, loss_fn, batch = _problem()
    _set_plan(monkeypatch, [{"var": "w", "mode": "nan", "step": 4,
                             "until": 100000}])
    policy = SentinelPolicy(max_skips_per_window=1, window_steps=50,
                            max_rollbacks_per_step=2)
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batch,
                    sentinel=policy)
    saver = Saver(directory=str(tmp_path), max_to_keep=10)
    import itertools
    with pytest.raises(TrainingDiverged, match="escalation ladder"):
        runner.fit(itertools.repeat(batch), steps=64, save_every=2,
                   saver=saver)
    assert runner.step_stats()["sentinel"]["rollbacks"] == 2
    # the second rollback at the same step halved the effective LR
    assert runner.sentinel.lr_scale == pytest.approx(0.5)
    assert tel.counters()["sentinel.lr_halvings"] == 1
    autodist_tpu.reset()


def test_rollback_without_checkpoints_is_typed(monkeypatch, tmp_path):
    """A rollback with nothing to restore must fail with the typed
    error naming the fix, not a generic FileNotFoundError."""
    params, loss_fn, batch = _problem()
    monkeypatch.setenv("ADT_CKPT_DIR", str(tmp_path))
    _set_plan(monkeypatch, [{"var": "w", "mode": "nan", "step": 1,
                             "until": 100000}])
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batch,
                    sentinel=SentinelPolicy(max_skips_per_window=1,
                                            window_steps=50))
    with pytest.raises(TrainingDiverged, match="no healthy committed"):
        _train(runner, batch, 10)
    autodist_tpu.reset()


def test_lr_halving_scales_updates_exactly():
    """The escalation's LR mechanism: halving ``lr_scale`` through the
    sync_state halves the applied update exactly (linear-in-lr optax
    semantics) without recompiling."""
    params, loss_fn, batch = _problem()
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batch,
                    sentinel=True, opt=optax.sgd(0.1))
    ref = _build(lambda: S.AllReduce(), params, loss_fn, batch,
                 sentinel=True, opt=optax.sgd(0.05))
    sen = Sentinel(SentinelPolicy(), runner)
    sen._halve_lr()  # lr_scale 1.0 -> 0.5
    d_half = runner.distributed_step.dispatches
    runner.run(batch)
    assert runner.distributed_step.dispatches == d_half + 1  # no recompile
    ref.run(batch)
    p_half = runner.distributed_step.gather_params(runner.state)
    p_ref = ref.distributed_step.gather_params(ref.state)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6), p_half,
        p_ref)
    autodist_tpu.reset()


# ------------------------------------------- quarantine + healthy stamp


def test_quarantine_vetoes_saves_and_stamps(monkeypatch, tmp_path):
    """While the verdict is bad: saves are vetoed (quarantine on) or
    stamped unhealthy (quarantine off); automatic restore paths skip the
    unhealthy stamp, an explicit path overrides it."""
    from autodist_tpu.checkpoint import integrity
    from autodist_tpu.checkpoint.saver import Saver
    params, loss_fn, batch = _problem()
    _set_plan(monkeypatch, [{"var": "w", "mode": "nan", "step": 2,
                             "until": 100000}])
    policy = SentinelPolicy(max_skips_per_window=100, window_steps=10)
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batch,
                    sentinel=policy)
    saver = Saver(directory=str(tmp_path))
    _train(runner, batch, 2)          # healthy so far
    assert saver.save(runner) is not None
    healthy_base = saver.latest()
    _train(runner, batch, 2)          # now inside the fault window
    assert runner.sentinel_save_veto()
    assert saver.save(runner) is None  # vetoed
    assert tel.counters()["sentinel.save_vetoes"] == 1

    # quarantine off: the save proceeds but carries the honest stamp
    runner.sentinel.policy.quarantine = False
    assert not runner.sentinel_save_veto()
    bad_base = saver.save(runner)
    assert bad_base is not None and bad_base != healthy_base
    status = integrity.validate_plain(*integrity.parse_base(bad_base))
    assert status.committed and status.healthy is False
    good = integrity.validate_plain(*integrity.parse_base(healthy_base))
    assert good.healthy is True

    # automatic paths skip the poisoned newest step
    assert saver.latest() == healthy_base
    _, step = saver.restore(runner)
    assert step == int(healthy_base.rsplit("ckpt-", 1)[1])
    assert tel.counters()["ckpt.unhealthy_skipped"] >= 2
    # an explicit path is a human override
    _, step = saver.restore(runner, path=bad_base)
    assert step == int(bad_base.rsplit("ckpt-", 1)[1])
    autodist_tpu.reset()


def test_prestamp_checkpoint_is_healthy_unknown(tmp_path):
    """Backfill semantics: a checkpoint whose meta predates the stamp
    classifies healthy-unknown (None) — resumable, never rejected."""
    from autodist_tpu.checkpoint import integrity
    from autodist_tpu.checkpoint.saver import Saver
    params, loss_fn, batch = _problem()
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batch)
    saver = Saver(directory=str(tmp_path))
    base = saver.save(runner)
    meta_path = base + ".meta.json"
    with open(meta_path) as f:
        meta = json.load(f)
    assert meta["healthy"] is True  # new saves always stamp
    meta.pop("healthy")
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    status = integrity.validate_plain(*integrity.parse_base(base))
    assert status.committed and status.healthy is None
    assert saver.latest() == base          # unknown stays resumable
    _, step = saver.restore(runner)
    assert step == int(base.rsplit("ckpt-", 1)[1])
    autodist_tpu.reset()


def test_sharded_saver_stamps_and_skips_unhealthy(tmp_path):
    """The sharded format carries the same stamp and the same automatic
    skip (the scale path must not be the unprotected one)."""
    from autodist_tpu.checkpoint import integrity
    from autodist_tpu.checkpoint.sharded import ShardedSaver
    params, loss_fn, batch = _problem()
    runner = _build(lambda: S.PartitionedAR(), params, loss_fn, batch)
    saver = ShardedSaver(directory=str(tmp_path))
    _train(runner, batch, 2)
    good = saver.save(runner)
    assert good is not None
    status = integrity.validate_sharded(*integrity.parse_base(good))
    assert status.healthy is True
    _train(runner, batch, 2)
    bad = saver.save(runner)
    # forge an unhealthy stamp on the newest step (a quarantine-off save
    # under a bad verdict would write exactly this)
    meta_path = bad + ".shard-meta.json"
    with open(meta_path) as f:
        meta = json.load(f)
    meta["healthy"] = False
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    status = integrity.validate_sharded(*integrity.parse_base(bad))
    assert status.committed and status.healthy is False
    assert saver.latest() == good
    _, step = saver.restore(runner)
    assert step == int(good.rsplit("ckpt-", 1)[1])
    assert tel.counters()["ckpt.unhealthy_skipped"] >= 2
    autodist_tpu.reset()


def test_cli_displays_health_stamp(tmp_path, capsys):
    """``checkpoint ls`` shows the stamp column: yes / NO / ? (and fsck
    counts unhealthy steps)."""
    from autodist_tpu.checkpoint import cli
    from autodist_tpu.checkpoint.saver import Saver
    params, loss_fn, batch = _problem()
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batch)
    saver = Saver(directory=str(tmp_path))
    _train(runner, batch, 1)
    base1 = saver.save(runner)
    _train(runner, batch, 1)
    base2 = saver.save(runner)
    # base1 -> pre-stamp (unknown), base2 -> unhealthy
    for base, mutate in ((base1, lambda m: m.pop("healthy")),
                         (base2, lambda m: m.update(healthy=False))):
        with open(base + ".meta.json") as f:
            meta = json.load(f)
        mutate(meta)
        with open(base + ".meta.json", "w") as f:
            json.dump(meta, f)
    assert cli.main(["--dir", str(tmp_path), "ls"]) == 0
    out = capsys.readouterr().out
    assert "HEALTHY" in out
    lines = {int(ln.split()[0]): ln for ln in out.splitlines()
             if ln.strip() and ln.split()[0].isdigit()}
    assert " ? " in lines[int(base1.rsplit("ckpt-", 1)[1])]
    assert " NO " in lines[int(base2.rsplit("ckpt-", 1)[1])]
    assert cli.main(["--dir", str(tmp_path), "fsck"]) == 0
    assert "1 stamped unhealthy" in capsys.readouterr().out
    # json surface carries it too
    assert cli.main(["--dir", str(tmp_path), "ls", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert {s["step"]: s["healthy"] for s in payload} == {
        int(base1.rsplit("ckpt-", 1)[1]): None,
        int(base2.rsplit("ckpt-", 1)[1]): False}
    autodist_tpu.reset()


# -------------------------------------------------- policy engine units


def test_policy_env_resolution(monkeypatch):
    monkeypatch.delenv("ADT_SENTINEL", raising=False)
    assert resolve_policy(None) is None
    assert resolve_policy(False) is None
    assert isinstance(resolve_policy(True), SentinelPolicy)
    monkeypatch.setenv("ADT_SENTINEL", "1")
    assert isinstance(resolve_policy(None), SentinelPolicy)
    monkeypatch.setenv("ADT_SENTINEL",
                       '{"max_skips_per_window": 7, "spike_zscore": 4.5}')
    p = resolve_policy(None)
    assert p.max_skips_per_window == 7 and p.spike_zscore == 4.5
    monkeypatch.setenv("ADT_SENTINEL", "0")
    assert resolve_policy(None) is None
    with pytest.raises(ValueError, match="window_steps"):
        SentinelPolicy(window_steps=0)
    with pytest.raises(TypeError):
        resolve_policy("yes")


def test_grad_fault_plan_rejects_unknown_fields():
    """The grad grammar is step-keyed: wire/ckpt knobs (nth/prob/...)
    must be rejected loudly, not silently dropped — a plan that tests
    something other than what it declares is worse than an error."""
    from autodist_tpu.runtime.faultinject import GradFaultPlan
    with pytest.raises(ValueError, match="unknown gradient fault field"):
        GradFaultPlan({"faults": [{"var": "w", "mode": "nan", "prob": 0.5}]})
    with pytest.raises(ValueError, match="unknown fault mode|unknown "
                                         "gradient fault"):
        GradFaultPlan({"faults": [{"var": "w", "mode": "explode"}]})
    # a top-level seed is tolerated for grammar-family symmetry only
    assert GradFaultPlan({"seed": 7, "faults": []}).rules == []


def test_lr_scale_resyncs_on_restore(monkeypatch, tmp_path):
    """The LR scale lives in three places (in-graph sync_state, the PS
    store, the Sentinel's ladder accounting); a restore replaces only
    the first — notify_state_restored must re-sync the other two, or an
    auto-resume after an escalation trains PS and device vars at
    different effective rates."""
    from autodist_tpu.checkpoint.saver import Saver
    params, loss_fn, batch = _problem()
    runner = _build(lambda: S.PS(), params, loss_fn, batch, sentinel=True)
    saver = Saver(directory=str(tmp_path))
    _train(runner, batch, 2)
    saver.save(runner)                     # checkpoint carries scale 1.0
    runner.sentinel._halve_lr()            # escalate: every copy -> 0.5
    assert runner.distributed_step.ps_store.update_scale == 0.5
    assert runner.sentinel.lr_scale == 0.5
    saver.restore(runner)                  # restored state says 1.0
    assert runner.distributed_step.ps_store.update_scale == 1.0
    assert runner.sentinel.lr_scale == 1.0
    autodist_tpu.reset()


def test_ewma_spike_detection_pends_rollback():
    """The loss-spike path the finiteness guards cannot see: a sustained
    EWMA z-score breach pends a rollback after ``spike_patience``
    consecutive spiking steps; a single outlier does not."""
    policy = SentinelPolicy(spike_zscore=4.0, spike_patience=3,
                            min_history=5, ewma_alpha=0.2)
    sen = Sentinel(policy, runner=None)
    for i in range(20):
        sen.observe({"loss": 1.0 + 0.01 * np.sin(i),
                     "sentinel": {"ok": 1, "grad_norm": 1.0,
                                  "bad_grads": 0, "bad_params": 0}})
    assert sen._pending_rollback is None
    spike = {"loss": 50.0, "sentinel": {"ok": 1, "grad_norm": 1.0,
                                        "bad_grads": 0, "bad_params": 0}}
    sen.observe(spike)
    assert sen._pending_rollback is None  # one outlier is not sustained
    sen.observe(spike)
    assert sen._pending_rollback is None
    sen.observe(spike)
    assert sen._pending_rollback is not None
    assert "loss spike" in sen._pending_rollback
    assert sen.quarantined  # saves vetoed while the spike is live


def test_unguarded_nonfinite_loss_pends_rollback():
    """step_fn-mode degradation: with no in-graph guards a nonfinite
    loss cannot be skipped, so it goes straight to the rollback path."""
    sen = Sentinel(SentinelPolicy(), runner=None)
    sen.observe({"loss": 1.0})
    assert sen._pending_rollback is None
    sen.observe({"loss": float("nan")})
    assert sen._pending_rollback is not None


def test_verify_sentinel_diagnostics():
    from autodist_tpu.analysis import rules
    policy = SentinelPolicy(window_steps=2)
    # guards compiled, small windows: clean
    assert rules.verify_sentinel(
        policy, {"sentinel_guards": True, "staleness": 0}) == []
    # no guards -> ADT420
    codes = [d.code for d in rules.verify_sentinel(
        policy, {"sentinel_guards": False})]
    assert codes == ["ADT420"]
    # stale window beyond the skip window -> ADT421
    codes = [d.code for d in rules.verify_sentinel(
        policy, {"sentinel_guards": True, "staleness": 5})]
    assert codes == ["ADT421"]
    assert rules.verify_sentinel(None, {}) == []


def test_step_fn_mode_gets_adt420_runner_diag():
    """build_step + sentinel: the opaque program carries no guards — the
    Runner logs ADT420 and the sentinel degrades to loss monitoring."""
    params, _, batch = _problem()

    def step_fn(state, b):
        loss = jnp.mean((b["x"] @ state["w"] + state["b"] - b["y"]) ** 2)
        return state, {"loss": loss}

    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build_step(step_fn, params, batch, sentinel=True)
    assert [d.code for d in runner._sentinel_diags] == ["ADT420"]
    runner.init(params)
    m = runner.run(batch)
    assert "sentinel" not in m  # no in-graph verdict on the opaque path
    assert runner.step_stats()["sentinel"]["skips"] == 0
    autodist_tpu.reset()


# ------------------------------------------ heartbeat compile grace


class _FakeCoordClient:
    def __init__(self):
        self.calls = []
        self.kv = {}

    def heartbeat(self, worker):
        self.calls.append(("heartbeat", worker))

    def put(self, key, value):
        self.calls.append(("put", key, value))
        self.kv[key] = value

    def get(self, key):
        return self.kv.get(key)


def test_pre_compile_heartbeat_and_grace_mark():
    """The heartbeat false-death fix: a beat plus a one-shot 'compiling'
    mark land BEFORE the first dispatch (which carries the compile), and
    the mark is cleared the moment the dispatch returns."""
    params, loss_fn, batch = _problem()
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batch)
    fake = _FakeCoordClient()
    runner._hb_enabled = True
    runner._async_hb = fake
    runner.run(batch)
    kinds = [c[0] for c in fake.calls]
    assert kinds[:2] == ["heartbeat", "put"]  # beat + mark pre-dispatch
    assert fake.calls[1][1] == "compiling/chief"
    assert float(fake.calls[1][2]) > 0
    # one-shot: cleared after the first dispatch (epoch-zero mark — the
    # line protocol needs a non-empty value), never re-marked
    assert fake.calls[-1] == ("put", "compiling/chief", "0")
    n_calls = len(fake.calls)
    runner.run(batch)
    assert [c for c in fake.calls[n_calls:] if c[0] == "put"] == []
    runner._hb_enabled = False
    runner._async_hb = None
    autodist_tpu.reset()


def test_watchdog_compile_grace_window(monkeypatch):
    """Coordinator side: a fresh mark shields the worker from the
    heartbeat reaper; an expired or cleared mark does not."""
    import time as time_mod
    from autodist_tpu.runtime.coordinator import Coordinator
    coord = Coordinator.__new__(Coordinator)
    coord._heartbeat_timeout = 10.0
    client = _FakeCoordClient()
    assert not coord._in_compile_grace(client, "w0")      # no mark
    client.kv["compiling/w0"] = repr(time_mod.time())
    assert coord._in_compile_grace(client, "w0")          # fresh mark
    client.kv["compiling/w0"] = repr(time_mod.time() - 10000.0)
    assert not coord._in_compile_grace(client, "w0")      # expired
    client.kv["compiling/w0"] = "0"                       # cleared
    assert not coord._in_compile_grace(client, "w0")
    client.kv["compiling/w0"] = ""                        # never marked
    assert not coord._in_compile_grace(client, "w0")
    client.kv["compiling/w0"] = "garbage"
    assert not coord._in_compile_grace(client, "w0")


# ------------------------------------------------- nightly slow matrix


@pytest.mark.slow
@pytest.mark.chaos
def test_slow_partitioned_ps_fused_guarded_rollback(monkeypatch,
                                                    tmp_path):
    """Nightly matrix leg: partitioned host-PS + fused k=2 under guards
    with a sustained bit-flip window — skip accounting at readback
    boundaries, rollback to a healthy stamp, completion."""
    from autodist_tpu.checkpoint.saver import Saver
    params, loss_fn, batch = _problem()
    _set_plan(monkeypatch, [{"var": "w", "mode": "bitflip", "step": 6,
                             "until": 9, "bit": 30}])
    policy = SentinelPolicy(max_skips_per_window=2, window_steps=50,
                            grad_norm_limit=100.0)
    runner = _build(lambda: S.UnevenPartitionedPS(), params, loss_fn,
                    batch, sentinel=policy)
    saver = Saver(directory=str(tmp_path), max_to_keep=10)
    import itertools
    history = runner.fit(itertools.repeat(batch), steps=20, save_every=2,
                         saver=saver, fuse_steps=2)
    assert len(history) == 20
    stats = runner.step_stats()["sentinel"]
    assert stats["rollbacks"] >= 1
    assert np.isfinite(float(history[-1]["loss"]))
    autodist_tpu.reset()
