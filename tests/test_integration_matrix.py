"""Strategy × model-case integration matrix.

The analog of reference ``tests/integration/test_all.py:20-46``: a cartesian
product of strategy builders and model "cases" chosen to cover distinct graph
shapes. The reference's cases map to JAX as:

- c0 dense + numeric correctness  -> tests/test_e2e_numeric.py (all builders)
- c1/c3/c5 Keras feeds            -> ``case_flax`` (flax.linen module)
- c2 sparse/embedding             -> ``case_sparse`` (lookup-dominated loss)
- c4 ``tf.while_loop``            -> ``case_scan`` (``lax.scan`` in the loss)
- c6 dynamic LSTM                 -> ``case_lstm`` (LSTM cell scanned over time)
- c7 ``model.fit``                -> ``function``-API loop inside every case
- c9 staleness                    -> ``test_staleness_accepted``
- c10 saver                       -> ``test_saver_roundtrip_under_strategy``

The reference isolates each combo in a fresh process
(``test_all.py:53-69``); our state is process-global but resettable, so each
combo runs in-process with ``autodist_tpu.reset()`` (see conftest fixture).
"""
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S

BATCH = 16


# ------------------------------------------------------------------- cases


def case_flax(seed=0):
    """c1/c3/c5 analog: a flax.linen module (the 'Keras model' shape)."""
    rng = np.random.RandomState(seed)

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(2)(x)

    model = MLP()
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 6), jnp.float32))["params"]

    def loss_fn(p, batch):
        pred = model.apply({"params": p}, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"x": rng.randn(BATCH, 6).astype(np.float32),
             "y": rng.randn(BATCH, 2).astype(np.float32)}
    return params, loss_fn, batch


def case_sparse(seed=1):
    """c2 analog: embedding-lookup-dominated model (sparse grads)."""
    rng = np.random.RandomState(seed)
    params = {"emb": jnp.asarray(rng.randn(33, 8).astype(np.float32)),  # uneven dim
              "out": jnp.asarray(rng.randn(8, 2).astype(np.float32))}

    def loss_fn(p, batch):
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        return jnp.mean((feat @ p["out"] - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, 33, (BATCH,)).astype(np.int32),
             "y": rng.randn(BATCH, 2).astype(np.float32)}
    return params, loss_fn, batch


def case_scan(seed=2):
    """c4 analog: data-dependent-iteration compute via ``lax.scan``."""
    rng = np.random.RandomState(seed)
    params = {"w": jnp.asarray(rng.randn(4, 4).astype(np.float32) * 0.1),
              "out": jnp.asarray(rng.randn(4, 1).astype(np.float32))}

    def loss_fn(p, batch):
        def body(h, _):
            return jnp.tanh(h @ p["w"]), None
        h, _ = jax.lax.scan(body, batch["x"], None, length=5)
        return jnp.mean((h @ p["out"] - batch["y"]) ** 2)

    batch = {"x": rng.randn(BATCH, 4).astype(np.float32),
             "y": rng.randn(BATCH, 1).astype(np.float32)}
    return params, loss_fn, batch


def case_lstm(seed=3):
    """c6 analog: dynamic LSTM — a recurrent cell scanned over time."""
    rng = np.random.RandomState(seed)
    cell = nn.OptimizedLSTMCell(features=8)
    x0 = jnp.zeros((BATCH, 4), jnp.float32)
    carry0 = cell.initialize_carry(jax.random.PRNGKey(0), x0.shape)
    params = cell.init(jax.random.PRNGKey(seed), carry0, x0)["params"]
    proj = jnp.asarray(rng.randn(8, 1).astype(np.float32))
    params = {"cell": params, "proj": proj}

    def loss_fn(p, batch):
        def body(carry, xt):
            carry, y = cell.apply({"params": p["cell"]}, carry, xt)
            return carry, y
        # time-major scan over the sequence axis; the carry is built from the
        # batch itself so it matches the per-replica batch under sharding
        xs = jnp.swapaxes(batch["x"], 0, 1)  # [T, B, 4]
        c0 = cell.initialize_carry(jax.random.PRNGKey(0), xs[0].shape)
        _, ys = jax.lax.scan(body, c0, xs)
        pred = ys[-1] @ p["proj"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"x": rng.randn(BATCH, 6, 4).astype(np.float32),
             "y": rng.randn(BATCH, 1).astype(np.float32)}
    return params, loss_fn, batch


CASES = [("flax", case_flax), ("sparse", case_sparse),
         ("scan", case_scan), ("lstm", case_lstm)]

BUILDERS = [
    ("PS", lambda: S.PS()),
    ("PartitionedPS", lambda: S.PartitionedPS()),
    ("AllReduce", lambda: S.AllReduce(chunk_size=4)),
    ("PartitionedAR", lambda: S.PartitionedAR()),
    ("Parallax", lambda: S.Parallax()),
]


def run_combo(builder_name: str, case_name: str, n_steps: int = 4):
    """One combo's full trajectory — THE shared definition used both by
    the in-process matrix equivalence check and the fresh-subprocess run
    in tests/test_matrix_subprocess.py (both sides must execute the same
    code for the comparison to mean anything)."""
    import optax
    autodist_tpu.reset()
    params, loss_fn, batch = dict(CASES)[case_name]()
    builder = dict(BUILDERS)[builder_name]()
    runner = autodist_tpu.AutoDist(strategy_builder=builder).build(
        loss_fn, optax.adam(1e-2), params, batch)
    runner.init(params)
    losses = [float(runner.run(batch)["loss"]) for _ in range(n_steps)]
    flat = jax.tree_util.tree_flatten_with_path(runner.gather_params())[0]
    params_out = {jax.tree_util.keystr(p): np.asarray(v) for p, v in flat}
    autodist_tpu.reset()
    return {"losses": losses, "params": params_out}


# ------------------------------------------------------------------ matrix


@pytest.mark.parametrize("bname,make_builder", BUILDERS, ids=[b[0] for b in BUILDERS])
@pytest.mark.parametrize("cname,make_case", CASES, ids=[c[0] for c in CASES])
def test_case_trains_under_strategy(cname, make_case, bname, make_builder):
    params, loss_fn, batch = make_case()
    ad = autodist_tpu.AutoDist(strategy_builder=make_builder())
    step = ad.function(loss_fn, optimizer=optax.adam(2e-2), params=params)
    losses = [step(batch)["loss"] for _ in range(6)]
    assert all(np.isfinite(l) for l in losses), (cname, bname, losses)
    assert losses[-1] < losses[0], (cname, bname, losses)


@pytest.mark.parametrize("cname,make_case", CASES, ids=[c[0] for c in CASES])
def test_case_numeric_vs_single_device(cname, make_case):
    """c0-style correctness for every case shape: one distributed SGD step
    equals the hand-computed full-batch single-device update."""
    params, loss_fn, batch = make_case()
    opt = optax.sgd(0.1)
    grads = jax.grad(loss_fn)(params, batch)
    updates, _ = opt.update(grads, opt.init(params), params)
    expected = optax.apply_updates(params, updates)

    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    runner.run(batch)
    got = runner.gather_params()
    flat = sorted(((jax.tree_util.keystr(k), v) for k, v in
                   jax.tree_util.tree_flatten_with_path(expected)[0]))
    flat_got = sorted(((jax.tree_util.keystr(k), v) for k, v in
                       jax.tree_util.tree_flatten_with_path(got)[0]))
    assert [n for n, _ in flat] == [n for n, _ in flat_got]
    for (n, e), (_, g) in zip(flat, flat_got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=1e-5, atol=1e-5, err_msg=str(n))


# ------------------------------------------------------- c9 / c10 analogs


def test_staleness_accepted():
    """c9 analog: bounded-staleness PS config trains in-process (cross-process
    pacing semantics are covered by tests/test_coordination.py)."""
    params, loss_fn, batch = case_flax()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PS(staleness=2))
    step = ad.function(loss_fn, optimizer=optax.adam(2e-2), params=params)
    losses = [step(batch)["loss"] for _ in range(4)]
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("bname,make_builder",
                         [("PartitionedAR", lambda: S.PartitionedAR()),
                          ("PartitionedPS", lambda: S.PartitionedPS())],
                         ids=["PartitionedAR", "PartitionedPS"])
def test_saver_roundtrip_under_strategy(tmp_path, bname, make_builder):
    """c10 analog: save under a partitioned strategy, restore into a FRESH
    framework instance under a DIFFERENT strategy, training continues."""
    from autodist_tpu.checkpoint.saver import Saver
    params, loss_fn, batch = case_sparse()
    opt = optax.adam(2e-2)
    ad = autodist_tpu.AutoDist(strategy_builder=make_builder())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(3):
        m = runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    path = saver.save(runner)
    autodist_tpu.reset()  # mid-test: allow a second AutoDist instance

    # restore into a different strategy family
    ad2 = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner2 = ad2.build(loss_fn, opt, params, batch)
    runner2.init(params)
    saver.restore(runner2, path)
    m2 = runner2.run(batch)
    assert m2["loss"] <= m["loss"] + 1e-5, (m, m2)
