"""Real multi-process distributed training test.

The analog of the reference's two-machine ``tests/integration/test_dist.py``
— no fake backend (SURVEY §4.3): two OS processes each holding 4 virtual CPU
devices join one jax.distributed job over a local coordinator, run the full
AutoDist stack (chief builds + serializes the strategy, the worker loads it,
both lower independently and train in lockstep over the 8-device global
mesh), and the parent asserts both processes observed identical losses that
match a single-process 8-device run of the same strategy bit-for-bit.

SSH launching is exercised dry-run (``ADT_DEBUG_REMOTE``) elsewhere
(tests/test_cluster.py); here the parent plays the external launcher so the
data path — cross-process Gloo collectives, strategy file handoff, global
mesh construction — is fully real.
"""
import contextlib
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from _capabilities import needs_mp_collectives

# tests below that launch a two-OS-process jax.distributed CPU job (or
# broadcast the strategy over a cross-process collective) carry
# @needs_mp_collectives(): a jaxlib whose CPU backend has no multi-process
# collectives fails them on environment grounds, so a real probe
# (tests/_capabilities.py) skips them cleanly; ADT_MP_PROBE=1 forces the
# run. Pure in-process tests (e.g. remapper validation) stay unmarked.

HERE = os.path.dirname(os.path.abspath(__file__))
DRIVER = os.path.join(HERE, "dist_driver.py")

def _pair_spec_yaml(devices_per_proc=4):
    cpus = ", ".join(str(i) for i in range(devices_per_proc))
    return ("nodes:\n"
            "  - address: 127.0.0.1\n    chief: true\n    cpus: [%s]\n"
            "  - address: localhost\n    cpus: [%s]\n" % (cpus, cpus))


SPEC_YAML = _pair_spec_yaml()


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_pair(tmp_path, builder, n_steps=6, external=False,
                 extra_env=None):
    """Run chief+worker. ``external=False`` models the chief-launched flow
    (file handoff by preset id — the parent stands in for the Coordinator's
    fresh remote_copy by clearing any stale file); ``external=True`` models
    GKE/mpirun-style simultaneous launch (collective-broadcast handoff)."""
    spec = tmp_path / "spec.yml"
    spec.write_text(SPEC_YAML)
    port = _free_port()
    strategy_id = "dist-test-%s-%d" % (builder, os.getpid())
    from autodist_tpu import const
    strategy_file = os.path.join(const.DEFAULT_SERIALIZATION_DIR, strategy_id)
    if os.path.exists(strategy_file):
        os.unlink(strategy_file)
    outs, procs = [], []
    for pid in range(2):
        out = tmp_path / ("out%d.json" % pid)
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # driver forces cpu via jax.config
        env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "ADT_COORDINATOR_ADDR": "127.0.0.1:%d" % port,
            "ADT_NUM_PROCESSES": "2",
            "ADT_PROCESS_ID": str(pid),
            "ADT_STRATEGY_ID": strategy_id,
            "ADT_DEBUG_REMOTE": "1",   # parent already launched the worker
            "PYTHONPATH": os.pathsep.join(
                [os.path.dirname(HERE)] +
                ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH") else [])),
        })
        if external:
            env["ADT_EXTERNAL_LAUNCH"] = "1"
        if extra_env:
            env.update(extra_env)
        if pid == 1:
            env["ADT_WORKER"] = "localhost"
        procs.append(subprocess.Popen(
            [sys.executable, DRIVER, str(spec), str(out), builder, str(n_steps)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
        outs.append(out)
    deadline = time.monotonic() + 240
    logs = []
    for p in procs:
        try:
            log, _ = p.communicate(timeout=max(5.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed pair timed out for %s" % builder)
        logs.append(log)
    for p, log in zip(procs, logs):
        assert p.returncode == 0, "process failed:\n%s" % log
    results = [json.loads(o.read_text()) for o in outs]
    for r, log in zip(results, logs):
        r["log"] = log
        r["strategy_id"] = strategy_id
    return results


def _single_process_reference(builder, n_steps=6):
    """Same strategy on this (8-device, single-process) runtime."""
    import autodist_tpu as adt
    from tests.dist_driver import BUILDERS, make_case
    import optax
    adt.reset()
    params, loss_fn, batch = make_case()
    ad = adt.AutoDist(strategy_builder=BUILDERS[builder]())
    step = ad.function(loss_fn, optimizer=optax.sgd(0.1), params=params)
    return [float(step(batch)["loss"]) for _ in range(n_steps)]


def _assert_pair_matches_reference(chief, worker, builder):
    for r in (chief, worker):
        assert r["process_count"] == 2
        assert r["local_devices"] == 4
        assert r["global_devices"] == 8
    # both processes ran the same lockstep program
    np.testing.assert_array_equal(chief["losses"], worker["losses"])
    for k in chief["params"]:
        np.testing.assert_array_equal(chief["params"][k], worker["params"][k])
    # and the distributed run computes the same math as one process
    # holding all 8 devices
    ref = _single_process_reference(builder)
    np.testing.assert_allclose(chief["losses"], ref, rtol=1e-5, atol=1e-6)
    assert chief["losses"][-1] < chief["losses"][0]


# Deliberately NOT gated behind --run-integration: these two cases are the
# only real (non-dry-run) coverage of the cross-process data path and must
# stay green in every run. One exercises each strategy family and each
# handoff mode with no redundancy; the wider matrix is opt-in below.
@pytest.mark.parametrize("builder,external", [("AllReduce", False),
                                              ("PartitionedPS", True)])
@needs_mp_collectives()
def test_two_process_training_matches_single_process(tmp_path, builder, external):
    chief, worker = _launch_pair(tmp_path, builder, external=external)
    _assert_pair_matches_reference(chief, worker, builder)


@pytest.mark.integration
@pytest.mark.parametrize("builder", ["PartitionedAR", "Parallax"])
@needs_mp_collectives()
def test_two_process_extended_matrix(tmp_path, builder):
    chief, worker = _launch_pair(tmp_path, builder, external=True)
    _assert_pair_matches_reference(chief, worker, builder)


@contextlib.contextmanager
def _coordination_service():
    """Live coordination service on a free port, exported to child
    processes via ADT_COORDSVC_PORT (restored on exit)."""
    from autodist_tpu.runtime.coordination import (CoordinationClient,
                                                   CoordinationServer)
    svc_port = _free_port()
    srv = CoordinationServer(port=svc_port)
    srv.start()
    old = os.environ.get("ADT_COORDSVC_PORT")
    os.environ["ADT_COORDSVC_PORT"] = str(svc_port)
    try:
        yield svc_port
    finally:
        if old is None:
            os.environ.pop("ADT_COORDSVC_PORT", None)
        else:
            os.environ["ADT_COORDSVC_PORT"] = old
        srv.stop()


@needs_mp_collectives()
def test_two_process_async_ps(tmp_path):
    """PS(sync=False) across two real processes: each runs its OWN local
    4-device mesh (between-graph replication — no cross-process
    collectives); the chief owns every variable and serves values / applies
    gradient blobs through the coordination service's BPUT/QPUSH wire (the
    reference's async accumulator path, ps_synchronizer.py:556-633)."""
    from autodist_tpu.runtime.coordination import CoordinationClient
    with _coordination_service() as svc_port:
        chief, worker = _launch_pair(tmp_path, "PSAsync", n_steps=10,
                                     external=True)
        for r in (chief, worker):
            # local mesh: 4 devices per process, NOT one 8-device program
            assert r["local_devices"] == 4
            assert "async PS serving" in r["log"], r["log"][-2000:]
            # async trajectories are process-specific; each must converge
            assert r["losses"][-1] < r["losses"][0]
        # the chief owned and applied gradient blobs: published version on
        # the service counts applies (>= chief's own 5 steps; the worker's
        # last pushes may legally land after the chief exits)
        client = CoordinationClient("127.0.0.1", svc_port)
        res = client.bget("ps:127.0.0.1/vals")
        assert res is not None
        version, _ = res
        assert version >= 5, "chief applied fewer blobs than its own steps"
        client.close()


@pytest.mark.integration
@needs_mp_collectives()
def test_two_process_async_multi_owner(tmp_path):
    """PSLoadBalancing(sync=False): variables spread across BOTH hosts, so
    each process serves its own group (apply loop + publishes) and fetches
    the peer's — the reference's sharded-PS deployment, asynchronously."""
    from autodist_tpu.runtime.coordination import CoordinationClient
    with _coordination_service() as svc_port:
        chief, worker = _launch_pair(tmp_path, "PSAsyncLB", n_steps=10,
                                     external=True)
        for r in (chief, worker):
            assert r["local_devices"] == 4
            # each process owns a NON-EMPTY group (load balancing spread)
            assert "owns ['" in r["log"], r["log"][-2000:]
            assert r["losses"][-1] < r["losses"][0]
        # BOTH hosts published value blobs on the service
        client = CoordinationClient("127.0.0.1", svc_port)
        assert client.bget("ps:127.0.0.1/vals") is not None
        assert client.bget("ps:localhost/vals") is not None
        client.close()


@needs_mp_collectives()
def test_two_process_async_per_shard_ownership(tmp_path):
    """PartitionedPS(sync=False): a partitioned variable's shards are
    owned by DIFFERENT hosts (the reference's per-shard PS task placement,
    ps_synchronizer.py:636-762). Each owner publishes only its own shard
    ranges; pulls reassemble the full variable across owners. The parent
    reads both hosts' published blobs and asserts the same variable
    appears in both, as disjoint shard keys."""
    from autodist_tpu.runtime import ps_service as pss
    from autodist_tpu.runtime.coordination import CoordinationClient
    with _coordination_service() as svc_port:
        chief, worker = _launch_pair(tmp_path, "PSAsyncPart", n_steps=10,
                                     external=True)
        for r in (chief, worker):
            assert r["local_devices"] == 4
            assert r["losses"][-1] < r["losses"][0]
        client = CoordinationClient("127.0.0.1", svc_port)
        blobs = {}
        for host in ("127.0.0.1", "localhost"):
            res = client.bget("ps:%s/vals" % host)
            assert res is not None, "host %s never published" % host
            blobs[host] = pss.unpack_arrays(res[1])
        client.close()
        by_var = {}
        for host, vals in blobs.items():
            for key in vals:
                if "!" in key:
                    continue  # legacy single-blob form (opt now rides
                    # the /opt side channel)
                name, si = key.rsplit("::", 1)
                by_var.setdefault(name, {}).setdefault(int(si), set()).add(host)
        split = {n: owners for n, owners in by_var.items()
                 if len({h for hs in owners.values() for h in hs}) > 1}
        assert split, "no variable's shards are owned by two hosts: %s" % by_var
        for name, owners in split.items():
            # every shard published by EXACTLY one owner, none missing
            assert sorted(owners) == list(range(len(owners))), owners
            for si, hosts in owners.items():
                assert len(hosts) == 1, (name, si, hosts)


@needs_mp_collectives()
def test_two_process_async_checkpoint_completeness(tmp_path):
    """A chief-side checkpoint under per-shard async ownership must carry
    LIVE Adam moments for every shard — including shards owned by the
    worker, whose moments exist on the chief only as frozen zero init and
    must come off the owner's published blob. A broken opt wire would
    save half-zero moments (silent optimizer corruption on resume)."""
    ckpt_dir = tmp_path / "ckpt"
    with _coordination_service():
        chief, worker = _launch_pair(
            tmp_path, "PSAsyncPart", n_steps=10, external=True,
            extra_env={"ADT_TEST_SAVE_DIR": str(ckpt_dir),
                       "ADT_TEST_OPTIMIZER": "adam"})
        for r in (chief, worker):
            assert r["losses"][-1] < r["losses"][0]
        metas = sorted(ckpt_dir.glob("ckpt-*.meta.json"))
        assert metas, "chief saved no checkpoint"
        stem = str(metas[-1])[: -len(".meta.json")]
        opt = np.load(stem + ".opt.npz")
        # the partitioned var's mu must be non-zero in EVERY shard range
        mu_keys = [k for k in opt.files if "/mu/" in k and "w1" in k]
        assert mu_keys, opt.files
        mu = opt[mu_keys[0]]
        half = mu.shape[0] // 2
        assert np.abs(mu[:half]).max() > 0, "first shard moments are zero"
        assert np.abs(mu[half:]).max() > 0, \
            "second (peer-owned) shard moments are zero — opt wire broken"


@needs_mp_collectives()
def test_two_process_mirror_check(tmp_path):
    """Sync host-PS across two real processes with the mirror-digest
    cross-check active (ADT_PS_MIRROR_CHECK_EVERY): every process's host
    mirror must stay bit-identical by deterministic replay; each publishes
    an md5 digest of its mirrors to the coordination service every N steps
    and a worker whose digest differs from the chief's aborts. Here the
    run must SURVIVE the check (identical mirrors) and both digests must
    be on the service afterwards, equal."""
    from autodist_tpu.runtime.coordination import CoordinationClient
    with _coordination_service() as svc_port:
        chief, worker = _launch_pair(
            tmp_path, "PS", n_steps=6, external=True,
            extra_env={"ADT_PS_MIRROR_CHECK_EVERY": "2"})
        np.testing.assert_array_equal(chief["losses"], worker["losses"])
        assert chief["losses"][-1] < chief["losses"][0]
        prefix = "mirror/%s" % chief["strategy_id"]
        client = CoordinationClient("127.0.0.1", svc_port)
        chief_v = client.get("%s/chief" % prefix)
        worker_v = client.get("%s/localhost" % prefix)
        client.close()
        assert chief_v is not None and worker_v is not None
        # final check step: same step id, same digest
        assert chief_v == worker_v, (chief_v, worker_v)


@needs_mp_collectives()
def test_two_process_staleness_pacing(tmp_path):
    """PS(staleness=2) across two real processes: the Runner's pacing
    client reports steps/heartbeats to a live coordination service (the
    reference's token-queue semantics, ps_synchronizer.py:388-458). The
    parent hosts the service and asserts both workers reported all steps."""
    from autodist_tpu.runtime.coordination import CoordinationClient
    with _coordination_service() as svc_port:
        chief, worker = _launch_pair(tmp_path, "PSStale", n_steps=5,
                                     external=True)
        np.testing.assert_array_equal(chief["losses"], worker["losses"])
        assert chief["losses"][-1] < chief["losses"][0]
        # BOTH pacing clients connected (min_step alone can't distinguish
        # one reporter from two)
        for r in (chief, worker):
            assert "staleness pacing (window=2) active" in r["log"], \
                r["log"][-2000:]
        client = CoordinationClient("127.0.0.1", svc_port)
        # clean exits DEREGISTER (GOODBYE): step records no longer bound
        # the staleness window and heartbeat records cannot age into a
        # false death. dead_workers(0.0) lists every registered worker,
        # so [] proves both records are gone.
        assert client.min_step() == 0
        assert client.dead_workers(0.0) == []
        client.close()


LOCAL_FEED_DRIVER = """
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import optax
import autodist_tpu as adt
from autodist_tpu import strategy

spec_path, out_path = sys.argv[1], sys.argv[2]
ad = adt.AutoDist(resource_spec_file=spec_path,
                  strategy_builder=strategy.AllReduce())
import jax.numpy as jnp
rng = np.random.RandomState(0)
params = {"w": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32)}

def loss_fn(p, batch):
    return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

# the GLOBAL batch is fixed across processes; each process LOADS only
# its own half (the sharded-input pattern) and feeds it via
# remap_feed_local
gx = rng.randn(16, 8).astype(np.float32)
gy = rng.randn(16, 4).astype(np.float32)
pid = jax.process_index()
local = {"x": gx[pid * 8:(pid + 1) * 8], "y": gy[pid * 8:(pid + 1) * 8]}
example = {"x": np.zeros_like(gx), "y": np.zeros_like(gy)}

runner = ad.build(loss_fn, optax.sgd(0.1), params, example)
runner.init(params)
losses = []
for _ in range(6):
    placed = runner.remapper.remap_feed_local(local)
    losses.append(float(runner.run(placed)["loss"]))
with open(out_path, "w") as f:
    json.dump({"losses": losses,
               "params": {k: np.asarray(v).tolist()
                          for k, v in runner.gather_params().items()},
               "local_devices": jax.local_device_count(),
               "global_devices": jax.device_count()}, f)
print("LOCAL_FEED_DONE", flush=True)
"""


@needs_mp_collectives()
def test_local_feed_matches_global_feed(tmp_path):
    """Two processes each feed only their OWN half of the global batch
    (remap_feed_local + per-process data loading); the trajectory must
    be bit-identical to one process feeding the full global batch — the
    sharded-input path computes the same math as the host-global path."""
    driver = tmp_path / "local_feed_driver.py"
    driver.write_text(LOCAL_FEED_DRIVER)
    spec = tmp_path / "spec.yml"
    spec.write_text(SPEC_YAML)
    port = _free_port()
    outs, procs = [], []
    for pid in range(2):
        out = tmp_path / ("lf%d.json" % pid)
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "ADT_COORDINATOR_ADDR": "127.0.0.1:%d" % port,
            "ADT_NUM_PROCESSES": "2", "ADT_PROCESS_ID": str(pid),
            "ADT_EXTERNAL_LAUNCH": "1", "ADT_DEBUG_REMOTE": "1",
            "PYTHONPATH": os.pathsep.join(
                [os.path.dirname(HERE)] +
                ([os.environ["PYTHONPATH"]]
                 if os.environ.get("PYTHONPATH") else [])),
        })
        if pid == 1:
            env["ADT_WORKER"] = "localhost"
        procs.append(subprocess.Popen(
            [sys.executable, str(driver), str(spec), str(out)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
        outs.append(out)
    logs = [p.communicate(timeout=240)[0] for p in procs]
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log
    res = [json.loads(o.read_text()) for o in outs]
    np.testing.assert_array_equal(res[0]["losses"], res[1]["losses"])

    # single-process reference on the SAME global batch
    import autodist_tpu as adt
    import jax.numpy as jnp
    import optax
    from autodist_tpu import strategy as S
    adt.reset()
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32)}
    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)
    gx = rng.randn(16, 8).astype(np.float32)
    gy = rng.randn(16, 4).astype(np.float32)
    ad = adt.AutoDist(strategy_builder=S.AllReduce())
    step = ad.function(loss_fn, optimizer=optax.sgd(0.1), params=params)
    ref = [float(step({"x": gx, "y": gy})["loss"]) for _ in range(6)]
    np.testing.assert_allclose(res[0]["losses"], ref, rtol=1e-6, atol=1e-7)
    adt.reset()


def test_remap_feed_local_validates_replica_divisibility(monkeypatch):
    """A replica count that does not divide over the process count must
    raise a clear error (not ZeroDivisionError), and the local path must
    apply the same sequence-shard validation as the global path."""
    import jax
    from jax.sharding import Mesh
    from autodist_tpu.remapper import Remapper
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "seq"))
    remapper = Remapper(mesh, "data", seq_axis="seq")
    monkeypatch.setattr(jax, "process_count", lambda: 3)
    with pytest.raises(ValueError, match="do not divide evenly"):
        remapper.remap_feed_local({"x": np.zeros((6, 4), np.float32)})
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # seq dim 3 not divisible by 2 seq shards: the shared _leaf_spec check
    with pytest.raises(ValueError, match="sequence dim"):
        remapper.remap_feed_local({"x": np.zeros((1, 3), np.float32)})


# ---------------------------------------------------------- sharded ckpt

SHARDED_DRIVER = os.path.join(HERE, "sharded_driver.py")


def _launch_sharded_pair(tmp_path, builder, phase, ckpt_dir,
                         devices_per_proc=4):
    spec = tmp_path / ("spec-%d.yml" % devices_per_proc)
    spec.write_text(_pair_spec_yaml(devices_per_proc))
    port = _free_port()
    strategy_id = "sharded-%s-%s-%d-%d" % (builder, phase, os.getpid(),
                                           devices_per_proc)
    outs, procs = [], []
    for pid in range(2):
        out = tmp_path / ("sh-%s-%d-%d.json" % (phase, pid,
                                                devices_per_proc))
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=%d"
                         % devices_per_proc,
            "ADT_COORDINATOR_ADDR": "127.0.0.1:%d" % port,
            "ADT_NUM_PROCESSES": "2",
            "ADT_PROCESS_ID": str(pid),
            "ADT_STRATEGY_ID": strategy_id,
            "ADT_DEBUG_REMOTE": "1",
            "ADT_EXTERNAL_LAUNCH": "1",
            "PYTHONPATH": os.pathsep.join(
                [os.path.dirname(HERE)] +
                ([os.environ["PYTHONPATH"]]
                 if os.environ.get("PYTHONPATH") else [])),
        })
        if pid == 1:
            env["ADT_WORKER"] = "localhost"
        procs.append(subprocess.Popen(
            [sys.executable, SHARDED_DRIVER, str(spec), str(out), builder,
             phase, str(ckpt_dir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
        outs.append(out)
    logs = [p.communicate(timeout=240)[0] for p in procs]
    for p, log in zip(procs, logs):
        assert p.returncode == 0, "process failed:\n%s" % log
    return [json.loads(o.read_text()) for o in outs]


@pytest.mark.parametrize("builder", ["PartitionedAR", "PartitionedPS"])
@needs_mp_collectives()
def test_two_process_sharded_checkpoint_resume_bitexact(tmp_path, builder):
    """The VERDICT-r3 acceptance: a partitioned (+ host-PS) model saves a
    sharded checkpoint across 2 processes — each process writing only its
    own shards — then FRESH processes restore reading only local slices
    and continue bit-exactly; peak host allocation during save/restore
    stays far below the full tree's bytes (the plain Saver gathers it
    all)."""
    ckpt = tmp_path / "ckpt"
    run0, run1 = _launch_sharded_pair(tmp_path, builder, "run", ckpt)

    # both processes wrote a shard file with disjoint keys (__nonce__ is
    # per-file commit bookkeeping, present in every file)
    files = sorted(f for f in os.listdir(ckpt) if f.endswith(".npz"))
    assert len(files) == 2, files
    keys = [set(np.load(str(ckpt / f)).files) - {"__nonce__"} for f in files]
    assert not (keys[0] & keys[1]), keys[0] & keys[1]
    if builder == "PartitionedAR":
        # the partitioned device var's slices are split between the files
        assert {k for k in keys[0] if k.startswith("P|emb|")}
        assert {k for k in keys[1] if k.startswith("P|emb|")}
    else:
        # mirror-mode host-PS: every process holds identical store state,
        # so the chief writes all H| shards and the worker none — an empty
        # worker file is the correct division of labor here
        assert {k for k in keys[0] if k.startswith("H|emb")}
        assert not keys[1]

    # no process's save peak came near the full tree
    for r in (run0, run1):
        assert r["peak_bytes"] < 0.6 * r["full_bytes"], \
            (r["peak_bytes"], r["full_bytes"])

    res0, res1 = _launch_sharded_pair(tmp_path, builder, "resume", ckpt)
    np.testing.assert_array_equal(res0["losses"], res1["losses"])
    # resumed steps 4..5 equal the uninterrupted run's steps 4..5
    np.testing.assert_array_equal(run0["losses"][3:], res0["losses"])
    for k in run0["params"]:
        np.testing.assert_array_equal(run0["params"][k], res0["params"][k])
    if builder == "PartitionedAR":
        # device-partitioned restore reads only local slices. (Mirror-mode
        # host-PS restore legitimately materializes the full PS store —
        # that IS its live working set on every process.)
        for r in (res0, res1):
            assert r["peak_bytes"] < 0.6 * r["full_bytes"], \
                (r["peak_bytes"], r["full_bytes"])


@needs_mp_collectives()
def test_two_process_sharded_async_ownership(tmp_path):
    """Async per-shard-ownership PS: each process's sharded checkpoint file
    carries exactly the H| shards it OWNS (disjoint, complete union), and
    fresh processes restore and keep training."""
    with _coordination_service():
        ckpt = tmp_path / "ckpt"
        _launch_sharded_pair(tmp_path, "PSAsyncPart", "run", ckpt)
        files = sorted(f for f in os.listdir(ckpt) if f.endswith(".npz"))
        assert len(files) == 2
        hkeys = [set(k for k in np.load(str(ckpt / f)).files
                     if k.startswith("H|")) for f in files]
        assert hkeys[0] and hkeys[1], hkeys  # both processes own shards
        assert not (hkeys[0] & hkeys[1])
        union = {k.split("|", 1)[1] for k in hkeys[0] | hkeys[1]}
        # every (var, shard) present exactly once
        assert any(k.endswith("::0") for k in union)
    with _coordination_service():
        res0, res1 = _launch_sharded_pair(tmp_path, "PSAsyncPart", "resume",
                                          ckpt)
        for r in (res0, res1):
            assert all(np.isfinite(r["losses"])), r["losses"]


def _launch_sharded_single(tmp_path, builder, ckpt_dir, n_devices):
    """Resume a sharded checkpoint in ONE fresh process with ``n_devices``
    local devices — a different world size AND mesh shape than the
    2-process x 4-device save."""
    spec = tmp_path / ("spec1-%d.yml" % n_devices)
    spec.write_text(
        "nodes:\n  - address: 127.0.0.1\n    chief: true\n    cpus: [%s]\n"
        % ", ".join(str(i) for i in range(n_devices)))
    out = tmp_path / ("sh1-resume-%d.json" % n_devices)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    for k in ("ADT_COORDINATOR_ADDR", "ADT_NUM_PROCESSES",
              "ADT_PROCESS_ID", "ADT_WORKER", "ADT_EXTERNAL_LAUNCH"):
        env.pop(k, None)
    env.update({
        "XLA_FLAGS": "--xla_force_host_platform_device_count=%d" % n_devices,
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE)] +
            ([os.environ["PYTHONPATH"]]
             if os.environ.get("PYTHONPATH") else [])),
    })
    proc = subprocess.Popen(
        [sys.executable, SHARDED_DRIVER, str(spec), str(out), builder,
         "resume", str(ckpt_dir)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    log = proc.communicate(timeout=240)[0]
    assert proc.returncode == 0, "single resume failed:\n%s" % log
    return json.loads(out.read_text())


@pytest.mark.parametrize("builder", ["PartitionedAR", "PartitionedPS"])
@needs_mp_collectives()
def test_sharded_cross_world_resume(tmp_path, builder):
    """VERDICT-r4 #1 acceptance at the process level: a checkpoint saved
    by 2 processes over an 8-device mesh restores in ONE process over a
    4-device mesh (reduced world size — the permanently-lost-worker
    shape), reading slices from BOTH saved shard files, and training
    continues on the uninterrupted run's trajectory."""
    ckpt = tmp_path / "ckpt"
    run0, _run1 = _launch_sharded_pair(tmp_path, builder, "run", ckpt)
    res = _launch_sharded_single(tmp_path, builder, ckpt, 4)
    assert res["process_count"] == 1
    # steps 4..5 after the cross-topology restore track the uninterrupted
    # run (reduction ORDER differs across device counts, so allclose)
    np.testing.assert_allclose(run0["losses"][3:], res["losses"],
                               rtol=1e-4, atol=1e-6)
    for k in run0["params"]:
        np.testing.assert_allclose(run0["params"][k], res["params"][k],
                                   rtol=1e-4, atol=1e-6, err_msg=k)


@pytest.mark.parametrize("builder", ["PartitionedAR"])
@needs_mp_collectives()
def test_sharded_cross_mesh_resume_peak_memory(tmp_path, builder):
    """Cross-TOPOLOGY restore keeps the memory property the format exists
    for: a checkpoint saved by 2 processes over an 8-device mesh resumes
    in 2 processes over a 4-device mesh (same world, halved mesh — every
    new slice spans two saved slices), trajectory matching the
    uninterrupted run, and NO process's restore peak approaches the full
    tree (each still assembles only its own half)."""
    ckpt = tmp_path / "ckpt"
    run0, _run1 = _launch_sharded_pair(tmp_path, builder, "run", ckpt)
    res0, res1 = _launch_sharded_pair(tmp_path, builder, "resume", ckpt,
                                      devices_per_proc=2)
    np.testing.assert_allclose(run0["losses"][3:], res0["losses"],
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_array_equal(res0["losses"], res1["losses"])
    for r in (res0, res1):
        assert r["peak_bytes"] < 0.6 * r["full_bytes"], \
            (r["peak_bytes"], r["full_bytes"])
