"""Elastic async-PS worker recovery (beyond the reference's fail-fast).

The reference's supervision is fail-fast only (``coordinator.py:98-110``;
SURVEY §5 "no elasticity"). Async host-PS makes per-worker restart SOUND:
processes couple only through the parameter service (no collective
lockstep, no jax.distributed process pinning), and a relaunched worker's
first pull fetches the owner's CURRENT published values — so with
``ADT_ELASTIC=<budget>`` the chief relaunches a dead worker instead of
aborting. Sync strategies (and PS groups owned by the dead worker) stay
fail-fast: the peers are wedged mid-collective / the authoritative state
died with the owner.

The e2e test runs the REAL chief-launched flow over the local transport:
the launched worker kills itself mid-run (first incarnation only), the
chief relaunches it, and the restarted worker trains to completion.
"""
import json
import os
import socket
import subprocess
import sys

import pytest

from _capabilities import needs_mp_collectives

# async-elastic recovery couples processes only through the coordination
# service (per-process local meshes — no cross-process collectives), so
# most tests here run anywhere; only the SYNC-elastic flows join a real
# two-process jax.distributed job and carry @needs_mp_collectives()

HERE = os.path.dirname(os.path.abspath(__file__))

USER_SCRIPT = """
import json, os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import optax
import autodist_tpu as adt
from autodist_tpu import strategy

spec, outdir = sys.argv[1], sys.argv[2]
mode = sys.argv[3] if len(sys.argv) > 3 else "crash"
ad = adt.AutoDist(resource_spec_file=spec,
                  strategy_builder=strategy.PS(sync=False))
import jax.numpy as jnp
rng = np.random.RandomState(0)
params = {"w": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32)}

def loss_fn(p, batch):
    return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

batch = {"x": rng.randn(8, 8).astype(np.float32),
         "y": rng.randn(8, 4).astype(np.float32)}
step = ad.function(loss_fn, optimizer=optax.sgd(0.05), params=params)
is_worker = bool(os.environ.get("ADT_WORKER"))
marker = os.path.join(outdir, "crashed_once")

if is_worker:
    restarted = os.path.exists(marker)
    losses = []
    for i in range(12):
        losses.append(float(step(batch)["loss"]))
        if i == 2 and not restarted:
            with open(marker, "w") as f:
                f.write("x")
            if mode == "crash":
                os._exit(3)  # first incarnation dies mid-run
            time.sleep(3600)  # deadlock: alive but silent — the chief's
            # watchdog must kill us so the process watcher relaunches
    with open(os.path.join(outdir, "out_worker.json"), "w") as f:
        json.dump({"losses": losses, "restarted": restarted}, f)
    print("WORKER_DONE", restarted, flush=True)
else:
    # the chief keeps stepping (async: no barrier with the worker) and
    # exits once the (restarted) worker reports in
    worker_out = os.path.join(outdir, "out_worker.json")
    losses = []
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and not os.path.exists(worker_out):
        losses.append(float(step(batch)["loss"]))
        time.sleep(0.05)
    applied = ad.runner.distributed_step.ps_store.applied_total()
    with open(os.path.join(outdir, "out_chief.json"), "w") as f:
        json.dump({"losses": losses, "applied": applied,
                   "worker_done": os.path.exists(worker_out)}, f)
    print("CHIEF_DONE", flush=True)
"""

SPEC_YAML = """
nodes:
  - address: 127.0.0.1
    chief: true
    cpus: [0, 1]
  - address: localhost
    cpus: [0, 1]
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_elastic(tmp_path, mode, extra_env=None):
    script = tmp_path / "user_script.py"
    script.write_text(USER_SCRIPT)
    spec = tmp_path / "spec.yml"
    spec.write_text(SPEC_YAML)
    env = dict(os.environ)
    for k in ("JAX_PLATFORMS", "ADT_DEBUG_REMOTE", "ADT_WORKER"):
        env.pop(k, None)
    env.update({
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "ADT_COORDINATOR_ADDR": "127.0.0.1:%d" % _free_port(),
        "ADT_COORDSVC_PORT": str(_free_port()),
        "ADT_ELASTIC": "1",
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE)] +
            ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH")
             else [])),
    })
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, str(script), str(spec), str(tmp_path), mode],
        env=env, capture_output=True, text=True, timeout=240)


def _assert_recovered(tmp_path, proc):
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "relaunching worker" in proc.stderr, proc.stderr[-3000:]
    worker = json.loads((tmp_path / "out_worker.json").read_text())
    chief = json.loads((tmp_path / "out_chief.json").read_text())
    # the SECOND incarnation wrote the output (first died at step 2)
    assert worker["restarted"] is True
    assert (tmp_path / "crashed_once").exists()
    assert chief["worker_done"] is True
    # both trajectories converge; the chief's owner loop applied blobs
    # from its own steps plus both worker incarnations
    assert worker["losses"][-1] < worker["losses"][0]
    assert chief["losses"][-1] < chief["losses"][0]
    assert chief["applied"] > len(chief["losses"])


def test_worker_crash_relaunches_and_recovers(tmp_path):
    _assert_recovered(tmp_path, _run_elastic(tmp_path, "crash"))


def test_worker_deadlock_detected_and_recovered(tmp_path):
    """The first incarnation HANGS (alive, silent) instead of dying: the
    chief's heartbeat watchdog must notice the silence, kill the wedged
    process, and let the process watcher relaunch it — the deadlock leg
    of elastic supervision (a crash alone never exercises the watchdog)."""
    proc = _run_elastic(tmp_path, "hang",
                        extra_env={"ADT_HEARTBEAT_TIMEOUT_S": "6"})
    assert "deadlock" in proc.stderr, proc.stderr[-3000:]
    _assert_recovered(tmp_path, proc)


def _coordinator_for(tmp_path, strategy):
    """A Coordinator over a 2-node loopback cluster with ``strategy``
    serialized under a preset id (no processes launched)."""
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.runtime.cluster import Cluster
    from autodist_tpu.runtime.coordinator import Coordinator
    spec = tmp_path / "spec.yml"
    spec.write_text(SPEC_YAML)
    strategy.id = "elastic-unit-%d" % os.getpid()
    strategy.serialize()
    cluster = Cluster(ResourceSpec(str(spec)))
    return Coordinator(strategy.id, cluster, max_restarts=1)


def _ps_strategy(sync, dest="127.0.0.1:CPU:0"):
    from autodist_tpu.strategy.base import (PSSynchronizer, Strategy,
                                            VarConfig)
    return Strategy(node_config=[
        VarConfig(var_name="w", synchronizer=PSSynchronizer(
            reduction_destination=dest, sync=sync))])


def test_restart_soundness_gate(tmp_path, monkeypatch):
    """Sync strategies and dead-owner groups refuse restart; pure async
    with surviving owners allows it; no ADT_ELASTIC bring-up refuses
    everything (processes joined jax.distributed)."""
    no_elastic = _coordinator_for(tmp_path, _ps_strategy(sync=False))
    assert "ADT_ELASTIC" in no_elastic._restart_unsound_reason("localhost")

    monkeypatch.setenv("ADT_ELASTIC", "1")
    ok = _coordinator_for(tmp_path, _ps_strategy(sync=False))
    assert ok._restart_unsound_reason("localhost") is None

    sync = _coordinator_for(tmp_path, _ps_strategy(sync=True))
    assert "not async" in sync._restart_unsound_reason("localhost")

    owner = _coordinator_for(
        tmp_path, _ps_strategy(sync=False, dest="localhost:CPU:0"))
    assert "OWNS" in owner._restart_unsound_reason("localhost")
    # ...but losing a NON-owner is still recoverable in the same job
    assert owner._restart_unsound_reason("10.0.0.9") is None


def test_reap_pattern_matches_command_not_itself():
    """The remote reap pattern must match the launched command line
    (what bash exec leaves in /proc cmdline) — including commands with
    regex metacharacters — but never the pkill wrapper's own cmdline,
    which embeds the pattern text."""
    import re
    from autodist_tpu.runtime.coordinator import _reap_pattern
    for command in ("python -u /tmp/s.py a b",
                    "python -u /runs/exp+1/train.py --lr (0.1)"):
        pat = _reap_pattern(command)
        assert re.search(pat, command), (pat, command)
        wrapper = "bash -c pkill -f %s || true" % pat
        assert not re.search(pat, wrapper), (pat, wrapper)


def test_restart_budget_exhausts_to_fail_fast(tmp_path, monkeypatch):
    """_try_restart honors the budget: first death relaunches (dry-run
    remote_exec returns None), second falls through to fail-fast."""
    monkeypatch.setenv("ADT_DEBUG_REMOTE", "1")
    monkeypatch.setenv("ADT_ELASTIC", "1")
    coord = _coordinator_for(tmp_path, _ps_strategy(sync=False))
    coord._launch_cmds["localhost"] = ("python -u x.py", {})
    assert coord._try_restart("localhost", 3) is True
    assert coord._try_restart("localhost", 3) is False


# ----------------------------------------------------- sync-elastic (r4)

SYNC_USER_SCRIPT = """
import json, os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import optax
import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.checkpoint.saver import Saver

spec, outdir = sys.argv[1], sys.argv[2]
ad = adt.AutoDist(resource_spec_file=spec,
                  strategy_builder=strategy.AllReduce())
import jax.numpy as jnp
rng = np.random.RandomState(0)
params = {"w": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32)}

def loss_fn(p, batch):
    return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

batch = {"x": rng.randn(8, 8).astype(np.float32),
         "y": rng.randn(8, 4).astype(np.float32)}
runner = ad.build(loss_fn, optax.sgd(0.05), params, batch)
runner.init(params)  # ADT_AUTO_RESUME restores on the re-exec'd run
start = int(np.asarray(jax.device_get(runner.state.step)))
saver = Saver(directory=os.environ["ADT_CKPT_DIR"])
is_worker = bool(os.environ.get("ADT_WORKER"))
role = "worker" if is_worker else "chief"
marker = os.path.join(outdir, "crashed_once")
losses = {}
for i in range(start, 8):
    losses[i] = float(runner.run(batch)["loss"])
    saver.save(runner)  # every process: the gathers are collectives
    if is_worker and i == 2 and not os.path.exists(marker):
        with open(marker, "w") as f:
            f.write("x")
        os._exit(3)  # first worker incarnation dies mid-lockstep
with open(os.path.join(outdir, "out_%s.json" % role), "w") as f:
    json.dump({"start": start, "losses": losses,
               "params": np.asarray(
                   runner.gather_params()["w"]).tolist()}, f)
print(role.upper() + "_DONE start=%d" % start, flush=True)
"""


@needs_mp_collectives()
def test_sync_elastic_whole_job_restart_resumes_from_checkpoint(tmp_path):
    """ADT_ELASTIC + ADT_ELASTIC_SYNC on a sync (AllReduce) job: a worker
    dies mid-lockstep, the chief reaps the mesh and re-execs itself, the
    resumed job restores the last checkpoint and finishes — final params
    bit-equal an uninterrupted single-process run of the same math."""
    script = tmp_path / "user_script.py"
    script.write_text(SYNC_USER_SCRIPT)
    spec = tmp_path / "spec.yml"
    spec.write_text(SPEC_YAML)
    ckpt = tmp_path / "ckpt"
    env = dict(os.environ)
    for k in ("JAX_PLATFORMS", "ADT_DEBUG_REMOTE", "ADT_WORKER"):
        env.pop(k, None)
    env.update({
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "ADT_COORDINATOR_ADDR": "127.0.0.1:%d" % _free_port(),
        "ADT_COORDSVC_PORT": str(_free_port()),
        "ADT_ELASTIC": "1",
        "ADT_ELASTIC_SYNC": "1",
        "ADT_CKPT_DIR": str(ckpt),
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE)] +
            ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH")
             else [])),
    })
    proc = subprocess.run(
        [sys.executable, str(script), str(spec), str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-4000:]
    assert "restarting the WHOLE job" in proc.stderr, proc.stderr[-4000:]
    assert "ADT_AUTO_RESUME: restored step" in proc.stderr, proc.stderr[-4000:]
    chief = json.loads((tmp_path / "out_chief.json").read_text())
    worker = json.loads((tmp_path / "out_worker.json").read_text())
    # the resumed incarnation started from the last committed checkpoint
    assert chief["start"] == 3, chief
    assert worker["start"] == 3, worker
    # steps 3..7 ran in the resumed incarnation; both processes agree
    assert sorted(map(int, chief["losses"])) == [3, 4, 5, 6, 7]
    for k in chief["losses"]:
        assert abs(chief["losses"][k] - worker["losses"][k]) < 1e-6

    # uninterrupted reference: same math, single process over 2 devices
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy as S
    adt.reset()
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32)}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    batch = {"x": rng.randn(8, 8).astype(np.float32),
             "y": rng.randn(8, 4).astype(np.float32)}
    ad = adt.AutoDist(strategy_builder=S.AllReduce())
    step = ad.function(loss_fn, optimizer=optax.sgd(0.05), params=params)
    ref_losses = [float(step(batch)["loss"]) for _ in range(8)]
    ref_params = np.asarray(step.get_runner().gather_params()["w"])
    adt.reset()
    for i in range(3, 8):
        np.testing.assert_allclose(chief["losses"][str(i)], ref_losses[i],
                                   rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(chief["params"]), ref_params,
                               rtol=1e-6, atol=1e-7)


# ------------------------------------- reduced-world sync-elastic (r5)

REDUCED_WORLD_SCRIPT = """
import json, os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import optax

spec, outdir = sys.argv[1], sys.argv[2]
die_marker = os.path.join(outdir, "worker_dead_forever")
is_worker = bool(os.environ.get("ADT_WORKER"))
if is_worker and os.path.exists(die_marker):
    os._exit(3)  # the host is "gone": every relaunch dies at startup

import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.checkpoint import ShardedSaver

ad = adt.AutoDist(resource_spec_file=spec,
                  strategy_builder=strategy.AllReduce())
import jax.numpy as jnp
rng = np.random.RandomState(0)
params = {"w": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32)}

def loss_fn(p, batch):
    return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

batch = {"x": rng.randn(8, 8).astype(np.float32),
         "y": rng.randn(8, 4).astype(np.float32)}
runner = ad.build(loss_fn, optax.sgd(0.05), params, batch)
runner.init(params)  # ADT_AUTO_RESUME restores on re-exec'd runs
start = int(np.asarray(jax.device_get(runner.state.step)))
saver = ShardedSaver(directory=os.environ["ADT_CKPT_DIR"])
losses = {}
for i in range(start, 8):
    losses[i] = float(runner.run(batch)["loss"])
    saver.save(runner)
    if is_worker and i == 2:
        with open(die_marker, "w") as f:
            f.write("x")
        os._exit(3)  # first death, mid-lockstep
with open(os.path.join(outdir, "out_chief.json"), "w") as f:
    json.dump({"start": start, "losses": losses,
               "world": jax.device_count(),
               "params": np.asarray(
                   runner.gather_params()["w"]).tolist()}, f)
print("CHIEF_DONE start=%d world=%d" % (start, jax.device_count()),
      flush=True)
"""


# --------------------------------- in-run shrink/grow (epoch-fenced, r13)

INRUN_CHAOS_SCRIPT = """
import json, os, signal, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import optax
import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.runtime import elastic
from autodist_tpu.telemetry import spans as tel

spec, outdir = sys.argv[1], sys.argv[2]
ad = adt.AutoDist(resource_spec_file=spec,
                  strategy_builder=strategy.AllReduce())
import jax.numpy as jnp
rng = np.random.RandomState(0)
params = {"w": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32)}

def loss_fn(p, batch):
    return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

batch = {"x": rng.randn(8, 8).astype(np.float32),
         "y": rng.randn(8, 4).astype(np.float32)}
runner = ad.build(loss_fn, optax.sgd(0.05), params, batch)
runner.init(params)
start = int(np.asarray(jax.device_get(runner.state.step)))
is_worker = bool(os.environ.get("ADT_WORKER"))
role = "worker" if is_worker else "chief"
marker = os.path.join(outdir, "crashed_once")
TOTAL = 12
losses = {}
for i in range(start, TOTAL):
    losses[i] = float(runner.run(batch)["loss"])
    if i == 2 and is_worker and not os.path.exists(marker):
        with open(marker, "w") as f:
            f.write("x")
        time.sleep(0.1)  # let the chief clear its own step-2 boundary
        os.kill(os.getpid(), signal.SIGKILL)  # die mid-run, no cleanup
    if i == 2 and not is_worker:
        # stay OUT of the next cross-process collective while the death
        # is detected: the shrink epoch must land at a boundary (the
        # production pattern is a superstep interval >> detection time)
        time.sleep(3.0)
    time.sleep(0.25)  # superstep pacing so grow can land mid-run
out = {"start": start, "losses": losses, "world": jax.device_count(),
       "reconfigs": getattr(runner, "_reconfigs", 0),
       "epoch": elastic.current().epoch if elastic.current() else None,
       "spans": tel.get_recorder().durations_s("elastic.reconfigure"),
       "params": np.asarray(runner.gather_params()["w"]).tolist()}
with open(os.path.join(outdir, "out_%s_%d.json" % (role, start)), "w") as f:
    json.dump(out, f)
print(role.upper() + "_DONE start=%d world=%d" % (start, jax.device_count()),
      flush=True)
"""


@pytest.mark.slow
@pytest.mark.chaos
@needs_mp_collectives()
def test_inrun_shrink_to_survivors_then_grow_on_join(tmp_path):
    """The in-run elastic acceptance path: SIGKILL one of two sync workers
    mid-run → the chief publishes epoch 2 and the survivor re-forms a
    1-process mesh IN-RUN (no whole-job re-exec, no 'restarting the WHOLE
    job' in the logs); the relaunched worker announces itself, is admitted
    at epoch 3, adopts the broadcast state, and the job grows back — with
    the chief's loss trajectory bit-matching an uninterrupted reference
    (data-parallel math is world-size invariant on a fixed global batch)."""
    script = tmp_path / "user_script.py"
    script.write_text(INRUN_CHAOS_SCRIPT)
    spec = tmp_path / "spec.yml"
    spec.write_text(SPEC_YAML)
    env = dict(os.environ)
    for k in ("JAX_PLATFORMS", "ADT_DEBUG_REMOTE", "ADT_WORKER"):
        env.pop(k, None)
    env.update({
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "ADT_COORDINATOR_ADDR": "127.0.0.1:%d" % _free_port(),
        "ADT_COORDSVC_PORT": str(_free_port()),
        "ADT_ELASTIC": "3",
        "ADT_ELASTIC_SYNC": "1",
        "ADT_ELASTIC_INRUN": "1",
        "ADT_ELASTIC_POLL_S": "0.05",
        "ADT_HEARTBEAT_TIMEOUT_S": "8",
        "ADT_CKPT_DIR": str(tmp_path / "ckpt"),
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE)] +
            ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH")
             else [])),
    })
    proc = subprocess.run(
        [sys.executable, str(script), str(spec), str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-6000:]
    err = proc.stderr
    assert "published cluster epoch 2" in err, err[-6000:]
    assert "published cluster epoch 3" in err, err[-6000:]
    assert "restarting the WHOLE job" not in err, err[-6000:]
    chief = json.loads((tmp_path / "out_chief_0.json").read_text())
    # shrink + grow both happened in-run on the survivor
    assert chief["reconfigs"] == 2, chief
    assert chief["epoch"] == 3, chief
    assert chief["world"] == 4, chief  # grown back to 2 procs x 2 devices
    assert len(chief["spans"]) == 2  # downtime is span-derived
    # the revived worker adopted the broadcast state mid-run and finished
    worker_outs = [f for f in os.listdir(tmp_path)
                   if f.startswith("out_worker_")]
    assert worker_outs, os.listdir(tmp_path)
    worker = json.loads((tmp_path / worker_outs[0]).read_text())
    assert worker["start"] > 2, worker  # not a from-scratch restart

    # loss continuity: bit-match an uninterrupted single-process run
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy as S
    adt.reset()
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32)}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    batch = {"x": rng.randn(8, 8).astype(np.float32),
             "y": rng.randn(8, 4).astype(np.float32)}
    ad = adt.AutoDist(strategy_builder=S.AllReduce())
    step = ad.function(loss_fn, optimizer=optax.sgd(0.05), params=params)
    ref = [float(step(batch)["loss"]) for _ in range(12)]
    adt.reset()
    for i_str, loss in chief["losses"].items():
        np.testing.assert_allclose(loss, ref[int(i_str)],
                                   rtol=1e-5, atol=1e-7)
    # every step the worker computed agrees with the chief's
    for i_str, loss in worker["losses"].items():
        np.testing.assert_allclose(loss, chief["losses"][i_str],
                                   rtol=1e-6, atol=1e-7)


@needs_mp_collectives()
def test_sync_elastic_reduced_world_after_permanent_loss(tmp_path):
    """VERDICT-r4 #1 (elastic half): a worker that dies on two consecutive
    incarnations is treated as PERMANENTLY lost — the chief excludes it,
    re-execs, and the job resumes at REDUCED world size (4 -> 2 devices)
    from its SHARDED checkpoints via the cross-topology restore, with loss
    continuity against an uninterrupted single-process run."""
    script = tmp_path / "user_script.py"
    script.write_text(REDUCED_WORLD_SCRIPT)
    spec = tmp_path / "spec.yml"
    spec.write_text(SPEC_YAML)
    ckpt = tmp_path / "ckpt"
    env = dict(os.environ)
    for k in ("JAX_PLATFORMS", "ADT_DEBUG_REMOTE", "ADT_WORKER",
              "ADT_ELASTIC_EXCLUDE"):
        env.pop(k, None)
    env.update({
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "ADT_COORDINATOR_ADDR": "127.0.0.1:%d" % _free_port(),
        "ADT_COORDSVC_PORT": str(_free_port()),
        "ADT_ELASTIC": "3",
        "ADT_ELASTIC_SYNC": "1",
        "ADT_CKPT_DIR": str(ckpt),
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE)] +
            ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH")
             else [])),
    })
    proc = subprocess.run(
        [sys.executable, str(script), str(spec), str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-6000:]
    assert "PERMANENTLY lost" in proc.stderr, proc.stderr[-6000:]
    assert "restore across topologies" in proc.stderr, proc.stderr[-6000:]
    chief = json.loads((tmp_path / "out_chief.json").read_text())
    # the surviving incarnation ran chief-only over its 2 local devices
    assert chief["world"] == 2, chief
    assert chief["start"] == 3, chief
    assert sorted(map(int, chief["losses"])) == [3, 4, 5, 6, 7]

    # uninterrupted reference: same math, single process
    import jax
    import jax.numpy as jnp
    import numpy as np_
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy as S
    adt.reset()
    rng = np_.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32)}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    batch = {"x": rng.randn(8, 8).astype(np_.float32),
             "y": rng.randn(8, 4).astype(np_.float32)}
    ad = adt.AutoDist(strategy_builder=S.AllReduce())
    step = ad.function(loss_fn, optimizer=optax.sgd(0.05), params=params)
    ref_losses = [float(step(batch)["loss"]) for _ in range(8)]
    ref_params = np_.asarray(step.get_runner().gather_params()["w"])
    adt.reset()
    for i in range(3, 8):
        np_.testing.assert_allclose(chief["losses"][str(i)], ref_losses[i],
                                    rtol=1e-5, atol=1e-7)
    np_.testing.assert_allclose(np_.asarray(chief["params"]), ref_params,
                                rtol=1e-5, atol=1e-7)
