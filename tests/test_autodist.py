"""AutoDist entry-object invariants (analog of reference ``tests/test_autodist.py``)."""
import jax.numpy as jnp
import numpy as np
import pytest

import autodist_tpu


def test_one_instance_per_process():
    ad = autodist_tpu.AutoDist()
    assert autodist_tpu.get_default_autodist() is ad
    with pytest.raises(NotImplementedError):
        autodist_tpu.AutoDist()


def test_reset_allows_new_instance():
    autodist_tpu.AutoDist()
    autodist_tpu.reset()
    autodist_tpu.AutoDist()  # no raise


def test_runner_fit_and_evaluate():
    """fit() trains over an iterable; evaluate() computes metrics without
    touching parameters (the reference's model.fit/evaluate path, c7)."""
    import jax.numpy as jnp
    import numpy as np
    import optax
    import autodist_tpu
    from autodist_tpu import strategy as S

    autodist_tpu.reset()
    rng = np.random.RandomState(0)
    W = rng.randn(4, 1).astype(np.float32)
    params = {"w": jnp.zeros((4, 1))}
    loss_fn = lambda p, b: jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)  # noqa: E731

    def batches(n):
        r = np.random.RandomState(1)
        for _ in range(n):
            x = r.randn(16, 4).astype(np.float32)
            yield {"x": x, "y": x @ W}

    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.2), params,
                      next(iter(batches(1))))
    runner.init(params)

    seen = []
    history = runner.fit(batches(40), callbacks=[lambda i, m: seen.append(i)])
    assert len(history) == 40 and seen == list(range(40))
    assert float(history[-1]["loss"]) < float(history[0]["loss"])

    before = np.asarray(runner.gather_params()["w"]).copy()
    ev = runner.evaluate(batches(5))
    assert set(ev) == {"loss"} and np.isfinite(ev["loss"])
    after = np.asarray(runner.gather_params()["w"])
    np.testing.assert_array_equal(before, after)  # evaluate must not train

    # steps bound on an infinite iterable
    import itertools
    h2 = runner.fit(itertools.cycle(batches(2)), steps=3)
    assert len(h2) == 3
    autodist_tpu.reset()


def test_step_stats_goodput():
    """Runner.step_stats(): first step isolates compile, steady
    percentiles describe the post-compile regime, goodput accounts the
    compile as lost time."""
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy
    adt.reset()
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(8, 4), jnp.float32)}
    loss = lambda p, b: jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)
    batch = {"x": rng.randn(8, 8).astype(np.float32),
             "y": rng.randn(8, 4).astype(np.float32)}
    ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
    runner = ad.build(loss, optax.sgd(0.1), params, batch)
    runner.init(params)
    stats0 = runner.step_stats()
    assert (stats0["steps"], stats0["supersteps"], stats0["microsteps"],
            stats0["total_s"], stats0["first_step_s"]) == (0, 0, 0, 0.0, None)
    # the shape is stable: steady percentiles exist (as None) pre-sample,
    # the telemetry merge carries the registry counters, and the sentinel
    # sub-dict exists (zeros/None) even with no policy armed
    assert stats0["steady_median_s"] is None
    assert stats0["telemetry"]["dispatches"] == 0.0
    assert stats0["sentinel"] == {"skips": 0, "rollbacks": 0,
                                  "last_grad_norm": None,
                                  "quarantined": False}
    for _ in range(12):
        runner.run(batch)
    stats = runner.step_stats()
    assert stats["steps"] == 12
    # without fusion the two units coincide
    assert stats["supersteps"] == stats["microsteps"] == 12
    # compile dominates the first step; steady steps are far faster
    assert stats["first_step_s"] > 5 * stats["steady_median_s"]
    assert stats["steady_p10_s"] <= stats["steady_median_s"] <= stats["steady_p90_s"]
    assert 0.0 < stats["goodput"] <= 1.0
    # with one compile amortized over 12 steps, goodput is well below 1
    assert stats["goodput"] < 0.9
    assert abs(stats["total_s"]
               - (stats["first_step_s"]
                  + sum(runner._recent_step_s))) < 1e-6
    adt.reset()


def test_step_stats_small_sample_percentiles_stay_in_range():
    """Two steady samples must not extrapolate percentiles outside the
    observed durations (the exclusive-quantiles trap)."""
    import autodist_tpu as adt
    from autodist_tpu.runtime.runner import Runner
    r = Runner.__new__(Runner)
    r._step_count = 3
    r._superstep_count = 3
    r._first_step_s = 1.0
    r._recent_step_s = [0.001, 0.005]
    r._total_step_s = 1.006
    stats = r.step_stats()
    assert stats["steady_p10_s"] >= 0.001
    assert stats["steady_p90_s"] <= 0.005
