"""AutoDist entry-object invariants (analog of reference ``tests/test_autodist.py``)."""
import pytest

import autodist_tpu


def test_one_instance_per_process():
    ad = autodist_tpu.AutoDist()
    assert autodist_tpu.get_default_autodist() is ad
    with pytest.raises(NotImplementedError):
        autodist_tpu.AutoDist()


def test_reset_allows_new_instance():
    autodist_tpu.AutoDist()
    autodist_tpu.reset()
    autodist_tpu.AutoDist()  # no raise
