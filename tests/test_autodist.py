"""AutoDist entry-object invariants (analog of reference ``tests/test_autodist.py``)."""
import pytest

import autodist_tpu


def test_one_instance_per_process():
    ad = autodist_tpu.AutoDist()
    assert autodist_tpu.get_default_autodist() is ad
    with pytest.raises(NotImplementedError):
        autodist_tpu.AutoDist()


def test_reset_allows_new_instance():
    autodist_tpu.AutoDist()
    autodist_tpu.reset()
    autodist_tpu.AutoDist()  # no raise


def test_runner_fit_and_evaluate():
    """fit() trains over an iterable; evaluate() computes metrics without
    touching parameters (the reference's model.fit/evaluate path, c7)."""
    import jax.numpy as jnp
    import numpy as np
    import optax
    import autodist_tpu
    from autodist_tpu import strategy as S

    autodist_tpu.reset()
    rng = np.random.RandomState(0)
    W = rng.randn(4, 1).astype(np.float32)
    params = {"w": jnp.zeros((4, 1))}
    loss_fn = lambda p, b: jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)  # noqa: E731

    def batches(n):
        r = np.random.RandomState(1)
        for _ in range(n):
            x = r.randn(16, 4).astype(np.float32)
            yield {"x": x, "y": x @ W}

    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.2), params,
                      next(iter(batches(1))))
    runner.init(params)

    seen = []
    history = runner.fit(batches(40), callbacks=[lambda i, m: seen.append(i)])
    assert len(history) == 40 and seen == list(range(40))
    assert float(history[-1]["loss"]) < float(history[0]["loss"])

    before = np.asarray(runner.gather_params()["w"]).copy()
    ev = runner.evaluate(batches(5))
    assert set(ev) == {"loss"} and np.isfinite(ev["loss"])
    after = np.asarray(runner.gather_params()["w"])
    np.testing.assert_array_equal(before, after)  # evaluate must not train

    # steps bound on an infinite iterable
    import itertools
    h2 = runner.fit(itertools.cycle(batches(2)), steps=3)
    assert len(h2) == 3
    autodist_tpu.reset()
