"""Pipeline parallelism: numeric equality with single-device training.

Same bar as tensor parallelism (``tests/test_tensor_parallel.py``): GPipe
microbatch pipelining over the ``pipe`` mesh axis must reproduce plain
full-batch single-device training EXACTLY — the scan/ppermute backward
schedule and the lowering's complement-axes gradient sync must cancel.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import autodist_tpu as adt
from autodist_tpu import const, strategy
from autodist_tpu.models import pipe_lm
from autodist_tpu.models.tp_lm import TPLMConfig
from autodist_tpu.parallel import pipeline


@pytest.fixture(autouse=True)
def _reset():
    adt.reset()
    yield
    adt.reset()


def test_pipeline_apply_matches_sequential():
    """pipeline_apply over 4 stages == sequential stacked apply, fwd + grad."""
    rng = np.random.RandomState(0)
    L, B, D = 4, 8, 6
    ws = rng.standard_normal((L, D, D)).astype(np.float32) * 0.3
    x = rng.standard_normal((B, D)).astype(np.float32)

    def block(w, h):
        return jnp.tanh(h @ w)

    def stage_fn(ws_local, h):
        return pipeline.stacked_scan(block, ws_local, h)

    def seq_loss(ws, x):
        return jnp.mean(pipeline.stacked_scan(block, ws, x) ** 2)

    ref, ref_grad = jax.value_and_grad(seq_loss)(ws, x)

    mesh = Mesh(np.array(jax.devices()[:4]), (const.PIPELINE_AXIS,))

    def pp_loss(ws_local, x):
        y = pipeline.pipeline_apply(stage_fn, ws_local, x, n_microbatches=2)
        return jnp.mean(y ** 2)

    def run(ws, x):
        loss, g = jax.value_and_grad(pp_loss)(ws, x)
        # grads of pipe-sharded params need no cross-pipe reduce; loss is
        # uniform; divide the psum-inflated loss by S for comparison
        return loss, g

    loss, grad = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(P(const.PIPELINE_AXIS), P()),
        out_specs=(P(), P(const.PIPELINE_AXIS)), check_vma=False))(ws, x)
    np.testing.assert_allclose(loss, ref, rtol=1e-5)
    # autodiff of the uniform (psum-broadcast) loss inflates grads by S;
    # undo for the raw-primitive comparison (the lowering's /N handles this
    # in the full stack)
    np.testing.assert_allclose(grad / 4, ref_grad, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("pp,tp,micro", [(2, 1, 2), (4, 1, 4), (2, 2, 2)])
def test_pp_lm_matches_single_device(pp, tp, micro):
    """Tiny stacked-blocks LM via the full stack (dp x pp x tp) == plain
    single-device training, 2 steps, exact."""
    cfg = TPLMConfig.tiny(num_layers=max(2, pp))  # >=1 layer per stage
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=8, seed=1, n_microbatches=micro)
    opt = optax.sgd(0.05)
    rng = np.random.RandomState(2)
    batches = [batch, {"tokens": rng.randint(
        0, cfg.vocab_size, batch["tokens"].shape).astype(np.int32)}]

    # single-device reference
    state = opt.init(params)

    @jax.jit
    def step(p, s, b):
        g = jax.grad(loss_fn)(p, b)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    ref = params
    for b in batches:
        ref, state = step(ref, state, b)

    model_axis = const.MODEL_AXIS if tp > 1 else None
    ad = adt.AutoDist(strategy_builder=strategy.PipelineParallel(
        pp_shards=pp, tp_shards=tp, n_microbatches=micro,
        mp_rules=pipe_lm.pp_rules(model_axis=model_axis)))
    runner = ad.build(loss_fn, opt, params, batches[0])
    layouts = runner.distributed_step.layouts
    assert layouts["blocks/attn/wq"].mp_axes[0] == (0, const.PIPELINE_AXIS)
    if tp > 1:
        assert (2, const.MODEL_AXIS) in layouts["blocks/attn/wq"].mp_axes
    runner.init(params)
    for b in batches:
        m = runner.run(b)
    assert np.isfinite(m["loss"])
    got = runner.gather_params()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6),
        got, ref)


def test_pp_trains():
    """Loss decreases over steps under dp2 x pp2 x tp2."""
    cfg = TPLMConfig.tiny()
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=8, seed=3, n_microbatches=2)
    ad = adt.AutoDist(strategy_builder=strategy.PipelineParallel(
        pp_shards=2, tp_shards=2, n_microbatches=2,
        mp_rules=pipe_lm.pp_rules(model_axis=const.MODEL_AXIS)))
    runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
    runner.init(params)
    first = runner.run(batch)["loss"]
    for _ in range(5):
        last = runner.run(batch)["loss"]
    assert np.isfinite(last) and last < first


# ------------------------------------------------------------------- 1F1B


@pytest.mark.parametrize("pp,tp,micro", [
    (2, 1, 2), (4, 1, 4), (2, 2, 2),
    # DEEP cases (VERDICT-r4 #7): M >> S drives the circular stash through
    # many wrap-arounds (M/S full rotations), and pp8 runs the deepest
    # pipe the 8-device mesh allows (dp1) — the indexing regimes structure
    # tests can't certify numerically
    (4, 1, 16), (8, 1, 8),
])
def test_pp_lm_1f1b_matches_single_device(pp, tp, micro):
    """Full stack with schedule='1f1b' == plain single-device training of
    the same (degenerate-path) loss — the interleaved schedule computes
    the same math as GPipe, with residency bounded at S. The pp x tp case
    exercises model-axis collectives INSIDE the lax.cond tick branches
    (legal: branch parity is uniform over the model axis)."""
    cfg = TPLMConfig.tiny(num_layers=max(2, pp))
    model_axis = const.MODEL_AXIS if tp > 1 else None
    dp = 8 // (pp * tp)
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=max(8, micro * dp), seed=1,
        n_microbatches=micro, schedule="1f1b", model_axis=model_axis)
    opt = optax.sgd(0.05)
    rng = np.random.RandomState(2)
    batches = [batch, {"tokens": rng.randint(
        0, cfg.vocab_size, batch["tokens"].shape).astype(np.int32)}]

    @jax.jit
    def step(p, s, b):
        g = jax.grad(loss_fn)(p, b)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    ref, state = params, opt.init(params)
    for b in batches:
        ref, state = step(ref, state, b)

    ad = adt.AutoDist(strategy_builder=strategy.PipelineParallel(
        pp_shards=pp, tp_shards=tp, n_microbatches=micro, schedule="1f1b",
        mp_rules=pipe_lm.pp_rules(model_axis=model_axis)))
    runner = ad.build(loss_fn, opt, params, batches[0])
    assert runner.distributed_step.strategy.graph_config.pp_schedule == "1f1b"
    runner.init(params)
    for b in batches:
        m = runner.run(b)
    assert np.isfinite(m["loss"])
    got = runner.gather_params()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6),
        got, ref)


def test_1f1b_schedule_structure():
    """Program structure of the fused schedule: ONE scan of 2M+2S-2 ticks
    whose carry holds an [S, ...] circular input stash — the bounded
    activation residency the schedule exists for (GPipe's AD instead
    stashes all M+S-1 ticks' residuals)."""
    from autodist_tpu.parallel import pipeline as pl
    S, M, B, D = 4, 16, 16, 6  # M >> S: the stash must stay S-slot
    mesh = Mesh(np.array(jax.devices()[:S]), (const.PIPELINE_AXIS,))

    def stage_fn(w, h):
        return jnp.tanh(h @ w[0])

    def head_fn(hp, h, y):
        return jnp.mean((h @ hp - y) ** 2)

    ws = jnp.zeros((S, D, D), jnp.float32)
    hw = jnp.zeros((D, 1), jnp.float32)
    x = jnp.zeros((B, D), jnp.float32)
    y = jnp.zeros((B, 1), jnp.float32)

    def run(ws_l, hw_l, x_l, y_l):
        return pl.pipeline_loss_1f1b(stage_fn, head_fn, ws_l, hw_l,
                                     x_l, y_l, M)

    jaxpr = jax.make_jaxpr(jax.shard_map(
        run, mesh=mesh,
        in_specs=(P(const.PIPELINE_AXIS), P(), P(), P()),
        out_specs=P(), check_vma=False))(ws, hw, x, y)

    from autodist_tpu.kernel.common import op_info
    scans = []

    def find_scans(jp):
        for eqn in jp.eqns:
            if eqn.primitive.name == "scan":
                scans.append(eqn)
            for sub in op_info.sub_jaxprs(eqn):
                find_scans(sub)
    find_scans(jaxpr.jaxpr)
    ticks = [int(e.params.get("length", 0)) for e in scans]
    assert (2 * M + 2 * S - 2) in ticks, ticks  # the fused fwd+bwd sweep
    fused = scans[ticks.index(2 * M + 2 * S - 2)]
    mb = B // M
    stash_shapes = [tuple(v.aval.shape) for v in fused.invars
                    if hasattr(v, "aval") and hasattr(v.aval, "shape")]
    assert (S, mb, D) in stash_shapes, stash_shapes  # S-slot stash, not M


def test_cost_model_ranks_1f1b_when_activations_dominate():
    """With HBM squeezed below the GPipe estimate but above the 1F1B one,
    the ranking flips to the 1f1b candidate; with room, GPipe's
    no-recompute schedule wins on speed."""
    from autodist_tpu.simulator.simulator import Simulator
    from autodist_tpu.model_item import ModelItem
    from autodist_tpu.resource_spec import ResourceSpec
    cfg = TPLMConfig.tiny(num_layers=4)
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=64, seed=0, n_microbatches=16)
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1),
                     params=params, example_batch=batch).prepare()
    spec = ResourceSpec.from_dict({
        "nodes": [{"address": "10.0.0.1", "tpus": 8, "chief": True}],
        "slice": {"type": "v5e", "ici_bandwidth": 400}})
    mk = lambda sched: strategy.PipelineParallel(  # noqa: E731
        pp_shards=8, n_microbatches=16, schedule=sched,
        mp_rules=pipe_lm.pp_rules()).build(item, spec)
    cands = [("pp/gpipe", mk("gpipe")), ("pp/1f1b", mk("1f1b"))]

    roomy = Simulator(item, spec, hbm_capacity_bytes=1e15)
    r = roomy.rank(cands)
    assert r[0].label == "pp/gpipe"  # no recompute tax when memory is free
    g_hbm = roomy.simulate(cands[0][1]).breakdown.hbm_bytes
    f_hbm = roomy.simulate(cands[1][1]).breakdown.hbm_bytes
    assert f_hbm < g_hbm  # the schedule's whole point
    tight = Simulator(item, spec,
                      hbm_capacity_bytes=(g_hbm + f_hbm) / 2)
    r = tight.rank(cands)
    assert r[0].label == "pp/1f1b"
    assert r[0].breakdown.feasible and not r[1].breakdown.feasible


# ----------------------------------------------------------- interleaved


def test_interleaved_primitive_matches_logical_reference():
    """pipeline_apply_interleaved == the single-device logical-order
    emulation (pp_shards_hint), forward AND gradient, at S=4 x V=2 with
    M=8 microbatches."""
    from autodist_tpu.parallel import pipeline as pl
    S, V, M, B, D = 4, 2, 8, 16, 6
    L = S * V * 3
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(L, D, D) * 0.2, jnp.float32)
    x = jnp.asarray(rng.randn(B, D), jnp.float32)

    def stage_fn(w, h):
        return pl.stacked_scan(lambda p, hh: jnp.tanh(hh @ p), w, h)

    ref = pl.pipeline_apply_interleaved(stage_fn, ws, x, M, V,
                                        pp_shards_hint=S)
    mesh = Mesh(np.array(jax.devices()[:S]), (const.PIPELINE_AXIS,))
    out = jax.jit(jax.shard_map(
        lambda w, xx: pl.pipeline_apply_interleaved(stage_fn, w, xx, M, V),
        mesh=mesh, in_specs=(P(const.PIPELINE_AXIS), P()), out_specs=P(),
        check_vma=False))(ws, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)

    def loss_ref(w):
        return jnp.sum(pl.pipeline_apply_interleaved(
            stage_fn, w, x, M, V, pp_shards_hint=S) ** 2)
    g_ref = jax.grad(loss_ref)(ws)
    g = jax.jit(jax.shard_map(
        lambda w, xx: jax.grad(lambda ww: jnp.sum(
            pl.pipeline_apply_interleaved(stage_fn, ww, xx, M, V) ** 2))(w),
        mesh=mesh, in_specs=(P(const.PIPELINE_AXIS), P()),
        out_specs=P(const.PIPELINE_AXIS), check_vma=False))(ws, x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-6)


def test_interleaved_schedule_structure():
    """The interleaved scan runs M*V + S - 1 slots (vs GPipe's M + S - 1
    of V-times-bigger chunks) and its ring ppermute carries the
    wraparound edge S-1 -> 0 that GPipe's chain never uses — the
    chunk-boundary hop the schedule is made of."""
    from autodist_tpu.parallel import pipeline as pl
    from autodist_tpu.kernel.common import op_info
    S, V, M, B, D = 4, 2, 8, 16, 6
    mesh = Mesh(np.array(jax.devices()[:S]), (const.PIPELINE_AXIS,))
    ws = jnp.zeros((S * V, D, D), jnp.float32)
    x = jnp.zeros((B, D), jnp.float32)

    def stage_fn(w, h):
        return pl.stacked_scan(lambda p, hh: jnp.tanh(hh @ p), w, h)

    jaxpr = jax.make_jaxpr(jax.shard_map(
        lambda w, xx: pl.pipeline_apply_interleaved(stage_fn, w, xx, M, V),
        mesh=mesh, in_specs=(P(const.PIPELINE_AXIS), P()), out_specs=P(),
        check_vma=False))(ws, x)
    scans, perms = [], []

    def walk(jp):
        for eqn in jp.eqns:
            if eqn.primitive.name == "scan":
                scans.append(int(eqn.params.get("length", 0)))
            if eqn.primitive.name == "ppermute":
                perms.append(eqn.params.get("perm"))
            for sub in op_info.sub_jaxprs(eqn):
                walk(sub)
    walk(jaxpr.jaxpr)
    assert (M * V + S - 1) in scans, scans
    ring = [p for p in perms if (S - 1, 0) in [tuple(e) for e in p]]
    assert ring, "no full-ring ppermute (wraparound edge) found: %s" % perms


def test_pp_lm_interleaved_matches_single_device():
    """Full stack with schedule='interleaved': the model's logical layer
    order is schedule-defined (physical chunk r*V+c = logical stage
    c*S+r), its degenerate path emulates the same order via
    pp_shards, and distributed training matches jax.grad of that very
    loss."""
    pp, V, micro = 2, 2, 4
    dp = 8 // pp
    cfg = TPLMConfig.tiny(num_layers=pp * V)
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=micro * dp, seed=1,
        n_microbatches=micro, schedule="interleaved",
        virtual_stages=V, pp_shards=pp, model_axis=None)
    opt = optax.sgd(0.05)
    rng = np.random.RandomState(2)
    batches = [batch, {"tokens": rng.randint(
        0, cfg.vocab_size, batch["tokens"].shape).astype(np.int32)}]

    @jax.jit
    def step(p, s, b):
        g = jax.grad(loss_fn)(p, b)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    ref, state = params, opt.init(params)
    for b in batches:
        ref, state = step(ref, state, b)

    ad = adt.AutoDist(strategy_builder=strategy.PipelineParallel(
        pp_shards=pp, n_microbatches=micro, schedule="interleaved",
        virtual_stages=V, mp_rules=pipe_lm.pp_rules()))
    runner = ad.build(loss_fn, opt, params, batches[0])
    gc = runner.distributed_step.strategy.graph_config
    assert gc.pp_schedule == "interleaved" and gc.pp_virtual == V
    runner.init(params)
    for b in batches:
        m = runner.run(b)
    assert np.isfinite(m["loss"])
    got = runner.gather_params()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6),
        got, ref)


def test_cost_model_ranks_interleaved_at_small_m():
    """At M close to S the GPipe bubble (S-1)/M dominates; the interleaved
    schedule's (S-1)/(V*M) must price faster — and the gap must shrink as
    M grows."""
    from autodist_tpu.simulator.simulator import Simulator
    from autodist_tpu.model_item import ModelItem
    from autodist_tpu.resource_spec import ResourceSpec
    cfg = TPLMConfig.tiny(num_layers=8)
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=32, n_microbatches=8)
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.01),
                     params=params, example_batch=batch).prepare()
    spec = ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 8}]})
    sim = Simulator(item, spec)

    def t(schedule, m, **kw):
        s = strategy.PipelineParallel(
            pp_shards=8, n_microbatches=m, schedule=schedule,
            mp_rules=pipe_lm.pp_rules(), **kw).build(item, spec)
        return sim.simulate(s).breakdown.compute_s

    assert t("interleaved", 8, virtual_stages=4) < t("gpipe", 8)
    gap_small_m = t("gpipe", 8) / t("interleaved", 8, virtual_stages=4)
    gap_big_m = t("gpipe", 64) / t("interleaved", 64, virtual_stages=4)
    assert gap_small_m > gap_big_m > 1.0


def test_build_rejects_schedule_loss_mismatch():
    """The schedule is baked into the loss; a strategy claiming another
    one (e.g. an AutoStrategy alternate) must fail the build with a
    rebuild instruction, not run GPipe while priced as 1F1B."""
    cfg = TPLMConfig.tiny()
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=8, n_microbatches=2, schedule="gpipe")
    ad = adt.AutoDist(strategy_builder=strategy.PipelineParallel(
        pp_shards=2, n_microbatches=2, schedule="1f1b",
        mp_rules=pipe_lm.pp_rules()))
    with pytest.raises(ValueError, match="rebuild the model's loss"):
        ad.build(loss_fn, optax.sgd(0.05), params, batch,
                 mp_meta={"pp_schedule": "gpipe"})


def test_interleaved_setup_requires_pp_shards():
    with pytest.raises(ValueError, match="requires pp_shards"):
        pipe_lm.make_train_setup(TPLMConfig.tiny(num_layers=4),
                                 schedule="interleaved", virtual_stages=2)


def test_pp_lm_interleaved_with_tp_matches_single_device():
    """interleaved x tensor-parallel composition: V chunks per pipe rank
    with Megatron column/row compute inside each chunk — the schedule has
    no per-tick branching (unlike 1F1B's lax.cond), so in-chunk model-axis
    collectives stay trivially matched."""
    pp, tp, V, micro = 2, 2, 2, 4
    dp = 8 // (pp * tp)
    cfg = TPLMConfig.tiny(num_layers=pp * V)
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=micro * dp, seed=1,
        n_microbatches=micro, schedule="interleaved",
        virtual_stages=V, pp_shards=pp, model_axis=const.MODEL_AXIS)
    opt = optax.sgd(0.05)

    @jax.jit
    def step(p, s, b):
        g = jax.grad(loss_fn)(p, b)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    ref, state = params, opt.init(params)
    for _ in range(2):
        ref, state = step(ref, state, batch)

    ad = adt.AutoDist(strategy_builder=strategy.PipelineParallel(
        pp_shards=pp, tp_shards=tp, n_microbatches=micro,
        schedule="interleaved", virtual_stages=V,
        mp_rules=pipe_lm.pp_rules(model_axis=const.MODEL_AXIS)))
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    for _ in range(2):
        m = runner.run(batch)
    assert np.isfinite(m["loss"])
    got = runner.gather_params()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6),
        got, ref)


def test_interleaved_remat_chunks_same_numerics_smaller_stash():
    """remat_chunks=True trades FLOPs for HBM: identical gradients, and
    the scan's AD residuals shrink (only slot inputs are stashed; the
    intra-chunk layer activations recompute in the backward)."""
    from autodist_tpu.parallel import pipeline as pl
    from autodist_tpu.kernel.common import op_info
    S, V, M, B, D = 4, 2, 8, 256, 8
    # big microbatches x 8 layers per chunk: the intra-chunk ACTIVATION
    # stash dominates the residuals (the per-slot chunk-param slices are
    # stored either way)
    L = S * V * 8
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(L, D, D) * 0.2, jnp.float32)
    x = jnp.asarray(rng.randn(B, D), jnp.float32)

    def stage_fn(w, h):
        return pl.stacked_scan(lambda p, hh: jnp.tanh(hh @ p), w, h)

    mesh = Mesh(np.array(jax.devices()[:S]), (const.PIPELINE_AXIS,))

    def grads(remat):
        return jax.jit(jax.shard_map(
            lambda w, xx: jax.grad(lambda ww: jnp.sum(
                pl.pipeline_apply_interleaved(
                    stage_fn, ww, xx, M, V,
                    remat_chunks=remat) ** 2))(w),
            mesh=mesh, in_specs=(P(const.PIPELINE_AXIS), P()),
            out_specs=P(const.PIPELINE_AXIS), check_vma=False))(ws, x)

    g0, g1 = grads(False), grads(True)
    np.testing.assert_allclose(np.asarray(g0), np.asarray(g1),
                               rtol=1e-5, atol=1e-7)

    def residual_bytes(remat):
        """Bytes the fwd scan hands the bwd scan (its non-carry outputs)."""
        jaxpr = jax.make_jaxpr(jax.shard_map(
            lambda w, xx: jax.grad(lambda ww: jnp.sum(
                pl.pipeline_apply_interleaved(
                    stage_fn, ww, xx, M, V, remat_chunks=remat) ** 2))(w),
            mesh=mesh, in_specs=(P(const.PIPELINE_AXIS), P()),
            out_specs=P(const.PIPELINE_AXIS), check_vma=False))(ws, x)
        best = 0
        def walk(jp):
            nonlocal best
            for eqn in jp.eqns:
                if eqn.primitive.name == "scan" and eqn.params.get(
                        "length") == M * V + S - 1:
                    n_carry = eqn.params["num_carry"]
                    stacked = sum(
                        int(np.prod(v.aval.shape[1:] or (1,)))
                        * v.aval.dtype.itemsize * v.aval.shape[0]
                        for v in eqn.outvars[n_carry:]
                        if hasattr(v, "aval") and v.aval.shape)
                    best = max(best, stacked)
                for sub in op_info.sub_jaxprs(eqn):
                    walk(sub)
        walk(jaxpr.jaxpr)
        return best

    plain, remat = residual_bytes(False), residual_bytes(True)
    assert plain > 0 and remat > 0
    assert remat < 0.5 * plain, (plain, remat)


def test_build_rejects_pp_knob_mismatches():
    """The guard covers every baked pipeline knob, not just the schedule:
    a strategy with different microbatches — or, for interleaved, a
    different stage count than the loss's logical layer order — fails
    loudly with the rebuild instruction."""
    cfg = TPLMConfig.tiny(num_layers=4)
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=8, n_microbatches=2, schedule="gpipe")
    ad = adt.AutoDist(strategy_builder=strategy.PipelineParallel(
        pp_shards=2, n_microbatches=4, mp_rules=pipe_lm.pp_rules()))
    with pytest.raises(ValueError, match="pp_microbatches"):
        ad.build(loss_fn, optax.sgd(0.05), params, batch,
                 mp_meta={"pp_schedule": "gpipe", "pp_microbatches": 2})
    adt.reset()

    # interleaved: the loss bakes pp_shards=2; a pp4 strategy must refuse
    loss_i, params_i, batch_i, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=8, n_microbatches=4,
        schedule="interleaved", virtual_stages=2, pp_shards=2)
    ad = adt.AutoDist(strategy_builder=strategy.PipelineParallel(
        pp_shards=4, n_microbatches=4, schedule="interleaved",
        virtual_stages=2, mp_rules=pipe_lm.pp_rules()))
    with pytest.raises(ValueError, match="pp_shards"):
        ad.build(loss_i, optax.sgd(0.05), params_i, batch_i,
                 mp_meta={"pp_schedule": "interleaved",
                          "pp_microbatches": 4, "pp_virtual": 2,
                          "pp_shards": 2})
