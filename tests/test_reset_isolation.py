"""reset() must actually isolate sequential in-process combos.

The reference runs every strategy x case combo in a fresh subprocess
(``tests/integration/test_all.py:53-69``); our matrix runs in-process on
``reset()``, so reset has to tear down every piece of process-global
state a combo can leak: async-PS serving threads, coordination sockets,
a capture context left by an exception mid-trace, and the id-keyed
optimizer-capture registry.
"""
import numpy as np
import jax.numpy as jnp
import optax
import pytest

import autodist_tpu as adt
from autodist_tpu import patch, strategy
from autodist_tpu.ops import embedding


def _linreg(seed=0):
    rng = np.random.RandomState(seed)
    params = {"w": jnp.zeros((8, 2), jnp.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    batch = {"x": rng.randn(16, 8).astype(np.float32),
             "y": rng.randn(16, 2).astype(np.float32)}
    return params, loss_fn, batch


def test_reset_stops_async_serving_threads():
    """An async-PS combo leaves an owner apply thread and a published
    service behind; reset() must stop the thread (a live one would keep
    applying stale gradients into the next combo's process)."""
    params, loss_fn, batch = _linreg()
    ad = adt.AutoDist(strategy_builder=strategy.PS(sync=False))
    runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
    runner.init(params)
    runner.run(batch)
    store = runner.distributed_step.ps_store
    workers = [g["worker"] for g in store._serve_groups.values()
               if g["worker"] is not None]
    assert workers and all(w._thread.is_alive() for w in workers)
    adt.reset()
    assert all(not w._thread.is_alive() for w in workers)


def test_reset_clears_leaked_capture_context():
    """A capture context orphaned by an exception mid-trace must not leak
    taps into the next build's lookups."""
    embedding._TLS.capture = embedding.SparseCapture(record=True)
    assert embedding.current_capture() is not None
    adt.reset()
    assert embedding.current_capture() is None


def test_reset_clears_optimizer_capture_registry():
    """The optimizer registry keys by object id; across a reset the
    allocator can reuse a freed id for a DIFFERENT optimizer, which would
    then inherit the stale record."""
    patch.patch_optax()
    opt = optax.adam(1e-3)
    name, _ = patch.lookup_optimizer(opt)
    assert name  # captured
    adt.reset()
    assert patch.lookup_optimizer(opt)[0] is None


def test_combo_results_identical_after_interleaved_combos():
    """State-bleed canary: combo A's trajectory must be bit-identical
    whether it runs first or after unrelated combos (async PS + sparse)
    with resets in between."""
    def run_a():
        params, loss_fn, batch = _linreg()
        ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
        runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
        runner.init(params)
        losses = [float(runner.run(batch)["loss"]) for _ in range(4)]
        out = {k: np.asarray(v) for k, v in runner.gather_params().items()}
        adt.reset()
        return losses, out

    first_losses, first_params = run_a()

    # unrelated combos in between: async serving + a sparse-wire build
    params, loss_fn, batch = _linreg(seed=7)
    ad = adt.AutoDist(strategy_builder=strategy.PS(sync=False))
    r = ad.build(loss_fn, optax.sgd(0.1), params, batch)
    r.init(params)
    r.run(batch)
    adt.reset()

    rng = np.random.RandomState(3)
    sp_params = {"emb": jnp.asarray(rng.randn(64, 8), jnp.float32)}

    def sp_loss(p, b):
        from autodist_tpu.ops.embedding import embedding_lookup
        return jnp.mean(embedding_lookup(p["emb"], b["ids"], name="emb") ** 2)

    sp_batch = {"ids": rng.randint(0, 64, (16,)).astype(np.int32)}
    ad = adt.AutoDist(strategy_builder=strategy.Parallax())
    r = ad.build(sp_loss, optax.sgd(0.1), sp_params, sp_batch)
    r.init(sp_params)
    r.run(sp_batch)
    adt.reset()

    again_losses, again_params = run_a()
    np.testing.assert_array_equal(first_losses, again_losses)
    for k in first_params:
        np.testing.assert_array_equal(first_params[k], again_params[k])
