"""Subprocess-isolated combos match the in-process (reset-isolated) runs.

The reference forks a fresh process per strategy x case combo
(``tests/integration/test_all.py:53-69``); our matrix runs in-process on
``reset()``. This module proves the two are equivalent: representative
combos run in a genuinely fresh subprocess and their full trajectories
must equal the in-process runs bit-for-bit — if ``reset()`` ever leaks
state that changes results, the in-process number drifts off the
fresh-process truth and this fails. Both sides execute the SAME code
(``test_integration_matrix.run_combo``), with the matrix's own builder
configurations.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))

COMBOS = [("AllReduce", "flax"), ("Parallax", "sparse"),
          ("PartitionedPS", "scan")]

CHILD = """
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, %(root)r)
sys.path.insert(0, %(tests)r)
import numpy as np
from test_integration_matrix import run_combo

out = run_combo(sys.argv[1], sys.argv[2])
out["params"] = {k: np.asarray(v).tolist() for k, v in out["params"].items()}
print("RESULT\\t" + json.dumps(out))
"""


@pytest.mark.parametrize("builder_name,case_name", COMBOS)
def test_subprocess_combo_matches_inprocess(builder_name, case_name):
    script = CHILD % {"root": os.path.dirname(HERE), "tests": HERE}
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    # APPEND the device-count flag: ambient numerics-affecting XLA flags
    # must apply identically to both sides of the comparison
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, "-c", script, builder_name, case_name],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("RESULT\t")][-1]
    fresh = json.loads(line[len("RESULT\t"):])

    from tests.test_integration_matrix import run_combo
    ours = run_combo(builder_name, case_name)
    np.testing.assert_array_equal(fresh["losses"], ours["losses"])
    assert set(fresh["params"]) == set(ours["params"])
    for k, v in fresh["params"].items():
        np.testing.assert_array_equal(np.asarray(v), ours["params"][k],
                                      err_msg=k)
