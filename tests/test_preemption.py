"""Preemption plane (runtime/preemption.py): advance-notice departure.

Tier-1 legs: loud knob validation, the notice/plan/left KV protocol and
the operator drain CLI against a REAL coordination service, the
watchdog's departure-mark consultation (an announced leaver is never
escalated as dead), the chief's planned shrink published while the
leaver is ALIVE, deterministic SIGTERM chaining with the blackbox dump
hook (both orders, dump LAST), the deadline-budgeted rescue checkpoint
(taken and the skip branch), serving drain under concurrent submit
(in-flight completes, queued sheds typed with Retry-After), the
``faultinject`` preempt delivery (real SIGTERM, deadline SIGKILL), the
ADT432 build-time warning, and a REAL solo graceful departure plus a
REAL planned peer-departure reconfigure, end to end in subprocesses.
The randomized five-plane chaos campaign is the slow/chaos leg
(``tests/chaos_campaign.py``; 3 seeds nightly).
"""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

from autodist_tpu.runtime import elastic, preemption
from autodist_tpu.runtime.coordination import (CoordinationClient,
                                               CoordinationServer)
from autodist_tpu.telemetry import spans as tel

HERE = os.path.dirname(os.path.abspath(__file__))
PORT = 15917


@pytest.fixture(scope="module")
def server():
    srv = CoordinationServer(port=PORT)
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture(autouse=True)
def _clean_plane():
    yield
    elastic.clear()
    preemption.reset()


def _client(**kw):
    return CoordinationClient("127.0.0.1", PORT, **kw)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _counter(name):
    return tel.counters().get(name, 0.0)


# ----------------------------------------------------------- knob validation


def test_preempt_knobs_validated_loudly(monkeypatch):
    """Garbage/negative preemption knobs raise the typed config error
    NAMING the knob (the ElasticConfigError pattern) at bring-up."""
    monkeypatch.setenv("ADT_PREEMPT_DEADLINE_S", "soon")
    with pytest.raises(elastic.ElasticConfigError) as e:
        preemption.validate_preempt_knobs()
    assert e.value.knob == "ADT_PREEMPT_DEADLINE_S"

    monkeypatch.setenv("ADT_PREEMPT_DEADLINE_S", "-5")
    with pytest.raises(elastic.ElasticConfigError,
                       match="ADT_PREEMPT_DEADLINE_S"):
        preemption.validate_preempt_knobs()

    monkeypatch.setenv("ADT_PREEMPT_DEADLINE_S", "45")
    monkeypatch.setenv("ADT_PREEMPT_POLL_S", "-1")
    with pytest.raises(elastic.ElasticConfigError,
                       match="ADT_PREEMPT_POLL_S"):
        preemption.validate_preempt_knobs()

    monkeypatch.setenv("ADT_PREEMPT_POLL_S", "0")
    monkeypatch.setenv("ADT_DRAIN_RETRY_AFTER_S", "later")
    with pytest.raises(elastic.ElasticConfigError,
                       match="ADT_DRAIN_RETRY_AFTER_S"):
        preemption.validate_preempt_knobs()

    monkeypatch.setenv("ADT_DRAIN_RETRY_AFTER_S", "2.5")
    assert preemption.validate_preempt_knobs() == (45.0, 0.0, 2.5)


# ----------------------------------------------------------- notice protocol


def test_notice_protocol_roundtrip(server):
    """publish/read/plan/left/clear over a real service; the seq cursor
    advances on every publish so pollers re-scan only on change."""
    c = _client()
    seq0 = c.get(preemption.SEQ_KEY)
    before = _counter("preempt.notices")
    notice = preemption.publish_notice(c, "w7", deadline_s=30,
                                       reason="maintenance")
    assert _counter("preempt.notices") == before + 1
    assert c.get(preemption.SEQ_KEY) != seq0
    got = preemption.read_notice(c, "w7")
    assert got is not None and got.reason == "maintenance"
    assert got.worker == "w7"
    # the wire rounds timestamps to the microsecond
    assert abs(got.deadline - notice.deadline) < 1e-3
    assert 0 < got.remaining_s() <= 30

    preemption.publish_plan(c, "w7", 12, notice)
    plan = preemption.read_plan(c, "w7")
    assert plan["rescue_step"] == 12 and plan["reason"] == "maintenance"

    assert preemption.has_left(c, "w7") is False
    preemption.mark_left(c, "w7")
    assert preemption.has_left(c, "w7") is True

    preemption.clear_notice(c, "w7")
    assert preemption.read_notice(c, "w7") is None
    assert preemption.read_plan(c, "w7") is None
    assert preemption.has_left(c, "w7") is False

    # an expired notice reads as None (GC-stale: cancelled eviction)
    c.put(preemption.NOTICE_PREFIX + "w8", preemption.PreemptionNotice(
        "w8", time.time() - preemption.NOTICE_STALE_AFTER_S - 1,
        "drain").to_json())
    assert preemption.read_notice(c, "w8") is None
    c.close()


def test_drain_cli_publishes_and_reports(server, capsys):
    """The operator ``drain`` verb publishes the mark; ``status`` reads
    it back as JSON."""
    rc = preemption.main(["drain", "w-cli", "--deadline", "42",
                          "--reason", "kernel-upgrade",
                          "--port", str(PORT)])
    assert rc == 0
    assert "w-cli" in capsys.readouterr().out
    c = _client()
    notice = preemption.read_notice(c, "w-cli")
    assert notice is not None and notice.reason == "kernel-upgrade"
    assert 0 < notice.remaining_s() <= 42
    c.close()

    rc = preemption.main(["status", "w-cli", "--port", str(PORT)])
    assert rc == 0
    status = json.loads(capsys.readouterr().out)
    assert status["notice"]["reason"] == "kernel-upgrade"
    assert status["left"] is False


def test_maintenance_poller_one_shot(tmp_path):
    """The cloud maintenance hook: file existence signals the eviction,
    its JSON body carries deadline/reason, and the event is one-shot."""
    path = tmp_path / "maintenance.json"
    poller = preemption.MaintenancePoller(str(path))
    assert poller.check() is None
    path.write_text(json.dumps({"deadline_s": 90, "reason": "tpu-maint"}))
    notice = poller.check()
    assert notice is not None and notice.reason == "tpu-maint"
    assert 80 < notice.remaining_s() <= 90
    assert poller.check() is None  # consumed

    # a bare touch file uses the env-default deadline
    bare = tmp_path / "bare"
    bare.write_text("")
    notice = preemption.MaintenancePoller(str(bare)).check()
    assert notice is not None and notice.reason == "maintenance"

    # a body carrying ONLY a reason keeps it (deadline defaults)
    partial = tmp_path / "partial.json"
    partial.write_text(json.dumps({"reason": "kernel-upgrade"}))
    notice = preemption.MaintenancePoller(str(partial)).check()
    assert notice is not None and notice.reason == "kernel-upgrade"
    assert notice.remaining_s() > 0


# -------------------------------------------- watchdog × announced departure


def _mini_coordinator(tmp_path, monkeypatch, inrun=False):
    monkeypatch.setenv("ADT_COORDSVC_PORT", str(PORT))
    if inrun:
        monkeypatch.setenv("ADT_ELASTIC", "1")
        monkeypatch.setenv("ADT_ELASTIC_SYNC", "1")
        monkeypatch.setenv("ADT_ELASTIC_INRUN", "1")
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.runtime.cluster import Cluster
    from autodist_tpu.runtime.coordinator import Coordinator
    spec = tmp_path / "spec.yml"
    spec.write_text(
        "nodes:\n  - address: 127.0.0.1\n    chief: true\n    cpus: [0]\n"
        "  - address: localhost\n    cpus: [0]\n")
    return Coordinator("sid-preempt", Cluster(ResourceSpec(str(spec))),
                       heartbeat_timeout=5.0,
                       max_restarts=1 if inrun else 0)


def test_watchdog_consults_departure_mark(server, tmp_path, monkeypatch):
    """Satellite: an announced leaver whose heartbeat stops mid-handoff
    must NOT be declared dead (no unplanned-death escalation, no mark
    GC) — the departure mark wins over heartbeat silence until a grace
    past the deadline."""
    coord = _mini_coordinator(tmp_path, monkeypatch)
    c = _client()
    assert coord._is_departing(c, "wdep") is False
    preemption.publish_notice(c, "wdep", deadline_s=30, reason="drain")
    assert coord._is_departing(c, "wdep") is True
    assert "wdep" in coord._planned_departures
    # aged-out notice: a NEXT incarnation must be supervisable again
    coord._planned_departures["wdep"] = (
        time.time() - 2 * coord._heartbeat_timeout - 1)
    assert coord._is_departing(c, "wdep") is False
    assert "wdep" not in coord._planned_departures
    preemption.clear_notice(c, "wdep")
    coord.stop_watchdog()
    c.close()


def test_planned_shrink_published_while_leaver_alive(server, tmp_path,
                                                     monkeypatch):
    """The chief's watchdog answers an announced departure by publishing
    the survivor roster at epoch+1 BEFORE the leaver dies — no reap, no
    relaunch, no restart-budget spend — and a planned leaver's process
    exit is shutdown, never an abort."""
    coord = _mini_coordinator(tmp_path, monkeypatch, inrun=True)
    # the shrink-soundness gate has its own tests (test_elastic_epoch);
    # here it must not veto the published plan over an unreadable
    # test-strategy id
    monkeypatch.setattr(coord, "_shrink_unsound_reason", lambda a: None)
    c = _client()
    base = 300
    elastic.publish_epoch(c, base, ["127.0.0.1", "localhost"])
    before = _counter("preempt.planned_shrinks")
    coord._maybe_plan_departures(c)  # no notice: nothing happens
    assert elastic.read_epoch(c)[0] == base

    preemption.publish_notice(c, "localhost", deadline_s=30,
                              reason="maintenance")
    coord._maybe_plan_departures(c)
    epoch, roster = elastic.read_epoch(c)
    assert epoch == base + 1 and roster == ["127.0.0.1"]
    assert _counter("preempt.planned_shrinks") == before + 1
    assert coord._restarts == {}  # planned: no budget spent
    # only an actually-SHRUNK departure lets the process watcher treat
    # a nonzero exit as shutdown (unsound/chief departures fall through
    # to the whole-job restart their log promises)
    assert "localhost" in coord._departures_shrunk
    # idempotent: the handled departure is not re-planned next tick
    coord._maybe_plan_departures(c)
    assert elastic.read_epoch(c)[0] == base + 1
    preemption.clear_notice(c, "localhost")
    coord.stop_watchdog()
    coord.join()
    c.close()


# ----------------------------------------------- SIGTERM chaining (dump-last)


def _fire_sigterm_handler():
    handler = signal.getsignal(signal.SIGTERM)
    assert callable(handler), "no SIGTERM handler installed"
    handler(signal.SIGTERM, None)


@pytest.mark.parametrize("order", ["blackbox-first", "preempt-first"])
def test_sigterm_chain_both_fire_dump_last(tmp_path, monkeypatch, order):
    """Satellite: the preemption SIGTERM handler and the blackbox dump
    hook chain deterministically in BOTH install orders — both fire, the
    dump runs LAST (its event tail contains the notice), and the
    process survives (grace window, no default-disposition re-raise)."""
    from autodist_tpu.telemetry import blackbox
    monkeypatch.setenv("ADT_BLACKBOX_DIR", str(tmp_path))
    monkeypatch.setenv("ADT_PREEMPT_DEADLINE_S", "30")
    original = signal.getsignal(signal.SIGTERM)
    try:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        monkeypatch.setattr(blackbox, "_signal_hook_installed", False)
        monkeypatch.setattr(preemption, "_sigterm_installed", False)
        monkeypatch.setattr(preemption, "_signal_notice", None)
        # a guard is armed (grace active): the chain must not re-raise
        preemption._armed_guards.append(object())
        fr = blackbox.get_flight_recorder()
        fr.clear()
        if order == "blackbox-first":
            blackbox._install_hooks()
            assert preemption.install_sigterm_notice() is True
        else:
            assert preemption.install_sigterm_notice() is True
            blackbox._install_hooks()
        dumps_before = fr.dumps
        _fire_sigterm_handler()
        # both fired: the notice is live AND a dump landed
        assert preemption.signal_notice() is not None
        assert fr.dumps == dumps_before + 1
        dump = blackbox.load_dump(fr.last_dump_path)
        kinds = [e["kind"] for e in dump["events"]]
        # dump-last: the dump's own event tail already CONTAINS the
        # notice — the notice handler ran before the snapshot was taken
        assert "preempt.notice" in kinds
        assert "signal" in kinds
    finally:
        signal.signal(signal.SIGTERM, original)
        preemption.reset()


# --------------------------------------------------- serving drain satellite


def test_serving_drain_under_concurrent_submit(monkeypatch):
    """Satellite: drain with traffic in flight — the in-flight group's
    futures COMPLETE, queued futures shed typed with the Retry-After,
    post-drain submits shed immediately, and the serve.shed /
    serve.drained counters account all of it."""
    import optax

    import autodist_tpu
    from autodist_tpu import strategy as S
    from autodist_tpu.serving import (InferenceEngine, MicroBatcher,
                                      ServingConfig, ServingUnavailable)
    rng = np.random.RandomState(0)
    params = {"emb": rng.randn(16, 4).astype(np.float32),
              "w": rng.randn(4, 2).astype(np.float32)}

    def loss_fn(p, batch):
        import jax.numpy as jnp
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        return jnp.mean((feat @ p["w"] - batch["y"]) ** 2)

    def serve_fn(p, batch):
        import jax.numpy as jnp
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        return {"score": feat @ p["w"]}

    batch = {"ids": rng.randint(0, 16, size=(8,)).astype(np.int32),
             "y": rng.randn(8, 2).astype(np.float32)}
    requests = [{"ids": batch["ids"][i]} for i in range(8)]
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.adam(0.1), params, batch)
    runner.init(params)
    engine = InferenceEngine(runner, serve_fn, requests[0],
                             ServingConfig(buckets=(8,),
                                           max_delay_ms=1.0)).warmup()
    from autodist_tpu.serving import active_batchers
    hold = threading.Event()
    real_run = engine.run_batch
    monkeypatch.setattr(
        engine, "run_batch",
        lambda reqs: (hold.wait(timeout=30), real_run(reqs))[1])
    mb = MicroBatcher(engine)
    assert mb in active_batchers()
    in_flight = mb.submit(requests[0])
    time.sleep(0.15)  # the worker took it and is blocked in run_batch
    queued = [mb.submit(r) for r in requests[1:3]]
    shed_before = _counter("serve.shed")
    drained_before = _counter("serve.drained")

    def release_soon():
        time.sleep(0.3)
        hold.set()
    threading.Thread(target=release_soon, daemon=True).start()
    shed = mb.drain(retry_after_s=7.5)
    assert shed == 2
    # in-flight COMPLETED during the drain — a real result, not a shed
    assert in_flight.result(timeout=5)["score"].shape == (2,)
    # queued futures carry the typed Retry-After shed
    for f in queued:
        with pytest.raises(ServingUnavailable) as e:
            f.result(timeout=1)
        assert e.value.retry_after_s == 7.5
    # post-drain submits shed synchronously, typed, with the Retry-After
    with pytest.raises(ServingUnavailable, match="draining") as e:
        mb.submit(requests[3])
    assert e.value.retry_after_s == 7.5
    stats = mb.stats()
    assert stats["drained"] == 1 and stats["shed"] >= 2
    assert _counter("serve.shed") == shed_before + 2
    assert _counter("serve.drained") == drained_before + 1
    mb.drain()  # idempotent
    autodist_tpu.reset()


# ------------------------------------------------ rescue deadline budgeting


def _build_tiny_runner(port, ckpt_dir, monkeypatch, preempt_poll="0.01"):
    import optax

    import autodist_tpu as adt
    from autodist_tpu import strategy
    monkeypatch.setenv("ADT_COORDSVC_PORT", str(port))
    monkeypatch.setenv("ADT_ELASTIC", "1")
    monkeypatch.setenv("ADT_ELASTIC_SYNC", "1")
    monkeypatch.setenv("ADT_ELASTIC_INRUN", "1")
    monkeypatch.setenv("ADT_ELASTIC_POLL_S", "0.01")
    monkeypatch.setenv("ADT_PREEMPT_POLL_S", preempt_poll)
    monkeypatch.setenv("ADT_CKPT_DIR", str(ckpt_dir))
    adt.reset()
    rng = np.random.RandomState(0)
    import jax
    params = {"w": jax.numpy.asarray(rng.randn(8, 4) * 0.3,
                                     jax.numpy.float32)}

    def loss_fn(p, batch):
        import jax.numpy as jnp
        return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

    batch = {"x": rng.randn(8, 8).astype(np.float32),
             "y": rng.randn(8, 4).astype(np.float32)}
    ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.05), params, batch)
    runner.init(params)
    return runner, batch


def test_rescue_checkpoint_deadline_skip_branch(server, tmp_path,
                                                monkeypatch):
    """Satellite: when the remaining grace cannot cover the measured
    ckpt.save_ms p99 (× safety), the rescue save is SKIPPED — counted,
    no file written — and the departure goes straight to the handoff."""
    ckpt_dir = tmp_path / "ckpt"
    runner, batch = _build_tiny_runner(PORT, ckpt_dir, monkeypatch)
    runner.run(batch)
    # measured saves are catastrophically slow vs a 0.8s grace window
    tel.hist_observe("ckpt.save_ms", 60000.0)
    c = _client()
    preemption.publish_notice(c, runner._preempt.worker, deadline_s=0.8,
                              reason="spot")
    time.sleep(0.05)
    skips_before = _counter("preempt.rescue_skips")
    with pytest.raises(preemption.PlannedDeparture):
        for _ in range(5):
            runner.run(batch)
    assert _counter("preempt.rescue_skips") == skips_before + 1
    stats = runner.step_stats()["preempt"]
    assert stats["rescue_saves"] == 0.0 or not os.path.exists(ckpt_dir) \
        or not any(f.endswith(".meta.json") for f in os.listdir(ckpt_dir))
    assert stats["handoffs"] >= 1.0
    preemption.clear_notice(c, runner._preempt.worker)
    c.close()


def test_solo_graceful_departure_e2e(server, tmp_path, monkeypatch):
    """A REAL drain end to end (single worker, no survivors): operator
    notice → cluster-agreed rescue plan → committed rescue checkpoint →
    serving drained → PlannedDeparture with exit code 0 and the left
    stamp published; fit()'s unwind does not mask the departure."""
    ckpt_dir = tmp_path / "ckpt"
    runner, batch = _build_tiny_runner(PORT, ckpt_dir, monkeypatch)
    c = _client()
    worker = runner._preempt.worker
    runner.run(batch)

    def drain_soon():
        time.sleep(0.3)
        preemption.publish_notice(c, worker, deadline_s=30, reason="drain")
    threading.Thread(target=drain_soon, daemon=True).start()
    import itertools
    with pytest.raises(preemption.PlannedDeparture) as e:
        runner.fit(itertools.repeat(batch), steps=10_000)
    assert e.value.code == 0
    stats = runner.step_stats()["preempt"]
    assert stats["rescue_saves"] == 1.0
    assert stats["handoffs"] == 1.0 and stats["last_handoff_s"] > 0
    # the rescue checkpoint COMMITTED at the agreed step
    from autodist_tpu.checkpoint import integrity
    committed = [s for s in integrity.scan(str(ckpt_dir))
                 if s.state == "committed"]
    plan = preemption.read_plan(c, worker)
    assert committed and plan is not None
    assert max(s.step for s in committed) >= plan["rescue_step"]
    assert preemption.has_left(c, worker) is True
    # planned path: zero checkpoint-fallback restores
    assert _counter("ckpt.fallback") == 0.0
    preemption.clear_notice(c, worker)
    c.close()


def test_exclusion_epoch_outrunning_notice_poll_still_departs(
        server, tmp_path, monkeypatch):
    """Race: the chief publishes the shrink epoch right after the drain
    notice, and the leaver's epoch poll (fast) sees the exclusion before
    its throttled notice poll (here: 60 s) ever adopted the mark — the
    reconfigure path must consult the KV notice UNTHROTTLED and depart
    gracefully, never crash with the zombie FencedOut."""
    ckpt_dir = tmp_path / "ckpt"
    runner, batch = _build_tiny_runner(PORT, ckpt_dir, monkeypatch,
                                       preempt_poll="60")
    c = _client()
    worker = runner._preempt.worker
    runner.run(batch)
    m = elastic.current()
    # notice + exclusion land back to back, before any notice poll
    preemption.publish_notice(c, worker, deadline_s=30, reason="drain")
    elastic.publish_epoch(c, m.epoch + 1, ["the-survivor"])
    time.sleep(0.05)
    with pytest.raises(preemption.PlannedDeparture) as e:
        for _ in range(5):
            runner.run(batch)
    assert e.value.code == 0 and e.value.reason == "drain"
    assert runner.step_stats()["preempt"]["handoffs"] == 1.0
    preemption.clear_notice(c, worker)
    c.close()


def test_fence_yields_to_announced_departure_until_deadline(server):
    """The planned-shrink epoch may land BEFORE the leaver's final
    boundary: an ANNOUNCED leaver's writes (rescue checkpoint, flush,
    left stamp) must pass the epoch fence until its deadline — and be
    fenced as a zombie again after it (the SIGKILL has fired; a late
    incarnation must not write)."""
    c = _client()
    base = 400
    elastic.publish_epoch(c, base, ["chief", "wleave"])
    leaver = elastic.Membership("wleave", base, ["chief", "wleave"],
                                client_factory=_client)
    elastic.publish_epoch(c, base + 1, ["chief"])  # announced shrink
    with pytest.raises(elastic.FencedOut):
        leaver.fence("ckpt.save")  # un-announced: zombie semantics
    leaver.expect_departure(time.time() + 30)
    leaver.fence("ckpt.save")  # announced: final boundary proceeds
    leaver.fence("ps.push")
    leaver.expect_departure(time.time() - 1)  # deadline passed...
    leaver.expect_departure(time.time() + 30)  # ...never shrinks back
    leaver.fence("ckpt.save")
    leaver._departure_until = time.time() - 1  # force-expire
    with pytest.raises(elastic.FencedOut):
        leaver.fence("ckpt.save")  # past the deadline: fenced again
    leaver.close()
    c.close()


# --------------------------------------------------- faultinject preempt op


STUBBORN = ("import signal, sys, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: None)\n"
            "print('up', flush=True)\n"
            "time.sleep(60)\n")

GRACEFUL = ("import signal, sys, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))\n"
            "print('up', flush=True)\n"
            "time.sleep(60)\n")


def _spawn_target(code):
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    assert proc.stdout.readline().strip() == "up"
    return proc


def test_deliver_preemption_sigterm_then_deadline_sigkill():
    """The preempt fault delivery: a stubborn target (ignores SIGTERM)
    is SIGKILLed at the deadline; a graceful one departs inside the
    window and is never touched by the killer."""
    from autodist_tpu.runtime import faultinject
    stubborn = _spawn_target(STUBBORN)
    killer = faultinject.deliver_preemption(stubborn.pid, deadline_s=0.5)
    assert stubborn.wait(timeout=10) == -signal.SIGKILL
    killer.join(timeout=5)

    graceful = _spawn_target(GRACEFUL)
    killer = faultinject.deliver_preemption(graceful.pid, deadline_s=2.0)
    assert graceful.wait(timeout=10) == 0  # exited inside the window
    killer.join(timeout=5)


@pytest.mark.chaos
def test_preempt_wire_op_fires_through_proxy(server):
    """The declarative ``{"op": "preempt"}`` wire rule delivers the real
    SIGTERM+deadline-SIGKILL when its nth matching RPC crosses the
    proxy."""
    from autodist_tpu.runtime.faultinject import FaultPlan, FaultyProxy
    stubborn = _spawn_target(STUBBORN)
    plan = FaultPlan({"faults": [
        {"op": "preempt", "match": "PUT", "nth": 2, "deadline_s": 0.5}]})
    with FaultyProxy("127.0.0.1", PORT, plan=plan,
                     preempt_pid=stubborn.pid) as proxy:
        c = CoordinationClient("127.0.0.1", proxy.port)
        c.put("preop/one", "1")     # nth=1: no fire
        assert stubborn.poll() is None
        c.put("preop/two", "2")     # nth=2: SIGTERM + deadline SIGKILL
        assert stubborn.wait(timeout=10) == -signal.SIGKILL
        assert "preempt:PUT" in plan.injected
        c.close()


# ------------------------------------------------------------------- ADT432


def test_adt432_warns_on_model_parallel_handoff():
    """Preemption handoff armed on a fail-fast (model-parallel) family
    warns at build time; data-parallel stays clean."""
    from autodist_tpu.analysis import rules
    mp = types.SimpleNamespace(
        graph_config=types.SimpleNamespace(
            mesh_shape={"data": 2, "model": 4}),
        node_config=[])
    diags = rules.verify_preemption(mp)
    assert [d.code for d in diags] == ["ADT432"]
    assert "model" in diags[0].message

    dp = types.SimpleNamespace(
        graph_config=types.SimpleNamespace(mesh_shape={"data": 8}),
        node_config=[])
    assert rules.verify_preemption(dp) == []
    degenerate = types.SimpleNamespace(
        graph_config=types.SimpleNamespace(
            mesh_shape={"data": 4, "model": 1}),
        node_config=[])
    assert rules.verify_preemption(degenerate) == []


# --------------------------------- planned peer departure: reconfigure e2e


PEER_DRIVER = """
import json, os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import optax
import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.runtime import elastic, preemption
from autodist_tpu.runtime.coordination import (CoordinationClient,
                                               CoordinationServer)
from autodist_tpu.telemetry import spans as tel

outdir = sys.argv[1]
port = int(os.environ["ADT_COORDSVC_PORT"])
srv = CoordinationServer(port)
srv.start()

rng = np.random.RandomState(0)
params = {"w": jax.numpy.asarray(rng.randn(8, 4) * 0.3, jax.numpy.float32)}

def loss_fn(p, batch):
    return jax.numpy.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

batch = {"x": rng.randn(8, 8).astype(np.float32),
         "y": rng.randn(8, 4).astype(np.float32)}

# uninterrupted reference first (no elastic knobs read at build)
ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
step = ad.function(loss_fn, optimizer=optax.sgd(0.05), params=params)
ref = [float(step(batch)["loss"]) for _ in range(10)]
adt.reset()

os.environ["ADT_ELASTIC"] = "1"
os.environ["ADT_ELASTIC_SYNC"] = "1"
os.environ["ADT_ELASTIC_INRUN"] = "1"
os.environ["ADT_ELASTIC_POLL_S"] = "0.01"
os.environ["ADT_PREEMPT_POLL_S"] = "0.01"

# pre-publish a TWO-member roster (this process + a phantom peer about
# to be evicted) so the build adopts it: the survivor's view of a real
# 2-worker job whose peer announces departure
client = CoordinationClient("127.0.0.1", port)
me = "127.0.0.1"
elastic.publish_epoch(client, 1, [me, "peer-leaving"])

ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
runner = ad.build(loss_fn, optax.sgd(0.05), params, batch)
runner.init(params)
m = elastic.current()
assert m is not None and m.roster == [me, "peer-leaving"], m.roster

losses = []
for i in range(10):
    losses.append(float(runner.run(batch)["loss"]))
    if i == 3:
        # the peer announces its departure: every process (this
        # survivor included) joins the rescue checkpoint and pre-stages
        # its snapshot for the announced shrink
        preemption.publish_notice(client, "peer-leaving", deadline_s=30,
                                  reason="maintenance")
        time.sleep(0.05)
    if i == 5:
        # the chief's planned shrink: survivor-only roster, published
        # while the leaver is still alive (here: the phantom peer)
        elastic.publish_epoch(client, 2, [me])
        time.sleep(0.05)

stats = runner.step_stats()
rec = tel.get_recorder()
reconf = [e for e in rec.events() if e.name == "elastic.reconfigure"]
out = {
    "ref": ref, "losses": losses,
    "reconfigs": stats["elastic"]["reconfigs"],
    "epoch": elastic.current().epoch,
    "preempt": stats["preempt"],
    "ckpt_fallback": tel.counters().get("ckpt.fallback", 0.0),
    "planned_flags": [bool(e.args.get("planned")) for e in reconf],
    "reconfigure_s": rec.durations_s("elastic.reconfigure"),
}
with open(os.path.join(outdir, "out.json"), "w") as f:
    json.dump(out, f)
print("DRIVER_DONE", flush=True)
srv.stop()
"""


def test_planned_peer_departure_reconfigures_without_fallback(tmp_path):
    """Acceptance core: a planned eviction of a sync peer completes the
    handoff from LIVE state — the surviving process rescue-checkpoints
    at the agreed step, pre-stages its snapshot, reconfigures under the
    announced shrink epoch with the ``planned`` flag on the downtime
    span, and ``ckpt.fallback`` stays at ZERO while the loss trajectory
    matches the uninterrupted run exactly."""
    script = tmp_path / "driver.py"
    script.write_text(PEER_DRIVER)
    env = dict(os.environ)
    for k in ("ADT_WORKER", "ADT_ELASTIC", "ADT_ELASTIC_SYNC",
              "ADT_ELASTIC_INRUN", "ADT_AUTO_RESUME"):
        env.pop(k, None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "ADT_COORDSVC_PORT": str(_free_port()),
        "ADT_CKPT_DIR": str(tmp_path / "ckpt"),
        "ADT_TRACE": "1",
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE)] +
            ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH")
             else [])),
    })
    proc = subprocess.run([sys.executable, str(script), str(tmp_path)],
                          env=env, capture_output=True, text=True,
                          timeout=240)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-4000:]
    out = json.loads((tmp_path / "out.json").read_text())
    assert out["reconfigs"] == 1 and out["epoch"] == 2, out
    # the survivor joined the cluster-agreed rescue checkpoint
    assert out["preempt"]["rescue_saves"] == 1.0, out["preempt"]
    # the handoff used LIVE state: the reconfigure ran with the
    # pre-staged snapshot (planned flag) and NEVER touched the
    # last-good-checkpoint fallback
    assert out["planned_flags"] == [True], out
    assert out["ckpt_fallback"] == 0.0, out
    assert out["reconfigure_s"][0] > 0
    np.testing.assert_allclose(out["losses"], out["ref"],
                               rtol=1e-6, atol=1e-7)


# ------------------------------------------------------------ chaos campaign


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_campaign_one_seed(tmp_path):
    """One seeded five-plane campaign (wire + partition + ckpt + grad +
    preempt): SIGKILL lands ``deadline_s`` after the SIGTERM, a
    committed rescue checkpoint exists, and the restarted job's loss
    trajectory matches the uncrashed reference. The nightly workflow
    runs 3 seeds and uploads the transcripts."""
    sys.path.insert(0, HERE)
    try:
        from chaos_campaign import run_campaign
        transcript = run_campaign(4242, str(tmp_path))
    finally:
        sys.path.remove(HERE)
    inv = transcript["invariants"]
    assert inv["always_resumable"] and inv["zero_corrupt_committed"]
    assert inv["loss_continuity_max_rel_err"] < 1e-4
    assert os.path.exists(transcript["path"])
