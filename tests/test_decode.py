"""Continuous-batching decode: KV-cache slot engine correctness.

The decode tentpole's contracts (``serving/decode.py``, docs/serving.md
"Continuous batching"):

- **exact parity**: greedy decode through the slot engine — prefill
  seeding the cache, cache-carried steps, eviction and readmission
  mid-flight — produces token-for-token what full-sequence recompute
  produces, for an AllReduce AND a PS-backed strategy;
- **mask identity**: a padded/dead slot's cache garbage never leaks
  into a live slot's attention (``ops.attention.cached_attention``
  masks rows past the cursor), so slot reuse needs no zeroing;
- **flash decode parity**: the pallas inner loop matches the reference
  cached attention to fp32 tolerance (2e-5 documented — the kernel's
  blocked online softmax reassociates the reduction);
- **zero recompiles after warmup**: one decode-step program serves
  every occupancy — admissions and evictions never grow a jit cache;
- **drain semantics**: in-flight sequences decode to completion,
  queued requests shed typed with a populated ``retry_after_s``.
"""
import time

import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.models import lm
from autodist_tpu.ops.attention import (cached_attention,
                                        flash_cached_attention,
                                        reference_attention)
from autodist_tpu.serving import ServingUnavailable
from autodist_tpu.serving.decode import (DecodeConfig, DecodeEngine,
                                         SlotScheduler)


# ------------------------------------------------------------- scheduler


class TestSlotScheduler:
    def test_continuous_admits_into_any_freed_slot(self):
        sched = SlotScheduler(4, "continuous")
        assert sched.admissible(queued=10) == 4
        sched.occupy(0, object())
        sched.occupy(2, object())
        assert sched.free_slots() == [1, 3]
        assert sched.admissible(queued=10) == 2
        assert sched.admissible(queued=1) == 1
        assert sched.occupancy() == 0.5

    def test_static_admits_only_when_all_slots_free(self):
        sched = SlotScheduler(4, "static")
        assert sched.admissible(queued=10) == 4
        sched.occupy(1, object())
        # the classic static-batching idle: three free slots, zero admits
        assert sched.admissible(queued=10) == 0
        sched.evict(1)
        assert sched.admissible(queued=2) == 2

    def test_evict_frees_for_readmission(self):
        sched = SlotScheduler(2)
        a, b = object(), object()
        sched.occupy(0, a)
        sched.occupy(1, b)
        assert sched.admissible(queued=5) == 0
        assert sched.evict(0) is a
        assert sched.get(0) is None
        assert sched.get(1) is b
        assert sched.live_slots() == [1]
        c = object()
        sched.occupy(0, c)
        assert sched.get(0) is c

    def test_config_validation(self):
        with pytest.raises(ValueError, match="admission"):
            DecodeConfig(admission="greedy")
        with pytest.raises(ValueError):
            DecodeConfig(slots=0)
        with pytest.raises(ValueError):
            DecodeConfig(max_new_tokens=0)


# ----------------------------------------------------- cached attention


def _rand_cache(rng, b, t, h, d):
    k = rng.randn(b, t, h, d).astype(np.float32)
    v = rng.randn(b, t, h, d).astype(np.float32)
    return k, v


def test_cached_attention_matches_reference_on_live_prefix():
    """Decode-shape attention == full attention restricted to the rows
    at/below the cursor, per example (exact — same fp32 softmax)."""
    rng = np.random.RandomState(0)
    b, t, h, d = 4, 32, 2, 8
    q = rng.randn(b, h, d).astype(np.float32)
    k, v = _rand_cache(rng, b, t, h, d)
    cursor = np.array([0, 5, 17, 31], np.int32)
    out = np.asarray(cached_attention(q, k, v, cursor))
    for i in range(b):
        c = int(cursor[i]) + 1
        ref = np.asarray(reference_attention(
            q[i:i + 1, None], k[i:i + 1, :c], v[i:i + 1, :c]))[:, 0]
        np.testing.assert_allclose(out[i:i + 1], ref, rtol=1e-6, atol=1e-6)


def test_cached_attention_masks_dead_rows():
    """Rows past the cursor are evicted sequences' garbage: scrambling
    them must not change a single output bit — the property that makes
    slot reuse safe without zeroing the cache."""
    rng = np.random.RandomState(1)
    b, t, h, d = 3, 16, 2, 4
    q = rng.randn(b, h, d).astype(np.float32)
    k, v = _rand_cache(rng, b, t, h, d)
    cursor = np.array([2, 7, 15], np.int32)
    base = np.asarray(cached_attention(q, k, v, cursor))
    k2, v2 = k.copy(), v.copy()
    for i in range(b):
        c = int(cursor[i]) + 1
        # evicted sequences leave real (finite) stale values behind —
        # scramble them hugely; the masked weights underflow to exact
        # zero so the products vanish bit-exactly
        k2[i, c:] = 1e6 * rng.randn(t - c, h, d)
        v2[i, c:] = -1e6 * rng.randn(t - c, h, d)
    out = np.asarray(cached_attention(q, k2, v2, cursor))
    np.testing.assert_array_equal(base, out)


def test_flash_cached_attention_parity():
    """The pallas flash inner loop vs the reference cached attention.
    Tolerance 2e-5 (documented): the blocked online softmax
    reassociates the fp32 reduction — observed error is ~1e-7, the
    bound leaves headroom for other backends' accumulation order."""
    rng = np.random.RandomState(2)
    b, t, h, d = 4, 64, 2, 16
    q = rng.randn(b, h, d).astype(np.float32)
    k, v = _rand_cache(rng, b, t, h, d)
    cursor = np.array([0, 5, 31, 63], np.int32)
    ref = np.asarray(cached_attention(q, k, v, cursor))
    out = np.asarray(flash_cached_attention(q, k, v, cursor))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


# ------------------------------------------- model-level decode parity


def _reference_tokens(apply_fn, params, prompt, max_new, eos_id=None):
    """Greedy generation by full-sequence recompute — the ground truth
    the cached decode path must match token for token."""
    ids = list(map(int, prompt))
    out = []
    for _ in range(max_new):
        logits = np.asarray(apply_fn(params, np.asarray([ids], np.int32)))
        nxt = int(np.argmax(logits[0, -1]))
        out.append(nxt)
        ids.append(nxt)
        if eos_id is not None and nxt == eos_id:
            break
    return out


def test_prefill_decode_step_parity_pure_model():
    """prefill + cached decode_step == full recompute, straight through
    ``model.apply`` (no engine, no mesh): localizes cursor/cache bugs
    away from the distribution machinery."""
    import jax
    import jax.numpy as jnp

    cfg = lm.LMConfig.tiny()
    _, params, _, apply_fn = lm.make_train_setup(cfg, seq_len=16,
                                                 batch_size=4)
    setup = lm.make_decode_setup(cfg)
    prompts = [[5, 9], [17, 3, 21, 8], [1]]
    plen = np.array([len(p) for p in prompts], np.int32)
    pad = max(len(p) for p in prompts)
    toks = np.zeros((len(prompts), pad), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    pre = setup.prefill_fn(params, {"tokens": jnp.asarray(toks),
                                    "length": jnp.asarray(plen)})
    dstate = setup.init_dstate(len(prompts))
    dstate["k"] = np.asarray(pre["k"])
    dstate["v"] = np.asarray(pre["v"])
    dstate["token"] = np.asarray(pre["next_token"])
    dstate["cursor"] = plen.copy()
    dstate["alive"] = np.ones(len(prompts), np.bool_)
    generated = [[int(t)] for t in dstate["token"]]
    step = jax.jit(setup.decode_fn)
    for _ in range(5):
        out = step(params, dstate)
        nxt = np.asarray(out["next_token"])
        dstate["k"], dstate["v"] = out["k"], out["v"]
        dstate["token"] = nxt
        dstate["cursor"] = dstate["cursor"] + 1
        for i in range(len(prompts)):
            generated[i].append(int(nxt[i]))
    for i, p in enumerate(prompts):
        ref = _reference_tokens(apply_fn, params, p, 6)
        assert generated[i] == ref, (
            "slot %d diverged: cached %s vs recompute %s"
            % (i, generated[i], ref))


# --------------------------------------------------- engine end to end


def _build_lm_runner(make_builder, train_steps=1):
    cfg = lm.LMConfig.tiny()
    loss_fn, params, batch, apply_fn = lm.make_train_setup(
        cfg, seq_len=16, batch_size=8)
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=make_builder())
    runner = ad.build(loss_fn, optax.adam(1e-3), params, batch)
    runner.init(params)
    for _ in range(train_steps):
        runner.run(batch)  # decode params that actually moved
    return runner, cfg, apply_fn


def test_engine_parity_eviction_readmission_allreduce():
    """The whole slot engine against full recompute: 12 overlapping
    requests through 8 slots (so sequences evict and new ones are
    admitted mid-flight), mixed prompt lengths and generation caps, an
    EOS stop, a done-at-admission request — every returned sequence
    must equal the reference token for token, with ZERO recompiles
    after warmup."""
    runner, cfg, apply_fn = _build_lm_runner(S.AllReduce)
    params = runner.gather_params()
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, (1 + i % 6,)).astype(np.int32)
               for i in range(12)]
    caps = [3 + (i * 3) % 8 for i in range(12)]
    caps[5] = 1  # satisfied by its prefill alone — never occupies a slot
    raw = [_reference_tokens(apply_fn, params, p, m)
           for p, m in zip(prompts, caps)]
    # an eos_id drawn from a reference stream: sequence 0 must stop
    # early with finished="eos"; any other sequence hitting it must too
    eos_id = raw[0][2]
    expected = []
    for toks in raw:
        cut = toks.index(eos_id) + 1 if eos_id in toks else len(toks)
        expected.append(toks[:cut])

    engine = DecodeEngine(runner, lm.make_decode_setup(cfg),
                          DecodeConfig(slots=8, max_new_tokens=8,
                                       prefill_len=8, eos_id=eos_id))
    try:
        engine.warmup()
        futures = [engine.submit(p, max_new_tokens=m)
                   for p, m in zip(prompts, caps)]
        results = [f.result(timeout=120) for f in futures]
        for i, (r, exp) in enumerate(zip(results, expected)):
            assert list(map(int, r["tokens"])) == exp, (
                "sequence %d diverged: engine %s vs recompute %s"
                % (i, list(map(int, r["tokens"])), exp))
            want = "eos" if exp[-1] == eos_id else "length"
            assert r["finished"] == want
            assert r["prompt_len"] == len(prompts[i])
        assert results[0]["finished"] == "eos"  # stopped at the EOS
        assert len(results[5]["tokens"]) == 1   # done at admission
        stats = engine.stats()
        assert stats["recompiles_after_warmup"] == 0, stats
        assert stats["completed"] == 12
        assert stats["evictions"] == 12
        assert stats["errors"] == 0
        assert stats["peak_occupancy"] > 0
        # the prefill program's shape is fixed: over-long prompts are
        # rejected synchronously, not silently truncated
        with pytest.raises(ValueError, match="prompt length"):
            engine.submit(np.zeros(9, np.int32))
    finally:
        engine.close()


def test_engine_parity_ps():
    """Same parity contract on a host-PS strategy: the decode step
    gathers PS-resident params through the shared prefill snapshot."""
    runner, cfg, apply_fn = _build_lm_runner(S.PS)
    params = runner.gather_params()
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, cfg.vocab_size, (2 + i,)).astype(np.int32)
               for i in range(4)]
    engine = DecodeEngine(runner, lm.make_decode_setup(cfg),
                          DecodeConfig(slots=8, max_new_tokens=6,
                                       prefill_len=8))
    try:
        engine.warmup()
        results = [engine.generate(p, timeout=120) for p in prompts]
        for p, r in zip(prompts, results):
            ref = _reference_tokens(apply_fn, params, p, 6)
            assert list(map(int, r["tokens"])) == ref
        assert engine.recompiles_after_warmup() == 0
    finally:
        engine.close()


def test_drain_completes_in_flight_and_sheds_queued():
    """Planned departure: sequences already in slots decode to
    completion and resolve normally; everything still queued sheds
    typed with the drain's Retry-After; later submits shed
    synchronously."""
    runner, cfg, _ = _build_lm_runner(S.PS, train_steps=0)
    rng = np.random.RandomState(5)
    engine = DecodeEngine(runner, lm.make_decode_setup(cfg),
                          DecodeConfig(slots=8, max_new_tokens=48,
                                       prefill_len=8))
    engine.warmup()
    first = [engine.submit(rng.randint(0, cfg.vocab_size, (4,))
                           .astype(np.int32)) for _ in range(8)]
    # wait until ALL EIGHT are in slots: the drain below must catch them
    # in flight, not still queued (48-token sequences stay live for far
    # longer than this poll)
    deadline = time.perf_counter() + 30
    while len(engine.scheduler.live_slots()) < 8:
        assert time.perf_counter() < deadline, "admission never happened"
        time.sleep(0.005)
    queued = [engine.submit(rng.randint(0, cfg.vocab_size, (4,))
                            .astype(np.int32)) for _ in range(8)]
    shed = engine.drain(retry_after_s=1.25)
    assert shed >= 1, "every queued request was somehow admitted"
    completed = 0
    for f in first:
        out = f.result(timeout=120)  # in-flight ran to completion
        assert len(out["tokens"]) == 48
        completed += 1
    assert completed == 8
    for f in queued:
        try:
            out = f.result(timeout=120)
            # admitted into a freed slot before the drain landed — must
            # then have completed fully
            assert len(out["tokens"]) == 48
        except ServingUnavailable as e:
            assert e.retry_after_s == 1.25
    with pytest.raises(ServingUnavailable) as ei:
        engine.submit(np.array([1], np.int32))
    assert ei.value.retry_after_s == 1.25
    assert engine.stats()["shed"] == shed
    engine.close()  # idempotent


# ------------------------------------------------------------ ADT442


def test_verify_decode_hbm_lint():
    from autodist_tpu.analysis import rules
    from autodist_tpu.analysis.memory import GIB

    diags = rules.verify_decode(16 * GIB, param_bytes=1 * GIB,
                                slots=64, max_len=2048, replicas=1,
                                budget_bytes=8 * GIB)
    assert [d.code for d in diags] == ["ADT442"]
    assert diags[0].severity.name == "WARNING"
    assert "64 slots x 2048 max_len" in diags[0].message
    assert "shrink slots" in diags[0].fixit
    # the slot dim shards over replicas: the same cache fits at 4
    assert rules.verify_decode(16 * GIB, param_bytes=1 * GIB,
                               replicas=4, budget_bytes=8 * GIB) == []
    # no budget configured -> nothing to project against, no noise
    assert rules.verify_decode(16 * GIB, param_bytes=1 * GIB) == []


# ------------------------------------------- batcher queue-age (sat.)


class _StubEngine:
    """The engine surface MicroBatcher touches, with a blockable
    dispatch — models a worker parked inside a long program call, the
    exact regime the queue-age floor exists for."""

    def __init__(self, release):
        from autodist_tpu.serving import ServingConfig
        self.config = ServingConfig(buckets=(1,), max_delay_ms=1.0,
                                    max_queue=2)
        self.max_batch = 1
        self.buckets = (1,)
        self.stats = {}
        self.entered = __import__("threading").Event()
        self._release = release

    def run_batch(self, feeds):
        self.entered.set()
        self._release.wait(timeout=30)
        return {"y": np.zeros((len(feeds), 1), np.float32)}, len(feeds)

    def fan_out(self, fetched, n):
        for i in range(n):
            yield {"y": fetched["y"][i]}

    def recompiles_after_warmup(self):
        return 0


def test_batcher_queue_age_floors_retry_after():
    """The head-of-line queue age is reported in ``stats()`` and FLOORS
    the computed Retry-After: a request that has already waited T
    seconds proves the tier clears slower than the drain-rate EWMA
    claims, so the hint must not promise anything sooner."""
    import threading

    from autodist_tpu.serving import MicroBatcher

    release = threading.Event()
    engine = _StubEngine(release)
    mb = MicroBatcher(engine)
    try:
        held = mb.submit({"x": np.zeros(1)})  # worker parks in dispatch
        assert engine.entered.wait(timeout=10)
        q1 = mb.submit({"x": np.zeros(1)})
        q2 = mb.submit({"x": np.zeros(1)})
        time.sleep(0.25)
        age = mb.stats()["oldest_queue_age_s"]
        assert age is not None and age >= 0.2
        # a huge measured drain rate would otherwise quote ~0s back-off
        mb._drain_rate = 1e6
        with pytest.raises(ServingUnavailable) as ei:
            mb.submit({"x": np.zeros(1)})
        assert ei.value.retry_after_s >= 0.2
    finally:
        release.set()
        for f in (held, q1, q2):
            try:
                f.result(timeout=10)
            except ServingUnavailable:
                pass  # shed at close is fine; hanging is not
        mb.close()
    assert mb.stats()["oldest_queue_age_s"] is None
