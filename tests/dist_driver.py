"""Driver script for the real multi-process distributed test.

Run by ``tests/test_distributed.py`` once per process (chief + worker), the
analog of the reference's two-machine ``tests/integration/test_dist.py``
where each node executes the same user script (reference
``docs/design/architecture.rst:43-47``). Both processes:

- join one jax.distributed job (4 virtual CPU devices each, 8 global),
- build/load the SAME strategy (chief builds under the preset
  ``ADT_STRATEGY_ID``; the worker polls for the serialized file),
- lower it independently and train in lockstep via global-mesh collectives,
- dump their observed losses + gathered params for the parent to compare.

Usage: dist_driver.py <resource_spec.yml> <out.json> <builder> <n_steps>
"""
import json
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import optax  # noqa: E402

import autodist_tpu as adt  # noqa: E402
from autodist_tpu import strategy as S  # noqa: E402

BUILDERS = {
    "AllReduce": lambda: S.AllReduce(chunk_size=2),
    "PartitionedAR": lambda: S.PartitionedAR(),
    "PartitionedPS": lambda: S.PartitionedPS(),
    "Parallax": lambda: S.Parallax(),
    # host-resident sync PS (mirror mode; with ADT_PS_MIRROR_CHECK_EVERY
    # set, the Runner cross-checks mirror digests over the coordsvc)
    "PS": lambda: S.PS(),
    # bounded staleness: exercises the Runner's cross-process pacing
    # client against a live coordination service
    "PSStale": lambda: S.PS(staleness=2),
    # int8 quantized ring: ppermute hops cross the process boundary
    "AllReduceInt8": lambda: S.AllReduce(compressor="Int8CompressorEF"),
    # fully-async PS: per-process local meshes, grads/values over the
    # coordination service's blob queues (no cross-process collectives)
    "PSAsync": lambda: S.PS(sync=False),
    # async with MULTI-OWNER serving: load balancing spreads variables
    # over both hosts, so each process runs an apply loop for its own
    # group and fetches the peer's
    "PSAsyncLB": lambda: S.PSLoadBalancing(sync=False),
    # async + partitioned: ONE variable's shards round-robin across both
    # hosts — per-SHARD ownership (each owner applies/publishes only its
    # shard ranges; pulls reassemble across owners)
    "PSAsyncPart": lambda: S.PartitionedPS(sync=False),
}


def make_case(seed=0):
    """Small 2-layer MLP; dims chosen divisible by 8 so partitioners bite."""
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    params = {
        "w1": jnp.asarray(rng.randn(8, 16) * 0.3, jnp.float32),
        "b1": jnp.zeros((16,), jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 4) * 0.3, jnp.float32),
    }

    def loss_fn(p, batch):
        h = jnp.tanh(batch["x"] @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] - batch["y"]) ** 2)

    batch = {"x": rng.randn(16, 8).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}
    return params, loss_fn, batch


def main():
    spec_yaml, out_path, builder_name, n_steps = (
        sys.argv[1], sys.argv[2], sys.argv[3], int(sys.argv[4]))
    # AutoDist first: joining the distributed runtime must precede any JAX
    # computation (make_case builds jnp params)
    ad = adt.AutoDist(resource_spec_file=spec_yaml,
                      strategy_builder=BUILDERS[builder_name]())
    params, loss_fn, batch = make_case()
    import os
    opt = (optax.adam(1e-2) if os.environ.get("ADT_TEST_OPTIMIZER") == "adam"
           else optax.sgd(0.1))
    step = ad.function(loss_fn, optimizer=opt, params=params)
    losses = [float(step(batch)["loss"]) for _ in range(n_steps)]
    save_dir = os.environ.get("ADT_TEST_SAVE_DIR")
    if save_dir:
        # checkpoint after training (async-PS completeness test: the saved
        # opt state must include peer-owned shards' moments, which only
        # exist locally as frozen init — they come off the wire). EVERY
        # process calls save(): the gathers are collectives under sync
        # builders; the default chief_only gates the file writes
        from autodist_tpu.checkpoint.saver import Saver
        Saver(directory=save_dir).save(step.get_runner())
    gathered = step.get_runner().gather_params()
    result = {
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "losses": losses,
        "params": {k: np.asarray(v).tolist() for k, v in gathered.items()},
    }
    with open(out_path, "w") as f:
        json.dump(result, f)
    print("dist_driver done:", builder_name, losses[-1], flush=True)


if __name__ == "__main__":
    main()
