"""Ring / Ulysses attention correctness on an 8-device seq-sharded mesh.

Exactness tests: sequence-parallel attention must reproduce the
single-device reference bit-for-bit-ish (fp32 tolerance), full and causal.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.ops.attention import (reference_attention, ring_attention,
                                        ulysses_attention)

B, S, H, D = 2, 64, 8, 16


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(B, S, H, D).astype(np.float32) * 0.3)
            for _ in range(3)]


def _mesh():
    return Mesh(np.array(jax.devices()[:8]), ("seq",))


def _run_sharded(fn, q, k, v):
    mesh = _mesh()
    spec = P(None, "seq")
    sharded = jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))
    return sharded(q, k, v)


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_ring_attention_exact(causal):
    q, k, v = _qkv()
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))[None, None] if causal else None
    expected = reference_attention(q, k, v, mask)
    got = _run_sharded(
        lambda a, b, c: ring_attention(a, b, c, "seq", causal=causal), q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_ulysses_attention_exact(causal):
    q, k, v = _qkv(1)
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))[None, None] if causal else None
    expected = reference_attention(q, k, v, mask)
    got = _run_sharded(
        lambda a, b, c: ulysses_attention(a, b, c, "seq", causal=causal),
        q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_sp_lm_one_step_matches_dp():
    """Full stack: tiny LM trained one step on a 2(data)x4(seq) mesh via
    SequenceParallelAR must match the equivalent non-SP model's step."""
    import optax
    import autodist_tpu
    from autodist_tpu import strategy as St
    from autodist_tpu.models import lm

    cfg = lm.LMConfig.tiny()
    seq_len, batch = 32, 8
    sp_loss, params, ex_batch, _ = lm.make_sp_train_setup(
        cfg, seq_len=seq_len, batch_size=batch, attention="ring")

    # single-device reference: same params, causal-mask model, same objective
    ref_model = lm.TransformerLM(cfg, attn_fn=None, seq_parallel=True)

    def ref_loss(p, b):
        tokens = b["tokens"]
        logits = ref_model.apply(p, tokens)
        targets = jnp.roll(tokens, -1, axis=1)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        w = (jnp.arange(seq_len) < seq_len - 1).astype(nll.dtype)[None, :]
        w = jnp.broadcast_to(w, nll.shape)
        return jnp.sum(nll * w) / jnp.sum(w)

    opt = optax.sgd(0.1)
    g = jax.grad(ref_loss)(params, ex_batch)
    updates, _ = opt.update(g, opt.init(params), params)
    import optax as _o
    expected = _o.apply_updates(params, updates)

    ad = autodist_tpu.AutoDist(
        strategy_builder=St.SequenceParallelAR(seq_shards=4))
    runner = ad.build(sp_loss, opt, params, ex_batch)
    runner.init(params)
    m = runner.run(ex_batch)
    assert np.isfinite(m["loss"])
    got = runner.gather_params()
    flat_e, _ = jax.tree_util.tree_flatten_with_path(expected)
    flat_g, _ = jax.tree_util.tree_flatten_with_path(got)
    for (path, e), (_, gv) in zip(flat_e, flat_g):
        np.testing.assert_allclose(np.asarray(gv), np.asarray(e), rtol=2e-4,
                                   atol=2e-5, err_msg=str(path))
    autodist_tpu.reset()


def test_ring_attention_grads_match():
    """Differentiability: grads through ring attention == reference grads."""
    q, k, v = _qkv(2)

    def ref_loss(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    expected = jax.grad(ref_loss)(q, k, v)

    mesh = _mesh()
    spec = P(None, "seq")

    def ring_loss_local(q, k, v):
        # local term of the global sum-loss; cross-device grad contributions
        # to k/v flow back through the ppermute transpose
        out = ring_attention(q, k, v, "seq")
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def sharded_grad(q, k, v):
        g = jax.grad(ring_loss_local)(q, k, v)
        return g

    f = jax.jit(jax.shard_map(sharded_grad, mesh=mesh,
                              in_specs=(spec, spec, spec), out_specs=spec,
                              check_vma=False))
    got = f(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-3, atol=2e-4)
