"""Ring / Ulysses attention correctness on an 8-device seq-sharded mesh.

Exactness tests: sequence-parallel attention must reproduce the
single-device reference bit-for-bit-ish (fp32 tolerance), full and causal.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.ops.attention import (reference_attention, ring_attention,
                                        ulysses_attention)

B, S, H, D = 2, 64, 8, 16


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(B, S, H, D).astype(np.float32) * 0.3)
            for _ in range(3)]


def _mesh():
    return Mesh(np.array(jax.devices()[:8]), ("seq",))


def _run_sharded(fn, q, k, v):
    mesh = _mesh()
    spec = P(None, "seq")
    sharded = jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))
    return sharded(q, k, v)


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_ring_attention_exact(causal):
    q, k, v = _qkv()
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))[None, None] if causal else None
    expected = reference_attention(q, k, v, mask)
    got = _run_sharded(
        lambda a, b, c: ring_attention(a, b, c, "seq", causal=causal), q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_ulysses_attention_exact(causal):
    q, k, v = _qkv(1)
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))[None, None] if causal else None
    expected = reference_attention(q, k, v, mask)
    got = _run_sharded(
        lambda a, b, c: ulysses_attention(a, b, c, "seq", causal=causal),
        q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_sp_lm_one_step_matches_dp():
    """Full stack: tiny LM trained one step on a 2(data)x4(seq) mesh via
    SequenceParallelAR must match the equivalent non-SP model's step."""
    import optax
    import autodist_tpu
    from autodist_tpu import strategy as St
    from autodist_tpu.models import lm

    cfg = lm.LMConfig.tiny()
    seq_len, batch = 32, 8
    sp_loss, params, ex_batch, _ = lm.make_sp_train_setup(
        cfg, seq_len=seq_len, batch_size=batch, attention="ring")

    # single-device reference: same params, causal-mask model, same objective
    ref_model = lm.TransformerLM(cfg, attn_fn=None, seq_parallel=True)

    def ref_loss(p, b):
        tokens = b["tokens"]
        logits = ref_model.apply(p, tokens)
        targets = jnp.roll(tokens, -1, axis=1)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        w = (jnp.arange(seq_len) < seq_len - 1).astype(nll.dtype)[None, :]
        w = jnp.broadcast_to(w, nll.shape)
        return jnp.sum(nll * w) / jnp.sum(w)

    opt = optax.sgd(0.1)
    g = jax.grad(ref_loss)(params, ex_batch)
    updates, _ = opt.update(g, opt.init(params), params)
    import optax as _o
    expected = _o.apply_updates(params, updates)

    ad = autodist_tpu.AutoDist(
        strategy_builder=St.SequenceParallelAR(seq_shards=4))
    runner = ad.build(sp_loss, opt, params, ex_batch)
    runner.init(params)
    m = runner.run(ex_batch)
    assert np.isfinite(m["loss"])
    got = runner.gather_params()
    flat_e, _ = jax.tree_util.tree_flatten_with_path(expected)
    flat_g, _ = jax.tree_util.tree_flatten_with_path(got)
    for (path, e), (_, gv) in zip(flat_e, flat_g):
        np.testing.assert_allclose(np.asarray(gv), np.asarray(e), rtol=2e-4,
                                   atol=2e-5, err_msg=str(path))
    autodist_tpu.reset()


def test_ring_attention_grads_match():
    """Differentiability: grads through ring attention == reference grads."""
    q, k, v = _qkv(2)

    def ref_loss(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    expected = jax.grad(ref_loss)(q, k, v)

    mesh = _mesh()
    spec = P(None, "seq")

    def ring_loss_local(q, k, v):
        # local term of the global sum-loss; cross-device grad contributions
        # to k/v flow back through the ppermute transpose
        out = ring_attention(q, k, v, "seq")
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def sharded_grad(q, k, v):
        g = jax.grad(ring_loss_local)(q, k, v)
        return g

    f = jax.jit(jax.shard_map(sharded_grad, mesh=mesh,
                              in_specs=(spec, spec, spec), out_specs=spec,
                              check_vma=False))
    got = f(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-3, atol=2e-4)


def test_seq_keys_exempt_non_sequence_leaves():
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy
    adt.reset()
    """SequenceParallelAR(seq_keys=[...]): only the declared token leaves
    shard dim 1 over the seq axis — a rank-2 one-hot-style leaf whose
    dim 1 is CLASSES (and not divisible by the shard count) is replicated
    per batch row instead of being sliced or spuriously rejected."""
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(8, 5).astype(np.float32))}
    S_SEQ, C = 16, 5  # C=5 NOT divisible by 2 seq shards

    def loss_fn(p, batch):
        # tokens [B, S] drive a trivial per-position embedding-free model;
        # weights [B, C] (dim 1 = classes) scale the loss per example
        feat = batch["tokens"][..., None].astype(jnp.float32) @ \
            jnp.ones((1, 8), jnp.float32)
        pred = feat @ p["w"]                       # [B, S, C]
        w = jnp.mean(batch["class_weights"], axis=1)  # [B]
        return jnp.mean(jnp.mean(pred ** 2, axis=(1, 2)) * w)

    batch = {"tokens": rng.randint(0, 9, (8, S_SEQ)).astype(np.int32),
             "class_weights": np.ones((8, C), np.float32)}

    ad = adt.AutoDist(strategy_builder=strategy.SequenceParallelAR(
        seq_shards=2, attention="ring", seq_keys=["tokens"]))
    runner = ad.build(loss_fn, optax.sgd(0.05), params, batch)
    runner.init(params)
    m = runner.run(batch)
    assert np.isfinite(m["loss"])
    placed = runner.remapper.remap_feed(batch)
    from jax.sharding import PartitionSpec as P
    assert placed["tokens"].sharding.spec == P(("data",), "seq")
    assert placed["class_weights"].sharding.spec == P(("data",))

    # without the declaration, the same batch is spuriously rejected
    adt.reset()
    ad2 = adt.AutoDist(strategy_builder=strategy.SequenceParallelAR(
        seq_shards=2, attention="ring"))
    runner2 = ad2.build(loss_fn, optax.sgd(0.05), params,
                        {"tokens": batch["tokens"],
                         "class_weights": batch["class_weights"]})
    runner2.init(params)
    with pytest.raises(ValueError, match="not divisible by the 2"):
        runner2.run(batch)
    adt.reset()


def test_ring_attention_skips_dead_final_rotation():
    """The ring issues N-1 K/V rotations, not N: the final block updates
    without the trailing ppermute pair nothing reads (1/N of the op's
    communication on an N-way ring)."""
    from autodist_tpu.kernel.common import op_info
    q, k, v = _qkv()
    mesh = _mesh()
    jaxpr = jax.make_jaxpr(jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, "seq"),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3, out_specs=P(None, "seq"),
        check_vma=False))(q, k, v)
    perms = [0]

    def walk(jp, mult=1):
        for eqn in jp.eqns:
            if eqn.primitive.name == "ppermute":
                perms[0] += mult
            m = mult
            if eqn.primitive.name in ("while", "scan"):
                # the fori_loop runs axis_size-1 iterations
                m = mult * 7
            for sub in op_info.sub_jaxprs(eqn):
                walk(sub, m)
    walk(jaxpr.jaxpr)
    assert perms[0] == 2 * 7, perms  # K+V per rotation, 7 rotations on 8


def test_ring_attn_fn_refuses_dense_mask():
    from autodist_tpu.ops.attention import make_attn_fn
    q, k, v = _qkv()
    attn = make_attn_fn("ring")
    with pytest.raises(ValueError, match="cannot apply a dense mask"):
        attn(q, k, v, jnp.ones((1, 1, 8, 8), jnp.bool_))


def test_ulysses_attn_fn_honors_mask():
    """The (q, k, v, mask) slot forwards the padding mask to ulysses —
    silently dropping it would let every token attend PAD positions."""
    from autodist_tpu.ops.attention import make_attn_fn
    q, k, v = _qkv()
    valid = np.ones((B, S), np.int32)
    valid[:, S - 16:] = 0
    mask = jnp.asarray(valid, jnp.bool_)[:, None, None, :]
    ref = reference_attention(q, k, v, mask)
    mesh = _mesh()
    out = jax.jit(jax.shard_map(
        make_attn_fn("ulysses"),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3 + (P(),),
        out_specs=P(None, "seq"), check_vma=False))(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out[:, :S - 16]),
                               np.asarray(ref[:, :S - 16]),
                               atol=2e-5, rtol=2e-5)
