"""Runtime telemetry: recorder semantics, overhead guard, Perfetto
export schema, cross-process merge/scrape, drift reports, and the
instrumented-path acceptance (a fused fit traces >= 2 subsystems and the
registry exposes >= 10 counters)."""
import json
import statistics
import time

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.telemetry import drift, export
from autodist_tpu.telemetry import spans as tel


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    """configure() overrides are sticky by design — drop them after each
    test so the rest of the suite stays env-driven (off)."""
    yield
    tel.configure(None)
    tel.reset()


# ---------------------------------------------------------------- recorder


def test_disabled_mode_overhead_guard():
    """ADT_TRACE=0 span enter/exit must stay near-free (< 1µs median is
    the design target; asserted loosely for shared CI hosts)."""
    tel.configure("0")
    assert not tel.tracing_enabled()
    reps, batch = 50, 400
    per_op = []
    for _ in range(reps):
        t0 = time.perf_counter_ns()
        for _ in range(batch):
            with tel.span("hot.noop", "test"):
                pass
        per_op.append((time.perf_counter_ns() - t0) / batch)
    median_ns = statistics.median(per_op)
    assert median_ns < 5000, "disabled span overhead %dns/op" % median_ns
    # and nothing was recorded
    assert tel.get_recorder().events() == []


def test_nested_spans_record_parent_ids_and_durations():
    tel.configure("1")
    rec = tel.get_recorder()
    with tel.span("outer", "test", k=2) as outer:
        assert tel.current_span_id() == outer.id
        with tel.span("inner", "test"):
            time.sleep(0.001)
    assert tel.current_span_id() == 0
    events = {e.name: e for e in rec.events()}
    assert set(events) == {"outer", "inner"}
    assert events["inner"].parent_id == events["outer"].span_id
    assert events["outer"].parent_id == 0
    # inner completed first but nests inside outer's interval
    assert events["outer"].dur_ns >= events["inner"].dur_ns > 0
    assert events["outer"].args == {"k": 2}


def test_counters_and_gauges_work_with_tracing_disabled():
    tel.configure("0")
    tel.counter_add("runner.steps", 3)
    tel.counter_add("custom.thing", 2.5)
    tel.gauge_set("prefetch.queue_depth", 4)
    c = tel.counters()
    assert c["runner.steps"] == 3.0
    assert c["custom.thing"] == 2.5
    assert tel.get_recorder().gauges()["prefetch.queue_depth"] == 4.0


def test_default_registry_exposes_at_least_ten_counters():
    tel.configure("0")
    text = export.metrics_text()
    counter_lines = [ln for ln in text.splitlines()
                     if ln.startswith("# TYPE") and ln.endswith("counter")]
    assert len(counter_lines) >= 10
    assert "adt_runner_steps_total" in text
    assert "adt_ps_bytes_pulled_total" in text


def test_sampled_mode_records_one_in_n():
    tel.configure("sampled", capacity=4096, sample=4)
    for _ in range(100):
        with tel.span("s", "test"):
            pass
    n = len(tel.get_recorder().events())
    assert n == 25, "sampled 1/4 of 100 spans -> 25, got %d" % n
    # instants are rare diagnostic markers: NEVER sampled out
    for _ in range(5):
        tel.instant("coord.breaker_open", "coord")
    instants = [e for e in tel.get_recorder().events()
                if e.name == "coord.breaker_open"]
    assert len(instants) == 5


def test_exported_timestamps_are_wall_clock_based():
    """perf_counter origins are arbitrary per process; exports re-base
    onto the wall clock so scraped traces from different hosts land on
    one comparable timeline."""
    rec = tel.TraceRecorder(capacity=8, sample=1, pid=1, host="h")
    with rec.span("s", "test"):
        pass
    trace = export.chrome_trace(rec)
    ts_us = next(e["ts"] for e in trace["traceEvents"] if e["ph"] == "X")
    assert abs(ts_us - time.time_ns() / 1e3) < 300e6  # within 5 minutes


def _count_spans(n=8):
    before = len(tel.get_recorder().events())
    for _ in range(n):
        with tel.span("s", "test"):
            pass
    return len(tel.get_recorder().events()) - before


def test_reset_resyncs_stride_and_mode_from_one_source(monkeypatch):
    """reset() re-derives BOTH the mode and the recorder's sampling
    stride from one source — a stale stride would silently drop spans
    while tracing_enabled() claims full-record mode."""
    tel.configure(None)  # env-driven
    monkeypatch.setenv("ADT_TRACE", "1")
    tel.reset()  # what autodist_tpu.reset() calls
    assert tel.tracing_enabled()
    assert _count_spans(8) == 8
    monkeypatch.setenv("ADT_TRACE", "sampled")
    monkeypatch.setenv("ADT_TRACE_SAMPLE", "4")
    tel.reset()
    assert _count_spans(8) == 2  # stride followed the mode


def test_configure_override_is_sticky_across_reset(monkeypatch):
    """An explicit configure() choice must survive autodist_tpu.reset()
    (run between every programmatic build) — without stickiness a traced
    session silently reverts to the env default and records nothing."""
    monkeypatch.delenv("ADT_TRACE", raising=False)
    tel.configure("1")
    tel.reset()
    assert tel.tracing_enabled()
    assert _count_spans(4) == 4
    tel.configure(None)  # back to env-driven: default off
    tel.reset()
    assert not tel.tracing_enabled()
    assert _count_spans(4) == 0


def test_ring_buffer_bounds_and_counts_drops():
    rec = tel.TraceRecorder(capacity=8, sample=1, pid=1, host="h")
    for i in range(20):
        with rec.span("s%d" % i, "test"):
            pass
    assert len(rec.events()) == 8
    assert rec.dropped_events == 12
    assert [e.name for e in rec.events()] == ["s%d" % i for i in range(12, 20)]


# ------------------------------------------------------------------ export


def _record_some(rec):
    with rec.span("a", "catA", n=1):
        with rec.span("b", "catB"):
            pass
    rec.counter_add("runner.steps", 2)
    rec.gauge_set("depth", 1)


def test_chrome_trace_schema_and_validation():
    rec = tel.TraceRecorder(capacity=64, sample=1, pid=101, host="hostx")
    _record_some(rec)
    trace = export.chrome_trace(rec)
    assert export.validate_chrome_trace(trace) == []
    json.dumps(trace)  # serializable end to end
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"a", "b"}
    for e in xs:
        assert e["pid"] == 101
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert "span_id" in e["args"]
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert any(e["name"] == "process_name"
               and e["args"]["name"] == "hostx:101" for e in meta)
    cs = {e["name"]: e["args"]["value"] for e in trace["traceEvents"]
          if e["ph"] == "C"}
    assert cs["runner.steps"] == 2.0 and cs["depth"] == 1.0


def test_validate_rejects_malformed_traces():
    assert export.validate_chrome_trace({}) == ["missing traceEvents list"]
    bad = {"traceEvents": [{"ph": "X", "name": "x", "pid": 1, "tid": 0,
                            "ts": "soon", "dur": 1.0}]}
    assert any("non-numeric ts" in e
               for e in export.validate_chrome_trace(bad))
    assert any("no span" in e
               for e in export.validate_chrome_trace(
                   {"traceEvents": [{"ph": "M", "name": "m", "pid": 1}]}))
    # counters-only exports (ADT_TRACE=0 registry mode) are VALID
    rec = tel.TraceRecorder(capacity=4, sample=1, pid=3, host="h")
    rec.counter_add("ps.pulls", 1)
    assert export.validate_chrome_trace(export.chrome_trace(rec)) == []
    # the error list truncates even when every event is malformed
    garbage = {"traceEvents": [{"bogus": i} for i in range(1000)]}
    errs = export.validate_chrome_trace(garbage)
    assert len(errs) < 30 and any(e.startswith("...") for e in errs)


def test_merge_keeps_processes_on_distinct_tracks():
    """Two in-proc recorders standing in for two worker processes: the
    merged timeline must keep one track per process, even on pid
    collision (two single-process hosts with the same OS pid)."""
    r1 = tel.TraceRecorder(capacity=64, sample=1, pid=500, host="host-a")
    r2 = tel.TraceRecorder(capacity=64, sample=1, pid=500, host="host-b")
    _record_some(r1)
    _record_some(r2)
    merged = export.merge_traces([export.chrome_trace(r1),
                                  export.chrome_trace(r2)])
    pids = {e["pid"] for e in merged["traceEvents"] if e["ph"] == "X"}
    assert len(pids) == 2, "pid collision collapsed the tracks"
    assert export.validate_chrome_trace(merged) == []
    assert set(merged["otherData"]["processes"]) == {"host-a:500",
                                                     "host-b:500"}


class _FakeCoordClient:
    """In-proc stand-in for CoordinationClient's blob API — the scrape
    plumbing without a socket."""

    def __init__(self):
        self.blobs = {}

    def bput(self, key, version, payload, token=None):
        self.blobs[key] = (version, payload)

    def bget(self, key):
        return self.blobs.get(key)


def test_publish_and_scrape_cluster_merges_workers():
    client = _FakeCoordClient()
    for worker, pid in (("w0", 700), ("w1", 701)):
        rec = tel.TraceRecorder(capacity=64, sample=1, pid=pid,
                                host="node-%s" % worker)
        _record_some(rec)
        rec.counter_add("ps.pulls", 1 if worker == "w0" else 7)
        export.publish_telemetry(client, worker, rec)
    scraped = export.scrape_cluster(client, ["w0", "w1", "w-dead"])
    assert scraped["workers"] == ["w0", "w1"]
    assert scraped["missing"] == ["w-dead"]
    assert export.validate_chrome_trace(scraped["trace"]) == []
    pids = {e["pid"] for e in scraped["trace"]["traceEvents"]
            if e["ph"] == "X"}
    assert pids == {700, 701}
    text = scraped["metrics_text"]
    assert 'adt_ps_pulls_total{worker="w0"} 1' in text
    assert 'adt_ps_pulls_total{worker="w1"} 7' in text


@pytest.mark.slow
def test_scrape_over_real_coordination_service():
    """End-to-end scrape over the REAL coordination-service wire: two
    'workers' (in-proc recorders, distinct process identities) publish
    versioned telemetry blobs, the coordinator scrapes and merges —
    the deployed-cluster path, minus the extra OS processes."""
    from autodist_tpu.runtime.coordination import (CoordinationClient,
                                                   CoordinationServer)
    port = 15917
    srv = CoordinationServer(port=port)
    srv.start()
    try:
        for worker, pid in (("w0", 910), ("w1", 911)):
            rec = tel.TraceRecorder(capacity=64, sample=1, pid=pid,
                                    host="node-%s" % worker)
            _record_some(rec)
            client = CoordinationClient("127.0.0.1", port)
            export.publish_telemetry(client, worker, rec)
            client.close()
        coord = CoordinationClient("127.0.0.1", port)
        scraped = export.scrape_cluster(coord, ["w0", "w1"])
        coord.close()
        assert scraped["workers"] == ["w0", "w1"]
        assert scraped["missing"] == []
        assert export.validate_chrome_trace(scraped["trace"]) == []
        assert {e["pid"] for e in scraped["trace"]["traceEvents"]
                if e["ph"] == "X"} == {910, 911}
        assert 'adt_runner_steps_total{worker="w0"} 2' \
            in scraped["metrics_text"]
    finally:
        srv.stop()


def test_metrics_text_prometheus_shape():
    rec = tel.TraceRecorder(capacity=4, sample=1, pid=1, host="h")
    rec.counter_add("a.b-c", 2)
    rec.gauge_set("g", 1.5)
    text = export.metrics_text(rec, labels={"worker": "w9"})
    assert '# TYPE adt_a_b_c_total counter' in text
    assert 'adt_a_b_c_total{worker="w9"} 2' in text
    assert 'adt_g{worker="w9"} 1.5' in text


def test_metrics_text_emits_help_lines():
    """Strict scrapers want # HELP before # TYPE for every metric —
    counters, gauges AND histograms."""
    rec = tel.TraceRecorder(capacity=4, sample=1, pid=1, host="h")
    rec.gauge_set("prefetch.queue_depth", 2)
    rec.hist_observe("serve.latency_ms", 1.0)
    lines = export.metrics_text(rec).splitlines()
    assert "# HELP adt_runner_steps_total" \
        in {ln.rsplit(" autodist_tpu", 1)[0] for ln in lines
            if ln.startswith("# HELP")}
    # every TYPE line is immediately preceded by its HELP line
    for i, ln in enumerate(lines):
        if ln.startswith("# TYPE "):
            mname = ln.split()[2]
            assert lines[i - 1].startswith("# HELP %s " % mname), ln
    assert any(ln.startswith("# HELP adt_serve_latency_ms ")
               for ln in lines)
    assert any(ln.startswith("# HELP adt_prefetch_queue_depth ")
               for ln in lines)


def test_metrics_text_escapes_label_values():
    """Label values with backslash/quote/newline must escape per the
    exposition format or a strict scraper rejects the whole page."""
    rec = tel.TraceRecorder(capacity=4, sample=1, pid=1, host="h")
    rec.counter_add("a.b", 1)
    text = export.metrics_text(rec, labels={"worker": 'w"1\\x\nend'})
    assert 'worker="w\\"1\\\\x\\nend"' in text
    assert "\nadt_a_b_total{" in text  # the raw newline never leaked
    sample = next(ln for ln in text.splitlines()
                  if ln.startswith("adt_a_b_total"))
    # one line, and every quote inside the value is escaped: exactly the
    # two delimiter quotes remain unescaped
    import re
    assert len(re.findall(r'(?<!\\)"', sample)) == 2


# --------------------------------------------------- instrumented runtime


def _build_runner(builder, params, loss_fn, batch, opt=None):
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=builder)
    runner = ad.build(loss_fn, opt or optax.adam(0.1), params, batch)
    runner.init(params)
    return runner


def _problem(n_batches=8, seed=0):
    rng = np.random.RandomState(seed)
    params = {"w": jnp.asarray(rng.randn(4, 2).astype(np.float32)),
              "b": jnp.zeros((2,), jnp.float32)}

    def loss_fn(p, batch):
        return jnp.mean((batch["x"] @ p["w"] + p["b"] - batch["y"]) ** 2)

    batches = [{"x": rng.randn(16, 4).astype(np.float32),
                "y": rng.randn(16, 2).astype(np.float32)}
               for _ in range(n_batches)]
    return params, loss_fn, batches


def test_fused_fit_traces_multiple_subsystems(tmp_path):
    """The acceptance run: fit(fuse_steps=4) with tracing on produces a
    Perfetto-loadable trace with dispatch + PS + checkpoint spans and a
    registry exposing >= 10 counters."""
    tel.configure("1")
    params, loss_fn, batches = _problem()
    # the build helper runs autodist_tpu.reset(); the configure()
    # override is sticky, so tracing stays armed through it
    runner = _build_runner(S.PS(), params, loss_fn, batches[0])
    assert tel.tracing_enabled()
    from autodist_tpu.checkpoint.saver import Saver
    saver = Saver(directory=str(tmp_path), async_save=False)
    hist = runner.fit(list(batches), fuse_steps=4, metrics_every=2,
                      save_every=4, saver=saver)
    assert len(hist) == len(batches)

    rec = tel.get_recorder()
    cats = {e.cat for e in rec.events()}
    assert {"runner", "dstep", "ps", "ckpt"} <= cats, cats
    names = {e.name for e in rec.events()}
    assert {"runner.dispatch", "dstep.dispatch", "dstep.pull_ps",
            "ps.pull", "ckpt.write"} <= names, names

    # exported trace is Perfetto-loadable
    path = str(tmp_path / "trace.json")
    export.write_trace(path)
    trace = export.load_trace(path)
    assert export.validate_chrome_trace(trace) == []

    # the registry exposes >= 10 counters, several of them live
    counters = rec.counters()
    assert len(counters) >= 10
    assert counters["runner.steps"] == len(batches)
    assert counters["dstep.dispatches"] >= 2
    assert counters["ps.pulls"] >= 1
    assert counters["ckpt.saves"] >= 1

    # step_stats merges the registry with a stable shape
    stats = runner.step_stats()
    assert stats["telemetry"]["dispatches"] == counters["dstep.dispatches"]
    assert stats["telemetry"]["d2h_bytes"] > 0
    autodist_tpu.reset()


def test_prefetcher_counts_and_logs_dropped_tail():
    tel.configure("0")
    from autodist_tpu.data.prefetch import DevicePrefetcher
    batches = [{"x": np.zeros((6, 2), np.float32)} for _ in range(7)]
    pf = DevicePrefetcher(iter(batches), lambda b: b, stack=3)
    consumed = list(pf)
    assert len(consumed) == 2  # 7 = 2 full stacks + a dropped tail of 1
    assert pf.dropped_batches == 1
    assert pf.dropped_examples == 6
    c = tel.counters()
    assert c["prefetch.dropped_batches"] == 1
    assert c["prefetch.dropped_examples"] == 6
    assert c["prefetch.batches"] == 2


# ------------------------------------------------------------------- drift


def _local_spec():
    return ResourceSpec.from_dict({
        "nodes": [{"address": "127.0.0.1", "cpus": 8, "chief": True,
                   "network_bandwidth": 25}],
        "slice": {"ici_bandwidth": 100}})


@pytest.mark.parametrize("builder", [S.AllReduce, S.PS],
                         ids=["AllReduce", "PS"])
def test_drift_report_feeds_calibration(builder, tmp_path):
    """Measured dispatch spans + static collective profile join against
    the cost model into a drift report calibration.fit can consume."""
    params, loss_fn, batches = _problem()
    runner = _build_runner(builder(), params, loss_fn, batches[0])
    tel.configure("1")
    for b in batches[:4]:
        runner.run(b)
    report = drift.report_for_runner(runner, resource_spec=_local_spec(),
                                     batch=batches[0])
    assert report.num_steps == 4
    assert report.measured_step_s > 0
    assert report.predicted_step_s > 0
    terms = {t.term: t for t in report.terms}
    assert terms["step"].measured_s == report.measured_step_s
    assert terms["step"].ratio > 0
    # per-collective measured-vs-predicted rows exist when the program
    # has collectives (the 8-way data-parallel gradient reduce)
    kinds = {c.kind for c in report.collectives}
    if builder is S.AllReduce:
        assert "reduce" in kinds
        row = next(c for c in report.collectives if c.kind == "reduce")
        assert row.measured_wire_bytes > 0
        assert row.ratio > 0

    # serialization + CLI table
    d = report.to_dict()
    json.dumps(d)
    path = report.save(str(tmp_path / "drift.json"))
    assert drift.load_report(path)["strategy_id"] == report.strategy_id
    table = report.format_table()
    assert "drift report" in table and "collective" in table

    # the calibration feed: fitted scales are finite and positive
    cal = drift.fit_calibration([report])
    for scale in (cal.compute_scale, cal.ar_scale, cal.ps_scale,
                  cal.latency_scale):
        assert np.isfinite(scale) and scale > 0
    autodist_tpu.reset()


def test_fit_calibration_requires_measurements():
    report = drift.DriftReport(
        strategy_id="s", num_steps=0, predicted_step_s=1.0,
        measured_step_s=None, terms=[], collectives=[],
        breakdown={"compute_s": 1.0, "allreduce_s": 0.0, "ps_s": 0.0,
                   "latency_s": 0.0, "mp_s": 0.0},
        counters={})
    with pytest.raises(ValueError, match="measured"):
        drift.fit_calibration([report])


# ------------------------------------------------------------ log format


def test_json_log_format_carries_span_ids():
    import logging as std_logging
    from autodist_tpu.utils import logging as adt_logging
    fmt = adt_logging.make_formatter("json")
    record = std_logging.LogRecord("autodist_tpu", std_logging.WARNING,
                                   "file.py", 12, "retry %d", (3,), None)
    line = json.loads(fmt.format(record))
    assert line["msg"] == "retry 3"
    assert line["level"] == "WARNING"
    assert "span_id" not in line  # no live span
    tel.configure("1")
    with tel.span("coord.backoff", "coord"):
        line = json.loads(fmt.format(record))
    assert line["span_id"] > 0
    # text mode still renders the classic format
    text = adt_logging.make_formatter("text").format(record)
    assert "retry 3" in text and not text.startswith("{")


def test_set_format_switches_live_handlers(monkeypatch):
    from autodist_tpu.utils import logging as adt_logging
    logger = adt_logging.get_logger()
    adt_logging.set_format("json")
    try:
        assert all(isinstance(h.formatter, adt_logging._JsonFormatter)
                   for h in logger.handlers)
    finally:
        adt_logging.set_format("text")


# --------------------------------------------------------------------- CLI


def test_cli_inspect_validate_merge_diff_drift(tmp_path, capsys):
    from autodist_tpu.telemetry import cli
    r1 = tel.TraceRecorder(capacity=64, sample=1, pid=11, host="a")
    r2 = tel.TraceRecorder(capacity=64, sample=1, pid=12, host="b")
    _record_some(r1)
    _record_some(r2)
    p1 = str(tmp_path / "t1.json")
    p2 = str(tmp_path / "t2.json")
    export.write_trace(p1, r1)
    export.write_trace(p2, r2)

    assert cli.main(["validate", p1]) == 0
    assert cli.main(["inspect", p1]) == 0
    out = capsys.readouterr().out
    assert "a" in out and "runner.steps" in out

    merged = str(tmp_path / "merged.json")
    assert cli.main(["merge", merged, p1, p2]) == 0
    merged_trace = export.load_trace(merged)
    assert export.validate_chrome_trace(merged_trace) == []
    # cluster totals SUM across processes (each worker counted steps=2)
    assert cli._counters(merged_trace)["runner.steps"] == 4.0
    assert cli._counters(export.load_trace(p1))["runner.steps"] == 2.0
    assert cli.main(["diff", p1, p2]) == 0

    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        json.dump({"traceEvents": []}, f)
    assert cli.main(["validate", bad]) == 1

    report = drift.DriftReport(
        strategy_id="s", num_steps=2, predicted_step_s=0.01,
        measured_step_s=0.02,
        terms=[drift.TermDrift("step", 0.01, 0.02)],
        collectives=[drift.CollectiveDrift("reduce", 100.0, 150.0)],
        breakdown={}, counters={})
    rpath = report.save(str(tmp_path / "drift.json"))
    assert cli.main(["drift", rpath]) == 0
    out = capsys.readouterr().out
    assert "reduce" in out and "strategy=s" in out


# ------------------------------------------------------------- histograms


def test_histogram_observe_quantiles_and_validation():
    h = tel.Histogram()
    assert h.quantile(0.5) is None  # empty
    for v in (1.0, 2.0, 3.0, 4.0, 100.0):
        h.observe(v)
    assert h.count == 5 and h.sum == 110.0
    assert h.min == 1.0 and h.max == 100.0
    # quantiles interpolate inside the bucket but never leave the data
    assert h.min <= h.quantile(0.5) <= h.max
    assert h.quantile(0.99) <= h.max
    assert h.quantile(1.0) == h.max
    with pytest.raises(ValueError, match="in \\[0, 1\\]"):
        h.quantile(1.5)
    with pytest.raises(ValueError, match="sorted"):
        tel.Histogram(bounds=(3.0, 1.0))
    with pytest.raises(ValueError, match="non-empty"):
        tel.Histogram(bounds=())
    # wire-format round trip (the cross-process scrape path)
    d = h.to_dict()
    assert d["p50"] == h.quantile(0.5) and d["p99"] == h.quantile(0.99)
    back = tel.Histogram.from_dict(d)
    assert back.to_dict() == d


def test_histogram_registry_prometheus_and_chrome_export():
    rec = tel.TraceRecorder(capacity=16, sample=1, pid=42, host="h")
    for v in (0.5, 2.0, 2.5, 40.0):
        rec.hist_observe("serve.latency_ms", v)
    assert rec.hist_quantile("serve.latency_ms", 0.5) is not None
    assert rec.hist_quantile("nope", 0.5) is None
    text = export.metrics_text(rec, labels={"worker": "w0"})
    assert "# TYPE adt_serve_latency_ms histogram" in text
    # cumulative le buckets merge the caller's labels, end at +Inf
    assert 'adt_serve_latency_ms_bucket{worker="w0",le="+Inf"} 4' in text
    assert 'adt_serve_latency_ms_sum{worker="w0"} 45' in text
    assert 'adt_serve_latency_ms_count{worker="w0"} 4' in text
    buckets = [int(l.rsplit(" ", 1)[1]) for l in text.splitlines()
               if l.startswith("adt_serve_latency_ms_bucket")]
    assert buckets == sorted(buckets)  # cumulative by construction
    trace = export.chrome_trace(rec)
    assert export.validate_chrome_trace(trace) == []
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "C"}
    assert {"serve.latency_ms.p50", "serve.latency_ms.p99"} <= names


def test_histogram_survives_publish_scrape_round_trip():
    client = _FakeCoordClient()
    rec = tel.TraceRecorder(capacity=16, sample=1, pid=7, host="n0")
    rec.hist_observe("serve.latency_ms", 3.0)
    rec.hist_observe("serve.latency_ms", 9.0)
    export.publish_telemetry(client, "w0", rec)
    scraped = export.scrape_cluster(client, ["w0"])
    text = scraped["metrics_text"]
    assert 'adt_serve_latency_ms_count{worker="w0"} 2' in text
    assert 'adt_serve_latency_ms_sum{worker="w0"} 12' in text


def test_module_level_histogram_helpers_and_reset():
    tel.hist_observe("serve.latency_ms", 5.0)
    assert tel.hist_quantile("serve.latency_ms", 0.5) is not None
    assert "serve.latency_ms" in tel.histograms()
    tel.get_recorder().clear()
    assert tel.hist_quantile("serve.latency_ms", 0.5) is None
    assert tel.histograms() == {}
