"""Driver for the two-process sharded-checkpoint test.

Each process writes/reads ONLY its own shards (checkpoint/sharded.py); the
parent asserts bit-exact resume plus the scale property the format exists
for: peak host allocation during save/restore stays well under the full
tree's bytes (the plain Saver's single-host gather would exceed it).

Usage: sharded_driver.py <spec.yml> <out.json> <builder> <phase> <ckpt_dir>
phase = run    -> train 3, sharded-save, train 2, dump finals
phase = resume -> fresh processes restore, train 2, dump finals
"""
import json
import sys
import tracemalloc

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import optax  # noqa: E402

import autodist_tpu as adt  # noqa: E402
from autodist_tpu import strategy as S  # noqa: E402

BUILDERS = {
    "PartitionedAR": lambda: S.PartitionedAR(),
    "PartitionedPS": lambda: S.PartitionedPS(),
    "PSAsyncPart": lambda: S.PartitionedPS(sync=False),
}


def make_case(seed=0):
    """One big partitioned var (the memory-assertion target) + small ones.
    emb is 4 MB; adam triples it, so the full tree is ~12 MB while each
    process's shards are ~half — the gap the parent asserts on."""
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    params = {
        "emb": jnp.asarray(rng.randn(4096, 256) * 0.1, jnp.float32),
        "w": jnp.asarray(rng.randn(256, 8) * 0.3, jnp.float32),
    }

    def loss_fn(p, batch):
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        return jnp.mean((feat @ p["w"] - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, 4096, (16,)).astype(np.int32),
             "y": rng.randn(16, 8).astype(np.float32)}
    return params, loss_fn, batch


def main():
    spec_yaml, out_path, builder_name, phase, ckpt_dir = sys.argv[1:6]
    ad = adt.AutoDist(resource_spec_file=spec_yaml,
                      strategy_builder=BUILDERS[builder_name]())
    params, loss_fn, batch = make_case()
    full_bytes = 3 * sum(np.asarray(v).nbytes for v in params.values())
    runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
    runner.init(params)
    from autodist_tpu.checkpoint import ShardedSaver
    saver = ShardedSaver(directory=ckpt_dir)

    losses = []
    if phase == "run":
        for _ in range(3):
            losses.append(float(runner.run(batch)["loss"]))
        tracemalloc.start()
        saver.save(runner)
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        for _ in range(2):
            losses.append(float(runner.run(batch)["loss"]))
    else:  # resume
        tracemalloc.start()
        saver.restore(runner)
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        for _ in range(2):
            losses.append(float(runner.run(batch)["loss"]))

    gathered = runner.gather_params()
    result = {
        "phase": phase,
        "losses": losses,
        "peak_bytes": int(peak),
        "full_bytes": int(full_bytes),
        "process_count": jax.process_count(),
        "params": {k: np.asarray(v).tolist() for k, v in gathered.items()},
    }
    with open(out_path, "w") as f:
        json.dump(result, f)
    print("sharded_driver done:", builder_name, phase, flush=True)


if __name__ == "__main__":
    main()
