"""DeviceSpec tests (analog of reference ``tests/test_device_spec.py``)."""
from autodist_tpu.resource_spec import DeviceSpec, DeviceType


def test_round_trip():
    d = DeviceSpec("10.0.0.1", DeviceType.TPU, 3)
    assert d.name_string() == "10.0.0.1:TPU:3"
    assert DeviceSpec.from_string(d.name_string()) == d


def test_from_string_forms():
    assert DeviceSpec.from_string("host").device_type == DeviceType.CPU
    assert DeviceSpec.from_string("host:2") == DeviceSpec("host", DeviceType.TPU, 2)
    # reference-style GPU names normalize onto TPU
    assert DeviceSpec.from_string("h:GPU:1") == DeviceSpec("h", DeviceType.TPU, 1)
    assert DeviceSpec.from_string("h:CPU:0").device_type == DeviceType.CPU


def test_hashable():
    s = {DeviceSpec("a", DeviceType.TPU, 0), DeviceSpec("a", DeviceType.TPU, 0)}
    assert len(s) == 1
