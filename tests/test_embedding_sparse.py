"""Sparse/embedding gradient wire path (VERDICT r1 item 2).

DLRM-style setting: vocab >= 100k, batch <= 1k. The sparse wire must cut
gradient-sync bytes by >= 10x vs dense psum while matching the dense
path's numerics (reference all_reduce_synchronizer.py:132-173 and
partitioner.py:660-684).
"""
import re

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.ops import embedding as E

VOCAB, DIM, BATCH = 100_000, 16, 512


def _model(sparse_names=True):
    """Tiny DLRM-ish tower: embedding lookup -> dense head."""
    rng = np.random.RandomState(0)
    params = {
        "emb": {"table": jnp.asarray(rng.randn(VOCAB, DIM) * 0.1, jnp.float32)},
        "head": jnp.asarray(rng.randn(DIM, 1) * 0.1, jnp.float32),
    }
    name = "emb/table" if sparse_names else None

    def loss_fn(p, batch):
        rows = E.embedding_lookup(p["emb"]["table"], batch["ids"], name=name)
        pred = rows @ p["head"]
        return jnp.mean((pred[:, 0] - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, VOCAB, (BATCH,)).astype(np.int32),
             "y": rng.randn(BATCH).astype(np.float32)}
    return loss_fn, params, batch


def _run(builder, sparse_names=True, steps=3):
    loss_fn, params, batch = _model(sparse_names)
    ad = adt.AutoDist(strategy_builder=builder)
    runner = ad.build(loss_fn, optax.sgd(0.5), params, batch)
    runner.init(params)
    for _ in range(steps):
        runner.run(batch)
    out = runner.gather_params()
    dstep = runner.distributed_step
    adt.reset()
    return out, dstep, runner


def test_lookup_is_plain_take_outside_capture():
    t = jnp.arange(12.0).reshape(4, 3)
    ids = jnp.asarray([1, 3])
    np.testing.assert_array_equal(
        np.asarray(E.embedding_lookup(t, ids, name="x")),
        np.asarray(t[ids]))


def test_tap_gradients_equal_dense_rows():
    """d loss/d tap == the gathered-row cotangent; stop_gradient kills the
    dense table grad."""
    t = jnp.arange(12.0).reshape(4, 3)
    ids = jnp.asarray([1, 3, 1])

    def loss(table, tap):
        with E.capture({"v": [tap]}):
            rows = E.embedding_lookup(table, ids, name="v")
        return jnp.sum(rows * rows)

    tap0 = jnp.zeros((3, 3))
    gt, gtap = jax.grad(loss, argnums=(0, 1))(t, tap0)
    assert np.all(np.asarray(gt) == 0)  # table got NO dense gradient
    np.testing.assert_allclose(np.asarray(gtap), 2 * np.asarray(t[ids]))


def test_sparse_wire_engages_and_matches_dense_numerics():
    sparse_params, sparse_dstep, _ = _run(strategy.AllReduce())
    assert sparse_dstep.metadata["sparse_wire"] == ["emb/table"]
    dense_params, dense_dstep, _ = _run(strategy.AllReduce(),
                                        sparse_names=False)
    assert dense_dstep.metadata["sparse_wire"] == []
    for k in ("emb/table", "head"):
        a = np.asarray(sparse_params["emb"]["table"] if k == "emb/table"
                       else sparse_params["head"])
        b = np.asarray(dense_params["emb"]["table"] if k == "emb/table"
                       else dense_params["head"])
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6,
                                   err_msg="sparse vs dense mismatch at %s" % k)


def _collective_bytes(hlo: str, op: str) -> int:
    """Total payload bytes of a collective kind in an HLO/StableHLO dump."""
    total = 0
    for m in re.finditer(r'"?%s"?[^\n]*' % op, hlo):
        line = m.group(0)
        for shape in re.findall(r"tensor<([0-9x]+)x(f32|f16|bf16|i32|si32|i8)",
                                line):
            dims, dt = shape
            n = 1
            for d in dims.split("x"):
                if d:
                    n *= int(d)
            total += n * (1 if dt == "i8" else 2 if dt in ("f16", "bf16") else 4)
    return total


def test_wire_bytes_at_least_10x_smaller():
    """The lowered program must not all-reduce a vocab-sized tensor; the
    sparse payload (all-gathered ids+values) is >= 10x smaller."""
    loss_fn, params, batch = _model(True)
    ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.5), params, batch)
    runner.init(params)
    sharded = runner.remapper.remap_feed(batch)
    hlo = runner.distributed_step.lowered_text(runner.state, sharded)

    dense_grad_bytes = VOCAB * DIM * 4
    # no all-reduce anywhere near the dense-table size
    ar_bytes = _collective_bytes(hlo, "all_reduce")
    assert ar_bytes < dense_grad_bytes / 10, \
        "dense-table all-reduce still present (%d bytes)" % ar_bytes
    # the sparse wire itself: gathered ids+values are batch-shaped
    ag_bytes = _collective_bytes(hlo, "all_gather")
    assert ag_bytes > 0, "no all-gather found — sparse wire not engaged"
    assert ag_bytes < dense_grad_bytes / 10, \
        "sparse wire too heavy: %d vs dense %d" % (ag_bytes, dense_grad_bytes)


def test_sparse_ps_ships_pairs_to_store():
    """PS host path: the store receives (ids, values), scatter-adds into
    shard index ranges, and the pushed wire bytes are batch-scale."""
    loss_fn, params, batch = _model(True)
    ad = adt.AutoDist(strategy_builder=strategy.PartitionedPS())
    runner = ad.build(loss_fn, optax.sgd(0.5), params, batch)
    runner.init(params)
    store = runner.distributed_step.ps_store
    assert store is not None and store.plans["emb/table"].partitioned
    runner.run(batch)
    runner.distributed_step.flush_ps()  # pipelined push lands off-thread
    dense_push = VOCAB * DIM * 4
    assert 0 < store.stats["bytes_pushed"] < dense_push / 10, \
        "sparse PS push not batch-scale: %d" % store.stats["bytes_pushed"]

    # numerics: same updates as the dense AllReduce run
    got = runner.gather_params()
    adt.reset()
    dense_params, _, _ = _run(strategy.AllReduce(), sparse_names=False,
                              steps=1)
    np.testing.assert_allclose(np.asarray(got["emb"]["table"]),
                               np.asarray(dense_params["emb"]["table"]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got["head"]),
                               np.asarray(dense_params["head"]),
                               rtol=1e-4, atol=1e-6)


def test_uncaptured_sparse_var_warns_and_falls_back(caplog):
    """A gather-detected var without a named lookup syncs dense, loudly."""
    import logging as pylog
    logger = pylog.getLogger("autodist_tpu")  # propagate=False: attach directly
    logger.addHandler(caplog.handler)
    try:
        _, dstep, _ = _run(strategy.AllReduce(), sparse_names=False, steps=1)
    finally:
        logger.removeHandler(caplog.handler)
    assert dstep.metadata["sparse_wire"] == []
    assert any("sync DENSE" in r.message for r in caplog.records)


def test_tied_embedding_stays_dense():
    """A table with a second differentiable use (tied output projection)
    MUST stay on the dense path — the sparse wire would drop the tied
    gradient component (safety check on the grad jaxpr)."""
    rng = np.random.RandomState(0)
    vocab, dim = 5000, 8
    params = {"emb": {"table": jnp.asarray(rng.randn(vocab, dim) * 0.1,
                                           jnp.float32)}}

    def loss_fn(p, batch):
        rows = E.embedding_lookup(p["emb"]["table"], batch["ids"],
                                  name="emb/table")
        logits = rows @ p["emb"]["table"].T  # tied: second (dense) use
        return jnp.mean(logits ** 2)

    batch = {"ids": rng.randint(0, vocab, (64,)).astype(np.int32)}
    ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
    runner.init(params)
    assert runner.distributed_step.metadata["sparse_wire"] == []
    # dense gradients flow: the table actually moves under training
    before = np.asarray(runner.gather_params()["emb"]["table"]).copy()
    runner.run(batch)
    after = np.asarray(runner.gather_params()["emb"]["table"])
    assert not np.allclose(before, after)


def test_small_vocab_cost_gate_keeps_dense():
    """vocab << batch: the gathered pair payload exceeds the dense grad,
    so the lowering keeps dense sync despite a named lookup."""
    rng = np.random.RandomState(0)
    vocab, dim, batch_n = 32, 4, 512
    params = {"t": jnp.asarray(rng.randn(vocab, dim) * 0.1, jnp.float32)}

    def loss_fn(p, batch):
        rows = E.embedding_lookup(p["t"], batch["ids"], name="t")
        return jnp.mean(rows ** 2)

    batch = {"ids": rng.randint(0, vocab, (batch_n,)).astype(np.int32)}
    ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
    runner.init(params)
    assert runner.distributed_step.metadata["sparse_wire"] == []


def test_ncf_sparse_embed_layers_engage():
    """The model zoo's SparseEmbed layers carry correctly-derived names —
    a big-vocab NCF engages the sparse wire end to end."""
    from autodist_tpu.models import ncf
    cfg = ncf.NCFConfig(num_users=20000, num_items=20000, mf_dim=8,
                        mlp_dims=(16, 8))
    model = ncf.NeuMF(cfg)
    import jax as _jax
    rng = np.random.RandomState(0)
    users = rng.randint(0, cfg.num_users, (64,)).astype(np.int32)
    items = rng.randint(0, cfg.num_items, (64,)).astype(np.int32)
    params = model.init(_jax.random.PRNGKey(0), users, items)

    def loss_fn(p, batch):
        logits = model.apply(p, batch["u"], batch["i"])
        y = batch["y"].astype(jnp.float32)
        return jnp.mean(jnp.maximum(logits, 0) - logits * y
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))

    batch = {"u": users, "i": items,
             "y": rng.randint(0, 2, (64,)).astype(np.int32)}
    ad = adt.AutoDist(strategy_builder=strategy.Parallax())
    runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
    runner.init(params)
    wired = runner.distributed_step.metadata["sparse_wire"]
    assert "params/mf_user_embedding/embedding" in wired, wired
    assert len(wired) == 4
    losses = [float(runner.run(batch)["loss"]) for _ in range(5)]
    assert losses[-1] < losses[0]


# ------------------------------------------------------------- strict mode


def test_require_sparse_raises_on_unrouted_lookup():
    """A builder that demanded the sparse wire (require_sparse=True) must
    raise — not warn — when a sparse var bypasses the named
    embedding_lookup and would silently sync dense (>10x wire)."""
    rng = np.random.RandomState(0)
    params = {"emb": jnp.asarray(rng.randn(4096, 16) * 0.1, jnp.float32),
              "w": jnp.asarray(rng.randn(16, 4) * 0.1, jnp.float32)}

    def loss_fn(p, batch):
        rows = jnp.take(p["emb"], batch["ids"], axis=0)  # NOT ops.embedding
        return jnp.mean((rows @ p["w"]) ** 2)

    batch = {"ids": rng.randint(0, 4096, (16,)).astype(np.int32)}
    ad = adt.AutoDist(
        strategy_builder=strategy.Parallax(require_sparse=True))
    with pytest.raises(ValueError, match="requires the sparse gradient"):
        ad.build(loss_fn, optax.sgd(0.1), params, batch)


def test_require_sparse_roundtrips_through_serialization(tmp_path):
    """require_sparse survives strategy serialize/deserialize — the
    worker's independently-lowered program enforces the same contract."""
    from autodist_tpu.strategy.base import Strategy, GraphConfig
    s = Strategy(graph_config=GraphConfig(replicas=["a"],
                                          require_sparse=True))
    s2 = Strategy.from_dict(s.to_dict())
    assert s2.graph_config.require_sparse is True


def test_require_sparse_satisfied_runs_clean(caplog):
    """A properly-routed embedding model under require_sparse engages the
    wire with ZERO sparse fallback warnings."""
    import logging as pylogging
    rng = np.random.RandomState(0)
    vocab, dim = 4096, 16
    params = {"emb": {"table": jnp.asarray(rng.randn(vocab, dim) * 0.1,
                                           jnp.float32)},
              "w": jnp.asarray(rng.randn(dim, 4) * 0.1, jnp.float32)}

    def loss_fn(p, batch):
        rows = E.embedding_lookup(p["emb"]["table"], batch["ids"],
                                  name="emb/table")
        return jnp.mean((rows @ p["w"] - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, vocab, (16,)).astype(np.int32),
             "y": rng.randn(16, 4).astype(np.float32)}
    ad = adt.AutoDist(
        strategy_builder=strategy.Parallax(require_sparse=True))
    with caplog.at_level(pylogging.WARNING, logger="autodist_tpu"):
        runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
        runner.init(params)
        losses = [float(runner.run(batch)["loss"]) for _ in range(4)]
    assert "emb/table" in runner.distributed_step.metadata["sparse_wire"]
    bad = [r for r in caplog.records if "sparse" in r.getMessage().lower()
           and ("dense" in r.getMessage().lower()
                or "failed" in r.getMessage().lower())]
    assert not bad, [r.getMessage() for r in bad]
    assert losses[-1] < losses[0]
