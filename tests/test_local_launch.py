"""Real (un-mocked) chief->worker launch over the local transport.

VERDICT r2 missing #2: the launch plane only ever ran under
ADT_DEBUG_REMOTE dry-run (no sshd in CI images). Loopback nodes now route
remote_exec/remote_copy through local bash/cp, so the reference's
chief-launched flow (``coordinator.py:46-110``: serialize strategy, copy
to worker, relaunch the same script with ADT_WORKER set, supervise,
fail-fast) executes for real: the chief process in these tests REALLY
spawns its worker, which joins the same jax.distributed job and trains in
lockstep.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from _capabilities import needs_mp_collectives

HERE = os.path.dirname(os.path.abspath(__file__))

USER_SCRIPT = """
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import optax
import autodist_tpu as adt
from autodist_tpu import strategy

spec, outdir, mode = sys.argv[1], sys.argv[2], sys.argv[3]
# AutoDist first: joining the distributed runtime must precede jnp use
ad = adt.AutoDist(resource_spec_file=spec,
                  strategy_builder=strategy.AllReduce())
if mode == "crash" and os.environ.get("ADT_WORKER"):
    os._exit(3)  # the supervised worker dies; the chief must fail fast

import jax.numpy as jnp
rng = np.random.RandomState(0)
params = {"w": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32)}

def loss_fn(p, batch):
    return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

batch = {"x": rng.randn(8, 8).astype(np.float32),
         "y": rng.randn(8, 4).astype(np.float32)}
step = ad.function(loss_fn, optimizer=optax.sgd(0.1), params=params)
losses = [float(step(batch)["loss"]) for _ in range(5)]
pid = int(os.environ.get("ADT_PROCESS_ID", "0"))
with open(os.path.join(outdir, "out_%d.json" % pid), "w") as f:
    json.dump({"losses": losses,
               "global_devices": len(jax.devices())}, f)
print("LOCAL_LAUNCH_DONE", pid, losses[-1], flush=True)
"""

SPEC_YAML = """
nodes:
  - address: 127.0.0.1
    chief: true
    cpus: [0, 1]
  - address: localhost
    cpus: [0, 1]
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_chief(tmp_path, mode):
    """Run the user script as the CHIEF only — it must launch its own
    worker through the local transport."""
    script = tmp_path / "user_script.py"
    script.write_text(USER_SCRIPT)
    spec = tmp_path / "spec.yml"
    spec.write_text(SPEC_YAML)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("ADT_DEBUG_REMOTE", None)   # REAL launch, no dry-run
    env.pop("ADT_WORKER", None)
    env.update({
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "ADT_COORDINATOR_ADDR": "127.0.0.1:%d" % _free_port(),
        "ADT_COORDSVC_PORT": str(_free_port()),
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE)] +
            ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH")
             else [])),
    })
    return subprocess.run(
        [sys.executable, str(script), str(spec), str(tmp_path), mode],
        env=env, capture_output=True, text=True, timeout=180)


@needs_mp_collectives()
def test_chief_launches_and_trains_with_worker(tmp_path):
    proc = _run_chief(tmp_path, "train")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "local_exec[localhost]" in proc.stderr, proc.stderr[-2000:]
    outs = {}
    for pid in (0, 1):
        path = tmp_path / ("out_%d.json" % pid)
        assert path.exists(), (
            "process %d wrote no output\n%s" % (pid, proc.stdout + proc.stderr))
        outs[pid] = json.loads(path.read_text())
    # one lockstep job: 2 processes x 2 devices, identical losses
    for pid in (0, 1):
        assert outs[pid]["global_devices"] == 4
    np.testing.assert_array_equal(outs[0]["losses"], outs[1]["losses"])
    assert outs[0]["losses"][-1] < outs[0]["losses"][0]


def test_chief_fail_fast_on_worker_death(tmp_path):
    """The launched worker exits nonzero right after construction; the
    chief's supervision watcher must abort the whole job (reference
    coordinator.py:98-110) instead of hanging in the collective."""
    proc = _run_chief(tmp_path, "crash")
    assert proc.returncode == 1, (proc.returncode, proc.stdout, proc.stderr)
    assert "aborting job" in proc.stderr, proc.stderr[-2000:]
