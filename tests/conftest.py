"""Test config: run everything on a virtual 8-device CPU mesh.

The analog of the reference's CPU-only resource specs (r2/r5), which let the
full strategy/transform path run with no accelerator
(reference ``tests/integration/test_dist.py`` notes in SURVEY §4.3).
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# The image's sitecustomize imports jax before this file runs, freezing the
# JAX_PLATFORMS env default (axon); override through the config instead.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, "virtual 8-device CPU mesh not active"
os.environ.setdefault("ADT_IS_TESTING", "1")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "integration: multi-process tests gated by --run-integration")
    config.addinivalue_line(
        "markers", "needs_mp_collectives: requires multi-process CPU "
        "collectives (probed lazily at first marked test's setup)")


def pytest_runtest_setup(item):
    # lazy capability gate: probe once per run, only when a marked test is
    # actually about to execute (collection stays probe-free)
    if "needs_mp_collectives" in item.keywords:
        from _capabilities import (MP_SKIP_REASON,
                                   multiprocess_collectives_supported)
        if not multiprocess_collectives_supported():
            pytest.skip(MP_SKIP_REASON)


def pytest_addoption(parser):
    parser.addoption("--run-integration", action="store_true", default=False,
                     help="run multi-process integration tests")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--run-integration"):
        return
    skip = pytest.mark.skip(reason="needs --run-integration")
    for item in items:
        if "integration" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _reset_autodist():
    yield
    import autodist_tpu
    autodist_tpu.reset()
