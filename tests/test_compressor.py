"""Gradient-compressor unit + e2e tests.

The reference ships PowerSGD fully commented out
(``kernel/synchronization/compressor.py:208-284``) and has no compressor
unit tests; here the whole registry is live and covered: reconstruction
exactness on low-rank gradients, error-feedback convergence (the arXiv
1905.13727 EF guarantee), bf16 wire-format round-trips, and the full-stack
mesh path with a warm-started Q carried in sync_state.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.kernel.synchronization import compressor as C

IDENT_PSUM = lambda x: x  # single-worker reduction  # noqa: E731


def test_registry_create_and_errors():
    assert isinstance(C.create(None), C.NoneCompressor)
    assert isinstance(C.create("BF16Compressor"), C.HorovodCompressor)
    with pytest.raises(ValueError, match="unknown compressor"):
        C.create("nope")
    with pytest.raises(ValueError, match="takes no argument"):
        C.create("HorovodCompressor:2")


def test_powersgd_rank_from_name():
    comp = C.create("PowerSGDCompressor:3", "w")
    assert isinstance(comp, C.PowerSGDCompressor) and comp.rank == 3
    state = comp.state_init((8, 6), jnp.float32)
    assert state["q"].shape == (6, 3)


def test_powersgd_exact_on_low_rank():
    """A rank-r gradient is reconstructed exactly by rank-r PowerSGD in one
    power iteration (P = MQ spans col(M) for generic Q)."""
    rng = np.random.RandomState(0)
    m = (rng.randn(10, 2) @ rng.randn(2, 7)).astype(np.float32)  # rank 2
    comp = C.PowerSGDCompressor("w", rank=2)
    state = comp.state_init(m.shape, jnp.float32)
    approx, _ = comp.reduce(jnp.asarray(m), state, IDENT_PSUM)
    np.testing.assert_allclose(np.asarray(approx), m, rtol=1e-4, atol=1e-4)


def test_powersgd_error_feedback_converges():
    """With a FIXED full-rank gradient, the EF residual keeps feeding the
    unsent mass back, so the running mean of transmitted approximations
    converges to the true gradient."""
    rng = np.random.RandomState(1)
    g = rng.randn(12, 9).astype(np.float32)
    comp = C.PowerSGDCompressor("w", rank=2)
    state = comp.state_init(g.shape, jnp.float32)
    total = np.zeros_like(g)
    steps = 60
    for _ in range(steps):
        approx, state = comp.reduce(jnp.asarray(g), state, IDENT_PSUM)
        total += np.asarray(approx)
    rel = np.linalg.norm(total / steps - g) / np.linalg.norm(g)
    assert rel < 0.05, rel


def test_powersgd_passthrough_for_vectors():
    comp = C.PowerSGDCompressor("b", rank=2)
    assert comp.state_init((8,), jnp.float32) is None
    v = jnp.arange(8, dtype=jnp.float32)
    out, state = comp.reduce(v, None, IDENT_PSUM)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(v))
    assert state is None


def test_horovod_ef_error_is_quantization_residual():
    rng = np.random.RandomState(2)
    g = rng.randn(32).astype(np.float32) * 1e-3
    comp = C.HorovodCompressorEF("w")
    state = comp.state_init(g.shape, jnp.float32)
    out1, state = comp.reduce(jnp.asarray(g), state, IDENT_PSUM)
    # residual + wire value == compensated gradient, exactly
    np.testing.assert_allclose(np.asarray(out1) + np.asarray(state), g,
                               rtol=0, atol=1e-8)
    # two EF steps transmit (almost) the full 2g despite bf16 rounding
    out2, state = comp.reduce(jnp.asarray(g), state, IDENT_PSUM)
    np.testing.assert_allclose(np.asarray(out1 + out2), 2 * g, rtol=2e-2)


def test_powersgd_e2e_on_mesh():
    """Full stack on the 8-device mesh: PowerSGD syncs per-var (not
    bucketed), carries Q + error in sync_state, and training converges.
    Rank 4 == full rank for a 16x4 gradient, so compression is exact and
    convergence matches plain SGD; lower ranks converge via EF (covered by
    test_powersgd_error_feedback_converges)."""
    rng = np.random.RandomState(3)
    params = {"w": jnp.zeros((16, 4), jnp.float32)}
    W = rng.randn(16, 4).astype(np.float32)
    x = rng.randn(64, 16).astype(np.float32)
    batch = {"x": x, "y": x @ W}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    ad = autodist_tpu.AutoDist(
        strategy_builder=S.AllReduce(compressor="PowerSGDCompressor:4"))
    step = ad.function(loss_fn, optimizer=optax.sgd(2e-2), params=params)
    losses = [float(step(batch)["loss"]) for _ in range(200)]
    assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])
    state = step.get_runner().state
    q = state.sync_state["var"]["w"]["q"]
    assert q.shape[-2:] == (4, 4)  # m x rank, warm-started across steps
    assert state.sync_state["var"]["w"]["error"].shape[-2:] == (16, 4)
