"""Gradient-compressor unit + e2e tests.

The reference ships PowerSGD fully commented out
(``kernel/synchronization/compressor.py:208-284``) and has no compressor
unit tests; here the whole registry is live and covered: reconstruction
exactness on low-rank gradients, error-feedback convergence (the arXiv
1905.13727 EF guarantee), bf16 wire-format round-trips, and the full-stack
mesh path with a warm-started Q carried in sync_state.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.kernel.synchronization import compressor as C

IDENT_PSUM = lambda x: x  # single-worker reduction  # noqa: E731


def test_registry_create_and_errors():
    assert isinstance(C.create(None), C.NoneCompressor)
    assert isinstance(C.create("BF16Compressor"), C.HorovodCompressor)
    with pytest.raises(ValueError, match="unknown compressor"):
        C.create("nope")
    with pytest.raises(ValueError, match="takes no argument"):
        C.create("HorovodCompressor:2")


def test_powersgd_rank_from_name():
    comp = C.create("PowerSGDCompressor:3", "w")
    assert isinstance(comp, C.PowerSGDCompressor) and comp.rank == 3
    state = comp.state_init((8, 6), jnp.float32)
    assert state["q"].shape == (6, 3)


def test_powersgd_exact_on_low_rank():
    """A rank-r gradient is reconstructed exactly by rank-r PowerSGD in one
    power iteration (P = MQ spans col(M) for generic Q)."""
    rng = np.random.RandomState(0)
    m = (rng.randn(10, 2) @ rng.randn(2, 7)).astype(np.float32)  # rank 2
    comp = C.PowerSGDCompressor("w", rank=2)
    state = comp.state_init(m.shape, jnp.float32)
    approx, _ = comp.reduce(jnp.asarray(m), state, IDENT_PSUM)
    np.testing.assert_allclose(np.asarray(approx), m, rtol=1e-4, atol=1e-4)


def test_powersgd_error_feedback_converges():
    """With a FIXED full-rank gradient, the EF residual keeps feeding the
    unsent mass back, so the running mean of transmitted approximations
    converges to the true gradient."""
    rng = np.random.RandomState(1)
    g = rng.randn(12, 9).astype(np.float32)
    comp = C.PowerSGDCompressor("w", rank=2)
    state = comp.state_init(g.shape, jnp.float32)
    total = np.zeros_like(g)
    steps = 60
    for _ in range(steps):
        approx, state = comp.reduce(jnp.asarray(g), state, IDENT_PSUM)
        total += np.asarray(approx)
    rel = np.linalg.norm(total / steps - g) / np.linalg.norm(g)
    assert rel < 0.05, rel


def test_powersgd_passthrough_for_vectors():
    comp = C.PowerSGDCompressor("b", rank=2)
    assert comp.state_init((8,), jnp.float32) is None
    v = jnp.arange(8, dtype=jnp.float32)
    out, state = comp.reduce(v, None, IDENT_PSUM)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(v))
    assert state is None


def test_horovod_ef_error_is_quantization_residual():
    rng = np.random.RandomState(2)
    g = rng.randn(32).astype(np.float32) * 1e-3
    comp = C.HorovodCompressorEF("w")
    state = comp.state_init(g.shape, jnp.float32)
    out1, state = comp.reduce(jnp.asarray(g), state, IDENT_PSUM)
    # residual + wire value == compensated gradient, exactly
    np.testing.assert_allclose(np.asarray(out1) + np.asarray(state), g,
                               rtol=0, atol=1e-8)
    # two EF steps transmit (almost) the full 2g despite bf16 rounding
    out2, state = comp.reduce(jnp.asarray(g), state, IDENT_PSUM)
    np.testing.assert_allclose(np.asarray(out1 + out2), 2 * g, rtol=2e-2)


def test_powersgd_e2e_on_mesh():
    """Full stack on the 8-device mesh: PowerSGD syncs per-var (not
    bucketed), carries Q + error in sync_state, and training converges.
    Rank 4 == full rank for a 16x4 gradient, so compression is exact and
    convergence matches plain SGD; lower ranks converge via EF (covered by
    test_powersgd_error_feedback_converges)."""
    rng = np.random.RandomState(3)
    params = {"w": jnp.zeros((16, 4), jnp.float32)}
    W = rng.randn(16, 4).astype(np.float32)
    x = rng.randn(64, 16).astype(np.float32)
    batch = {"x": x, "y": x @ W}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    ad = autodist_tpu.AutoDist(
        strategy_builder=S.AllReduce(compressor="PowerSGDCompressor:4"))
    step = ad.function(loss_fn, optimizer=optax.sgd(2e-2), params=params)
    losses = [float(step(batch)["loss"]) for _ in range(200)]
    assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])
    state = step.get_runner().state
    q = state.sync_state["var"]["w"]["q"]
    assert q.shape[-2:] == (4, 4)  # m x rank, warm-started across steps
    assert state.sync_state["var"]["w"]["error"].shape[-2:] == (16, 4)


def test_int8_ring_all_reduce_matches_sum():
    """The quantized ring produces bit-identical, ~1%-accurate sums."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from autodist_tpu.parallel.collectives import int8_ring_all_reduce
    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("data",))
    rng = np.random.RandomState(0)
    L = 1000  # not divisible by 8 -> exercises padding
    x = rng.randn(8, L).astype(np.float32)
    out = jax.jit(jax.shard_map(
        lambda xs: int8_ring_all_reduce(xs.reshape(-1), "data", 8),
        mesh=mesh, in_specs=P("data"), out_specs=P("data"),
        check_vma=False))(x.reshape(8 * L))
    got = np.asarray(out).reshape(8, L)
    exact = x.sum(axis=0)
    # SPMD invariant: every replica holds bit-identical reduced values
    assert np.max(np.abs(got - got[0])) == 0.0
    rel = np.abs(got[0] - exact) / (np.abs(exact) + 1e-6)
    assert np.median(rel) < 0.03, np.median(rel)


def test_int8_ef_trains_to_convergence():
    """Int8CompressorEF through the full stack: error feedback recovers
    what quantization drops, converging like the uncompressed path."""
    import jax.numpy as jnp
    import optax
    import autodist_tpu
    from autodist_tpu import strategy as S
    rng = np.random.RandomState(0)
    W = rng.randn(6, 2).astype(np.float32)
    x = rng.randn(64, 6).astype(np.float32)
    batch = {"x": x, "y": x @ W}
    losses = {}
    for comp in ("NoneCompressor", "Int8CompressorEF"):
        autodist_tpu.reset()
        params = {"w": jnp.zeros((6, 2))}
        loss_fn = lambda p, b: jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)  # noqa: E731
        ad = autodist_tpu.AutoDist(
            strategy_builder=S.AllReduce(compressor=comp))
        step = ad.function(loss_fn, optimizer=optax.sgd(0.2), params=params)
        losses[comp] = [float(step(batch)["loss"]) for _ in range(80)]
    assert losses["Int8CompressorEF"][-1] < 1e-4, losses["Int8CompressorEF"][-8:]
    # EF keeps the compressed path within an order of magnitude of exact
    assert losses["Int8CompressorEF"][-1] < max(10 * losses["NoneCompressor"][-1], 1e-4)


def test_int8_resume_bitexact(tmp_path):
    """EF residuals round-trip through checkpoints (sync_state)."""
    import jax.numpy as jnp
    import optax
    import autodist_tpu
    from autodist_tpu import strategy as S
    from autodist_tpu.checkpoint import Saver
    rng = np.random.RandomState(1)
    params = {"w": jnp.asarray(rng.randn(8, 2) * 0.3, jnp.float32)}
    loss_fn = lambda p, b: jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)  # noqa: E731
    batch = {"x": rng.randn(16, 8).astype(np.float32),
             "y": rng.randn(16, 2).astype(np.float32)}
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(
        strategy_builder=S.AllReduce(compressor="Int8CompressorEF"))
    runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    saver.save(runner)
    for _ in range(2):
        runner.run(batch)
    a = runner.gather_params()
    saver.restore(runner)
    for _ in range(2):
        runner.run(batch)
    b = runner.gather_params()
    np.testing.assert_array_equal(np.asarray(a["w"]), np.asarray(b["w"]))


def test_int8_multi_axis_ring_matches_sum():
    """Sequential per-axis quantized rings on a 2-axis (4x2) mesh: the
    result approximates the full 8-way sum (VERDICT r1: int8 must not
    silently degrade to bf16 on dp x sp / dp x tp meshes)."""
    from jax.sharding import Mesh, PartitionSpec as P
    from autodist_tpu.parallel.collectives import int8_multi_axis_all_reduce
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devs, ("data", "seq"))
    rng = np.random.RandomState(0)
    xs = rng.randn(8, 33).astype(np.float32)

    out = jax.jit(jax.shard_map(
        lambda x: int8_multi_axis_all_reduce(
            x.reshape(-1), (("data", 4), ("seq", 2))),
        mesh=mesh, in_specs=P(("data", "seq")), out_specs=P(),
        check_vma=False))(xs)
    want = xs.sum(axis=0)
    # two quantization stages: tolerance ~2x the single-ring bound
    scale = np.abs(xs).sum(axis=0).max()
    np.testing.assert_allclose(np.asarray(out), want,
                               atol=4 * scale / 127.0, rtol=0.1)


def test_int8_bucket_armed_on_two_axis_mesh():
    """Through the full stack on a dp x seq mesh, the int8 bucket must run
    the explicit two-phase quantized all-reduce (all_to_all + all_gather
    in the lowered program), not the bf16 psum fallback."""
    import autodist_tpu as adt
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(8, 4) * 0.1, jnp.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    batch = {"x": rng.randn(16, 8).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}
    from autodist_tpu.strategy.base import (AllReduceSynchronizer, GraphConfig,
                                            Strategy, VarConfig)
    from autodist_tpu.strategy.base import StrategyBuilder

    class Int8TwoAxis(StrategyBuilder):
        def build(self, model_item, resource_spec):
            return Strategy(
                node_config=[VarConfig(
                    var_name="w",
                    synchronizer=AllReduceSynchronizer(
                        compressor="Int8CompressorEF"))],
                graph_config=GraphConfig(
                    replicas=[d.name_string() for d in resource_spec.devices],
                    mesh_shape={"data": 4, "seq": 2}))

    ad = adt.AutoDist(strategy_builder=Int8TwoAxis())
    runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
    runner.init(params)
    sharded = runner.remapper.remap_feed(batch)
    hlo = runner.distributed_step.lowered_text(runner.state, sharded)
    assert "all_to_all" in hlo and "all_gather" in hlo, \
        "int8 two-phase wire not armed on 2-axis mesh"
    # and it trains
    losses = [float(runner.run(batch)["loss"]) for _ in range(10)]
    assert losses[-1] < losses[0]


def test_hierarchical_psum_matches_plain():
    """spec=DCN lowering: reduce-scatter/psum/all-gather equals one psum
    numerically, and the lowered program carries the scatter+gather."""
    from jax.sharding import Mesh, PartitionSpec as P
    from autodist_tpu.parallel.collectives import hierarchical_psum
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dcnaxis", "data"))
    rng = np.random.RandomState(0)
    xs = rng.randn(8, 5, 3).astype(np.float32)

    fn = jax.jit(jax.shard_map(
        lambda x: hierarchical_psum(x.reshape(5, 3), ("data",), ("dcnaxis",)),
        mesh=mesh, in_specs=P(("dcnaxis", "data")), out_specs=P(),
        check_vma=False))
    out = fn(xs)
    np.testing.assert_allclose(np.asarray(out), xs.sum(axis=0), rtol=1e-5,
                               atol=1e-5)
    hlo = fn.lower(xs).as_text()
    assert "reduce_scatter" in hlo and "all_gather" in hlo


def test_spec_dcn_consumed_in_lowering(monkeypatch):
    """An AllReduce strategy with spec=DCN on a 2-axis mesh (data marked
    DCN via the override) must lower the gradient reduce hierarchically —
    the spec hint is no longer dead metadata (VERDICT r1)."""
    import autodist_tpu as adt
    monkeypatch.setenv("ADT_DCN_AXES", "data")
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(8, 4) * 0.1, jnp.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    batch = {"x": rng.randn(16, 8).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}
    from autodist_tpu.strategy.base import (AllReduceSynchronizer, GraphConfig,
                                            Strategy, StrategyBuilder,
                                            VarConfig)

    class DCNHint(StrategyBuilder):
        def build(self, model_item, resource_spec):
            return Strategy(
                node_config=[VarConfig(
                    var_name="w",
                    synchronizer=AllReduceSynchronizer(spec="DCN"))],
                graph_config=GraphConfig(
                    replicas=[d.name_string() for d in resource_spec.devices],
                    mesh_shape={"data": 4, "seq": 2}))

    ad = adt.AutoDist(strategy_builder=DCNHint())
    runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
    runner.init(params)
    sharded = runner.remapper.remap_feed(batch)
    hlo = runner.distributed_step.lowered_text(runner.state, sharded)
    assert "reduce_scatter" in hlo, "spec=DCN did not lower hierarchically"
    losses = [float(runner.run(batch)["loss"]) for _ in range(5)]
    assert losses[-1] < losses[0]
