"""Program-structure assertions for the multi-axis dryrun legs.

VERDICT r2 weak #7: the dryrun legs asserted only ``loss is finite`` —
a lowering regression that silently fell back to pure data parallelism
would still print OK. ``__graft_entry__._one_step`` now checks the
lowered StableHLO for the collectives each parallelism family is made
of; these tests prove the check (a) passes on the real configs (the full
dryrun runs in CI via test_autodist's entry checks and the driver) and
(b) actually FAILS when the lowering is deliberately broken.
"""
import numpy as np
import pytest

import __graft_entry__ as ge
from autodist_tpu import strategy
from autodist_tpu.models import moe_lm


def test_moe_leg_asserts_all_to_all():
    """The real MoE config passes with its all_to_all expectation."""
    ge._one_step(
        strategy.ExpertParallel(ep_shards=2, mp_rules=moe_lm.ep_rules()),
        moe_lm.make_train_setup(moe_lm.MoEConfig.tiny(), seq_len=8,
                                batch_size=8),
        "ep2 structure", expect_ops=[("all_to_all", "MoE token routing")])


def test_broken_lowering_fails_not_ok():
    """Deliberate break: run the MoE model under a ZeRO data-parallel
    strategy — it compiles and trains happily (moe_ffn's dense fallback,
    finite loss, vars sharded) but there is NO expert token routing. The
    structure assertion must fail loudly instead of printing OK."""
    with pytest.raises(AssertionError, match="all_to_all"):
        ge._one_step(
            strategy.PartitionedAR(),
            moe_lm.make_train_setup(moe_lm.MoEConfig.tiny(), seq_len=8,
                                    batch_size=8),
            "ep2 broken", expect_ops=[("all_to_all", "MoE token routing")])


def test_moe_embedding_rides_sparse_wire():
    """With a realistic vocab (the cost gate compares batch-scale wire vs
    vocab-scale dense), the untied MoE token table synchronizes as
    (ids, values) — VERDICT r2 weak #4: the multi-axis zoo was shipping
    vocab-sized gradients."""
    import optax
    import autodist_tpu as adt
    adt.reset()
    cfg = moe_lm.MoEConfig.tiny(vocab_size=4096)
    loss_fn, params, batch, _ = moe_lm.make_train_setup(cfg, seq_len=8,
                                                        batch_size=8)
    runner = adt.AutoDist(strategy_builder=strategy.ExpertParallel(
        ep_shards=2, mp_rules=moe_lm.ep_rules())).build(
        loss_fn, optax.adam(1e-3), params, batch)
    runner.init(params)
    m = runner.run(batch)
    assert np.isfinite(m["loss"])
    assert "embed" in runner.distributed_step.metadata["sparse_wire"]
    adt.reset()
