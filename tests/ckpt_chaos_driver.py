"""Driver for the checkpoint crash-resume chaos test (test_ckpt_chaos.py).

One incarnation of a training job: build on the mesh described by the
resource spec, auto-resume from ``ADT_CKPT_DIR`` if ``ADT_AUTO_RESUME``
is set (last *committed* checkpoint — torn/corrupt ones are skipped),
train to ``steps`` saving a sharded checkpoint every 2 steps, and dump
the per-step losses plus the ckpt.* telemetry counters.

The parent arranges the violence: a ``ADT_CKPT_FAULT_PLAN`` kill rule
SIGKILLs the first incarnation mid-save, file damage is injected on a
committed checkpoint, and the second incarnation runs on a SMALLER mesh
(8 -> 4 devices) — the cross-topology restore path under real crash
debris.

Usage: ckpt_chaos_driver.py <spec.yml> <out.json> <builder> <ckpt_dir> <steps>
"""
import json
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import optax  # noqa: E402

import autodist_tpu as adt  # noqa: E402
from autodist_tpu import strategy as S  # noqa: E402
from autodist_tpu.checkpoint import ShardedSaver  # noqa: E402
from autodist_tpu.telemetry import spans as tel  # noqa: E402

BUILDERS = {
    "PartitionedAR": lambda: S.PartitionedAR(),
    "PartitionedPS": lambda: S.PartitionedPS(),
}


def make_case(seed=7):
    """Split dim 18 is not divisible by 8 or 4, so every mesh size pads
    differently — the resume-on-a-smaller-mesh restore must re-pad, not
    just re-slice (same construction as the in-process flex tests)."""
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    params = {"emb": jnp.asarray(rng.randn(18, 4).astype(np.float32)),
              "w": jnp.asarray(rng.randn(4, 2).astype(np.float32))}

    def loss_fn(p, batch):
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        return jnp.mean((feat @ p["w"] - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, 18, (16,)).astype(np.int32),
             "y": rng.randn(16, 2).astype(np.float32)}
    return params, loss_fn, batch


def main():
    spec_yaml, out_path, builder_name, ckpt_dir, steps = sys.argv[1:6]
    steps = int(steps)
    ad = adt.AutoDist(resource_spec_file=spec_yaml,
                      strategy_builder=BUILDERS[builder_name]())
    params, loss_fn, batch = make_case()
    runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
    runner.init(params)  # ADT_AUTO_RESUME restores the last-good here
    start = int(np.asarray(jax.device_get(runner.state.step)))
    saver = ShardedSaver(directory=ckpt_dir)
    losses = {}
    for i in range(start + 1, steps + 1):
        losses[i] = float(runner.run(batch)["loss"])
        if i % 2 == 0:
            saver.save(runner)  # the fault plan may SIGKILL us in here
    counters = {k: v for k, v in tel.counters().items()
                if k.startswith("ckpt.")}
    with open(out_path, "w") as f:
        json.dump({"start": start, "losses": losses,
                   "device_count": jax.device_count(),
                   "counters": counters}, f)
    print("ckpt_chaos_driver done: start=%d devices=%d"
          % (start, jax.device_count()), flush=True)


if __name__ == "__main__":
    main()
