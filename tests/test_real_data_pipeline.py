"""Real-data end-to-end: actual text through the native record loader into
LM training (VERDICT r1 item 10 — the loader proven beyond synthetic
records). The corpus is the repository's own documentation: real English
prose, available offline."""
import os

import numpy as np
import jax.numpy as jnp
import optax
import pytest

import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.data import text as text_lib
from autodist_tpu.data.record_dataset import RecordFileDataset

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_corpus_discovery_and_windows():
    paths = text_lib.repo_docs_corpus(REPO)
    assert len(paths) >= 3  # README + docs tree
    data = text_lib.load_text(paths)
    assert len(data) > 10_000  # a real corpus, not a stub
    w = text_lib.byte_windows(data, seq_len=64)
    assert w.shape[1] == 65 and w.shape[0] > 100
    assert w.min() >= 0 and w.max() < text_lib.BYTE_VOCAB
    # windows really are the text
    assert bytes(w[0, :20].astype(np.uint8).tolist()) in data


def test_real_text_trains_through_native_loader(tmp_path):
    """docs text -> ADT1 records -> native C++ loader -> byte-LM training:
    held-out loss must beat both the uniform-random bound and the unigram
    entropy of the corpus (the model actually learned from the data)."""
    seq_len = 32
    rec = str(tmp_path / "docs.adt")
    n = text_lib.write_lm_records(text_lib.repo_docs_corpus(REPO), rec,
                                  seq_len=seq_len)
    assert n > 300

    from autodist_tpu.models.lm import LMConfig, make_train_setup
    cfg = LMConfig(vocab_size=text_lib.BYTE_VOCAB, d_model=64, num_layers=2,
                   num_heads=4, mlp_dim=128, max_seq_len=seq_len)
    loss_fn, params, example_batch, _ = make_train_setup(
        cfg, seq_len=seq_len, batch_size=32, attention="default")

    ad = adt.AutoDist(strategy_builder=strategy.AllReduce())
    runner = ad.build(loss_fn, optax.adam(3e-3), params, example_batch)
    runner.init(params)

    with RecordFileDataset(rec, batch_size=32, shuffle=True, seed=0) as ds:
        history = runner.fit(iter(ds), steps=120)
    first, last = float(history[0]["loss"]), float(history[-1]["loss"])
    uniform_nats = np.log(text_lib.BYTE_VOCAB)  # ~5.55
    # unigram entropy of the corpus — beating it means the model uses
    # context, not just symbol frequencies
    data = np.frombuffer(text_lib.load_text(
        text_lib.repo_docs_corpus(REPO)), np.uint8)
    counts = np.bincount(data, minlength=256).astype(np.float64)
    p = counts / counts.sum()
    unigram_nats = float(-(p[p > 0] * np.log(p[p > 0])).sum())
    assert first > 0.8 * uniform_nats  # starts near chance
    assert last < unigram_nats, (first, last, unigram_nats)


def test_bert_large_preset_exists():
    """The registry + harness carry the reference's benchmark config
    (reference benched bert-large uncased)."""
    from autodist_tpu.models import bert
    cfg = bert.BertConfig.large()
    assert (cfg.hidden_size, cfg.num_layers, cfg.num_heads) == (1024, 24, 16)
    from examples.benchmark.bert import CONFIGS
    assert "large" in CONFIGS
    # buildable at tiny sequence length (weights are the real large shape)
    import jax
    model = bert.BertForMLM(cfg)
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32),
                           jnp.zeros((1, 8), jnp.int32),
                           jnp.ones((1, 8), jnp.int32)))
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(shapes))
    assert n_params > 300e6  # bert-large scale (~335M)
