"""Quantized wire collectives (blockwise int8 AR + PS push/pull).

Pins the PR's contracts end to end: the blockwise codec (round-trip
bound, NaN poisoning, host/device bit-equality), the EQuARX two-phase
all-reduce (sum accuracy, SPMD bit-identity, all_to_all+all_gather
lowering), training parity of the quantized wire vs fp32 on both the
AllReduce and host-PS paths (per-step AND fused k=4), the ADT310/311
diagnostics and the search-space canon that never emits them, the
byte-accounting agreement between the telemetry counters, the cost
model, and the ADT5xx measured profile, degraded PS pulls dequantizing
the last-good snapshot, and the PR 6 searcher choosing
``wire_dtype=int8`` on its own when bandwidth-bound.
"""
import dataclasses
import random

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.model_item import ModelItem
from autodist_tpu.parallel import collectives as C
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.telemetry import spans as tel


# ------------------------------------------------------------------ codec


def test_block_codec_roundtrip_bound():
    """Per-element error is bounded by its OWN block's absmax/127 —
    tighter than a per-tensor scale when magnitudes vary across blocks."""
    rng = np.random.RandomState(0)
    # block 0 small-magnitude, block 1 large: per-tensor scaling would
    # wipe out block 0's resolution
    x = np.concatenate([rng.randn(64).astype(np.float32) * 1e-3,
                        rng.randn(64).astype(np.float32) * 1e3])
    q, s = C.quant_i8_block(jnp.asarray(x), block=64)
    back = np.asarray(C.dequant_i8_block(q, s, 128))
    for b in range(2):
        sl = slice(64 * b, 64 * (b + 1))
        bound = np.abs(x[sl]).max() / 127.0 + 1e-12
        assert np.abs(back[sl] - x[sl]).max() <= bound * 1.0001
    # per-tensor codec CANNOT hit block 0's bound (sanity of "blockwise")
    qt, st = C._quant_i8(jnp.asarray(x))
    back_t = np.asarray(C._dequant_i8(qt, st))
    assert (np.abs(back_t[:64] - x[:64]).max()
            > np.abs(back[:64] - x[:64]).max() * 10)


def test_block_codec_padding_and_nan_poisoning():
    x = np.arange(100, dtype=np.float32)  # not a block multiple
    q, s = C.quant_i8_block(jnp.asarray(x), block=32)
    assert q.shape == (4, 32) and s.shape == (4,)
    back = np.asarray(C.dequant_i8_block(q, s, 100))
    assert back.shape == (100,)
    # a NaN poisons ITS block's scale (divergence must propagate), the
    # other blocks stay finite
    x[5] = np.nan
    q, s = C.quant_i8_block(jnp.asarray(x), block=32)
    s = np.asarray(s)
    assert not np.isfinite(s[0]) and np.isfinite(s[1:]).all()


def test_host_and_device_codec_bitwise_equal():
    """quant_wire_np (the PS store's host side) and quant_wire (the
    in-graph side) must produce identical bytes — the fused engine's
    in-scan codec emulation depends on it."""
    rng = np.random.RandomState(1)
    arr = rng.randn(37, 11).astype(np.float32) * 3.7
    w_host = C.quant_wire_np(arr)
    w_dev = jax.tree_util.tree_map(np.asarray, C.quant_wire(arr))
    np.testing.assert_array_equal(w_host["q"], w_dev["q"])
    np.testing.assert_array_equal(w_host["s"], w_dev["s"])
    back = C.dequant_wire_np(w_host, (37, 11))
    np.testing.assert_array_equal(
        back, np.asarray(C.dequant_wire(w_dev, (37, 11))))
    # aval stand-ins match the real containers exactly
    av = C.wire_avals((37, 11))
    assert av["q"].shape == w_host["q"].shape
    assert av["s"].shape == w_host["s"].shape


def test_error_feedback_residual_is_wire_error():
    """residual + quantized image == the compensated gradient, exactly —
    the EF invariant that preserves the sum of updates."""
    rng = np.random.RandomState(2)
    g = rng.randn(300).astype(np.float32) * 1e-2
    q, s = C.quant_i8_block(jnp.asarray(g), block=64)
    image = np.asarray(C.dequant_i8_block(q, s, 300))
    residual = g - image
    np.testing.assert_allclose(residual + image, g, rtol=0, atol=1e-7)


def test_int8_wire_payload_bytes_formula():
    q, f = C.int8_wire_payload_bytes(1000, 4, block=256)
    assert f == 4000
    assert q == 4 * 256 + 4 * 4  # padded int8 body + f32 sidecar
    # sub-block payload: sidecar + padding exceed the saving (ADT311)
    q_small, f_small = C.int8_wire_payload_bytes(8, 4, block=256)
    assert q_small > f_small


# -------------------------------------------------- two-phase all-reduce


def test_int8_block_all_reduce_two_phase():
    """Sum accuracy, SPMD bit-identity, and the EQuARX lowering shape:
    ONE all_to_all (the int8 reduce-scatter) + all_gather — not the
    2(n-1)-hop ppermute ring."""
    from jax.sharding import Mesh, PartitionSpec as P
    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("data",))
    rng = np.random.RandomState(0)
    L = 1000  # not divisible by 8 -> exercises chunk/block padding
    x = rng.randn(8, L).astype(np.float32)
    fn = jax.jit(jax.shard_map(
        lambda xs: C.int8_block_all_reduce(xs.reshape(-1), "data", 8),
        mesh=mesh, in_specs=P("data"), out_specs=P("data"),
        check_vma=False))
    got = np.asarray(fn(x.reshape(8 * L))).reshape(8, L)
    exact = x.sum(axis=0)
    # every replica holds bit-identical reduced values
    assert np.max(np.abs(got - got[0])) == 0.0
    rel = np.abs(got[0] - exact) / (np.abs(exact) + 1e-6)
    assert np.median(rel) < 0.03, np.median(rel)
    hlo = fn.lower(x.reshape(8 * L)).as_text()
    assert "all_to_all" in hlo and "all_gather" in hlo
    assert "collective_permute" not in hlo


# --------------------------------------------------------- training parity


def _mlp_setup(seed=0, din=64, dout=8, batch=32):
    rng = np.random.RandomState(seed)
    params = {"w": jnp.asarray(rng.randn(din, dout) * 0.1, jnp.float32),
              "v": jnp.asarray(rng.randn(dout, dout) * 0.1, jnp.float32)}
    batch_np = {"x": rng.randn(batch, din).astype(np.float32),
                "y": rng.randn(batch, dout).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["w"])
        return jnp.mean((h @ p["v"] - b["y"]) ** 2)

    return loss_fn, params, batch_np


def _train(builder, loss_fn, params, batch, steps=12, fuse=0):
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=builder)
    runner = ad.build(loss_fn, optax.adam(0.05), params, batch)
    runner.init(params)
    if fuse:
        hist = runner.fit([batch] * steps, fuse_steps=fuse)
    else:
        hist = runner.fit([batch] * steps)
    return ([float(m["loss"]) for m in hist], runner)


def test_quantized_ar_parity_and_counters():
    """AllReduce wire_dtype=int8: loss curve stays on the fp32
    trajectory (error feedback), the wire counters report the saving,
    and the dispatch count is unchanged (the codec lives in-graph)."""
    loss_fn, params, batch = _mlp_setup()
    fp, r_fp = _train(S.AllReduce(), loss_fn, params, batch)
    q, r_q = _train(S.AllReduce(wire_dtype="int8"), loss_fn, params, batch)
    counters = tel.counters()
    assert counters["wire.bytes_saved"] > 0
    assert counters["wire.bytes_quantized"] > 0
    assert r_q.distributed_step.dispatches == r_fp.distributed_step.dispatches
    np.testing.assert_allclose(q, fp, rtol=0.25, atol=1e-3)
    assert abs(q[-1] - fp[-1]) < 0.1 * max(abs(fp[-1]), 1e-3) + 1e-3
    # the lowering carries the two-phase quantized collective
    sharded = r_q.remapper.remap_feed(batch)
    hlo = r_q.distributed_step.lowered_text(r_q.state, sharded)
    assert "all_to_all" in hlo and "i8" in hlo


def test_quantized_ar_fused_matches_per_step():
    """Fused k=4 with the quantized AR wire is allclose to the per-step
    quantized loop with k x fewer dispatches (the codec composes with
    the lax.scan engine)."""
    loss_fn, params, batch = _mlp_setup(seed=3)
    per, r_per = _train(S.AllReduce(wire_dtype="int8"), loss_fn, params,
                        batch, steps=8)
    fused, r_fused = _train(S.AllReduce(wire_dtype="int8"), loss_fn,
                            params, batch, steps=8, fuse=4)
    np.testing.assert_allclose(per, fused, rtol=1e-5, atol=1e-6)
    assert r_fused.distributed_step.dispatches == \
        r_per.distributed_step.dispatches // 4


def test_quantized_ps_parity_per_step_and_fused():
    """Host-PS wire_dtype=int8: values pull as int8+scales (dequant
    in-graph), grads push the same way (dequant at the store boundary);
    the fused engine's in-scan codec emulation matches the per-step
    quantized loop."""
    loss_fn, params, batch = _mlp_setup(seed=5)
    fp, _ = _train(S.PS(), loss_fn, params, batch)
    q, r_q = _train(S.PS(wire_dtype="int8"), loss_fn, params, batch)
    # w (64x8 = 512 el) rides the quantized wire; v (8x8 = 64 el) is
    # sub-block and stays fp32 (the builder's ADT311 gate)
    assert r_q.distributed_step.ps_store.wire_quant == ["w"]
    np.testing.assert_allclose(q, fp, rtol=0.25, atol=1e-3)
    assert abs(q[-1] - fp[-1]) < 0.1 * max(abs(fp[-1]), 1e-3) + 1e-3
    counters = tel.counters()
    assert counters["wire.bytes_quantized"] > 0
    assert counters["wire.bytes_saved"] > 0
    # fused k=4 vs per-step, both quantized
    per, _ = _train(S.PS(wire_dtype="int8"), loss_fn, params, batch,
                    steps=8)
    fused, _ = _train(S.PS(wire_dtype="int8"), loss_fn, params, batch,
                      steps=8, fuse=4)
    np.testing.assert_allclose(per, fused, rtol=1e-4, atol=1e-5)


def test_quantized_ps_eval_and_checkpoint_stay_exact(tmp_path):
    """The store holds exact fp32 (only the wire is lossy): checkpoints
    round-trip bit-exactly and evaluate runs through the wire-form
    snapshot."""
    from autodist_tpu.checkpoint import Saver
    loss_fn, params, batch = _mlp_setup(seed=7)
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(
        strategy_builder=S.PS(wire_dtype="int8"))
    runner = ad.build(loss_fn, optax.adam(0.05), params, batch)
    runner.init(params)
    for _ in range(3):
        runner.run(batch)
    ev = runner.evaluate([batch])
    assert np.isfinite(float(ev["loss"]))
    saver = Saver(directory=str(tmp_path))
    saver.save(runner)
    for _ in range(2):
        runner.run(batch)
    a = runner.gather_params()
    saver.restore(runner)
    for _ in range(2):
        runner.run(batch)
    b = runner.gather_params()
    np.testing.assert_array_equal(np.asarray(a["w"]), np.asarray(b["w"]))


# ------------------------------------------------------- degraded pulls


def test_degraded_pull_dequantizes_last_good_snapshot(monkeypatch):
    """Fault leg: with the owner unreachable, a quantized pull serves the
    LAST fetched values through the same wire codec — the device-side
    dequant of a degraded pull equals the last-good snapshot within the
    codec bound, and past the window the pull still fails loudly."""
    from autodist_tpu.model_item import VarInfo
    from autodist_tpu.parallel.ps import PSStore, PSVarPlan
    from test_faults import _FlakyService

    monkeypatch.setenv("ADT_PS_MAX_LAG", "2")
    infos = {"w": VarInfo(name="w", shape=(32, 16), dtype="float32")}
    plans = {"w": PSVarPlan(var_name="w", destinations=("hostA:CPU:0",),
                            sync=False, wire_dtype="int8")}
    rng = np.random.RandomState(0)
    init = {"w": rng.randn(32, 16).astype(np.float32)}
    owner_svc = _FlakyService()
    owner = PSStore(dict(plans), infos, optax.sgd(0.1))
    owner.init_params(init)
    owner.enable_serving(lambda host: owner_svc, my_host="hostA")
    try:
        worker = PSStore(dict(plans), infos, optax.sgd(0.1))
        worker.init_params(init)
        worker.enable_serving(lambda host: owner_svc, my_host="hostB")
        good = worker.pull()  # healthy fetch primes the cache; wire form
        assert set(good["w"]) == {"q", "s"}
        good_vals = C.dequant_wire_np(good["w"], (32, 16))
        np.testing.assert_allclose(good_vals, init["w"],
                                   atol=np.abs(init["w"]).max() / 127 + 1e-6)
        owner_svc.down = True
        for _ in range(2):  # inside the window: last-good, still wire-form
            vals = worker.pull()
            assert set(vals["w"]) == {"q", "s"}
            np.testing.assert_array_equal(vals["w"]["q"], good["w"]["q"])
            np.testing.assert_array_equal(vals["w"]["s"], good["w"]["s"])
        assert worker.stats["degraded_pulls"] == 2
        with pytest.raises(RuntimeError, match="degraded-serve window"):
            worker.pull()
    finally:
        owner_svc.down = False
        owner.close()


# ------------------------------------------------------------ diagnostics


def _lint(strategy, item, spec):
    from autodist_tpu.analysis import verify
    return list(verify(strategy, item, spec))


def _spec_2x2():
    return ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 4}]})


def _emb_item():
    params = {"emb": jnp.zeros((4096, 64)),
              "w": jnp.zeros((64, 512)),
              "tiny": jnp.zeros((8,))}

    def loss_fn(p, batch):
        e = jnp.take(p["emb"], batch["ids"], axis=0)
        return jnp.mean((e @ p["w"]).sum(-1) + p["tiny"].sum())

    batch = {"ids": np.zeros((32,), np.int32)}
    return ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-3),
                     params=params, example_batch=batch).prepare()


def test_adt310_errors_and_warnings():
    from autodist_tpu.strategy.base import (AllReduceSynchronizer,
                                            GraphConfig, PSSynchronizer,
                                            Strategy, VarConfig)
    item, spec = _emb_item(), _spec_2x2()
    replicas = [d.name_string() for d in spec.devices]

    def plan(**node_kw):
        nodes = [VarConfig(var_name="emb",
                           synchronizer=AllReduceSynchronizer()),
                 VarConfig(var_name="tiny",
                           synchronizer=AllReduceSynchronizer()),
                 VarConfig(var_name="w", **node_kw)]
        return Strategy(node_config=nodes,
                        graph_config=GraphConfig(replicas=replicas))

    # sparse var on the quantized wire: error
    sp = plan(synchronizer=AllReduceSynchronizer())
    sp.find("emb").synchronizer = AllReduceSynchronizer(wire_dtype="int8")
    diags = _lint(sp, item, spec)
    assert any(d.code == "ADT310" and d.severity.name == "ERROR"
               and d.var == "emb" for d in diags), diags
    # compressor + wire codec: error
    both = plan(synchronizer=AllReduceSynchronizer(
        compressor="HorovodCompressor", wire_dtype="int8"))
    diags = _lint(both, item, spec)
    assert any(d.code == "ADT310" and d.severity.name == "ERROR"
               and d.var == "w" for d in diags), diags
    # unknown wire dtype: error
    bad = plan(synchronizer=AllReduceSynchronizer(wire_dtype="int4"))
    assert any(d.code == "ADT310" and d.severity.name == "ERROR"
               for d in _lint(bad, item, spec))
    # partitioned AR: warning (ignored)
    part = plan(partitioner="2,1", part_configs=[
        VarConfig(var_name="w/part_%d" % i,
                  synchronizer=AllReduceSynchronizer(wire_dtype="int8"))
        for i in range(2)])
    diags = _lint(part, item, spec)
    assert any(d.code == "ADT310" and d.severity.name == "WARNING"
               for d in diags), diags
    # proxied PS: warning (no host wire)
    proxy = plan(synchronizer=PSSynchronizer(
        reduction_destination="127.0.0.1:CPU:0", local_replication=True,
        wire_dtype="int8"))
    diags = _lint(proxy, item, spec)
    assert any(d.code == "ADT310" and d.severity.name == "WARNING"
               for d in diags), diags
    # sub-block var: ADT311 warning
    small = plan(synchronizer=AllReduceSynchronizer())
    small.find("tiny").synchronizer = AllReduceSynchronizer(
        wire_dtype="int8")
    diags = _lint(small, item, spec)
    assert any(d.code == "ADT311" and d.var == "tiny" for d in diags), diags
    # clean quantized plan lints with NO errors
    ok = plan(synchronizer=AllReduceSynchronizer(wire_dtype="int8"))
    errs = [d for d in _lint(ok, item, spec)
            if d.severity.name == "ERROR"]
    assert not errs, errs


def test_builder_quantized_plans_lint_clean():
    """The wire_dtype builders gate sparse/integer vars themselves, so
    their plans carry no ADT310 errors (CI lints the same combos)."""
    item, spec = _emb_item(), _spec_2x2()
    for builder in (S.AllReduce(wire_dtype="int8"),
                    S.PS(wire_dtype="int8")):
        strat = builder.build(item, spec)
        errs = [d for d in _lint(strat, item, spec)
                if d.severity.name == "ERROR"]
        assert not errs, (builder, errs)
        # serialization round-trips the wire axis
        from autodist_tpu.strategy.base import Strategy
        clone = Strategy.from_dict(strat.to_dict())
        assert clone.to_dict() == strat.to_dict()
        assert any(
            (getattr(n.synchronizer, "wire_dtype", "fp32") == "int8")
            for n in clone.node_config if n.synchronizer is not None)


def test_search_canon_never_emits_wire_diagnostics():
    """120 random mutations (wire operator included): every materialized
    plan verifies with zero ADT310/311 diagnostics of ANY severity —
    canon keeps the searcher out of the warning space entirely."""
    from autodist_tpu.search.space import PlanSpace
    item, spec = _emb_item(), _spec_2x2()
    space = PlanSpace(item, spec)
    assert space.wire_options["w"] == ("fp32", "int8")
    assert space.wire_options["emb"] == ("fp32",)     # sparse
    assert space.wire_options["tiny"] == ("fp32",)    # sub-block
    rng = random.Random(0)
    plan = space.seeds()[0][1]
    seen_wire_mutation = False
    for _ in range(120):
        out = space.mutate(plan, rng)
        if out is None:
            continue
        plan, desc = out
        seen_wire_mutation |= desc.startswith("wire[")
        strat = space.build(plan)
        assert not [d for d in _lint(strat, item, spec)
                    if d.code in ("ADT310", "ADT311")], (desc, plan)
    assert seen_wire_mutation, "wire operator never fired in 120 draws"


# ------------------------------------------------------- byte accounting


def test_wire_byte_accounting_agrees_across_layers():
    """Satellite: the telemetry counters, the lowering's static
    accounting, the cost model's priced payload, and the ADT5xx measured
    profile agree on the quantized payload within tolerance — scale
    sidecar included everywhere."""
    # large enough that chunk/block padding is negligible next to the
    # payload (w: 512x64, v: 64x64 -> 36864 elements, whole blocks)
    loss_fn, params, batch = _mlp_setup(seed=9, din=512, dout=64, batch=16)
    steps = 6
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(
        strategy_builder=S.AllReduce(wire_dtype="int8"))
    runner = ad.build(loss_fn, optax.adam(0.05), params, batch)
    runner.init(params)
    for _ in range(steps):
        runner.run(batch)
    counters = tel.counters()
    meta = runner.distributed_step.metadata
    per_step_meta = meta["wire_quant_bytes_per_step"]
    assert per_step_meta > 0
    # counters == static accounting, exactly (same formula, same source)
    assert counters["wire.bytes_quantized"] == pytest.approx(
        per_step_meta * steps)
    saved_meta = (meta["wire_fp32_bytes_per_step"] - per_step_meta)
    assert counters["wire.bytes_saved"] == pytest.approx(saved_meta * steps)
    # cost model's priced payload within 30% (per-var sidecars vs the
    # bucket's concatenated payload differ only by block padding)
    from autodist_tpu.simulator.cost_model import CostModel
    item = runner.distributed_step.model_item
    cm = CostModel(item, _spec_2x2())
    priced = sum(cm._int8_payload(item.var_infos[n].num_elements)
                 for n in ("w", "v"))
    assert priced == pytest.approx(per_step_meta, rel=0.3)
    # drift report surfaces the wire section with the reduction factor
    # (read BEFORE the reset below wipes the recorder)
    from autodist_tpu.telemetry import drift as drift_lib
    report = drift_lib.build_report(cm, runner.distributed_step.strategy)
    assert report.wire is not None
    assert report.wire["reduction_x"] > 2.0
    assert "quantized wire" in report.format_table()
    # ADT5xx measured profile prices the int8 payload at true byte width:
    # the quantized program's total collective payload must be far below
    # the fp32 program's (which moves the same gradients at 4 bytes/elem)
    sharded = runner.remapper.remap_feed(batch)
    from autodist_tpu.analysis import hlo as hlo_lib
    sched_q = hlo_lib.collective_schedule(
        runner.distributed_step.lowered_text(runner.state, sharded))
    payload_q = sum(c.payload_bytes for c in sched_q)
    autodist_tpu.reset()
    ad_fp = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    r_fp = ad_fp.build(loss_fn, optax.adam(0.05), params, batch)
    r_fp.init(params)
    sched_fp = hlo_lib.collective_schedule(
        r_fp.distributed_step.lowered_text(r_fp.state,
                                           r_fp.remapper.remap_feed(batch)))
    payload_fp = sum(c.payload_bytes for c in sched_fp)
    assert payload_q < payload_fp / 2.0, (payload_q, payload_fp)


# ------------------------------------------------------------- searcher


def _search_fixture(width=256, batch=16, depth=3):
    """Large FLAT (rank-1) weights, reshaped inside the loss: rank-1
    tensors pass through PowerSGD (ADT308), so the wire contest the
    searcher faces is fp32 vs bf16 vs the blockwise int8 codec — the
    axis under test — rather than low-rank factorization winning
    outright on matrices."""
    params = {"w%d" % i: jnp.zeros((width * width,)) for i in range(depth)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(depth):
            h = jnp.tanh(h @ p["w%d" % i].reshape(width, width))
        return jnp.mean(h ** 2)

    batch_np = {"x": np.zeros((batch, width), np.float32)}
    item = ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1),
                     params=params, example_batch=batch_np).prepare()
    return loss_fn, params, batch_np, item


def test_search_picks_int8_wire_when_bandwidth_bound():
    """Acceptance: under a bandwidth-constrained ResourceSpec the
    searcher selects wire_dtype=int8 for at least one variable with NO
    hand-pinning; on a compute-bound spec it refuses to pay the accuracy
    premium."""
    from autodist_tpu.search.drivers import SearchConfig, run_search
    _loss_fn, _params, _batch, item = _search_fixture()
    # 4 v5e nodes behind 1 Gbps everywhere: strong compute, starved wire
    # -> the 1.15x lossy premium is decisively repaid by the ~3.9x cut
    nodes = [{"address": "10.0.0.%d" % (i + 1), "tpus": 4,
              "chief": i == 0, "network_bandwidth": 1}
             for i in range(4)]
    starved = ResourceSpec.from_dict(
        {"nodes": nodes, "slice": {"type": "v5e", "ici_bandwidth": 1}})
    r = run_search(item, starved, config=SearchConfig(budget=48, seed=0))
    assert r.ok
    wired = [n for n, c in r.plan.choices if c.wire_dtype == "int8"]
    assert wired, "bandwidth-bound search never chose the int8 wire: %s" \
        % r.plan.describe()
    # compute-bound (local CPU devices, default fat-enough wire): the
    # quantized wire's premium is never repaid
    fat = ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True,
                    "cpus": list(range(8))}]})
    r_fat = run_search(item, fat, config=SearchConfig(budget=48, seed=0))
    assert r_fat.ok
    assert not [n for n, c in r_fat.plan.choices
                if c.wire_dtype == "int8"], r_fat.plan.describe()


def test_searched_quantized_plan_trains_end_to_end(monkeypatch):
    """Satellite: a bandwidth-starved search over the test env's OWN
    devices (ICI and the host-PS PCIe wire both constrained) chooses a
    quantized plan, which then compiles and trains through the full
    stack."""
    from autodist_tpu.search.drivers import SearchConfig, run_search
    from autodist_tpu.simulator import cost_model as cm_lib
    width, batch = 256, 16
    loss_fn, params, batch_np, item = _search_fixture(width, batch)
    monkeypatch.setattr(cm_lib, "PCIE_BANDWIDTH_BYTES_S", 1e8)
    local = ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True,
                    "cpus": list(range(8))}],
         "slice": {"ici_bandwidth": 1}})
    r = run_search(item, local, config=SearchConfig(budget=48, seed=0))
    assert r.ok
    wired = [n for n, c in r.plan.choices if c.wire_dtype == "int8"]
    assert wired, r.plan.describe()

    class Pin(S.StrategyBuilder):
        def build(self, model_item, resource_spec):
            return r.strategy

    autodist_tpu.reset()
    rng = np.random.RandomState(0)
    live_params = {k: jnp.asarray(rng.randn(width * width) * 0.05,
                                  jnp.float32) for k in params}
    live_batch = {"x": rng.randn(batch, width).astype(np.float32)}
    ad = autodist_tpu.AutoDist(strategy_builder=Pin())
    runner = ad.build(loss_fn, optax.sgd(0.1), live_params, live_batch)
    runner.init(live_params)
    losses = [float(runner.run(live_batch)["loss"]) for _ in range(5)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_from_strategy_converts_int8_compressor_to_wire_axis():
    """A zoo strategy built with the (still-supported) Int8CompressorEF
    converts into a wire_dtype=int8 seed instead of silently losing its
    ~4x compression (the compressor axis no longer offers int8)."""
    from autodist_tpu.search.space import PlanSpace
    item, spec = _emb_item(), _spec_2x2()
    space = PlanSpace(item, spec)
    strat = S.AllReduce(compressor="Int8CompressorEF").build(item, spec)
    plan = space.from_strategy(strat)
    assert plan is not None
    cm = plan.choice_map()
    assert cm["w"].wire_dtype == "int8"
    assert cm["w"].compressor == "NoneCompressor"


def test_cost_model_does_not_discount_ignored_wire_paths():
    """wire_dtype=int8 on a proxied PS var (no host wire exists — the
    runtime psums full-width) must NOT be priced at quantized width:
    identical estimate to the fp32 spelling."""
    from autodist_tpu.simulator.cost_model import CostModel
    from autodist_tpu.strategy.base import (GraphConfig, PSSynchronizer,
                                            Strategy, VarConfig)
    item, spec = _emb_item(), _spec_2x2()
    replicas = [d.name_string() for d in spec.devices]

    def proxy_plan(wire):
        return Strategy(node_config=[
            VarConfig(var_name=n, synchronizer=PSSynchronizer(
                reduction_destination="127.0.0.1:CPU:0",
                local_replication=True, wire_dtype=wire))
            for n in ("emb", "w", "tiny")],
            graph_config=GraphConfig(replicas=replicas))

    cm = CostModel(item, spec)
    est_q = cm.estimate(proxy_plan("int8"))
    est_fp = cm.estimate(proxy_plan("fp32"))
    assert est_q.allreduce_s == pytest.approx(est_fp.allreduce_s)
    assert est_q.step_time_s == pytest.approx(est_fp.step_time_s)


def test_from_strategy_roundtrips_wire_axis():
    from autodist_tpu.search.space import PlanSpace
    item, spec = _emb_item(), _spec_2x2()
    space = PlanSpace(item, spec)
    strat = S.AllReduce(wire_dtype="int8").build(item, spec)
    plan = space.from_strategy(strat)
    assert plan is not None
    cm = plan.choice_map()
    assert cm["w"].wire_dtype == "int8"
    assert cm["emb"].wire_dtype == "fp32"   # sparse: canon strips it
    assert cm["tiny"].wire_dtype == "fp32"  # sub-block: canon strips it
    assert "int8w=" in plan.describe()
