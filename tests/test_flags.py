"""Benchmark flag system (the reference's vendored TF-official
``utils/flags`` package, reference ``examples/benchmark/utils/flags/``)."""
import pytest

from examples.benchmark.utils import flags


@pytest.fixture(autouse=True)
def _fresh():
    flags.reset()
    yield
    flags.reset()


def test_define_parse_and_read():
    flags.DEFINE_integer("train_batch_size", 8, "Total batch size.")
    flags.DEFINE_string("strategy", "Parallax", "Strategy builder name.")
    flags.DEFINE_boolean(name="proxy", default=True, help="proxy toggle")
    flags.DEFINE_float("lr", 1e-3, "learning rate")
    flags.DEFINE_enum("dtype", "bf16", ["bf16", "fp32"], "compute dtype")
    flags.parse(["--train_batch_size", "64", "--no-proxy",
                 "--dtype", "fp32"])
    assert flags.FLAGS.train_batch_size == 64
    assert flags.FLAGS.strategy == "Parallax"      # default
    assert flags.FLAGS.proxy is False              # BooleanOptionalAction
    assert flags.FLAGS.lr == 1e-3
    assert flags.FLAGS.dtype == "fp32"
    assert flags.flags_dict()["train_batch_size"] == 64


def test_read_before_parse_raises():
    flags.DEFINE_integer("n", 1, "")
    with pytest.raises(AttributeError, match="before flags.parse"):
        flags.FLAGS.n


def test_unknown_flag_and_redefine():
    flags.DEFINE_integer("n", 1, "")
    flags.parse([])
    with pytest.raises(AttributeError, match="unknown flag"):
        flags.FLAGS.missing
    with pytest.raises(ValueError, match="already defined"):
        flags.DEFINE_integer("n", 2, "")


def test_grouped_defines_and_env_override(monkeypatch):
    flags.define_base()
    flags.define_performance()
    flags.define_benchmark()
    monkeypatch.setenv("ADT_FLAG_BATCH_SIZE", "128")
    monkeypatch.setenv("ADT_FLAG_USE_SYNTHETIC_DATA", "0")
    flags.parse([])
    assert flags.FLAGS.batch_size == 128          # env beats default
    assert flags.FLAGS.use_synthetic_data is False
    assert flags.FLAGS.dtype == "bf16"
    flags.reset()
    flags.define_base()
    monkeypatch.setenv("ADT_FLAG_BATCH_SIZE", "128")
    flags.parse(["--batch_size", "256"])          # CLI beats env
    assert flags.FLAGS.batch_size == 256


def test_enum_rejects_bad_choice():
    flags.define_performance()
    with pytest.raises(SystemExit):
        flags.parse(["--dtype", "int8"])


def test_env_overrides_are_validated(monkeypatch):
    """Env overrides get the SAME validation as CLI values: argparse only
    checks explicit args, so parse() must validate enum choices and
    boolean spellings itself."""
    flags.define_performance()
    monkeypatch.setenv("ADT_FLAG_DTYPE", "int8")
    with pytest.raises(SystemExit, match="not in choices"):
        flags.parse([])
    monkeypatch.setenv("ADT_FLAG_DTYPE", "fp32")
    monkeypatch.setenv("ADT_FLAG_USE_SYNTHETIC_DATA", "FALSE")
    flags.parse([])
    assert flags.FLAGS.use_synthetic_data is False  # uppercase spelling
    monkeypatch.setenv("ADT_FLAG_USE_SYNTHETIC_DATA", "maybe")
    with pytest.raises(SystemExit, match="not a boolean"):
        flags.parse([])
