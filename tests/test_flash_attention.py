"""Pallas flash attention vs. the XLA reference implementation.

Runs in interpret mode on the CPU backend (conftest forces cpu); the same
kernels compile for real on TPU. Mirrors the reference's numeric-assertion
style (tests/integration/cases/c0.py:92-121): exactness is checked against
an independently computed ground truth, not just for finiteness.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.ops.attention import reference_attention
from autodist_tpu.ops.flash_attention import flash_attention, make_flash_attn_fn


def _rand(shape, dtype=jnp.float32, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), dtype)


def _mask(s, causal):
    return jnp.tril(jnp.ones((s, s), jnp.bool_))[None, None] if causal else None


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 128, 2, 32), (2, 256, 4, 64)])
def test_forward_matches_reference(causal, shape):
    q, k, v = (_rand(shape, seed=i) for i in range(3))
    out = flash_attention(q, k, v, causal)
    ref = reference_attention(q, k, v, _mask(shape[1], causal))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    shape = (1, 256, 2, 32)
    q, k, v = (_rand(shape, seed=i) for i in range(3))
    mask = _mask(shape[1], causal)

    def loss_flash(q, k, v):
        return jnp.mean(flash_attention(q, k, v, causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.mean(reference_attention(q, k, v, mask) ** 2)

    gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-4)


def test_uneven_block_sizes():
    # Sq != Sk and blocks smaller than the 128 default (64-divisible seqs)
    q = _rand((1, 64, 2, 32), seed=0)
    k = _rand((1, 192, 2, 32), seed=1)
    v = _rand((1, 192, 2, 32), seed=2)
    out = flash_attention(q, k, v, causal=False)
    ref = reference_attention(q, k, v, None)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_bfloat16_forward():
    shape = (1, 128, 2, 32)
    q, k, v = (_rand(shape, jnp.bfloat16, seed=i) for i in range(3))
    out = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32), _mask(shape[1], True))
    np.testing.assert_allclose(out.astype(jnp.float32), ref, atol=3e-2)


def test_untileable_seq_falls_back():
    # 100 has no power-of-two divisor >= 8 above 4 -> XLA reference fallback,
    # still differentiable
    shape = (1, 100, 2, 16)
    q, k, v = (_rand(shape, seed=i) for i in range(3))
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, _mask(shape[1], True))
    np.testing.assert_allclose(out, ref, atol=1e-5)
    g = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, True) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_attn_fn_adapter_in_model_layer():
    from autodist_tpu.models.layers import MultiHeadAttention
    attn = make_flash_attn_fn(causal=True)
    layer = MultiHeadAttention(num_heads=2, head_dim=16, attn_fn=attn)
    x = _rand((2, 128, 32))
    params = layer.init(jax.random.PRNGKey(0), x)
    out = layer.apply(params, x)
    assert out.shape == x.shape
    # same layer with the XLA mask path must agree
    ref_layer = MultiHeadAttention(num_heads=2, head_dim=16)
    ref = ref_layer.apply(params, x, jnp.tril(
        jnp.ones((1, 1, 128, 128), jnp.bool_)))
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_flash_kind_registered():
    from autodist_tpu.ops.attention import make_attn_fn
    fn = make_attn_fn("flash", causal=True)
    shape = (1, 128, 2, 32)
    q, k, v = (_rand(shape, seed=i) for i in range(3))
    out = fn(q, k, v)
    ref = reference_attention(q, k, v, _mask(shape[1], True))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
