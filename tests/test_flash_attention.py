"""Pallas flash attention vs. the XLA reference implementation.

Runs in interpret mode on the CPU backend (conftest forces cpu); the same
kernels compile for real on TPU. Mirrors the reference's numeric-assertion
style (tests/integration/cases/c0.py:92-121): exactness is checked against
an independently computed ground truth, not just for finiteness.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.ops.attention import reference_attention
from autodist_tpu.ops.flash_attention import flash_attention, make_flash_attn_fn


def _rand(shape, dtype=jnp.float32, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), dtype)


def _mask(s, causal):
    return jnp.tril(jnp.ones((s, s), jnp.bool_))[None, None] if causal else None


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(1, 128, 2, 32), (2, 256, 4, 64)])
def test_forward_matches_reference(causal, shape):
    q, k, v = (_rand(shape, seed=i) for i in range(3))
    out = flash_attention(q, k, v, causal)
    ref = reference_attention(q, k, v, _mask(shape[1], causal))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    shape = (1, 256, 2, 32)
    q, k, v = (_rand(shape, seed=i) for i in range(3))
    mask = _mask(shape[1], causal)

    def loss_flash(q, k, v):
        return jnp.mean(flash_attention(q, k, v, causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.mean(reference_attention(q, k, v, mask) ** 2)

    gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-4)


def test_uneven_block_sizes():
    # Sq != Sk and blocks smaller than the 128 default (64-divisible seqs)
    q = _rand((1, 64, 2, 32), seed=0)
    k = _rand((1, 192, 2, 32), seed=1)
    v = _rand((1, 192, 2, 32), seed=2)
    out = flash_attention(q, k, v, causal=False)
    ref = reference_attention(q, k, v, None)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_bfloat16_forward():
    shape = (1, 128, 2, 32)
    q, k, v = (_rand(shape, jnp.bfloat16, seed=i) for i in range(3))
    out = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32), _mask(shape[1], True))
    # atol 3e-2: bf16 has 8 mantissa bits (~2-3 decimal digits); outputs
    # are O(1) softmax-weighted averages, so one-ulp rounding is ~4e-3
    # and the row-sum accumulation ~1e-2 — 3e-2 holds across seeds
    np.testing.assert_allclose(out.astype(jnp.float32), ref, atol=3e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_bfloat16_grads_match_f32_reference(causal):
    """bf16 grads vs the f32 XLA reference — the correctness baseline
    the analyzer's future attention-impl axis (and today's bf16 compute
    tier, which runs this kernel in half precision) needs. Tolerances:
    bf16 carries 8 mantissa bits, so single ops round at ~4e-3 relative;
    the backward pass chains two matmuls and a softmax rescale per
    block, compounding to ~1e-2 relative on O(1) gradients — atol/rtol
    5e-2 gives ~4x margin over the observed worst case without masking a
    wrong-formula bug (any algebraic error is O(1), not O(1e-2))."""
    shape = (1, 256, 2, 32)
    q, k, v = (_rand(shape, jnp.bfloat16, seed=i) for i in range(3))
    mask = _mask(shape[1], causal)

    def loss_flash(q, k, v):
        return jnp.mean(flash_attention(q, k, v, causal)
                        .astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.mean(reference_attention(q.astype(jnp.float32),
                                            k.astype(jnp.float32),
                                            v.astype(jnp.float32),
                                            mask) ** 2)

    gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, (0, 1, 2))(
        *(x.astype(jnp.float32) for x in (q, k, v)))
    for a, b in zip(gf, gr):
        # grads w.r.t. bf16 inputs come out bf16 — compare in f32
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(a.astype(jnp.float32), b,
                                   atol=5e-2, rtol=5e-2)


def test_untileable_seq_falls_back():
    # 100 has no power-of-two divisor >= 8 above 4 -> XLA reference fallback,
    # still differentiable
    shape = (1, 100, 2, 16)
    q, k, v = (_rand(shape, seed=i) for i in range(3))
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, _mask(shape[1], True))
    np.testing.assert_allclose(out, ref, atol=1e-5)
    g = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, True) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_attn_fn_adapter_in_model_layer():
    from autodist_tpu.models.layers import MultiHeadAttention
    attn = make_flash_attn_fn(causal=True)
    layer = MultiHeadAttention(num_heads=2, head_dim=16, attn_fn=attn)
    x = _rand((2, 128, 32))
    params = layer.init(jax.random.PRNGKey(0), x)
    out = layer.apply(params, x)
    assert out.shape == x.shape
    # same layer with the XLA mask path must agree
    ref_layer = MultiHeadAttention(num_heads=2, head_dim=16)
    ref = ref_layer.apply(params, x, jnp.tril(
        jnp.ones((1, 1, 128, 128), jnp.bool_)))
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_flash_kind_registered():
    from autodist_tpu.ops.attention import make_attn_fn
    fn = make_attn_fn("flash", causal=True)
    shape = (1, 128, 2, 32)
    q, k, v = (_rand(shape, seed=i) for i in range(3))
    out = fn(q, k, v)
    ref = reference_attention(q, k, v, _mask(shape[1], True))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


# -------------------------------------------------------- segments / masks


def _seg_mask(q_seg, kv_seg):
    return (np.asarray(q_seg)[:, :, None]
            == np.asarray(kv_seg)[:, None, :])[:, None]


@pytest.mark.parametrize("causal", [False, True])
def test_padding_mask_matches_reference(causal):
    """BERT-style key padding as segment ids: fwd + grads equal the XLA
    reference under the equivalent q_seg==kv_seg mask."""
    B, S = 2, 256
    shape = (B, S, 2, 32)
    q, k, v = (_rand(shape, seed=i) for i in range(3))
    rng = np.random.RandomState(7)
    lengths = rng.randint(S // 4, S, (B,))
    seg = (np.arange(S)[None, :] < lengths[:, None]).astype(np.int32)
    mask = jnp.asarray(_seg_mask(seg, seg))
    if causal:
        mask = jnp.logical_and(mask, _mask(S, True))

    out = flash_attention(q, k, v, causal, segment_ids=jnp.asarray(seg))
    ref = reference_attention(q, k, v, mask)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal, segment_ids=jnp.asarray(seg))
        return jnp.mean(o ** 2)

    def loss_ref(q, k, v):
        return jnp.mean(reference_attention(q, k, v, mask) ** 2)

    gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-4)


def test_packed_sequences_do_not_cross_attend():
    """Two packed documents in one row: tokens never attend across the
    segment boundary (the sequence-packing use, beyond padding)."""
    B, S = 1, 256
    shape = (B, S, 2, 32)
    q, k, v = (_rand(shape, seed=i) for i in range(3))
    seg = np.zeros((B, S), np.int32)
    seg[:, S // 2:] = 1  # two docs, split mid-sequence
    out = flash_attention(q, k, v, False, segment_ids=jnp.asarray(seg))
    ref = reference_attention(q, k, v, jnp.asarray(_seg_mask(seg, seg)))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)
    # doc-0 queries must be independent of doc-1 keys/values entirely
    k2 = k.at[:, S // 2:].set(0.0)
    v2 = v.at[:, S // 2:].set(0.0)
    out2 = flash_attention(q, k2, v2, False, segment_ids=jnp.asarray(seg))
    np.testing.assert_allclose(out[:, :S // 2], out2[:, :S // 2],
                               atol=1e-6, rtol=1e-6)


def test_attn_fn_adapter_accepts_padding_mask():
    """The layers' attn_fn slot: a [B, 1, 1, S] boolean key-padding mask
    routes through the segment path and matches the reference."""
    B, S = 2, 128
    shape = (B, S, 2, 32)
    q, k, v = (_rand(shape, seed=i) for i in range(3))
    valid = np.ones((B, S), np.int32)
    valid[:, S - 32:] = 0
    mask4 = jnp.asarray(valid, jnp.bool_)[:, None, None, :]
    attn = make_flash_attn_fn(causal=False)
    out = attn(q, k, v, mask4)
    ref = reference_attention(q, k, v, jnp.asarray(_seg_mask(valid, valid)))
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_empty_query_rows_emit_zeros_with_zero_grads():
    """A (q_seg, kv_seg) pair where some query segment matches NO key: the
    empty rows output zeros (not a garbage average of values) and their
    gradients vanish — guarded in both the forward and backward kernels
    (advisor finding, flash_attention.py empty-row case)."""
    B, S = 1, 128
    shape = (B, S, 2, 32)
    q, k, v = (_rand(shape, seed=i) for i in range(3))
    q_seg = np.zeros((B, S), np.int32)
    q_seg[:, S // 2:] = 7           # segment 7 appears in NO key
    kv_seg = np.zeros((B, S), np.int32)
    out = flash_attention(q, k, v, False,
                          segment_ids=(jnp.asarray(q_seg),
                                       jnp.asarray(kv_seg)))
    # live rows match the reference; empty rows are exactly zero
    ref = reference_attention(q, k, v, jnp.asarray(_seg_mask(q_seg, kv_seg)))
    np.testing.assert_allclose(out[:, :S // 2], ref[:, :S // 2],
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_array_equal(np.asarray(out[:, S // 2:]), 0.0)

    def loss(q, k, v):
        o = flash_attention(q, k, v, False,
                            segment_ids=(jnp.asarray(q_seg),
                                         jnp.asarray(kv_seg)))
        return jnp.sum(o * o)
    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for g in (dq, dk, dv):
        assert np.all(np.isfinite(np.asarray(g)))
    # empty query rows contribute nothing anywhere
    np.testing.assert_array_equal(np.asarray(dq[:, S // 2:]), 0.0)
