"""Native coordination-service tests: build the C++ binary, drive it over
TCP from multiple threads (barriers, staleness windows, heartbeats)."""
import threading
import time

import pytest

from autodist_tpu.runtime.coordination import (CoordinationClient,
                                               CoordinationServer)

PORT = 15913


@pytest.fixture(scope="module")
def server():
    srv = CoordinationServer(port=PORT)
    srv.start()
    yield srv
    srv.stop()


def _client(**kw):
    return CoordinationClient("127.0.0.1", PORT, **kw)


def test_ping_kv_counter(server):
    c = _client()
    assert c.ping()
    c.put("strategy_id", "20260729T0001 with spaces")
    assert c.get("strategy_id") == "20260729T0001 with spaces"
    assert c.get("missing") is None
    assert c.incr("n") == 1
    assert c.incr("n") == 2
    c.close()


def test_barrier_releases_all(server):
    results = []

    def worker(i):
        c = _client()
        c.barrier("b1", 3)
        results.append(i)
        c.close()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    assert results == []  # nobody through until the third arrives
    c = _client()
    c.barrier("b1", 3)
    for t in threads:
        t.join(timeout=5)
    assert sorted(results) == [0, 1]
    c.close()


def test_staleness_window_blocks_fast_worker(server):
    c_fast, c_slow = _client(), _client()
    c_slow.report_step("slow", 0)
    c_fast.report_step("fast", 3)
    assert c_fast.min_step() == 0
    # fast worker at step 3 with staleness 1 must block until slow reaches 2
    released = threading.Event()

    def fast_wait():
        c = _client()
        c.wait_staleness(3, 1)
        released.set()
        c.close()

    t = threading.Thread(target=fast_wait)
    t.start()
    time.sleep(0.2)
    assert not released.is_set()
    c_slow.report_step("slow", 2)
    t.join(timeout=5)
    assert released.is_set()
    # staleness 0 == lockstep: step equal to min passes immediately
    c_fast.wait_staleness(2, 0)
    c_fast.close()
    c_slow.close()


def test_heartbeat_dead_detection(server):
    c = _client()
    c.heartbeat("w0")
    assert c.dead_workers(5.0) == []
    time.sleep(0.3)
    dead = c.dead_workers(0.1)
    assert "w0" in dead
    c.close()


def test_coordinator_watchdog_fail_fast(tmp_path):
    """A worker that stops heartbeating kills the chief process (the
    reference's fail-fast supervision, coordinator.py:98-110). Run in a
    subprocess because the watchdog aborts via os._exit(1)."""
    import os
    import socket
    import subprocess
    import sys
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "watchdog_host.py"
    script.write_text("""
import sys, time
PORT = %d
from autodist_tpu.runtime.coordination import CoordinationServer, CoordinationClient
from autodist_tpu.runtime.coordinator import Coordinator
from autodist_tpu.runtime.cluster import Cluster
from autodist_tpu.resource_spec import ResourceSpec

srv = CoordinationServer(PORT)
srv.start()
client = CoordinationClient("127.0.0.1", PORT)
client.heartbeat("w1")

class _S:
    id = "watchdog-test"

spec = ResourceSpec.from_dict(
    {"nodes": [{"address": "127.0.0.1", "chief": True, "cpus": [0]}]})
coord = Coordinator(_S(), Cluster(spec, coordsvc_port=PORT),
                    heartbeat_timeout=1.0)
coord.start_watchdog()
print("WATCHDOG_UP", flush=True)
time.sleep(12)  # w1 never heartbeats again; the watchdog must abort us
print("STILL_ALIVE", flush=True)
""" % port)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    try:
        proc = subprocess.run([sys.executable, str(script)], env=env,
                              capture_output=True, text=True, timeout=120)
    finally:
        # os._exit(1) (and any failure) orphans the service subprocess
        subprocess.run(["pkill", "-f", "coordination_service %d" % port],
                       check=False)
    assert "WATCHDOG_UP" in proc.stdout
    assert "STILL_ALIVE" not in proc.stdout, proc.stdout
    assert proc.returncode == 1


def test_queue_cap_rejects_then_recovers(server):
    """QPUSH past the server-side cap is rejected loudly (a queue nobody
    drains — dead owner — must not eat the host's memory), and draining
    makes room again."""
    c = _client()
    for _ in range(4096):
        c.qpush("capq", b"x")
    with pytest.raises(RuntimeError, match="queue full"):
        c.qpush("capq", b"y")
    assert c.qlen("capq") == 4096
    assert c.qpop("capq") == b"x"
    c.qpush("capq", b"y")  # room again
    c.close()


def test_goodbye_deregisters(server):
    """GOODBYE removes the worker from the DEADLIST universe (a finished
    worker must not age into a false death) and releases its hold on the
    staleness window."""
    c = _client()
    c.heartbeat("w7")
    c.report_step("w7", -100)  # uniquely below any other test's steps
    time.sleep(0.3)
    assert "w7" in c.dead_workers(0.1)
    assert c.min_step() == -100
    c.goodbye("w7")
    assert "w7" not in c.dead_workers(0.1)
    assert c.min_step() != -100  # no longer bounds the staleness window
    c.close()


def test_binary_blob_roundtrip_and_text_interop(server):
    """The binary frames (BPUTB/BGETB/QPUSHB/QPOPB) carry RAW payloads;
    storage is raw for both wire forms, so text b64 commands interoperate
    on the same keys/queues."""
    import base64
    c = _client()
    payload = bytes(range(256)) * 64 + b"\n\r binary-hostile \x00\xff"
    c.bput("bin/key", 7, payload)            # binary publish
    got = c.bget("bin/key")                  # binary fetch
    assert got == (7, payload)
    # text fetch of the binary-written blob: b64 at the boundary
    resp = c._cmd("BGET bin/key")
    assert resp.startswith("BVAL 7 ")
    assert base64.b64decode(resp.split(" ", 2)[2]) == payload
    # text publish, binary fetch
    c._cmd("BPUT bin/key2 3 %s" % base64.b64encode(payload).decode())
    assert c.bget("bin/key2") == (3, payload)
    # queues: binary push, binary pop; then text pop sees raw->b64
    c.qpush("bin/q", payload)
    c.qpush("bin/q", payload)
    assert c.qpop("bin/q") == payload
    resp = c._cmd("QPOP bin/q")
    assert base64.b64decode(resp[5:]) == payload
    # empty payload edge
    c.bput("bin/empty", 1, b"")
    assert c.bget("bin/empty") == (1, b"")
    c.close()


def test_rejected_blob_frame_does_not_desync(server):
    """A BPUTB whose declared length exceeds the service cap is rejected,
    but the payload bytes the client already sent must be DRAINED — not
    parsed as command lines (a gradient blob containing b"\\nSHUTDOWN\\n"
    must not stop the service). Advisor finding, coordination_service.cc."""
    c = _client()
    # hand-craft an oversized frame: declare cap+16 bytes, send a small
    # hostile payload that would read as commands if the parser desynced.
    cap = CoordinationClient.MAX_BLOB_BYTES
    hostile = b"\nSHUTDOWN\nPUT pwned yes\n"
    c._sock.sendall(b"BPUTB bad/key 1 %d\n" % (cap + 16) + hostile)
    assert c._recv_line().startswith("ERR bad length")
    # the service is now draining cap+16 bytes; finish the declared frame
    # so the connection resyncs (chunked, to exercise partial drains)
    remaining = cap + 16 - len(hostile)
    chunk = b"\x00" * (1 << 20)
    while remaining > 0:
        n = min(remaining, len(chunk))
        c._sock.sendall(chunk[:n])
        remaining -= n
    # after the drain the same connection parses frames normally again
    assert c.ping()
    # ...and the hostile payload neither stopped the service nor wrote keys
    assert c.get("pwned") is None
    c.close()

    c2 = _client()
    assert c2.ping()  # service alive for new connections too
    c2.close()


def test_negative_blob_length_closes_connection(server):
    """A negative declared length is unrecoverable (the payload boundary is
    unknowable) — the service replies ERR and closes that connection."""
    import socket as _socket
    c = _client()
    c._sock.sendall(b"QPUSHB q/neg -5\ngarbage")
    assert c._recv_line().startswith("ERR bad length")
    # connection is closed by the server: next read returns EOF
    c._sock.settimeout(5.0)
    assert c._sock.recv(1) == b""
    # other connections unaffected
    c2 = _client()
    assert c2.ping()
    c2.close()


def test_client_rejects_oversized_payload_before_send(server):
    """Client-side cap validation: an oversized payload raises locally
    without any bytes hitting the wire."""
    c = _client()
    big = _FakeBytes(CoordinationClient.MAX_BLOB_BYTES + 1)
    with pytest.raises(ValueError, match="exceeds the service cap"):
        c._cmd_raw("BPUTB k 1 %d" % len(big), big)
    assert c.ping()  # connection untouched
    c.close()


class _FakeBytes(bytes):
    """len()-only stand-in: allocating 2 GB in the test is pointless."""
    def __new__(cls, n):
        obj = super().__new__(cls)
        obj._n = n
        return obj

    def __len__(self):
        return self._n


def test_unparseable_blob_length_closes_connection(server):
    """atol('x16') == 0 would accept a zero-byte frame and parse the real
    payload as command lines; strict parsing must reject and close."""
    c = _client()
    c._sock.sendall(b"BPUTB k 1 x16\n" + b"\nSHUTDOWN\nPUT pwned2 yes\n"[:16])
    assert c._recv_line().startswith("ERR bad length")
    c._sock.settimeout(5.0)
    assert c._sock.recv(1) == b""  # closed: payload never parsed
    c2 = _client()
    assert c2.ping()                # service alive, nothing executed
    assert c2.get("pwned2") is None
    c2.close()


def test_whitespace_keys_rejected_client_side(server):
    """A key with whitespace would shift the line-protocol arity — and on
    the binary commands the payload would already be in flight when the
    server takes the unknown-command branch, re-opening the desync. The
    client rejects such names before any bytes hit the wire."""
    c = _client()
    for call in (lambda: c.bput("my weight", 1, b"x"),
                 lambda: c.qpush("q one", b"x"),
                 lambda: c.put("a key", "v"),
                 lambda: c.get("a\tkey"),
                 lambda: c.heartbeat("worker one"),
                 lambda: c.qpush("", b"x")):
        with pytest.raises(ValueError, match="no\\s+whitespace|non-empty"):
            call()
    assert c.ping()  # connection untouched by the rejected calls
    c.close()
