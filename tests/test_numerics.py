"""ADT6xx numerics-safety analyzer (analysis/numerics.py + verify_numerics).

Four layers, matching the analyzer's design:

1. the mutation matrix: >= 10 seeded numerics defects, every one caught
   through BOTH the API (``numerics.lint_text`` / ``rules.verify_numerics``)
   and the CLI (``--programs`` dump mode, ``--strategy-json``, and the
   example mode's ``--numerics``/``--compute-dtype`` flags);
2. the clean matrix: example x builder x {f32, bf16} plans lint with zero
   ADT60x errors (the managed tier is clean BY CONSTRUCTION);
3. the lowering: bf16-compute programs from real builds pass the
   dtype-flow pass through ``Runner.lint_lowered``, the master params
   stay f32, and a bf16 run tracks the f32 loss curve;
4. the search space: canon never materializes a plan with ADT60x findings
   at ANY severity (the ADT312/313-style by-construction guarantee).
"""
import json

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.analysis import cli, numerics
from autodist_tpu.analysis.diagnostics import Severity
from autodist_tpu.analysis.rules import verify, verify_numerics
from autodist_tpu.model_item import ModelItem


def codes(diags):
    return sorted(d.code for d in diags)


# --------------------------------------------------------------- fixtures

_HEADER = ('module @jit_step attributes {mhlo.num_partitions = 4 : i32, '
           'mhlo.num_replicas = 1 : i32} {')
_GROUPS = ('replica_groups = dense<[[0, 1, 2, 3]]> : tensor<1x4xi64>, '
           'use_global_device_ids')


def _all_reduce(val, num, ty, handle=1):
    """A region-bearing stablehlo.all_reduce statement over ``ty``."""
    scalar = ty.split("x")[-1]
    return """    %%%d = "stablehlo.all_reduce"(%s) <{channel_handle = #stablehlo.channel_handle<handle = %d, type = 1>, %s}> ({
    ^bb0(%%lhs: tensor<%s>, %%rhs: tensor<%s>):
      %%s = stablehlo.add %%lhs, %%rhs : tensor<%s>
      stablehlo.return %%s : tensor<%s>
    }) : (tensor<%s>) -> tensor<%s>""" % (
        num, val, handle, _GROUPS, scalar, scalar, scalar, scalar, ty, ty)


def _program(body, args="%arg0: tensor<8x4xf32>", results="tensor<f32>",
             ret="%9 : tensor<f32>"):
    return "%s\n  func.func public @main(%s) -> (%s) {\n%s\n    return %s\n  }\n}\n" % (
        _HEADER, args, results, body, ret)


# The clean shape the REAL bf16 lowering emits: params arrive f32, a COPY
# is cast down for compute, the gradient is cast back to f32 BEFORE the
# accumulating collective, and the loss is f32. Zero ADT60x findings.
CLEAN_BF16 = _program(
    "\n".join([
        "    %0 = stablehlo.convert %arg0 : (tensor<8x4xf32>) -> tensor<8x4xbf16>",
        "    %1 = stablehlo.dot_general %0, %0, contracting_dims = [1] x [1] : (tensor<8x4xbf16>, tensor<8x4xbf16>) -> tensor<8x8xbf16>",
        "    %2 = stablehlo.convert %1 : (tensor<8x8xbf16>) -> tensor<8x8xf32>",
        _all_reduce("%2", 3, "8x8xf32"),
        "    %cst = stablehlo.constant dense<0.0> : tensor<f32>",
        "    %9 = stablehlo.reduce(%3 init: %cst) applies stablehlo.add across dimensions = [0, 1] : (tensor<8x8xf32>, tensor<f32>) -> tensor<f32>",
    ]))

# Every text-level mutation: (name, program text, code, severity). Each is
# CLEAN_BF16 with exactly one numerics defect injected.
TEXT_MUTATIONS = [
    # 1. gradient psum in bf16 — the accumulator rounds every hop
    ("bf16_psum", _program("\n".join([
        "    %0 = stablehlo.convert %arg0 : (tensor<8x4xf32>) -> tensor<8x4xbf16>",
        _all_reduce("%0", 1, "8x4xbf16"),
        "    %2 = stablehlo.convert %1 : (tensor<8x4xbf16>) -> tensor<8x4xf32>",
        "    %cst = stablehlo.constant dense<0.0> : tensor<f32>",
        "    %9 = stablehlo.reduce(%2 init: %cst) applies stablehlo.add across dimensions = [0, 1] : (tensor<8x4xf32>, tensor<f32>) -> tensor<f32>",
    ])), "ADT601", Severity.ERROR),
    # 2. f16 variant of the same defect (the table covers both halves)
    ("f16_psum", _program("\n".join([
        "    %0 = stablehlo.convert %arg0 : (tensor<8x4xf32>) -> tensor<8x4xf16>",
        _all_reduce("%0", 1, "8x4xf16"),
        "    %2 = stablehlo.convert %1 : (tensor<8x4xf16>) -> tensor<8x4xf32>",
        "    %cst = stablehlo.constant dense<0.0> : tensor<f32>",
        "    %9 = stablehlo.reduce(%2 init: %cst) applies stablehlo.add across dimensions = [0, 1] : (tensor<8x4xf32>, tensor<f32>) -> tensor<f32>",
    ])), "ADT601", Severity.ERROR),
    # 3. reduce_scatter in bf16 — the ZeRO wire without the f32 cast-up
    ("bf16_reduce_scatter", _program("\n".join([
        "    %0 = stablehlo.convert %arg0 : (tensor<8x4xf32>) -> tensor<8x4xbf16>",
        ('    %1 = "stablehlo.reduce_scatter"(%0) <{channel_handle = '
         '#stablehlo.channel_handle<handle = 1, type = 1>, '
         'scatter_dimension = 0 : i64, ' + _GROUPS + '}> ({'),
        "    ^bb0(%lhs: tensor<bf16>, %rhs: tensor<bf16>):",
        "      %s = stablehlo.add %lhs, %rhs : tensor<bf16>",
        "      stablehlo.return %s : tensor<bf16>",
        "    }) : (tensor<8x4xbf16>) -> tensor<2x4xbf16>",
        "    %2 = stablehlo.convert %1 : (tensor<2x4xbf16>) -> tensor<2x4xf32>",
        "    %cst = stablehlo.constant dense<0.0> : tensor<f32>",
        "    %9 = stablehlo.reduce(%2 init: %cst) applies stablehlo.add across dimensions = [0, 1] : (tensor<2x4xf32>, tensor<f32>) -> tensor<f32>",
    ])), "ADT601", Severity.ERROR),
    # 4. scalar bf16 cross-replica sum: the loss pmean on rounded values
    ("bf16_scalar_loss_pmean", _program("\n".join([
        "    %cst = stablehlo.constant dense<0.0> : tensor<f32>",
        "    %0 = stablehlo.reduce(%arg0 init: %cst) applies stablehlo.add across dimensions = [0, 1] : (tensor<8x4xf32>, tensor<f32>) -> tensor<f32>",
        "    %1 = stablehlo.convert %0 : (tensor<f32>) -> tensor<bf16>",
        _all_reduce("%1", 2, "bf16"),
        "    %9 = stablehlo.convert %2 : (tensor<bf16>) -> tensor<f32>",
    ])), "ADT603", Severity.WARNING),
    # 5. master round-trip: the "updated" f32 param IS the rounded value
    ("master_roundtrip", _program("\n".join([
        "    %0 = stablehlo.convert %arg0 : (tensor<8x4xf32>) -> tensor<8x4xbf16>",
        "    %1 = stablehlo.convert %0 : (tensor<8x4xbf16>) -> tensor<8x4xf32>",
        "    %cst = stablehlo.constant dense<0.0> : tensor<f32>",
        "    %9 = stablehlo.reduce(%1 init: %cst) applies stablehlo.add across dimensions = [0, 1] : (tensor<8x4xf32>, tensor<f32>) -> tensor<f32>",
    ])), "ADT602", Severity.ERROR),
    # 6. the round-trip hidden behind other value-preserving ops
    ("master_roundtrip_via_transpose", _program("\n".join([
        "    %0 = stablehlo.convert %arg0 : (tensor<8x4xf32>) -> tensor<8x4xbf16>",
        "    %1 = stablehlo.transpose %0, dims = [1, 0] : (tensor<8x4xbf16>) -> tensor<4x8xbf16>",
        "    %2 = stablehlo.convert %1 : (tensor<4x8xbf16>) -> tensor<4x8xf32>",
        "    %cst = stablehlo.constant dense<0.0> : tensor<f32>",
        "    %9 = stablehlo.reduce(%2 init: %cst) applies stablehlo.add across dimensions = [0, 1] : (tensor<4x8xf32>, tensor<f32>) -> tensor<f32>",
    ])), "ADT602", Severity.ERROR),
    # 7. entry returns the loss as a bf16 scalar — rounded before any
    # consumer (sentinel EWMA, early stopping) sees it
    ("half_loss_returned", _program("\n".join([
        "    %cst = stablehlo.constant dense<0.0> : tensor<f32>",
        "    %0 = stablehlo.reduce(%arg0 init: %cst) applies stablehlo.add across dimensions = [0, 1] : (tensor<8x4xf32>, tensor<f32>) -> tensor<f32>",
        "    %9 = stablehlo.convert %0 : (tensor<f32>) -> tensor<bf16>",
    ]), results="tensor<bf16>", ret="%9 : tensor<bf16>"),
     "ADT603", Severity.WARNING),
]

# train/eval pair whose collectives are order-compatible (same kind,
# groups, element count) but disagree on the element dtype: the ADT605
# rendezvous defect no shape-level check can see.
TRAIN_F32 = _program("\n".join([
    _all_reduce("%arg0", 1, "8x4xf32"),
    "    %cst = stablehlo.constant dense<0.0> : tensor<f32>",
    "    %9 = stablehlo.reduce(%1 init: %cst) applies stablehlo.add across dimensions = [0, 1] : (tensor<8x4xf32>, tensor<f32>) -> tensor<f32>",
]))
EVAL_BF16 = _program("\n".join([
    "    %0 = stablehlo.convert %arg0 : (tensor<8x4xf32>) -> tensor<8x4xbf16>",
    _all_reduce("%0", 1, "8x4xbf16"),
    "    %2 = stablehlo.convert %1 : (tensor<8x4xbf16>) -> tensor<8x4xf32>",
    "    %cst = stablehlo.constant dense<0.0> : tensor<f32>",
    "    %9 = stablehlo.reduce(%2 init: %cst) applies stablehlo.add across dimensions = [0, 1] : (tensor<8x4xf32>, tensor<f32>) -> tensor<f32>",
]))


def _mlp_item(dtype=np.float32):
    rng = np.random.RandomState(0)
    params = {"w1": jnp.asarray(rng.randn(16, 32) * 0.1, dtype),
              "w2": jnp.asarray(rng.randn(32, 4) * 0.1, dtype)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["w1"].astype(jnp.float32))
        return jnp.mean((h @ p["w2"].astype(jnp.float32) - b["y"]) ** 2)

    batch = {"x": np.zeros((8, 16), np.float32),
             "y": np.zeros((8, 4), np.float32)}
    return ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-3),
                     params=params, example_batch=batch).prepare(), batch


def _spec(n=4):
    from autodist_tpu.resource_spec import ResourceSpec
    return ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": n}]})


# ------------------------------------------------- 1. the mutation matrix


def test_clean_bf16_shape_has_no_findings():
    """The managed tier's exact lowering shape — bf16 compute, f32
    accumulation, f32 loss — produces ZERO findings (the analyzer must
    not cry wolf on the thing it exists to enable)."""
    assert numerics.lint_text(CLEAN_BF16) == []


@pytest.mark.parametrize("name,text,code,severity",
                         TEXT_MUTATIONS,
                         ids=[m[0] for m in TEXT_MUTATIONS])
def test_text_mutations_caught_via_api(name, text, code, severity):
    diags = numerics.lint_text(text)
    hits = [d for d in diags if d.code == code]
    assert hits, (name, codes(diags))
    assert all(d.severity == severity for d in hits), hits


@pytest.mark.parametrize("name,text,code,severity",
                         TEXT_MUTATIONS,
                         ids=[m[0] for m in TEXT_MUTATIONS])
def test_text_mutations_caught_via_cli(tmp_path, capsys, name, text, code,
                                       severity):
    """The same defects through ``--programs`` dump mode: errors exit 1,
    warnings exit 0, and the finding appears in the JSON document."""
    f = tmp_path / ("%s.hlo" % name)
    f.write_text(text)
    rc = cli.main(["--programs", str(f), "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    found = {d["code"] for p in doc["programs"] for d in p["diagnostics"]}
    assert code in found, (name, found)
    assert rc == (1 if severity >= Severity.ERROR else 0)


def test_cross_program_dtype_mismatch_api():
    diags = numerics.lint_programs({"train": TRAIN_F32, "eval": EVAL_BF16})
    assert "ADT605" in codes(diags)
    # ADT605 only fires on a genuine disagreement: the pair against
    # itself is clean, and the bf16 side alone carries its own ADT601
    assert "ADT605" not in codes(
        numerics.lint_programs({"a": TRAIN_F32, "b": TRAIN_F32}))


def test_cross_program_dtype_mismatch_cli(tmp_path, capsys):
    ftrain = tmp_path / "train.hlo"
    feval = tmp_path / "eval.hlo"
    ftrain.write_text(TRAIN_F32)
    feval.write_text(EVAL_BF16)
    rc = cli.main(["--programs", str(ftrain), str(feval),
                   "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    cross = {d["code"]
             for d in doc["schedule_check"]["diagnostics"]}
    assert "ADT605" in cross
    assert rc == 1


def test_half_stored_params_plan_level_api():
    """Mutation: params STORED in bf16 under AllReduce — no f32 master
    anywhere. Both plan-level errors fire through verify_numerics AND
    through the registered rule that verify()/the searcher runs."""
    item, _ = _mlp_item(jnp.bfloat16)
    spec = _spec()
    strategy = S.AllReduce().build(item, spec)
    diags = verify_numerics(strategy, item, spec)
    assert "ADT601" in codes(diags) and "ADT602" in codes(diags)
    errors = [d for d in diags if d.severity >= Severity.ERROR]
    assert {"ADT601", "ADT602"} <= {d.code for d in errors}
    # the registered rule path (what AutoDist(validate=) and the search
    # scorer consume) sees the same errors
    assert {"ADT601", "ADT602"} <= set(codes(verify(strategy, item, spec)))


def test_half_stored_params_lowered_cli(tmp_path, capsys):
    """The SAME defect caught one layer down: lower a real bf16-stored
    training step and run the CLI dtype-flow pass over the dump — the
    half psum is right there in the text (ADT601 at exit 1)."""
    autodist_tpu.reset()
    item, batch = _mlp_item(jnp.bfloat16)
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(item.loss_fn, optax.adam(1e-3),
                      dict(item.params), batch)
    runner.init(dict(item.params))
    text = runner.lowered_text(batch)
    autodist_tpu.reset()
    f = tmp_path / "half_stored.hlo"
    f.write_text(text)
    rc = cli.main(["--programs", str(f), "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    found = {d["code"] for p in doc["programs"] for d in p["diagnostics"]}
    assert "ADT601" in found
    assert rc == 1


def test_unknown_compute_dtype_api_and_cli(tmp_path, capsys):
    """Mutation: compute_dtype="fp8" (not a supported tier). The plan
    rule errors through the API, and a serialized strategy carrying it
    is rejected by the CLI's --strategy-json mode at exit 1."""
    item, _ = _mlp_item()
    spec = _spec()
    strategy = S.AllReduce().build(item, spec)
    strategy.graph_config.compute_dtype = "fp8"
    diags = verify(strategy, item, spec)
    bad = [d for d in diags if d.code == "ADT602"]
    assert bad and all(d.severity >= Severity.ERROR for d in bad)

    f = tmp_path / "strategy.json"
    f.write_text(json.dumps(strategy.to_dict()))
    rc = cli.main(["sentiment_classifier", "--strategy-json", str(f),
                   "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert "ADT602" in {d["code"] for d in doc["diagnostics"]}


def test_sentinel_less_bf16_api_and_cli(capsys):
    """Mutation: a bf16 plan armed with NO sentinel — legal but
    unguarded (ADT604 warning, exit stays 0). An enabled policy
    silences it."""
    from autodist_tpu.runtime.sentinel import SentinelPolicy
    item, _ = _mlp_item()
    spec = _spec()
    strategy = S.AllReduce(compute_dtype="bf16").build(item, spec)
    diags = verify_numerics(strategy, item, spec)
    assert "ADT604" in codes(diags)
    assert all(d.severity == Severity.WARNING
               for d in diags if d.code == "ADT604")
    armed = verify_numerics(strategy, item, spec,
                            sentinel_policy=SentinelPolicy(enabled=True))
    assert "ADT604" not in codes(armed)

    rc = cli.main(["sentiment_classifier", "--strategy", "AllReduce",
                   "--numerics", "--compute-dtype", "bf16",
                   "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["errors"] == 0
    assert "ADT604" in {d["code"] for d in doc["diagnostics"]}


def test_zero_sharded_exemption_flip():
    """bf16-stored params are EXEMPT under an all-ZeroSharded plan (f32
    shard math + f32 opt state IS the master); the same vars under
    AllReduce are the ADT601/602 mutation. The flip is the boundary."""
    item, _ = _mlp_item(jnp.bfloat16)
    spec = _spec()
    zero = S.ZeroSharded().build(item, spec)
    meta_ok = all("Zero" in type(n.synchronizer).__name__
                  for n in zero.node_config)
    assert meta_ok, [type(n.synchronizer).__name__
                     for n in zero.node_config]
    clean = [d for d in verify_numerics(zero, item, spec)
             if d.code in ("ADT601", "ADT602")]
    assert clean == [], codes(clean)
    flipped = S.AllReduce().build(item, spec)
    assert {"ADT601", "ADT602"} <= set(
        codes(verify_numerics(flipped, item, spec)))


def test_loss_tier_warning_on_unmanaged_half_params():
    """ADT603 at plan level: half-stored params WITHOUT the managed
    compute tier leak the compute dtype into the loss; the managed tier
    (f32 params + compute_dtype=bf16) does not trip it."""
    item, _ = _mlp_item(jnp.bfloat16)
    spec = _spec()
    unmanaged = S.AllReduce().build(item, spec)
    assert "ADT603" in codes(verify_numerics(unmanaged, item, spec))
    f32_item, _ = _mlp_item()
    managed = S.AllReduce(compute_dtype="bf16").build(f32_item, spec)
    assert "ADT603" not in codes(verify_numerics(managed, f32_item, spec))


# ------------------------------------------------------ 2. the clean matrix

_MATRIX_EXAMPLES = ["sentiment_classifier", "lm1b"]
_MATRIX_BUILDERS = ["PS", "PSLoadBalancing", "PartitionedPS", "AllReduce",
                    "AllReduceInt8Wire", "PSInt8Wire", "PartitionedAR",
                    "ZeroSharded", "ZeroShardedInt8Wire", "Parallax",
                    "WithRemat"]


@pytest.mark.parametrize("example", _MATRIX_EXAMPLES)
@pytest.mark.parametrize("builder", _MATRIX_BUILDERS)
@pytest.mark.parametrize("dtype", ["f32", "bf16"])
def test_example_builder_dtype_matrix_lints_clean(capsys, example, builder,
                                                  dtype):
    """Acceptance: every example x builder x {f32, bf16} builder plan
    lints with zero ADT60x ERRORS through the CLI's --numerics leg (the
    sentinel-less ADT604 warning is expected on bf16 and does not fail
    the lint)."""
    rc = cli.main([example, "--strategy", builder, "--numerics",
                   "--compute-dtype", dtype, "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0, doc
    adt6_errors = [d for d in doc["diagnostics"]
                   if d["code"].startswith("ADT60")
                   and d["severity"] == "error"]
    assert adt6_errors == []


# ------------------------------------------------------- 3. the lowering


BF16_BUILDERS = [
    ("AllReduce-bf16", lambda: S.AllReduce(compute_dtype="bf16")),
    ("ZeroSharded-bf16", lambda: S.ZeroSharded(compute_dtype="bf16")),
    ("PS-bf16", lambda: S.PS(compute_dtype="bf16")),
]


@pytest.mark.parametrize("name,builder", BF16_BUILDERS,
                         ids=[b[0] for b in BF16_BUILDERS])
def test_bf16_lowered_program_lints_clean(name, builder):
    """The managed tier's real lowering passes its own analyzer: bf16
    compute is visible in the program, but accumulation and loss are
    f32, so Runner.lint_lowered reports zero ADT60x."""
    autodist_tpu.reset()
    item, batch = _mlp_item()
    ad = autodist_tpu.AutoDist(strategy_builder=builder())
    runner = ad.build(item.loss_fn, optax.adam(1e-2),
                      dict(item.params), batch)
    runner.init(dict(item.params))
    text = runner.lowered_text(batch)
    assert "bf16" in text, "the bf16 tier lowered no bf16 compute"
    diags = runner.lint_lowered(batch)
    adt6 = [d for d in diags if d.code.startswith("ADT60")]
    assert adt6 == [], codes(adt6)
    autodist_tpu.reset()


def test_bf16_e2e_loss_parity_and_f32_master():
    """Acceptance: a bf16 plan TRAINS — the loss tracks the f32 curve
    within the sentinel-scale band, step_stats reports the tier, and
    gathered params stay float32 (the master never leaves f32)."""
    import jax

    def leg(compute_dtype):
        autodist_tpu.reset()
        item, batch = _mlp_item()
        rng = np.random.RandomState(1)
        batches = [{"x": rng.randn(8, 16).astype(np.float32),
                    "y": rng.randn(8, 4).astype(np.float32)}
                   for _ in range(10)]
        ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce(
            compute_dtype=compute_dtype))
        runner = ad.build(item.loss_fn, optax.adam(1e-2),
                          dict(item.params), batches[0])
        runner.init(dict(item.params))
        hist = runner.fit(batches)
        stats = runner.step_stats()
        leaves = {str(x.dtype) for x in jax.tree_util.tree_leaves(
            runner.gather_params())}
        return [float(m["loss"]) for m in hist], stats, leaves

    f_losses, f_stats, f_leaves = leg("f32")
    b_losses, b_stats, b_leaves = leg("bf16")
    autodist_tpu.reset()
    assert f_stats["compute_dtype"] == "f32"
    assert b_stats["compute_dtype"] == "bf16"
    assert f_leaves == b_leaves == {"float32"}
    np.testing.assert_allclose(b_losses, f_losses, rtol=0.3, atol=5e-3)
    assert abs(b_losses[-1] - f_losses[-1]) <= (
        0.1 * max(abs(f_losses[-1]), 1e-3) + 1e-3)


# ----------------------------------------------------- 4. the search space


def test_search_canon_never_emits_adt60x():
    """Acceptance: seeds + a deep mutation sweep, every materialized
    plan verified — zero ADT60x at ANY severity (with a sentinel armed,
    as the searcher's deployments are). The compute axis is in the
    space (both tiers must appear) yet canon keeps it numerics-clean by
    construction."""
    import random
    from autodist_tpu.runtime.sentinel import SentinelPolicy
    from autodist_tpu.search.space import PlanSpace
    item, _ = _mlp_item()
    spec = _spec()
    space = PlanSpace(item, spec)
    rng = random.Random(0)
    frontier = [plan for _, plan in space.seeds()]
    assert {p.compute_dtype for p in frontier} == {"f32", "bf16"}
    seen_dtypes = set()
    policy = SentinelPolicy(enabled=True)
    for step in range(150):
        plan = frontier[rng.randrange(len(frontier))]
        mut = space.mutate(plan, rng)
        if mut is None:
            continue
        plan, _op = mut
        frontier.append(plan)
        seen_dtypes.add(plan.compute_dtype)
        strategy = space.build(plan)
        adt6 = [d for d in verify(strategy, item, spec)
                if d.code.startswith("ADT60")]
        adt6 += [d for d in verify_numerics(strategy, item, spec,
                                            sentinel_policy=policy)
                 if d.code.startswith("ADT60")]
        assert adt6 == [], (plan.describe(), codes(adt6))
    assert seen_dtypes == {"f32", "bf16"}, seen_dtypes


def test_plan_roundtrip_keeps_compute_dtype():
    """Strategy IR round-trip: compute_dtype survives to_dict/from_dict
    and from_strategy rejects an out-of-space tier instead of laundering
    it into the search frontier."""
    from autodist_tpu.search.space import PlanSpace
    from autodist_tpu.strategy.base import Strategy
    item, _ = _mlp_item()
    spec = _spec()
    space = PlanSpace(item, spec)
    strategy = S.AllReduce(compute_dtype="bf16").build(item, spec)
    rt = Strategy.from_dict(strategy.to_dict())
    assert rt.graph_config.compute_dtype == "bf16"
    plan = space.from_strategy(rt)
    assert plan is not None and plan.compute_dtype == "bf16"
    rt.graph_config.compute_dtype = "fp8"
    assert space.from_strategy(rt) is None
