"""Opaque step_fn capture mode (VERDICT-r4 #2).

The framework's analog of the reference's distribute-any-graph generality
(reference ``tests/integration/cases/c4.py:31`` distributes arbitrary
captured graphs, while-loops and all): a hand-written
``step_fn(state, batch) -> (new_state, metrics)`` — gradients, momentum,
update rule all inside, invisible to the framework — lowers by sharding
assignment (``GraphTransformer._transform_step_fn``) and matches
single-device numerics under the AllReduce and Partitioned families.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import autodist_tpu
from autodist_tpu import strategy as S


def _opaque_problem():
    rng = np.random.RandomState(3)
    w = jnp.asarray(rng.randn(16, 4) * 0.3, jnp.float32)
    b = jnp.zeros((4,), jnp.float32)
    # the state bundles params AND optimizer state (momentum) in one opaque
    # tree — the framework must not need to understand its structure
    state = {"w": w, "b": b,
             "mom": {"w": jnp.zeros_like(w), "b": jnp.zeros_like(b)}}
    batch = {"x": rng.randn(32, 16).astype(np.float32),
             "y": rng.randn(32, 4).astype(np.float32)}

    def step_fn(state, batch):
        def loss(p):
            pred = batch["x"] @ p["w"] + p["b"]
            return jnp.mean((pred - batch["y"]) ** 2)
        l, g = jax.value_and_grad(loss)({"w": state["w"], "b": state["b"]})
        mom = {k: 0.9 * state["mom"][k] + g[k] for k in g}
        new = {"w": state["w"] - 0.1 * mom["w"],
               "b": state["b"] - 0.1 * mom["b"], "mom": mom}
        return new, {"loss": l}

    return state, step_fn, batch


def _flatten(tree):
    from autodist_tpu.kernel.common.variable_utils import flatten_named
    names, leaves, _ = flatten_named(tree)
    return dict(zip(names, (np.asarray(l) for l in leaves)))


@pytest.mark.parametrize("builder", ["AllReduce", "PartitionedAR"])
def test_step_fn_matches_single_device(builder):
    state, step_fn, batch = _opaque_problem()

    # single-device reference trajectory
    sstep = jax.jit(step_fn)
    ref_state, ref_losses = state, []
    for _ in range(5):
        ref_state, m = sstep(ref_state, batch)
        ref_losses.append(float(m["loss"]))

    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=getattr(S, builder)())
    runner = ad.build_step(step_fn, state, batch)
    runner.init(state)
    losses = [float(runner.run(batch)["loss"]) for _ in range(5)]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)

    got = _flatten(runner.gather_params())
    want = _flatten(ref_state)
    assert sorted(got) == sorted(want)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6,
                                    err_msg=k)
    autodist_tpu.reset()


def test_step_fn_partitioned_storage_is_sharded():
    """PartitionedAR assigns ZeRO-style sharded storage: the big state
    leaves live sharded over the data axis (one shard per device), and the
    lowered program carries the implied gathers."""
    state, step_fn, batch = _opaque_problem()
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build_step(step_fn, state, batch)
    runner.init(state)
    runner.run(batch)
    w = runner.state.params["w"]
    from jax.sharding import PartitionSpec as P
    assert w.sharding.spec == P("data"), w.sharding
    # 16 rows over 8 devices -> 2-row shards, no padding on the opaque path
    assert w.addressable_shards[0].data.shape == (2, 4)
    autodist_tpu.reset()


def test_step_fn_refuses_host_ps():
    state, step_fn, batch = _opaque_problem()
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedPS())
    with pytest.raises(ValueError, match="step_fn capture mode cannot"):
        ad.build_step(step_fn, state, batch)
    autodist_tpu.reset()


def test_step_fn_bad_structure_raises():
    state, _step, batch = _opaque_problem()

    def bad(state, batch):
        return state  # no metrics

    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    with pytest.raises(ValueError, match="must return"):
        ad.build_step(bad, state, batch)
    autodist_tpu.reset()


def test_step_fn_tensor_parallel_storage():
    """TP works for free in step_fn mode: mp-ruled leaves store sharded
    over the model axis, GSPMD inserts the Megatron collectives the
    global-semantics matmuls imply, and numerics match single-device."""
    rng = np.random.RandomState(0)
    state = {"w1": jnp.asarray(rng.randn(16, 64) * 0.2, jnp.float32),
             "w2": jnp.asarray(rng.randn(64, 4) * 0.2, jnp.float32)}
    batch = {"x": rng.randn(32, 16).astype(np.float32),
             "y": rng.randn(32, 4).astype(np.float32)}

    def user_step(s, b):
        def loss(p):
            h = jnp.tanh(b["x"] @ p["w1"])
            return jnp.mean((h @ p["w2"] - b["y"]) ** 2)
        l, g = jax.value_and_grad(loss)(s)
        new = jax.tree_util.tree_map(lambda w, gg: w - 0.1 * gg, s, g)
        return new, {"loss": l}

    sstep = jax.jit(user_step)
    ref = state
    for _ in range(5):
        ref, _m = sstep(ref, batch)

    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.TensorParallel(
        tp_shards=2, mp_rules=[(r"^w1$", {1: "model"}),
                               (r"^w2$", {0: "model"})]))
    runner = ad.build_step(user_step, state, batch)
    runner.init(state)
    for _ in range(5):
        m = runner.run(batch)
    assert np.isfinite(m["loss"])
    got = _flatten(runner.gather_params())
    want = _flatten(ref)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6,
                                    err_msg=k)
    # storage really is column/row sharded over the model axis
    from jax.sharding import PartitionSpec as P
    w1 = runner.state.params["w1"]
    assert w1.sharding.spec == P(None, "model"), w1.sharding
    assert w1.addressable_shards[0].data.shape == (16, 32)
    autodist_tpu.reset()


def test_step_fn_checkpoint_roundtrip(tmp_path):
    """Checkpoints work on the opaque path: the user state saves in the
    original layout (vanilla numpy-loadable) and restores bit-exact —
    retraining from the restore matches the uninterrupted run."""
    from autodist_tpu.checkpoint.saver import Saver
    state, step_fn, batch = _opaque_problem()
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build_step(step_fn, state, batch)
    runner.init(state)
    for _ in range(3):
        runner.run(batch)
    saver = Saver(directory=str(tmp_path))
    path = saver.save(runner)
    # original layout, framework-free load
    flat = dict(np.load(path + ".params.npz"))
    assert flat["w"].shape == (16, 4) and flat["mom/w"].shape == (16, 4)
    for _ in range(2):
        runner.run(batch)
    final_a = _flatten(runner.gather_params())

    _, step = saver.restore(runner)
    assert step == 3
    for _ in range(2):
        runner.run(batch)
    final_b = _flatten(runner.gather_params())
    for k in final_a:
        np.testing.assert_array_equal(final_a[k], final_b[k], err_msg=k)
    autodist_tpu.reset()


def test_step_fn_sharded_checkpoint_roundtrip(tmp_path):
    """The sharded format works on the opaque path too (the intended
    checkpoint path for the ZeRO/TP families step_fn serves): save
    commits, restore rebuilds the placed state, training continues."""
    from autodist_tpu.checkpoint import ShardedSaver
    state, step_fn, batch = _opaque_problem()
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build_step(step_fn, state, batch)
    runner.init(state)
    for _ in range(3):
        runner.run(batch)
    saver = ShardedSaver(directory=str(tmp_path))
    saver.save(runner)
    for _ in range(2):
        runner.run(batch)
    final_a = _flatten(runner.gather_params())

    _, step = saver.restore(runner)
    assert step == 3
    for _ in range(2):
        runner.run(batch)
    final_b = _flatten(runner.gather_params())
    for k in final_a:
        np.testing.assert_array_equal(final_a[k], final_b[k], err_msg=k)
    autodist_tpu.reset()
