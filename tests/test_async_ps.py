"""Async parameter server: serving protocol units + single-process e2e.

The cross-process async run lives in tests/test_distributed.py
(test_two_process_async_ps); here the serving machinery is exercised
in-process: blob packing, owner apply loop, worker fetch/push through both
the LocalPSService and two stores role-playing owner and worker.
"""
import time

import numpy as np
import jax.numpy as jnp
import optax
import pytest

import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.model_item import VarInfo
from autodist_tpu.parallel.ps import PSStore, PSVarPlan
from autodist_tpu.runtime import ps_service as pss


def test_pack_unpack_roundtrip():
    arrays = {
        "a/w": np.random.RandomState(0).randn(3, 5).astype(np.float32),
        "b": np.arange(7, dtype=np.int32),
        "scalar": np.float64(3.5) * np.ones(()),
    }
    out = pss.unpack_arrays(pss.pack_arrays(arrays))
    assert set(out) == set(arrays)
    for k in arrays:
        np.testing.assert_array_equal(out[k], np.asarray(arrays[k]))
        assert out[k].dtype == np.asarray(arrays[k]).dtype


def _two_stores():
    """Owner ('hostA') + worker ('hostB') stores over the same plan,
    sharing in-process services — the serving protocol without processes."""
    infos = {"w": VarInfo(name="w", shape=(4, 2), dtype="float32")}
    plans = {"w": PSVarPlan(var_name="w", destinations=("hostA:CPU:0",),
                            sync=False)}
    opt = optax.sgd(0.1)
    init = {"w": np.ones((4, 2), np.float32)}
    services = {}

    def service_for_host(host):
        return services.setdefault(host, pss.LocalPSService())

    owner = PSStore(dict(plans), infos, opt)
    owner.init_params(init)
    owner.enable_serving(service_for_host, my_host="hostA")
    worker = PSStore(dict(plans), infos, opt)
    worker.init_params(init)
    worker.enable_serving(service_for_host, my_host="hostB")
    return owner, worker, services


def test_owner_worker_push_pull_cycle():
    owner, worker, services = _two_stores()
    try:
        # worker's first pull = owner's initial publish (version 0)
        vals0 = worker.pull()
        np.testing.assert_array_equal(vals0["w"], np.ones((4, 2)))

        # worker pushes a gradient; owner's apply thread applies it and
        # republishes — with NO action from the owner's main thread
        g = np.full((4, 2), 2.0, np.float32)
        worker.push({"w": jnp.asarray(g)})
        deadline = time.monotonic() + 10
        while owner.applied_total() < 1:
            assert time.monotonic() < deadline, "apply loop never ran"
            time.sleep(0.005)
        want = 1.0 - 0.1 * 2.0
        np.testing.assert_allclose(owner._local_full()["w"],
                                   np.full((4, 2), want), rtol=1e-6)

        # worker sees the new version on its next pull
        deadline = time.monotonic() + 10
        while True:
            vals1 = worker.pull()
            if not np.allclose(vals1["w"], 1.0):
                break
            assert time.monotonic() < deadline, "new version never served"
            time.sleep(0.005)
        np.testing.assert_allclose(vals1["w"], np.full((4, 2), want), rtol=1e-6)

        # the worker applied nothing locally (it does not own 'w')
        assert worker.applied_total() == 0
        assert worker.stats["bytes_pushed"] > 0
    finally:
        owner.close()
        worker.close()


def test_async_applies_interleave_without_barrier():
    """Two pushes from the worker while the owner's main thread is idle:
    both apply individually (reference async semantics — one grad at a
    time, no averaging)."""
    owner, worker, _ = _two_stores()
    try:
        for _ in range(2):
            worker.push({"w": jnp.full((4, 2), 1.0)})
        deadline = time.monotonic() + 10
        while owner.applied_total() < 2:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        # two sequential SGD applies of grad=1: 1 - 0.1 - 0.1
        np.testing.assert_allclose(owner._local_full()["w"],
                                   np.full((4, 2), 0.8), rtol=1e-6)
    finally:
        owner.close()
        worker.close()


def test_async_e2e_single_process():
    """PS(sync=False) through the full stack: local service, apply thread
    decoupled from stepping, convergence, metadata flags."""
    rng = np.random.RandomState(0)
    true_w = rng.randn(8, 1).astype(np.float32)
    X = rng.randn(64, 8).astype(np.float32)
    batch = {"x": X, "y": X @ true_w}
    params = {"w": jnp.zeros((8, 1), jnp.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    ad = adt.AutoDist(strategy_builder=strategy.PS(sync=False))
    runner = ad.build(loss_fn, optax.sgd(0.2), params, batch)
    runner.init(params)
    dstep = runner.distributed_step
    assert dstep.metadata["async"] is True
    store = dstep.ps_store
    assert store is not None and store.serving

    # An untamed async loop is free to outrun the apply thread — gradients
    # computed at stale values stack up and can diverge (true async PS
    # behavior). Pace like a bounded-staleness worker: let the queue drain
    # every few steps, stay async within the window.
    losses = []
    for i in range(60):
        losses.append(float(runner.run(batch)["loss"]))
        if i % 5 == 4:
            dstep.flush_ps()  # pipelined pushes must reach the queue first
            store.drain()
    dstep.flush_ps()
    store.drain()
    assert store.applied_total() == 60
    # async pulls may observe stale versions, but the trajectory converges
    assert losses[-1] < 1e-2 < losses[0]
    w = np.asarray(runner.gather_params()["w"])
    np.testing.assert_allclose(w, true_w, atol=5e-2)
    store.close()


def test_async_rejects_mixed_strategies():
    """Async must be pure host-PS: an AR var in the mix needs a lockstep
    collective, which async training cannot have."""
    params = {"w": jnp.zeros((8, 2), jnp.float32),
              "b": jnp.zeros((2,), jnp.float32)}
    batch = {"x": np.zeros((8, 8), np.float32),
             "y": np.zeros((8, 2), np.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] + p["b"] - b["y"]) ** 2)

    from autodist_tpu.strategy.base import (AllReduceSynchronizer, GraphConfig,
                                            PSSynchronizer, Strategy, VarConfig)

    class Mixed(strategy.PS.__bases__[0]):
        def build(self, model_item, resource_spec):
            dest = "%s:CPU:0" % resource_spec.node_addresses[0]
            return Strategy(
                node_config=[
                    VarConfig(var_name="w", synchronizer=PSSynchronizer(
                        reduction_destination=dest, sync=False)),
                    VarConfig(var_name="b",
                              synchronizer=AllReduceSynchronizer()),
                ],
                graph_config=GraphConfig(replicas=[
                    d.name_string() for d in resource_spec.devices]))

    ad = adt.AutoDist(strategy_builder=Mixed())
    with pytest.raises(ValueError, match="async PS"):
        ad.build(loss_fn, optax.sgd(0.1), params, batch)


def test_per_shard_ownership_and_opt_checkpoint_wire():
    """A partitioned var with shards owned by DIFFERENT hosts: each owner
    applies only its shard range, and a checkpoint on either side sees the
    PEER's live optimizer moments via the published ::si!leaf entries —
    not its own frozen local init (per-shard ownership means no single
    process applies to every shard)."""
    infos = {"w": VarInfo(name="w", shape=(4, 2), dtype="float32")}
    plans = {"w": PSVarPlan(var_name="w",
                            destinations=("hostA:CPU:0", "hostB:CPU:0"),
                            shard_sizes=(2, 2), sync=False)}
    opt = optax.adam(0.1)
    init = {"w": np.ones((4, 2), np.float32)}
    services = {}

    def service_for_host(host):
        return services.setdefault(host, pss.LocalPSService())

    a = PSStore(dict(plans), infos, opt)
    a.init_params(init)
    a.enable_serving(service_for_host, my_host="hostA")
    b = PSStore(dict(plans), infos, opt)
    b.init_params(init)
    b.enable_serving(service_for_host, my_host="hostB")
    try:
        g = np.arange(8, dtype=np.float32).reshape(4, 2) + 1.0
        a.push({"w": jnp.asarray(g)})
        deadline = time.monotonic() + 10
        while a.applied_total() < 1 or b.applied_total() < 1:
            assert time.monotonic() < deadline, "apply loops never ran"
            time.sleep(0.005)
        a.drain()
        b.drain()
        # each owner applied ONLY its own shard range: hostA's local copy
        # of shard 1 is untouched (still ones), hostB's shard 0 likewise
        with a._lock:
            np.testing.assert_array_equal(a._values["w"][1], np.ones((2, 2)))
            assert not np.allclose(a._values["w"][0], 1.0)
        with b._lock:
            np.testing.assert_array_equal(b._values["w"][0], np.ones((2, 2)))
            assert not np.allclose(b._values["w"][1], 1.0)
        # pull reassembles the var across owners: BOTH halves updated
        assembled = a.pull()["w"]
        assert not np.allclose(assembled[:2], 1.0)
        assert not np.allclose(assembled[2:], 1.0)
        # checkpoint from hostA: the hostB-owned shard's Adam moments come
        # off the wire (non-zero), not hostA's frozen local init
        mu = a.full_opt_leaf("0/mu/w", "w")
        assert not np.allclose(np.asarray(mu)[2:], 0.0), \
            "peer shard moments are frozen init — opt wire not working"
        np.testing.assert_allclose(
            np.asarray(mu), 0.1 * g, rtol=1e-5)  # adam mu after one grad
    finally:
        a.close()
        b.close()


def test_sharded_restore_activates_deferred_serving():
    """enable_serving() before any values exist defers bring-up; when a
    SHARDED-checkpoint restore is what first populates the store (the
    ADT_AUTO_RESUME path, which never calls init_params), serving must
    activate at the end of load_shard_states — or the job would silently
    train disconnected local mirrors with no owner loops at all."""
    infos = {"w": VarInfo(name="w", shape=(4, 2), dtype="float32")}
    plans = {"w": PSVarPlan(var_name="w", destinations=("hostA:CPU:0",),
                            sync=False)}
    services = {}

    def service_for_host(host):
        return services.setdefault(host, pss.LocalPSService())

    store = PSStore(dict(plans), infos, optax.sgd(0.1))
    store.enable_serving(service_for_host, my_host="hostA")
    assert not store.serving  # deferred: no values yet

    value = np.full((4, 2), 3.0, np.float32)

    def provider(name, si):
        return value, {}

    store.load_shard_states(provider)
    assert store.serving, "restore-first bring-up never started serving"
    # the owner loop exists and the restored values were published
    grp = store._serve_groups["hostA"]
    assert grp["owned"] and grp["worker"] is not None
    res = services["hostA"].fetch()
    assert res is not None
    blobs = pss.unpack_arrays(res[1])
    np.testing.assert_array_equal(blobs["w::0"], value)
    store.close()


def test_serving_publishes_opt_on_side_channel():
    """Per-step value publishes carry NO optimizer leaves (the 3x-wire
    saving); the moments ride the /opt side channel, fetched only by
    checkpoint reconstruction. Adam, so moments exist."""
    infos = {"w": VarInfo(name="w", shape=(4, 2), dtype="float32")}
    plans = {"w": PSVarPlan(var_name="w", destinations=("hostA:CPU:0",),
                            sync=False)}
    services = {}

    def service_for_host(host):
        return services.setdefault(host, pss.LocalPSService())

    init = {"w": np.ones((4, 2), np.float32)}
    owner = PSStore(dict(plans), infos, optax.adam(0.1))
    owner.init_params(init)
    owner.enable_serving(service_for_host, my_host="hostA")
    worker = PSStore(dict(plans), infos, optax.adam(0.1))
    worker.init_params(init)
    worker.enable_serving(service_for_host, my_host="hostB")
    try:
        g = {"w": np.full((4, 2), 0.5, np.float32)}
        worker.push(g)
        owner.drain()
        res = services["hostA"].fetch()
        assert res is not None
        vals = pss.unpack_arrays(res[1])
        assert set(vals) == {"w::0"}  # values only, no '!' opt keys
        res_opt = services["hostA"].fetch_opt()
        assert res_opt is not None
        opts = pss.unpack_arrays(res_opt[1])
        assert opts and all("!" in k for k in opts)
    finally:
        owner.close()
        worker.close()
