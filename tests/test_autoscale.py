"""Load-adaptive serving fleet (serving/autoscale.py).

Tier-1 legs: the AutoscalePolicy unit matrix — hysteresis (no flap
across the band edge), sustain windows, per-direction cooldown
enforcement (stamped by the ACTUATOR, not the decision), min/max
clamps, stale-telemetry holds — plus the FleetAutoscaler against a REAL
coordination service: grow-on-join admission, refusal onto a worker
with a pending preemption notice, planned drain-then-shrink through
``retire_worker``, and the epoch fence (a decision computed against a
stale epoch is dropped as ``FencedOut``, never double-applied). The
ADT440/441 lints run at controller construction. The end-to-end load
ramp (2→4→2 with live traffic) is the bench leg
(``bench.py --autoscale``); the oscillating-load chaos leg is nightly.
"""
import socket
import types

import pytest

from autodist_tpu.analysis import rules
from autodist_tpu.analysis.diagnostics import DiagnosticError
from autodist_tpu.runtime import elastic, preemption
from autodist_tpu.runtime.coordination import (CoordinationClient,
                                               CoordinationServer)
from autodist_tpu.serving.autoscale import (AutoscalePolicy,
                                            AutoscaleSignals,
                                            FleetAutoscaler, lint_policy)
from autodist_tpu.telemetry import spans as tel


def _policy(**kw):
    base = dict(min_replicas=1, max_replicas=4, queue_high=10.0,
                queue_low=2.0, sustain_s=1.0, grow_cooldown_s=5.0,
                shrink_cooldown_s=5.0)
    base.update(kw)
    return AutoscalePolicy(**base)


def _sig(depth, **kw):
    return AutoscaleSignals(queue_depth=depth, **kw)


# --------------------------------------------------------- config validation


def test_policy_rejects_bad_bounds():
    with pytest.raises(ValueError, match="min_replicas"):
        _policy(min_replicas=0)
    with pytest.raises(ValueError, match="clamp is empty"):
        _policy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="hysteresis band is empty"):
        _policy(queue_high=5.0, queue_low=5.0)
    with pytest.raises(ValueError, match=">= 0"):
        _policy(sustain_s=-1.0)


# ------------------------------------------------------------- decision core


def test_sustained_overload_grows():
    p = _policy()
    assert p.decide(_sig(50), replicas=2, now=0.0).direction == "hold"
    d = p.decide(_sig(50), replicas=2, now=1.5)
    assert d.direction == "grow" and d.target == 3


def test_sustained_idle_shrinks():
    p = _policy()
    p.decide(_sig(0), replicas=3, now=0.0)
    d = p.decide(_sig(0), replicas=3, now=1.5)
    assert d.direction == "shrink" and d.target == 2


def test_hysteresis_in_band_resets_sustain():
    """A signal dipping back INTO the band must re-earn its full
    sustain window — the excursion timer does not accumulate across
    band re-entries, which is what prevents edge flap."""
    p = _policy()
    p.decide(_sig(50), replicas=2, now=0.0)      # above: arms
    p.decide(_sig(5), replicas=2, now=0.6)       # in-band: resets
    d = p.decide(_sig(50), replicas=2, now=1.2)  # above again
    assert d.direction == "hold"                 # 1.2s total, 0s sustained
    assert p.decide(_sig(50), replicas=2, now=2.5).direction == "grow"


def test_hysteresis_falling_below_high_does_not_arm_shrink():
    """Between the bands NOTHING happens: dropping out of overload to a
    mid-band depth must not start the idle timer."""
    p = _policy()
    p.decide(_sig(50), replicas=3, now=0.0)
    p.decide(_sig(5), replicas=3, now=1.0)       # mid-band, NOT idle
    d = p.decide(_sig(5), replicas=3, now=10.0)  # still mid-band
    assert d.direction == "hold" and d.reason == "in-band"


def test_cooldown_stamped_by_actuator_not_decision():
    """decide() returning "grow" must NOT start the grow cooldown — a
    refused/fenced actuation would otherwise burn it with no scale
    event. Only note_scaled (the actuator's confirmation) stamps it."""
    p = _policy()
    p.decide(_sig(50), replicas=2, now=0.0)
    assert p.decide(_sig(50), replicas=2, now=1.5).direction == "grow"
    # not actuated: the same sustained state still commands a grow
    assert p.decide(_sig(50), replicas=2, now=1.6).direction == "grow"
    p.note_scaled("grow", now=1.6)
    # actuated: cooldown holds, and the sustain timer was reset
    p.decide(_sig(50), replicas=3, now=1.7)
    d = p.decide(_sig(50), replicas=3, now=3.0)
    assert d.direction == "hold" and "cooldown" in d.reason
    assert p.decide(_sig(50), replicas=3, now=7.0).direction == "grow"


def test_shrink_cooldown_enforced():
    p = _policy()
    p.decide(_sig(0), replicas=4, now=0.0)
    assert p.decide(_sig(0), replicas=4, now=1.5).direction == "shrink"
    p.note_scaled("shrink", now=1.5)
    p.decide(_sig(0), replicas=3, now=1.6)
    d = p.decide(_sig(0), replicas=3, now=3.5)
    assert d.direction == "hold" and "cooldown" in d.reason
    assert p.decide(_sig(0), replicas=3, now=8.0).direction == "shrink"


def test_min_max_clamps():
    p = _policy(min_replicas=2, max_replicas=3)
    p.decide(_sig(50), replicas=3, now=0.0)
    d = p.decide(_sig(50), replicas=3, now=2.0)
    assert d.direction == "hold" and "max_replicas" in d.reason
    p2 = _policy(min_replicas=2, max_replicas=3)
    p2.decide(_sig(0), replicas=2, now=0.0)
    d = p2.decide(_sig(0), replicas=2, now=2.0)
    assert d.direction == "hold" and "min_replicas" in d.reason


def test_p99_alone_triggers_overload():
    p = _policy(p99_high_ms=100.0)
    p.decide(_sig(0, p99_ms=500.0), replicas=2, now=0.0)
    d = p.decide(_sig(0, p99_ms=500.0), replicas=2, now=1.5)
    assert d.direction == "grow"


def test_stale_telemetry_holds():
    """A controller that cannot currently SEE the fleet must refuse to
    scale it — and reset its sustain timers (the window must be
    measured, not assumed)."""
    p = _policy(stale_signal_s=5.0)
    stale = _sig(50, scrape_ages={"w1": 30.0})
    d = p.decide(stale, replicas=2, now=0.0)
    assert d.direction == "hold" and "stale" in d.reason
    # fresh again: sustain restarts from scratch
    p.decide(_sig(50, scrape_ages={"w1": 0.1}), replicas=2, now=1.0)
    assert p.decide(_sig(50, scrape_ages={"w1": 0.1}),
                    replicas=2, now=2.5).direction == "grow"


# ------------------------------------------------------------------- lints


def _ps_strategy(*hosts):
    nodes = [types.SimpleNamespace(
        var_name="v%d" % i, part_configs=None,
        synchronizer=types.SimpleNamespace(reduction_destination=h))
        for i, h in enumerate(hosts)]
    return types.SimpleNamespace(
        graph_config=types.SimpleNamespace(mesh_shape={"data": 2}),
        node_config=nodes)


def _model_parallel_strategy():
    return types.SimpleNamespace(
        graph_config=types.SimpleNamespace(
            mesh_shape={"data": 2, "model": 2}),
        node_config=[])


def test_adt440_min_below_ps_owner_floor():
    diags = rules.verify_autoscale(
        _policy(min_replicas=1),
        strategy=_ps_strategy("10.0.0.1:7070", "10.0.0.2:7070"))
    assert [d.code for d in diags] == ["ADT440"]
    assert diags[0].severity.name == "ERROR"
    with pytest.raises(DiagnosticError, match="ADT440"):
        lint_policy(_policy(min_replicas=1),
                    strategy=_ps_strategy("10.0.0.1:7070",
                                          "10.0.0.2:7070"))
    # at the floor: sound
    assert lint_policy(_policy(min_replicas=2),
                       strategy=_ps_strategy("10.0.0.1:7070",
                                             "10.0.0.2:7070")) == []


def test_adt440_fail_fast_family_cannot_scale():
    diags = rules.verify_autoscale(_policy(min_replicas=1,
                                           max_replicas=4),
                                   strategy=_model_parallel_strategy())
    assert "ADT440" in [d.code for d in diags]
    # pinned bounds: no replica-count change armed, no error
    assert rules.verify_autoscale(
        _policy(min_replicas=2, max_replicas=2),
        strategy=_model_parallel_strategy()) == []


def test_adt441_threshold_warnings():
    diags = rules.verify_autoscale(_policy(queue_high=100.0,
                                           queue_low=2.0),
                                   max_queue=64)
    assert [d.code for d in diags] == ["ADT441"]
    assert diags[0].severity.name == "WARNING"
    # warnings do not raise at construction
    lint_policy(_policy(queue_high=100.0, queue_low=2.0), max_queue=64)
    diags = rules.verify_autoscale(
        _policy(sustain_s=0.0, grow_cooldown_s=0.0, shrink_cooldown_s=0.0))
    assert [d.code for d in diags] == ["ADT441"]


# ----------------------------------------------------- actuation (real wire)


@pytest.fixture()
def server():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    srv = CoordinationServer(port=port)
    srv.start()
    yield port
    srv.stop()


@pytest.fixture(autouse=True)
def _clean_plane():
    yield
    elastic.clear()
    preemption.reset()


CHIEF = "10.0.0.1:9000"
W2 = "10.0.0.2:9000"
W3 = "10.0.0.3:9000"


def _scaler(client, signals, **kw):
    base = dict(min_replicas=1, max_replicas=4, queue_high=10.0,
                queue_low=2.0, sustain_s=0.0, grow_cooldown_s=60.0,
                shrink_cooldown_s=60.0)
    base.update(kw.pop("policy_kw", {}))
    return FleetAutoscaler(client, AutoscalePolicy(**base), CHIEF,
                           signals_fn=lambda: signals, **kw)


def test_grow_admits_pool_worker(server):
    client = CoordinationClient("127.0.0.1", server)
    elastic.publish_epoch(client, 1, [CHIEF])
    sc = _scaler(client, _sig(50), pool=[W2, W3])
    d = sc.step()
    assert d.direction == "grow"
    assert elastic.read_epoch(client) == (2, [CHIEF, W2])
    assert sc.stats()["grows"] == 1
    assert tel.counters()["autoscale.grows"] >= 1


def test_grow_prefers_announced_joiner(server):
    client = CoordinationClient("127.0.0.1", server)
    elastic.publish_epoch(client, 1, [CHIEF])
    elastic.announce_join(client, W3)
    sc = _scaler(client, _sig(50), pool=[W2, W3])
    sc.step()
    # W3 asked for admission, so it outranks the cold spare W2 — and
    # its join announcement is consumed by the admission
    assert elastic.read_epoch(client) == (2, [CHIEF, W3])
    assert not elastic.pending_join(client, W3)


def test_grow_refused_onto_pending_notice(server):
    """The platform is about to take W2 — growing onto it would be a
    scale event that immediately unwinds. Refused (counted), and the
    next admissible candidate is used instead."""
    client = CoordinationClient("127.0.0.1", server)
    elastic.publish_epoch(client, 1, [CHIEF])
    preemption.publish_notice(client, W2, deadline_s=60, reason="spot")
    sc = _scaler(client, _sig(50), pool=[W2, W3])
    d = sc.step()
    assert d.direction == "grow"
    assert elastic.read_epoch(client) == (2, [CHIEF, W3])
    assert sc.stats()["refusals"] == 1
    # every candidate under notice: the grow degrades to a hold
    preemption.publish_notice(client, W3, deadline_s=60, reason="spot")
    elastic.publish_epoch(client, 3, [CHIEF])
    sc2 = _scaler(client, _sig(50), pool=[W2, W3])
    d = sc2.step()
    assert d.direction == "hold" and "admissible" in d.reason
    assert elastic.read_epoch(client) == (3, [CHIEF])


def test_shrink_goes_through_planned_departure(server):
    client = CoordinationClient("127.0.0.1", server)
    elastic.publish_epoch(client, 1, [CHIEF, W2])
    before = tel.counters().get("preempt.notices", 0.0)
    sc = _scaler(client, _sig(0), notice_deadline_s=45.0)
    d = sc.step()
    assert d.direction == "shrink"
    # the leaver got an ADVANCE notice (arming its graceful-departure
    # path) before the survivor epoch was published
    notice = preemption.read_notice(client, W2)
    assert notice is not None and notice.reason == "autoscale-idle"
    assert elastic.read_epoch(client) == (2, [CHIEF])
    assert tel.counters()["preempt.notices"] == before + 1
    assert sc.stats()["shrinks"] == 1


def test_shrink_never_retires_the_controller(server):
    client = CoordinationClient("127.0.0.1", server)
    elastic.publish_epoch(client, 1, [CHIEF])
    sc = _scaler(client, _sig(0))
    d = sc.step()
    # min_replicas=1 and the only member is the controller: hold
    assert d.direction == "hold"
    assert elastic.read_epoch(client) == (1, [CHIEF])


def test_stale_epoch_decision_is_fenced_and_dropped(server):
    """The race the fence exists for: between this controller's epoch
    read and its actuation, ANOTHER controller moves the fleet. The
    stale decision must die as FencedOut — dropped, counted, and
    absolutely not applied on top (no double-scale)."""
    client = CoordinationClient("127.0.0.1", server)
    elastic.publish_epoch(client, 1, [CHIEF])

    def racing_signals():
        # runs after step() read epoch 1, before the actuation: a rival
        # controller admits W3 first
        if elastic.read_epoch(client)[0] == 1:
            elastic.publish_epoch(client, 2, [CHIEF, W3])
        return _sig(50)

    sc = FleetAutoscaler(
        client, AutoscalePolicy(min_replicas=1, max_replicas=4,
                                queue_high=10.0, queue_low=2.0,
                                sustain_s=0.0, grow_cooldown_s=60.0,
                                shrink_cooldown_s=60.0),
        CHIEF, pool=[W2], signals_fn=racing_signals)
    d = sc.step()
    assert d.direction == "hold" and "fenced" in d.reason
    assert sc.stats()["fenced"] == 1
    # the rival's epoch stands untouched — W2 was NOT admitted on top
    assert elastic.read_epoch(client) == (2, [CHIEF, W3])
    # the cooldown was not burned: the next (fresh-epoch) step may grow
    d = sc.step()
    assert d.direction == "grow"
    assert elastic.read_epoch(client) == (3, [CHIEF, W3, W2])


def test_step_without_published_epoch_raises(server):
    client = CoordinationClient("127.0.0.1", server)
    sc = _scaler(client, _sig(50))
    with pytest.raises(RuntimeError, match="no membership epoch"):
        sc.step()


def test_construction_lints_against_strategy(server):
    client = CoordinationClient("127.0.0.1", server)
    with pytest.raises(DiagnosticError, match="ADT440"):
        FleetAutoscaler(client, _policy(min_replicas=1), CHIEF,
                        strategy=_ps_strategy("10.0.0.1:7070",
                                              "10.0.0.2:7070"))


def test_retire_worker_validates_membership(server):
    client = CoordinationClient("127.0.0.1", server)
    with pytest.raises(RuntimeError, match="no membership epoch"):
        preemption.retire_worker(client, W2)
    elastic.publish_epoch(client, 1, [CHIEF])
    with pytest.raises(RuntimeError, match="not in the current roster"):
        preemption.retire_worker(client, W2)


def test_admit_worker_is_idempotent(server):
    client = CoordinationClient("127.0.0.1", server)
    elastic.publish_epoch(client, 1, [CHIEF])
    assert elastic.admit_worker(client, W2) == 2
    assert elastic.admit_worker(client, W2) == 2  # already a member
    assert elastic.read_epoch(client) == (2, [CHIEF, W2])
