"""Cluster observability plane (ISSUE 11): clock-offset handshake
(skew/jitter tolerance, min-RTT filtering), goodput attribution (buckets
sum to wall time), straggler flagging, flight-recorder dumps on injected
``TrainingDiverged`` / breaker-open, fleet-profiling windows, and the
scrape-age / workers-missing satellites."""
import json
import os
import threading
import time

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.telemetry import blackbox, cluster, export, goodput
from autodist_tpu.telemetry import spans as tel


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    yield
    tel.configure(None)
    tel.reset()
    blackbox.reset()


class FakeCoordClient:
    """In-proc stand-in for the coordination client's KV/queue/blob API
    — the cluster-plane plumbing without a socket. ``delay_s`` simulates
    wire latency on every call (the jitter knob the clock tests turn)."""

    def __init__(self, delay_s=0.0):
        self.kv = {}
        self.queues = {}
        self.blobs = {}
        self.counters = {}
        self.delay_s = delay_s
        self._lock = threading.Lock()

    def _wire(self):
        if self.delay_s:
            time.sleep(self.delay_s)

    def put(self, key, value):
        self._wire()
        with self._lock:
            self.kv[key] = value

    def get(self, key):
        self._wire()
        with self._lock:
            return self.kv.get(key)

    def incr(self, name):
        self._wire()
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + 1
            return self.counters[name]

    def qpush(self, queue, payload, token=None):
        self._wire()
        with self._lock:
            self.queues.setdefault(queue, []).append(payload)

    def qpop(self, queue):
        self._wire()
        with self._lock:
            q = self.queues.get(queue)
            return q.pop(0) if q else None

    def bput(self, key, version, payload, token=None):
        self._wire()
        with self._lock:
            self.blobs[key] = (version, payload)

    def bget(self, key):
        self._wire()
        with self._lock:
            return self.blobs.get(key)


# ------------------------------------------------------------- clock sync


def test_clock_offset_recovers_injected_skew():
    """A worker whose wall clock runs 3s ahead must estimate an offset
    that cancels the skew, within the estimator's own reported error."""
    client = FakeCoordClient()
    skew_ns = 3_000_000_000
    with cluster.ClockSyncResponder(client, poll_s=0.001):
        est = cluster.estimate_clock_offset(
            client, "w0", rounds=4,
            clock=lambda: time.time_ns() + skew_ns)
    assert est.rounds == 4
    assert abs(est.offset_ns + skew_ns) <= max(est.error_ns, 50_000_000)
    assert est.error_ns == est.rtt_ns // 2 + 1
    d = cluster.ClockOffset.from_dict(est.to_dict())
    assert d.offset_ns == est.offset_ns


def test_clock_offset_min_rtt_filters_jitter():
    """Per-round wire jitter inflates RTT symmetrically; the min-RTT
    round wins, so the estimate stays tight even when most rounds are
    slow. The responder answers instantly (its own clock is the
    reference) while the REQUEST path jitters."""
    client = FakeCoordClient()
    # jitter: every call sleeps a random-ish amount, varying per call
    delays = iter([0.05, 0.0, 0.05, 0.0, 0.002, 0.0, 0.03, 0.0] * 8)

    orig_qpush = client.qpush

    def jittered_qpush(queue, payload, token=None):
        time.sleep(next(delays, 0.0))
        orig_qpush(queue, payload, token=token)

    client.qpush = jittered_qpush
    with cluster.ClockSyncResponder(client, poll_s=0.001):
        est = cluster.estimate_clock_offset(client, "w0", rounds=4)
    # no injected skew: the estimate must be ~zero despite 50ms jitter
    # rounds — bounded by the WINNING round's error, not the worst's
    assert abs(est.offset_ns) <= max(est.error_ns, 20_000_000)
    assert est.error_ns < 25_000_000  # the 2ms-ish round won, not 50ms


def test_clock_offset_times_out_without_responder():
    client = FakeCoordClient()
    with pytest.raises(TimeoutError, match="ClockSyncResponder"):
        cluster.estimate_clock_offset(client, "w0", rounds=2,
                                      round_timeout_s=0.05)


@pytest.mark.slow
def test_clock_offset_over_real_service_with_fault_proxy(monkeypatch):
    """The satellite acceptance: injected skew + fault-proxy DELAY
    jitter on the real coordination-service wire; the min-RTT filter
    still aligns within tolerance."""
    from autodist_tpu.runtime.coordination import (CoordinationClient,
                                                   CoordinationServer)
    from autodist_tpu.runtime.faultinject import FaultPlan, FaultyProxy
    port = 15913
    srv = CoordinationServer(port=port)
    srv.start()
    proxy = FaultyProxy("127.0.0.1", port, plan=FaultPlan({
        # delay every 3rd QPUSH by 80ms: two rounds pay the jitter, the
        # clean rounds win the min-RTT race
        "faults": [{"op": "delay", "match": "QPUSHB", "nth": 3,
                    "repeat": True, "delay_s": 0.08}]}))
    proxy.start()
    responder_client = CoordinationClient("127.0.0.1", port)
    worker_client = CoordinationClient("127.0.0.1", proxy.port)
    skew_ns = 2_500_000_000
    try:
        with cluster.ClockSyncResponder(responder_client, poll_s=0.001):
            est = cluster.estimate_clock_offset(
                client=worker_client, worker="w0", rounds=6,
                clock=lambda: time.time_ns() + skew_ns)
        assert abs(est.offset_ns + skew_ns) <= max(est.error_ns,
                                                   50_000_000)
        assert est.rtt_ns < 80_000_000  # a non-delayed round won
    finally:
        worker_client.close()
        responder_client.close()
        proxy.stop()
        srv.stop()


def test_chrome_trace_applies_clock_offset():
    """The exported timeline is reference-clock corrected: two recorders
    with a simulated 2s wall-clock disagreement (one corrected by the
    handshake offset) land their simultaneous spans together."""
    r_ref = tel.TraceRecorder(capacity=8, sample=1, pid=1, host="ref")
    r_skew = tel.TraceRecorder(capacity=8, sample=1, pid=2, host="skew")
    skew_ns = 2_000_000_000
    r_skew.epoch_offset_ns += skew_ns      # this host's clock runs ahead
    r_skew.clock_offset_ns = -skew_ns      # ...and the handshake knows
    r_skew.clock_error_ns = 1_000_000
    with r_ref.span("s", "t"):
        pass
    with r_skew.span("s", "t"):
        pass
    t_ref = next(e["ts"] for e in export.chrome_trace(r_ref)["traceEvents"]
                 if e["ph"] == "X")
    skew_trace = export.chrome_trace(r_skew)
    t_skew = next(e["ts"] for e in skew_trace["traceEvents"]
                  if e["ph"] == "X")
    assert abs(t_ref - t_skew) < 1e6  # within 1s (was 2s apart)
    assert skew_trace["otherData"]["clock_offset_ns"] == -skew_ns
    assert skew_trace["otherData"]["clock_error_ns"] == 1_000_000


def test_step_alignment_reads_merged_step_args():
    r1 = tel.TraceRecorder(capacity=16, sample=1, pid=1, host="a")
    r2 = tel.TraceRecorder(capacity=16, sample=1, pid=2, host="b")
    for rec in (r1, r2):
        for step in range(3):
            with rec.span("runner.dispatch", "runner", step=step):
                pass
    merged = export.merge_traces([export.chrome_trace(r1),
                                  export.chrome_trace(r2)])
    align = cluster.step_alignment(merged)
    assert align["aligned_steps"] == 3
    assert set(align["steps"]) == {0, 1, 2}
    for row in align["steps"].values():
        assert len(row["starts_us"]) == 2
        assert row["spread_us"] >= 0.0


# ---------------------------------------------------------------- goodput


def test_goodput_buckets_sum_to_wall_time_synthetic():
    rec = tel.TraceRecorder(capacity=256, sample=1, pid=1, host="h")
    with rec.span("runner.fit", "runner"):
        for step in range(3):
            with rec.span("runner.dispatch", "runner", step=step):
                with rec.span("runner.feed", "runner"):
                    time.sleep(0.002)
                with rec.span("dstep.dispatch", "dstep"):
                    with rec.span("ps.pull", "ps"):
                        time.sleep(0.002)
                    time.sleep(0.004)
            with rec.span("runner.readback", "runner"):
                time.sleep(0.001)
        with rec.span("ckpt.write", "ckpt"):
            time.sleep(0.002)
    report = goodput.breakdown_from_events(
        goodput._normalize_recorder(rec))
    assert report.wall_s > 0
    assert abs(report.attributed_s - report.wall_s) < 0.02 * report.wall_s
    b = report.buckets
    assert b["ps_wire"] >= 3 * 0.002 * 0.9
    assert b["host_input"] >= 3 * 0.002 * 0.9
    assert b["readback"] >= 3 * 0.001 * 0.9
    assert b["checkpoint"] >= 0.002 * 0.9
    assert b["compute"] >= 3 * 0.004 * 0.9
    assert report.num_dispatches == 3
    assert report.first_dispatch_s is not None
    # serialization round trip + table
    back = goodput.GoodputReport.from_dict(report.to_dict())
    assert back.buckets == {k: round(v, 6) for k, v in b.items()}
    assert "compute" in report.format_table()


def test_goodput_ignores_background_threads():
    """Async writer-thread time overlaps the wall; only the training
    thread's spans decompose it."""
    rec = tel.TraceRecorder(capacity=64, sample=1, pid=1, host="h")
    with rec.span("runner.dispatch", "runner", step=0):
        time.sleep(0.002)

    def background():
        with rec.span("ckpt.write", "ckpt"):
            time.sleep(0.01)
    t = threading.Thread(target=background, name="adt-ckpt-writer")
    t.start()
    t.join()
    report = goodput.breakdown_from_events(
        goodput._normalize_recorder(rec))
    assert report.buckets["checkpoint"] == 0.0
    assert report.wall_s < 0.009  # the 10ms background write is excluded


def test_goodput_real_fit_coverage_within_two_percent(tmp_path):
    """The acceptance bound on a real traced fit: attributed buckets sum
    to the recorded wall time within 2%, and the same decomposition is
    reachable from the exported trace file (the CLI path)."""
    from autodist_tpu.telemetry import cli
    tel.configure("1")
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(4, 2).astype(np.float32)),
              "b": jnp.zeros((2,), jnp.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] + p["b"] - b["y"]) ** 2)

    batches = [{"x": rng.randn(16, 4).astype(np.float32),
                "y": rng.randn(16, 2).astype(np.float32)}
               for _ in range(8)]
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PS())
    runner = ad.build(loss_fn, optax.adam(0.1), params, batches[0])
    runner.init(params)
    runner.fit(list(batches), fuse_steps=4, metrics_every=2)
    report = runner.goodput_report()
    assert report is not None
    assert abs(report.coverage - 1.0) < 0.02
    assert report.buckets["ps_wire"] > 0       # host-PS strategy
    assert report.buckets["compute"] > 0
    stats = runner.step_stats()
    assert stats["goodput_breakdown"] == {
        k: round(v, 6) for k, v in report.buckets.items()}
    assert stats["straggler"]["flags"] == 0
    # drift joins the attributed buckets
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.telemetry import drift
    spec = ResourceSpec.from_dict({
        "nodes": [{"address": "127.0.0.1", "cpus": 8, "chief": True,
                   "network_bandwidth": 25}],
        "slice": {"ici_bandwidth": 100}})
    dr = drift.report_for_runner(runner, resource_spec=spec)
    assert dr.goodput is not None
    terms = {t.term: t for t in dr.terms}
    assert terms["compute"].measured_s is not None
    # CLI: per-process goodput table from the exported trace
    path = str(tmp_path / "trace.json")
    export.write_trace(path)
    assert cli.main(["goodput", path]) == 0
    # and from a saved report
    rpath = report.save(str(tmp_path / "goodput.json"))
    assert cli.main(["goodput", rpath]) == 0
    autodist_tpu.reset()


def test_goodput_report_none_when_tracing_off():
    tel.configure("0")
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(4, 2).astype(np.float32)),
              "b": jnp.zeros((2,), jnp.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] + p["b"] - b["y"]) ** 2)

    batch = {"x": np.zeros((8, 4), np.float32),
             "y": np.zeros((8, 2), np.float32)}
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.adam(0.1), params, batch)
    runner.init(params)
    runner.run(batch)
    assert runner.goodput_report() is None
    assert runner.step_stats()["goodput_breakdown"] is None
    autodist_tpu.reset()


def test_cluster_goodput_flags_stragglers():
    """A merged trace whose second worker's dispatches run 3x slower
    must show the skew ratio and flag the straggler pid."""
    recs = []
    for pid, base in ((1, 0.001), (2, 0.003)):
        rec = tel.TraceRecorder(capacity=64, sample=1, pid=pid,
                                host="n%d" % pid)
        for step in range(4):
            with rec.span("runner.dispatch", "runner", step=step):
                time.sleep(base)
        recs.append(rec)
    merged = export.merge_traces([export.chrome_trace(r) for r in recs])
    out = goodput.cluster_goodput(merged, flag_ratio=1.5)
    assert out["skew_ratio"] > 1.5
    assert [s["pid"] for s in out["stragglers"]] == [2]
    assert set(out["workers"]) == {1, 2}


def test_straggler_ewma_flags_and_clears():
    det = goodput.StragglerEwma(alpha=0.2, zscore=4.0, patience=2,
                                warmup=4)
    for _ in range(10):
        assert det.observe(0.010 + np.random.RandomState(0).rand() * 1e-4) \
            is None
    assert det.observe(0.100) is None       # patience 1/2
    assert det.observe(0.100) == "flag"     # sustained → flag
    assert det.flagged and det.flags == 1
    assert det.observe(0.100) is None       # still flagged, no re-fire
    assert det.observe(0.010) == "clear"    # recovery
    assert not det.flagged
    stats = det.stats()
    assert stats["flags"] == 1 and stats["ewma_s"] is not None


# --------------------------------------------------------------- blackbox


def _problem(seed=0):
    rng = np.random.RandomState(seed)
    params = {"w": jnp.asarray(rng.randn(4, 2).astype(np.float32)),
              "b": jnp.zeros((2,), jnp.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] + p["b"] - b["y"]) ** 2)

    batch = {"x": rng.randn(16, 4).astype(np.float32),
             "y": rng.randn(16, 2).astype(np.float32)}
    return params, loss_fn, batch


def test_blackbox_dump_on_injected_training_diverged(monkeypatch,
                                                     tmp_path, capsys):
    """The acceptance artifact: an injected unbounded grad fault drives
    rollback → ladder exhaustion → ``TrainingDiverged``, and the run
    leaves a parseable blackbox dump containing the fatal verdict AND
    the last rollback event/span."""
    from autodist_tpu.checkpoint.saver import Saver
    from autodist_tpu.runtime.sentinel import SentinelPolicy, TrainingDiverged
    from autodist_tpu.telemetry import cli
    bb_dir = str(tmp_path / "blackbox")
    monkeypatch.setenv("ADT_BLACKBOX_DIR", bb_dir)
    monkeypatch.setenv("ADT_GRAD_FAULT_PLAN", json.dumps(
        {"faults": [{"var": "w", "mode": "nan", "step": 4,
                     "until": 100000}]}))
    tel.configure("1")  # the span tail must carry sentinel.rollback
    params, loss_fn, batch = _problem()
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.adam(0.1), params, batch,
                      sentinel=SentinelPolicy(max_skips_per_window=1,
                                              window_steps=50,
                                              max_rollbacks_per_step=2))
    runner.init(params)
    saver = Saver(directory=str(tmp_path / "ckpt"), max_to_keep=10)
    import itertools
    with pytest.raises(TrainingDiverged):
        runner.fit(itertools.repeat(batch), steps=64, save_every=2,
                   saver=saver)
    dumps = sorted(os.listdir(bb_dir))
    assert dumps, "no blackbox dump written"
    latest = os.path.join(bb_dir, dumps[-1])
    d = blackbox.load_dump(latest)
    assert d["trigger"] == "training_diverged"
    kinds = [e["kind"] for e in d["events"]]
    assert "sentinel.diverged" in kinds          # the fatal verdict
    assert "sentinel.rollback" in kinds          # the rollback trail
    assert "sentinel.verdict" in kinds           # bad verdicts leading in
    assert any(s["name"] == "sentinel.rollback"  # the last rollback SPAN
               for s in d["spans"])
    assert d["counters"]["sentinel.rollbacks"] >= 1
    # rollbacks dumped their own black boxes along the way
    triggers = {blackbox.load_dump(os.path.join(bb_dir, f))["trigger"]
                for f in dumps}
    assert any(t.startswith("sentinel rollback") for t in triggers)
    # the CLI renders it
    assert cli.main(["blackbox", latest]) == 0
    out = capsys.readouterr().out
    assert "training_diverged" in out and "sentinel.rollback" in out
    autodist_tpu.reset()


def test_blackbox_dump_on_breaker_open(monkeypatch, tmp_path):
    """Breaker-open against an unreachable service dumps the box with
    the breaker event and the retry trail."""
    from autodist_tpu.runtime.resilience import (CoordinationUnavailable,
                                                 ResilientCoordinationClient)
    bb_dir = str(tmp_path / "bb")
    monkeypatch.setenv("ADT_BLACKBOX_DIR", bb_dir)
    client = ResilientCoordinationClient(
        "127.0.0.1", 1, rpc_timeout=0.2, max_retries=2,
        backoff_base_s=0.001, backoff_max_s=0.002,
        breaker_failures=2, breaker_cooldown_s=0.2,
        connect_timeout=0.1, seed=0)
    with pytest.raises(CoordinationUnavailable):
        client.ping()
    dumps = [f for f in os.listdir(bb_dir) if f.endswith(".json")]
    assert dumps
    d = blackbox.load_dump(os.path.join(bb_dir, sorted(dumps)[-1]))
    assert d["trigger"] == "breaker_open"
    assert any(e["kind"] == "coord.breaker_open" for e in d["events"])
    assert d["counters"]["coord.breaker_opens"] >= 1


def test_blackbox_bounded_retention_and_log_tail(monkeypatch, tmp_path):
    monkeypatch.setenv("ADT_BLACKBOX_KEEP", "2")
    from autodist_tpu.utils import logging as adt_logging
    fr = blackbox.get_flight_recorder()
    fr.clear()
    adt_logging.warning("blackbox tail marker %d", 42)
    for i in range(4):
        fr.record("test.event", i=i)
        fr.dump("retention-test", directory=str(tmp_path))
    kept = [f for f in os.listdir(str(tmp_path)) if f.endswith(".json")]
    assert len(kept) == 2  # pruned to ADT_BLACKBOX_KEEP
    d = blackbox.load_dump(os.path.join(str(tmp_path), sorted(kept)[-1]))
    assert any("blackbox tail marker 42" in rec["msg"]
               for rec in d["logs"])
    assert [e["data"]["i"] for e in d["events"]] == [0, 1, 2, 3]


def test_blackbox_disabled_writes_nothing(monkeypatch, tmp_path):
    monkeypatch.setenv("ADT_BLACKBOX", "0")
    blackbox.record("test.event")
    assert blackbox.dump("disabled-test", directory=str(tmp_path)) is None
    assert not os.listdir(str(tmp_path))


# -------------------------------------------------------- fleet profiling


def test_profile_flag_round_trip_and_clear():
    client = FakeCoordClient()
    assert cluster.read_profile_window(client) is None
    seq = cluster.request_profile(client, 3, 5)
    assert cluster.read_profile_window(client) == (seq, 3, 5)
    seq2 = cluster.request_profile(client, 10, 12)
    assert seq2 > seq
    assert cluster.read_profile_window(client) == (seq2, 10, 12)
    cluster.clear_profile(client)
    assert cluster.read_profile_window(client) is None
    with pytest.raises(ValueError):
        cluster.request_profile(client, 5, 3)


def test_parse_profile_env():
    assert cluster.parse_profile_env("") is None
    assert cluster.parse_profile_env("3:5") == (3, 5)
    assert cluster.parse_profile_env("4") == (4, 4)
    assert cluster.parse_profile_env("5:3") is None
    assert cluster.parse_profile_env("nope") is None


def test_runner_env_window_captures_jax_profile(monkeypatch, tmp_path):
    """ADT_PROFILE_STEPS=N:M arms the fleet-window machinery locally:
    the runner captures a jax.profiler trace for exactly that step
    window."""
    monkeypatch.setenv("ADT_WORKING_DIR", str(tmp_path))
    monkeypatch.setenv("ADT_PROFILE_STEPS", "2:3")
    # DEFAULT_TRACE_DIR is computed at const import; patch it directly
    from autodist_tpu import const as const_mod
    monkeypatch.setattr(const_mod, "DEFAULT_TRACE_DIR",
                        str(tmp_path / "traces"))
    params, loss_fn, batch = _problem()
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.adam(0.1), params, batch)
    runner.init(params)
    for _ in range(5):
        runner.run(batch)
    assert not runner._profile_active
    out = str(tmp_path / "traces" / "fleet-0-chief")
    assert os.path.isdir(out)
    captured = [f for _, _, files in os.walk(out) for f in files]
    assert captured, "jax.profiler wrote nothing into the fleet window"
    assert tel.counters()["profiler.windows"] == 1
    autodist_tpu.reset()


# ------------------------------------------------- scrape-age satellites


def test_scrape_cluster_reports_ages_and_missing_gauge():
    client = FakeCoordClient()
    rec = tel.TraceRecorder(capacity=16, sample=1, pid=5, host="n0")
    with rec.span("s", "t"):
        pass
    export.publish_telemetry(client, "w0", rec)
    time.sleep(0.02)
    scraped = export.scrape_cluster(client, ["w0", "ghost1", "ghost2"])
    assert scraped["missing"] == ["ghost1", "ghost2"]
    assert scraped["scrape_age_s"]["w0"] >= 0.02
    assert tel.get_recorder().gauges()["cluster.workers_missing"] == 2.0
    text = scraped["metrics_text"]
    assert "adt_cluster_workers_missing 2" in text
    assert 'adt_cluster_scrape_age_seconds{worker="w0"}' in text
    assert "# HELP adt_cluster_workers_missing" in text
    # per-worker clock metadata rides the scrape
    assert scraped["clocks"]["w0"]["offset_ns"] == 0


def test_scrape_age_is_reference_clock_corrected():
    """A worker whose clock runs ahead publishes a corrected stamp: its
    age must read ~0, not negative/clamped garbage."""
    client = FakeCoordClient()
    rec = tel.TraceRecorder(capacity=4, sample=1, pid=5, host="n0")
    rec.clock_offset_ns = -3_000_000_000  # clock 3s ahead of reference
    rec.counter_add("runner.steps", 1)
    # publish stamps time.time() + offset -> ~3s in the "past" locally,
    # but correct on the reference timeline... the age is computed by a
    # coordinator whose clock IS the reference here, so simulate that by
    # checking the published stamp directly
    export.publish_telemetry(client, "w0", rec)
    payload = json.loads(client.blobs["telemetry/w0"][1].decode())
    assert payload["published_at"] == pytest.approx(time.time() - 3.0,
                                                    abs=0.5)
    assert payload["clock"]["offset_ns"] == -3_000_000_000
