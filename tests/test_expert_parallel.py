"""Expert parallelism: all_to_all routing matches dense local computation.

Same bar as the TP/PP suites: with capacity high enough that no token
drops, MoE under (data x expert) sharding must reproduce single-device
training EXACTLY (the aux loss is disabled for the equality checks — its
local-mean formulation is deliberately shard-local).
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import autodist_tpu as adt
from autodist_tpu import const, strategy
from autodist_tpu.models import moe_lm
from autodist_tpu.parallel import expert


@pytest.fixture(autouse=True)
def _reset():
    adt.reset()
    yield
    adt.reset()


def _moe_args(rng, E=4, d=8, f=16):
    return dict(
        router_w=rng.standard_normal((d, E)).astype(np.float32) * 0.5,
        w1=rng.standard_normal((E, d, f)).astype(np.float32) * 0.3,
        b1=np.zeros((E, f), np.float32),
        w2=rng.standard_normal((E, f, d)).astype(np.float32) * 0.3,
        b2=np.zeros((E, d), np.float32))


def test_moe_ffn_sharded_matches_dense():
    rng = np.random.RandomState(0)
    E, d = 4, 8
    p = _moe_args(rng, E=E, d=d)
    x = rng.standard_normal((16, d)).astype(np.float32)

    # dense single-device reference (axis unbound); generous capacity
    ref, _ = expert.moe_ffn(x, capacity_factor=float(E), **p)

    mesh = Mesh(np.array(jax.devices()[:4]), (const.EXPERT_AXIS,))

    def f(x_local, router_w, w1, b1, w2, b2):
        out, aux = expert.moe_ffn(x_local, router_w, w1, b1, w2, b2,
                                  capacity_factor=float(E))
        return out

    got = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(const.EXPERT_AXIS), P(), P(const.EXPERT_AXIS),
                  P(const.EXPERT_AXIS), P(const.EXPERT_AXIS),
                  P(const.EXPERT_AXIS)),
        out_specs=P(const.EXPERT_AXIS), check_vma=False))(
            x, p["router_w"], p["w1"], p["b1"], p["w2"], p["b2"])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_moe_capacity_drops_tokens():
    """With capacity 1 per expert, at most E tokens survive; dropped tokens'
    outputs are exactly zero (they ride the residual only)."""
    rng = np.random.RandomState(1)
    E, T = 4, 16
    p = _moe_args(rng, E=E)
    x = rng.standard_normal((T, 8)).astype(np.float32)
    out, aux = expert.moe_ffn(x, capacity_factor=E / T, **p)  # C = 1
    zero_rows = int(np.sum(np.all(np.asarray(out) == 0.0, axis=-1)))
    assert zero_rows >= T - E, zero_rows
    assert np.isfinite(aux)


@pytest.mark.parametrize("ep", [2, 4])
def test_ep_lm_matches_single_device(ep):
    """MoE LM via the full stack (data x expert mesh, joint batch sharding)
    == single-device training, no-drop capacity, aux off."""
    cfg = moe_lm.MoEConfig.tiny(capacity_factor=float(
        moe_lm.MoEConfig.tiny().num_experts))
    loss_fn, params, batch, _ = moe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=8, seed=2, aux_coef=0.0)
    opt = optax.sgd(0.05)
    rng = np.random.RandomState(3)
    batches = [batch, {"tokens": rng.randint(
        0, cfg.vocab_size, batch["tokens"].shape).astype(np.int32)}]

    state = opt.init(params)

    @jax.jit
    def step(p, s, b):
        g = jax.grad(loss_fn)(p, b)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    ref = params
    for b in batches:
        ref, state = step(ref, state, b)

    ad = adt.AutoDist(strategy_builder=strategy.ExpertParallel(
        ep_shards=ep, mp_rules=moe_lm.ep_rules()))
    runner = ad.build(loss_fn, opt, params, batches[0])
    layouts = runner.distributed_step.layouts
    assert layouts["layer_0/moe/w1"].mp_axes == ((0, const.EXPERT_AXIS),)
    assert layouts["layer_0/moe/router"].mp_axes == ()
    runner.init(params)
    for b in batches:
        m = runner.run(b)
    assert np.isfinite(m["loss"])
    got = runner.gather_params()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6),
        got, ref)


def test_ep_trains_with_aux():
    """Realistic capacity + Switch aux loss: loss decreases under dp2xep4."""
    cfg = moe_lm.MoEConfig.tiny(capacity_factor=2.0)
    loss_fn, params, batch, _ = moe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=8, seed=4)
    ad = adt.AutoDist(strategy_builder=strategy.ExpertParallel(
        ep_shards=4, mp_rules=moe_lm.ep_rules()))
    runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
    runner.init(params)
    first = runner.run(batch)["loss"]
    for _ in range(5):
        last = runner.run(batch)["loss"]
    assert np.isfinite(last) and last < first
