"""Chaos suite: injected wire-level faults against the control plane.

Every fault traverses the REAL wire path — a live ``coordination_service``
process, real TCP connections, and (where a middlebox is needed) the
:class:`~autodist_tpu.runtime.faultinject.FaultyProxy` executing a seeded
declarative plan. The assertions are the failure model's contract
(``docs/failure_model.md``): under each fault class the operation either
completes with the exact fault-free result (idempotent retry — a retried
``QPUSH``/``INC``/``BPUT``/``BARRIER`` is applied exactly once across a
forced reconnect) or fails with an explicit diagnostic. Silent stalls and
double-applies are the two forbidden outcomes.

Fast tests run in tier-1 (``chaos`` marker, not ``slow``); the
two-process end-to-end matrix is ``slow`` and runs in the nightly chaos
job (``.github/workflows/nightly-chaos.yml``).
"""
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from autodist_tpu import const
from autodist_tpu.runtime import ps_service as pss
from autodist_tpu.runtime.coordination import (CoordinationClient,
                                               CoordinationServer)
from autodist_tpu.runtime.faultinject import FaultPlan, FaultyProxy
from autodist_tpu.runtime.resilience import (CircuitOpenError,
                                             CoordinationUnavailable,
                                             ResilientCoordinationClient)

pytestmark = pytest.mark.chaos

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def server():
    srv = CoordinationServer(port=_free_port())
    srv.start()
    yield srv
    srv.stop()


# --------------------------------------------------------------------------
# idempotency tokens: exactly-once across reconnects (service-side dedup)
# --------------------------------------------------------------------------

def test_incr_token_replay_exactly_once(server):
    """A retried INC (same token, new connection — the ambiguous-drop
    recovery) replays the recorded reply instead of double-counting."""
    c1 = CoordinationClient("127.0.0.1", server.port)
    assert c1.incr("chaos/n", token="tok-incr-1") == 1
    c1.close()  # the connection the reply rode is gone
    c2 = CoordinationClient("127.0.0.1", server.port)
    assert c2.incr("chaos/n", token="tok-incr-1") == 1  # replayed, not 2
    assert c2.incr("chaos/n") == 2                      # fresh op advances
    c2.close()


def test_qpush_token_exactly_once(server):
    c1 = CoordinationClient("127.0.0.1", server.port)
    c1.qpush("chaos/q", b"grad-blob", token="tok-q-1")
    c1.close()
    c2 = CoordinationClient("127.0.0.1", server.port)
    c2.qpush("chaos/q", b"grad-blob", token="tok-q-1")  # retry: deduped
    assert c2.qlen("chaos/q") == 1
    assert c2.qpop("chaos/q") == b"grad-blob"
    assert c2.qlen("chaos/q") == 0
    c2.close()


def test_bput_token_replay(server):
    c = CoordinationClient("127.0.0.1", server.port)
    c.bput("chaos/blob", 3, b"v3", token="tok-b-1")
    # meanwhile a newer version lands (no token)
    c.bput("chaos/blob", 4, b"v4")
    # the stale retry replays OK but must NOT clobber version 4
    c.bput("chaos/blob", 3, b"v3", token="tok-b-1")
    assert c.bget("chaos/blob") == (4, b"v4")
    c.close()


def test_barrier_token_replay_does_not_rewait(server):
    """After a 1-of-1 barrier fired, a retried arrival with the same token
    gets OK immediately — it must not park waiting for peers who already
    passed (the retried-after-release hang)."""
    c = CoordinationClient("127.0.0.1", server.port)
    c.barrier("chaos/b", 1, token="tok-bar-1")
    c.close()
    c2 = CoordinationClient("127.0.0.1", server.port, timeout=5.0)
    c2.barrier("chaos/b", 1, token="tok-bar-1")  # would hang without replay
    c2.close()


def test_parked_barrier_drop_then_retry_counts_once(server):
    """A barrier arrival whose connection DIES while parked is forgotten;
    the client's retry (same token) is the single arrival — the barrier
    needs exactly num_workers live arrivals to fire."""
    dead = CoordinationClient("127.0.0.1", server.port)
    dead._sock.sendall(b"BARRIER chaos/b2 2 tok-bar-2\n")
    time.sleep(0.2)
    dead._sock.close()  # dropped while parked: arrival must be forgotten
    time.sleep(0.2)
    released = threading.Event()

    def retry_then_wait():
        c = CoordinationClient("127.0.0.1", server.port)
        c.barrier("chaos/b2", 2, token="tok-bar-2")  # the retry
        released.set()
        c.close()

    t = threading.Thread(target=retry_then_wait, daemon=True)
    t.start()
    time.sleep(0.3)
    assert not released.is_set()  # one live arrival, not two
    c = CoordinationClient("127.0.0.1", server.port)
    c.barrier("chaos/b2", 2)  # the second worker releases it
    t.join(timeout=5)
    assert released.is_set()
    c.close()


# --------------------------------------------------------------------------
# FaultyProxy: fault classes on the real wire path
# --------------------------------------------------------------------------

def test_connection_reset_storm_exactly_once(server):
    """Ambiguous drops (request applied, reply lost, TCP RST) on every 3rd
    non-PING RPC: the resilient client retries on its idempotency token
    and the counter advances EXACTLY once per logical increment — final
    state bit-identical to the fault-free run."""
    plan = FaultPlan({"seed": 7, "faults": [
        {"op": "reset", "match": "INC", "nth": 3, "repeat": True,
         "when": "after"}]})
    with FaultyProxy("127.0.0.1", server.port, plan=plan) as proxy:
        rc = ResilientCoordinationClient("127.0.0.1", proxy.port,
                                         rpc_timeout=5.0, seed=0)
        values = [rc.incr("chaos/storm") for _ in range(10)]
        rc.close()
    assert values == list(range(1, 11)), values
    assert any(i.startswith("reset:") for i in plan.injected), plan.injected
    # ground truth straight from the service, no proxy
    c = CoordinationClient("127.0.0.1", server.port)
    assert c.incr("chaos/storm") == 11
    c.close()


def test_qpush_through_resets_no_duplicates(server):
    """Gradient-push shaped traffic through ambiguous resets: every blob
    arrives exactly once, in order."""
    plan = FaultPlan({"seed": 3, "faults": [
        {"op": "reset", "match": "QPUSHB", "nth": 2, "repeat": True,
         "when": "after"}]})
    with FaultyProxy("127.0.0.1", server.port, plan=plan) as proxy:
        rc = ResilientCoordinationClient("127.0.0.1", proxy.port,
                                         rpc_timeout=5.0, seed=0)
        for i in range(6):
            rc.qpush("chaos/gq", b"blob-%d" % i)
        rc.close()
    c = CoordinationClient("127.0.0.1", server.port)
    assert c.qlen("chaos/gq") == 6
    got = [c.qpop("chaos/gq") for _ in range(6)]
    assert got == [b"blob-%d" % i for i in range(6)]
    c.close()


def test_rpc_delay_past_deadline_is_retried(server):
    """An RPC held beyond the client deadline turns into a timeout +
    retry, not an eternal stall. The delay rule fires once; the retry
    lands on the fast path."""
    plan = FaultPlan({"seed": 1, "faults": [
        {"op": "delay", "match": "GET", "nth": 1, "delay_s": 1.0}]})
    with FaultyProxy("127.0.0.1", server.port, plan=plan) as proxy:
        rc = ResilientCoordinationClient("127.0.0.1", proxy.port,
                                         rpc_timeout=0.25, seed=0)
        rc.put("chaos/k", "v")
        t0 = time.monotonic()
        assert rc.get("chaos/k") == "v"
        elapsed = time.monotonic() - t0
        assert rc.stats["retries"] >= 1
        assert elapsed < 10.0
        rc.close()


def test_truncated_blob_detected_and_retried(server):
    """A value blob cut mid-payload (proxy forwards 64 bytes then RST):
    the client sees a dead connection — never a silently short array —
    and the retry fetches the full bit-exact payload."""
    payload = np.arange(4096, dtype=np.float32).tobytes()
    seed_client = CoordinationClient("127.0.0.1", server.port)
    seed_client.bput("chaos/big", 9, payload)
    seed_client.close()
    plan = FaultPlan({"seed": 2, "faults": [
        {"op": "truncate", "match": "BGETB", "nth": 1, "bytes": 64}]})
    with FaultyProxy("127.0.0.1", server.port, plan=plan) as proxy:
        rc = ResilientCoordinationClient("127.0.0.1", proxy.port,
                                         rpc_timeout=5.0, seed=0)
        ver, got = rc.bget("chaos/big")
        rc.close()
    assert (ver, got) == (9, payload)
    assert "truncate:BGETB" in plan.injected


def test_service_restart_midrun_reconnects(server):
    """Control-plane crash mid-run (restart-at-step-N): the service is
    killed and relaunched on the same port when step 3 passes; the
    resilient client reconnects through the same proxy address and keeps
    working. Volatile state died with the service — the documented
    contract — so only post-restart semantics are asserted."""
    restarts = []

    def restart_service():
        server.stop()
        server.start()
        restarts.append(time.monotonic())

    plan = FaultPlan({"seed": 5, "faults": [{"op": "restart", "at_step": 3}]})
    with FaultyProxy("127.0.0.1", server.port, plan=plan,
                     restart_fn=restart_service) as proxy:
        rc = ResilientCoordinationClient("127.0.0.1", proxy.port,
                                         rpc_timeout=5.0, seed=0)
        for step in range(1, 6):
            rc.report_step("w0", step)
        # the restart runs on the proxy's connection thread: the client's
        # retries only complete once the NEW service is up, but the
        # callback's bookkeeping can trail them by a beat — wait for it
        deadline = time.monotonic() + 10
        while not restarts and time.monotonic() < deadline:
            time.sleep(0.02)
        assert restarts, "restart fault never fired"
        assert "restart:STEP" in plan.injected
        rc.put("chaos/after", "alive")
        assert rc.get("chaos/after") == "alive"
        # retried/post-restart STEPs landed on the fresh service only
        assert 3 <= rc.min_step() <= 5
        rc.close()


def test_fault_plan_parsing_env_and_file(tmp_path, monkeypatch):
    spec = {"seed": 42, "faults": [
        {"op": "delay", "match": "PUT", "nth": 2, "delay_s": 0.1}]}
    monkeypatch.setenv("ADT_FAULT_PLAN", json.dumps(spec))
    plan = FaultPlan.from_env()
    assert plan.seed == 42 and len(plan.rules) == 1
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(spec))
    monkeypatch.setenv("ADT_FAULT_PLAN", "@%s" % p)
    assert len(FaultPlan.from_env().rules) == 1
    monkeypatch.setenv("ADT_FAULT_PLAN", str(p))  # bare path works too
    assert FaultPlan.from_env().seed == 42
    # determinism: same seed -> same probabilistic decisions
    mk = lambda: FaultPlan({"seed": 9, "faults": [  # noqa: E731
        {"op": "delay", "match": "*", "prob": 0.5, "delay_s": 0}]})
    a, b = mk(), mk()
    decisions_a = [bool(a.decide("GET", None)) for _ in range(32)]
    decisions_b = [bool(b.decide("GET", None)) for _ in range(32)]
    assert decisions_a == decisions_b


# --------------------------------------------------------------------------
# resilient client: deadlines, retry budget, circuit breaker
# --------------------------------------------------------------------------

def test_retry_budget_exhaustion_is_loud():
    dead_port = _free_port()  # nothing listens here
    rc = ResilientCoordinationClient("127.0.0.1", dead_port,
                                     max_retries=1, backoff_base_s=0.01,
                                     breaker_failures=100, seed=0)
    with pytest.raises(CoordinationUnavailable, match="failed after 2"):
        rc.ping()
    rc.close()


def test_circuit_breaker_opens_then_recovers():
    port = _free_port()
    rc = ResilientCoordinationClient(
        "127.0.0.1", port, max_retries=1, backoff_base_s=0.01,
        breaker_failures=2, breaker_cooldown_s=0.4, seed=0)
    with pytest.raises(CoordinationUnavailable):
        rc.ping()  # 2 transport failures -> breaker opens
    t0 = time.monotonic()
    with pytest.raises(CircuitOpenError):
        rc.ping()  # fails FAST, no connect attempts
    assert time.monotonic() - t0 < 0.3
    # service appears; after the cooldown the half-open probe succeeds
    srv = CoordinationServer(port=port)
    srv.start()
    try:
        time.sleep(0.5)
        assert rc.ping()
        assert rc.stats["breaker_opens"] >= 1
    finally:
        rc.close()
        srv.stop()


# --------------------------------------------------------------------------
# graceful degradation: owner apply loop + worker pulls + watchdog
# --------------------------------------------------------------------------

class _FlakyService(pss.LocalPSService):
    """In-process service whose transport can be forced down (every call
    raises ConnectionResetError) and counts reconnect() kicks."""

    def __init__(self):
        super().__init__()
        self.down = False
        self.reconnects = 0

    def _check(self):
        if self.down:
            raise ConnectionResetError("injected transport failure")

    def reconnect(self):
        self.reconnects += 1

    def publish(self, version, blob):
        self._check()
        super().publish(version, blob)

    def fetch(self):
        self._check()
        return super().fetch()

    def push_grads(self, blob):
        self._check()
        super().push_grads(blob)

    def pop_grads(self):
        self._check()
        return super().pop_grads()

    def pending_grads(self):
        self._check()
        return super().pending_grads()


def _worker_pair(service, **kw):
    applied = []

    def apply_fn(arrays):
        applied.append(arrays["g"].copy())

    worker = pss.AsyncPSWorker(
        service, apply_fn,
        lambda: {"v": np.full((2,), float(len(applied)), np.float32)}, **kw)
    return worker, applied


def test_async_worker_survives_service_blip():
    """The owner apply loop used to die silently on the first transport
    error from pop_grads; now it reconnects, republishes its last applied
    version, and keeps applying."""
    svc = _FlakyService()
    worker, applied = _worker_pair(svc, reconnect_budget_s=30.0)
    worker.start()
    try:
        svc.push_grads(pss.pack_arrays({"g": np.ones(2, np.float32)}))
        deadline = time.monotonic() + 10
        while len(applied) < 1:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        svc.down = True           # service blip...
        time.sleep(0.3)
        assert worker.healthy     # degraded, not dead
        assert worker.last_error is not None
        svc.down = False          # ...service returns
        deadline = time.monotonic() + 10
        while svc.fetch() is None or svc.fetch()[0] != 1:
            assert time.monotonic() < deadline, "no republish after blip"
            time.sleep(0.005)
        svc.push_grads(pss.pack_arrays({"g": np.ones(2, np.float32) * 2}))
        deadline = time.monotonic() + 10
        while len(applied) < 2:
            assert time.monotonic() < deadline, "applies did not resume"
            time.sleep(0.005)
        assert worker.healthy and worker.last_error is None
        assert svc.reconnects >= 1
    finally:
        assert worker.stop()


def test_async_worker_unhealthy_after_budget_and_runner_fails_loud():
    """Budget exhausted -> healthy flips False with last_error set, and
    the Runner-side check turns that into a loud RuntimeError instead of
    a silent stall."""
    svc = _FlakyService()
    worker, _applied = _worker_pair(svc, reconnect_budget_s=0.3)
    worker.start()
    try:
        svc.down = True
        deadline = time.monotonic() + 10
        while worker.healthy:
            assert time.monotonic() < deadline, "never turned unhealthy"
            time.sleep(0.02)
        assert worker.last_error is not None

        # Runner._check_ps_owner_health against a stub store wired to this
        # worker (full Runner construction needs a compiled step)
        from autodist_tpu.runtime.runner import Runner

        class _StubStore:
            serving = True

            @staticmethod
            def owner_health_errors():
                return [("hostA", str(worker.last_error))]

        class _StubStep:
            ps_store = _StubStore()

        stub = Runner.__new__(Runner)
        stub._dstep = _StubStep()
        with pytest.raises(RuntimeError, match="owner apply loop"):
            Runner._check_ps_owner_health(stub)
    finally:
        worker.stop()


def test_worker_pull_degrades_to_last_fetch_then_fails(monkeypatch):
    """A worker that cannot reach an owner serves its LAST fetched values
    for up to the staleness/lag bound (training continues through a
    blip), then fails with an explicit diagnostic."""
    import optax
    from autodist_tpu.model_item import VarInfo
    from autodist_tpu.parallel.ps import PSStore, PSVarPlan

    monkeypatch.setenv("ADT_PS_MAX_LAG", "2")  # degraded window = 2 pulls
    infos = {"w": VarInfo(name="w", shape=(4, 2), dtype="float32")}
    plans = {"w": PSVarPlan(var_name="w", destinations=("hostA:CPU:0",),
                            sync=False)}
    init = {"w": np.ones((4, 2), np.float32)}
    owner_svc = _FlakyService()

    owner = PSStore(dict(plans), infos, optax.sgd(0.1))
    owner.init_params(init)
    owner.enable_serving(lambda host: owner_svc, my_host="hostA")
    try:
        worker = PSStore(dict(plans), infos, optax.sgd(0.1))
        worker.init_params(init)
        worker.enable_serving(lambda host: owner_svc, my_host="hostB")
        vals = worker.pull()  # healthy fetch, primes the cache
        np.testing.assert_array_equal(vals["w"], np.ones((4, 2)))
        owner_svc.down = True
        for i in range(2):  # inside the window: serve the cached fetch
            vals = worker.pull()
            np.testing.assert_array_equal(vals["w"], np.ones((4, 2)))
        assert worker.stats["degraded_pulls"] == 2
        with pytest.raises(RuntimeError, match="degraded-serve window"):
            worker.pull()  # window exhausted: loud failure
    finally:
        owner_svc.down = False
        owner.close()


def test_watchdog_supervision_resumes_after_service_bounce(tmp_path):
    """Regression for the one-shot watchdog client: bounce the service
    under a live watchdog, then let a worker go silent — the watchdog
    must still detect it and abort (supervision RESUMED after the blip;
    before the fix the first OSError ended supervision forever). Run in a
    subprocess because the watchdog aborts via os._exit(1)."""
    port = _free_port()
    script = tmp_path / "watchdog_bounce.py"
    script.write_text("""
import sys, time
PORT = %d
from autodist_tpu.runtime.coordination import CoordinationServer, CoordinationClient
from autodist_tpu.runtime.coordinator import Coordinator
from autodist_tpu.runtime.cluster import Cluster
from autodist_tpu.resource_spec import ResourceSpec

srv = CoordinationServer(PORT)
srv.start()

class _S:
    id = "watchdog-bounce-test"

spec = ResourceSpec.from_dict(
    {"nodes": [{"address": "127.0.0.1", "chief": True, "cpus": [0]}]})
coord = Coordinator(_S(), Cluster(spec, coordsvc_port=PORT),
                    heartbeat_timeout=1.0)
coord.start_watchdog()
print("WATCHDOG_UP", flush=True)
time.sleep(1.5)   # let the watchdog poll at least once
srv.stop()        # service blip: the old client dies mid-supervision
time.sleep(1.0)
srv = CoordinationServer(PORT)
srv.start()       # service returns on the same port
print("BOUNCED", flush=True)
c = CoordinationClient("127.0.0.1", PORT)
c.heartbeat("w1") # fresh record on the fresh service...
c.close()
time.sleep(20)    # ...that then goes silent: the (reconnected) watchdog
print("STILL_ALIVE", flush=True)  # must have aborted us before this
""" % port)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(HERE)
    try:
        proc = subprocess.run([sys.executable, str(script)], env=env,
                              capture_output=True, text=True, timeout=120)
    finally:
        subprocess.run(["pkill", "-f", "coordination_service %d" % port],
                       check=False)
    assert "WATCHDOG_UP" in proc.stdout, proc.stdout + proc.stderr
    assert "BOUNCED" in proc.stdout, proc.stdout + proc.stderr
    assert "STILL_ALIVE" not in proc.stdout, proc.stdout
    assert proc.returncode == 1


# --------------------------------------------------------------------------
# server lifecycle + configurable timeouts (satellites)
# --------------------------------------------------------------------------

def test_server_stop_kills_wedged_service():
    """stop() against a wedged service (SIGSTOP: accepts connections,
    answers nothing) must fall through to SIGKILL within its deadline —
    not hang forever on the SHUTDOWN reply."""
    srv = CoordinationServer(port=_free_port())
    srv.start()
    proc = srv._proc
    os.kill(proc.pid, signal.SIGSTOP)
    try:
        t0 = time.monotonic()
        srv.stop()
        assert time.monotonic() - t0 < 15.0
        assert proc.poll() is not None, "wedged service not killed"
    finally:
        if proc.poll() is None:
            os.kill(proc.pid, signal.SIGCONT)
            proc.kill()


def test_connect_timeout_env_plumbed(monkeypatch):
    captured = {}
    real_create = socket.create_connection

    def fake_create(addr, timeout=None, **kw):
        captured["timeout"] = timeout
        raise OSError("probe only")

    monkeypatch.setattr(socket, "create_connection", fake_create)
    monkeypatch.setenv("ADT_CONNECT_TIMEOUT_S", "1.25")
    with pytest.raises(OSError):
        CoordinationClient("127.0.0.1", 1)
    assert captured["timeout"] == 1.25
    # explicit argument beats the env default
    with pytest.raises(OSError):
        CoordinationClient("127.0.0.1", 1, connect_timeout=0.5)
    assert captured["timeout"] == 0.5
    monkeypatch.setattr(socket, "create_connection", real_create)


def test_server_start_timeout_env(monkeypatch):
    """ADT_COORDSVC_START_TIMEOUT_S bounds the bring-up wait, and the
    timeout path reaps the unresponsive process instead of leaking it."""
    import autodist_tpu.runtime.coordination as coordination

    class _NeverUp:
        def __init__(self, *a, **k):
            raise ConnectionRefusedError("never up")

    monkeypatch.setattr(coordination, "CoordinationClient", _NeverUp)
    monkeypatch.setenv("ADT_COORDSVC_START_TIMEOUT_S", "0.3")
    srv = CoordinationServer(port=_free_port())
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="ADT_COORDSVC_START_TIMEOUT_S"):
        srv.start()
    assert time.monotonic() - t0 < 5.0
    assert srv._proc is None  # not leaked


# --------------------------------------------------------------------------
# two-process end-to-end chaos matrix (nightly; slow)
# --------------------------------------------------------------------------

CHAOS_USER_SCRIPT = """
import json, os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import optax
import autodist_tpu as adt
from autodist_tpu import strategy

spec, outdir = sys.argv[1], sys.argv[2]
ad = adt.AutoDist(resource_spec_file=spec,
                  strategy_builder=strategy.PS(sync=False))
import jax.numpy as jnp
rng = np.random.RandomState(0)
params = {"w": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32)}

def loss_fn(p, batch):
    return jnp.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

batch = {"x": rng.randn(8, 8).astype(np.float32),
         "y": rng.randn(8, 4).astype(np.float32)}
step = ad.function(loss_fn, optimizer=optax.sgd(0.05), params=params)
is_worker = bool(os.environ.get("ADT_WORKER"))
losses = []
for i in range(12):
    losses.append(float(step(batch)["loss"]))
    time.sleep(0.05)  # stretch the run so injected faults land mid-train
if is_worker:
    with open(os.path.join(outdir, "out_worker.json"), "w") as f:
        json.dump({"losses": losses}, f)
    print("WORKER_DONE", flush=True)
else:
    worker_out = os.path.join(outdir, "out_worker.json")
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and not os.path.exists(worker_out):
        time.sleep(0.1)
    applied = ad.runner.distributed_step.ps_store.applied_total()
    with open(os.path.join(outdir, "out_chief.json"), "w") as f:
        json.dump({"losses": losses, "applied": applied,
                   "worker_done": os.path.exists(worker_out)}, f)
    print("CHIEF_DONE", flush=True)
"""

CHAOS_SPEC_YAML = """
nodes:
  - address: 127.0.0.1
    chief: true
    cpus: [0, 1]
  - address: localhost
    cpus: [0, 1]
"""

E2E_FAULT_PLANS = {
    # ambiguous gradient-push drops: applied server-side, reply lost
    "reset": {"seed": 11, "faults": [
        {"op": "reset", "match": "QPUSHB", "nth": 4, "repeat": True,
         "when": "after"}]},
    # value fetches held past the 0.5s RPC deadline -> timeout + retry
    "delay": {"seed": 12, "faults": [
        {"op": "delay", "match": "BGETB", "nth": 6, "repeat": True,
         "delay_s": 1.0}]},
    # a value blob cut mid-payload -> dead connection, never a short read
    "truncate": {"seed": 13, "faults": [
        {"op": "truncate", "match": "BGETB", "nth": 5, "bytes": 128}]},
    # service restart handled by the parent (see bounce below)
    "restart": {"seed": 14, "faults": []},
}


def _run_chaos_pair(tmp_path, plan, bounce_service=False):
    """REAL two-process async-PS run (the chief-launched elastic flow:
    chief owns the variables and launches the worker; no jax.distributed
    join) with every coordination RPC routed through a FaultyProxy. The
    real service runs on a hidden port; the proxy holds the advertised
    ``ADT_COORDSVC_PORT`` (the chief's own service bring-up loses the
    bind race and degrades to using ours — by design)."""
    svc_port = _free_port()
    srv = CoordinationServer(port=svc_port)
    srv.start()
    proxy = FaultyProxy("127.0.0.1", svc_port, plan=plan)
    proxy.start()
    script = tmp_path / "user_script.py"
    script.write_text(CHAOS_USER_SCRIPT)
    spec = tmp_path / "spec.yml"
    spec.write_text(CHAOS_SPEC_YAML)
    env = dict(os.environ)
    for k in ("JAX_PLATFORMS", "ADT_DEBUG_REMOTE", "ADT_WORKER"):
        env.pop(k, None)
    env.update({
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "ADT_COORDINATOR_ADDR": "127.0.0.1:%d" % _free_port(),
        "ADT_COORDSVC_PORT": str(proxy.port),
        "ADT_ELASTIC": "1",
        "ADT_RPC_TIMEOUT_S": "0.5",  # so injected delays exceed it
        # widen the degraded-pull window so a service bounce that lines up
        # badly with a worker's retry schedule degrades instead of
        # consuming the whole window (the window-exhaustion abort has its
        # own dedicated test; here we assert SURVIVAL)
        "ADT_PS_MAX_LAG": "4",
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE)] +
            ([os.environ["PYTHONPATH"]]
             if os.environ.get("PYTHONPATH") else [])),
    })
    try:
        proc = subprocess.Popen(
            [sys.executable, str(script), str(spec), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        if bounce_service:
            # control-plane crash mid-run: kill the REAL service once
            # training is under way, restart it on the same hidden port;
            # every client reconnects through the unchanged proxy address
            time.sleep(8.0)
            srv.stop()
            time.sleep(0.5)
            srv.start()
        out, err = proc.communicate(timeout=240)
    finally:
        proxy.stop()
        srv.stop()
    return proc.returncode, out, err


def _assert_chaos_run_healthy(tmp_path, rc, out, err, plan):
    assert rc == 0, out + err
    chief = json.loads((tmp_path / "out_chief.json").read_text())
    worker = json.loads((tmp_path / "out_worker.json").read_text())
    assert chief["worker_done"] is True
    for r in (chief, worker):
        assert len(r["losses"]) == 12          # no stall: every step ran
        assert np.isfinite(r["losses"]).all()  # no corruption
        assert r["losses"][-1] < r["losses"][0]
    # gradients kept flowing through the faults: the chief's owner loop
    # applied blobs beyond its own pushes
    assert chief["applied"] >= len(chief["losses"])


@pytest.mark.slow
@pytest.mark.parametrize("fault", sorted(E2E_FAULT_PLANS))
def test_two_process_async_ps_under_faults(tmp_path, fault):
    """The acceptance gate: under each injected fault class the REAL
    two-process async-PS run completes with finite, decreasing loss on
    both processes — no stall, no crash, no double-applied gradients
    (the idempotent QPUSH retries land exactly once)."""
    plan = FaultPlan(E2E_FAULT_PLANS[fault])
    rc, out, err = _run_chaos_pair(tmp_path, plan,
                                   bounce_service=(fault == "restart"))
    _assert_chaos_run_healthy(tmp_path, rc, out, err, plan)
    if E2E_FAULT_PLANS[fault]["faults"]:
        assert plan.injected, "fault plan never fired — test proves nothing"


@pytest.mark.slow
def test_two_process_sync_barrier_loss_parity_under_resets(tmp_path,
                                                           monkeypatch):
    """Sync lockstep run with staleness pacing riding the coordination
    service through ambiguous STEP resets: pacing is control-plane only,
    so the losses must match the fault-free two-process run BIT-EXACTLY —
    the idempotent STEP retry may never skew training."""
    from tests.test_distributed import (_launch_pair,
                                        _single_process_reference)

    svc_port = _free_port()
    srv = CoordinationServer(port=svc_port)
    srv.start()
    plan = FaultPlan({"seed": 21, "faults": [
        {"op": "reset", "match": "STEP", "nth": 3, "repeat": True,
         "when": "after"}]})
    proxy = FaultyProxy("127.0.0.1", svc_port, plan=plan)
    proxy.start()
    monkeypatch.setenv("ADT_COORDSVC_PORT", str(proxy.port))
    try:
        chief, worker = _launch_pair(tmp_path, "PSStale", n_steps=8,
                                     external=True)
        np.testing.assert_array_equal(chief["losses"], worker["losses"])
        ref = _single_process_reference("PSStale", n_steps=8)
        np.testing.assert_allclose(chief["losses"], ref, rtol=1e-5,
                                   atol=1e-6)
        assert plan.injected, "fault plan never fired"
    finally:
        proxy.stop()
        srv.stop()
