"""Topology-aware communication analyzer (ADT520-525) + hierarchical
collective-schedule synthesis.

Pins the PR's acceptance contract: on a dryrun 2-level topology with a
slow inter-host link the searcher picks the hierarchical schedule and
the static per-level profile proves strictly fewer inter-host bytes than
the flat ring; on a flat mesh it refuses (ADT520 silent, ring retained);
synthesized schedules are numerically exact vs the epilogue psum.
"""
import json

import jax
import numpy as np
import pytest

from autodist_tpu.analysis import hlo
from autodist_tpu.analysis import topology as topo_lib
from autodist_tpu.analysis.diagnostics import Severity
from autodist_tpu.resource_spec import (ResourceSpec, Topology,
                                        TopologyConfigError)

POD64 = {"hosts": 8, "chips_per_host": 8,
         "levels": [{"name": "ici", "bandwidth_gbps": 400},
                    {"name": "dcn", "bandwidth_gbps": 25}]}


def pod64():
    return Topology.from_dict(POD64)


def codes(diags):
    return {d.code for d in diags}


def _sched(entries):
    return hlo.CollectiveSchedule(
        hlo.CollectiveOp(kind=k, op=k, payload_bytes=b, result_bytes=b,
                         replica_groups=g, channel=i, lineno=i,
                         loop_depth=0)
        for i, (k, b, g) in enumerate(entries))


FLAT64 = (tuple(range(64)),)                       # one ring over the pod
LEADERS = (tuple(range(0, 64, 8)),)                # one member per host
INTRA = tuple(tuple(range(h * 8, (h + 1) * 8)) for h in range(8))


# ------------------------------------------------- topology spec (sat. 2)


def test_malformed_topology_fails_loudly():
    """Zero/negative bandwidth, indivisible chips, a missing inter-host
    level: every malformed entry raises the named-knob
    ``TopologyConfigError``, never a bare traceback mid-build."""
    bad = [
        ({"hosts": 8, "chips": 63,
          "levels": [{"bandwidth_gbps": 1}, {"bandwidth_gbps": 1}]},
         "topology.chips"),
        ({"hosts": 2, "chips_per_host": 4,
          "levels": [{"bandwidth_gbps": 0}, {"bandwidth_gbps": 1}]},
         "bandwidth_gbps"),
        ({"hosts": 2, "chips_per_host": 4,
          "levels": [{"bandwidth_gbps": 10}, {"bandwidth_gbps": -3}]},
         "bandwidth_gbps"),
        ({"hosts": 2, "chips_per_host": 4,
          "levels": [{"bandwidth_gbps": 10}]},
         "topology.levels"),
        ({"hosts": 0, "chips_per_host": 4,
          "levels": [{"bandwidth_gbps": 10}]},
         "topology.hosts"),
        ({"hosts": 2, "chips_per_host": 4, "levels": []},
         "topology.levels"),
    ]
    for d, knob in bad:
        with pytest.raises(TopologyConfigError) as ei:
            Topology.from_dict(d)
        msg = str(ei.value)
        assert knob in msg and "unset it" in msg, (d, msg)


def test_topology_yaml_roundtrip(tmp_path):
    p = tmp_path / "pod.yml"
    p.write_text("topology:\n  hosts: 8\n  chips_per_host: 8\n  levels:\n"
                 "    - {name: ici, bandwidth_gbps: 400}\n"
                 "    - {name: dcn, bandwidth_gbps: 25}\n")
    topo = Topology.from_yaml(str(p))
    assert topo.num_devices == 64
    assert topo.intra_level.name == "ici"
    assert topo.inter_level.name == "dcn"
    assert Topology.from_dict(topo.to_dict()).to_dict() == topo.to_dict()
    with pytest.raises(TopologyConfigError):
        Topology.from_yaml(str(tmp_path / "missing.yml"))


def test_resource_spec_topology_section():
    """No ``topology:`` section -> ``topology()`` is None (flat specs are
    untouched); with one, the spec carries it and ``without_nodes``
    propagates it."""
    flat = ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 4}]})
    assert flat.topology() is None
    spec = ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 4}],
         "topology": POD64})
    assert spec.topology() is not None
    assert spec.topology().hosts == 8


# ------------------------------------------------------ per-level algebra


def test_per_level_byte_algebra():
    """Flat ring inter bytes = B * 2(n-1)/n * P (B = hosts spanned for a
    contiguous group); hierarchical inter bytes = 2(H-1) * P/c —
    strictly fewer exactly when c > 1, equal at c = 1 (leader groups)."""
    P = 4096.0
    flat = topo_lib.flat_inter_bytes(P, 64, 8)
    assert flat == pytest.approx(8 * 2 * 63 / 64 * P)
    hier = topo_lib.hier_inter_bytes(P, 8, 8)
    assert hier == pytest.approx(2 * 7 * P / 8)
    assert hier < flat
    # c == 1: the hierarchical leader ring IS the flat ring over leaders
    assert (topo_lib.hier_inter_bytes(P, 8, 1)
            == pytest.approx(topo_lib.flat_inter_bytes(P, 8, 8)))


def test_schedule_level_bytes_attribution():
    topo = pod64()
    P = 1024
    rows = topo_lib.schedule_level_bytes(
        _sched([("reduce", P, FLAT64)]), topo)
    per_link = 2 * 63 / 64 * P
    assert rows["dcn"] == pytest.approx(8 * per_link)
    assert rows["ici"] == pytest.approx((64 - 8) * per_link)
    # single-host group: all bytes on the intra level
    rows = topo_lib.schedule_level_bytes(
        _sched([("reduce", P, (INTRA[0],))]), topo)
    assert rows["dcn"] == 0.0
    assert rows["ici"] == pytest.approx(8 * 2 * 7 / 8 * P)


# --------------------------------------------------------- lowered lints


def test_flat_reduce_spanning_hosts_is_adt520():
    diags = topo_lib.lint_schedule(
        _sched([("reduce", 4096, FLAT64)]), pod64(), label="train.hlo")
    assert codes(diags) == {"ADT520"}
    d = diags[0]
    assert d.severity >= Severity.ERROR
    assert "train.hlo" in d.message
    # the proof: both byte counts are in the message (P=4096 on 8x8)
    assert "64512" in d.message and "7168" in d.message


def test_leader_subgroup_reduce_is_silent():
    """One member per host is exactly the hierarchical lowering's inter
    stage — ADT520 must not fire on it (c == 1: nothing to shrink)."""
    assert topo_lib.lint_schedule(
        _sched([("reduce", 4096, LEADERS)]), pod64()) == []


def test_hierarchical_lowering_is_clean():
    """The full synthesized composition (intra scatter, leader reduce,
    intra gather) lints clean on the topology it was synthesized for."""
    sched = _sched([("scatter", 4096, INTRA),
                    ("reduce", 512, LEADERS),
                    ("gather", 512, INTRA)])
    assert topo_lib.lint_schedule(sched, pod64()) == []


def test_noncontiguous_straddle_is_adt521():
    strided = (tuple(range(0, 64, 8)) + tuple(range(1, 64, 8)),)
    diags = topo_lib.lint_schedule(
        _sched([("gather", 256, strided)]), pod64())
    assert codes(diags) == {"ADT521"}
    assert all(d.severity < Severity.ERROR for d in diags)


def test_out_of_range_group_is_adt525():
    diags = topo_lib.lint_schedule(
        _sched([("reduce", 256, ((0, 1, 2, 999),))]), pod64())
    assert codes(diags) == {"ADT525"}
    assert diags[0].severity >= Severity.ERROR


def test_budget_overrun_is_adt523():
    topo = Topology.from_dict({
        "hosts": 8, "chips_per_host": 8,
        "levels": [{"name": "ici", "bandwidth_gbps": 400},
                   {"name": "dcn", "bandwidth_gbps": 25,
                    "budget_ms": 1e-9}]})
    diags = topo_lib.lint_schedule(
        _sched([("reduce", 4096, LEADERS)]), topo)
    assert "ADT523" in codes(diags)
    assert all(d.severity < Severity.ERROR
               for d in diags if d.code == "ADT523")


# ------------------------------------------- synthesis + equivalence (522)


def test_synthesized_candidates_are_reduction_equivalent():
    from autodist_tpu.parallel.collectives import (
        reduction_equivalent, synthesize_collective_candidates)
    cands = synthesize_collective_candidates(
        "g0", ("ici", "dcn"), intra_axes=("ici",), inter_axes=("dcn",),
        payload_elems=1024)
    assert set(cands) == {"ring", "rhd", "hier"}
    target = cands["ring"][0]
    for name, stages in cands.items():
        assert reduction_equivalent(stages, target), name
    # no intra/inter split -> no hierarchical candidate
    flat = synthesize_collective_candidates("g0", ("data",))
    assert set(flat) == {"ring", "rhd"}


def test_non_equivalent_composition_is_adt522():
    from autodist_tpu.parallel import collectives as C
    cands = C.synthesize_collective_candidates(
        "g0", ("ici", "dcn"), intra_axes=("ici",), inter_axes=("dcn",))
    target = cands["ring"][0]
    # scatter with no matching gather: shards never re-broadcast
    broken = (cands["hier"][0], cands["hier"][1])
    diags = topo_lib.lint_stage_composition(broken, target, var="w")
    assert codes(diags) == {"ADT522"}
    assert diags[0].severity >= Severity.ERROR
    # gather over the WRONG axes: result layout diverges per device
    mismatched = (cands["hier"][0], cands["hier"][1],
                  C.CollectiveOp(kind="all_gather", unit="g0",
                                 axes=("dcn",), var_names=(),
                                 payload_elems=0, wire_dtype="fp32"))
    assert codes(topo_lib.lint_stage_composition(
        mismatched, target)) == {"ADT522"}


# ------------------------------------------- static profile level rows


def test_static_profile_hier_strictly_fewer_inter_bytes():
    """The acceptance proof, at the profile level: the hierarchical
    lowering's static per-level profile crosses strictly fewer
    inter-host bytes than the flat ring's for the same payload."""
    from autodist_tpu.simulator.cost_model import StaticCollectiveProfile
    topo = pod64()
    P = 64 * 1024
    flat = StaticCollectiveProfile.from_schedule(
        _sched([("reduce", P, FLAT64)]), topology=topo)
    hier = StaticCollectiveProfile.from_schedule(
        _sched([("scatter", P, INTRA),
                ("reduce", P // 8, LEADERS),
                ("gather", P // 8, INTRA)]), topology=topo)
    assert flat.level_wire_bytes["dcn"] > 0
    assert hier.level_wire_bytes["dcn"] < flat.level_wire_bytes["dcn"]
    # flat single-level profile keeps the old single-row accounting
    flat_no_topo = StaticCollectiveProfile.from_schedule(
        _sched([("reduce", P, FLAT64)]))
    assert flat_no_topo.level_wire_bytes == {}
    assert flat_no_topo.class_wire_bytes == flat.class_wire_bytes


def test_static_profile_multi_axis_combined_bytes():
    """Satellite: a dp x tp psum lowered as CHAINED ops prices each op at
    its own group size — pin the combined byte accounting."""
    from autodist_tpu.simulator.cost_model import StaticCollectiveProfile
    P_DP, P_TP = 4096, 1024
    dp_groups = ((0, 1, 2, 3), (4, 5, 6, 7))        # dp rings of 4
    tp_groups = ((0, 4), (1, 5), (2, 6), (3, 7))    # tp rings of 2
    prof = StaticCollectiveProfile.from_schedule(
        _sched([("reduce", P_DP, dp_groups), ("reduce", P_TP, tp_groups)]))
    expected = 2 * (4 - 1) / 4 * P_DP + 2 * (2 - 1) / 2 * P_TP
    assert prof.class_wire_bytes["reduce"] == pytest.approx(expected)
    assert prof.class_payload_bytes["reduce"] == P_DP + P_TP
    assert prof.num_collectives == 2


# ------------------------------------------------------- plan-level pass


def _tiny_item():
    import jax.numpy as jnp
    from autodist_tpu.model_item import ModelItem
    # big enough that bandwidth (not per-hop latency) dominates pricing
    params = {"W": jnp.zeros((256, 256)), "b": jnp.zeros((256,))}

    def loss_fn(p, batch):
        pred = batch["x"] @ p["W"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"x": jnp.zeros((8, 256), jnp.float32),
             "y": jnp.zeros((8, 256), jnp.float32)}
    return ModelItem(loss_fn=loss_fn, params=params,
                     example_batch=batch).prepare()


def _pod_spec(topo=None):
    from autodist_tpu.analysis.cli import topology_spec
    return topology_spec(topo or pod64())


def test_resolve_schedule_semantics():
    topo = pod64()
    assert topo_lib.resolve_schedule("auto", topo, 64) == "hier"
    assert topo_lib.resolve_schedule("auto", topo, 8) == "ring"
    assert topo_lib.resolve_schedule("auto", None, 64) == "ring"
    # explicit hier on a flat mesh is REFUSED back to ring
    assert topo_lib.resolve_schedule("hier", None, 64) == "ring"
    assert topo_lib.resolve_schedule("hier", topo, 64) == "hier"
    assert topo_lib.resolve_schedule("ring", topo, 64) == "ring"


def test_verify_pinned_ring_is_adt520_auto_is_silent():
    from autodist_tpu import strategy as S
    from autodist_tpu.analysis.rules import verify
    item = _tiny_item()
    spec = _pod_spec()
    strat = S.AllReduce().build(item, spec)
    assert not any(d.code.startswith("ADT52")
                   for d in verify(strat, item, spec))
    for n in strat.node_config:
        if n.synchronizer is not None and n.synchronizer.kind == "AllReduce":
            n.synchronizer.schedule = "ring"
    diags = [d for d in verify(strat, item, spec)
             if d.code.startswith("ADT52")]
    assert codes(diags) == {"ADT520"}
    assert all(d.severity >= Severity.ERROR for d in diags)


def test_verify_flat_spec_has_no_adt52x():
    """Flat single-level mesh: the rule is gated on a declared topology,
    so every existing spec lints exactly as before."""
    from autodist_tpu import strategy as S
    from autodist_tpu.analysis.cli import default_spec
    from autodist_tpu.analysis.rules import verify
    item = _tiny_item()
    spec = default_spec(8)
    strat = S.AllReduce().build(item, spec)
    for n in strat.node_config:
        if n.synchronizer is not None and n.synchronizer.kind == "AllReduce":
            n.synchronizer.schedule = "ring"
    assert not any(d.code.startswith("ADT52")
                   for d in verify(strat, item, spec))


def test_verify_replicas_exceeding_topology_is_adt525():
    from autodist_tpu import strategy as S
    from autodist_tpu.analysis.rules import verify
    item = _tiny_item()
    spec = _pod_spec()
    small = Topology.from_dict({
        "hosts": 2, "chips_per_host": 2,
        "levels": [{"name": "ici", "bandwidth_gbps": 400},
                   {"name": "dcn", "bandwidth_gbps": 25}]})
    strat = S.AllReduce().build(item, spec)
    spec.set_topology(small)
    diags = [d for d in verify(strat, item, spec)
             if d.code.startswith("ADT52")]
    assert codes(diags) == {"ADT525"}


def test_plan_level_bytes_hier_moves_bytes_off_inter():
    from autodist_tpu import strategy as S
    item = _tiny_item()
    topo = pod64()
    spec = _pod_spec(topo)
    strat = S.AllReduce().build(item, spec)  # auto -> hier on this pod
    hier = topo_lib.plan_level_bytes(strat, item, topo)
    for n in strat.node_config:
        if n.synchronizer is not None and n.synchronizer.kind == "AllReduce":
            n.synchronizer.schedule = "ring"
    ring = topo_lib.plan_level_bytes(strat, item, topo)
    assert 0 < hier["dcn"] < ring["dcn"]


# --------------------------------------------------- cost model + search


def test_cost_model_prices_hier_strictly_cheaper_on_slow_inter():
    from autodist_tpu.search.space import PlanSpace, VarChoice
    from autodist_tpu.simulator.cost_model import CostModel
    item = _tiny_item()
    spec = _pod_spec()
    space = PlanSpace(item, spec)
    cm = CostModel(item, spec)

    def ar_s(sched):
        plan = space.make_plan(
            {n: VarChoice(schedule=sched) for n in space.var_names})
        return cm.estimate(space.build(plan)).allreduce_s

    assert ar_s("hier") < ar_s("rhd") < ar_s("ring")
    assert ar_s("auto") == ar_s("hier")  # auto resolves hierarchical


def test_searcher_picks_hier_on_slow_inter_refuses_on_flat():
    """THE acceptance criterion: ranked over the seed families, the
    winning plan on the 2-level slow-inter pod resolves hierarchical;
    on the flat mesh the schedule axis cannot even express hier (ring
    retained by construction, ADT520 silent)."""
    from autodist_tpu.analysis.cli import default_spec
    from autodist_tpu.search.space import PlanSpace
    from autodist_tpu.simulator.cost_model import CostModel
    item = _tiny_item()
    topo = pod64()
    spec = _pod_spec(topo)
    space = PlanSpace(item, spec)
    assert space.schedule_options == ("auto", "ring", "rhd", "hier")
    assert "seed:ar-hier" in {name for name, _ in space.seeds()}
    cm = CostModel(item, spec)
    ranked = sorted(
        ((cm.estimate(space.build(p)).step_time_s, name, p)
         for name, p in space.seeds()), key=lambda t: t[:2])
    _, best_name, best = ranked[0]
    ar_choices = [c for _, c in best.choices if c.sync == "AllReduce"
                  and not c.zero and c.shards == 1]
    assert ar_choices, best_name
    resolved = {topo_lib.resolve_schedule(c.schedule, topo, 64)
                for c in ar_choices}
    assert resolved == {"hier"}, (best_name, resolved)

    flat_space = PlanSpace(item, default_spec(8))
    assert "hier" not in flat_space.schedule_options
    assert "seed:ar-hier" not in {n for n, _ in flat_space.seeds()}


def test_space_canon_strips_schedule_off_non_ar_paths():
    from autodist_tpu.search.space import PlanSpace, VarChoice
    item = _tiny_item()
    space = PlanSpace(item, _pod_spec())
    assert space.canon(VarChoice(sync="PS", schedule="hier"),
                       "W").schedule == "auto"
    assert space.canon(VarChoice(zero=True, schedule="hier"),
                       "W").schedule == "auto"
    assert space.canon(VarChoice(schedule="hier"), "W").schedule == "hier"


def test_synchronizer_schedule_round_trips():
    from autodist_tpu.strategy.base import (AllReduceSynchronizer,
                                            synchronizer_from_dict)
    s = AllReduceSynchronizer(schedule="hier")
    assert synchronizer_from_dict(s.to_dict()).schedule == "hier"
    # pre-schedule serialized plans (no key) default to auto
    d = s.to_dict()
    del d["schedule"]
    assert synchronizer_from_dict(d).schedule == "auto"


# ----------------------------------------------------- numerical parity


def test_rhd_psum_bitwise_matches_plain_psum():
    """Recursive halving/doubling is the same summation as the epilogue
    psum: with integer-valued floats the result is bitwise identical."""
    from jax.sharding import Mesh, PartitionSpec as P
    from autodist_tpu.parallel.collectives import rhd_psum
    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, ("data",))
    rng = np.random.RandomState(0)
    xs = rng.randint(-100, 100, size=(8, 5, 3)).astype(np.float32)

    def run(fn):
        f = jax.jit(jax.shard_map(
            lambda x: fn(x.reshape(5, 3)), mesh=mesh,
            in_specs=P("data"), out_specs=P(), check_vma=False))
        return np.asarray(f(xs))

    got = run(lambda x: rhd_psum(x, ("data",)))
    want = run(lambda x: jax.lax.psum(x, ("data",)))
    np.testing.assert_array_equal(got, want)
    # and the lowering carries the explicit scatter+gather composition
    f = jax.jit(jax.shard_map(
        lambda x: rhd_psum(x.reshape(5, 3), ("data",)), mesh=mesh,
        in_specs=P("data"), out_specs=P(), check_vma=False))
    hlo_text = f.lower(xs).as_text()
    assert "reduce_scatter" in hlo_text and "all_gather" in hlo_text


def test_hier_psum_bitwise_matches_plain_psum():
    from jax.sharding import Mesh, PartitionSpec as P
    from autodist_tpu.parallel.collectives import hierarchical_psum
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dcn", "ici"))
    rng = np.random.RandomState(1)
    xs = rng.randint(-100, 100, size=(8, 4, 3)).astype(np.float32)

    def run(fn):
        f = jax.jit(jax.shard_map(
            lambda x: fn(x.reshape(4, 3)), mesh=mesh,
            in_specs=P(("dcn", "ici")), out_specs=P(), check_vma=False))
        return np.asarray(f(xs))

    got = run(lambda x: hierarchical_psum(x, ("ici",), ("dcn",)))
    want = run(lambda x: jax.lax.psum(x, ("dcn", "ici")))
    np.testing.assert_array_equal(got, want)


def test_schedule_rhd_trains_identically_to_ring():
    """End to end through the lowering: a strategy pinned to rhd
    produces the same training trajectory as the flat ring (same
    summation, different route)."""
    import jax.numpy as jnp
    import optax

    import autodist_tpu as adt
    from autodist_tpu.strategy.base import (AllReduceSynchronizer,
                                            GraphConfig, Strategy,
                                            StrategyBuilder, VarConfig)
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(
        rng.randint(-3, 4, size=(8, 4)).astype(np.float32))}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    batch = {"x": rng.randint(-2, 3, size=(16, 8)).astype(np.float32),
             "y": rng.randint(-2, 3, size=(16, 4)).astype(np.float32)}

    def mk(schedule):
        class Pinned(StrategyBuilder):
            def build(self, model_item, resource_spec):
                return Strategy(
                    node_config=[VarConfig(
                        var_name="w",
                        synchronizer=AllReduceSynchronizer(
                            schedule=schedule))],
                    graph_config=GraphConfig(
                        replicas=[d.name_string()
                                  for d in resource_spec.devices]))
        adt.reset()
        ad = adt.AutoDist(strategy_builder=Pinned())
        runner = ad.build(loss_fn, optax.sgd(0.01), params, batch)
        runner.init(params)
        return [float(runner.run(batch)["loss"]) for _ in range(3)]

    ring = mk("ring")
    rhd = mk("rhd")
    assert ring == rhd
    assert rhd[-1] < rhd[0]


# ------------------------------------------------------------------ CLI


def test_cli_topology_clean_and_malformed(tmp_path, capsys):
    from autodist_tpu.analysis import cli
    good = tmp_path / "pod.yml"
    good.write_text("topology:\n  hosts: 8\n  chips_per_host: 8\n"
                    "  levels:\n    - {name: ici, bandwidth_gbps: 400}\n"
                    "    - {name: dcn, bandwidth_gbps: 25}\n")
    rc = cli.main(["linear_regression", "--strategy", "AllReduce",
                   "--topology", str(good), "--quiet"])
    assert rc == 0
    capsys.readouterr()
    bad = tmp_path / "bad.yml"
    bad.write_text("topology:\n  hosts: 8\n  chips: 63\n"
                   "  levels:\n    - {bandwidth_gbps: 400}\n"
                   "    - {bandwidth_gbps: 25}\n")
    rc = cli.main(["linear_regression", "--topology", str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "ADT524" in out and "topology.chips" in out


def test_cli_topology_pinned_ring_plan_exits_1(tmp_path, capsys):
    from autodist_tpu import strategy as S
    from autodist_tpu.analysis import cli
    item = _tiny_item()  # matches no CLI example; use strategy-json path
    spec = _pod_spec()
    strat = S.AllReduce().build(item, spec)
    for n in strat.node_config:
        if n.synchronizer is not None and n.synchronizer.kind == "AllReduce":
            n.synchronizer.schedule = "ring"
    plan = tmp_path / "plan.json"
    strat.serialize(path=str(plan))
    topo = tmp_path / "pod.yml"
    topo.write_text("topology:\n  hosts: 8\n  chips_per_host: 8\n"
                    "  levels:\n    - {name: ici, bandwidth_gbps: 400}\n"
                    "    - {name: dcn, bandwidth_gbps: 25}\n")
    rc = cli.main(["linear_regression", "--strategy-json", str(plan),
                   "--topology", str(topo), "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(d["code"] == "ADT520" for d in doc["diagnostics"])


_PROG_TMPL = """module @jit_step {
  func.func public @main(%%arg0: tensor<4xf32>) -> (tensor<4xf32>) {
    %%1 = "stablehlo.all_reduce"(%%arg0) <{channel_handle = \
#stablehlo.channel_handle<handle = 1, type = 1>, replica_groups = \
dense<%s> : tensor<%s>, use_global_device_ids}> ({
    ^bb0(%%arg2: tensor<f32>, %%arg3: tensor<f32>):
      %%9 = stablehlo.add %%arg2, %%arg3 : tensor<f32>
      stablehlo.return %%9 : tensor<f32>
    }) : (tensor<4xf32>) -> tensor<4xf32>
    return %%1 : tensor<4xf32>
  }
}
"""


def test_programs_mode_attributes_offending_file(tmp_path, capsys):
    """Satellite: cross-program findings must name the OFFENDING file's
    path (not just the reference's basename) so multi-file CI output is
    actionable."""
    from autodist_tpu.analysis import cli
    ref = tmp_path / "train.hlo"
    ref.write_text(_PROG_TMPL % ("[[0, 1, 2, 3]]", "1x4xi64"))
    sub = tmp_path / "sub"
    sub.mkdir()
    other = sub / "eval.hlo"
    other.write_text(_PROG_TMPL % ("[[0, 1], [2, 3]]", "2x2xi64"))
    rc = cli.main(["--programs", str(ref), str(other), "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0  # group mismatch is the ADT511 warning
    cross = doc["schedule_check"]["diagnostics"]
    assert cross, "expected an ADT511 cross-program finding"
    for d in cross:
        assert str(other) in (d["var"] or "") + d["message"]
    # the reference label is the full path too
    assert doc["schedule_check"]["reference"] == str(ref)


def test_programs_mode_topology_lint(tmp_path, capsys):
    """--programs --topology: the per-level ADT52x pass runs over every
    lowered program; a flat 4-wide all-reduce on a 2x2 topology spans
    hosts and fires ADT520 (exit 1)."""
    from autodist_tpu.analysis import cli
    prog = tmp_path / "train.hlo"
    prog.write_text(_PROG_TMPL % ("[[0, 1, 2, 3]]", "1x4xi64"))
    topo = tmp_path / "t.yml"
    topo.write_text("topology:\n  hosts: 2\n  chips_per_host: 2\n"
                    "  levels:\n    - {name: ici, bandwidth_gbps: 400}\n"
                    "    - {name: dcn, bandwidth_gbps: 25}\n")
    rc = cli.main(["--programs", str(prog), "--topology", str(topo),
                   "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    found = [d for p in doc["programs"] for d in p["diagnostics"]
             if d["code"] == "ADT520"]
    assert found and str(prog) in found[0]["var"] + found[0]["message"]


# ------------------------------------- cross-topology compare (satellite)


def test_compare_schedules_across_topologies_composes():
    """Programs lowered on DIFFERENT topology specs: the order/dtype
    checks still compose with per-level attribution — identical hier
    programs are clean, and the leader-subgroup collective never
    false-positives ADT520/511."""
    from autodist_tpu.analysis import numerics as numerics_lib
    hier_a = _sched([("scatter", 4096, INTRA),
                     ("reduce", 512, LEADERS),
                     ("gather", 512, INTRA)])
    hier_b = _sched([("scatter", 4096, INTRA),
                     ("reduce", 512, LEADERS),
                     ("gather", 512, INTRA)])
    assert hlo.compare_schedules(hier_a, hier_b) == []
    assert numerics_lib.compare_schedule_dtypes(hier_a, hier_b) == []
    # and each side's per-level attribution still works independently
    rows = topo_lib.schedule_level_bytes(hier_a, pod64())
    assert rows["dcn"] > 0 and rows["ici"] > 0
    flat_topo = Topology.from_dict({
        "hosts": 1, "chips_per_host": 64,
        "levels": [{"name": "ici", "bandwidth_gbps": 400}]})
    flat = _sched([("reduce", 4096, FLAT64)])
    # single-host topology: everything intra, ADT520 silent
    assert topo_lib.lint_schedule(flat, flat_topo) == []
    assert topo_lib.schedule_level_bytes(flat, flat_topo)["ici"] > 0


def test_drift_report_levels_section():
    from autodist_tpu import strategy as S
    from autodist_tpu.simulator.cost_model import (CostModel,
                                                   StaticCollectiveProfile)
    from autodist_tpu.telemetry import spans as spans_lib
    from autodist_tpu.telemetry.drift import DriftReport, build_report
    item = _tiny_item()
    topo = pod64()
    spec = _pod_spec(topo)
    strat = S.AllReduce().build(item, spec)
    cm = CostModel(item, spec)
    profile = StaticCollectiveProfile.from_schedule(
        _sched([("scatter", 4096, INTRA),
                ("reduce", 512, LEADERS),
                ("gather", 512, INTRA)]), topology=topo)
    rep = build_report(cm, strat, recorder=spans_lib.TraceRecorder(),
                       static_profile=profile)
    assert rep.levels is not None
    by_level = {row["level"]: row for row in rep.levels}
    assert set(by_level) == {"ici", "dcn"}
    assert by_level["dcn"]["predicted_bytes"] > 0
    assert by_level["dcn"]["measured_bytes"] is not None
    # round-trips through the serializer and renders
    rt = DriftReport.from_dict(rep.to_dict())
    assert rt.levels == rep.to_dict()["levels"]
    assert "level" in rep.format_table()
