"""Pre-compile strategy verifier: plan rules, mutation coverage, CLI.

Three layers of coverage, matching the analyzer's design:

1. every bundled strategy builder's emitted Strategy on the ``models/``
   zoo lints CLEAN (no error-severity diagnostics) on a 2x2 mesh spec;
2. mutation tests: each rule fires with its expected ``ADT`` code on a
   deliberately-broken plan, both through :func:`verify` and through the
   linter CLI (``--strategy-json`` -> nonzero exit);
3. the compile paths (``VarConfig``, ``StrategyCompiler``,
   ``VariablePartitioner``, ``synchronizer_from_dict``) raise
   ``DiagnosticError`` carrying the SAME codes — no rule implemented
   twice.
"""
import copy

import jax.numpy as jnp
import pytest

from autodist_tpu import const
from autodist_tpu.analysis import cli
from autodist_tpu.analysis.diagnostics import (Severity,
                                               StrategyVerificationError)
from autodist_tpu.analysis.lowered import lint_lowered_text
from autodist_tpu.analysis.rules import verify
from autodist_tpu.model_item import ModelItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import (AllReduceSynchronizer, PSSynchronizer,
                                        StrategyCompiler, VarConfig,
                                        synchronizer_from_dict)


def spec_2x2() -> ResourceSpec:
    """Single node, 4 chips — the 2x2 lint-time mesh."""
    return ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 4}]})


def errors(diags):
    return [d for d in diags if d.severity >= Severity.ERROR]


def codes(diags):
    return {d.code for d in diags}


@pytest.fixture(scope="module")
def emb_item() -> ModelItem:
    """Embedding + dense head (one sparse var) — the mutation target."""
    loss_fn, params, batch, _ = cli.EXAMPLES["sentiment_classifier"]()
    return ModelItem(loss_fn=loss_fn, params=params,
                     example_batch=batch).prepare()


# ------------------------------------------------- 1. builders lint clean


DP_BUILDERS = ["PS", "PSLoadBalancing", "PartitionedPS",
               "UnevenPartitionedPS", "AllReduce", "PartitionedAR",
               "RandomAxisPartitionAR", "Parallax", "SequenceParallelAR",
               "WithRemat"]


@pytest.fixture(scope="module")
def zoo_items():
    """ModelItems for a zoo cross-section: dense scalar model, embedding
    model, transformer LM, CNN."""
    out = {}
    for name in ("linear_regression", "sentiment_classifier", "lm1b",
                 "image_classifier"):
        loss_fn, params, batch, _ = cli.EXAMPLES[name]()
        out[name] = ModelItem(loss_fn=loss_fn, params=params,
                              example_batch=batch).prepare()
    return out


@pytest.mark.parametrize("builder_name", DP_BUILDERS)
def test_dp_builders_lint_clean(builder_name, zoo_items):
    spec = spec_2x2()
    builders = cli._builders(None)
    for model_name, item in zoo_items.items():
        strategy = builders[builder_name]().build(item, spec)
        diags = verify(strategy, item, spec)
        assert not errors(diags), (
            "%s on %s should lint clean, got: %s"
            % (builder_name, model_name,
               [d.format() for d in errors(diags)]))


@pytest.mark.parametrize("example,builder_name", [
    ("tp_lm", "TensorParallel"),
    ("pipe_lm", "PipelineParallel"),
    ("moe_lm", "ExpertParallel"),
])
def test_mp_builders_lint_clean(example, builder_name):
    spec = spec_2x2()
    loss_fn, params, batch, mp_rules = cli.EXAMPLES[example]()
    item = ModelItem(loss_fn=loss_fn, params=params,
                     example_batch=batch).prepare()
    strategy = cli._builders(mp_rules)[builder_name]().build(item, spec)
    diags = verify(strategy, item, spec)
    assert not errors(diags), [d.format() for d in errors(diags)]


# ----------------------------------------------------- 2. mutation tests


class DictItem:
    """Minimal model-item stand-in: a var_infos dict is all the builders
    and the verifier need."""

    def __init__(self, infos):
        self.var_infos = dict(infos)

    @property
    def trainable_var_names(self):
        return [n for n, v in self.var_infos.items() if v.trainable]


def clean_strategy(item, spec=None):
    from autodist_tpu.strategy import AllReduce
    if isinstance(item, dict):
        item = DictItem(item)
    return AllReduce().build(item, spec or spec_2x2())


def _mutations(item):
    """(name, mutate(strategy), expected code). Every plan starts from
    the lint-clean AllReduce build of the embedding model."""
    emb_dim0 = item.var_infos["embedding"].shape[0]

    def m_drop_node(s):
        s.node_config = [n for n in s.node_config
                         if n.var_name != "embedding"]

    def m_duplicate(s):
        s.node_config.append(copy.deepcopy(s.node_config[0]))

    def m_no_replicas(s):
        s.graph_config.replicas = []

    def m_bogus_replica(s):
        s.graph_config.replicas[0] = "10.9.9.9:TPU:0"

    def m_mesh_mismatch(s):
        s.graph_config.mesh_shape = {const.DATA_AXIS: 3,
                                     const.MODEL_AXIS: 2}

    def m_no_sync(s):
        s.find("embedding").synchronizer = None

    def m_partitioner_dangling(s):
        s.find("embedding").partitioner = "4,"

    def m_partitioner_alpha(s):
        s.find("embedding").partitioner = "a,1"

    def m_partitioner_rank(s):
        s.find("embedding").partitioner = "2,1,1"

    def m_partitioner_multi_axis(s):
        s.find("embedding").partitioner = "2,2"

    def m_shard_sizes(s):
        node = s.find("embedding")
        node.partitioner = "2,1"
        node.shard_sizes = [1, 2]  # sums to 3, dim is emb_dim0

    def m_ps_empty_dest(s):
        s.find("embedding").synchronizer = PSSynchronizer()

    def m_ps_bad_dest(s):
        s.find("embedding").synchronizer = PSSynchronizer(
            reduction_destination="10.9.9.9:CPU:0")

    def m_stale_async(s):
        s.find("embedding").synchronizer = PSSynchronizer(
            reduction_destination="127.0.0.1:CPU:0", sync=False,
            staleness=2)

    def m_bad_compressor(s):
        s.find("embedding").synchronizer = AllReduceSynchronizer(
            compressor="GzipCompressor")

    def m_mixed_async(s):
        s.find("embedding").synchronizer = PSSynchronizer(
            reduction_destination="127.0.0.1:CPU:0", sync=False)
        # the other vars stay AllReduce -> not all-or-nothing

    def m_mp_unknown_axis(s):
        s.find("embedding").synchronizer = None
        s.find("embedding").mp_axes = {0: const.MODEL_AXIS}  # no mesh

    def m_mp_indivisible(s):
        s.graph_config.mesh_shape = {const.DATA_AXIS: 2,
                                     const.MODEL_AXIS: 2}
        node = s.find("dense/bias")  # shape (1,): 1 % 2 != 0
        node.synchronizer = None
        node.mp_axes = {0: const.MODEL_AXIS}

    def m_mp_duplicate_axis(s):
        s.graph_config.mesh_shape = {const.DATA_AXIS: 2,
                                     const.MODEL_AXIS: 2}
        node = s.find("embedding")
        node.synchronizer = None
        node.mp_axes = {0: const.MODEL_AXIS, 1: const.MODEL_AXIS}

    def m_interleaved(s):
        s.graph_config.mesh_shape = {const.PIPELINE_AXIS: 2,
                                     const.DATA_AXIS: 2}
        s.graph_config.pp_schedule = "interleaved"
        s.graph_config.pp_microbatches = 3  # 3 % 2 != 0
        s.graph_config.pp_virtual = 2

    def m_sparse_dense(s):
        node = s.find("embedding")
        node.partitioner = "2,1"
        node.synchronizer = None
        node.part_configs = [
            VarConfig("embedding/part_%d" % i, AllReduceSynchronizer())
            for i in range(2)]
        s.graph_config.require_sparse = True

    assert emb_dim0 != 3  # m_shard_sizes relies on a wrong sum
    return [
        ("drop_node", m_drop_node, "ADT101"),
        ("duplicate_node", m_duplicate, "ADT103"),
        ("no_replicas", m_no_replicas, "ADT104"),
        ("bogus_replica", m_bogus_replica, "ADT105"),
        ("mesh_mismatch", m_mesh_mismatch, "ADT106"),
        ("no_synchronizer", m_no_sync, "ADT108"),
        ("partitioner_dangling", m_partitioner_dangling, "ADT201"),
        ("partitioner_alpha", m_partitioner_alpha, "ADT201"),
        ("partitioner_rank", m_partitioner_rank, "ADT202"),
        ("partitioner_multi_axis", m_partitioner_multi_axis, "ADT204"),
        ("shard_sizes", m_shard_sizes, "ADT208"),
        ("ps_empty_dest", m_ps_empty_dest, "ADT302"),
        ("ps_bad_dest", m_ps_bad_dest, "ADT303"),
        ("stale_async", m_stale_async, "ADT304"),
        ("bad_compressor", m_bad_compressor, "ADT305"),
        ("mixed_async", m_mixed_async, "ADT307"),
        ("mp_unknown_axis", m_mp_unknown_axis, "ADT205"),
        ("mp_indivisible", m_mp_indivisible, "ADT206"),
        ("mp_duplicate_axis", m_mp_duplicate_axis, "ADT207"),
        ("interleaved_microbatches", m_interleaved, "ADT402"),
        ("sparse_dense_wire", m_sparse_dense, "ADT309"),
    ]


def test_mutation_names_unique(emb_item):
    muts = _mutations(emb_item)
    names = [m[0] for m in muts]
    assert len(set(names)) == len(names) and len(muts) >= 8


def test_clean_baseline_has_no_errors(emb_item):
    assert not errors(verify(clean_strategy(emb_item), emb_item, spec_2x2()))


def test_each_mutation_fires_expected_code(emb_item):
    spec = spec_2x2()
    for name, mutate, code in _mutations(emb_item):
        s = clean_strategy(emb_item, spec)
        mutate(s)
        diags = verify(s, emb_item, spec)
        assert code in codes(errors(diags)), (
            "mutation %r should raise %s, got %s"
            % (name, code, [d.format() for d in diags]))


def test_cli_rejects_each_mutation(emb_item, tmp_path, capsys):
    """>= 8 mutation-broken plans through the REAL CLI entry point:
    nonzero exit and the expected ADT code in the table."""
    spec = spec_2x2()
    ran = 0
    for name, mutate, code in _mutations(emb_item):
        s = clean_strategy(emb_item, spec)
        mutate(s)
        try:
            path = s.serialize(str(tmp_path / name))
        except ValueError:
            continue  # mutations the serializer itself rejects
        rc = cli.main(["sentiment_classifier", "--strategy-json", path])
        out = capsys.readouterr().out
        assert rc == 1, "CLI should exit 1 for mutation %r" % name
        assert code in out, (name, code, out)
        ran += 1
    assert ran >= 8


def test_warning_rules_fire(emb_item):
    """Hazard rules that warn rather than error: pipeline bubble (401),
    PS load skew (403), no-op staleness window (404), compressor on a
    non-float dtype (306), undersized split dim (203)."""
    from autodist_tpu.model_item import VarInfo
    spec = spec_2x2()

    s = clean_strategy(emb_item, spec)
    s.graph_config.mesh_shape = {const.PIPELINE_AXIS: 2, const.DATA_AXIS: 2}
    s.graph_config.pp_schedule = "gpipe"
    s.graph_config.pp_microbatches = 1
    diags = verify(s, emb_item, spec)
    assert "ADT401" in codes(diags) and not errors(diags)

    two_node = ResourceSpec.from_dict({"nodes": [
        {"address": "10.0.0.1", "chief": True, "tpus": 2},
        {"address": "10.0.0.2", "tpus": 2}]})
    infos = {"big": VarInfo("big", (4096, 64), "float32"),
             "small": VarInfo("small", (4,), "float32")}
    skewed = clean_strategy(infos, two_node)
    skewed.find("big").synchronizer = PSSynchronizer(
        reduction_destination="10.0.0.1:CPU:0")
    skewed.find("small").synchronizer = PSSynchronizer(
        reduction_destination="10.0.0.2:CPU:0")
    assert "ADT403" in codes(verify(skewed, infos, two_node))

    s = clean_strategy(emb_item, spec)
    s.find("embedding").synchronizer = PSSynchronizer(
        reduction_destination="127.0.0.1:CPU:0", sync=True, staleness=2)
    assert "ADT404" in codes(verify(s, emb_item, spec))

    int_infos = {"steps": VarInfo("steps", (8, 8), "int32")}
    s = clean_strategy(int_infos, spec)
    s.find("steps").synchronizer = AllReduceSynchronizer(
        compressor="BF16Compressor")
    diags = verify(s, int_infos, spec)
    assert "ADT306" in codes(diags) and not errors(diags)

    tiny = {"t": VarInfo("t", (2, 8), "float32")}
    s = clean_strategy(tiny, spec)
    s.find("t").partitioner = "4,1"
    assert "ADT203" in codes(verify(s, tiny, spec))


# ------------------------------------------------------------ 3. CLI exit


def test_cli_clean_combo_exits_zero(capsys):
    rc = cli.main(["linear_regression", "--strategy", "PS"])
    assert rc == 0
    assert "plan is clean" in capsys.readouterr().out


def test_cli_json_output(capsys):
    rc = cli.main(["linear_regression", "--strategy", "AllReduce", "--json"])
    assert rc == 0
    import json
    doc = json.loads(capsys.readouterr().out)
    assert doc["errors"] == 0 and doc["strategy"] == "AllReduce"


def test_cli_usage_errors():
    assert cli.main([]) == 2
    assert cli.main(["nope", "--strategy", "PS"]) == 2
    assert cli.main(["linear_regression", "--strategy", "Bogus"]) == 2
    assert cli.main(["linear_regression", "--strategy", "TensorParallel"]) == 2


@pytest.mark.slow
def test_cli_subprocess_exit_codes(tmp_path, emb_item):
    """The module entry point itself: exit 0 on a clean combo, 1 on a
    broken plan."""
    import os
    import subprocess
    import sys
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.analysis", "linear_regression",
         "--strategy", "PS"], env=env, capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    s = clean_strategy(emb_item)
    s.find("embedding").partitioner = "4,"
    path = s.serialize(str(tmp_path / "broken"))
    r = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.analysis",
         "sentiment_classifier", "--strategy-json", path],
        env=env, capture_output=True, text=True)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "ADT201" in r.stdout


# ---------------------------------------- 4. shared rules on compile path


def test_varconfig_malformed_partitioner_raises_adt201():
    for bad in ("4,", "a,1", "0,2", ","):
        node = VarConfig("w", partitioner=bad)
        with pytest.raises(ValueError) as ei:
            node.num_shards
        assert getattr(ei.value, "code", None) == "ADT201", bad
        with pytest.raises(ValueError):
            node.partition_axis


def test_synchronizer_from_dict_names_kinds_and_var():
    with pytest.raises(ValueError) as ei:
        synchronizer_from_dict({"kind": "Gossip"}, var_name="dense/kernel")
    msg = str(ei.value)
    assert "Gossip" in msg and "dense/kernel" in msg
    assert "PS" in msg and "AllReduce" in msg
    assert getattr(ei.value, "code", None) == "ADT301"
    # invalid fields for a known kind also name the variable
    with pytest.raises(ValueError) as ei:
        synchronizer_from_dict({"kind": "PS", "bogus_field": 1},
                               var_name="emb")
    assert "emb" in str(ei.value)


def test_ps_synchronizer_empty_default_is_flagged(emb_item):
    """PSSynchronizer() defaults to an empty reduction_destination; the
    verifier must flag it (ADT302) rather than silently accepting."""
    assert PSSynchronizer().reduction_destination == ""
    s = clean_strategy(emb_item)
    s.find("embedding").synchronizer = PSSynchronizer()
    assert "ADT302" in codes(errors(verify(s, emb_item, spec_2x2())))


def test_strategy_compiler_raises_adt101(emb_item):
    s = clean_strategy(emb_item)
    s.node_config = s.node_config[1:]
    with pytest.raises(ValueError) as ei:
        StrategyCompiler(emb_item, spec_2x2()).compile(s)
    assert getattr(ei.value, "code", None) == "ADT101"


def test_partitioner_kernel_raises_same_code_as_lint(emb_item):
    """VariablePartitioner._mp_layout and the ADT206 rule are the same
    function — the compile error carries the lint code."""
    from autodist_tpu.kernel.partitioner import VariablePartitioner
    s = clean_strategy(emb_item)
    node = s.find("dense/bias")
    node.synchronizer = None
    node.mp_axes = {0: const.MODEL_AXIS}
    s.graph_config.mesh_shape = {const.DATA_AXIS: 2, const.MODEL_AXIS: 2}
    with pytest.raises(ValueError) as ei:
        VariablePartitioner.apply(
            s, emb_item.var_infos, 2,
            mesh_axis_sizes={const.DATA_AXIS: 2, const.MODEL_AXIS: 2})
    assert getattr(ei.value, "code", None) == "ADT206"
    assert "ADT206" in codes(verify(s, emb_item, spec_2x2()))


# ------------------------------------------------------- 5. simulator gate


def test_simulator_skips_unverifiable_candidates(emb_item):
    from autodist_tpu.simulator.simulator import Simulator
    spec = spec_2x2()
    good = clean_strategy(emb_item, spec)
    broken = clean_strategy(emb_item, spec)
    broken.find("embedding").synchronizer = AllReduceSynchronizer(
        compressor="GzipCompressor")
    sim = Simulator(emb_item, spec)
    ranking = sim.rank([("good", good), ("broken", broken)])
    assert [r.label for r in ranking] == ["good"]
    # all-broken: ranking still returns (unverified, with a warning)
    ranking = sim.rank([("broken", broken)])
    assert [r.label for r in ranking] == ["broken"]


def test_autostrategy_still_picks_under_verification(emb_item):
    from autodist_tpu.strategy import AutoStrategy
    s = AutoStrategy().build(emb_item, spec_2x2())
    assert not errors(verify(s, emb_item, spec_2x2()))


# ------------------------------------------------------ 6. lowered pass


def test_lowered_flags_full_gather_of_mp_param():
    text = """
  func.func @main(%arg0: tensor<4x16xf32>) -> tensor<8x16xf32> {
    %0 = "stablehlo.all_gather"(%arg0) : (tensor<4x16xf32>) -> tensor<8x16xf32>
    return %0 : tensor<8x16xf32>
  }
"""
    diags = lint_lowered_text(text, mp_full_shapes={"wq": (8, 16)})
    assert "ADT405" in codes(diags)
    # without a matching full shape: no finding
    assert "ADT405" not in codes(
        lint_lowered_text(text, mp_full_shapes={"wq": (32, 16)}))


def test_lowered_flags_host_transfer():
    text = 'x = "stablehlo.custom_call"() {call_target_name = "SendToHost"}'
    assert "ADT406" in codes(lint_lowered_text(text))
    assert "ADT406" not in codes(lint_lowered_text("stablehlo.add"))


def test_lowered_flags_collective_in_branch():
    text = """
  %1 = "stablehlo.if"(%pred) ({
    %2 = "stablehlo.all_reduce"(%arg0) : (tensor<4xf32>) -> tensor<4xf32>
    stablehlo.return %2 : tensor<4xf32>
  }, {
    stablehlo.return %arg0 : tensor<4xf32>
  }) : (tensor<i1>) -> tensor<4xf32>
"""
    assert "ADT407" in codes(lint_lowered_text(text))
    flat = '%2 = "stablehlo.all_reduce"(%arg0) : (tensor<4xf32>) -> tensor<4xf32>'
    assert "ADT407" not in codes(lint_lowered_text(flat))


def test_lowered_flags_collective_in_jaxpr_cond():
    """jaxpr dumps spell conditionals `cond[branches=(...)]` — with the
    braces on the same line or later lines — and must flag ADT407 too."""
    one_line = "e:f32[4] = cond[branches=({ lambda ; a:f32[4]. let " \
               "b:f32[4] = psum[axes=('data',)] a in (b,) })] c d"
    assert "ADT407" in codes(lint_lowered_text(one_line))
    multi_line = """
e:f32[4] = cond[
  branches=(
    { lambda ; a:f32[4]. let
        b:f32[4] = psum[axes=('data',)] a
      in (b,) }
  )
] c d
"""
    assert "ADT407" in codes(lint_lowered_text(multi_line))
    assert "ADT407" not in codes(
        lint_lowered_text("b:f32[4] = psum[axes=('data',)] a"))


def test_lowered_nested_scan_in_scan_flags_adt408():
    """Regression: region tracking beyond one level. A scan-in-scan
    program (jaxpr pretty-print) must flag a host transfer in the INNER
    body, in the outer body AFTER the inner scan closes, and — the case
    the old brace-only tracker lost — inside a ``while[`` whose statement
    carries TWO sub-jaxprs (cond_jaxpr + body_jaxpr)."""
    inner = """
c:f32[] d:f32[3,4] = scan[
  jaxpr={ lambda ; e:f32[] f:f32[4]. let
      g:f32[] = scan[
        jaxpr={ lambda ; h:f32[] i:f32[]. let
            j:f32[] = outfeed h
          in (j,) }
      ] e f
    in (g,) }
] a b
"""
    assert "ADT408" in codes(lint_lowered_text(inner))
    after_inner = """
c:f32[] = scan[
  jaxpr={ lambda ; e:f32[]. let
      g:f32[] = scan[
        jaxpr={ lambda ; h:f32[]. let
            k:f32[] = add h h
          in (k,) }
      ] e
      m:f32[] = outfeed g
    in (m,) }
] a
o:f32[] = outfeed c
"""
    diags = lint_lowered_text(after_inner)
    # in-loop transfer is ADT408; the one AFTER the whole scan statement
    # closes is back on the flat hot path (ADT406)
    assert {"ADT406", "ADT408"} <= codes(diags)
    two_region_while = """
b:f32[] = while[
  cond_jaxpr={ lambda ; a:f32[]. let
      c:bool[] = lt a 1.0
    in (c,) }
  body_jaxpr={ lambda ; a:f32[]. let
      d:f32[] = outfeed a
    in (d,) }
] x
"""
    diags = lint_lowered_text(two_region_while)
    assert "ADT408" in codes(diags) and "ADT406" not in codes(diags)


def test_cli_strategy_json_deserialize_defect_exits_one(tmp_path, capsys):
    """A plan whose defect surfaces at DESERIALIZE time (unknown
    synchronizer kind) is still an ADT finding: exit 1 with ADT301 in
    the table, not the exit-2 tooling-failure path."""
    import json as json_lib
    doc = {"id": "x", "graph_config": {"replicas": ["127.0.0.1:TPU:0"]},
           "node_config": [{"var_name": "w",
                            "synchronizer": {"kind": "Gossip"}}]}
    path = tmp_path / "gossip.json"
    path.write_text(json_lib.dumps(doc))
    rc = cli.main(["sentiment_classifier", "--strategy-json", str(path)])
    out = capsys.readouterr().out
    assert rc == 1 and "ADT301" in out


def test_runner_lint_lowered_end_to_end():
    """Real build: Runner.lowered_text + lint on the 8-device CPU mesh."""
    import numpy as np
    import optax
    import autodist_tpu
    from autodist_tpu import strategy as S

    params = {"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))}
    loss_fn = lambda p, b: jnp.mean((b["x"] @ p["w"] + p["b"]) ** 2)  # noqa: E731
    batch = {"x": np.zeros((16, 8), np.float32)}
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce(),
                               validate="error")
    runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
    runner.init(params)
    text = runner.lowered_text(batch)
    assert "stablehlo" in text or "func" in text
    diags = runner.lint_lowered(batch)
    assert not [d for d in diags if d.code == "ADT405"]


# --------------------------------------------- 7. AutoDist validate modes


def test_autodist_validate_error_raises(emb_item):
    import autodist_tpu
    from autodist_tpu.strategy.base import StrategyBuilder

    class Broken(StrategyBuilder):
        def build(self, model_item, resource_spec):
            from autodist_tpu.strategy import AllReduce
            s = AllReduce().build(model_item, resource_spec)
            s.node_config[0].synchronizer = AllReduceSynchronizer(
                compressor="GzipCompressor")
            return s

    loss_fn, params, batch, _ = cli.EXAMPLES["linear_regression"]()
    ad = autodist_tpu.AutoDist(strategy_builder=Broken(), validate="error")
    import optax
    with pytest.raises(StrategyVerificationError) as ei:
        ad.build(loss_fn, optax.sgd(0.1), params, batch)
    assert any(d.code == "ADT305" for d in ei.value.diagnostics)


def test_autodist_validate_rejects_bad_mode():
    import autodist_tpu
    with pytest.raises(ValueError):
        autodist_tpu.AutoDist(validate="loud")
