"""Chunked softmax cross-entropy vs the standard log_softmax path."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.ops.xent import chunked_softmax_xent


def _ref_nll(x, w, b, targets):
    logits = x.astype(jnp.float32) @ w.astype(jnp.float32) + b
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, targets[:, None], axis=1)[:, 0]


@pytest.mark.parametrize("vocab,chunk", [(1000, 256), (1000, 1000),
                                         (777, 256), (512, 512)])
def test_matches_reference_fwd_and_grad(vocab, chunk):
    """Exact same nll and grads as log_softmax+gather, including the
    ragged final chunk (vocab not a chunk multiple)."""
    rng = np.random.RandomState(0)
    n, d = 64, 32
    x = jnp.asarray(rng.randn(n, d), jnp.float32)
    w = jnp.asarray(rng.randn(d, vocab) * 0.1, jnp.float32)
    b = jnp.asarray(rng.randn(vocab) * 0.1, jnp.float32)
    t = jnp.asarray(rng.randint(0, vocab, (n,)), jnp.int32)

    nll = chunked_softmax_xent(x, w, b, t, chunk)
    np.testing.assert_allclose(nll, _ref_nll(x, w, b, t), rtol=1e-5,
                               atol=1e-5)

    def loss_c(x, w, b):
        return jnp.mean(chunked_softmax_xent(x, w, b, t, chunk))

    def loss_r(x, w, b):
        return jnp.mean(_ref_nll(x, w, b, t))

    gc = jax.grad(loss_c, (0, 1, 2))(x, w, b)
    gr = jax.grad(loss_r, (0, 1, 2))(x, w, b)
    for a, bb in zip(gc, gr):
        np.testing.assert_allclose(a, bb, rtol=2e-4, atol=1e-6)


def test_bf16_activations():
    """bf16 activations (the LM's dtype) accumulate in fp32."""
    rng = np.random.RandomState(1)
    n, d, vocab = 32, 16, 300
    x = jnp.asarray(rng.randn(n, d), jnp.bfloat16)
    w = jnp.asarray(rng.randn(d, vocab) * 0.1, jnp.bfloat16)
    b = jnp.asarray(np.zeros(vocab), jnp.float32)
    t = jnp.asarray(rng.randint(0, vocab, (n,)), jnp.int32)
    nll = chunked_softmax_xent(x, w, b, t, 128)
    ref = _ref_nll(x, w, b, t)
    np.testing.assert_allclose(nll, ref, rtol=2e-2, atol=2e-2)
    g = jax.grad(lambda w: jnp.mean(chunked_softmax_xent(x, w, b, t, 128)))(w)
    assert g.dtype == jnp.bfloat16 and bool(jnp.isfinite(
        g.astype(jnp.float32)).all())


def test_no_full_logits_in_program():
    """The jaxpr never holds an [N, V] buffer — the memory property the
    op exists for (V=4096, chunk=512: biggest vocab-dim tensor is the
    [N, 512] chunk; weight-shaped [D, V] tensors are params/grads)."""
    n, d, vocab, chunk = 128, 64, 4096, 512
    x = jnp.zeros((n, d), jnp.float32)
    w = jnp.zeros((d, vocab), jnp.float32)
    b = jnp.zeros((vocab,), jnp.float32)
    t = jnp.zeros((n,), jnp.int32)

    def loss(x, w, b):
        return jnp.mean(chunked_softmax_xent(x, w, b, t, chunk))

    jaxpr = jax.make_jaxpr(jax.grad(loss, (0, 1, 2)))(x, w, b)
    from autodist_tpu.kernel.common import op_info

    def walk(jp, out):
        for eqn in jp.eqns:
            for v in list(eqn.outvars) + list(eqn.invars):
                shape = tuple(getattr(getattr(v, "aval", None), "shape", ()))
                if shape:
                    out.add(shape)
            for sub in op_info.sub_jaxprs(eqn):
                walk(sub, out)
    shapes = set()
    walk(jaxpr.jaxpr, shapes)
    assert (n, vocab) not in shapes, "full logits materialized"
    assert any(s[-1] == chunk and s[0] in (n,) for s in shapes
               if len(s) == 2), shapes
    # the weights are read in place: no stacked [nchunks, D, C] copy of W
    assert (vocab // chunk, d, chunk) not in shapes, "chunked W copy"


def test_lm_lean_head_matches_standard_loss():
    """The LM's lean-head loss equals the standard log_softmax loss and
    trains identically (same grads to float tolerance)."""
    import optax
    from autodist_tpu.models import lm
    cfg = lm.LMConfig.tiny()
    lf_lean, p1, batch, _ = lm.make_train_setup(cfg, seq_len=16,
                                                batch_size=4,
                                                attention="default",
                                                lean_head=True)
    lf_std, p2, _, _ = lm.make_train_setup(cfg, seq_len=16, batch_size=4,
                                           attention="default",
                                           lean_head=False)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), p1, p2)
    np.testing.assert_allclose(float(lf_lean(p1, batch)),
                               float(lf_std(p2, batch)), rtol=1e-5)
    g1 = jax.grad(lf_lean)(p1, batch)
    g2 = jax.grad(lf_std)(p2, batch)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=5e-4, atol=1e-5), g1, g2)


def test_out_of_vocab_target_clamps_like_reference():
    """An out-of-range token id clamps to vocab-1 exactly as the standard
    take_along_axis path does — no silent nll = lse."""
    rng = np.random.RandomState(2)
    n, d, vocab = 16, 8, 100
    x = jnp.asarray(rng.randn(n, d), jnp.float32)
    w = jnp.asarray(rng.randn(d, vocab) * 0.1, jnp.float32)
    b = jnp.zeros((vocab,), jnp.float32)
    t = jnp.asarray([vocab + 5] * n, jnp.int32)  # all out of range
    nll = chunked_softmax_xent(x, w, b, t, 32)
    ref = _ref_nll(x, w, b, jnp.clip(t, 0, vocab - 1))
    np.testing.assert_allclose(nll, ref, rtol=1e-5, atol=1e-5)
    g = jax.grad(lambda w: jnp.mean(chunked_softmax_xent(x, w, b, t, 32)))(w)
    gr = jax.grad(lambda w: jnp.mean(_ref_nll(
        x, w, b, jnp.clip(t, 0, vocab - 1))))(w)
    np.testing.assert_allclose(g, gr, rtol=2e-4, atol=1e-6)
