"""Tensor parallelism: numeric equality with single-device training.

The TP analog of the reference's hand-computed gradient-average assertions
(reference ``tests/integration/cases/c0.py:92-121``): training under
dp x tp sharding must produce the SAME parameters as plain full-batch
single-device training — Megatron psums + the lowering's
``psum(complement)/N`` sync must cancel exactly, not approximately.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import autodist_tpu as adt
from autodist_tpu import const, strategy
from autodist_tpu.models import tp_lm
from autodist_tpu.parallel import tensor


@pytest.fixture(autouse=True)
def _reset():
    adt.reset()
    yield
    adt.reset()


def _mlp_params(rng, d_in=8, d_h=16, d_out=4):
    return {
        "fc1": {"w": rng.standard_normal((d_in, d_h)).astype(np.float32) * 0.3,
                "b": np.zeros((d_h,), np.float32)},
        "fc2": {"w": rng.standard_normal((d_h, d_out)).astype(np.float32) * 0.3,
                "b": np.zeros((d_out,), np.float32)},
    }


def _mlp_loss(p, batch):
    h = jax.nn.relu(tensor.column_parallel_dense(
        batch["x"], p["fc1"]["w"], p["fc1"]["b"]))
    y = tensor.row_parallel_dense(h, p["fc2"]["w"], p["fc2"]["b"])
    return jnp.mean((y - batch["y"]) ** 2)


MLP_RULES = [(r"fc1/w$", {1: const.MODEL_AXIS}),
             (r"fc1/b$", {0: const.MODEL_AXIS}),
             (r"fc2/w$", {0: const.MODEL_AXIS})]


def _train_single(loss_fn, params, opt, batches):
    state = opt.init(params)

    @jax.jit
    def step(p, s, b):
        g = jax.grad(loss_fn)(p, b)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    for b in batches:
        params, state = step(params, state, b)
    return params


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_mlp_matches_single_device(tp):
    rng = np.random.RandomState(0)
    params = _mlp_params(rng)
    batches = [{"x": rng.standard_normal((8, 8)).astype(np.float32),
                "y": rng.standard_normal((8, 4)).astype(np.float32)}
               for _ in range(3)]
    opt = optax.adam(1e-2)

    ref = _train_single(_mlp_loss, params, opt, batches)

    ad = adt.AutoDist(strategy_builder=strategy.TensorParallel(
        tp_shards=tp, mp_rules=MLP_RULES))
    runner = ad.build(_mlp_loss, opt, params, batches[0])
    runner.init(params)
    for b in batches:
        m = runner.run(b)
    assert np.isfinite(m["loss"])
    got = runner.gather_params()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6),
        got, ref)


def test_tp_layouts_and_strategy_roundtrip():
    rng = np.random.RandomState(1)
    params = _mlp_params(rng)
    batch = {"x": rng.standard_normal((8, 8)).astype(np.float32),
             "y": rng.standard_normal((8, 4)).astype(np.float32)}
    ad = adt.AutoDist(strategy_builder=strategy.TensorParallel(
        tp_shards=2, mp_rules=MLP_RULES))
    runner = ad.build(_mlp_loss, optax.sgd(0.1), params, batch)
    layouts = runner.distributed_step.layouts
    assert layouts["fc1/w"].mp_axes == ((1, const.MODEL_AXIS),)
    assert layouts["fc2/w"].mp_axes == ((0, const.MODEL_AXIS),)
    assert layouts["fc2/b"].mp_axes == ()  # bias after reduce: replicated
    # serialization round-trip preserves mp_axes
    from autodist_tpu.strategy.base import Strategy
    s = strategy.TensorParallel(2, MLP_RULES).build(
        runner.distributed_step.model_item, ad.resource_spec)
    rt = Strategy.from_dict(s.to_dict())
    assert rt.find("fc1/w").mp_axes == {1: const.MODEL_AXIS}


def test_vocab_parallel_ops_match_dense():
    """vocab_parallel_embed / logits / xent == dense reference, vocab
    sharded 4-way inside shard_map."""
    rng = np.random.RandomState(2)
    V, D, B, S = 16, 8, 2, 6
    table = rng.standard_normal((V, D)).astype(np.float32)
    ids = rng.randint(0, V, (B, S)).astype(np.int32)
    x = rng.standard_normal((B, S, D)).astype(np.float32)
    targets = rng.randint(0, V, (B, S)).astype(np.int32)

    # dense reference
    emb_ref = table[ids]
    logits_ref = x @ table.T
    logp = jax.nn.log_softmax(logits_ref)
    nll_ref = -np.take_along_axis(np.asarray(logp), targets[..., None], -1)[..., 0]

    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), (const.MODEL_AXIS,))

    def f(table_shard, ids, x, targets):
        emb = tensor.vocab_parallel_embed(table_shard, ids)
        logits = tensor.vocab_parallel_logits(x, table_shard)
        nll = tensor.vocab_parallel_xent(logits, targets)
        return emb, nll

    emb, nll = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(const.MODEL_AXIS), P(), P(), P()),
        out_specs=(P(), P()), check_vma=False))(table, ids, x, targets)
    np.testing.assert_allclose(emb, emb_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(nll, nll_ref, rtol=1e-5, atol=1e-5)


def test_tp_lm_matches_single_device():
    """Tiny TP transformer LM through the full stack (dp2 x tp4) == plain
    single-device training, 2 steps."""
    cfg = tp_lm.TPLMConfig.tiny()
    loss_fn, params, batch, _ = tp_lm.make_train_setup(
        cfg, seq_len=16, batch_size=4, seed=3)
    opt = optax.sgd(0.05)
    rng = np.random.RandomState(4)
    batches = [batch] + [{"tokens": rng.randint(
        0, cfg.vocab_size, batch["tokens"].shape).astype(np.int32)}]

    ref = _train_single(loss_fn, params, opt, batches)

    ad = adt.AutoDist(strategy_builder=strategy.TensorParallel(
        tp_shards=4, mp_rules=tp_lm.tp_rules()))
    runner = ad.build(loss_fn, opt, params, batches[0])
    runner.init(params)
    for b in batches:
        m = runner.run(b)
    assert np.isfinite(m["loss"])
    got = runner.gather_params()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6),
        got, ref)


def test_tp_frozen_embed_matches_single_device():
    """A frozen (non-trainable) var matching an mp rule must still get
    sharded storage — regression for the compiler pruning frozen-var nodes
    (the TP compute consumes local shards regardless of trainability)."""
    cfg = tp_lm.TPLMConfig.tiny()
    loss_fn, params, batch, _ = tp_lm.make_train_setup(
        cfg, seq_len=16, batch_size=4, seed=6)
    opt = optax.sgd(0.05)
    freeze = lambda name: name != "embed"  # noqa: E731

    # single-device reference with frozen embed
    state = opt.init(params)

    @jax.jit
    def step(p, s, b):
        g = jax.grad(loss_fn)(p, b)
        g = {n: (jnp.zeros_like(v) if n == "embed" else v)
             for n, v in g.items()}
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s

    ref = params
    for _ in range(2):
        ref, state = step(ref, state, batch)

    ad = adt.AutoDist(strategy_builder=strategy.TensorParallel(
        tp_shards=4, mp_rules=tp_lm.tp_rules()))
    runner = ad.build(loss_fn, opt, params, batch, trainable_filter=freeze)
    assert runner.distributed_step.layouts["embed"].mp_axes, \
        "frozen embed lost its mp layout"
    runner.init(params)
    for _ in range(2):
        m = runner.run(batch)
    assert np.isfinite(m["loss"])
    got = runner.gather_params()
    np.testing.assert_allclose(got["embed"], params["embed"], atol=0)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-6),
        got, ref)


def test_tp_sp_lm_runs():
    """TP x SP composition: ring attention over seq axis + Megatron sharding
    over model axis, loss finite and decreasing-ish."""
    cfg = tp_lm.TPLMConfig.tiny()
    loss_fn, params, batch, _ = tp_lm.make_train_setup(
        cfg, seq_len=16, batch_size=4, seed=5, attention="ring")
    ad = adt.AutoDist(strategy_builder=strategy.TensorParallel(
        tp_shards=2, mp_rules=tp_lm.tp_rules(), seq_shards=2))
    runner = ad.build(loss_fn, optax.adam(1e-2), params, batch)
    runner.init(params)
    first = runner.run(batch)["loss"]
    for _ in range(5):
        last = runner.run(batch)["loss"]
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first


def test_vocab_parallel_oov_consistency():
    """Out-of-range targets CLAMP identically in the sharded and unbound
    paths of vocab_parallel_xent (previously the sharded loss silently
    degraded to the bare lse with a garbage gradient on a -1 ignore
    sentinel); out-of-range ids NaN-poison vocab_parallel_embed rows in
    the sharded path instead of embedding as silent zeros."""
    from jax.sharding import Mesh, PartitionSpec as P
    from autodist_tpu.parallel import tensor
    V, Dm, B = 16, 8, 4
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(B, V).astype(np.float32))
    targets = jnp.asarray([3, -1, V + 2, 7], jnp.int32)  # two OOV
    ref = tensor.vocab_parallel_xent(logits, targets)  # unbound (clamped)
    mesh = Mesh(np.array(jax.devices()[:4]), ("model",))
    got = jax.jit(jax.shard_map(
        lambda lg, t: tensor.vocab_parallel_xent(lg, t),
        mesh=mesh, in_specs=(P(None, "model"), P()), out_specs=P(),
        check_vma=False))(logits, targets)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    # gradient parity on the OOV rows too
    g_ref = jax.grad(lambda lg: jnp.sum(
        tensor.vocab_parallel_xent(lg, targets)))(logits)
    g = jax.jit(jax.shard_map(
        jax.grad(lambda lg, t: jnp.sum(
            tensor.vocab_parallel_xent(lg, t))),
        mesh=mesh, in_specs=(P(None, "model"), P()),
        out_specs=P(None, "model"), check_vma=False))(logits, targets)
    # raw-primitive convention: the replicated (psum-broadcast) loss
    # inflates grads by the axis size; the lowering's /N undoes this in
    # the full stack (see test_pipeline_apply_matches_sequential)
    np.testing.assert_allclose(np.asarray(g) / 4, np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)

    table = jnp.asarray(rng.randn(V, Dm).astype(np.float32))
    ids = jnp.asarray([[1, 5, V + 3, 2]], jnp.int32)
    emb = jax.jit(jax.shard_map(
        lambda tb, i: tensor.vocab_parallel_embed(tb, i),
        mesh=mesh, in_specs=(P("model"), P()), out_specs=P(),
        check_vma=False))(table, ids)
    emb = np.asarray(emb)
    assert np.all(np.isfinite(emb[0, [0, 1, 3]]))
    assert np.all(np.isnan(emb[0, 2]))  # poisoned, not silent zeros
